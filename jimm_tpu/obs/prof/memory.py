"""HBM observability: per-device memory gauges + a monotonic-leak watchdog.

``MemoryMonitor.sample()`` reads each device's allocator stats
(``device.memory_stats()`` — TPU/GPU backends) and publishes ``jimm_hbm_*``
gauges: bytes in use, peak, limit, and a fragmentation estimate. Backends
without allocator stats (CPU in CI) fall back to summing live jax arrays
per device, so the series exist — and the leak watchdog works — on every
platform the tests run on.

**Per-subsystem attribution**: ``register_subsystem(name, fn)`` binds a
byte-counting callable (model pool residency, retrieval index bytes, serve
trace-ring bytes...) into ``jimm_hbm_subsystem_{name}_bytes`` so "where
did HBM go" decomposes the same way goodput decomposes wall time.

**Leak watchdog**: when total in-use bytes grow monotonically across
``leak_window`` consecutive samples by at least ``leak_min_growth_frac``
(and ``leak_min_growth_bytes``), it journals ``hbm_leak_suspected`` with a
fresh correlation id and the subsystem snapshot — once per episode; any
decrease closes the episode. The cid threads into a deep capture the same
way serve incidents do.

jax is imported lazily inside :meth:`sample` so importing the module (and
the jax-free ``obs prof`` CLI verbs) never drags in the runtime.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable

from jimm_tpu.obs.journal import get_journal, new_correlation_id
from jimm_tpu.obs.registry import get_registry

__all__ = ["MemoryMonitor", "device_memory_rows"]


def device_memory_rows() -> list[dict]:
    """One row per jax device: allocator stats when the backend exposes
    them, live-array accounting otherwise. Each row carries ``source``
    ("allocator" | "live_arrays") so consumers know the fidelity."""
    import jax

    live_by_device: dict = {}
    rows = []
    devices = jax.devices()
    need_live = any(_stats_of(d) is None for d in devices)
    if need_live:
        for arr in jax.live_arrays():
            for shard in getattr(arr, "addressable_shards", []):
                nbytes = getattr(shard.data, "nbytes", 0)
                live_by_device[shard.device] = \
                    live_by_device.get(shard.device, 0) + int(nbytes)
    for i, dev in enumerate(devices):
        stats = _stats_of(dev)
        if stats is not None:
            in_use = int(stats.get("bytes_in_use", 0))
            limit = int(stats.get("bytes_limit", 0) or
                        stats.get("bytes_reservable_limit", 0))
            peak = int(stats.get("peak_bytes_in_use", in_use))
            free = max(0, limit - in_use) if limit else 0
            largest = int(stats.get("largest_free_block_bytes", 0))
            # classic allocator fragmentation estimate: the share of free
            # memory NOT in the largest free block — 0 when contiguous
            frag = (1.0 - largest / free) if (free and largest) else 0.0
            rows.append({"device": i, "platform": dev.platform,
                         "source": "allocator", "bytes_in_use": in_use,
                         "peak_bytes_in_use": peak, "bytes_limit": limit,
                         "fragmentation": round(max(0.0, frag), 4)})
        else:
            rows.append({"device": i, "platform": dev.platform,
                         "source": "live_arrays",
                         "bytes_in_use": live_by_device.get(dev, 0),
                         "peak_bytes_in_use": 0, "bytes_limit": 0,
                         "fragmentation": 0.0})
    return rows


def _stats_of(dev) -> dict | None:
    try:
        stats = dev.memory_stats()
    except Exception:  # noqa: BLE001 — a backend without allocator stats raises or returns None; both mean "fall back"
        return None
    return stats if isinstance(stats, dict) and stats else None


class MemoryMonitor:
    """Periodic HBM sampler + leak watchdog publishing ``jimm_hbm_*``.

    ``sample()`` is callable directly (train loop, tests); ``start()``
    spawns a daemon polling thread for serving processes."""

    def __init__(self, *, period_s: float = 10.0, leak_window: int = 5,
                 leak_min_growth_frac: float = 0.05,
                 leak_min_growth_bytes: int = 1 << 20,
                 journal=None, sampler: Callable[[], list[dict]]
                 | None = None):
        self.period_s = float(period_s)
        self.leak_window = max(2, int(leak_window))
        self.leak_min_growth_frac = float(leak_min_growth_frac)
        self.leak_min_growth_bytes = int(leak_min_growth_bytes)
        self._journal = journal
        self._sampler = sampler or device_memory_rows
        self._subsystems: dict[str, Callable[[], float]] = {}
        self._lock = threading.Lock()
        self._last: dict[str, float] = {}
        self._bound: set[str] = set()
        self._totals: deque[float] = deque(maxlen=self.leak_window + 1)
        self._leak_open = False
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._reg = get_registry("jimm_hbm")
        self._samples_total = self._reg.counter("samples_total")
        self._leaks_total = self._reg.counter("leak_suspected_total")
        self.last_leak_cid: str | None = None

    def register_subsystem(self, name: str,
                           fn: Callable[[], float]) -> None:
        """Attribute bytes to a named subsystem (model pool, retrieval
        index, serve buffers). ``fn`` returns current bytes; it is called
        at sample time and a raising fn reports 0 (attribution must never
        break sampling)."""
        self._subsystems[name] = fn

    def _gauge(self, key: str, value: float) -> None:
        self._last[key] = float(value)
        if key not in self._bound:
            self._bound.add(key)
            self._reg.gauge(key, lambda k=key: self._last.get(k, 0.0))

    def sample(self) -> dict:
        """One sampling pass: refresh every gauge, run the leak check.
        Returns ``{"devices": rows, "total_bytes_in_use": n,
        "subsystems": {...}, "leak_suspected": bool}``."""
        rows = self._sampler()
        with self._lock:
            total = 0
            for row in rows:
                i = row["device"]
                total += row["bytes_in_use"]
                self._gauge(f"device{i}_bytes_in_use",
                            row["bytes_in_use"])
                self._gauge(f"device{i}_peak_bytes_in_use",
                            row["peak_bytes_in_use"])
                self._gauge(f"device{i}_bytes_limit", row["bytes_limit"])
                self._gauge(f"device{i}_fragmentation",
                            row["fragmentation"])
            self._gauge("total_bytes_in_use", total)
            subsystems = {}
            for name, fn in self._subsystems.items():
                try:
                    subsystems[name] = float(fn())
                except Exception:  # noqa: BLE001 — attribution is best-effort; a broken counter must not kill the sampler
                    subsystems[name] = 0.0
                self._gauge(f"subsystem_{name}_bytes", subsystems[name])
            self._samples_total.inc()
            leak = self._check_leak(total, subsystems)
        return {"devices": rows, "total_bytes_in_use": total,
                "subsystems": subsystems, "leak_suspected": leak}

    def _check_leak(self, total: float, subsystems: dict) -> bool:
        self._totals.append(total)
        if len(self._totals) < self._totals.maxlen:
            return self._leak_open
        deltas = [b - a for a, b in zip(self._totals,
                                        list(self._totals)[1:])]
        if any(d <= 0 for d in deltas):
            self._leak_open = False  # any decrease closes the episode
            return False
        growth = self._totals[-1] - self._totals[0]
        base = self._totals[0] or 1.0
        if growth < self.leak_min_growth_bytes \
                or growth / base < self.leak_min_growth_frac:
            return self._leak_open
        if self._leak_open:
            return True  # one journal record per episode
        self._leak_open = True
        self._leaks_total.inc()
        cid = new_correlation_id()
        self.last_leak_cid = cid
        journal = self._journal if self._journal is not None \
            else get_journal()
        journal.emit("hbm_leak_suspected", cid=cid,
                     growth_bytes=int(growth),
                     window=self.leak_window,
                     total_bytes_in_use=int(total),
                     subsystems={k: int(v) for k, v in subsystems.items()})
        return True

    # -- background polling -----------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="jimm-hbm-monitor",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — a transient backend error must not end monitoring; the next tick retries
                time.sleep(0.0)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
