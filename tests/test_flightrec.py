"""Flight-recorder tests: journal, timeline export, SLO burn rates, and
the baseline regression gate — including the crash-shaped edge cases
(rotation mid-write, truncated tails, empty/partial timelines,
zero-traffic burn windows, single-sample percentiles)."""

import json

import pytest

from jimm_tpu.obs.baseline import (BaselineStore, check_rows, is_fallback,
                                   row_key, summarize)
from jimm_tpu.obs.journal import (EventJournal, chain, configure_journal,
                                  correlate, current_cid, get_journal,
                                  new_correlation_id, read_events,
                                  reset_journal)
from jimm_tpu.obs.registry import Histogram, MetricRegistry, percentile
from jimm_tpu.obs.slo import SloEngine, SloObjective
from jimm_tpu.obs.timeline import (export_timeline, journal_to_trace_events,
                                   traces_to_trace_events,
                                   validate_chrome_trace, write_timeline)


@pytest.fixture
def fresh_global_journal():
    """Give the test an isolated memory-only global journal."""
    j = configure_journal(None)
    yield j
    reset_journal()


# ---------------------------------------------------------------------------
# journal
# ---------------------------------------------------------------------------

class TestJournal:
    def test_emit_record_shape_and_seq(self):
        j = EventJournal()
        a = j.emit("preempt_detected", cid="c1", step=7)
        b = j.emit("grace_save_committed", cid="c1", dur_s=0.5)
        assert a["seq"] == 0 and b["seq"] == 1
        assert a["event"] == "preempt_detected" and a["step"] == 7
        assert a["cid"] == "c1" and "ts" in a and "mono" in a
        assert b["mono"] >= a["mono"]
        assert [r["event"] for r in j.tail(10)] == [
            "preempt_detected", "grace_save_committed"]

    def test_concurrent_emit_seq_matches_ring_order(self):
        # regression (JL017): seq was minted outside the journal lock, so
        # two threads could append to the ring in the opposite order of
        # their seq values; readers treat seq as the total order
        import threading

        n_threads, per_thread = 8, 200
        j = EventJournal(ring=n_threads * per_thread)
        start = threading.Barrier(n_threads)

        def hammer(tid):
            start.wait()
            for i in range(per_thread):
                j.emit("hammer", tid=tid, i=i)

        threads = [threading.Thread(target=hammer, args=(t,))
                   for t in range(n_threads)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        tail = j.tail(n_threads * per_thread)
        seqs = [r["seq"] for r in tail]
        assert seqs == sorted(seqs), "ring order must equal seq order"
        assert len(set(seqs)) == len(seqs) == n_threads * per_thread

    def test_correlation_ids_unique_and_ambient(self):
        assert new_correlation_id() != new_correlation_id()
        j = EventJournal()
        assert current_cid() is None
        with correlate("inc-1"):
            assert current_cid() == "inc-1"
            inherited = j.emit("checkpoint_restored", step=3)
            explicit = j.emit("other", cid="inc-2")
        outside = j.emit("standalone")
        assert inherited["cid"] == "inc-1"
        assert explicit["cid"] == "inc-2"
        assert outside["cid"] is None
        # correlate(None) is a no-op block, not a crash
        with correlate(None):
            assert current_cid() is None

    def test_chain_filters_one_incident_in_order(self):
        j = EventJournal()
        j.emit("replica_fault", cid="i1", replica=0)
        j.emit("unrelated")
        j.emit("replica_fenced", cid="i1")
        j.emit("replica_fault", cid="i2", replica=1)
        j.emit("heal_rebuilt", cid="i1", dur_s=0.1)
        got = [e["event"] for e in j.chain("i1")]
        assert got == ["replica_fault", "replica_fenced", "heal_rebuilt"]
        assert chain(j.events(), "i2")[0]["replica"] == 1

    def test_persistence_and_tolerant_read(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        j = EventJournal(path)
        j.emit("a", x=1)
        j.emit("b", x=2)
        j.close()
        # crash mid-write: a truncated final line plus log noise
        with open(path, "a", encoding="utf-8") as f:
            f.write('{"seq": 2, "event": "tru')
        events = read_events(path)
        assert [e["event"] for e in events] == ["a", "b"]
        # and a journal reopened on the same path appends, not truncates
        j2 = EventJournal(path)
        j2.emit("c")
        j2.close()
        assert [e["event"] for e in read_events(path)] == ["a", "b", "c"]

    def test_rotation_mid_write_preserves_every_record(self, tmp_path):
        path = tmp_path / "journal.jsonl"
        j = EventJournal(path, max_bytes=512, max_segments=3)
        n = 40
        for i in range(n):
            j.emit("tick", i=i, pad="x" * 64)
        j.close()
        segments = sorted(p.name for p in tmp_path.iterdir())
        assert "journal.jsonl" in segments and "journal.1.jsonl" in segments
        assert len(segments) <= 4  # live + max_segments rotated
        events = read_events(path)
        # rotation drops only whole oldest segments, never mid-record
        assert all(e["event"] == "tick" for e in events)
        got = [e["i"] for e in events]
        assert got == sorted(got)
        assert got[-1] == n - 1
        for line in path.read_text().splitlines():
            assert json.loads(line)  # every surviving line parses whole

    def test_ring_survives_without_path_and_bounds_memory(self):
        j = EventJournal(ring=8)
        for i in range(20):
            j.emit("e", i=i)
        assert [r["i"] for r in j.events()] == list(range(12, 20))

    def test_global_journal_env_config(self, tmp_path, monkeypatch):
        reset_journal()
        target = tmp_path / "j.jsonl"
        monkeypatch.setenv("JIMM_JOURNAL", str(target))
        try:
            get_journal().emit("from_env")
            assert [e["event"] for e in read_events(target)] == ["from_env"]
        finally:
            reset_journal()

    def test_configure_journal_replaces_global(self, fresh_global_journal):
        assert get_journal() is fresh_global_journal
        fresh_global_journal.emit("one")
        assert get_journal().tail(5)[0]["event"] == "one"


# ---------------------------------------------------------------------------
# timeline
# ---------------------------------------------------------------------------

class TestTimeline:
    def test_empty_journal_exports_valid_trace(self, tmp_path):
        trace = export_timeline([])
        assert validate_chrome_trace(trace) == []
        assert all(e["ph"] == "M" for e in trace["traceEvents"])
        out = write_timeline(tmp_path / "t.json", trace)
        assert json.loads(out.read_text())["displayTimeUnit"] == "ms"

    def test_partial_records_without_mono_are_skipped(self):
        events = [{"event": "ok", "mono": 10.0, "seq": 0},
                  {"event": "truncated", "seq": 1},          # no mono
                  {"event": "corrupt", "mono": "nan?"}]      # bad mono
        tev = journal_to_trace_events(events)
        assert [e["name"] for e in tev] == ["ok"]
        assert validate_chrome_trace(export_timeline(events)) == []

    def test_instant_vs_span_and_lanes(self):
        events = [
            {"event": "preempt_detected", "mono": 100.0, "cid": "c1"},
            {"event": "grace_save_committed", "mono": 101.0, "cid": "c1",
             "dur_s": 0.5},
            {"event": "replica_fenced", "mono": 100.2, "cid": "c2"},
            {"event": "advisor_decision", "mono": 100.3},
            {"event": "custom_thing", "mono": 100.4},
        ]
        tev = {e["name"]: e for e in journal_to_trace_events(events)}
        assert tev["preempt_detected"]["ph"] == "i"
        assert tev["preempt_detected"]["ts"] == 0.0
        assert tev["preempt_detected"]["tid"] == "train"
        span = tev["grace_save_committed"]
        assert span["ph"] == "X" and span["dur"] == pytest.approx(5e5)
        # the span is placed backwards from its end stamp
        assert span["ts"] == pytest.approx((101.0 - 0.5 - 100.0) * 1e6)
        assert tev["replica_fenced"]["tid"] == "serve"
        assert tev["advisor_decision"]["tid"] == "advisor"
        assert tev["custom_thing"]["tid"] == "events"
        assert tev["grace_save_committed"]["args"]["cid"] == "c1"

    def test_serve_traces_on_replica_lanes(self):
        rows = [{"trace_id": 7, "replica": 1, "bucket": 4,
                 "queue_s": 0.01, "pad_s": 0.002, "device_s": 0.05,
                 "readback_s": 0.003, "total_s": 0.07, "done_mono": 50.0},
                {"trace_id": 8}]  # legacy row, no done_mono: skipped
        tev = traces_to_trace_events(rows)
        assert {e["tid"] for e in tev} == {"replica1"}
        assert [e["name"] for e in tev] == ["queue", "pad", "device",
                                           "readback"]
        # phases lie end to end and finish at done_mono
        end = tev[-1]["ts"] + tev[-1]["dur"]
        start = tev[0]["ts"]
        assert end - start == pytest.approx(
            (0.01 + 0.002 + 0.05 + 0.003) * 1e6)
        assert validate_chrome_trace(export_timeline([], traces=rows)) == []

    def test_merged_export_shares_one_clock(self):
        events = [{"event": "replica_fault", "mono": 99.0, "cid": "x"}]
        rows = [{"trace_id": 1, "replica": 0, "device_s": 0.1,
                 "total_s": 0.1, "done_mono": 100.0}]
        trace = export_timeline(events, traces=rows,
                                goodput={"step": 2.0, "heal": 0.5,
                                         "empty": 0.0})
        assert validate_chrome_trace(trace) == []
        by_name = {e["name"]: e for e in trace["traceEvents"]}
        assert by_name["replica_fault"]["ts"] == 0.0  # earliest event is t0
        assert by_name["step"]["tid"] == "goodput"
        assert "empty" not in by_name  # zero buckets draw nothing
        lanes = {e["args"]["name"] for e in trace["traceEvents"]
                 if e["ph"] == "M" and e["name"] == "thread_name"}
        assert {"serve", "replica0", "goodput"} <= lanes

    def test_validator_rejects_malformed_events(self):
        bad = {"traceEvents": [
            {"ph": "X", "pid": 1, "tid": "t", "ts": 0.0, "dur": 1.0},
            {"name": "n", "ph": "Z", "pid": 1, "tid": "t", "ts": 0.0},
            {"name": "n", "ph": "i", "pid": 1, "tid": "t", "ts": -5.0},
            {"name": "n", "ph": "X", "pid": 1, "tid": "t", "ts": 0.0},
            {"name": "n", "ph": "i", "ts": 0.0},
            "not an event",
        ]}
        problems = validate_chrome_trace(bad)
        assert len(problems) == 6
        assert validate_chrome_trace("nope") == ["trace must be a JSON "
                                                 "object"]
        assert validate_chrome_trace({}) == ["traceEvents must be a list"]


# ---------------------------------------------------------------------------
# SLO burn rates
# ---------------------------------------------------------------------------

def make_engine(objectives=None, **kw):
    """An engine on a fake clock and a private registry (no hub publish)."""
    clock = {"t": 1000.0}
    kw.setdefault("registry", MetricRegistry("slo_test"))
    eng = SloEngine(objectives, clock=lambda: clock["t"], **kw)
    return eng, clock


class TestSlo:
    def test_objective_validation(self):
        assert SloObjective(0.999).error_budget == pytest.approx(0.001)
        with pytest.raises(ValueError):
            SloObjective(availability=1.0)
        with pytest.raises(ValueError):
            SloObjective(availability=0.9, latency_ms=0)
        with pytest.raises(ValueError):
            SloObjective.from_dict({"availability": 0.9, "bogus": 1})
        assert SloObjective.from_dict(
            {"availability": 0.99, "latency_ms": 250}).latency_ms == 250.0

    def test_zero_traffic_windows_burn_nothing(self):
        eng, clock = make_engine({"t": SloObjective(0.9)})
        assert eng.burn_rate("t", 60.0) == 0.0
        assert eng.fast_burning() == []
        # traffic, then a long quiet stretch: the window empties again
        eng.observe("t", False)
        assert eng.burn_rate("t", 60.0) > 0.0
        clock["t"] += 10_000.0
        assert eng.burn_rate("t", 60.0) == 0.0

    def test_burn_rate_math(self):
        # availability 0.9 -> budget 0.1; 1 bad in 10 -> bad_frac 0.1 ->
        # burn exactly 1.0 (spending the budget exactly as provisioned)
        eng, clock = make_engine({"t": SloObjective(0.9)})
        for _ in range(9):
            eng.observe("t", True)
        eng.observe("t", False)
        assert eng.burn_rate("t", 60.0) == pytest.approx(1.0)
        # all-bad traffic burns at 1/budget
        eng2, _ = make_engine({"t": SloObjective(0.9)})
        eng2.observe("t", False)
        assert eng2.burn_rate("t", 60.0) == pytest.approx(10.0)

    def test_multi_window_guard(self):
        # a fresh burst of errors after a long clean stretch: the fast
        # window pages only once the slow window is burning too
        eng, clock = make_engine({"t": SloObjective(0.5)},
                                 fast_window_s=60, slow_window_s=600,
                                 fast_burn_threshold=1.5)
        for _ in range(400):
            eng.observe("t", True)
        clock["t"] += 300.0
        eng.observe("t", False)
        # fast window: 1 bad / 1 total -> burn 2.0 >= 1.5; slow window is
        # diluted by the 400 good -> not burning -> guard holds
        assert eng.burn_rate("t", 60.0) == pytest.approx(2.0)
        assert eng.burn_rate("t", 600.0) < 1.0
        assert eng.fast_burning() == []
        for _ in range(500):
            eng.observe("t", False)
        assert "t" in eng.fast_burning()

    def test_latency_target_counts_slow_success_as_bad(self):
        eng, _ = make_engine({"t": SloObjective(0.9, latency_ms=100.0)})
        assert eng.observe("t", True, latency_s=0.05) is True
        assert eng.observe("t", True, latency_s=0.5) is False
        assert eng.observe("t", False, latency_s=0.01) is False
        snap = eng.snapshot()["tenants"]["t"]
        assert snap["good_total"] == 1 and snap["bad_total"] == 2

    def test_unknown_tenant_folds_to_default(self):
        eng, _ = make_engine({"vip": SloObjective(0.99)})
        eng.observe("attacker-invented-name", False)
        eng.observe(None, True)
        snap = eng.snapshot()["tenants"]
        assert set(snap) == {"vip", "default"}  # bounded cardinality
        assert snap["default"]["bad_total"] == 1
        assert snap["default"]["good_total"] == 1

    def test_publishes_jimm_slo_series(self):
        from jimm_tpu import obs
        eng = SloEngine({"alice": SloObjective(0.99)})
        try:
            eng.observe("alice", True)
            snap = obs.snapshot()
            assert snap["jimm_slo_alice_good_total"] == 1
            assert "jimm_slo_alice_fast_burn_rate" in snap
        finally:
            from jimm_tpu.obs.registry import unpublish
            unpublish("jimm_slo")

    def test_snapshot_shape(self):
        eng, _ = make_engine({"t": SloObjective(0.999)})
        snap = eng.snapshot()
        assert snap["fast_window_s"] == 60.0
        assert snap["fast_burn_threshold"] == 14.4
        assert snap["fast_burning"] == []
        assert snap["tenants"]["t"]["objective"] == {"availability": 0.999}


class TestSloTransitions:
    """Fast-burn *transition* events (add_listener) under bursty traffic.

    The listener contract is edge-triggered: one call on entering fast
    burn, one on exiting, nothing while the state holds — this is what
    the cascade autoscaler hangs capacity decisions on.
    """

    @staticmethod
    def make_listening_engine(**kw):
        kw.setdefault("fast_window_s", 60)
        kw.setdefault("slow_window_s", 600)
        kw.setdefault("fast_burn_threshold", 1.5)
        eng, clock = make_engine({"t": SloObjective(0.5)}, **kw)
        events = []
        eng.add_listener(
            lambda tenant, entered, fast, slow:
            events.append((tenant, entered, fast, slow)))
        return eng, clock, events

    def test_enter_fires_once_not_per_observation(self):
        eng, clock, events = self.make_listening_engine()
        # all-bad traffic: budget 0.5 -> burn 2.0 in both windows, over
        # the 1.5 fast threshold and the 1.0 slow guard immediately
        eng.observe("t", False)
        assert events == [("t", True, pytest.approx(2.0),
                           pytest.approx(2.0))]
        # staying in fast burn is not a transition
        for _ in range(5):
            eng.observe("t", False)
        assert len(events) == 1

    def test_exit_fires_when_windows_drain(self):
        eng, clock, events = self.make_listening_engine()
        eng.observe("t", False)
        assert [e[1] for e in events] == [True]
        # idle past both windows: the exit is reported with the next
        # request (transitions are evaluated on observations)
        clock["t"] += 700.0
        assert len(events) == 1
        eng.observe("t", True)
        assert [e[1] for e in events] == [True, False]
        tenant, entered, fast, slow = events[-1]
        assert fast < 1.5 and slow < 1.0

    def test_burst_diluted_by_slow_window_never_fires(self):
        # a fresh error burst after a long clean stretch: fast window
        # burns but the 600s window is diluted -> multi-window guard
        # holds and no transition is emitted
        eng, clock, events = self.make_listening_engine()
        for _ in range(400):
            eng.observe("t", True)
        clock["t"] += 300.0
        eng.observe("t", False)
        assert eng.burn_rate("t", 60.0) >= 1.5
        assert eng.burn_rate("t", 600.0) < 1.0
        assert events == []
        # sustained errors eventually tip the slow window too -> enter
        for _ in range(500):
            eng.observe("t", False)
        assert [e[1] for e in events] == [True]
        assert events[0][3] >= 1.0

    def test_flap_across_windows_yields_paired_transitions(self):
        # bursty traffic that alternates bad bursts and quiet recovery:
        # each burn episode yields exactly one enter/exit pair
        eng, clock, events = self.make_listening_engine()
        for _ in range(3):
            eng.observe("t", False)          # enter
            clock["t"] += 700.0              # drain 60s and 600s windows
            eng.observe("t", True)           # exit reported here
            clock["t"] += 700.0              # drain the recovery probe too
        assert [e[1] for e in events] == [True, False] * 3

    def test_listener_errors_counted_not_raised(self):
        eng, clock, events = self.make_listening_engine()

        def broken(tenant, entered, fast, slow):
            raise RuntimeError("consumer bug")

        eng._listeners.insert(0, broken)
        # the broken consumer neither fails accounting nor starves the
        # healthy one
        assert eng.observe("t", False) is False
        assert [e[1] for e in events] == [True]
        assert eng.registry.counter("listener_errors_total").value == 1


# ---------------------------------------------------------------------------
# baseline store / regression gate
# ---------------------------------------------------------------------------

ROW = {"ts": "t1", "phase": "serve_bench", "backend": "cpu",
       "preset": "vit-b16", "qps": 505.0}


class TestBaseline:
    def test_is_fallback(self):
        assert is_fallback({"fallback": True})
        assert is_fallback({"metric": "images_per_sec (cpu smoke)"})
        assert not is_fallback(ROW)

    def test_row_key(self):
        assert row_key(ROW) == "serve_bench/cpu/vit-b16"
        assert row_key({"metric": "flash_parity", "device": "TPU v5",
                        "case": "seq512"}) == "flash_parity/TPU v5/seq512"
        assert row_key({"phase": "sweep",
                        "variant": {"remat": "dots", "ln": "fused"}}) \
            == "sweep/unknown/ln=fused,remat=dots"
        assert row_key({"rc": 0}) is None

    def test_adopt_then_gate(self, tmp_path):
        store = BaselineStore(tmp_path / "b.json")
        adopted = store.adopt_rows([ROW, {"fallback": True, **ROW}])
        assert adopted == ["serve_bench/cpu/vit-b16:qps"]  # fallback skipped
        store.save()
        store2 = BaselineStore(tmp_path / "b.json")
        assert store2.get("serve_bench/cpu/vit-b16", "qps") == 505.0
        ok = check_rows(store2, [dict(ROW, qps=500.0)])
        assert [v["status"] for v in ok] == ["ok"]

    def test_exactly_threshold_drop_is_flagged(self, tmp_path):
        store = BaselineStore(tmp_path / "b.json")
        store.adopt_rows([ROW])
        verdicts = check_rows(store, [dict(ROW, qps=505.0 * 0.8)])
        assert verdicts[0]["status"] == "regression"
        assert verdicts[0]["delta_frac"] == pytest.approx(-0.2)

    def test_direction_awareness_and_improvement(self, tmp_path):
        store = BaselineStore(tmp_path / "b.json")
        base = {"phase": "train", "backend": "tpu", "preset": "p",
                "step_time_ms": 100.0, "images_per_sec": 1000.0}
        store.adopt_rows([base])
        worse = dict(base, step_time_ms=130.0, images_per_sec=1000.0)
        statuses = {v["metric"]: v["status"]
                    for v in check_rows(store, [worse])}
        assert statuses == {"step_time_ms": "regression",
                            "images_per_sec": "ok"}
        better = dict(base, step_time_ms=70.0, images_per_sec=1300.0)
        statuses = {v["metric"]: v["status"]
                    for v in check_rows(store, [better])}
        assert statuses == {"step_time_ms": "improved",
                            "images_per_sec": "improved"}

    def test_fallback_rows_reported_not_gated(self, tmp_path):
        store = BaselineStore(tmp_path / "b.json")
        store.adopt_rows([ROW])
        rows = [dict(ROW, qps=1.0, fallback=True),  # would be a -99.8% drop
                dict(ROW, qps=500.0)]
        verdicts = check_rows(store, rows)
        counts = summarize(verdicts)
        assert counts["regression"] == 0
        assert counts["fallback_excluded"] == 1 and counts["ok"] == 1

    def test_unbaselined_rows_are_visible(self, tmp_path):
        store = BaselineStore(tmp_path / "b.json")
        verdicts = check_rows(store, [ROW])
        assert [v["status"] for v in verdicts] == ["no_baseline"]

    def test_corrupt_store_reads_as_empty(self, tmp_path):
        p = tmp_path / "b.json"
        p.write_text("{not json")
        assert BaselineStore(p).baselines == {}


# ---------------------------------------------------------------------------
# obs regress / timeline CLI verbs
# ---------------------------------------------------------------------------

class TestObsCli:
    def run_obs(self, *argv):
        from jimm_tpu.obs.cli import main
        return main(["obs", *argv])

    def test_regress_adopt_pass_and_flag(self, tmp_path, capsys):
        m = tmp_path / "m.jsonl"
        b = tmp_path / "b.json"
        m.write_text(json.dumps(ROW) + "\nnot json\n")
        assert self.run_obs("regress", "--measurements", str(m),
                            "--baselines", str(b), "--adopt",
                            "--note", "test seed") == 0
        # unchanged rows pass...
        assert self.run_obs("regress", "--measurements", str(m),
                            "--baselines", str(b)) == 0
        # ...a 20% injected drop fails the gate
        m2 = tmp_path / "m2.jsonl"
        m2.write_text(json.dumps(dict(ROW, qps=505.0 * 0.8)) + "\n")
        assert self.run_obs("regress", "--measurements", str(m2),
                            "--baselines", str(b)) == 1
        assert "REGRESSION" in capsys.readouterr().out
        # fallback rows are excluded unless --fail-on-fallback
        m3 = tmp_path / "m3.jsonl"
        m3.write_text(json.dumps(dict(ROW, qps=1.0, fallback=True)) + "\n")
        assert self.run_obs("regress", "--measurements", str(m3),
                            "--baselines", str(b)) == 0
        assert self.run_obs("regress", "--measurements", str(m3),
                            "--baselines", str(b), "--fail-on-fallback") == 1

    def test_timeline_verb_round_trip(self, tmp_path, capsys):
        jpath = tmp_path / "journal.jsonl"
        j = EventJournal(jpath)
        cid = new_correlation_id()
        j.emit("replica_fault", cid=cid, replica=0)
        j.emit("heal_rebuilt", cid=cid, dur_s=0.2)
        j.close()
        traces = tmp_path / "traces.json"
        traces.write_text(json.dumps({"traces": [
            {"trace_id": 1, "replica": 0, "device_s": 0.01,
             "total_s": 0.01, "done_mono": 123.0}]}))
        out = tmp_path / "timeline.json"
        assert self.run_obs("timeline", str(jpath), "-o", str(out),
                            "--traces", str(traces)) == 0
        trace = json.loads(out.read_text())
        assert validate_chrome_trace(trace) == []
        names = {e["name"] for e in trace["traceEvents"]}
        assert {"replica_fault", "heal_rebuilt", "device"} <= names

    def test_tail_traces_from_file(self, tmp_path, capsys):
        dump = tmp_path / "traces.json"
        dump.write_text(json.dumps({"traces": [
            {"trace_id": 42, "replica": 1, "bucket": 8, "queue_s": 0.001,
             "device_s": 0.02, "total_s": 0.021}]}))
        assert self.run_obs("tail", "--traces", str(dump)) == 0
        out = capsys.readouterr().out
        assert "42" in out and "replica=1" in out and "device=20.00ms" in out


# ---------------------------------------------------------------------------
# single-sample percentiles (the timeline/SLO tooling leans on these)
# ---------------------------------------------------------------------------

class TestPercentileEdges:
    def test_single_sample_histogram(self):
        h = Histogram("lat")
        h.observe(42.0)
        assert h.percentile(50) == 42.0
        assert h.percentile(99) == 42.0
        snap = h.snapshot()
        assert snap["lat_p50"] == snap["lat_p99"] == 42.0
        assert snap["lat_count"] == 1

    def test_empty_and_two_sample(self):
        assert percentile([], 99) == 0.0
        assert percentile([1.0], 0) == 1.0
        assert percentile([1.0, 9.0], 50) == 1.0  # nearest rank (banker's)
        assert percentile([1.0, 9.0], 99) == 9.0
        assert percentile([1.0, 9.0], 0) == 1.0


# ---------------------------------------------------------------------------
# policy slo section -> engine
# ---------------------------------------------------------------------------

class TestPolicySlo:
    def test_policy_slo_parses_and_feeds_engine(self):
        from jimm_tpu.serve.qos.policy import TenantRegistry
        reg = TenantRegistry.from_dict({
            "tenants": {"alice": {"class": "interactive"}},
            "slo": {"alice": {"availability": 0.999, "latency_ms": 250},
                    "default": {"availability": 0.99}},
        })
        assert reg.slo["alice"] == {"availability": 0.999,
                                    "latency_ms": 250.0}
        assert reg.describe()["slo"]["default"] == {"availability": 0.99}
        eng = SloEngine.from_objective_dicts(
            reg.slo, registry=MetricRegistry("slo_test2"))
        assert eng.objectives["alice"].latency_ms == 250.0

    def test_policy_slo_validation(self):
        from jimm_tpu.serve.qos.policy import (QosPolicyError,
                                               TenantRegistry)
        base = {"tenants": {"alice": {"class": "interactive"}}}
        with pytest.raises(QosPolicyError, match="not a declared tenant"):
            TenantRegistry.from_dict(
                dict(base, slo={"ghost": {"availability": 0.9}}))
        with pytest.raises(QosPolicyError, match="availability"):
            TenantRegistry.from_dict(
                dict(base, slo={"alice": {"availability": 2}}))
        with pytest.raises(QosPolicyError, match="unknown keys"):
            TenantRegistry.from_dict(
                dict(base, slo={"alice": {"burn": 1}}))
        assert TenantRegistry.from_dict(base).slo == {}


# ---------------------------------------------------------------------------
# the correlated incident chain through the supervisor
# ---------------------------------------------------------------------------

class TestIncidentChain:
    def test_supervisor_threads_one_cid_through_recovery(
            self, fresh_global_journal):
        from jimm_tpu.resilience import Supervisor

        calls = []

        def attempt(i, resume):
            # whatever the restarted attempt emits joins the incident
            calls.append(current_cid())
            if i == 0:
                raise RuntimeError("worker died")
            get_journal().emit("checkpoint_restored", step=3)
            return 0

        sup = Supervisor(max_restarts=2, sleep=lambda s: None)
        assert sup.run(attempt) == 0
        events = fresh_global_journal.events()
        failed = [e for e in events if e["event"] == "attempt_failed"]
        assert len(failed) == 1
        cid = failed[0]["cid"]
        assert cid
        got = [e["event"] for e in chain(events, cid)]
        assert got == ["attempt_failed", "restart", "checkpoint_restored",
                       "supervise_recovered"]
        # first attempt ran uncorrelated, the restart inherited the cid
        assert calls == [None, cid]

    def test_preemption_cid_carries_across_the_error(
            self, fresh_global_journal):
        from jimm_tpu.resilience import Supervisor
        from jimm_tpu.resilience.preemption import PreemptedError

        def attempt(i, resume):
            if i == 0:
                raise PreemptedError(5, cid="preempt-cid")
            return 0

        sup = Supervisor(max_restarts=1, sleep=lambda s: None)
        assert sup.run(attempt) == 0
        events = fresh_global_journal.events()
        got = {e["event"] for e in chain(events, "preempt-cid")}
        assert {"attempt_failed", "restart", "supervise_recovered"} <= got

    def test_give_up_emits_terminal_event(self, fresh_global_journal):
        from jimm_tpu.resilience import GiveUpError, Supervisor

        def attempt(i, resume):
            raise RuntimeError("boom")

        sup = Supervisor(max_restarts=1, sleep=lambda s: None)
        with pytest.raises(GiveUpError):
            sup.run(attempt)
        events = fresh_global_journal.events()
        gave_up = [e for e in events if e["event"] == "supervise_gave_up"]
        assert len(gave_up) == 1 and gave_up[0]["attempts"] == 2
        # both failures chained onto the one incident the first crash minted
        assert len(chain(events, gave_up[0]["cid"])) == 4
