"""Project-wide symbol table and call graph for whole-program lint checks.

Layer 1 rules see one file at a time; everything here exists so rules can
ask questions that cross file boundaries:

- *who calls this function, and from which thread?* Entry points are
  discovered structurally — ``threading.Thread(target=...)``, executor
  ``submit``/``run_in_executor``, ``asyncio.run_coroutine_threadsafe``,
  ``do_VERB`` HTTP handlers, gauge/done callbacks, ``async def`` bodies —
  and propagated through resolved call edges, so "this attribute is
  written from the event loop AND an HTTP handler thread" is a query, not
  a guess.
- *which locks protect this statement?* Lexical ``with <lock>:`` contexts
  are tracked per statement, and a callee inherits the locks every one of
  its (direct, same-thread) callers holds, so a helper that is only ever
  invoked under ``self._lock`` counts as guarded.
- *what type is this expression?* A deliberately small inferencer —
  parameter/attribute annotations, ``self.x = ClassName(...)``,
  container element types, function return annotations — resolves enough
  receivers (``self.engine.submit``, ``get_journal().emit``) to build a
  useful edge set without import-time execution. Unresolvable calls are
  dropped, never guessed wide: every analysis downstream is tuned to
  prefer a false negative over a false positive.

Everything is stdlib ``ast`` — like the per-file rules, building the graph
imports nothing from the analyzed project.
"""

from __future__ import annotations

import ast
import dataclasses

from jimm_tpu.lint.core import collect_files

__all__ = ["ProjectGraph", "FunctionInfo", "ClassInfo", "ModuleInfo",
           "WriteSite", "CallSite", "AcquireSite", "BlockSite"]

#: method names that mark a function as an HTTP-request thread entry
DO_VERBS = frozenset({"do_GET", "do_POST", "do_PUT", "do_DELETE", "do_HEAD"})

#: lock constructors, by discipline (asyncio locks guard tasks, not threads)
_THREAD_LOCK_CTORS = frozenset({"threading.Lock", "threading.RLock",
                                "threading.Condition", "Lock", "RLock",
                                "Condition"})
_ASYNC_LOCK_CTORS = frozenset({"asyncio.Lock", "asyncio.Condition"})

#: callback registrars: attr name -> the thread root the callback runs on
_CALLBACK_ROOTS = {
    "bind_gauge": "metrics-scrape",  # evaluated inside snapshot()/scrapes
    "gauge": "metrics-scrape",       # MetricRegistry.gauge(name, fn)
    "add_done_callback": "loop",     # asyncio task callbacks run on the loop
}

#: method names too generic to resolve by name alone (dict.get, list.append,
#: Queue.put, Executor.submit... a name-match here would wire half the tree
#: together); typed receivers still resolve these precisely
_COMMON_METHOD_NAMES = frozenset({
    "get", "put", "pop", "items", "keys", "values", "append", "appendleft",
    "add", "close", "open", "read", "write", "update", "copy", "start",
    "stop", "run", "join", "wait", "set", "clear", "result", "done",
    "cancel", "send", "recv", "acquire", "release", "submit", "snapshot",
    "emit", "reset", "flush", "count", "observe", "inc", "tail", "events",
    "describe", "search", "encode", "decode", "render", "log", "select",
    "next", "extend", "index", "sort", "split", "merge", "setdefault",
    "serve_forever", "shutdown", "server_close",
})

#: dotted call names that block the calling thread (JL019's vocabulary);
#: file writes/flushes are deliberately absent — writing under a lock is
#: the journal's correctness mechanism, not a hazard
_BLOCKING_DOTTED = frozenset({
    "time.sleep", "urllib.request.urlopen", "urlopen", "requests.get",
    "requests.post", "requests.request", "socket.create_connection",
    "subprocess.run", "subprocess.check_output", "subprocess.check_call",
})

#: receiver type -> method names that block on it
_BLOCKING_METHODS = {
    "queue.Queue": frozenset({"get", "put", "join"}),
    "threading.Thread": frozenset({"join"}),
    "threading.Event": frozenset({"wait"}),
    "threading.Condition": frozenset({"wait", "wait_for"}),
}

#: attribute calls that block regardless of receiver type
_BLOCKING_ATTRS = frozenset({"block_until_ready"})

#: device-sync calls for the interprocedural JL006 escalation (narrower
#: than HOST_SYNC_CALLS: np.asarray of a host list is legitimate loop work,
#: a device wait never is)
_DEVICE_SYNC_DOTTED = frozenset({"jax.device_get", "device_get"})

#: tier-IO vocabulary (JL023): calls that move cluster payloads to or from
#: disk *synchronously*. Enqueue-style calls (``TierIoEngine.prefetch``)
#: and waiting on a worker-completed fetch (``.collect``) are deliberately
#: absent — that worker-thread split is the sanctioned request-path shape;
#: what the rule hunts is the direct read/write that skips the worker.
_TIER_IO_DOTTED = frozenset({"np.load", "numpy.load", "np.fromfile",
                             "numpy.fromfile", "np.save", "numpy.save"})
_TIER_IO_ATTRS = frozenset({"read_bytes", "write_bytes"})
#: receiver class name -> methods that hit the artifact store / disk
_TIER_IO_CLASSES = {
    "ArtifactStore": frozenset({"get", "put"}),
    "TierIoEngine": frozenset({"spill"}),
}

_EVICTION_METHODS = frozenset({"pop", "popitem", "popleft", "clear"})


@dataclasses.dataclass
class WriteSite:
    """One instance-attribute mutation: ``obj.attr = ...``, ``obj.attr +=``,
    or ``next(obj.attr)`` (advancing a stateful iterator IS a write)."""
    owner: str                 # resolved class name of ``obj``
    attr: str
    func: "FunctionInfo"
    lineno: int
    guards: frozenset         # lexical thread-lock ids held at the write
    in_init: bool
    kind: str                  # "store" | "aug" | "next"


@dataclasses.dataclass
class CallSite:
    callee: str | None         # resolved function id (None: unresolved)
    raw: str                   # best-effort dotted descriptor
    lineno: int
    guards: frozenset         # thread-lock ids lexically held at the call
    ctx: str                   # "direct" | "thread:<name>" | "executor"
    #                          # | "loop" | callback root name


@dataclasses.dataclass
class AcquireSite:
    lock: str
    lineno: int
    held: frozenset           # every lock id (thread + async) held before
    kind: str                  # "threading" | "asyncio"


@dataclasses.dataclass
class BlockSite:
    what: str
    lineno: int
    guards: frozenset         # thread-lock ids lexically held


class FunctionInfo:
    """One function/method/lambda-callback: its collected facts plus the
    propagation results (thread roots, locks held at entry)."""

    def __init__(self, fid: str, name: str, qual: str, path: str,
                 node: ast.AST, cls: "ClassInfo | None", module: "ModuleInfo",
                 is_async: bool):
        self.fid = fid
        self.name = name
        self.qual = qual
        self.path = path
        self.node = node
        self.cls = cls
        self.module = module
        self.is_async = is_async
        self.lineno = getattr(node, "lineno", 0)
        self.writes: list[WriteSite] = []
        self.calls: list[CallSite] = []
        self.acquires: list[AcquireSite] = []
        self.blocking: list[BlockSite] = []
        self.tier_io: list[BlockSite] = []
        self.device_syncs: list[tuple[str, int]] = []
        self.jit_sites: list[int] = []
        self.swallow_lines: list[int] = []
        self.param_types: dict[str, str] = {}
        self.local_types: dict[str, str] = {}
        self.return_type: str | None = None
        #: thread roots this function is reachable from (propagated)
        self.roots: set[str] = set()
        #: thread-lock ids held on EVERY same-thread path into this
        #: function (None until some caller is seen; resolves to set())
        self.entry_guards: frozenset | None = None

    def effective_guards(self, lexical: frozenset) -> frozenset:
        return lexical | (self.entry_guards or frozenset())

    def __repr__(self):
        return f"<fn {self.qual} roots={sorted(self.roots)}>"


class ClassInfo:
    def __init__(self, name: str, path: str, node: ast.ClassDef,
                 module: "ModuleInfo"):
        self.name = name
        self.path = path
        self.node = node
        self.module = module
        self.bases: list[str] = []
        self.methods: dict[str, FunctionInfo] = {}
        #: lock-valued attributes: attr -> "threading" | "asyncio"
        self.lock_attrs: dict[str, str] = {}
        #: attr -> class-name (annotations + ``self.x = ClassName(...)``)
        self.attr_types: dict[str, str] = {}
        #: attr -> element class-name for list-of-instances containers
        self.elem_types: dict[str, str] = {}
        #: attrs with an eviction path somewhere in THIS class body
        self.evict_attrs: set[str] = set()

    def __repr__(self):
        return f"<class {self.name} locks={sorted(self.lock_attrs)}>"


class ModuleInfo:
    def __init__(self, path: str, name: str):
        self.path = path
        self.name = name
        self.imports: dict[str, str] = {}       # alias -> dotted module
        self.from_imports: dict[str, str] = {}  # name -> "module.name"
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self.locks: dict[str, str] = {}         # module-global lock -> kind


def _dotted(node: ast.AST) -> str | None:
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _ann_type(ann: ast.AST | None) -> str | None:
    """Class name out of an annotation: ``Foo``, ``"Foo"``, ``Foo | None``,
    ``Optional[Foo]``, ``module.Foo``. Returns the dotted name."""
    if ann is None:
        return None
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if isinstance(ann, ast.BinOp) and isinstance(ann.op, ast.BitOr):
        for side in (ann.left, ann.right):
            got = _ann_type(side)
            if got is not None and got != "None":
                return got
        return None
    if isinstance(ann, ast.Subscript):
        base = _dotted(ann.value)
        if base in ("Optional", "typing.Optional"):
            return _ann_type(ann.slice)
        return base  # "queue.Queue" from queue.Queue[int], list[...] -> list
    name = _dotted(ann)
    return None if name in (None, "None") else name


def _ann_elem(ann: ast.AST | None) -> str | None:
    """Element/value type of a container annotation: ``list[X]`` -> X,
    ``dict[K, V]`` -> V (what subscripting/iterating values() yields)."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return None
    if not isinstance(ann, ast.Subscript):
        return None
    base = (_dotted(ann.value) or "").rsplit(".", 1)[-1]
    sl = ann.slice
    if base in ("list", "List", "set", "Set", "deque"):
        return _ann_type(sl)
    if base in ("dict", "Dict") and isinstance(sl, ast.Tuple) \
            and len(sl.elts) == 2:
        return _ann_type(sl.elts[1])
    return None


def _lock_ctor_kind(value: ast.AST) -> str | None:
    if not isinstance(value, ast.Call):
        return None
    name = _dotted(value.func)
    if name in _THREAD_LOCK_CTORS:
        return "threading"
    if name in _ASYNC_LOCK_CTORS:
        return "asyncio"
    return None


def _module_name(path: str) -> str:
    p = path.replace("\\", "/")
    if p.endswith(".py"):
        p = p[:-3]
    parts = [seg for seg in p.split("/") if seg not in ("", ".", "..")]
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts[-4:])  # tail is plenty for resolution + display


class ProjectGraph:
    """The whole-program index: modules, classes, functions, call edges,
    thread roots, and inferred guard sets."""

    def __init__(self):
        self.modules: dict[str, ModuleInfo] = {}
        self.functions: dict[str, FunctionInfo] = {}
        self.classes: dict[str, ClassInfo] = {}
        self._methods_by_name: dict[str, list[FunctionInfo]] = {}
        self._lambda_n = 0

    # -- construction ------------------------------------------------------

    @classmethod
    def build(cls, paths: list[str]) -> "ProjectGraph":
        """Parse every ``.py`` file under ``paths`` and run resolution,
        root propagation, and entry-guard inference. Unparseable files are
        skipped (JL000 already reports them per-file)."""
        graph = cls()
        for path in collect_files(paths):
            try:
                with open(path, encoding="utf-8") as f:
                    tree = ast.parse(f.read(), filename=path)
            except (OSError, UnicodeDecodeError, SyntaxError):
                continue
            graph._collect_module(path, tree)
        graph._link_http_handlers()
        graph._scan_bodies()
        graph._propagate_roots()
        graph._propagate_entry_guards()
        return graph

    # -- pass 1: symbols ---------------------------------------------------

    def _collect_module(self, path: str, tree: ast.Module) -> None:
        mod = ModuleInfo(path, _module_name(path))
        self.modules[path] = mod
        for node in tree.body:
            if isinstance(node, ast.Import):
                for alias in node.names:
                    mod.imports[alias.asname or alias.name.split(".")[0]] = \
                        alias.name
            elif isinstance(node, ast.ImportFrom):
                for alias in node.names:
                    mod.from_imports[alias.asname or alias.name] = \
                        f"{node.module or ''}.{alias.name}"
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, node, cls=None)
            elif isinstance(node, ast.ClassDef):
                self._add_class(mod, node)
            elif isinstance(node, ast.Assign):
                kind = _lock_ctor_kind(node.value)
                if kind is not None:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            mod.locks[tgt.id] = kind

    def _add_function(self, mod: ModuleInfo, node, cls: ClassInfo | None,
                      parent_qual: str = "") -> FunctionInfo:
        if parent_qual:
            qual = f"{parent_qual}.{node.name}"
        elif cls is not None:
            qual = f"{cls.name}.{node.name}"
        else:
            qual = node.name
        fid = f"{mod.path}::{qual}"
        info = FunctionInfo(fid, node.name, qual, mod.path, node, cls, mod,
                            isinstance(node, ast.AsyncFunctionDef))
        args = node.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            t = _ann_type(a.annotation)
            if t is not None:
                info.param_types[a.arg] = t
        info.return_type = _ann_type(node.returns)
        self.functions[fid] = info
        if parent_qual:
            pass  # a closure, not a method/module function
        elif cls is not None:
            cls.methods[node.name] = info
            self._methods_by_name.setdefault(node.name, []).append(info)
        else:
            mod.functions.setdefault(node.name, info)
        # nested defs are separate functions (they may run on other threads
        # via Thread(target=run)); `self` inside them closes over the
        # enclosing method's instance, so they keep the same class context
        for stmt in ast.walk(node):
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and stmt is not node and self._direct_parent_fn(
                        node, stmt) is node:
                self._add_function(mod, stmt, cls=cls, parent_qual=qual)
        return info

    @staticmethod
    def _direct_parent_fn(root, target) -> ast.AST | None:
        """The innermost function node enclosing ``target`` within
        ``root`` (``root`` itself when un-nested further)."""
        parent = root
        stack = [(root, root)]
        while stack:
            node, owner = stack.pop()
            for child in ast.iter_child_nodes(node):
                if child is target:
                    return owner
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)) and \
                        child is not target:
                    stack.append((child, child))
                else:
                    stack.append((child, owner))
        return parent

    def _add_class(self, mod: ModuleInfo, node: ast.ClassDef) -> None:
        ci = ClassInfo(node.name, mod.path, node, mod)
        ci.bases = [b for b in (_dotted(base) for base in node.bases)
                    if b is not None]
        mod.classes[node.name] = ci
        self.classes.setdefault(node.name, ci)
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(mod, stmt, cls=ci)
            elif isinstance(stmt, ast.AnnAssign) and \
                    isinstance(stmt.target, ast.Name):
                t = _ann_type(stmt.annotation)
                if t is not None:
                    ci.attr_types[stmt.target.id] = t
                elem = _ann_elem(stmt.annotation)
                if elem is not None:
                    ci.elem_types[stmt.target.id] = elem
        # attribute facts come from every method body: lock attrs, attr
        # types from annotated-parameter assignment / direct construction,
        # container element types, and eviction evidence
        for meth in ast.walk(node):
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._scan_class_attrs(ci, meth)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr in _EVICTION_METHODS:
                attr = self._self_attr(sub.func.value)
                if attr is not None:
                    ci.evict_attrs.add(attr)
            elif isinstance(sub, ast.Delete):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Subscript):
                        attr = self._self_attr(tgt.value)
                        if attr is not None:
                            ci.evict_attrs.add(attr)

    @staticmethod
    def _self_attr(node: ast.AST) -> str | None:
        if isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and node.value.id == "self":
            return node.attr
        return None

    def _scan_class_attrs(self, ci: ClassInfo, meth) -> None:
        params = {}
        args = meth.args
        for a in (args.posonlyargs + args.args + args.kwonlyargs):
            t = _ann_type(a.annotation)
            if t is not None:
                params[a.arg] = t
        for node in ast.walk(meth):
            targets = []
            value = None
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets, value = [node.target], node.value
                attr = self._self_attr(node.target)
                if attr is not None:
                    ann = _ann_type(node.annotation)
                    if ann is not None:
                        ci.attr_types.setdefault(attr, ann)
                    elem = _ann_elem(node.annotation)
                    if elem is not None:
                        ci.elem_types.setdefault(attr, elem)
            for tgt in targets:
                attr = self._self_attr(tgt)
                if attr is None:
                    continue
                kind = _lock_ctor_kind(value)
                if kind is not None:
                    ci.lock_attrs[attr] = kind
                    continue
                if isinstance(value, ast.Call):
                    name = _dotted(value.func)
                    if name is not None:
                        ci.attr_types.setdefault(attr, name)
                elif isinstance(value, ast.Name) and value.id in params:
                    ci.attr_types.setdefault(attr, params[value.id])
                elif isinstance(value, (ast.ListComp, ast.List)):
                    elts = ([value.elt] if isinstance(value, ast.ListComp)
                            else value.elts)
                    for elt in elts:
                        if isinstance(elt, ast.Call):
                            name = _dotted(elt.func)
                            if name is not None:
                                ci.elem_types.setdefault(attr, name)
                                break

    def _link_http_handlers(self) -> None:
        """``BaseHTTPRequestHandler`` subclasses see the server instance as
        ``self.server`` (set by the stdlib, invisible to annotation-driven
        inference). When a module pairs a ``do_VERB`` handler class with an
        ``*HTTPServer`` subclass, wire the attribute so ``self.server.app``
        chains resolve."""
        for mod in self.modules.values():
            server_cls = next(
                (ci for ci in mod.classes.values()
                 if any(b.rsplit(".", 1)[-1].endswith("HTTPServer")
                        for b in ci.bases)), None)
            if server_cls is None:
                continue
            for ci in mod.classes.values():
                if any(name in DO_VERBS for name in ci.methods):
                    ci.attr_types.setdefault("server", server_cls.name)

    # -- type resolution ---------------------------------------------------

    def _class_named(self, name: str | None) -> ClassInfo | None:
        if name is None:
            return None
        return self.classes.get(name.rsplit(".", 1)[-1])

    def inherited_evictions(self, ci: ClassInfo) -> set[str]:
        """Evicted attrs of ``ci`` including its project base classes —
        the interprocedural complement to JL014's per-class scan."""
        out, stack, seen = set(), [ci], set()
        while stack:
            cur = stack.pop()
            if cur.name in seen:
                continue
            seen.add(cur.name)
            out |= cur.evict_attrs
            for base in cur.bases:
                bi = self._class_named(base)
                if bi is not None:
                    stack.append(bi)
        return out

    def _expr_type(self, expr: ast.AST, fn: FunctionInfo,
                   depth: int = 0) -> str | None:
        """Best-effort static type (a dotted class name) of ``expr``."""
        if depth > 4:
            return None
        if isinstance(expr, ast.Name):
            if expr.id == "self" and fn.cls is not None:
                return fn.cls.name
            return fn.local_types.get(expr.id) or fn.param_types.get(expr.id)
        if isinstance(expr, ast.Attribute):
            owner = self._expr_type(expr.value, fn, depth + 1)
            oc = self._class_named(owner)
            if oc is not None:
                return oc.attr_types.get(expr.attr)
            return None
        if isinstance(expr, ast.Subscript):
            owner = self._expr_type(expr.value, fn, depth + 1)
            # container element type: self._replicas[i] -> _Replica
            if isinstance(expr.value, ast.Attribute):
                oc = self._class_named(
                    self._expr_type(expr.value.value, fn, depth + 1))
                if oc is not None:
                    elem = oc.elem_types.get(expr.value.attr)
                    if elem is not None:
                        return elem
            return None if owner in (None, "list", "dict") else None
        if isinstance(expr, ast.Call):
            name = _dotted(expr.func)
            if self._class_named(name) is not None:
                return name  # constructor call
            callee = self._resolve_call_target(expr.func, fn)
            if callee is not None:
                return callee.return_type
            return None
        return None

    def _resolve_call_target(self, func: ast.AST,
                             fn: FunctionInfo) -> FunctionInfo | None:
        """Resolve a call expression's target to a project function."""
        if isinstance(func, ast.Name):
            mod = fn.module
            if func.id in mod.functions:
                return mod.functions[func.id]
            # nested def in the same enclosing scope
            nested = self.functions.get(f"{fn.path}::{fn.qual}.{func.id}")
            if nested is not None:
                return nested
            imported = mod.from_imports.get(func.id)
            if imported is not None:
                leaf = imported.rsplit(".", 1)[-1]
                for other in self.modules.values():
                    if leaf in other.functions and \
                            other.name.endswith(
                                imported.rsplit(".", 1)[0].split(".")[-1]):
                        return other.functions[leaf]
                for other in self.modules.values():
                    if leaf in other.functions:
                        return other.functions[leaf]
            ctor = self._class_named(func.id)
            if ctor is not None:
                return ctor.methods.get("__init__")
            return None
        if isinstance(func, ast.Attribute):
            recv_type = self._expr_type(func.value, fn)
            ci = self._class_named(recv_type)
            if ci is not None:
                return self._method_on(ci, func.attr)
            # module.function via plain imports
            base = _dotted(func.value)
            if base is not None and base in fn.module.imports:
                target_mod = fn.module.imports[base]
                for other in self.modules.values():
                    if other.name.endswith(target_mod.split(".")[-1]) and \
                            func.attr in other.functions:
                        return other.functions[func.attr]
            # last resort, ONLY for receivers with no inferred type: a
            # method name that is project-unique and not generic —
            # `state.bucket.try_take(...)` resolves, `.get()` never; a
            # known non-project receiver (asyncio.Queue, an executor)
            # never falls through to this, so stdlib methods that happen
            # to share a project method's name don't create false edges
            if recv_type is None and func.attr not in _COMMON_METHOD_NAMES:
                cands = self._methods_by_name.get(func.attr, [])
                if len(cands) == 1:
                    return cands[0]
            return None
        return None

    def _method_on(self, ci: ClassInfo, name: str) -> FunctionInfo | None:
        seen: set[str] = set()
        stack = [ci]
        while stack:
            cur = stack.pop()
            if cur.name in seen:
                continue
            seen.add(cur.name)
            if name in cur.methods:
                return cur.methods[name]
            for base in cur.bases:
                bi = self._class_named(base)
                if bi is not None:
                    stack.append(bi)
        return None

    def _methods_named(self, name: str) -> list[FunctionInfo]:
        return list(self._methods_by_name.get(name, []))

    # -- pass 2: bodies ----------------------------------------------------

    def _scan_bodies(self) -> None:
        # local types first (two rounds so x = self.attr chains settle),
        # then the guard-context body walk
        for info in list(self.functions.values()):
            self._infer_locals(info)
        for info in list(self.functions.values()):
            self._infer_locals(info)
        for info in list(self.functions.values()):
            body = getattr(info.node, "body", None)
            if body is not None:
                self._walk_stmts(info, body, frozenset(), frozenset())

    def _infer_locals(self, fn: FunctionInfo) -> None:
        for node in ast.walk(fn.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn.node:
                continue
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                t = self._expr_type(node.value, fn)
                if t is not None:
                    fn.local_types.setdefault(node.targets[0].id, t)
            elif isinstance(node, ast.For) and \
                    isinstance(node.target, ast.Name) and \
                    isinstance(node.iter, ast.Attribute):
                oc = self._class_named(self._expr_type(node.iter.value, fn))
                if oc is not None:
                    elem = oc.elem_types.get(node.iter.attr)
                    if elem is not None:
                        fn.local_types.setdefault(node.target.id, elem)

    def _lock_id(self, expr: ast.AST, fn: FunctionInfo
                 ) -> tuple[str, str] | None:
        """(lock id, kind) when ``expr`` denotes a known lock object."""
        if isinstance(expr, ast.Name):
            kind = fn.module.locks.get(expr.id)
            if kind is not None:
                return f"{fn.module.name}.{expr.id}", kind
            t = fn.local_types.get(expr.id) or fn.param_types.get(expr.id)
            if t in _THREAD_LOCK_CTORS:
                return f"{fn.qual}.{expr.id}", "threading"
            return None
        if isinstance(expr, ast.Attribute):
            owner = self._class_named(self._expr_type(expr.value, fn))
            if owner is not None and expr.attr in owner.lock_attrs:
                return (f"{owner.name}.{expr.attr}",
                        owner.lock_attrs[expr.attr])
        return None

    def _walk_stmts(self, fn: FunctionInfo, stmts, held_thread: frozenset,
                    held_all: frozenset) -> None:
        for stmt in stmts:
            self._walk_stmt(fn, stmt, held_thread, held_all)

    def _walk_stmt(self, fn: FunctionInfo, stmt: ast.AST,
                   held_thread: frozenset, held_all: frozenset) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate FunctionInfo scans its own body
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            new_thread, new_all = set(held_thread), set(held_all)
            for item in stmt.items:
                self._scan_expr(fn, item.context_expr, held_thread, held_all)
                got = self._lock_id(item.context_expr, fn)
                if got is not None:
                    lock, kind = got
                    fn.acquires.append(AcquireSite(
                        lock, stmt.lineno, frozenset(held_all), kind))
                    new_all.add(lock)
                    if kind == "threading":
                        new_thread.add(lock)
            self._walk_stmts(fn, stmt.body, frozenset(new_thread),
                             frozenset(new_all))
            return
        # non-with statements: scan this node's own expressions, then
        # recurse into child statements with the same lock context
        for field in stmt._fields:
            value = getattr(stmt, field, None)
            for child in (value if isinstance(value, list) else [value]):
                if isinstance(child, ast.stmt):
                    self._walk_stmt(fn, child, held_thread, held_all)
                elif isinstance(child, ast.expr):
                    self._scan_expr(fn, child, held_thread, held_all)
                elif isinstance(child, (ast.excepthandler,)):
                    self._note_swallow(fn, child)
                    self._walk_stmts(fn, child.body, held_thread, held_all)
                elif isinstance(child, (ast.withitem, ast.keyword)):
                    pass
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            self._note_writes(fn, stmt, held_thread)

    def _note_swallow(self, fn: FunctionInfo, h: ast.excepthandler) -> None:
        broad = h.type is None or (isinstance(h.type, ast.Name)
                                   and h.type.id in ("Exception",
                                                     "BaseException"))
        if broad and all(isinstance(s, ast.Pass) for s in h.body):
            fn.swallow_lines.append(h.lineno)

    def _note_writes(self, fn: FunctionInfo, stmt,
                     held_thread: frozenset) -> None:
        if isinstance(stmt, ast.Assign):
            targets, kind = stmt.targets, "store"
        elif isinstance(stmt, ast.AugAssign):
            targets, kind = [stmt.target], "aug"
        else:
            targets, kind = [stmt.target], "store"
        for tgt in targets:
            nodes = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for node in nodes:
                if not isinstance(node, ast.Attribute):
                    continue
                owner = self._expr_type(node.value, fn)
                oc = self._class_named(owner)
                if oc is None:
                    continue
                fn.writes.append(WriteSite(
                    oc.name, node.attr, fn, node.lineno, held_thread,
                    fn.name == "__init__" and oc is fn.cls, kind))

    def _scan_expr(self, fn: FunctionInfo, expr: ast.AST | None,
                   held_thread: frozenset, held_all: frozenset) -> None:
        """Walk an expression tree noting calls. Calls consumed by a
        special form (a thread target, an executor submission, the
        coroutine handed to ``run_coroutine_threadsafe``) must NOT also be
        recorded as plain same-thread edges, so the walk descends manually
        and skips whatever :meth:`_note_call` claims."""
        if expr is None:
            return
        stack: list[ast.AST] = [expr]
        while stack:
            node = stack.pop()
            if isinstance(node, ast.Lambda):
                continue  # callbacks are handled at registrar call sites
            if isinstance(node, ast.Call):
                consumed = self._note_call(fn, node, held_thread, held_all)
                stack.extend(c for c in ast.iter_child_nodes(node)
                             if all(c is not skip for skip in consumed))
            else:
                stack.extend(ast.iter_child_nodes(node))

    def _callable_arg(self, fn: FunctionInfo, arg: ast.AST,
                      lineno: int, ctx: str) -> None:
        """An expression passed somewhere it will be *invoked* on another
        thread/root: resolve it (or scan a lambda as a synthetic fn)."""
        if isinstance(arg, ast.Lambda):
            self._lambda_n += 1
            lam = FunctionInfo(
                f"{fn.path}::{fn.qual}.<lambda@{lineno}.{self._lambda_n}>",
                "<lambda>", f"{fn.qual}.<lambda@{lineno}>", fn.path,
                arg, fn.cls, fn.module, False)
            lam.param_types = dict(fn.param_types)
            lam.local_types = dict(fn.local_types)
            self.functions[lam.fid] = lam
            self._scan_expr(lam, arg.body, frozenset(), frozenset())
            fn.calls.append(CallSite(lam.fid, lam.qual, lineno,
                                     frozenset(), ctx))
            return
        target = self._resolve_call_target(arg, fn) if isinstance(
            arg, (ast.Name, ast.Attribute)) else None
        if target is not None:
            fn.calls.append(CallSite(target.fid, target.qual, lineno,
                                     frozenset(), ctx))

    def _note_call(self, fn: FunctionInfo, node: ast.Call,
                   held_thread: frozenset, held_all: frozenset
                   ) -> list[ast.AST]:
        """Record whatever ``node`` means for the graph; returns the child
        expressions the caller must NOT descend into (already consumed as
        spawn targets / callbacks / loop-dispatched coroutines)."""
        name = _dotted(node.func) or ""
        attr = node.func.attr if isinstance(node.func, ast.Attribute) else ""
        lineno = node.lineno

        # thread/executor/loop/callback entry discovery ---------------------
        if name.endswith("threading.Thread") or name == "Thread":
            target = next((kw.value for kw in node.keywords
                           if kw.arg == "target"), None)
            if target is not None:
                label = _dotted(target) or "<lambda>"
                self._callable_arg(fn, target, lineno,
                                   f"thread:{label.rsplit('.', 1)[-1]}")
                return [target]
            return []
        if attr == "run_in_executor" and len(node.args) >= 2:
            self._callable_arg(fn, node.args[1], lineno, "executor")
            return [node.args[1]]
        if attr == "submit" and node.args and isinstance(
                node.args[0], (ast.Name, ast.Attribute, ast.Lambda)):
            # executor.submit(fn, ...) — only when arg0 IS a callable ref
            # AND the receiver is not a project class with its own submit
            # (engine.submit(image) resolves as a plain method call below)
            target = self._resolve_call_target(node.args[0], fn) \
                if not isinstance(node.args[0], ast.Lambda) else None
            if target is not None or isinstance(node.args[0], ast.Lambda):
                recv = self._expr_type(node.func.value, fn)
                if self._class_named(recv) is None:
                    self._callable_arg(fn, node.args[0], lineno, "executor")
                    return [node.args[0]]
        if name.endswith("run_coroutine_threadsafe") and node.args and \
                isinstance(node.args[0], ast.Call):
            # the coroutine runs on the event loop thread, with none of
            # this caller's locks held
            inner = node.args[0]
            target = self._resolve_call_target(inner.func, fn)
            if target is not None:
                fn.calls.append(CallSite(target.fid, target.qual,
                                         inner.lineno, frozenset(), "loop"))
            for arg in list(inner.args) + [kw.value
                                           for kw in inner.keywords]:
                self._scan_expr(fn, arg, held_thread, held_all)
            return [inner]
        if attr in _CALLBACK_ROOTS:
            consumed: list[ast.AST] = []
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                if isinstance(arg, ast.Lambda) or (
                        isinstance(arg, (ast.Name, ast.Attribute)) and
                        self._resolve_call_target(arg, fn) is not None):
                    self._callable_arg(fn, arg, lineno, _CALLBACK_ROOTS[attr])
                    consumed.append(arg)
            return consumed

        # next(obj.attr): advancing a shared iterator is a write ------------
        if name == "next" and node.args and \
                isinstance(node.args[0], ast.Attribute):
            tgt = node.args[0]
            oc = self._class_named(self._expr_type(tgt.value, fn))
            if oc is not None:
                fn.writes.append(WriteSite(
                    oc.name, tgt.attr, fn, lineno, held_thread,
                    fn.name == "__init__", "next"))

        # blocking calls ----------------------------------------------------
        blocked = None
        if name in _BLOCKING_DOTTED:
            blocked = name
        elif attr in _BLOCKING_ATTRS:
            blocked = f".{attr}()"
        elif attr and isinstance(node.func, ast.Attribute):
            recv = self._expr_type(node.func.value, fn)
            if recv is not None:
                for rtype, meths in _BLOCKING_METHODS.items():
                    if recv.endswith(rtype.rsplit(".", 1)[-1]) and \
                            recv.split(".")[0] == rtype.split(".")[0] and \
                            attr in meths:
                        blocked = f"{recv}.{attr}()"
                # Condition.wait releases ITS OWN lock while waiting: only
                # *other* held locks make it a hazard
                if blocked and attr in ("wait", "wait_for"):
                    own = self._lock_id(node.func.value, fn)
                    if own is not None and held_thread <= {own[0]}:
                        blocked = None
        if blocked is not None:
            fn.blocking.append(BlockSite(blocked, lineno, held_thread))

        # tier IO (interprocedural JL023) -----------------------------------
        tio = None
        if name in _TIER_IO_DOTTED:
            tio = name
        elif attr in _TIER_IO_ATTRS:
            tio = f".{attr}()"
        elif attr and isinstance(node.func, ast.Attribute):
            recv = self._expr_type(node.func.value, fn)
            if recv is not None:
                meths = _TIER_IO_CLASSES.get(recv.rsplit(".", 1)[-1])
                if meths is not None and attr in meths:
                    tio = f"{recv}.{attr}()"
            elif attr in ("read", "write") and \
                    isinstance(node.func.value, ast.Call) and \
                    isinstance(node.func.value.func, ast.Name) and \
                    node.func.value.func.id == "open":
                # open(...).read(): an unbuffered inline file transfer
                tio = f"open().{attr}()"
        if tio is not None:
            fn.tier_io.append(BlockSite(tio, lineno, held_thread))

        # device syncs + jit construction (interprocedural JL006/JL008) ----
        if name in _DEVICE_SYNC_DOTTED or attr in _BLOCKING_ATTRS:
            fn.device_syncs.append((name or f".{attr}()", lineno))
        if name == "jit" or name.endswith(".jit"):
            fn.jit_sites.append(lineno)

        # plain resolved edge ----------------------------------------------
        target = self._resolve_call_target(node.func, fn)
        if target is not None:
            fn.calls.append(CallSite(target.fid, target.qual, lineno,
                                     held_thread, "direct"))
        return []

    # -- pass 3: propagation ----------------------------------------------

    def _propagate_roots(self) -> None:
        for info in self.functions.values():
            if info.is_async:
                info.roots.add("loop")
            if info.name in DO_VERBS:
                info.roots.add("http-handler")
        changed = True
        while changed:
            changed = False
            for info in self.functions.values():
                for site in info.calls:
                    if site.callee is None:
                        continue
                    callee = self.functions.get(site.callee)
                    if callee is None:
                        continue
                    if site.ctx == "direct":
                        contrib = info.roots
                    elif site.ctx == "loop":
                        contrib = {"loop"}
                    else:
                        contrib = {site.ctx}  # thread:<n>/executor/metrics
                    if not contrib <= callee.roots:
                        callee.roots |= contrib
                        changed = True

    def _propagate_entry_guards(self) -> None:
        for _round in range(12):
            changed = False
            for info in self.functions.values():
                own = info.entry_guards or frozenset()
                for site in info.calls:
                    if site.callee is None or site.ctx != "direct":
                        continue
                    callee = self.functions.get(site.callee)
                    if callee is None or callee is info:
                        continue
                    g = frozenset(site.guards | own)
                    new = g if callee.entry_guards is None \
                        else callee.entry_guards & g
                    if new != callee.entry_guards:
                        callee.entry_guards = new
                        changed = True
            if not changed:
                break

    # -- queries -----------------------------------------------------------

    def function(self, qual: str) -> FunctionInfo | None:
        """Look up by ``Class.method`` / function name (first match)."""
        for info in self.functions.values():
            if info.qual == qual:
                return info
        return None

    def write_sites(self) -> dict[tuple[str, str], list[WriteSite]]:
        """(class, attr) -> every non-``__init__`` write site."""
        out: dict[tuple[str, str], list[WriteSite]] = {}
        for info in self.functions.values():
            for w in info.writes:
                if not w.in_init:
                    out.setdefault((w.owner, w.attr), []).append(w)
        return out

    def guard_sets(self, class_name: str) -> dict[str, frozenset]:
        """Inferred guard set per attribute of ``class_name``: the
        thread-lock ids held (lexically or at entry of the writing
        function) at EVERY non-init write. Empty set = unguarded."""
        out: dict[str, frozenset] = {}
        for (owner, attr), sites in self.write_sites().items():
            if owner != class_name:
                continue
            guards = None
            for w in sites:
                eff = w.func.effective_guards(w.guards)
                guards = eff if guards is None else guards & eff
            out[attr] = guards or frozenset()
        return out

    def roots_of(self, qual: str) -> set[str]:
        info = self.function(qual)
        return set(info.roots) if info is not None else set()
