"""jimm_tpu.tune — persistent Pallas kernel autotuner.

Block sizes for the fused kernels (`ops/flash_attention.py`,
`ops/layer_norm.py`) are shape- and hardware-dependent: FlashAttention
(arXiv:2205.14135) reports large margins between tuned and fixed tiles.
This package measures candidate configs **offline** (``jimm-tpu tune``)
and persists the winner in a fingerprint-keyed store built on the AOT
machinery, so the hot path only ever does a lookup::

    from jimm_tpu import tune

    cfg = tune.best_config("flash_attention", shapes, dtypes,
                           default={"block_q": 512, "block_k": 512})

`best_config` NEVER measures unless ``JIMM_TUNE=1`` is set: a miss falls
back to the kernel's safe default and counts
``jimm_tune_{miss,fallback}_total``. Tuning cost is paid once per
(kernel, shapes, dtypes, backend, jax version) and amortized across train
restarts and serve replicas, exactly like the AOT compile-artifact store.

The package imports jax lazily: ``jimm-tpu tune ls`` and the feasibility
pruning in `space.py` run on a box with no accelerator.
"""

from jimm_tpu.tune.api import (KERNELS, best_config, configure, get_cache,
                               tune_kernel)
from jimm_tpu.tune.cache import (TUNE_FORMAT_VERSION, TuneCache, TuneKey,
                                 tune_key)
from jimm_tpu.tune.measure import measure, trimmed_median
from jimm_tpu.tune.space import kernel_space

__all__ = [
    "KERNELS", "TUNE_FORMAT_VERSION", "TuneCache", "TuneKey", "best_config",
    "configure", "get_cache", "kernel_space", "measure", "trimmed_median",
    "tune_key", "tune_kernel",
]
