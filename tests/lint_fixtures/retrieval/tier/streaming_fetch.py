"""Interprocedural JL023 seed: the cold-cluster payload is fetched
inline, three hops below the do_GET handler — per-file rules can't see
the handler, the call graph can. The clean twin names the cluster to the
IO engine worker (``prefetch``) and waits on the staged result
(``collect``), and the daemon-side ``spill``/``get`` calls show the
rule's thread-root boundary: tier IO off the request path is silent.
"""

import numpy as np


class ArtifactStore:
    def get(self, fp, *, expect_versions=None):
        return b""

    def put(self, fp, payload, meta):
        return fp


class TierIoEngine:
    def prefetch(self, cluster, fingerprint):
        pass

    def collect(self, cluster, *, timeout_s=60.0):
        return np.empty(0, np.int64), np.empty((0, 8), np.float32)


def _read_segment(store: ArtifactStore, cluster):
    return store.get(f"tier-idx-c{cluster}")  # JL023: inline disk get


def _load_shard(path):
    return np.load(path)  # JL023: inline mmap/read on the request path


class InlineFetchHandler:
    def __init__(self, artifacts: ArtifactStore):
        self.artifacts = artifacts

    def do_GET(self):
        return self._serve_query([3, 7])

    def _serve_query(self, clusters):
        return [_read_segment(self.artifacts, c) for c in clusters]

    def do_POST(self):
        return _load_shard("/tmp/shard.npy")


class WorkerFetchHandler:
    """Clean: the request thread only enqueues and waits; the transfer
    itself happens on the engine's worker thread."""

    def __init__(self, engine: TierIoEngine):
        self.engine = engine

    def do_GET(self):
        self.engine.prefetch(3, "fp3")
        return self.engine.collect(3)


def _daemon_cycle(store: ArtifactStore, engine: TierIoEngine):
    # clean: maintenance-thread IO — same calls, no http-handler root
    payload = store.get("tier-idx-c9")
    store.put("tier-idx-c9", payload, {"kind": "tier_cluster"})
    return payload
