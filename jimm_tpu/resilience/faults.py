"""Deterministic fault injection for resilience drills.

Generalizes the original ``--fake-failure-at-step`` crash into a plan of
typed events fired at configured steps::

    preempt@STEP          os.kill(SIGTERM) — exercises the grace-window save
    crash@STEP            hard RuntimeError after STEP's checkpoint commits
    stall@STEP:SECONDS    slow-host stall (sleep) before the next step
    corrupt@STEP          garbage the newest committed checkpoint's metadata

Events at the same step fire in a fixed order (stall, corrupt, preempt,
crash): a stall happens while the step is still "running", corruption must
precede the failure that exposes it, and a preemption signal precedes a
hard crash. The plan is pure data — the same spec string replays the same
drill, which is what lets ``scripts/resilience_smoke.py`` assert resumed
losses bit-for-bit against an uninterrupted control run.
"""

from __future__ import annotations

import os
import signal
import time
from dataclasses import dataclass

__all__ = ["Fault", "FaultPlan", "corrupt_latest_checkpoint"]

#: intra-step firing order (see module docstring)
_ORDER = {"stall": 0, "corrupt": 1, "preempt": 2, "crash": 3}


@dataclass(frozen=True)
class Fault:
    kind: str
    step: int
    arg: float | None = None  # stall duration; unused otherwise

    def __str__(self) -> str:
        suffix = f":{self.arg:g}" if self.arg is not None else ""
        return f"{self.kind}@{self.step}{suffix}"


class FaultPlan:
    """A parsed ``--inject-faults`` spec: the train loop calls
    :meth:`fire` once per step and the plan does the rest."""

    def __init__(self, faults: list[Fault], *, sleep=time.sleep):
        self.faults = sorted(faults, key=lambda f: (f.step, _ORDER[f.kind]))
        self._sleep = sleep
        self.fired: list[Fault] = []

    @classmethod
    def parse(cls, spec: str, *, sleep=time.sleep) -> "FaultPlan":
        """``"preempt@2,stall@4:0.5,corrupt@5,crash@5"`` -> plan."""
        faults: list[Fault] = []
        for item in (spec or "").split(","):
            item = item.strip()
            if not item:
                continue
            try:
                kind, at, rest = item.partition("@")
                kind = kind.strip()
                if not at or kind not in _ORDER:
                    raise ValueError(f"expected one of {sorted(_ORDER)} "
                                     f"before '@'")
                step_s, _, arg_s = rest.partition(":")
                step = int(step_s)
                if step < 0:
                    raise ValueError("step must be >= 0")
                if kind == "stall":
                    if not arg_s:
                        raise ValueError("stall needs a duration: "
                                         "stall@STEP:SECONDS")
                    arg = float(arg_s)
                elif arg_s:
                    raise ValueError(f"{kind} takes no ':' argument")
                else:
                    arg = None
            except ValueError as e:
                raise ValueError(
                    f"bad fault spec entry {item!r}: {e}") from None
            faults.append(Fault(kind, step, arg))
        return cls(faults, sleep=sleep)

    def events_at(self, step: int) -> list[Fault]:
        return [f for f in self.faults if f.step == step]

    def needs(self, kind: str) -> bool:
        return any(f.kind == kind for f in self.faults)

    def fire(self, step: int, *, ckpt=None) -> None:
        """Fire every event configured for ``step`` (called at the end of
        the step, after its checkpoint save was initiated). ``ckpt`` is
        the run's CheckpointManager — corrupt/crash events flush it so the
        injected failure lands on a *committed* checkpoint, the way a real
        preemption races a real write."""
        for fault in self.events_at(step):
            self.fired.append(fault)
            if fault.kind == "stall":
                self._sleep(fault.arg)
            elif fault.kind == "corrupt":
                if ckpt is None:
                    raise ValueError("corrupt@STEP faults need a "
                                     "checkpoint directory")
                ckpt.wait()  # commit + marker, THEN corrupt the bytes
                corrupt_latest_checkpoint(ckpt)
            elif fault.kind == "preempt":
                os.kill(os.getpid(), signal.SIGTERM)
            elif fault.kind == "crash":
                if ckpt is not None:
                    ckpt.wait()
                    ckpt.close()
                raise RuntimeError(
                    f"injected failure at step {step} "
                    "(fault drill; rerun with --resume)")


def corrupt_latest_checkpoint(ckpt) -> str:
    """Overwrite the newest committed step's structural metadata with
    garbage, so the next restore of that step fails deterministically.

    The array bytes themselves carry no checksum — flipping them may load
    "successfully"; the per-item ``_METADATA`` (zarr/ocdbt structure) is
    parsed on every restore, so garbaging it is a reliable, reproducible
    corruption. Returns the corrupted step directory."""
    step = ckpt.latest_step()
    if step is None:
        raise FileNotFoundError("no committed checkpoint to corrupt")
    step_dir = ckpt.directory / str(step)
    targets = sorted(step_dir.glob("*/_METADATA"))
    if not targets:
        targets = [step_dir / "_CHECKPOINT_METADATA"]
    for target in targets:
        target.write_text("jimm fault drill: deliberately corrupted\n")
    return str(step_dir)
