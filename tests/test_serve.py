"""jimm_tpu.serve: buckets, cache, admission, engine, and the HTTP stack.

The e2e class runs a real `ServingServer` over a tiny random-init CLIP and
asserts the two acceptance properties of the serving design: zero recompiles
after warmup under 64-way concurrent load (trace-count instrumentation), and
>90% class-embedding cache hit rate on a repeated label set.
"""

import asyncio
import concurrent.futures
import threading

import numpy as np
import pytest

from jimm_tpu.serve import (AdmissionController, AdmissionPolicy, BucketTable,
                            DeadlineExceededError, EmbeddingCache,
                            EngineClosedError, InferenceEngine, QueueFullError,
                            RequestError, ServeClient, ServeClientError,
                            ServeMetrics, ServingServer, ZeroShotService,
                            counting_forward, pad_batch, prompt_set_key)
from jimm_tpu.serve.buckets import DEFAULT_BATCH_BUCKETS, default_buckets


# ---------------------------------------------------------------------------
# buckets
# ---------------------------------------------------------------------------

class TestBuckets:
    def test_sorted_and_deduped(self):
        assert BucketTable((8, 1, 4, 4, 2)).sizes == (1, 2, 4, 8)

    def test_rejects_empty_and_nonpositive(self):
        with pytest.raises(ValueError):
            BucketTable(())
        with pytest.raises(ValueError):
            BucketTable((0, 4))

    def test_select_smallest_fitting(self):
        table = BucketTable((1, 2, 4, 8))
        assert table.select(1) == 1
        assert table.select(3) == 4
        assert table.select(8) == 8
        assert table.select(9) is None
        with pytest.raises(ValueError):
            table.select(0)

    def test_shed_largest_full(self):
        table = BucketTable((2, 4, 8))
        assert table.shed(1) == 2  # never below the smallest bucket
        assert table.shed(5) == 4
        assert table.shed(64) == 8

    def test_pad_batch(self):
        rows = [np.full(3, i, np.float32) for i in range(3)]
        out = pad_batch(rows, 4)
        assert out.shape == (4, 3)
        assert np.allclose(out[2], 2.0)
        assert np.allclose(out[3], 0.0)  # zero padding
        assert pad_batch(rows, 3).shape == (3, 3)
        with pytest.raises(ValueError):
            pad_batch(rows, 2)
        with pytest.raises(ValueError):
            pad_batch([], 2)

    def test_default_table_on_cpu(self):
        assert default_buckets("cpu").sizes == DEFAULT_BATCH_BUCKETS
        assert default_buckets("tpu").max_size == 256


# ---------------------------------------------------------------------------
# cache
# ---------------------------------------------------------------------------

class TestEmbeddingCache:
    def test_prompt_set_key_separates_models_and_rows(self):
        rows = [[1, 2, 3], [4, 5, 6]]
        k1 = prompt_set_key("clip:a", rows)
        assert k1 == prompt_set_key("clip:a", np.asarray(rows))
        assert k1 != prompt_set_key("clip:b", rows)
        assert k1 != prompt_set_key("clip:a", [[1, 2, 3], [4, 5, 7]])
        # shape is hashed too: (6,) and (2, 3) with equal bytes differ
        assert (prompt_set_key("m", np.arange(6))
                != prompt_set_key("m", np.arange(6).reshape(2, 3)))

    def test_hit_miss_accounting(self):
        cache = EmbeddingCache(capacity=4)
        assert cache.get("k") is None
        cache.put("k", np.ones(2))
        assert cache.get("k") is not None
        assert (cache.hits, cache.misses) == (1, 1)
        assert cache.hit_rate == 0.5

    def test_lru_eviction_order(self):
        cache = EmbeddingCache(capacity=2)
        cache.put("a", np.zeros(1))
        cache.put("b", np.zeros(1))
        cache.get("a")              # refresh a; b is now least-recent
        cache.put("c", np.zeros(1))
        assert cache.get("b") is None
        assert cache.get("a") is not None
        assert cache.get("c") is not None
        assert cache.evictions == 1

    def test_get_or_build_builds_once(self):
        cache = EmbeddingCache()
        built = []

        def builder():
            built.append(1)
            return np.arange(3)

        a = cache.get_or_build("k", builder)
        b = cache.get_or_build("k", builder)
        assert built == [1]
        assert np.array_equal(a, b)

    def test_repeat_label_set_hit_rate_over_90(self):
        cache = EmbeddingCache()
        key = prompt_set_key("m", [[1, 2], [3, 4]])
        for _ in range(20):
            cache.get_or_build(key, lambda: np.ones((2, 8)))
        assert cache.hit_rate > 0.9
        assert cache.stats()["cache_entries"] == 1


# ---------------------------------------------------------------------------
# admission + metrics
# ---------------------------------------------------------------------------

class TestAdmission:
    def test_admit_bounds_queue(self):
        ctl = AdmissionController(AdmissionPolicy(max_queue=2))
        ctl.admit(0)
        ctl.admit(1)
        with pytest.raises(QueueFullError) as ei:
            ctl.admit(2)
        assert ei.value.http_status == 503
        assert ctl.metrics.count("rejected_total") == 1

    def test_shed_watermark(self):
        ctl = AdmissionController(AdmissionPolicy(max_queue=8,
                                                  shed_fraction=0.25))
        assert ctl.policy.shed_depth == 2
        assert not ctl.under_pressure(1)
        assert ctl.under_pressure(2)
        # empty queue never counts as pressure even with tiny fractions
        assert AdmissionPolicy(max_queue=4, shed_fraction=0.01).shed_depth == 1

    def test_deadline_default_and_override(self):
        ctl = AdmissionController(AdmissionPolicy(default_timeout_s=5.0))
        assert ctl.deadline_for(None, 100.0) == 105.0
        assert ctl.deadline_for(0.5, 100.0) == 100.5

    def test_metrics_snapshot_and_prometheus(self):
        m = ServeMetrics()
        m.inc("requests_total", 3)
        m.observe_batch(3, 4)
        m.observe_latency(0.010)
        m.bind_gauge("compile_count", lambda: 2)
        m.bind_gauge("broken", lambda: 1 / 0)  # must not kill rendering
        snap = m.snapshot()
        assert snap["requests_total"] == 3
        assert snap["batch_fill_ratio"] == 0.75
        assert snap["latency_p50_ms"] == 10.0
        assert snap["compile_count"] == 2.0
        assert "broken" not in snap
        text = m.render_prometheus()
        assert "# TYPE jimm_serve_requests_total counter" in text
        assert "jimm_serve_batch_fill_ratio 0.75" in text


# ---------------------------------------------------------------------------
# engine (fake forward — no model, no JAX compile)
# ---------------------------------------------------------------------------

def _make_engine(fwd=None, **kw):
    calls = []

    def default_fwd(batch):
        calls.append(batch.shape)
        return batch * 2.0

    kw.setdefault("buckets", BucketTable((1, 2, 4)))
    engine = InferenceEngine(fwd or default_fwd, item_shape=(3,), **kw)
    return engine, calls


class TestEngine:
    def test_roundtrip_single_request(self):
        async def go():
            engine, calls = _make_engine(max_delay_ms=1.0)
            await engine.start()
            out = await engine.submit(np.full(3, 5.0, np.float32))
            await engine.stop()
            return out, calls

        out, calls = asyncio.run(go())
        assert np.allclose(out, 10.0)
        assert calls == [(1, 3)]  # n=1 picks the 1-bucket

    def test_concurrent_submits_coalesce_into_one_batch(self):
        async def go():
            engine, calls = _make_engine(max_delay_ms=50.0)
            await engine.start()
            outs = await asyncio.gather(*[
                engine.submit(np.full(3, i, np.float32)) for i in range(3)])
            await engine.stop()
            return outs, calls, engine.metrics

        outs, calls, metrics = asyncio.run(go())
        assert calls == [(4, 3)]  # one batch, padded 3 -> bucket 4
        for i, out in enumerate(outs):  # row i answers request i
            assert np.allclose(out, 2.0 * i)
        assert metrics.batch_fill_ratio == 0.75
        assert metrics.count("responses_total") == 3

    def test_bucket_padding_under_deadline_window(self):
        # 5 concurrent submits > max bucket 4: the batcher caps the batch at
        # the largest bucket and the straggler rides the next batch
        async def go():
            engine, calls = _make_engine(max_delay_ms=20.0)
            await engine.start()
            outs = await asyncio.gather(*[
                engine.submit(np.full(3, i, np.float32)) for i in range(5)])
            await engine.stop()
            return outs, calls

        outs, calls = asyncio.run(go())
        assert sorted(c[0] for c in calls) == [1, 4]
        for i, out in enumerate(outs):
            assert np.allclose(out, 2.0 * i)

    def test_wrong_shape_rejected(self):
        async def go():
            engine, _ = _make_engine()
            await engine.start()
            try:
                with pytest.raises(RequestError):
                    await engine.submit(np.zeros(5, np.float32))
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_submit_before_start_is_engine_closed(self):
        async def go():
            engine, _ = _make_engine()
            with pytest.raises(EngineClosedError):
                await engine.submit(np.zeros(3, np.float32))

        asyncio.run(go())

    def test_deadline_timeout_cancels_request(self):
        def slow(batch):
            import time
            time.sleep(0.3)
            return batch

        async def go():
            engine, _ = _make_engine(slow, max_delay_ms=1.0)
            await engine.start()
            try:
                with pytest.raises(DeadlineExceededError) as ei:
                    await engine.submit(np.zeros(3, np.float32),
                                        timeout_s=0.05)
                assert ei.value.http_status == 504
            finally:
                await engine.stop()
            return engine.metrics

        metrics = asyncio.run(go())
        assert metrics.count("timeouts_total") == 1

    def test_queue_full_rejection(self):
        release = threading.Event()

        def blocked(batch):
            release.wait(5)
            return batch

        async def go():
            engine, _ = _make_engine(
                blocked, buckets=BucketTable((1,)), max_delay_ms=1.0,
                policy=AdmissionPolicy(max_queue=2, default_timeout_s=10.0))
            await engine.start()
            item = np.zeros(3, np.float32)
            inflight = [asyncio.create_task(engine.submit(item))]
            await asyncio.sleep(0.05)  # batcher takes it; executor blocked
            inflight += [asyncio.create_task(engine.submit(item))
                         for _ in range(2)]
            await asyncio.sleep(0.05)  # both queued: depth == max_queue
            with pytest.raises(QueueFullError):
                await engine.submit(item)
            release.set()
            await asyncio.gather(*inflight)
            await engine.stop()
            return engine.metrics

        metrics = asyncio.run(go())
        assert metrics.count("rejected_total") == 1
        assert metrics.count("responses_total") == 3

    def test_shed_skips_coalescing_wait_under_pressure(self):
        # window is 5 s; without shedding, 3 submits (< max bucket) would sit
        # out the window and the 3 s harness timeout below would trip
        async def go():
            engine, calls = _make_engine(
                max_delay_ms=5000.0,
                policy=AdmissionPolicy(max_queue=8, shed_fraction=0.25,
                                       default_timeout_s=30.0))
            await engine.start()
            outs = await asyncio.gather(*[
                engine.submit(np.full(3, i, np.float32)) for i in range(3)])
            await engine.stop()
            return outs, calls, engine.metrics

        outs, calls, metrics = asyncio.run(asyncio.wait_for(go(), timeout=3))
        assert calls == [(4, 3)]
        assert metrics.count("shed_batches_total") == 1
        for i, out in enumerate(outs):
            assert np.allclose(out, 2.0 * i)

    def test_warmup_compiles_every_bucket(self):
        engine, calls = _make_engine()
        times = engine.warmup_blocking()
        assert set(times) == {1, 2, 4}
        assert sorted(calls) == [(1, 3), (2, 3), (4, 3)]


# ---------------------------------------------------------------------------
# HTTP e2e over a tiny random-init CLIP
# ---------------------------------------------------------------------------

TOKENS_A = {"cat": [[1, 2, 3], [4, 5]], "dog": [6, 7]}   # ragged ensemble
TOKENS_B = {"ant": [8, 9], "bee": [10, 11], "fly": [12]}


@pytest.fixture(scope="module")
def clip_server():
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.cli import _tiny_override

    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    model = CLIP(cfg, rngs=nnx.Rngs(0))
    forward, traces = counting_forward(model, "encode_image")
    engine = InferenceEngine(
        forward, item_shape=(cfg.vision.image_size, cfg.vision.image_size, 3),
        buckets=BucketTable((1, 2, 4)), max_delay_ms=5.0,
        policy=AdmissionPolicy(max_queue=256, default_timeout_s=30.0),
        trace_count=traces)
    zero_shot = ZeroShotService(model, model_key="clip:test-tiny:f32",
                                cache=EmbeddingCache(capacity=8))
    server = ServingServer(engine, zero_shot=zero_shot, port=0)
    server.start()
    try:
        yield server, model, traces
    finally:
        server.stop()


@pytest.fixture()
def client(clip_server):
    server, _, _ = clip_server
    return ServeClient(port=server.port, timeout_s=60.0)


def _image(seed=0, size=32):
    return np.random.RandomState(seed).rand(size, size, 3).astype(np.float32)


class TestHttpEndToEnd:
    def test_healthz(self, client):
        health = client.healthz()
        assert health["status"] == "ok"
        assert health["buckets"] == [1, 2, 4]

    def test_embed_matches_direct_forward(self, clip_server, client):
        _, model, _ = clip_server
        image = _image(1)
        got = np.asarray(client.embed(image), np.float32)
        want = np.asarray(model.encode_image(image[None]))[0]
        assert np.allclose(got, want, atol=1e-4)

    def test_classify_request_to_logits(self, client):
        result = client.classify(_image(2), TOKENS_A)
        assert set(result["scores"]) == {"cat", "dog"}
        probs = np.array(list(result["scores"].values()))
        assert abs(probs.sum() - 1.0) < 1e-3  # CLIP: softmax over labels
        assert result["cached"] is False
        again = client.classify(_image(3), TOKENS_A)
        assert again["cached"] is True

    def test_cache_hit_rate_over_90_on_repeated_labels(self, clip_server,
                                                       client):
        server, _, _ = clip_server
        cache = server.zero_shot.cache
        hits0, misses0 = cache.hits, cache.misses
        for i in range(20):
            client.classify(_image(10 + i), TOKENS_B)
        dh, dm = cache.hits - hits0, cache.misses - misses0
        assert dm <= 1  # one cold build for this label set, then all hits
        assert dh / (dh + dm) > 0.9

    def test_64_concurrent_clients_zero_recompiles(self, clip_server, client):
        server, _, traces = clip_server
        before = traces()
        assert before == 3  # warmup compiled exactly the three buckets
        responses0 = server.metrics.count("responses_total")

        def one_client(i):
            if i % 2:
                return client.classify(_image(i), TOKENS_B)["scores"]
            return client.embed(_image(i))

        with concurrent.futures.ThreadPoolExecutor(max_workers=64) as pool:
            results = list(pool.map(one_client, range(128)))
        assert len(results) == 128
        assert traces() == before  # zero recompiles under concurrent load
        assert server.metrics.count("responses_total") - responses0 == 128
        # micro-batching actually batched: fewer dispatches than requests
        assert server.metrics.count("batches_total") \
            < server.metrics.count("responses_total")

    def test_revive_runs_on_the_engine_loop_thread(self, clip_server):
        # regression (JL017): admin revive used to mutate replica
        # bookkeeping (pool/restarts/dead/incident_cid) directly from the
        # HTTP handler thread while the watchdog mutates it from loop
        # coroutines; the server must hop onto the loop first
        import threading

        server, _, _ = clip_server
        engine = server.engine
        seen = {}

        def recording_revive(index):
            seen["thread"] = threading.current_thread().name
            seen["index"] = index
            return {"dead": False, "revived": 1}

        orig = engine.revive
        engine.revive = recording_revive
        try:
            out = server.revive({"replica": 0})
        finally:
            engine.revive = orig
        assert seen == {"thread": "jimm-serve-loop", "index": 0}
        assert out["revived"] == 0
        assert out["replica_stats"]["dead"] is False

    def test_bad_requests_get_typed_errors(self, clip_server, client):
        with pytest.raises(ServeClientError) as ei:
            client.embed(np.zeros((8, 8, 3), np.float32))  # wrong shape
        assert (ei.value.status, ei.value.code) == (400, "bad_request")
        raw = ServeClient(port=clip_server[0].port)
        with pytest.raises(ServeClientError) as ei:
            raw._request("POST", "/v1/classify", {"tokens": TOKENS_B})
        assert ei.value.code == "bad_request"  # missing image
        with pytest.raises(ServeClientError) as ei:
            client.classify(_image(), {"cat": list(range(99))})  # ctx is 8
        assert ei.value.code == "bad_request"

    def test_metrics_endpoint(self, client):
        text = client.metrics_text()
        assert "# TYPE jimm_serve_requests_total counter" in text
        assert "jimm_serve_compile_count" in text
        assert "jimm_serve_cache_hit_rate" in text
