"""``jimm-tpu aot`` — manage the persistent compile-artifact store.

Four verbs:

- ``warmup``  — build a preset (or tiny override) and precompile every
  serve bucket into the store, so the next ``jimm-tpu serve`` reaches
  readiness with zero fresh jit compilations.
- ``ls``      — list store entries (fingerprint, size, label, ages).
- ``gc``      — evict least-recently-used entries down to a byte cap.
- ``verify``  — re-hash every entry; quarantine any that fail integrity
  or format-version checks.

``ls``/``gc``/``verify`` never import jax (pure host tools, usable on a
box with no accelerator — same rule as ``jimm-tpu obs``). ``warmup`` is
the one verb that compiles.

Wired as a subparser under the main ``jimm-tpu`` CLI (see jimm_tpu/cli.py).
"""

from __future__ import annotations

import argparse
import json

from jimm_tpu.aot.store import ArtifactStore

__all__ = ["add_aot_parser", "cmd_aot"]


def _human(n: float) -> str:
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024
    return f"{n:.1f}GiB"


def _cmd_warmup(args) -> int:
    # model construction reuses the main CLI's preset plumbing; imported
    # lazily so `aot ls` never pays (or requires) a jax import
    from jimm_tpu.cli import (_configure_backend, _family, _model_cls,
                              _tiny_override)
    _configure_backend(args)
    import jax.numpy as jnp
    from flax import nnx

    from jimm_tpu import preset
    from jimm_tpu.aot.warmup import warmup_store
    from jimm_tpu.serve import BucketTable, default_buckets

    dtype = jnp.bfloat16 if args.bf16 else jnp.float32
    fam = _family(args.preset)
    cfg = preset(args.preset)
    if args.tiny:
        cfg = _tiny_override(cfg)
    if args.ckpt:
        model = _model_cls(fam).from_pretrained(args.ckpt, dtype=dtype)
        label = f"{fam}:{args.ckpt}"
    else:
        model = _model_cls(fam)(cfg, rngs=nnx.Rngs(0), dtype=dtype,
                                param_dtype=dtype)
        label = f"{fam}:{args.preset}" + (":tiny" if args.tiny else "")
    label += ":bf16" if args.bf16 else ":f32"
    method = "encode_image" if fam in ("clip", "siglip") else "__call__"
    buckets = (BucketTable(tuple(int(s) for s in args.buckets.split(",")))
               if args.buckets else default_buckets())
    size = model.config.vision.image_size
    store = ArtifactStore(args.store)
    report = warmup_store(model, method=method, buckets=buckets,
                          item_shape=(size, size, 3), in_dtype="float32",
                          store=store, label=label, force=args.force)
    print(json.dumps({"store": str(store.root), "label": label,
                      "method": method,
                      "buckets": {str(k): v for k, v in report.items()}},
                     indent=2))
    return 0


def _cmd_ls(args) -> int:
    store = ArtifactStore(args.store)
    rows = [e.to_row() for e in store.entries()]
    if args.json:
        print(json.dumps(rows, indent=2, sort_keys=True))
        return 0
    if not rows:
        print(f"(empty store: {store.root})")
        return 0
    for r in sorted(rows, key=lambda r: r["last_used"], reverse=True):
        print(f"{r['fingerprint'][:16]}  {_human(r['size']):>10}  "
              f"bucket={r.get('bucket')}  {r.get('label') or '-'}  "
              f"jax={r.get('jax') or '?'}")
    print(f"total: {len(rows)} entries, {_human(store.total_bytes)}")
    return 0


def _cmd_gc(args) -> int:
    store = ArtifactStore(args.store, max_bytes=args.max_bytes)
    evicted = store.gc()
    print(json.dumps({"evicted": evicted,
                      "remaining_bytes": store.total_bytes,
                      "cap_bytes": store.max_bytes}))
    return 0


def _cmd_verify(args) -> int:
    store = ArtifactStore(args.store)
    problems = store.verify()
    print(json.dumps({"entries": len(store.entries()),
                      "problems": problems}))
    return 1 if problems else 0


def add_aot_parser(subparsers) -> None:
    """Attach the ``aot`` subcommand tree to the main CLI's subparsers."""
    p = subparsers.add_parser(
        "aot", help="manage the persistent AOT compile-artifact store")
    p.set_defaults(fn=cmd_aot)
    sub = p.add_subparsers(dest="aot_cmd", required=True)

    pw = sub.add_parser("warmup",
                        help="precompile every serve bucket for a preset "
                             "into the store")
    pw.add_argument("--preset", required=True)
    pw.add_argument("--store", required=True,
                    help="artifact store root directory")
    pw.add_argument("--ckpt", default=None,
                    help="load weights (safetensors/hub id) instead of "
                         "random init — keys ignore weights, so this only "
                         "changes the recorded label")
    pw.add_argument("--tiny", action="store_true",
                    help="shrink the preset to CPU-demo size")
    pw.add_argument("--bf16", action="store_true")
    pw.add_argument("--buckets", default=None,
                    help="comma-separated batch buckets (default 1,2,4,8)")
    pw.add_argument("--force", action="store_true",
                    help="recompile buckets that already have entries")
    pw.add_argument("--platform", choices=["cpu", "tpu"], default=None)
    pw.add_argument("--host-devices", type=int, default=None)
    pw.set_defaults(aot_func=_cmd_warmup)

    pl = sub.add_parser("ls", help="list store entries")
    pl.add_argument("--store", required=True)
    pl.add_argument("--json", action="store_true")
    pl.set_defaults(aot_func=_cmd_ls)

    pg = sub.add_parser("gc", help="evict LRU entries down to a byte cap")
    pg.add_argument("--store", required=True)
    pg.add_argument("--max-bytes", type=int, default=None,
                    help="override the store cap for this run")
    pg.set_defaults(aot_func=_cmd_gc)

    pv = sub.add_parser("verify",
                        help="re-hash entries; quarantine failures")
    pv.add_argument("--store", required=True)
    pv.set_defaults(aot_func=_cmd_verify)


def cmd_aot(args) -> int:
    return args.aot_func(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jimm-tpu-aot")
    sub = parser.add_subparsers(dest="command", required=True)
    add_aot_parser(sub)
    args = parser.parse_args(argv)
    return cmd_aot(args)


if __name__ == "__main__":
    raise SystemExit(main())
