"""Numerical-safety tooling (SURVEY §5 "race detection / sanitizers" row:
the TPU-native equivalents are nan-checking and bounds checkify).

- :func:`nan_debug` — context manager enabling ``jax_debug_nans`` /
  ``jax_debug_infs`` so the first NaN/Inf produced inside jit raises with a
  de-optimized traceback.
- :func:`checked` — wrap a function with ``jax.experimental.checkify`` to
  surface division/OOB/NaN errors as python exceptions.
- :func:`assert_finite` — pytree-wide finiteness assert for tests/trainers.
"""

from __future__ import annotations

from contextlib import contextmanager
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.experimental import checkify


@contextmanager
def nan_debug(infs: bool = True):
    old_nans = jax.config.jax_debug_nans
    old_infs = jax.config.jax_debug_infs
    jax.config.update("jax_debug_nans", True)
    if infs:
        jax.config.update("jax_debug_infs", True)
    try:
        yield
    finally:
        jax.config.update("jax_debug_nans", old_nans)
        jax.config.update("jax_debug_infs", old_infs)


def checked(fn: Callable, *, errors=checkify.float_checks) -> Callable:
    """Return ``fn`` instrumented with checkify; raises on the host at call
    time if a float error fired inside."""
    cfn = checkify.checkify(fn, errors=errors)

    def wrapper(*args: Any, **kwargs: Any):
        err, out = cfn(*args, **kwargs)
        err.throw()
        return out

    return wrapper


def assert_finite(tree: Any, *, name: str = "tree") -> None:
    bad = []

    def visit(path, leaf):
        arr = jnp.asarray(leaf)
        if jnp.issubdtype(arr.dtype, jnp.floating):
            if not bool(jnp.isfinite(arr).all()):
                bad.append(jax.tree_util.keystr(path))

    jax.tree_util.tree_map_with_path(visit, tree)
    if bad:
        raise FloatingPointError(f"non-finite values in {name}: {bad}")
