"""HF-interoperable checkpoint export — the save path the reference lacks
entirely (SURVEY §5 "Checkpoint / resume": load-only).

Reverses each model's declarative mapping table (`jimm_tpu/weights/loader.py`)
to produce an HF-keyed safetensors state dict: per-layer tensors are unstacked
from the scanned ``(layers, ...)`` params, transforms are inverted, and
``Chunk`` entries sharing one torch fused tensor (the MAP head's
``in_proj_*``) are re-fused by concatenation. Round-trip is tested against
``transformers.*.from_pretrained`` in `tests/test_export.py`.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import numpy as np
from flax import nnx

from jimm_tpu.weights.loader import Chunk, M, order_for
from jimm_tpu.weights.safetensors_io import save_file


def _to_numpy(value) -> np.ndarray:
    return np.asarray(value)


def to_hf_state_dict(model: nnx.Module, entries: list[M], *, num_layers: int,
                     num_layers_by_prefix: dict[str, int] | None = None,
                     layer_order: dict[str, np.ndarray] | None = None
                     ) -> dict[str, np.ndarray]:
    """``layer_order`` mirrors `loader.apply_mapping`: stored row j holds
    canonical layer order[j], so the export emits row j under the canonical
    HF index order[j]."""
    params = dict(nnx.to_flat_state(nnx.state(model, nnx.Param)))
    flat = {".".join(map(str, k)): _to_numpy(v.get_value())
            for k, v in params.items()}

    def layer_count(dst: str) -> int:
        for prefix, n in (num_layers_by_prefix or {}).items():
            if dst.startswith(prefix):
                return n
        return num_layers

    out: dict[str, np.ndarray] = {}
    fused: dict[str, list[tuple[int, np.ndarray]]] = {}
    for e in entries:
        if e.dst not in flat:
            if e.optional:
                continue
            raise KeyError(f"model has no parameter {e.dst!r}")
        arr = flat[e.dst]
        per_layer = "{i}" in e.src
        if per_layer:
            order = order_for(e.dst, layer_order)
            canon = (order if order is not None
                     else range(layer_count(e.dst)))
            layers = [(e.src.format(i=ci), arr[j])
                      for j, ci in enumerate(canon)]
        else:
            layers = [(e.src, arr)]
        for key, a in layers:
            if isinstance(e.transform, Chunk):
                fused.setdefault(key, []).append(
                    (e.transform.idx, e.transform.inv(a)))
            elif e.transform is not None:
                out[key] = e.transform.inv(a)
            else:
                out[key] = a
    for key, parts in fused.items():
        out[key] = np.concatenate(
            [a for _, a in sorted(parts, key=lambda t: t[0])], axis=0)
    return out


def save_pretrained(model: nnx.Module, save_dir: str | os.PathLike, *,
                    state_hook=None, config_hook=None) -> None:
    """Write an HF-compatible directory: ``model.safetensors`` +
    ``config.json`` readable by ``transformers`` and by our
    ``from_pretrained``.

    ``state_hook(state_dict)`` / ``config_hook(config_dict)`` let a model
    emit a format variant (e.g. SigLIP's ``flavor="siglip2"``) while sharing
    this one pipeline — both mutate-and-return their dict."""
    d = Path(save_dir)
    d.mkdir(parents=True, exist_ok=True)
    state = to_hf_state_dict(model, model.hf_mapping(model.config),
                             **_layer_kwargs(model))
    if state_hook is not None:
        state = state_hook(state)
    config = model.hf_config()
    if config_hook is not None:
        config = config_hook(config)
    save_file(state, d / "model.safetensors", metadata={"format": "pt"})
    with open(d / "config.json", "w") as f:
        json.dump(config, f, indent=2)


def _layer_kwargs(model) -> dict[str, Any]:
    from jimm_tpu.weights.loader import layer_orders

    cfg = model.config
    if hasattr(cfg, "text"):
        return {"num_layers": cfg.vision.depth,
                "num_layers_by_prefix": {"text.": cfg.text.depth},
                "layer_order": layer_orders(cfg)}
    return {"num_layers": cfg.vision.depth,
            "layer_order": layer_orders(cfg)}
