"""jimm_tpu.retrieval: vector store, streaming top-k, sharded search, and
the /v1/search + bulk /v1/embed serving surface.

The parity tests compare the device program against a stable NumPy argsort
oracle — including at the awkward shapes (corpus not a multiple of the
block, k larger than the block, exact score ties) where a blocked merge is
easiest to get wrong. The sharded tests run the same corpus over a 2x2
replica topology on the 8 virtual CPU devices and require bit-identical
results plus an AOT-warm second life with zero traces.
"""

import concurrent.futures

import numpy as np
import pytest

from jimm_tpu.retrieval import (IndexSearcher, PersistentEmbeddingCache,
                                RetrievalService, RetrievalStoreError,
                                Searcher, VectorStore, merge_partials,
                                normalize_rows, streaming_topk)
from jimm_tpu.retrieval.store import decode_segment, encode_segment


def oracle_topk(queries, corpus, k):
    """Reference ranking: full scores + stable argsort (ties -> lowest
    global index first), the order the streaming merge must reproduce."""
    scores = (np.asarray(queries, np.float32)
              @ np.asarray(corpus, np.float32).T)
    order = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return np.take_along_axis(scores, order, axis=1), order


def unit_rows(n, d, seed=0):
    rng = np.random.RandomState(seed)
    return normalize_rows(rng.randn(n, d).astype(np.float32))


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

class TestVectorStore:
    def test_create_add_load_roundtrip(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 16)
        vecs = unit_rows(10, 16)
        store.add("idx", [f"a{i}" for i in range(10)], vecs)
        store.add("idx", [f"b{i}" for i in range(5)], unit_rows(5, 16, 1))
        index = store.load("idx")
        assert len(index) == 15
        assert index.ids[:10] == tuple(f"a{i}" for i in range(10))
        assert np.allclose(index.matrix_f32()[:10], vecs, atol=1e-6)
        # rows come back unit-normalized even if the caller's weren't
        store.add("idx", ["big"], np.full((1, 16), 3.0, np.float32))
        mat = store.load("idx").matrix_f32()
        assert np.allclose(np.linalg.norm(mat, axis=1), 1.0, atol=1e-5)

    def test_rejections(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 8)
        with pytest.raises(RetrievalStoreError, match="duplicate"):
            store.add("idx", ["x", "x"], unit_rows(2, 8))
        store.add("idx", ["x"], unit_rows(1, 8))
        with pytest.raises(RetrievalStoreError, match="already live"):
            store.add("idx", ["x"], unit_rows(1, 8))
        with pytest.raises(RetrievalStoreError, match="dim"):
            store.add("idx", ["y"], unit_rows(1, 4))
        with pytest.raises(RetrievalStoreError, match="non-finite"):
            store.add("idx", ["y"], np.full((1, 8), np.nan, np.float32))
        with pytest.raises(RetrievalStoreError):
            store.create("idx", 8)  # exists, no exist_ok
        store.create("idx", 8, exist_ok=True)
        with pytest.raises(RetrievalStoreError):
            store.load("missing")
        for bad in ("a/b", ".hidden"):
            with pytest.raises(RetrievalStoreError):
                store.create(bad, 8)

    def test_delete_tombstones_then_readd(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 8)
        store.add("idx", ["a", "b", "c"], unit_rows(3, 8))
        assert store.delete("idx", ["b", "nope"]) == 1
        index = store.load("idx")
        assert index.ids == ("a", "c")
        assert store.stats("idx")["dead_rows"] == 1
        # a tombstoned id can be re-added with a fresh vector
        fresh = unit_rows(1, 8, seed=9)
        store.add("idx", ["b"], fresh)
        index = store.load("idx")
        assert index.ids == ("a", "c", "b")
        assert np.allclose(index.matrix_f32()[2], fresh[0], atol=1e-6)

    def test_compact_reclaims_and_preserves(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 8)
        for s in range(4):
            store.add("idx", [f"s{s}.{i}" for i in range(6)],
                      unit_rows(6, 8, seed=s))
        store.delete("idx", [f"s1.{i}" for i in range(6)])
        before = store.load("idx")
        report = store.compact("idx")
        assert report["segments_before"] == 4
        assert report["segments_after"] == 1
        assert report["rows"] == 18
        assert report["reclaimed_bytes"] > 0
        after = store.load("idx")
        assert after.ids == before.ids
        assert np.allclose(after.matrix_f32(), before.matrix_f32())
        assert store.stats("idx")["dead_rows"] == 0

    def test_bf16_storage(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 8, dtype="bfloat16")
        vecs = unit_rows(4, 8)
        store.add("idx", list("abcd"), vecs)
        index = store.load("idx")
        assert index.dtype == "bfloat16"
        assert np.allclose(index.matrix_f32(), vecs, atol=1e-2)

    def test_segment_codec_rejects_bad_framing(self):
        payload = encode_segment(["a"], unit_rows(1, 8), "float32")
        ids, mat = decode_segment(payload)
        assert ids == ["a"] and mat.shape == (1, 8)
        with pytest.raises(RetrievalStoreError):
            decode_segment(payload[:-3])  # truncated matrix bytes
        with pytest.raises(RetrievalStoreError):
            decode_segment(b"junk\n" + payload)

    def test_corrupt_segment_quarantined(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 8)
        fp = store.add("idx", ["a", "b"], unit_rows(2, 8))
        entry = store.artifacts.entry_dir(fp)
        for f in entry.iterdir():
            if "meta" not in f.name:
                f.write_bytes(b"\x00" * 64)
        fresh = VectorStore(tmp_path)  # no hot-tier copy
        with pytest.raises(RetrievalStoreError):
            fresh.load("idx")
        problems = VectorStore(tmp_path).verify()
        assert problems and any(p["index"] == "idx" for p in problems)
        qdir = fresh.artifacts.quarantine_dir
        assert qdir.exists() and any(qdir.iterdir())

    def test_ls_and_hot_tier_invalidation(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("one", 8)
        store.add("one", ["a"], unit_rows(1, 8))
        rows = store.ls()
        assert [r["name"] for r in rows] == ["one"]
        assert rows[0]["rows"] == 1
        first = store.load("one")
        # hot tier: same manifest state returns the same backing arrays
        assert store.load("one").vectors is first.vectors
        store.add("one", ["b"], unit_rows(1, 8, 1))
        assert len(store.load("one")) == 2  # state changed -> reload


class TestPersistentPromptCache:
    def test_survives_process_restart(self, tmp_path):
        cache = VectorStore(tmp_path).prompt_cache()
        built = []

        def build():
            built.append(1)
            return np.arange(6, dtype=np.float32).reshape(2, 3)

        a = cache.get_or_build("clip:x:prompts", build)
        b = cache.get_or_build("clip:x:prompts", build)
        assert len(built) == 1 and np.allclose(a, b)
        # a brand-new store instance = a restarted process: disk tier hits
        cache2 = VectorStore(tmp_path).prompt_cache()
        c = cache2.get_or_build("clip:x:prompts", build)
        assert len(built) == 1
        assert np.allclose(c, a)
        assert cache2.disk_hits == 1
        assert isinstance(cache2, PersistentEmbeddingCache)
        assert cache2.get("never-seen") is None


# ---------------------------------------------------------------------------
# streaming top-k parity
# ---------------------------------------------------------------------------

class TestStreamingTopkParity:
    def test_corpus_not_multiple_of_block(self):
        corpus = unit_rows(1000, 24)
        queries = unit_rows(4, 24, seed=3)
        vals, idx = streaming_topk(queries, corpus, 10, block_n=128)
        want_v, want_i = oracle_topk(queries, corpus, 10)
        assert np.array_equal(idx, want_i)
        assert np.allclose(vals, want_v, atol=1e-6)

    def test_k_larger_than_block(self):
        corpus = unit_rows(100, 16, seed=1)
        queries = unit_rows(3, 16, seed=2)
        vals, idx = streaming_topk(queries, corpus, 16, block_n=8)
        want_v, want_i = oracle_topk(queries, corpus, 16)
        assert np.array_equal(idx, want_i)
        assert np.allclose(vals, want_v, atol=1e-6)

    def test_k_exceeds_corpus(self):
        corpus = unit_rows(5, 8)
        vals, idx = streaming_topk(unit_rows(2, 8, 1), corpus, 9,
                                   block_n=4)
        assert np.all(idx[:, :5] >= 0)
        assert np.all(idx[:, 5:] == -1)
        assert np.all(np.isneginf(vals[:, 5:]))

    def test_exact_ties_follow_stable_order(self):
        base = unit_rows(7, 12, seed=4)
        corpus = np.concatenate([base, base, base])  # every score x3
        queries = unit_rows(2, 12, seed=5)
        vals, idx = streaming_topk(queries, corpus, 9, block_n=5)
        want_v, want_i = oracle_topk(queries, corpus, 9)
        assert np.array_equal(idx, want_i)  # lowest global index wins ties
        assert np.allclose(vals, want_v, atol=1e-6)

    def test_merge_partials_matches_flat_oracle(self):
        rng = np.random.RandomState(6)
        vals = rng.randn(3, 2, 4).astype(np.float32)
        idx = rng.permutation(100)[:24].reshape(3, 2, 4).astype(np.int64)
        vals[1, 0, 2] = -np.inf
        idx[1, 0, 2] = -1  # padding candidate must lose to everything
        got_v, got_i = merge_partials(vals, idx, 5)
        flat_v = vals.transpose(1, 0, 2).reshape(2, 12)
        flat_i = idx.transpose(1, 0, 2).reshape(2, 12)
        for b in range(2):
            order = sorted(range(12),
                           key=lambda j: (-flat_v[b, j],
                                          flat_i[b, j] if flat_i[b, j] >= 0
                                          else np.iinfo(np.int64).max))[:5]
            assert list(got_i[b]) == [flat_i[b, j] for j in order]
            assert np.allclose(got_v[b], [flat_v[b, j] for j in order])

    def test_merge_partials_k_exceeds_total_candidates(self):
        # IVF regression: k > P * kk (few probed rows across few shards)
        # must pad with (-inf, -1) tails, never underfill or raise
        vals = np.array([[[3.0, 1.0]], [[2.0, 2.0]]], np.float32)
        idx = np.array([[[7, 9]], [[4, 11]]], np.int64)
        got_v, got_i = merge_partials(vals, idx, 6)
        assert got_v.shape == (1, 6) and got_i.shape == (1, 6)
        # exact tie at 2.0: lowest global index (4) outranks 11
        assert list(got_i[0]) == [7, 4, 11, 9, -1, -1]
        assert np.allclose(got_v[0, :4], [3.0, 2.0, 2.0, 1.0])
        assert np.all(np.isneginf(got_v[0, 4:]))

    def test_merge_partials_all_tombstoned_partials(self):
        # every shard returned only padding (all candidates dead): the
        # merged row must stay all-sentinel rather than promote padding
        vals = np.full((3, 2, 4), -np.inf, np.float32)
        idx = np.full((3, 2, 4), -1, np.int64)
        got_v, got_i = merge_partials(vals, idx, 5)
        assert np.all(got_i == -1)
        assert np.all(np.isneginf(got_v))


# ---------------------------------------------------------------------------
# warm searchers: tune + AOT store integration
# ---------------------------------------------------------------------------

class TestSearcherWarmPaths:
    def test_explicit_block_bypasses_tuner(self):
        s = Searcher(unit_rows(300, 16), k=5, block_n=64)
        assert s.block_n == 64

    def test_tuner_space_registered(self):
        from jimm_tpu.tune.api import KERNELS
        from jimm_tpu.tune.space import retrieval_space
        assert "retrieval_topk" in KERNELS
        space = retrieval_space(shapes=[(8, 32), (10_000, 32)],
                                dtypes=[np.dtype(np.float32)])
        assert all(c["block_n"] >= 128 for c in space)
        # tiny corpora don't get blocks wider than their (padded) rows
        small = retrieval_space(shapes=[(8, 32), (100, 32)],
                                dtypes=[np.dtype(np.float32)])
        assert all(c["block_n"] <= 128 for c in small)

    def test_aot_second_life_zero_traces(self, tmp_path):
        from jimm_tpu.aot import ArtifactStore
        corpus = unit_rows(500, 16, seed=7)
        queries = unit_rows(4, 16, seed=8)
        store = ArtifactStore(tmp_path / "aot")
        life1 = Searcher(corpus, k=6, buckets=(4,), block_n=64,
                         aot_store=store, label="t")
        assert life1.warmup() == {4: "miss"}  # compiled + written through
        assert life1.trace_count() >= 1
        want_v, want_i = oracle_topk(queries, corpus, 6)
        # second life: same shapes -> fully AOT-sourced, zero traces
        life2 = Searcher(corpus, k=6, buckets=(4,), block_n=64,
                         aot_store=store, label="t")
        assert life2.warmup() == {4: "aot"}
        vals, idx = life2.search_partial(queries)  # (S=1, B, k) partials
        assert life2.trace_count() == 0
        assert np.array_equal(idx[0], want_i)
        assert np.allclose(vals[0], want_v, atol=1e-6)

    def test_corrupt_artifact_degrades_to_fresh(self, tmp_path):
        from jimm_tpu.aot import ArtifactStore
        corpus = unit_rows(200, 16, seed=9)
        store = ArtifactStore(tmp_path / "aot")
        Searcher(corpus, k=4, buckets=(1,), block_n=64, aot_store=store,
                 label="t").warmup()
        fp = Searcher(corpus, k=4, buckets=(1,), block_n=64,
                      aot_store=store, label="t").key_for(1).fingerprint()
        entry = store.entry_dir(fp)
        for f in entry.iterdir():
            if "meta" not in f.name:
                f.write_bytes(b"garbage")
        s = Searcher(corpus, k=4, buckets=(1,), block_n=64,
                     aot_store=store, label="t")
        source = s.prepare(1)
        assert source != "aot"  # bad payload must not be served
        queries = unit_rows(2, 16, seed=10)
        vals, idx = s.search_partial(queries)
        want_v, want_i = oracle_topk(queries, corpus, 4)
        assert np.array_equal(idx[0], want_i)
        assert np.allclose(vals[0], want_v, atol=1e-6)

    def test_bucket_padding_and_overflow_chunks(self):
        corpus = unit_rows(128, 16, seed=11)
        s = Searcher(corpus, k=3, buckets=(2, 4), block_n=64)
        vals, idx = s.search_partial(unit_rows(3, 16, seed=12))
        assert vals.shape[-2:] == (3, 3) and idx.shape[-2:] == (3, 3)
        # past the max bucket: chunked through it, still exact
        queries = unit_rows(9, 16, seed=13)
        vals, idx = s.search_partial(queries)
        want_v, want_i = oracle_topk(queries, corpus, 3)
        assert np.array_equal(idx[0], want_i)
        assert np.allclose(vals[0], want_v, atol=1e-6)


# ---------------------------------------------------------------------------
# sharded vs unsharded parity over the PR 6 topology
# ---------------------------------------------------------------------------

class TestShardedParity:
    @pytest.fixture()
    def index(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("corpus", 32)
        store.add("corpus", [f"v{i}" for i in range(700)],
                  unit_rows(700, 32, seed=20))
        return store.load("corpus")

    def test_2x2_plan_matches_single_device(self, index, eight_devices):
        from jimm_tpu.serve.topology import plan_topology
        plan = plan_topology(2, 2)  # 2 replicas x (model=2) submeshes
        flat = IndexSearcher(index, k=10, buckets=(1, 4), block_n=64)
        sharded = IndexSearcher(index, k=10, buckets=(1, 4), block_n=64,
                                plan=plan)
        assert len(sharded.searchers) == 2
        queries = np.random.RandomState(21).randn(4, 32).astype(np.float32)
        fv, fi, fids = flat.search(queries)
        sv, si, sids = sharded.search(queries)
        assert np.array_equal(fi, si)
        assert np.allclose(fv, sv, atol=1e-5)
        assert fids == sids
        assert fids[0][0] == f"v{fi[0, 0]}"

    def test_sharded_aot_second_life(self, index, eight_devices, tmp_path):
        from jimm_tpu.aot import ArtifactStore
        from jimm_tpu.serve.topology import plan_topology
        plan = plan_topology(2, 2)
        store = ArtifactStore(tmp_path / "aot")
        life1 = IndexSearcher(index, k=5, buckets=(4,), block_n=64,
                              plan=plan, aot_store=store)
        # replica 0 compiles + writes through; replica 1 shares the
        # fingerprint (equal-padded partitions) and loads it -> "mixed"
        assert life1.warmup()[4] in ("mixed", "miss")
        life2 = IndexSearcher(index, k=5, buckets=(4,), block_n=64,
                              plan=plan, aot_store=store)
        assert life2.warmup() == {4: "aot"}
        queries = np.random.RandomState(22).randn(3, 32).astype(np.float32)
        sv, si, _ = life2.search(queries)
        assert life2.trace_count() == 0
        fv, fi, _ = IndexSearcher(index, k=5, buckets=(4,),
                                  block_n=64).search(queries)
        assert np.array_equal(fi, si)
        assert np.allclose(fv, sv, atol=1e-5)


# ---------------------------------------------------------------------------
# service facade
# ---------------------------------------------------------------------------

class TestRetrievalService:
    @pytest.fixture()
    def service(self, tmp_path):
        store = VectorStore(tmp_path)
        store.create("idx", 16)
        store.add("idx", [f"v{i}" for i in range(50)],
                  unit_rows(50, 16, seed=30))
        return RetrievalService.from_store(store, "idx", k=5, block_n=64)

    def test_search_blocking_and_describe(self, service):
        queries = np.random.RandomState(31).randn(2, 16)
        values, ids = service.search_blocking(queries, k=3)
        assert values.shape == (2, 3)
        assert all(len(row) == 3 for row in ids)
        assert np.all(np.diff(values, axis=1) <= 1e-6)  # sorted desc
        d = service.describe()
        assert d["index"] == "idx" and d["rows"] == 50 and d["k"] == 5
        one_v, one_ids = service.search_blocking(queries[0])  # (D,) form
        assert one_v.shape == (1, 5) and len(one_ids[0]) == 5

    def test_request_validation(self, service):
        from jimm_tpu.serve.admission import RequestError
        with pytest.raises(RequestError, match="dim"):
            service.search_blocking(np.zeros((1, 7), np.float32))
        with pytest.raises(RequestError, match="non-finite"):
            service.search_blocking(np.full((1, 16), np.inf, np.float32))
        with pytest.raises(RequestError, match="k must be"):
            service.search_blocking(np.zeros((1, 16), np.float32), k=9)
        with pytest.raises(RequestError, match="k must be"):
            service.search_blocking(np.zeros((1, 16), np.float32), k=0)

    def test_metrics_and_gauges(self, service):
        from jimm_tpu import obs
        before = obs.get_registry("jimm_retrieval").counter(
            "search_total").value
        service.search_blocking(np.zeros((3, 16), np.float32))
        snap = obs.snapshot()
        assert snap["jimm_retrieval_search_total"] == before + 3
        assert snap["jimm_retrieval_index_size"] == 50.0
        assert snap["jimm_retrieval_index_segments"] == 1.0
        assert snap["jimm_retrieval_index_staleness_seconds"] >= 0.0
        # the retrieval_topk span lands as histogram series in jimm_spans
        assert any("retrieval_topk" in k for k in snap)


# ---------------------------------------------------------------------------
# HTTP endpoint integration (tiny CLIP + real index)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def search_server(tmp_path_factory):
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.serve import (AdmissionPolicy, BucketTable,
                                InferenceEngine, ServingServer,
                                counting_forward)

    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    model = CLIP(cfg, rngs=nnx.Rngs(0))
    size = cfg.vision.image_size
    dim = int(np.asarray(
        model.encode_image(np.zeros((1, size, size, 3), np.float32))
    ).shape[-1])
    root = tmp_path_factory.mktemp("index-store")
    vstore = VectorStore(root)
    vstore.create("corpus", dim)
    vstore.add("corpus", [f"doc{i}" for i in range(200)],
               unit_rows(200, dim, seed=40))
    retrieval = RetrievalService.from_store(vstore, "corpus", k=8,
                                            block_n=64)
    forward, traces = counting_forward(model, "encode_image")
    engine = InferenceEngine(
        forward, item_shape=(size, size, 3),
        buckets=BucketTable((1, 4)), max_delay_ms=5.0,
        policy=AdmissionPolicy(max_queue=256, default_timeout_s=30.0),
        trace_count=traces)
    server = ServingServer(engine, retrieval=retrieval, port=0)
    server.start()
    try:
        yield server, model, traces, dim
    finally:
        server.stop()


@pytest.fixture()
def search_client(search_server):
    from jimm_tpu.serve import ServeClient
    server, _, _, _ = search_server
    return ServeClient(port=server.port, timeout_s=60.0)


class TestSearchEndpoint:
    def test_vector_search(self, search_server, search_client):
        _, _, _, dim = search_server
        q = np.random.RandomState(41).randn(dim).astype(np.float32)
        out = search_client.search(vector=q, k=4)
        assert out["index"] == "corpus" and out["k"] == 4
        assert len(out["ids"]) == 4 and len(out["scores"]) == 4
        assert out["scores"] == sorted(out["scores"], reverse=True)
        assert all(i.startswith("doc") for i in out["ids"])

    def test_image_search_routes_through_engine(self, search_server,
                                                search_client):
        server, model, _, _ = search_server
        img = np.random.RandomState(42).rand(
            *server.engine.item_shape).astype(np.float32)
        out = search_client.search(image=img)
        feat = normalize_rows(np.asarray(model.encode_image(img[None]),
                                         np.float32))
        want, _ = oracle_topk(feat, server.retrieval.index.matrix_f32(), 1)
        assert abs(out["scores"][0] - want[0, 0]) < 1e-4

    def test_bulk_embed_counts_rows(self, search_server, search_client):
        server, _, _, _ = search_server
        imgs = [np.random.RandomState(50 + i).rand(
            *server.engine.item_shape).astype(np.float32) for i in range(5)]
        feats = search_client.embed_many(imgs)
        assert len(feats) == 5
        single = search_client.embed(imgs[0])
        assert np.allclose(feats[0], single, atol=1e-4)
        text = search_client.metrics_text()
        assert "jimm_retrieval_embed_total" in text
        assert "jimm_retrieval_search_total" in text
        assert "jimm_retrieval_index_size 200" in text

    def test_healthz_reports_retrieval(self, search_client):
        h = search_client.healthz()
        assert h["retrieval"]["index"] == "corpus"
        assert h["retrieval"]["rows"] == 200

    def test_concurrent_search_zero_recompiles(self, search_server,
                                               search_client):
        server, _, traces, dim = search_server
        # prime both the engine buckets and the searcher bucket
        search_client.search(vector=[0.0] * dim)
        before = traces() + server.retrieval.trace_count()
        rng = np.random.RandomState(43)
        qs = [rng.randn(dim).astype(np.float32) for _ in range(64)]
        with concurrent.futures.ThreadPoolExecutor(max_workers=16) as pool:
            outs = list(pool.map(
                lambda q: search_client.search(vector=q, k=2), qs))
        assert len(outs) == 64
        assert all(len(o["ids"]) == 2 for o in outs)
        assert traces() + server.retrieval.trace_count() == before

    def test_bad_requests(self, search_server, search_client):
        from jimm_tpu.serve import ServeClientError
        _, _, _, dim = search_server
        with pytest.raises(ServeClientError) as ei:
            search_client.search(vector=[1.0, 2.0])  # wrong dim
        assert ei.value.code == "bad_request"
        with pytest.raises(ServeClientError) as ei:
            search_client.search(vector=[0.0] * dim, k=99)  # k > compiled
        assert ei.value.code == "bad_request"
        with pytest.raises(ValueError):
            search_client.search()  # neither vector nor image
