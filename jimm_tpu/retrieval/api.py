"""Service facade gluing the vector store and the top-k searcher into the
serving stack, plus the ``jimm_retrieval`` observability namespace.

:class:`RetrievalService` is what ``serve --index`` constructs and
:class:`~jimm_tpu.serve.server.ServingServer` consults for ``/v1/search``:
it owns the loaded index, the warm :class:`~jimm_tpu.retrieval.topk
.IndexSearcher`, and the metric series the obs docs list —

- ``jimm_retrieval_search_total`` / ``jimm_retrieval_embed_total``
  counters (embed counts rows, not requests: a bulk ``/v1/embed`` of 16
  images is 16),
- ``jimm_retrieval_index_size`` / ``jimm_retrieval_index_segments`` /
  ``jimm_retrieval_index_staleness_seconds`` gauges (staleness = seconds
  since the manifest last changed; a serving process holds the index
  snapshot it loaded, so a growing staleness under active writers says
  "restart or reload me"),
- the ``retrieval_topk`` span around every scoring call (device scan +
  host merge), which lands in ``jimm_spans_*`` like every other span.

Everything here is callable from HTTP handler threads (blocking is fine;
the engine's event loop is never entered) and from the CLI.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from jimm_tpu.retrieval.store import LoadedIndex, VectorStore
from jimm_tpu.retrieval.topk import IndexSearcher

__all__ = ["RetrievalService", "retrieval_metrics"]


def retrieval_metrics():
    """The ``jimm_retrieval`` registry's (search_total, embed_total)
    counters — shared by the service and the bulk-embed endpoint."""
    from jimm_tpu import obs
    reg = obs.get_registry("jimm_retrieval")
    return reg.counter("search_total"), reg.counter("embed_total")


class RetrievalService:
    """One named index, searchable: loaded snapshot + warm searcher +
    metrics. Built once at serve startup (``from_store``) or directly in
    tests/benches with a pre-built searcher."""

    def __init__(self, index: LoadedIndex, searcher: IndexSearcher, *,
                 store: VectorStore | None = None):
        from jimm_tpu import obs
        self.index = index
        self.searcher = searcher
        self.store = store
        self.search_counter, self.embed_counter = retrieval_metrics()
        reg = obs.get_registry("jimm_retrieval")
        reg.gauge("index_size", lambda: float(len(self.index)))
        reg.gauge("index_segments", fn=self._segments_now)
        reg.gauge("index_staleness_seconds", fn=self._staleness_now)

    @classmethod
    def from_store(cls, store: VectorStore, name: str, *, k: int = 10,
                   buckets=(1,), block_n: int | None = None,
                   plan: Any = None, aot_store: Any = None
                   ) -> "RetrievalService":
        index = store.load(name)
        searcher = IndexSearcher(index, k=k, buckets=buckets,
                                 block_n=block_n, plan=plan,
                                 aot_store=aot_store)
        return cls(index, searcher, store=store)

    # -- gauges -----------------------------------------------------------

    def _segments_now(self) -> float:
        if self.store is None:
            return 1.0
        try:
            return float(self.store.stats(self.index.name)["segments"])
        except Exception:  # noqa: BLE001 — a gauge must never raise
            return 0.0

    def _staleness_now(self) -> float:
        """Seconds since the *on-disk* manifest last changed — reads
        through to the store so concurrent writers move this gauge even
        though the serving snapshot is pinned."""
        updated = self.index.updated
        if self.store is not None:
            try:
                updated = float(
                    self.store.manifest(self.index.name)["updated"])
            except Exception:  # noqa: BLE001
                pass
        return max(0.0, round(time.time() - updated, 3))

    # -- lifecycle --------------------------------------------------------

    def warmup(self) -> dict[int, str]:
        """Warm every (replica, bucket); the serve ready line and healthz
        report the per-bucket sources."""
        return self.searcher.warmup()

    def trace_count(self) -> int:
        return self.searcher.trace_count()

    def describe(self) -> dict:
        return {"index": self.index.name, "rows": len(self.index),
                "dim": self.index.dim, "dtype": self.index.dtype,
                "metric": self.index.metric, "k": self.searcher.k,
                "block_n": self.searcher.block_n,
                "buckets": list(self.searcher.buckets),
                "partitions": len(self.searcher.searchers),
                "staleness_s": self._staleness_now()}

    # -- queries ----------------------------------------------------------

    def search_blocking(self, queries: np.ndarray, k: int | None = None
                        ) -> tuple[np.ndarray, list[list[str]]]:
        """Top-k ids + scores for a ``(D,)`` or ``(B, D)`` query batch.
        ``k`` may trim below the searcher's compiled k but never exceed it
        (the device program's carry width is fixed at build time). Call
        from a handler thread or the CLI — this blocks on the device."""
        from jimm_tpu import obs
        from jimm_tpu.serve.admission import RequestError
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.ndim != 2 or queries.shape[1] != self.index.dim:
            raise RequestError(
                f"query must have dim {self.index.dim} (index "
                f"{self.index.name!r}); got shape {tuple(queries.shape)}")
        if not np.all(np.isfinite(queries)):
            raise RequestError("query contains non-finite values")
        k_eff = self.searcher.k if k is None else int(k)
        if k_eff < 1 or k_eff > self.searcher.k:
            raise RequestError(
                f"k must be in [1, {self.searcher.k}] (the searcher's "
                f"compiled carry width); got {k_eff}")
        with obs.span("retrieval_topk"):
            values, _indices, ids = self.searcher.search(queries)
        self.search_counter.inc(queries.shape[0])
        return values[:, :k_eff], [row[:k_eff] for row in ids]
