"""WebDataset-style tar shards as a third input format (records/grain cover
TFRecord; this covers the ubiquitous image-corpus tar layout).

A shard is a plain POSIX tar whose members are grouped by key — the filename
up to the LAST extension. Per example:

- ``<key>.jpg`` / ``.jpeg`` / ``.png``: encoded image bytes (required)
- ``<key>.cls``: ascii integer class label (classification)
- ``<key>.json``: JSON object with a ``tokens`` list of int ids
  (contrastive; pre-tokenized, keeping the zero-tokenizer runtime)

Batches are identical to `jimm_tpu.data.records` — the decode/resize/
normalize/pad code IS records' (shared helpers), only the container format
differs. Sequential tar read (no index needed), multi-host sharding by
example stride, buffer shuffle, epoch repeat: the records loader semantics.

The reference's only input path is a network tfds call
(ref `examples/vit_training.py:205-212`).
"""

from __future__ import annotations

import glob as _glob
import io
import json
import random
import tarfile
from pathlib import Path
from typing import Iterator, Sequence

import numpy as np

from jimm_tpu.data.preprocess import SIGLIP_MEAN, SIGLIP_STD
from jimm_tpu.data.records import (classification_batches_from,
                                   image_text_batches_from)

_IMAGE_EXTS = {".jpg", ".jpeg", ".png"}


def resolve_tar_paths(data: str | Sequence[str | Path]) -> list[str]:
    """Glob pattern, directory, single tar, or explicit list -> tar files.
    Directory scans match ``*.tar*`` so compressed shards (``.tar.gz``,
    ``.tar.zst`` ...) route here too — `_iter_tar` reads any compression."""
    if isinstance(data, (str, Path)):
        p = Path(data)
        if p.is_dir():
            paths = sorted(str(q) for q in p.glob("*.tar*"))
        elif any(ch in str(data) for ch in "*?["):
            paths = sorted(_glob.glob(str(data)))
        else:
            paths = [str(p)]
    else:
        paths = [str(p) for p in data]
    if not paths:
        raise FileNotFoundError(f"no tar shards match {data!r}")
    return paths


def _split_key(name: str) -> tuple[str, str]:
    base = name.rsplit("/", 1)[-1]
    key, dot, ext = base.rpartition(".")
    return (name[: len(name) - len(ext) - 1], "." + ext.lower()) if dot \
        else (name, "")


def _iter_tar(path: str) -> Iterator[dict]:
    """Group consecutive members sharing a key into one example dict in the
    records schema ({"image": [bytes], "label": [int], "tokens": [ids]})."""
    with tarfile.open(path, "r|*") as tf:  # streaming read, any compression
        cur_key, cur = None, {}
        for member in tf:
            if not member.isfile():
                continue
            key, ext = _split_key(member.name)
            if key != cur_key:
                if cur_key is not None and "image" in cur:
                    yield cur
                cur_key, cur = key, {}
            data = tf.extractfile(member).read()
            if ext in _IMAGE_EXTS:
                cur["image"] = [data]
            elif ext == ".cls":
                cur["label"] = [int(data.decode().strip())]
            elif ext == ".json":
                tokens = json.loads(data.decode()).get("tokens")
                if tokens is not None:
                    cur["tokens"] = [int(t) for t in tokens]
            # unknown extensions are carried metadata: ignored
        if cur_key is not None and "image" in cur:
            yield cur


def iter_wds_examples(paths: Sequence[str], *, repeat: bool = True,
                      shuffle_buffer: int = 0, seed: int = 0,
                      shard_index: int = 0, shard_count: int = 1
                      ) -> Iterator[dict]:
    """records.iter_examples semantics over tar shards."""
    rng = random.Random(seed)
    buf: list[dict] = []
    while True:
        files = list(paths)
        if shuffle_buffer:
            rng.shuffle(files)
        idx = 0
        for path in files:
            for ex in _iter_tar(path):
                idx += 1
                if (idx - 1) % shard_count != shard_index:
                    continue
                if shuffle_buffer:
                    buf.append(ex)
                    if len(buf) >= shuffle_buffer:
                        yield buf.pop(rng.randrange(len(buf)))
                else:
                    yield ex
        if not repeat:
            break
    while buf:
        yield buf.pop(rng.randrange(len(buf)))


def wds_image_text_batches(data, batch_size: int, *, image_size: int,
                           seq_len: int, pad_id: int = 0, mean=SIGLIP_MEAN,
                           std=SIGLIP_STD, shuffle_buffer: int = 0,
                           seed: int = 0, repeat: bool = True,
                           shard_index: int = 0, shard_count: int = 1,
                           skip_examples: int = 0,
                           drop_remainder: bool = True
                           ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Contrastive batches from tar shards — records' batch builder over
    the tar example stream."""
    examples = iter_wds_examples(resolve_tar_paths(data), repeat=repeat,
                                 shuffle_buffer=shuffle_buffer, seed=seed,
                                 shard_index=shard_index,
                                 shard_count=shard_count)
    return image_text_batches_from(
        examples, batch_size, image_size=image_size, seq_len=seq_len,
        pad_id=pad_id, mean=mean, std=std, skip_examples=skip_examples,
        drop_remainder=drop_remainder)


def wds_classification_batches(data, batch_size: int, *, image_size: int,
                               mean=SIGLIP_MEAN, std=SIGLIP_STD,
                               shuffle_buffer: int = 0, seed: int = 0,
                               repeat: bool = True, shard_index: int = 0,
                               shard_count: int = 1, skip_examples: int = 0,
                               drop_remainder: bool = True
                               ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Classification batches from tar shards — records' batch builder."""
    examples = iter_wds_examples(resolve_tar_paths(data), repeat=repeat,
                                 shuffle_buffer=shuffle_buffer, seed=seed,
                                 shard_index=shard_index,
                                 shard_count=shard_count)
    return classification_batches_from(
        examples, batch_size, image_size=image_size, mean=mean, std=std,
        skip_examples=skip_examples, drop_remainder=drop_remainder)


# ---------------------------------------------------------------------------
# Writing (dataset preparation tooling)
# ---------------------------------------------------------------------------

def write_wds_shard(path: str | Path, examples: Sequence[dict], *,
                    encoding: str = "png") -> int:
    """[{"image": array|bytes, "label": int | "tokens": [ids]}, ...] -> one
    tar shard. Returns the example count."""
    from jimm_tpu.data.records import encode_image_feature

    with tarfile.open(path, "w") as tf:
        for i, ex in enumerate(examples):
            key = f"{i:08d}"
            feats = encode_image_feature(ex["image"], encoding=encoding)
            img_ext = ".png" if feats["image"][:4] == b"\x89PNG" else (
                ".jpg" if feats["image"][:2] == b"\xff\xd8" else ".png")
            if "shape" in feats:
                raise ValueError("webdataset shards hold ENCODED images; "
                                 "use encoding='png' or 'jpeg'")
            _add(tf, key + img_ext, feats["image"])
            if "label" in ex:
                _add(tf, key + ".cls", str(int(ex["label"])).encode())
            if "tokens" in ex:
                _add(tf, key + ".json", json.dumps(
                    {"tokens": [int(t) for t in ex["tokens"]]}).encode())
    return len(examples)


def _add(tf: tarfile.TarFile, name: str, data: bytes) -> None:
    info = tarfile.TarInfo(name)
    info.size = len(data)
    tf.addfile(info, io.BytesIO(data))
