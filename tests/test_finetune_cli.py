"""`train --from-pretrained`: CLI fine-tuning from HF checkpoints with
optional resolution change and classifier head swap."""

import numpy as np

from jimm_tpu.cli import main

from hf_util import save_tiny_siglip, save_tiny_vit


def test_vit_finetune_head_swap_and_resolution(tmp_path, capsys):
    ckpt = save_tiny_vit(tmp_path / "ckpt")  # 7 classes, 48px, patch 16
    rc = main(["train", "--preset", "vit-base-patch16-224",
               "--from-pretrained", str(ckpt), "--image-size", "96",
               "--num-classes", "3", "--steps", "2", "--batch-size", "4",
               "--platform", "cpu", "--log-every", "1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "fresh classifier head: 3 classes" in out
    assert "step 1" in out


def test_siglip_finetune_ring_loss_on_mesh(tmp_path, capsys, eight_devices):
    ckpt = save_tiny_siglip(tmp_path / "ckpt")
    rc = main(["train", "--preset", "siglip-base-patch16-256",
               "--from-pretrained", str(ckpt), "--steps", "2",
               "--batch-size", "8", "--platform", "cpu",
               "--host-devices", "8", "--mesh", "data=4,model=2",
               "--rules", "fsdp_tp", "--loss", "siglip_ring",
               "--log-every", "1"])
    assert rc == 0
    assert "step 1" in capsys.readouterr().out


def test_evaluate_finetuned_run(tmp_path, rng, capsys):
    """evaluate --from-pretrained rebuilds the fine-tuned architecture
    (incl. the swapped head) so the orbax restore shapes match."""
    import json

    from jimm_tpu.data.records import write_classification_records
    ckpt = save_tiny_vit(tmp_path / "ckpt")
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8), i % 3)
             for i in range(8)]
    write_classification_records(tmp_path / "d.tfrecord", pairs,
                                 encoding="raw")
    ck = tmp_path / "run"
    assert main(["train", "--preset", "vit-base-patch16-224",
                 "--from-pretrained", str(ckpt), "--data",
                 str(tmp_path / "d.tfrecord"), "--num-classes", "3",
                 "--steps", "2", "--batch-size", "4", "--platform", "cpu",
                 "--ckpt-dir", str(ck), "--save-every", "1"]) == 0
    assert main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
                 "--preset", "vit-base-patch16-224", "--from-pretrained",
                 str(ckpt), "--num-classes", "3", "--ckpt-dir", str(ck),
                 "--batch-size", "4", "--platform", "cpu"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 8


def test_export_run_roundtrips_through_hf(tmp_path):
    """fine-tune -> export-run -> the output loads in transformers AND back
    through from_pretrained with the trained head."""
    from transformers import ViTForImageClassification

    from jimm_tpu import VisionTransformer
    ckpt = save_tiny_vit(tmp_path / "ckpt")
    ck, out = tmp_path / "run", tmp_path / "exported"
    assert main(["train", "--preset", "vit-base-patch16-224",
                 "--from-pretrained", str(ckpt), "--num-classes", "3",
                 "--steps", "2", "--batch-size", "4", "--platform", "cpu",
                 "--ckpt-dir", str(ck), "--save-every", "1"]) == 0
    assert main(["export-run", str(out), "--ckpt-dir", str(ck),
                 "--preset", "vit-base-patch16-224", "--from-pretrained",
                 str(ckpt), "--num-classes", "3", "--platform", "cpu"]) == 0
    again = VisionTransformer.from_pretrained(str(out))
    assert again.config.num_classes == 3
    hf = ViTForImageClassification.from_pretrained(str(out)).eval()
    assert hf.config.num_labels == 3


def test_vit_finetune_keeps_matching_head(tmp_path, capsys):
    ckpt = save_tiny_vit(tmp_path / "ckpt")  # 7 classes
    rc = main(["train", "--preset", "vit-base-patch16-224",
               "--from-pretrained", str(ckpt), "--num-classes", "7",
               "--steps", "1", "--batch-size", "4", "--platform", "cpu",
               "--log-every", "1"])
    assert rc == 0
    assert "fresh classifier head" not in capsys.readouterr().out


def test_finetune_rejects_bad_pipeline_config_before_compile(
        tmp_path, eight_devices):
    """The parse-time pipeline validation must also cover the fine-tune
    path: runtime pp flags are applied to the loaded checkpoint's config,
    so bad values used to surface only inside the shard_map trace."""
    import pytest

    ckpt = save_tiny_siglip(tmp_path / "ckpt")  # depth-3 towers
    with pytest.raises(SystemExit,
                       match="not divisible by 2 stages x 2 virtual"):
        main(["train", "--preset", "siglip-base-patch16-256",
              "--from-pretrained", str(ckpt), "--steps", "1",
              "--batch-size", "8", "--platform", "cpu",
              "--host-devices", "8", "--mesh", "data=4,stage=2",
              "--rules", "pp", "--pipeline-microbatches", "3",
              "--pipeline-virtual", "2"])
