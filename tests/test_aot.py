"""jimm_tpu.aot: keys, store, export round-trip, and serve warm-start.

The e2e class asserts the subsystem's two acceptance properties on CPU:
a fresh engine over a populated store reaches readiness with **zero**
fresh jit compilations (the serve `compile_count` gauge), and a corrupt
or version-mismatched store degrades to fresh compiles — incrementing
``jimm_aot_fallback_total`` — while still serving correct results.
"""

import asyncio
import json
import subprocess
import sys

import numpy as np
import pytest

from jimm_tpu.aot import (AOT_FORMAT_VERSION, ArtifactStore, canonical_json,
                          config_hash, donation_signature, serve_forward_key)

# ---------------------------------------------------------------------------
# keys
# ---------------------------------------------------------------------------

#: one fully-pinned key tuple, shared by the golden + subprocess tests
GOLDEN_KEY_KWARGS = dict(
    method="encode_image", bucket=4, item_shape=(32, 32, 3),
    in_dtype="float32", param_dtype="float32", mesh={"data": 8},
    backend="cpu", jax_version="0.0-test", jaxlib_version="0.0-test")
GOLDEN_CONFIG = {"family": "clip",
                 "vision": {"width": 64, "depth": 2, "image_size": 32}}
GOLDEN_FP = "e9ae5ee4081cf8d1a67403e413530de3bac7f25931ddfc98c4c02472229b0de1"


def golden_key():
    return serve_forward_key(GOLDEN_CONFIG, donation=donation_signature(),
                             **GOLDEN_KEY_KWARGS)


class TestKeys:
    def test_canonical_json_is_order_insensitive(self):
        a = canonical_json({"b": 1, "a": {"y": 2, "x": (3, 4)}})
        b = canonical_json({"a": {"x": [3, 4], "y": 2}, "b": 1})
        assert a == b == '{"a":{"x":[3,4],"y":2},"b":1}'

    def test_config_hash_ignores_key_order_not_values(self):
        assert config_hash({"w": 64, "d": 2}) == config_hash({"d": 2, "w": 64})
        assert config_hash({"w": 64}) != config_hash({"w": 65})

    def test_golden_fingerprint(self):
        # byte-stability contract: this digest may only change with a
        # deliberate AOT_FORMAT_VERSION bump (which invalidates stores)
        assert golden_key().fingerprint() == GOLDEN_FP

    def test_fingerprint_stable_across_processes(self):
        code = (
            "from jimm_tpu.aot import serve_forward_key, donation_signature\n"
            f"key = serve_forward_key({GOLDEN_CONFIG!r}, "
            f"donation=donation_signature(), **{GOLDEN_KEY_KWARGS!r})\n"
            "print(key.fingerprint())\n")
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, check=True)
        assert out.stdout.strip() == GOLDEN_FP

    def test_every_field_changes_the_fingerprint(self):
        base = golden_key().fingerprint()
        for change in (dict(bucket=8), dict(method="__call__"),
                       dict(item_shape=(64, 64, 3)), dict(in_dtype="bfloat16"),
                       dict(param_dtype="bfloat16"), dict(mesh={"data": 4}),
                       dict(backend="tpu"), dict(jax_version="9.9"),
                       dict(jaxlib_version="9.9")):
            kw = {**GOLDEN_KEY_KWARGS, **change}
            other = serve_forward_key(GOLDEN_CONFIG,
                                      donation=donation_signature(), **kw)
            assert other.fingerprint() != base, change
        assert serve_forward_key(
            GOLDEN_CONFIG, donation=donation_signature(
                donate_argnums=(0,)),
            **GOLDEN_KEY_KWARGS).fingerprint() != base

    def test_mesh_object_and_dict_agree(self):
        class FakeMesh:
            shape = {"data": 8}
        a = serve_forward_key(GOLDEN_CONFIG, mesh=FakeMesh(),
                              donation=donation_signature(),
                              **{k: v for k, v in GOLDEN_KEY_KWARGS.items()
                                 if k != "mesh"})
        assert a.fingerprint() == GOLDEN_FP


# ---------------------------------------------------------------------------
# store
# ---------------------------------------------------------------------------

class TestStore:
    def test_put_get_roundtrip(self, tmp_path):
        store = ArtifactStore(tmp_path / "s")
        fp = "ab" + "0" * 62
        store.put(fp, b"payload-bytes", meta={"label": "t", "bucket": 1})
        assert store.contains(fp)
        assert store.get(fp) == b"payload-bytes"
        [entry] = store.entries()
        assert entry.fingerprint == fp
        assert entry.meta["label"] == "t"
        assert entry.meta["format_version"] == AOT_FORMAT_VERSION

    def test_get_miss_returns_none(self, tmp_path):
        store = ArtifactStore(tmp_path / "s")
        assert store.get("cd" + "0" * 62) is None

    def test_corrupt_payload_quarantined(self, tmp_path):
        store = ArtifactStore(tmp_path / "s")
        fp = "ab" + "1" * 62
        store.put(fp, b"good-bytes")
        (store.entry_dir(fp) / "artifact.bin").write_bytes(b"bit-rotted!")
        assert store.get(fp) is None          # never a corrupt executable
        assert not store.contains(fp)          # next lookup is a clean miss
        [q] = list(store.quarantine_dir.iterdir())
        assert "sha256 mismatch" in (q / "reason.txt").read_text()

    def test_format_version_mismatch_quarantined(self, tmp_path):
        store = ArtifactStore(tmp_path / "s")
        fp = "ab" + "2" * 62
        store.put(fp, b"old-format")
        meta_path = store.entry_dir(fp) / "meta.json"
        meta = json.loads(meta_path.read_text())
        meta["format_version"] = AOT_FORMAT_VERSION + 1
        meta_path.write_text(json.dumps(meta))
        assert store.get(fp) is None
        assert not store.contains(fp)

    def test_jax_version_mismatch_quarantined(self, tmp_path):
        # an entry recorded under another jax must never deserialize; the
        # caller sees a miss and compiles fresh, without error
        store = ArtifactStore(tmp_path / "s")
        fp = "ab" + "3" * 62
        store.put(fp, b"other-jax", meta={"jax": "0.1-old"})
        assert store.get(fp, expect_versions={"jax": "0.4-new"}) is None
        assert not store.contains(fp)
        [q] = list(store.quarantine_dir.iterdir())
        assert "jax mismatch" in (q / "reason.txt").read_text()
        # same fingerprint can be re-put afterwards (fresh write-through)
        store.put(fp, b"recompiled", meta={"jax": "0.4-new"})
        assert store.get(fp, expect_versions={"jax": "0.4-new"}) \
            == b"recompiled"

    def test_lru_eviction_by_size_cap(self, tmp_path):
        import os
        import time
        store = ArtifactStore(tmp_path / "s", max_bytes=250)
        fps = [f"{i:02x}" + str(i) * 62 for i in range(3)]
        now = time.time()
        for i, fp in enumerate(fps):
            store.put(fp, bytes(100))
            # deterministic LRU order without sleeping: backdate mtimes
            os.utime(store.entry_dir(fp) / "artifact.bin",
                     (now - 100 + i, now - 100 + i))
        # 300 bytes > 250 cap: the least-recently-used entry is gone
        assert not store.contains(fps[0])
        assert store.contains(fps[1]) and store.contains(fps[2])
        # a hit refreshes recency: touch fps[1], add a fourth entry
        store.get(fps[1])
        fp3 = "ff" + "9" * 62
        store.put(fp3, bytes(100))
        assert store.contains(fps[1])
        assert not store.contains(fps[2])

    def test_verify_quarantines_bad_entries(self, tmp_path):
        store = ArtifactStore(tmp_path / "s")
        good, bad = "aa" + "0" * 62, "bb" + "0" * 62
        store.put(good, b"fine")
        store.put(bad, b"fine-too")
        (store.entry_dir(bad) / "artifact.bin").write_bytes(b"flipped")
        problems = store.verify()
        assert [p["fingerprint"] for p in problems] == [bad]
        assert store.contains(good) and not store.contains(bad)
        assert store.verify() == []  # quarantine is not re-reported


# ---------------------------------------------------------------------------
# export round-trip + serve warm-start e2e (tiny CLIP, CPU)
# ---------------------------------------------------------------------------

BUCKETS = (1, 2)


@pytest.fixture(scope="module")
def tiny_clip():
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.cli import _tiny_override
    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    return CLIP(cfg, rngs=nnx.Rngs(0))


@pytest.fixture(scope="module")
def warm_store(tiny_clip, tmp_path_factory):
    from jimm_tpu.aot.warmup import warmup_store
    store = ArtifactStore(tmp_path_factory.mktemp("aot"))
    report = warmup_store(tiny_clip, method="encode_image", buckets=BUCKETS,
                          item_shape=(32, 32, 3), store=store, label="test")
    assert {b: r["action"] for b, r in report.items()} \
        == {1: "compiled", 2: "compiled"}
    return store


def make_forward(model, store):
    from jimm_tpu.aot.warmup import AotForward
    return AotForward(model, method="encode_image", item_shape=(32, 32, 3),
                      store=store, label="test")


def counter_values():
    from jimm_tpu import obs
    snap = obs.get_registry("jimm_aot").snapshot()
    return {k: snap.get(k, 0.0)
            for k in ("hit_total", "miss_total", "fallback_total")}


class TestWarmStartE2E:
    def test_populated_store_zero_fresh_compiles(self, tiny_clip, warm_store):
        from jimm_tpu.serve import BucketTable, InferenceEngine
        before = counter_values()
        forward = make_forward(tiny_clip, warm_store)
        engine = InferenceEngine(forward, item_shape=(32, 32, 3),
                                 buckets=BucketTable(BUCKETS),
                                 trace_count=forward.trace_count)
        engine.warmup_blocking()
        # THE acceptance property: readiness without one fresh jit trace
        assert forward.trace_count() == 0
        assert engine.metrics.snapshot()["compile_count"] == 0
        assert engine.warmup_report == {
            1: {"seconds": engine.warmup_report[1]["seconds"],
                "source": "aot"},
            2: {"seconds": engine.warmup_report[2]["seconds"],
                "source": "aot"}}
        after = counter_values()
        assert after["hit_total"] - before["hit_total"] == len(BUCKETS)
        assert after["fallback_total"] == before["fallback_total"]

    def test_aot_forward_matches_fresh_model(self, tiny_clip, warm_store):
        forward = make_forward(tiny_clip, warm_store)
        for b in BUCKETS:
            forward.prepare_bucket(b)
        x = np.random.RandomState(0).randn(2, 32, 32, 3).astype(np.float32)
        got = np.asarray(forward(x))
        want = np.asarray(tiny_clip.encode_image(x))
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
        assert forward.trace_count() == 0

    def test_corrupt_store_falls_back_and_still_serves(self, tiny_clip,
                                                       warm_store, tmp_path):
        import shutil
        store = ArtifactStore(tmp_path / "corrupt")
        shutil.copytree(warm_store.root / "objects", store.root / "objects",
                        dirs_exist_ok=True)
        for entry in store.entries():
            (entry.path / "artifact.bin").write_bytes(b"garbage")
        before = counter_values()
        forward = make_forward(tiny_clip, store)
        from jimm_tpu.serve import BucketTable, InferenceEngine
        engine = InferenceEngine(forward, item_shape=(32, 32, 3),
                                 buckets=BucketTable(BUCKETS),
                                 trace_count=forward.trace_count)
        engine.warmup_blocking()  # degrades, never raises
        assert {v["source"] for v in engine.warmup_report.values()} \
            == {"fallback"}
        after = counter_values()
        assert after["fallback_total"] - before["fallback_total"] \
            == len(BUCKETS)
        assert forward.trace_count() > 0  # fresh compiles did the work
        # ...and it still serves correct numbers end-to-end
        async def roundtrip():
            await engine.start()
            try:
                x = np.ones((32, 32, 3), np.float32)
                out = await engine.submit(x)
                return np.asarray(out)
            finally:
                await engine.stop()
        got = asyncio.run(roundtrip())
        want = np.asarray(tiny_clip.encode_image(
            np.ones((1, 32, 32, 3), np.float32)))[0]
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)

    def test_version_mismatch_falls_back_without_error(self, tiny_clip,
                                                       warm_store, tmp_path):
        import shutil
        store = ArtifactStore(tmp_path / "verdrift")
        shutil.copytree(warm_store.root / "objects", store.root / "objects",
                        dirs_exist_ok=True)
        for entry in store.entries():
            meta = dict(entry.meta)
            meta["jax"] = "0.0.1-ancient"
            (entry.path / "meta.json").write_text(json.dumps(meta))
        before = counter_values()
        forward = make_forward(tiny_clip, store)
        # never raises: the mismatched entry is quarantined, the bucket
        # falls back to a fresh compile, and serving proceeds
        assert forward.prepare_bucket(1) == "fallback"
        after = counter_values()
        assert after["fallback_total"] - before["fallback_total"] == 1
        fp = forward.key_for(1).fingerprint()
        assert not store.contains(fp)  # quarantined, not deleted
        assert any(store.quarantine_dir.iterdir())
        x = np.ones((1, 32, 32, 3), np.float32)
        want = np.asarray(tiny_clip.encode_image(x))
        np.testing.assert_allclose(np.asarray(forward(x)), want,
                                   rtol=1e-5, atol=1e-5)
        assert forward.trace_count() > 0  # the fresh compile did the work

    def test_write_through_populates_empty_store(self, tiny_clip, tmp_path):
        store = ArtifactStore(tmp_path / "wt")
        forward = make_forward(tiny_clip, store)
        assert forward.prepare_bucket(1) == "miss"
        assert len(store.entries()) == 1  # write-through happened
        # a second process (fresh forward) now starts warm
        forward2 = make_forward(tiny_clip, store)
        assert forward2.prepare_bucket(1) == "aot"
        assert forward2.trace_count() == 0

    def test_warmup_naflex_compiles_one_program_per_bucket_pair(self):
        """NaFlex serve warmup: one compile per (batch, seq) bucket pair,
        and a padded batch with different mask CONTENTS reuses the warm
        executable (the mask is runtime data, not a compile shape)."""
        from flax import nnx

        from jimm_tpu import SigLIP
        from jimm_tpu.aot.warmup import warmup_naflex
        from jimm_tpu.configs import SigLIPConfig, TextConfig, VisionConfig
        cfg = SigLIPConfig(
            vision=VisionConfig(image_size=16, patch_size=8, width=32,
                                depth=2, num_heads=2, mlp_dim=64,
                                act="gelu_tanh", pooling="map"),
            text=TextConfig(vocab_size=64, context_length=8, width=32,
                            depth=2, num_heads=2, mlp_dim=64,
                            act="gelu_tanh", causal=False, pooling="last",
                            proj_bias=True),
            projection_dim=32)
        model = SigLIP(cfg, rngs=nnx.Rngs(0))
        report = warmup_naflex(model, batch_buckets=(1, 2),
                               seq_buckets=(8,))
        assert set(report) == {(1, 8), (2, 8)}
        assert all(r["traces"] == 1 for r in report.values())
        assert all(r["seconds"] >= 0 for r in report.values())

    def test_enable_persistent_cache(self, tmp_path):
        import jax

        from jimm_tpu.aot.export import enable_persistent_cache
        old = jax.config.jax_compilation_cache_dir
        try:
            assert enable_persistent_cache(tmp_path / "xla") is True
            assert jax.config.jax_compilation_cache_dir \
                == str(tmp_path / "xla")
        finally:
            jax.config.update("jax_compilation_cache_dir", old)


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
