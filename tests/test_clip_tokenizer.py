"""Pure-python CLIP tokenizer vs the transformers oracle: identical ids on
the same vocab/merges files."""

import numpy as np
import pytest

transformers = pytest.importorskip("transformers")

from jimm_tpu.data.clip_tokenizer import CLIPTokenizer


@pytest.fixture(scope="module")
def vocab_dir(clip_vocab_dir):
    # shared synthetic vocab/merges builder: tests/conftest.py
    return clip_vocab_dir


PROMPTS = [
    "a photo of a cat",
    "The THE the",
    "hello, world!!",
    "don't stop",
    "42 cats",
    "  spaced   out  ",
    "café ph",
    "a cat <|endoftext|> the",  # literal special maps to its single id
]


@pytest.mark.parametrize("text", PROMPTS)
def test_ids_match_transformers(vocab_dir, text):
    ours = CLIPTokenizer.from_dir(vocab_dir)
    oracle = transformers.CLIPTokenizer(str(vocab_dir / "vocab.json"),
                                        str(vocab_dir / "merges.txt"))
    assert ours.encode(text) == oracle(text)["input_ids"], text


def test_batch_padding_matches_transformers(vocab_dir):
    ours = CLIPTokenizer.from_dir(vocab_dir)
    oracle = transformers.CLIPTokenizer(str(vocab_dir / "vocab.json"),
                                        str(vocab_dir / "merges.txt"))
    got = ours(PROMPTS[:4], context_length=16)
    want = oracle(PROMPTS[:4], padding="max_length", truncation=True,
                  max_length=16)["input_ids"]
    np.testing.assert_array_equal(got, np.asarray(want, np.int32))


def test_truncation_keeps_eot(vocab_dir):
    ours = CLIPTokenizer.from_dir(vocab_dir)
    ids = ours("cat " * 50, context_length=8)[0]
    assert ids.shape == (8,)
    assert ids[0] == ours.sot_id and ids[-1] == ours.eot_id


def test_eot_is_max_id(vocab_dir):
    # our CLIP text pooling (argmax fallback) relies on EOT being the max id
    ours = CLIPTokenizer.from_dir(vocab_dir)
    assert ours.eot_id == max(ours.encoder.values())
