"""Text tower: token + positional embedding, encoder, final LN, pooling.

Parity notes (SURVEY Appendix A):
- CLIP: causal encoder; pooled feature = hidden state at ``argmax(token_ids)``
  (EOT has the maximum token id in CLIP's vocab — ref `models/clip.py:164-166`).
- SigLIP: bidirectional encoder; pooled feature = last position ``x[:, -1]``
  (requires max-length padding at tokenization — ref `models/siglip.py:151`).
- Positional embedding is sliced to the input sequence length
  (ref `models/clip.py:160`, `models/siglip.py:147`).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import nnx

from jimm_tpu.configs import TextConfig
from jimm_tpu.nn.transformer import Transformer, _layernorm
from jimm_tpu.parallel.sharding import logical, logical_constraint


class TextTower(nnx.Module):
    def __init__(self, cfg: TextConfig, rngs: nnx.Rngs, *, dtype=None,
                 param_dtype=jnp.float32):
        self.cfg = cfg
        self.token_embed = nnx.Embed(
            cfg.vocab_size, cfg.width, dtype=dtype, param_dtype=param_dtype,
            embedding_init=logical(nnx.initializers.normal(0.02),
                                   "vocab", "embed"),
            rngs=rngs)
        self.pos_embed = nnx.Param(
            logical(nnx.initializers.normal(0.01), "pos", "embed")(
                rngs.params(), (cfg.context_length, cfg.width), param_dtype))
        self.encoder = Transformer(cfg.encoder(), rngs, dtype=dtype,
                                   param_dtype=param_dtype)
        self.ln_final = _layernorm(cfg.width, cfg.ln_eps, rngs, dtype=dtype,
                                   param_dtype=param_dtype)

    def __call__(self, text: jax.Array) -> jax.Array:
        """(B, S) int token ids -> (B, S, width) final hidden states."""
        seq_len = text.shape[1]
        # Under FSDP rules the table's embed dim is sharded over "data"; a
        # direct gather then yields width-sharded activations that XLA cannot
        # reshard to the batch layout on a hybrid mesh without a full
        # replicate ("[SPMD] Involuntary full rematerialization", r2 dryrun).
        # Constrain the table to vocab-sharding only — the standard FSDP
        # gather-on-use — so the lookup inherits the batch sharding from the
        # indices instead.
        table = self.token_embed.embedding[...]
        if self.token_embed.dtype is not None:
            table = table.astype(self.token_embed.dtype)
        table = logical_constraint(table, "vocab", None)
        x = jnp.take(table, text, axis=0)
        x = x + self.pos_embed[...][:seq_len].astype(x.dtype)
        x = logical_constraint(x, "batch", "seq", None)
        x = self.encoder(x)
        return self.ln_final(x)

    def pool(self, hidden: jax.Array, text: jax.Array) -> jax.Array:
        """Pool final hidden states per the configured strategy."""
        if self.cfg.pooling == "eot":
            if self.cfg.eos_token_id in (None, 2):
                # HF CLIPTextTransformer's LEGACY path: configs carrying the
                # historical bogus eos_token_id=2 (all original OpenAI CLIP
                # checkpoints) pool at argmax(ids) — EOT is the max vocab id
                eot = jnp.argmax(text, axis=-1)
            else:
                # modern HF configs: FIRST occurrence of the real EOS id.
                # argmax over the boolean mask returns the first True (or 0
                # when the row has no EOS — same as HF).
                eot = jnp.argmax(text == self.cfg.eos_token_id, axis=-1)
            return hidden[jnp.arange(hidden.shape[0]), eot]
        if self.cfg.pooling == "last":
            return hidden[:, -1]
        raise ValueError(f"unsupported text pooling {self.cfg.pooling!r}")
