"""jimm_tpu — a TPU-native image-model framework (ViT / CLIP / SigLIP).

TPU-first rebuild of the capabilities of `pythoncrazy/jimm`: flax-NNX models
with scanned layer stacks, logical-axis sharding policies over `jax.sharding`
meshes, pure-safetensors HuggingFace checkpoint loading (zero torch), Pallas
flash attention, and distributed contrastive training with a ring sigmoid
loss.

The package namespace is lazy (PEP 562): importing ``jimm_tpu`` (or a pure
host subpackage like ``jimm_tpu.aot``/``jimm_tpu.tune``/``jimm_tpu.obs``)
does NOT import jax. The model/config names below resolve on first access,
which is when the version floor is checked and the flax compat backfills
(`jimm_tpu.utils.compat`) load — so ``jimm-tpu tune ls``/``aot ls``/``obs``
stay usable on a box with no accelerator stack.
"""

from __future__ import annotations

import importlib
from typing import Any

__version__ = "0.1.0"

#: lazily resolved public names -> defining module
_LAZY = {
    "CLIP": "jimm_tpu.models",
    "SigLIP": "jimm_tpu.models",
    "VisionTransformer": "jimm_tpu.models",
    "CLIPConfig": "jimm_tpu.configs",
    "SigLIPConfig": "jimm_tpu.configs",
    "ViTConfig": "jimm_tpu.configs",
    "VisionConfig": "jimm_tpu.configs",
    "TextConfig": "jimm_tpu.configs",
    "TransformerConfig": "jimm_tpu.configs",
    "PRESETS": "jimm_tpu.configs",
    "preset": "jimm_tpu.configs",
    "RUNTIME_FIELDS": "jimm_tpu.configs",
    "with_runtime": "jimm_tpu.configs",
}

__all__ = [
    "CLIP", "SigLIP", "VisionTransformer",
    "CLIPConfig", "SigLIPConfig", "ViTConfig", "VisionConfig", "TextConfig",
    "TransformerConfig", "PRESETS", "preset",
    "RUNTIME_FIELDS", "with_runtime",
]


def _check_versions() -> None:
    """Fail fast with a clear message on JAX/flax older than the tested
    floor (pyproject.toml mirrors these; pip cannot enforce them for
    source checkouts or pre-installed environments)."""
    import jax
    from flax import __version__ as flax_version

    def parse(v: str) -> tuple[int, ...]:
        parts = []
        for p in v.split(".")[:3]:
            digits = "".join(ch for ch in p if ch.isdigit())
            if not digits:
                break
            parts.append(int(digits))
        return tuple(parts)

    floors = (("jax", jax.__version__, (0, 4, 35)),
              ("flax", flax_version, (0, 10)))
    for name, have, floor in floors:
        if parse(have) and parse(have) < floor:
            raise ImportError(
                f"jimm_tpu requires {name} >= {'.'.join(map(str, floor))}, "
                f"found {have}. Upgrade with `pip install -U {name}` "
                f"(TPU: `pip install -U 'jax[tpu]'`).")


_ready = False


def _prepare() -> None:
    """Version floor + compat backfills, once, before any model/config
    attribute resolves. `jimm_tpu.utils.compat` is imported for its side
    effects: it backfills nnx module/class attributes (to_flat_state,
    Variable.set_value, ...) that flax 0.10 lacks. Modules that use those
    backfills also import it directly, so reaching them through a plain
    submodule import (bypassing this hook) stays safe."""
    global _ready
    if not _ready:
        _check_versions()
        importlib.import_module("jimm_tpu.utils.compat")
        _ready = True


def __getattr__(name: str) -> Any:
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module 'jimm_tpu' has no attribute {name!r}")
    _prepare()
    value = getattr(importlib.import_module(module), name)
    globals()[name] = value
    return value


def __dir__() -> list[str]:
    return sorted(set(globals()) | set(_LAZY))
