"""From-scratch ViT classification training on a device mesh.

Equivalent of the reference's `examples/vit_training.py` (MNIST DP training),
rebuilt on the library's training machinery: logical-rules sharding (DP by
default, `--rules fsdp` for ZeRO-style), prefetching input pipeline,
warmup-cosine AdamW, MFU/throughput metrics, and orbax checkpointing. Uses a
procedural dataset so it runs offline; swap `blob_classification` for your
own iterator of (images NHWC float32, integer labels).

Run:  python examples/vit_training.py --steps 200 --batch-size 256
"""

from __future__ import annotations

import jimm_tpu.utils.env
jimm_tpu.utils.env.configure_platform()

import argparse

import jax
import numpy as np
from flax import nnx

from jimm_tpu import ViTConfig, VisionConfig, VisionTransformer
from jimm_tpu.data import PrefetchIterator, blob_classification
from jimm_tpu.parallel import PRESET_RULES, make_mesh, use_sharding
from jimm_tpu.train import (CheckpointManager, MetricsLogger, OptimizerConfig,
                            StepTimer, make_classifier_train_step,
                            make_optimizer, mfu)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--image-size", type=int, default=28)
    p.add_argument("--width", type=int, default=256)
    p.add_argument("--depth", type=int, default=4)
    p.add_argument("--rules", default="dp", choices=sorted(PRESET_RULES))
    p.add_argument("--model-axis", type=int, default=1)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--log", default=None, help="JSONL metrics path")
    args = p.parse_args()

    mesh = make_mesh({"data": -1, "model": args.model_axis})
    rules = PRESET_RULES[args.rules]
    print(f"mesh {dict(mesh.shape)} rules {args.rules}")

    cfg = ViTConfig(
        vision=VisionConfig(image_size=args.image_size, patch_size=7,
                            width=args.width, depth=args.depth,
                            num_heads=max(2, args.width // 64),
                            mlp_dim=args.width * 4, ln_eps=1e-12),
        num_classes=4)
    model = VisionTransformer(cfg, rngs=nnx.Rngs(0), mesh=mesh, rules=rules)
    optimizer = make_optimizer(model, OptimizerConfig(
        learning_rate=args.lr, warmup_steps=20, total_steps=args.steps))
    train_step = make_classifier_train_step(donate=True)
    logger = MetricsLogger(path=args.log, print_every=10)
    ckpt = CheckpointManager(args.ckpt_dir) if args.ckpt_dir else None

    data = PrefetchIterator(
        blob_classification(args.batch_size, image_size=args.image_size),
        mesh=mesh, rules=rules)
    timer = StepTimer()
    images_per_step = args.batch_size

    with use_sharding(mesh, rules):
        for step, (images, labels) in zip(range(args.steps), data):
            timer.start()
            metrics = train_step(model, optimizer, images, labels)
            dt = timer.stop(metrics["loss"])
            logger.log(step, loss=metrics["loss"],
                       accuracy=metrics["accuracy"],
                       images_per_sec=images_per_step / dt)
            if ckpt and step and step % 100 == 0:
                ckpt.save(step, model, optimizer)
    if ckpt:
        ckpt.save(args.steps, model, optimizer, force=True)
        ckpt.wait()
        ckpt.close()
    data.close()
    logger.close()
    print(f"final: loss={float(metrics['loss']):.4f} "
          f"accuracy={float(metrics['accuracy']):.4f}")


if __name__ == "__main__":
    main()
