"""``jimm_tpu.serve`` — async micro-batching inference serving.

The path from a loaded checkpoint to sustained request traffic: an asyncio
engine coalesces single requests into micro-batches, pads them into a fixed
set of warm-compiled shape buckets (zero recompiles after warmup — the
runtime side of the linter's JLT103 discipline), a small LRU skips the text
tower on repeat zero-shot label sets, and bounded-queue admission control
with per-request deadlines keeps overload behavior predictable. Front end
and client are stdlib-only. See ``docs/serving.md``.
"""

from jimm_tpu.serve.admission import (AdmissionController, AdmissionPolicy,
                                      DeadlineExceededError, EngineClosedError,
                                      QueueFullError, RequestError,
                                      ServeError, ServeMetrics, ShedError,
                                      ThrottledError)
from jimm_tpu.serve.buckets import (DEFAULT_BATCH_BUCKETS, SERVE_DTYPES,
                                    TPU_BATCH_BUCKETS, BucketTable,
                                    default_buckets, pad_batch)
from jimm_tpu.serve.cache import (EmbeddingCache, class_embedding_cache,
                                  prompt_set_key)
from jimm_tpu.serve.cascade import (CascadeAutoscaler, CascadeCalibration,
                                    CascadeResult, CascadeRouter,
                                    CascadeStage, ScaleTarget,
                                    fit_calibration, fit_from_logits,
                                    load_calibration, save_calibration)
from jimm_tpu.serve.client import (CascadeInfo, EmbedResult, ServeClient,
                                   ServeClientError, ShedClientError,
                                   ThrottledClientError,
                                   encode_image_payload,
                                   parse_cascade_headers)
from jimm_tpu.serve.engine import InferenceEngine, counting_forward
from jimm_tpu.serve.qos import (ModelPool, QosPolicyError, QosScheduler,
                                TenantRegistry, TenantSpec,
                                WeightedFairQueue, load_policy)
from jimm_tpu.serve.server import (ServingServer, ZeroShotService,
                                   decode_image_payload)
from jimm_tpu.serve.topology import (ReplicaForward, TopologyPlan,
                                     build_replica_forwards, plan_topology)

__all__ = [
    "AdmissionController", "AdmissionPolicy", "BucketTable",
    "CascadeAutoscaler", "CascadeCalibration", "CascadeInfo",
    "CascadeResult", "CascadeRouter", "CascadeStage",
    "DEFAULT_BATCH_BUCKETS", "DeadlineExceededError", "EmbedResult",
    "EmbeddingCache",
    "EngineClosedError", "InferenceEngine", "ModelPool", "QosPolicyError",
    "QosScheduler", "QueueFullError", "ReplicaForward",
    "RequestError", "ScaleTarget", "ServeClient", "ServeClientError",
    "ServeError",
    "SERVE_DTYPES", "ServeMetrics", "ServingServer", "ShedClientError",
    "ShedError", "TPU_BATCH_BUCKETS", "TenantRegistry", "TenantSpec",
    "ThrottledClientError", "ThrottledError", "TopologyPlan",
    "WeightedFairQueue",
    "ZeroShotService", "build_replica_forwards", "class_embedding_cache",
    "counting_forward", "decode_image_payload", "default_buckets",
    "encode_image_payload", "fit_calibration", "fit_from_logits",
    "load_calibration", "load_policy", "pad_batch",
    "parse_cascade_headers", "plan_topology",
    "prompt_set_key", "save_calibration",
]
