"""Ring attention (sequence parallelism) vs full-attention oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu.ops.attention import reference_attention
from jimm_tpu.parallel import make_mesh
from jimm_tpu.parallel.ring_attention import ring_attention


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh({"seq": 8})


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(rng, mesh, causal):
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.5)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh=mesh, is_causal=causal)
    ref = reference_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_sharded_inputs_under_jit(rng, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32))
               for _ in range(3))
    sharding = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh=mesh))(qs, ks, vs)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)
    # output stays sequence-sharded — no gather materializes the full seq
    assert out.sharding.spec == P(None, "seq")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_gradients_match_full_attention(rng, mesh, causal):
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32) * 0.5)
               for _ in range(3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh,
                                      is_causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, is_causal=causal) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_matches_full_attention(rng, mesh, causal):
    """Flash-within-chip x ring-across-chips composition; causal runs
    block-causally (own chunk causal, earlier full, later skipped)."""
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.5)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh=mesh, impl="flash", is_causal=causal)
    ref = reference_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_flash_ring_gradients_match(rng, mesh, causal):
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32) * 0.5)
               for _ in range(3))

    def loss(fn):
        return jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v) ** 2), argnums=(0, 1, 2)
        )(q, k, v)

    gr = loss(lambda q, k, v: ring_attention(q, k, v, mesh=mesh,
                                             impl="flash", is_causal=causal))
    gf = loss(lambda q, k, v: reference_attention(q, k, v,
                                                  is_causal=causal))
    for name, a, b in zip("qkv", gr, gf):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=f"d{name}")


def test_transformer_ring_impl_matches_xla(rng, mesh):
    """attn_impl='ring' inside a full encoder stack under a seq-sharded mesh
    equals the single-device xla path."""
    import jax.numpy as jnp
    from flax import nnx
    from jimm_tpu.configs import TransformerConfig
    from jimm_tpu.nn.transformer import Transformer
    from jimm_tpu.parallel import (SEQUENCE_PARALLEL, make_mesh, shard_batch,
                                   use_sharding)

    sp_mesh = make_mesh({"data": 1, "seq": 8})
    x = rng.randn(2, 64, 32).astype(np.float32)

    base = dict(width=32, depth=2, num_heads=2, mlp_dim=64)
    plain = Transformer(TransformerConfig(**base, attn_impl="xla"),
                        nnx.Rngs(0))
    ref = np.asarray(plain(jnp.asarray(x)))

    ringed = Transformer(TransformerConfig(**base, attn_impl="ring"),
                         nnx.Rngs(0))
    with use_sharding(sp_mesh, SEQUENCE_PARALLEL):
        xs = shard_batch(x, sp_mesh, SEQUENCE_PARALLEL)
        out = np.asarray(ringed(xs))
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_zigzag_order_roundtrip():
    from jimm_tpu.parallel import zigzag_order, zigzag_shard, zigzag_unshard
    order = zigzag_order(16, 4)
    # device 0 gets chunks (0, 7), device 1 (1, 6), ...
    np.testing.assert_array_equal(order[:4], [0, 1, 14, 15])
    np.testing.assert_array_equal(order[4:8], [2, 3, 12, 13])
    x = jnp.arange(2 * 16 * 3, dtype=jnp.float32).reshape(2, 16, 3)
    np.testing.assert_array_equal(zigzag_unshard(zigzag_shard(x, 4), 4), x)


@pytest.mark.parametrize("impl", ["einsum", "flash"])
def test_zigzag_causal_matches_dense(rng, mesh, impl):
    """Causal ring in the zigzag layout (balanced per-rank work) is still
    exact: zigzag_shard -> ring -> zigzag_unshard == dense causal."""
    from jimm_tpu.parallel import zigzag_shard, zigzag_unshard
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.5)
               for _ in range(3))
    qz, kz, vz = (zigzag_shard(x, 8) for x in (q, k, v))
    out = ring_attention(qz, kz, vz, mesh=mesh, impl=impl, is_causal=True,
                         zigzag=True)
    out = zigzag_unshard(out, 8)
    ref = reference_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)


@pytest.mark.parametrize("impl", ["einsum", "flash"])
@pytest.mark.slow
def test_zigzag_causal_gradients_match(rng, mesh, impl):
    from jimm_tpu.parallel import zigzag_shard, zigzag_unshard
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32) * 0.5)
               for _ in range(3))

    def loss_zig(q, k, v):
        out = ring_attention(*(zigzag_shard(x, 8) for x in (q, k, v)),
                             mesh=mesh, impl=impl, is_causal=True,
                             zigzag=True)
        return jnp.sum(zigzag_unshard(out, 8) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, is_causal=True) ** 2)

    gr = jax.grad(loss_zig, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=f"d{name}")
