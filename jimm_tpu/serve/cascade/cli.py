"""``jimm-tpu cascade`` — fit and inspect cascade calibrations.

Two verbs, jax-free (numpy + stdlib — this must run on an operator
laptop or in CI, never on the serving box):

- ``calibrate`` — fit the confidence threshold from a holdout file of
  cheap/reference score rows for a target top-1 disagreement rate and
  persist it as a content-addressed artifact on the AOT store; prints
  the fingerprint a router loads it by.
- ``ls``        — list the calibrations resident on a store.

Wired as a subparser under the main ``jimm-tpu`` CLI (see jimm_tpu/cli.py).
"""

from __future__ import annotations

import argparse
import json
import sys

__all__ = ["add_cascade_parser", "cmd_cascade"]


def _load_holdout(path: str) -> tuple:
    """(cheap, reference) score rows from a holdout file: ``.npz`` with
    ``cheap``/``reference`` arrays, or ``.json`` with the same keys as
    nested lists."""
    if path.endswith(".npz"):
        import numpy as np
        data = np.load(path)
        keys = set(data.files)
    else:
        with open(path, encoding="utf-8") as fh:
            data = json.load(fh)
        keys = set(data)
    missing = {"cheap", "reference"} - keys
    if missing:
        raise ValueError(f"{path}: holdout missing {sorted(missing)} "
                         f"(has {sorted(keys)})")
    return data["cheap"], data["reference"]


def _cmd_calibrate(args) -> int:
    from jimm_tpu.aot.store import ArtifactStore
    from jimm_tpu.serve.cascade.calibrate import (fit_from_logits,
                                                  save_calibration)
    try:
        cheap, reference = _load_holdout(args.holdout)
        calib = fit_from_logits(
            cheap, reference, cheap_model=args.cheap_model,
            reference_model=args.reference_model,
            target_disagreement=args.target_disagreement)
    except (OSError, ValueError) as e:
        print(f"calibration failed: {e}", file=sys.stderr)
        return 1
    fingerprint = save_calibration(ArtifactStore(args.store), calib)
    if args.json:
        print(json.dumps(dict(calib.to_dict(), fingerprint=fingerprint),
                         indent=2, sort_keys=True))
        return 0
    print(f"calibration {calib.cheap_model} -> {calib.reference_model} "
          f"over {calib.holdout} holdout rows:")
    print(f"  temperature            {calib.temperature:g}")
    print(f"  threshold              {calib.threshold:g}")
    print(f"  measured disagreement  {calib.measured_disagreement:.4f} "
          f"(target {calib.target_disagreement:g})")
    print(f"  escalation fraction    {calib.escalation_fraction:.4f}")
    print(f"  fingerprint            {fingerprint}")
    return 0


def _cmd_ls(args) -> int:
    from jimm_tpu.aot.store import ArtifactStore
    from jimm_tpu.serve.cascade.calibrate import list_calibrations
    rows = list_calibrations(ArtifactStore(args.store))
    if args.json:
        print(json.dumps(rows, indent=2, sort_keys=True))
        return 0
    if not rows:
        print(f"no calibrations in {args.store}")
        return 0
    print(f"  {'label':<28} {'thresh':>8} {'temp':>8} {'disagree':>9} "
          f"{'escalate':>9}  fingerprint")
    for r in rows:
        print(f"  {str(r['label']):<28} {r['threshold']:>8g} "
              f"{r['temperature']:>8g} {r['measured_disagreement']:>9.4f} "
              f"{r['escalation_fraction']:>9.4f}  "
              f"{r['fingerprint'][:16]}…")
    return 0


def add_cascade_parser(subparsers) -> None:
    """Attach the ``cascade`` subcommand tree to the main CLI."""
    p = subparsers.add_parser(
        "cascade", help="fit and inspect cascade confidence calibrations")
    p.set_defaults(fn=cmd_cascade)
    sub = p.add_subparsers(dest="cascade_cmd", required=True)

    pc = sub.add_parser(
        "calibrate",
        help="fit a confidence threshold from a holdout file and persist "
             "it on the AOT store")
    pc.add_argument("holdout",
                    help=".npz or .json with cheap/reference score rows")
    pc.add_argument("--cheap-model", required=True,
                    help="pool name of the cheap (narrow-dtype) model")
    pc.add_argument("--reference-model", required=True,
                    help="pool name of the reference (wide-dtype) model")
    pc.add_argument("--target-disagreement", type=float, default=0.01,
                    help="max top-1 disagreement on accepted answers "
                         "(default 0.01)")
    pc.add_argument("--store", required=True,
                    help="AOT artifact store root to persist into")
    pc.add_argument("--json", action="store_true",
                    help="print the calibration as JSON")
    pc.set_defaults(cascade_func=_cmd_calibrate)

    pl = sub.add_parser("ls", help="list calibrations on a store")
    pl.add_argument("--store", required=True,
                    help="AOT artifact store root to list")
    pl.add_argument("--json", action="store_true",
                    help="print the listing as JSON")
    pl.set_defaults(cascade_func=_cmd_ls)


def cmd_cascade(args) -> int:
    return args.cascade_func(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jimm-tpu-cascade")
    sub = parser.add_subparsers(dest="command", required=True)
    add_cascade_parser(sub)
    args = parser.parse_args(argv)
    return cmd_cascade(args)


if __name__ == "__main__":
    raise SystemExit(main())
