"""Command-line interface: ``python -m jimm_tpu.lint [paths] [--trace]
[--concurrency] [--jaxpr] [--json] [--sarif OUT] [--suppressions]``.

Exit status is 1 when any **error**-severity finding survives suppression;
warnings are reported but never block. ``--json`` emits a machine-readable
report (one object per finding: rule, severity, path, line, message) and
``--sarif OUT`` writes a SARIF 2.1.0 log for code-scanning upload — both
carry findings from every enabled layer.
"""

from __future__ import annotations

import argparse
import json
import sys

from jimm_tpu.lint.core import (ERROR, Finding, lint_paths,
                                suppression_audit)
from jimm_tpu.lint.rules_ast import DEFAULT_VMEM_BUDGET

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="python -m jimm_tpu.lint",
        description="TPU-correctness static analyzer for jimm_tpu "
                    "(AST rules JL0xx; --concurrency adds whole-program "
                    "lock-discipline checks; --jaxpr adds trace-level "
                    "JLT104-106; --trace adds lowered-HLO checks JLT1xx)")
    parser.add_argument("paths", nargs="*", default=["jimm_tpu", "tests"],
                        help="files or directories to lint "
                             "(default: jimm_tpu tests)")
    parser.add_argument("--concurrency", action="store_true",
                        help="build the project-wide call/flow graph and run "
                             "the lock-discipline race detector (JL017-019) "
                             "plus interprocedural escalations of "
                             "JL006/JL008/JL013 and JL014 inheritance "
                             "waivers")
    parser.add_argument("--jaxpr", action="store_true",
                        help="abstractly trace registered entry points (no "
                             "compile) and check jaxpr invariants: f32 "
                             "promotion drift, baked host constants, "
                             "collective count drift vs goldens "
                             "(JLT104-106; imports JAX, a few seconds)")
    parser.add_argument("--trace", action="store_true",
                        help="also lower registered model entry points on "
                             "tiny shapes and check donation aliasing, FSDP "
                             "gather behavior, and batch-bucket stability "
                             "(imports JAX, takes ~a minute)")
    parser.add_argument("--update-goldens", action="store_true",
                        help="with --jaxpr: re-trace entry points and "
                             "rewrite jaxpr_goldens.json instead of "
                             "checking against it")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON array on stdout")
    parser.add_argument("--sarif", metavar="OUT",
                        help="also write findings as a SARIF 2.1.0 log to "
                             "OUT (for code-scanning upload)")
    parser.add_argument("--suppressions", action="store_true",
                        help="print an audit table of every `# jaxlint: "
                             "disable=` directive (path, line, rules, "
                             "justification) and exit 0")
    parser.add_argument("--vmem-budget", type=int,
                        default=DEFAULT_VMEM_BUDGET, metavar="BYTES",
                        help="VMEM budget for the JL005 block-size estimate "
                             f"(default {DEFAULT_VMEM_BUDGET})")
    return parser


def to_sarif(findings: list[Finding]) -> dict:
    """Render findings as a minimal SARIF 2.1.0 log (one run, one result
    per finding; trace/jaxpr pseudo-paths pass through as URIs)."""
    rules_seen: dict[str, dict] = {}
    results = []
    for f in findings:
        rules_seen.setdefault(f.rule, {"id": f.rule})
        results.append({
            "ruleId": f.rule,
            "level": "error" if f.severity == ERROR else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path},
                    "region": {"startLine": max(f.line, 1)},
                }
            }],
        })
    return {
        "version": SARIF_VERSION,
        "$schema": SARIF_SCHEMA,
        "runs": [{
            "tool": {"driver": {
                "name": "jaxlint",
                "informationUri":
                    "docs/static_analysis.md",
                "rules": sorted(rules_seen.values(),
                                key=lambda r: r["id"]),
            }},
            "results": results,
        }],
    }


def _print_suppression_audit(paths: list[str]) -> None:
    rows = suppression_audit(paths)
    if not rows:
        print("no suppression directives found")
        return
    widths = (max(len(r[0]) for r in rows),
              max(len(str(r[1])) for r in rows),
              max(len(r[2]) for r in rows))
    for path, line, rules, justification in rows:
        print(f"{path:<{widths[0]}}  {line:>{widths[1]}}  "
              f"{rules:<{widths[2]}}  "
              f"{justification or '(no justification -- JL020)'}")
    bare = sum(1 for r in rows if not r[3])
    print(f"{len(rows)} directive(s), {bare} without justification")


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.suppressions:
        _print_suppression_audit(args.paths)
        return 0
    if args.update_goldens:
        from jimm_tpu.lint.jaxpr import GOLDENS_PATH, update_goldens
        written = update_goldens()
        print(f"wrote {len(written)} entry golden(s) to {GOLDENS_PATH}")
        return 0

    findings: list[Finding] = lint_paths(args.paths,
                                         vmem_budget=args.vmem_budget)
    if args.concurrency:
        from jimm_tpu.lint.concurrency import (apply_jl014_waivers,
                                               run_concurrency_checks)
        from jimm_tpu.lint.core import collect_files
        from jimm_tpu.lint.graph import ProjectGraph
        files = collect_files(args.paths)
        graph = ProjectGraph.build(files)
        extra = run_concurrency_checks(files, graph=graph)
        seen = {(f.rule, f.path, f.line) for f in findings}
        findings.extend(f for f in extra
                        if (f.rule, f.path, f.line) not in seen)
        findings = apply_jl014_waivers(findings, graph)
    if args.jaxpr:
        from jimm_tpu.lint.jaxpr import run_jaxpr_checks
        findings.extend(run_jaxpr_checks())
    if args.trace:
        from jimm_tpu.lint.trace import run_trace_checks
        findings.extend(run_trace_checks())

    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    if args.sarif:
        with open(args.sarif, "w") as fh:
            json.dump(to_sarif(findings), fh, indent=2)
    if args.as_json:
        print(json.dumps([f.to_dict() for f in findings], indent=2))
    else:
        for f in findings:
            print(f.render())
        errors = sum(f.severity == ERROR for f in findings)
        warnings = len(findings) - errors
        print(f"{errors} error(s), {warnings} warning(s)")
    return 1 if any(f.severity == ERROR for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
