"""Extended remat save-lists (+ln/+act/+attn), the saveable-probs attention
impl, and the bf16-moment optimizer option.

These are pure runtime (execution-strategy) knobs: every variant must produce
the same loss as the baseline "dots" policy, because none of them changes
the math — only what the backward keeps vs recomputes.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from jimm_tpu import SigLIP
from jimm_tpu.configs import (SigLIPConfig, TextConfig, VisionConfig,
                              with_runtime)
from jimm_tpu.ops.attention import reference_attention, saveable_attention
from jimm_tpu.train import (OptimizerConfig, make_contrastive_train_step,
                            make_optimizer)


def tiny_cfg(**runtime):
    cfg = SigLIPConfig(
        vision=VisionConfig(image_size=32, patch_size=16, width=64, depth=2,
                            num_heads=2, mlp_dim=96, act="gelu_tanh",
                            pooling="map"),
        text=TextConfig(vocab_size=64, context_length=8, width=64, depth=2,
                        num_heads=2, mlp_dim=96, act="gelu_tanh", causal=False,
                        pooling="last", proj_bias=True),
        projection_dim=64)
    return with_runtime(cfg, **runtime) if runtime else cfg


def test_saveable_attention_matches_reference():
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(2, 6, 2, 8), jnp.float32)
               for _ in range(3))
    out = saveable_attention(q, k, v)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
    # causal too
    out_c = saveable_attention(q, k, v, is_causal=True)
    ref_c = reference_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(ref_c), atol=1e-5)


def _one_step_loss(policy: str, attn: str = "auto") -> float:
    cfg = tiny_cfg(remat=True, remat_policy=policy, attn_impl=attn)
    model = SigLIP(cfg, rngs=nnx.Rngs(0))
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-3))
    step = make_contrastive_train_step("siglip")
    rng = np.random.RandomState(0)
    images = jnp.asarray(rng.randn(4, 32, 32, 3), jnp.float32)
    text = jnp.asarray(rng.randint(1, 64, size=(4, 8)), jnp.int32)
    m = step(model, opt, images, text)
    m = step(model, opt, images, text)  # second step sees updated params
    return float(m["loss"])


@pytest.mark.parametrize("policy,attn", [
    ("dots+ln", "auto"),
    ("dots+act", "auto"),
    ("dots+ln+act", "auto"),
    ("dots+attn", "saveable"),
    ("dots+ln+act+attn", "saveable"),
])
def test_extended_policies_match_dots(policy, attn):
    base = _one_step_loss("dots")
    got = _one_step_loss(policy, attn)
    # identical math, different save-lists: losses agree to fp tolerance
    assert abs(got - base) < 5e-4, (policy, got, base)


def test_unknown_policy_rejected():
    cfg = tiny_cfg(remat=True, remat_policy="dots+bogus")
    model = SigLIP(cfg, rngs=nnx.Rngs(0))
    with pytest.raises(ValueError, match="remat_policy"):
        model(jnp.ones((1, 32, 32, 3)), jnp.ones((1, 8), jnp.int32))


def test_attn_save_requires_saveable_impl():
    # "+attn" with an impl that never emits attn_probs would silently
    # measure plain "dots"; it must refuse instead
    cfg = tiny_cfg(remat=True, remat_policy="dots+attn", attn_impl="auto")
    model = SigLIP(cfg, rngs=nnx.Rngs(0))
    with pytest.raises(ValueError, match="saveable"):
        model(jnp.ones((1, 32, 32, 3)), jnp.ones((1, 8), jnp.int32))


def test_parse_remat():
    from jimm_tpu.configs import parse_remat
    assert parse_remat("none") == {"remat": False, "remat_policy": "none"}
    assert parse_remat("full") == {"remat": True, "remat_policy": "none"}
    assert parse_remat("dots+ln+act") == {"remat": True,
                                          "remat_policy": "dots+ln+act"}
    with pytest.raises(ValueError):
        parse_remat("dot")  # typo must fail at parse time, not in jit trace


def test_bf16_moment_dtype():
    cfg = tiny_cfg()
    model = SigLIP(cfg, rngs=nnx.Rngs(0))
    opt = make_optimizer(model, OptimizerConfig(moment_dtype="bfloat16"))
    leaves = jax.tree.leaves(nnx.state(opt))
    assert any(getattr(l, "dtype", None) == jnp.bfloat16 for l in leaves), \
        "no bf16 moment buffers found in optimizer state"
    # and the step still runs
    step = make_contrastive_train_step("siglip")
    m = step(model, opt, jnp.ones((2, 32, 32, 3)),
             jnp.ones((2, 8), jnp.int32))
    assert np.isfinite(float(m["loss"]))
