"""Goodput-driven elastic adaptation (ISSUE 12): the mesh replanner, the
bounded GoodputAdvisor, checkpoint mesh-layout metadata, and the serving
engine's revive / live-replan / self-heal paths.

The advisor and replanner are host-only (no jax); the engine tests use
plain-callable forwards, so nothing here compiles a model — the end-to-end
drills live in tests/test_failure_recovery.py and scripts/elastic_smoke.py.
"""

import asyncio

import numpy as np
import pytest

from jimm_tpu.resilience import GoodputAdvisor, plan_data_axis
from jimm_tpu.resilience.elastic import KNOB_BOUNDS


# ---------------------------------------------------------------------------
# plan_data_axis
# ---------------------------------------------------------------------------

class TestPlanDataAxis:
    @pytest.mark.parametrize("n_devices,batch,expected", [
        (8, 8, 8),      # full width
        (4, 8, 4),      # shrink: half the devices still divide the batch
        (8, 4, 4),      # batch-bound: never wider than the batch
        (6, 8, 4),      # 6 doesn't divide 8 -> largest divisor below
        (3, 8, 2),
        (1, 8, 1),      # single survivor: degenerate but runnable
        (5, 7, 1),      # coprime: falls all the way to 1
    ])
    def test_widest_dividing_axis(self, n_devices, batch, expected):
        assert plan_data_axis(n_devices, batch) == expected

    def test_rejects_nonpositive(self):
        with pytest.raises(ValueError):
            plan_data_axis(0, 8)
        with pytest.raises(ValueError):
            plan_data_axis(4, 0)


# ---------------------------------------------------------------------------
# GoodputAdvisor
# ---------------------------------------------------------------------------

def _advisor(**kw):
    lines = []
    kw.setdefault("knobs", {"save_every": 8, "grace_steps": 1,
                            "scan_unroll": 4})
    adv = GoodputAdvisor(emit=lines.append, **kw)
    return adv, lines


class TestGoodputAdvisor:
    def test_healthy_window_makes_no_decision(self):
        adv, lines = _advisor(window=2, cooldown=0)
        for i in range(4):
            d = adv.observe(i, 10.0, {"step": 9.0, "checkpoint": 0.2})
            assert d is None
        assert adv.decisions == [] and lines == []
        assert adv.knobs["save_every"] == 8

    def test_high_lost_work_halves_save_every(self):
        adv, lines = _advisor(window=2, cooldown=0)
        d = adv.observe(0, 10.0, {"lost_work": 3.0, "step": 6.0})
        assert d is not None and d["knob"] == "save_every"
        assert d["from"] == 8 and d["to"] == 4
        assert adv.knobs["save_every"] == 4
        assert len(lines) == 1 and "goodput_advisor_decision" in lines[0]

    def test_save_every_floor_escalates_to_grace_steps(self):
        adv, _ = _advisor(window=1, cooldown=0,
                          knobs={"save_every": 1, "grace_steps": 1})
        d = adv.observe(0, 10.0, {"lost_work": 3.0})
        assert d["knob"] == "grace_steps" and d["to"] == 2

    def test_cooldown_suppresses_back_to_back_decisions(self):
        adv, _ = _advisor(window=1, cooldown=1)
        assert adv.observe(0, 10.0, {"lost_work": 3.0}) is not None
        # next observation is still bad but falls inside the cooldown
        assert adv.observe(1, 10.0, {"lost_work": 3.0}) is None
        assert adv.observe(2, 10.0, {"lost_work": 3.0}) is not None

    def test_checkpoint_relax_respects_dead_band(self):
        adv, _ = _advisor(window=1, cooldown=0,
                          lost_work_high=0.08, checkpoint_high=0.25)
        # checkpoint heavy but lost_work INSIDE the dead band
        # (>= lost_work_high / 2): neither rule may fire, so the two
        # cadence rules can never ping-pong
        d = adv.observe(0, 10.0, {"checkpoint": 4.0, "lost_work": 0.5})
        assert d is None
        # comfortably low lost work -> relax the cadence
        d = adv.observe(1, 10.0, {"checkpoint": 4.0, "lost_work": 0.0})
        assert d is not None and d["knob"] == "save_every" and d["to"] == 16

    def test_compile_dominating_pins_scan_unroll(self):
        adv, _ = _advisor(window=2, cooldown=0)
        assert adv.observe(0, 10.0, {"compile": 6.0}) is None, \
            "one attempt is not a trend"
        d = adv.observe(1, 10.0, {"compile": 6.0})
        assert d["knob"] == "scan_unroll" and d["to"] == 1

    def test_every_knob_stays_inside_bounds(self):
        adv, _ = _advisor(window=1, cooldown=0,
                          knobs={"save_every": 2, "grace_steps": 7})
        for i in range(40):
            adv.observe(i, 10.0, {"lost_work": 5.0})
        lo, hi = KNOB_BOUNDS["save_every"]
        assert lo <= adv.knobs["save_every"] <= hi
        lo, hi = KNOB_BOUNDS["grace_steps"]
        assert lo <= adv.knobs["grace_steps"] <= hi
        # once every reachable knob is at its clamp the advisor goes quiet
        # instead of emitting no-op decisions
        assert adv.knobs["grace_steps"] == hi
        tail = adv.observe(99, 10.0, {"lost_work": 5.0})
        assert tail is None

    def test_decisions_are_counted_in_registry(self):
        from jimm_tpu.obs import get_registry
        reg = get_registry("jimm_train")
        before = reg.snapshot().get("goodput_advisor_decisions_total", 0)
        adv = GoodputAdvisor(window=1, cooldown=0, emit=lambda _: None,
                             knobs={"save_every": 8})
        adv.observe(0, 10.0, {"lost_work": 3.0})
        after = reg.snapshot().get("goodput_advisor_decisions_total", 0)
        assert after == before + 1

    def test_argv_overrides_spell_train_flags(self):
        adv, _ = _advisor()
        flags = adv.argv_overrides()
        assert flags[flags.index("--save-every") + 1] == "8"
        assert flags[flags.index("--grace-steps") + 1] == "1"
        assert flags[flags.index("--scan-unroll") + 1] == "4"


# ---------------------------------------------------------------------------
# checkpoint mesh-layout metadata
# ---------------------------------------------------------------------------

class TestMeshLayout:
    def test_layout_fingerprint(self, eight_devices):
        import jax

        from jimm_tpu.parallel.mesh import make_mesh
        from jimm_tpu.train.checkpoint import _mesh_layout
        assert _mesh_layout(None) is None
        mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
        assert _mesh_layout(mesh) == {"axes": {"data": 4}, "n_devices": 4}

    def test_note_mesh_change_counts_and_records(self, tmp_path,
                                                 eight_devices):
        import jax

        from jimm_tpu import obs
        from jimm_tpu.parallel.mesh import make_mesh
        from jimm_tpu.train.checkpoint import CheckpointManager
        mesh = make_mesh({"data": 4}, devices=jax.devices()[:4])
        mgr = CheckpointManager(tmp_path / "ckpt", mesh=mesh)
        before = obs.snapshot().get(
            "jimm_train_checkpoint_topology_changes_total", 0)
        # same shape: not a topology change
        mgr._note_mesh_change(3, {"axes": {"data": 4}, "n_devices": 4})
        assert mgr.last_topology_change is None
        # different shape: recorded + counted
        mgr._note_mesh_change(3, {"axes": {"data": 8}, "n_devices": 8})
        assert mgr.last_topology_change["step"] == 3
        assert mgr.last_topology_change["saved"]["n_devices"] == 8
        after = obs.snapshot().get(
            "jimm_train_checkpoint_topology_changes_total", 0)
        assert after == before + 1


# ---------------------------------------------------------------------------
# engine: revive / replan / self-heal
# ---------------------------------------------------------------------------

def _engine(forwards, **kw):
    from jimm_tpu.serve import BucketTable, InferenceEngine
    kw.setdefault("item_shape", (3,))
    kw.setdefault("buckets", BucketTable((1, 2)))
    kw.setdefault("max_delay_ms", 1.0)
    return InferenceEngine(forwards, **kw)


def _ok(x):
    return np.asarray(x) * 2


class _Raiser:
    def __call__(self, x):
        raise RuntimeError("device lost")


async def _fence_replica(engine, index=1, tries=30):
    """Drive traffic until the watchdog fences ``index`` (or a replan
    already healed it)."""
    for _ in range(tries):
        try:
            await engine.submit(np.ones(3, np.float32))
        except RuntimeError:
            pass
        if index in engine.dead_replicas():
            return
        if engine.metrics.count("replans_total") > 0:
            return
        await asyncio.sleep(0.01)


class TestReviveHook:
    def test_revive_unfences_and_rearms(self):
        engine = _engine([_ok, _ok])

        async def go():
            await engine.start()
            try:
                engine._replicas[1].forward = _Raiser()
                await _fence_replica(engine)
                assert engine.dead_replicas() == [1]
                engine._replicas[1].forward = _ok  # lane repaired
                row = engine.revive(1)
                assert row["dead"] is False and row["revived"] == 1
                assert row["restarts"] == 0, "restart budget re-armed"
                assert engine.dead_replicas() == []
                assert engine.metrics.count("revives_total") == 1
                assert engine.metrics.count("replica_1_revived_total") == 1
                for _ in range(4):
                    out = await engine.submit(np.ones(3, np.float32))
                    np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_revive_rejects_bad_targets(self):
        engine = _engine([_ok, _ok])
        with pytest.raises(ValueError, match="no replica 7"):
            engine.revive(7)
        with pytest.raises(ValueError, match="not fenced"):
            engine.revive(0)

    def test_server_revive_route_and_healthz(self):
        from jimm_tpu.serve import ServingServer
        from jimm_tpu.serve.admission import RequestError
        engine = _engine([_ok, _ok])
        engine._replicas[1].dead = True
        server = ServingServer(engine, warmup=False)
        out = server.healthz()
        assert out["status"] == "degraded"
        assert out["replans"] == 0
        assert out["replicas"][1]["revived"] == 0
        res = server.revive({"replica": 1})
        assert res["revived"] == 1 and res["dead_replicas"] == []
        out = server.healthz()
        assert out["status"] == "ok"
        assert out["replicas"][1]["revived"] == 1
        with pytest.raises(RequestError):
            server.revive({"replica": "one"})
        with pytest.raises(RequestError):
            server.revive({"replica": 5})


class TestReplan:
    def test_replan_grows_and_shrinks_live(self):
        engine = _engine([_ok, _ok])

        async def go():
            await engine.start()
            try:
                out = await engine.submit(np.ones(3, np.float32))
                np.testing.assert_allclose(np.asarray(out), 2.0)
                # grow 2 -> 3
                info = await engine.replan([_ok, _ok, _ok])
                assert info["replicas"] == 3 and info["was_running"]
                assert engine.n_replicas == 3
                for _ in range(6):
                    out = await engine.submit(np.ones(3, np.float32))
                    np.testing.assert_allclose(np.asarray(out), 2.0)
                # shrink 3 -> 2; ghost replica 2 gauges freeze at zero
                await engine.replan([_ok, _ok])
                assert engine.n_replicas == 2
                snap = engine.metrics.snapshot()
                assert snap["replica_2_inflight"] == 0.0
                assert engine.metrics.count("replans_total") == 2
                out = await engine.submit(np.ones(3, np.float32))
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_replan_keeps_queued_requests(self):
        engine = _engine([_ok])

        async def go():
            await engine.start()
            try:
                # enqueue while the replan swap is in flight: submissions
                # must keep being accepted and answered by the new replicas
                submits = [asyncio.ensure_future(
                    engine.submit(np.ones(3, np.float32)))
                    for _ in range(8)]
                await engine.replan([_ok, _ok])
                outs = await asyncio.gather(*submits)
                for out in outs:
                    np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_replan_warms_prepare_bucket_forwards(self):
        calls = []

        class StoreBacked:
            def prepare_bucket(self, bucket):
                calls.append(bucket)
                return "aot"

            def __call__(self, x):
                return np.asarray(x) * 2

        engine = _engine([_ok, _ok])

        async def go():
            await engine.start()
            try:
                await engine.replan([StoreBacked(), StoreBacked()])
                # every bucket of every new forward prepared BEFORE the
                # swap — an unprepared bucket would fall back to a fresh
                # trace on first traffic
                assert sorted(calls) == [1, 1, 2, 2]
                out = await engine.submit(np.ones(3, np.float32))
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())


class TestSelfHeal:
    def test_fence_escalates_to_replan_around(self):
        heal_calls = []

        def heal():
            heal_calls.append(1)
            return [_ok, _ok], lambda: 0

        engine = _engine([_ok, _Raiser()])
        engine.set_heal(heal)

        async def go():
            await engine.start()
            try:
                await _fence_replica(engine)
                for _ in range(100):
                    if engine.metrics.count("replans_total") >= 1:
                        break
                    await asyncio.sleep(0.01)
                assert heal_calls == [1]
                assert engine.metrics.count("replans_total") == 1
                assert engine.dead_replicas() == []
                assert engine.n_replicas == 2
                for _ in range(6):
                    out = await engine.submit(np.ones(3, np.float32))
                    np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_transient_fault_revives_in_place(self):
        heal_calls = []
        flaky = {"fails": 0}

        def sometimes(x):
            # fails exactly twice (restart, then fence), then recovers —
            # the heal probe finds a working lane and revives it without
            # a rebuild
            if flaky["fails"] < 2:
                flaky["fails"] += 1
                raise RuntimeError("transient")
            return np.asarray(x) * 2

        def heal():
            heal_calls.append(1)
            return [_ok, _ok], None

        engine = _engine([_ok, sometimes])
        engine.set_heal(heal)

        async def go():
            await engine.start()
            try:
                await _fence_replica(engine)
                for _ in range(100):
                    if engine.metrics.count("revives_total") >= 1:
                        break
                    await asyncio.sleep(0.01)
                assert engine.metrics.count("revives_total") == 1
                assert heal_calls == [], \
                    "a lane that probes healthy must not trigger a rebuild"
                assert engine.dead_replicas() == []
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_failed_heal_is_counted_not_fatal(self):
        def heal():
            raise OSError("store unreachable")

        engine = _engine([_ok, _Raiser()])
        engine.set_heal(heal)

        async def go():
            await engine.start()
            try:
                await _fence_replica(engine)
                for _ in range(100):
                    if engine.metrics.count("heal_failures_total") >= 1:
                        break
                    await asyncio.sleep(0.01)
                assert engine.metrics.count("heal_failures_total") == 1
                assert "store unreachable" in engine.last_heal_error
                # degraded but serving: the live lane still answers
                out = await engine.submit(np.ones(3, np.float32))
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())
