"""CI tier-1 smoke for the unified observability stack.

Runs a 3-step CPU training through the real CLI entry point, then serves one
request through a real `InferenceEngine`, all in ONE process — and asserts
the invariant the obs hub exists to provide: a single unified dump carrying
``jimm_train_*`` AND ``jimm_serve_*`` series, in valid Prometheus text form,
with no duplicate registrations. Exits nonzero (with a JSON error line) on
any violation, so the CI step fails loudly.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.obs_smoke
"""

from __future__ import annotations

import json
import sys


def fail(msg: str) -> int:
    print(json.dumps({"metric": "obs_smoke", "value": 0.0, "error": msg}),
          flush=True)
    return 1


def main() -> int:
    import numpy as np

    from jimm_tpu import cli, obs

    # --- train: 3 synthetic steps through the shipped CLI ----------------
    rc = cli.main(["train", "--preset", "vit-tiny-patch16-224", "--tiny",
                   "--steps", "3", "--batch-size", "8"])
    if rc:
        return fail(f"cli train exited {rc}")

    # --- serve: one request through a real engine -------------------------
    import asyncio

    from jimm_tpu.serve import BucketTable, InferenceEngine

    def forward(batch):
        return batch.reshape(batch.shape[0], -1)[:, :4]

    engine = InferenceEngine(forward, item_shape=(8, 8, 3),
                             buckets=BucketTable((1, 2)), max_delay_ms=2.0)

    async def one_request():
        await engine.start()
        try:
            await engine.submit(np.zeros((8, 8, 3), np.float32))
        finally:
            await engine.stop()

    asyncio.run(one_request())

    # --- the unified dump invariants --------------------------------------
    text = obs.render_prometheus()
    series = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        if name in series:
            return fail(f"duplicate series in unified dump: {name}")
        series[name] = float(value)  # also validates the value renders

    train = sorted(k for k in series if k.startswith("jimm_train_"))
    serve = sorted(k for k in series if k.startswith("jimm_serve_"))
    if not train:
        return fail("no jimm_train_* series after a 3-step train")
    if not serve:
        return fail("no jimm_serve_* series after a serve request")
    for required in ("jimm_train_steps_logged_total",
                     "jimm_serve_responses_total",
                     "jimm_train_goodput_ratio"):
        if required not in series:
            return fail(f"missing required series {required}")
    if series["jimm_serve_responses_total"] < 1:
        return fail("serve request not counted")

    # per-request span decomposition reached the serve registry
    for phase in ("queue", "pad", "device", "readback"):
        if f"jimm_serve_span_{phase}_seconds_count" not in series:
            return fail(f"serve span phase {phase!r} never observed")

    print(json.dumps({"metric": "obs_smoke", "value": 1.0,
                      "train_series": len(train),
                      "serve_series": len(serve),
                      "total_series": len(series)}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
