"""Test harness: force an 8-device virtual CPU platform so sharding,
FSDP/TP, ring-loss, and distributed tests run without a TPU pod
(SURVEY §4 "Implication for the build").

Must run before jax initializes a backend — pytest imports conftest first.
"""

import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # JAX < 0.5 has no jax_num_cpu_devices config key; the XLA_FLAGS
    # fallback set above already forces 8 virtual host devices.
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402

# the package namespace is lazy (PEP 562) and only loads the flax compat
# backfills when a model/config attribute resolves; tests use nnx directly
# (Variable.set_value, to_flat_state, ...) so load them up front
import jimm_tpu.utils.compat  # noqa: E402,F401


@pytest.fixture(autouse=True)
def _tune_cache_in_tmp(tmp_path, monkeypatch):
    """Point the kernel-tune cache at a per-test tmp dir: ops resolve block
    sizes through jimm_tpu.tune.best_config, which would otherwise mkdir
    (and persist configs under) ~/.cache/jimm_tpu/tune during the suite.
    Also reset the process-wide cache handle so the env var is re-read."""
    monkeypatch.setenv("JIMM_TUNE_CACHE", str(tmp_path / "tune-cache"))
    monkeypatch.delenv("JIMM_TUNE", raising=False)
    import sys
    api = sys.modules.get("jimm_tpu.tune.api")
    if api is not None:
        api._cache = None
    yield
    api = sys.modules.get("jimm_tpu.tune.api")
    if api is not None:
        api._cache = None


@pytest.fixture(scope="session")
def rng() -> np.random.RandomState:
    return np.random.RandomState(0)


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs


@pytest.fixture(scope="session")
def clip_vocab_dir(tmp_path_factory):
    """Synthetic CLIP vocab/merges in the real layout (byte alphabet, </w>
    variants, merged tokens, specials last) — shared by the tokenizer
    parity suites."""
    import json

    from jimm_tpu.data.clip_tokenizer import bytes_to_unicode
    d = tmp_path_factory.mktemp("clip_vocab")
    alphabet = list(bytes_to_unicode().values())
    merges = [("t", "h"), ("th", "e</w>"), ("c", "a"), ("ca", "t</w>"),
              ("p", "h"), ("ph", "o"), ("o", "f</w>"), ("4", "2</w>"),
              ("i", "n"), ("a", "n"), ("an", "d</w>"), ("e", "r</w>")]
    vocab_tokens = (alphabet + [ch + "</w>" for ch in alphabet]
                    + ["".join(m) for m in merges]
                    + ["<|startoftext|>", "<|endoftext|>"])
    (d / "vocab.json").write_text(
        json.dumps({tok: i for i, tok in enumerate(vocab_tokens)}),
        encoding="utf-8")
    (d / "merges.txt").write_text(
        "#version: 0.2\n" + "\n".join(f"{a} {b}" for a, b in merges) + "\n",
        encoding="utf-8")
    return d
