"""Tenant identity and QoS policy: classes, rate limits, quotas.

Stdlib-only (``json`` + ``tomllib``) so the ``jimm-tpu qos`` CLI and any
front-end proxy can load and validate a policy without the accelerator
stack. A policy file (JSON or TOML) looks like::

    {
      "classes": {"interactive": {"weight": 8},
                  "batch":       {"weight": 2},
                  "background":  {"weight": 1}},
      "tenants": {
        "alice": {"class": "interactive", "rate": 200, "burst": 400,
                  "timeout_s": 2.0, "max_queued": 64},
        "bob":   {"class": "batch", "rate": 50}
      },
      "default": {"class": "interactive"},
      "slo": {
        "alice":   {"availability": 0.999, "latency_ms": 250},
        "default": {"availability": 0.99}
      }
    }

Class **priority is declaration order** (first listed = highest = shed
last); ``weight`` sets the weighted-fair dequeue share, so priority (who
is shed first) and share (who drains faster) are independent knobs.
Requests carrying no tenant id — or an id the policy doesn't name — map
to the **default tenant**: one shared spec and one shared runtime state,
so an adversary inventing tenant names cannot grow any per-tenant table
(the bounded-cardinality discipline lint rule JL014 enforces across
``serve/``).

The optional ``slo`` section declares per-tenant service-level
objectives (availability as a success-rate fraction, optional latency
target in ms). Names must be declared tenants or ``default``; the serve
CLI feeds the parsed objectives into the burn-rate engine
(:class:`jimm_tpu.obs.slo.SloEngine`).
"""

from __future__ import annotations

import dataclasses
import json
import re

__all__ = ["ClassSpec", "DEFAULT_CLASSES", "QosPolicyError", "TenantRegistry",
           "TenantSpec", "load_policy"]

#: shipped class ladder: (name, weight) in priority order. A policy file
#: may re-weight, drop, or extend these; declaration order stays the
#: priority order either way.
DEFAULT_CLASSES: tuple[tuple[str, float], ...] = (
    ("interactive", 8.0), ("batch", 2.0), ("background", 1.0))

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_.-]*$")


class QosPolicyError(ValueError):
    """Malformed QoS policy (bad file, unknown class, non-positive rate)."""


@dataclasses.dataclass(frozen=True)
class ClassSpec:
    """One priority class: ``rank`` 0 is highest priority (shed last),
    ``weight`` is its deficit-round-robin dequeue share."""

    name: str
    weight: float
    rank: int


@dataclasses.dataclass(frozen=True)
class TenantSpec:
    """One tenant's policy: class membership, token-bucket rate limit
    (``rate`` requests/s refill, ``burst`` bucket depth), an optional
    per-tenant default deadline (inherited by requests that carry none),
    and a ``max_queued`` quota bounding this tenant's share of the
    admission queue."""

    name: str
    klass: str = "interactive"
    rate: float | None = None
    burst: float | None = None
    timeout_s: float | None = None
    max_queued: int | None = None


def _check_name(kind: str, name: str, problems: list[str]) -> None:
    if not isinstance(name, str) or not _NAME_RE.match(name):
        problems.append(f"{kind} name {name!r} is not a valid identifier "
                        "([A-Za-z_][A-Za-z0-9_.-]*)")


def _parse_classes(raw, problems: list[str]) -> dict[str, ClassSpec]:
    if raw is None:
        raw = {name: {"weight": weight} for name, weight in DEFAULT_CLASSES}
    if not isinstance(raw, dict) or not raw:
        problems.append("'classes' must be a non-empty mapping")
        return {}
    classes: dict[str, ClassSpec] = {}
    for rank, (name, spec) in enumerate(raw.items()):
        _check_name("class", name, problems)
        if not isinstance(spec, dict):
            spec = {"weight": spec}
        weight = spec.get("weight", 1.0)
        if not isinstance(weight, (int, float)) or weight <= 0:
            problems.append(f"class {name!r}: weight must be > 0, "
                            f"got {weight!r}")
            weight = 1.0
        classes[str(name)] = ClassSpec(str(name), float(weight), rank)
    return classes


def _parse_tenant(name: str, spec, classes: dict[str, ClassSpec],
                  problems: list[str]) -> TenantSpec:
    if not isinstance(spec, dict):
        problems.append(f"tenant {name!r}: spec must be a mapping")
        spec = {}
    klass = spec.get("class", spec.get("klass"))
    if klass is None:
        klass = next(iter(classes), "interactive")
    if klass not in classes:
        problems.append(f"tenant {name!r}: unknown class {klass!r} "
                        f"(declared: {sorted(classes)})")
    rate = spec.get("rate")
    if rate is not None and (not isinstance(rate, (int, float)) or rate <= 0):
        problems.append(f"tenant {name!r}: rate must be > 0, got {rate!r}")
        rate = None
    burst = spec.get("burst")
    if burst is not None and (not isinstance(burst, (int, float))
                              or burst < 1):
        problems.append(f"tenant {name!r}: burst must be >= 1, got {burst!r}")
        burst = None
    timeout_s = spec.get("timeout_s")
    if timeout_s is not None and (not isinstance(timeout_s, (int, float))
                                  or timeout_s <= 0):
        problems.append(f"tenant {name!r}: timeout_s must be > 0, "
                        f"got {timeout_s!r}")
        timeout_s = None
    max_queued = spec.get("max_queued")
    if max_queued is not None and (not isinstance(max_queued, int)
                                   or max_queued < 1):
        problems.append(f"tenant {name!r}: max_queued must be an int >= 1, "
                        f"got {max_queued!r}")
        max_queued = None
    unknown = set(spec) - {"class", "klass", "rate", "burst", "timeout_s",
                           "max_queued"}
    if unknown:
        problems.append(f"tenant {name!r}: unknown keys {sorted(unknown)}")
    return TenantSpec(name=str(name), klass=str(klass),
                      rate=None if rate is None else float(rate),
                      burst=None if burst is None else float(burst),
                      timeout_s=(None if timeout_s is None
                                 else float(timeout_s)),
                      max_queued=max_queued)


def _parse_slo(raw, tenants: dict[str, TenantSpec],
               problems: list[str]) -> dict[str, dict]:
    """Validate the optional ``slo`` section into plain objective dicts
    keyed by tenant name (``SloEngine.from_objective_dicts`` consumes
    them). Names must be declared tenants or ``default`` — an SLO for a
    tenant the policy never admits would silently track nothing."""
    if raw is None:
        return {}
    if not isinstance(raw, dict):
        problems.append("'slo' must be a mapping of tenant -> objective")
        return {}
    slo: dict[str, dict] = {}
    for name, spec in raw.items():
        if name != TenantRegistry.DEFAULT_TENANT and name not in tenants:
            problems.append(f"slo {name!r}: not a declared tenant "
                            f"(declared: {sorted(tenants)} + ['default'])")
            continue
        if not isinstance(spec, dict):
            problems.append(f"slo {name!r}: objective must be a mapping")
            continue
        unknown = set(spec) - {"availability", "latency_ms"}
        if unknown:
            problems.append(f"slo {name!r}: unknown keys {sorted(unknown)}")
            continue
        availability = spec.get("availability", 0.999)
        if (not isinstance(availability, (int, float))
                or not 0.0 < availability < 1.0):
            problems.append(f"slo {name!r}: availability must be in (0, 1), "
                            f"got {availability!r}")
            continue
        latency_ms = spec.get("latency_ms")
        if latency_ms is not None and (
                not isinstance(latency_ms, (int, float)) or latency_ms <= 0):
            problems.append(f"slo {name!r}: latency_ms must be > 0, "
                            f"got {latency_ms!r}")
            continue
        slo[str(name)] = {"availability": float(availability)}
        if latency_ms is not None:
            slo[str(name)]["latency_ms"] = float(latency_ms)
    return slo


def _parse_cascade(raw, problems: list[str]) -> dict:
    """Validate the optional ``cascade`` section: the stage ladder
    (``order``, cheapest model first) and the store fingerprints of each
    non-terminal stage's calibration — the policy file carries artifact
    *references*, never threshold values (JL021)."""
    if raw is None:
        return {}
    if not isinstance(raw, dict):
        problems.append("'cascade' must be a mapping")
        return {}
    unknown = set(raw) - {"order", "calibrations", "agreement_floor"}
    if unknown:
        problems.append(f"cascade: unknown keys {sorted(unknown)}")
        return {}
    order = raw.get("order")
    if (not isinstance(order, list) or len(order) < 2
            or not all(isinstance(n, str) and n for n in order)
            or len(set(order)) != len(order)):
        problems.append("cascade: 'order' must list >= 2 distinct model "
                        f"names cheapest-first, got {order!r}")
        return {}
    calibrations = raw.get("calibrations")
    if calibrations is None or not isinstance(calibrations, dict) or not all(
            isinstance(k, str) and isinstance(v, str) and v
            for k, v in calibrations.items()):
        problems.append("cascade: 'calibrations' must map stage name -> "
                        "store fingerprint")
        return {}
    missing = [n for n in order[:-1] if n not in calibrations]
    if missing:
        problems.append(f"cascade: stages {missing} have no calibration "
                        "fingerprint (only the terminal stage may accept "
                        "unconditionally)")
    stray = sorted(set(calibrations) - set(order[:-1]))
    if stray:
        problems.append(f"cascade: calibrations for {stray} name no "
                        "non-terminal stage in 'order'")
    out = {"order": [str(n) for n in order],
           "calibrations": {str(k): str(v)
                            for k, v in calibrations.items()}}
    floor = raw.get("agreement_floor")
    if floor is not None:
        if not isinstance(floor, (int, float)) or not 0.0 < floor <= 1.0:
            problems.append("cascade: agreement_floor must be in (0, 1], "
                            f"got {floor!r}")
        else:
            out["agreement_floor"] = float(floor)
    return out


def _parse_autoscale(raw, classes: dict, problems: list[str]) -> dict:
    """Validate the optional ``autoscale`` section (the
    :class:`~jimm_tpu.serve.cascade.autoscale.CascadeAutoscaler` knobs:
    trip points + hysteresis)."""
    if raw is None:
        return {}
    if not isinstance(raw, dict):
        problems.append("'autoscale' must be a mapping")
        return {}
    unknown = set(raw) - {"watch_class", "burn_high", "queue_high",
                          "window", "cooldown"}
    if unknown:
        problems.append(f"autoscale: unknown keys {sorted(unknown)}")
        return {}
    out: dict = {}
    watch = raw.get("watch_class")
    if watch is not None:
        if not isinstance(watch, str) or (classes and watch not in classes):
            problems.append(f"autoscale: watch_class {watch!r} is not a "
                            f"declared class ({sorted(classes)})")
        else:
            out["watch_class"] = watch
    for key in ("burn_high", "queue_high"):
        value = raw.get(key)
        if value is None:
            continue
        if not isinstance(value, (int, float)) or value <= 0:
            problems.append(f"autoscale: {key} must be > 0, got {value!r}")
        else:
            out[key] = float(value)
    for key, floor in (("window", 1), ("cooldown", 0)):
        value = raw.get(key)
        if value is None:
            continue
        if not isinstance(value, int) or isinstance(value, bool) \
                or value < floor:
            problems.append(f"autoscale: {key} must be an int >= {floor}, "
                            f"got {value!r}")
        else:
            out[key] = value
    return out


class TenantRegistry:
    """The parsed policy: priority classes, named tenants, and the shared
    default tenant that anonymous/unknown traffic maps to."""

    DEFAULT_TENANT = "default"

    def __init__(self, classes: dict[str, ClassSpec],
                 tenants: dict[str, TenantSpec], default: TenantSpec,
                 slo: dict[str, dict] | None = None,
                 cascade: dict | None = None,
                 autoscale: dict | None = None):
        self.classes = classes
        self.tenants = tenants
        self.default = default
        #: per-tenant SLO objective dicts from the policy's ``slo`` section
        #: (empty when the policy declares none)
        self.slo = dict(slo or {})
        #: cascade stage ladder + calibration fingerprints (``cascade``
        #: section; None when the policy declares none)
        self.cascade = dict(cascade) if cascade else None
        #: autoscaler trip points + hysteresis (``autoscale`` section)
        self.autoscale = dict(autoscale) if autoscale else None
        #: class names in priority order (rank 0 first) — the weighted-fair
        #: queue's drain order and the INVERSE of the shed order
        self.class_order = tuple(sorted(classes, key=lambda n:
                                        classes[n].rank))

    # -- construction -----------------------------------------------------

    @classmethod
    def from_dict(cls, data: dict) -> "TenantRegistry":
        if not isinstance(data, dict):
            raise QosPolicyError("policy must be a mapping")
        problems: list[str] = []
        unknown = set(data) - {"classes", "tenants", "default", "slo",
                               "cascade", "autoscale"}
        if unknown:
            problems.append(f"unknown top-level keys {sorted(unknown)}")
        classes = _parse_classes(data.get("classes"), problems)
        raw_tenants = data.get("tenants") or {}
        if not isinstance(raw_tenants, dict):
            problems.append("'tenants' must be a mapping")
            raw_tenants = {}
        tenants: dict[str, TenantSpec] = {}
        for name, spec in raw_tenants.items():
            _check_name("tenant", name, problems)
            tenants[str(name)] = _parse_tenant(str(name), spec, classes,
                                               problems)
        default = _parse_tenant(cls.DEFAULT_TENANT, data.get("default") or {},
                                classes, problems)
        slo = _parse_slo(data.get("slo"), tenants, problems)
        cascade = _parse_cascade(data.get("cascade"), problems)
        autoscale = _parse_autoscale(data.get("autoscale"), classes,
                                     problems)
        if problems:
            raise QosPolicyError("; ".join(problems))
        return cls(classes, tenants, default, slo, cascade, autoscale)

    @classmethod
    def load(cls, path: str) -> "TenantRegistry":
        """Parse a JSON (``.json``) or TOML (``.toml``) policy file."""
        if str(path).endswith(".toml"):
            try:
                import tomllib
            except ImportError as e:  # pragma: no cover — Python < 3.11
                raise QosPolicyError(
                    "TOML policy files need Python >= 3.11 (tomllib); "
                    "use JSON") from e
            try:
                with open(path, "rb") as f:
                    data = tomllib.load(f)
            except (OSError, tomllib.TOMLDecodeError) as e:
                raise QosPolicyError(f"cannot load {path}: {e}") from e
        else:
            try:
                with open(path, "r", encoding="utf-8") as f:
                    data = json.load(f)
            except (OSError, ValueError) as e:
                raise QosPolicyError(f"cannot load {path}: {e}") from e
        return cls.from_dict(data)

    # -- queries ----------------------------------------------------------

    def resolve_spec(self, tenant: str | None) -> TenantSpec:
        """The spec governing ``tenant``; anonymous (None) and unknown ids
        share the default spec, so tenant cardinality is bounded by this
        file, not by what clients send."""
        if tenant is None:
            return self.default
        return self.tenants.get(tenant, self.default)

    def rank_of(self, klass: str) -> int:
        return self.classes[klass].rank

    def describe(self) -> dict:
        """JSON-shaped summary (the ``qos ls`` CLI and healthz payload)."""
        out = {
            "classes": [{"name": c.name, "weight": c.weight, "rank": c.rank}
                        for c in sorted(self.classes.values(),
                                        key=lambda c: c.rank)],
            "tenants": [dataclasses.asdict(t) for t in
                        sorted(self.tenants.values(), key=lambda t: t.name)],
            "default": dataclasses.asdict(self.default),
        }
        if self.slo:
            out["slo"] = {name: dict(obj)
                          for name, obj in sorted(self.slo.items())}
        if self.cascade:
            out["cascade"] = dict(self.cascade)
        if self.autoscale:
            out["autoscale"] = dict(self.autoscale)
        return out


def load_policy(path: str) -> TenantRegistry:
    """Module-level alias for :meth:`TenantRegistry.load`."""
    return TenantRegistry.load(path)
