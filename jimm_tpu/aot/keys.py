"""Canonical cache keys for AOT compile artifacts.

An artifact is reusable only when *everything* that shaped the compiled
program matches: the model architecture (config), the entry method, the
padded batch bucket and item shape, the batch dtype the engine assembles,
the parameter dtype the weights live in, the mesh layout, the backend, the
jax/jaxlib pair that produced the StableHLO, and the donation signature.
One field drifting silently would hand a stale executable to a different
program — so all of them are folded into a single SHA-256 fingerprint over
a canonical JSON form (sorted keys, no whitespace, primitives only), which
is byte-stable across processes and platforms by construction
(``tests/test_aot.py`` pins a golden digest).

jax is imported lazily and only to *default* the version/backend fields;
passing them explicitly keeps this module usable from pure-host tooling
(``jimm-tpu aot ls``/``gc`` never touch a backend).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
from typing import Any, Mapping, Sequence

__all__ = ["AotKey", "canonical_json", "config_hash", "donation_signature",
           "serve_forward_key"]

#: bump when the artifact payload layout changes (meta schema, leaf
#: partitioning, serialization framing) — old entries then quarantine
#: instead of deserializing garbage
AOT_FORMAT_VERSION = 1


def _canonical(obj: Any) -> Any:
    """Reduce ``obj`` to JSON primitives, deterministically.

    Handles the types that appear in model configs and key fields:
    dataclasses, mappings (key-sorted), sequences, dtypes (by name), and
    scalars. Anything else falls back to ``repr`` — stable for the frozen
    config values this module sees.
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        return _canonical(dataclasses.asdict(obj))
    if isinstance(obj, Mapping):
        return {str(k): _canonical(obj[k]) for k in sorted(obj, key=str)}
    if isinstance(obj, (list, tuple)):
        return [_canonical(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    if hasattr(obj, "name") and hasattr(obj, "itemsize"):  # np/jnp dtype
        return str(obj.name)
    return repr(obj)


def canonical_json(obj: Any) -> str:
    """The one serialization fingerprints hash: sorted keys, tightest
    separators, no NaN laxness — identical bytes in every process."""
    return json.dumps(_canonical(obj), sort_keys=True,
                      separators=(",", ":"), allow_nan=False)


def config_hash(config: Any) -> str:
    """SHA-256 over the canonical JSON of a model config (dataclass or
    mapping) — the architecture half of the key. Weights are *not* hashed:
    artifacts hold the program, parameters ride in as call arguments, so
    every checkpoint of one architecture shares the same executables."""
    return hashlib.sha256(canonical_json(config).encode()).hexdigest()


def donation_signature(donate_argnums: Sequence[int] = (),
                       donate_argnames: Sequence[str] = ()) -> str:
    """Stable encoding of buffer-donation settings. Donation changes the
    compiled program's aliasing contract, so two jits differing only in
    ``donate_argnums`` must never share an artifact."""
    return canonical_json({"argnums": sorted(int(i) for i in donate_argnums),
                           "argnames": sorted(str(s)
                                              for s in donate_argnames)})


def _default_versions() -> tuple[str, str]:
    import jax
    import jaxlib
    return jax.__version__, jaxlib.__version__


def _default_backend() -> str:
    import jax
    return jax.default_backend()


@dataclasses.dataclass(frozen=True)
class AotKey:
    """Every field that must match for an artifact to be reusable."""

    config_hash: str
    method: str
    bucket: int
    item_shape: tuple[int, ...]
    in_dtype: str
    param_dtype: str
    mesh_axes: tuple[tuple[str, int], ...]
    backend: str
    jax_version: str
    jaxlib_version: str
    donation: str
    format_version: int = AOT_FORMAT_VERSION

    def fingerprint(self) -> str:
        """Hex SHA-256 over the canonical JSON of all fields — the store's
        content address. Byte-stable across processes (golden-tested)."""
        return hashlib.sha256(
            canonical_json(dataclasses.asdict(self)).encode()).hexdigest()

    def describe(self) -> dict:
        """Human-facing metadata subset recorded in the store entry."""
        return {"method": self.method, "bucket": self.bucket,
                "item_shape": list(self.item_shape),
                "in_dtype": self.in_dtype, "param_dtype": self.param_dtype,
                "backend": self.backend, "jax": self.jax_version,
                "jaxlib": self.jaxlib_version,
                "config_hash": self.config_hash[:12]}


def serve_forward_key(config: Any, *, method: str, bucket: int,
                      item_shape: Sequence[int], in_dtype: Any,
                      param_dtype: Any, mesh: Any = None,
                      backend: str | None = None,
                      jax_version: str | None = None,
                      jaxlib_version: str | None = None,
                      donation: str | None = None) -> AotKey:
    """Build the key for one serve bucket's forward.

    Version/backend fields default from the running jax, but every field
    accepts an explicit value so keys can be computed (and golden-tested)
    without a backend.
    """
    if jax_version is None or jaxlib_version is None:
        jv, jlv = _default_versions()
        jax_version = jax_version or jv
        jaxlib_version = jaxlib_version or jlv
    if backend is None:
        backend = _default_backend()
    mesh_axes: tuple[tuple[str, int], ...] = ()
    if mesh is not None:
        shape = dict(mesh.shape) if hasattr(mesh, "shape") else dict(mesh)
        mesh_axes = tuple(sorted((str(k), int(v)) for k, v in shape.items()))
    import numpy as np
    return AotKey(
        config_hash=config_hash(config),
        method=str(method),
        bucket=int(bucket),
        item_shape=tuple(int(d) for d in item_shape),
        in_dtype=str(np.dtype(in_dtype).name),
        param_dtype=str(param_dtype),
        mesh_axes=mesh_axes,
        backend=str(backend),
        jax_version=str(jax_version),
        jaxlib_version=str(jaxlib_version),
        donation=donation if donation is not None else donation_signature(),
    )
