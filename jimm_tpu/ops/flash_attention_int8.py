"""Pallas TPU flash attention with int8-quantized Q/K — the serving variant.

A registered low-precision variant of ``ops/flash_attention.py`` (the
Flashlight template discipline: same grid layout, same online-softmax
recurrence, same DMA-eliding causal index maps — only the score matmul
changes). Q and K are quantized symmetrically per row at trace time
(:func:`_quantize_heads`, scale = max|row|/127) so the (S, S) score matmul
runs int8 x int8 -> int32 on the MXU at twice the bf16 rate; the int32
scores dequantize through the per-row scale outer product inside
:func:`_dequant_scores` (the one sanctioned f32 upcast — JL012), and the
softmax + P@V accumulation stay in f32/storage dtype exactly as in the f32
kernel. V is NOT quantized: the probability-weighted value sum is where
per-row quantization error would compound, and keeping it full-precision is
what holds end-to-end cosine above the 0.999 parity bound the smoke
enforces.

Head dim pads to 128 lanes for the int8 operands (int8 Mosaic tiles are
(32, 128); d=64 towers would otherwise sit below the minimum lane tile).
Zero padding quantizes to zero and contributes nothing to the dot.

Differentiable end-to-end: the forward also emits the per-row lse (same
``(BN, 1, Sq)`` stat layout as the f32 kernel) and a custom VJP pairs it
with dq / dkv backward kernels that **recompute the score tiles from the
saved int8 operands** — bit-identical to what the forward multiplied, so
the softmax recomputation is exact and the gradient is the straight-
through estimate of the quantized forward (the ``int8_qk`` training
policy's contract). dq/dk contract ``ds`` against the dequantized
counterpart operand in the storage dtype, matching the f32 backward's
precision story. Block sizes resolve through
``tune.best_config("flash_attention_int8", ...)``; VMEM per grid cell is
modeled by :func:`_per_head_vmem_bytes` /
:func:`_per_head_bwd_vmem_bytes` (mirrored jax-free in
``tune.space.int8_flash_vmem_bytes`` /
``tune.space.int8_flash_bwd_vmem_bytes``, sync-tested).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jax.ad_checkpoint import checkpoint_name

from jimm_tpu.ops.flash_attention import (NEG_INF, _LANES, _SEMANTICS,
                                          _bcast_lanes, _causal_kv_index,
                                          _causal_q_index, _ceil_to,
                                          _flatten_heads, _from_lanes,
                                          _interpret, _pad_seq, _pick_block,
                                          _unflatten_heads)

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512

#: same per-cell budget as the f32 kernel (of ~16MB/core VMEM)
_VMEM_BUDGET = 8 * 1024 * 1024


def _per_head_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Resident VMEM per head in one grid cell. int8 q/k tiles carry the
    128-padded head dim; v and the out tile keep the storage dtype (bf16
    bound); scales ride in the lse-style (hb, 1, block) layout; the f32
    lse out row feeds the backward. Mirrored jax-free in
    ``tune.space.int8_flash_vmem_bytes`` (sync-tested)."""
    dp = _ceil_to(d, _LANES)
    return (block_q * dp + block_k * dp   # int8 q/k tiles
            + 2 * block_k * d * 2         # v in + double-buffer
            + block_q * d * 2             # out tile
            + 2 * block_q * _LANES * 4    # m/l stats scratch
            + block_q * d * 4             # fp32 accumulator
            + (block_q + block_k) * 4     # per-row q/k scale tiles
            + block_q * 4                 # f32 lse out row
            + block_q * block_k * 6)      # s fp32 + p bf16 intermediate


def _per_head_bwd_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Shared upper bound on one backward grid cell's per-head working set
    (the dq and dkv cells overlap heavily; the bound covers both): int8
    q/k tiles, storage-dtype v/do, scale + lse + delta stat rows, the f32
    dq / dk / dv scratch at their lane-padded widths, and the recomputed
    s/p/ds f32 temporaries. Mirrored jax-free in
    ``tune.space.int8_flash_bwd_vmem_bytes`` (sync-tested)."""
    dp = _ceil_to(d, _LANES)
    return (block_q * dp + block_k * dp        # int8 q/k tiles
            + block_k * d * 2 + block_q * d * 2  # v and do tiles
            + (block_q + block_k) * 4          # per-row q/k scale tiles
            + 2 * block_q * 4                  # lse + delta rows
            + (block_k * dp + block_k * d) * 4  # dk/dv f32 scratch
            + block_q * dp * 4                 # dq f32 scratch
            + 3 * block_q * block_k * 4)       # s/p/ds f32 temporaries


def _pick_hb(bn: int, block_q: int, block_k: int, d: int,
             vmem_fn=_per_head_vmem_bytes) -> int:
    per_head = vmem_fn(block_q, block_k, d)
    for hb in (8, 4, 2):
        if bn % hb == 0 and hb * per_head <= _VMEM_BUDGET:
            return hb
    return 1


def _dequant_scores(s: jax.Array, q_scale: jax.Array,
                    k_scale: jax.Array) -> jax.Array:
    """int32 score block -> f32 via the per-row quantization scales' outer
    product. A sanctioned f32 upcast (JL012)."""
    return s.astype(jnp.float32) * q_scale[:, None] * k_scale[None, :]


def _dequant_operand(x_q: jax.Array, x_scale: jax.Array,
                     dtype) -> jax.Array:
    """int8 operand tile -> storage dtype via its per-row scale, for the
    backward's ds contractions (the f32 kernel contracts ds against the
    bf16 k/q tiles; this is the quantized path's equivalent). A sanctioned
    f32 upcast (JL012)."""
    return (x_q.astype(jnp.float32) * x_scale[:, None]).astype(dtype)


def _bwd_scores(qq, kq, q_scale, k_scale, sm_scale, pos):
    """Recompute one masked f32 score tile from the **saved** int8
    operands — the same int8 dot the forward ran, so the softmax
    recomputation in the backward is bit-identical."""
    s_i32 = jax.lax.dot_general(qq, kq, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.int32)
    s = _dequant_scores(s_i32, q_scale, k_scale) * sm_scale
    return jnp.where(pos, s, NEG_INF)


def _ds_tile(s, do, v, lse, delta):
    """Backward score-gradient (softmax recurrence of the f32 template):
    ``p`` from the recomputed score tile and the saved lse, then
    ``ds = p * (dp - delta)`` — unscaled; the chain-rule sm_scale lands at
    the dq/dk finalize. Returns (p, ds)."""
    dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                             preferred_element_type=jnp.float32)
    p = jnp.exp(s - lse[:, None])
    ds = p * (dp - delta[:, None])
    return p, ds


def _fwd_kernel(qq_ref, kq_ref, v_ref, qs_ref, ks_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr, *, sk_real: int, block_k: int,
                causal: bool, sm_scale: float, n_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    hb, bq, _ = qq_ref.shape

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def compute():
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = k_pos < sk_real
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            mask = mask & (k_pos <= q_pos)
        for h in range(hb):
            qq = qq_ref[h]                               # (bq, dp) int8
            kq = kq_ref[h]                               # (bk, dp) int8
            v = v_ref[h]                                 # (bk, d)
            s_i32 = jax.lax.dot_general(
                qq, kq, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            s = _dequant_scores(s_i32, qs_ref[h, 0, :],
                                ks_ref[h, 0, :]) * sm_scale
            s = jnp.where(mask, s, NEG_INF)
            m_prev = _from_lanes(m_scr[h])
            l_prev = _from_lanes(l_scr[h])
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=1)
            acc_scr[h] = acc_scr[h] * corr[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[h] = _bcast_lanes(m_new)
            l_scr[h] = _bcast_lanes(l_new)

    if causal:
        pl.when(kj * block_k <= (qi + 1) * bq - 1)(compute)
        last_j = jnp.minimum(n_k - 1, ((qi + 1) * bq - 1) // block_k)
    else:
        compute()
        last_j = n_k - 1

    @pl.when(kj == last_j)
    def _finalize():
        for h in range(hb):
            m = _from_lanes(m_scr[h])
            l = _from_lanes(l_scr[h])
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[h] = (acc_scr[h] / l_safe[:, None]).astype(o_ref.dtype)
            lse_ref[h, 0, :] = m + jnp.log(l_safe)


def _bwd_dq_kernel(qq_ref, kq_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref,
                   delta_ref, dq_ref, dq_scr, *, sk_real: int, block_k: int,
                   causal: bool, sm_scale: float, n_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    hb, bq, _ = qq_ref.shape

    @pl.when(kj == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def compute():
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        pos = k_pos < sk_real
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            pos = pos & (k_pos <= q_pos)
        for h in range(hb):
            s = _bwd_scores(qq_ref[h], kq_ref[h], qs_ref[h, 0, :],
                            ks_ref[h, 0, :], sm_scale, pos)
            _, ds = _ds_tile(s, do_ref[h], v_ref[h], lse_ref[h, 0, :],
                             delta_ref[h, 0, :])
            kd = _dequant_operand(kq_ref[h], ks_ref[h, 0, :], do_ref.dtype)
            dq_scr[h] += jax.lax.dot_general(
                ds.astype(kd.dtype), kd, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        pl.when(kj * block_k <= (qi + 1) * bq - 1)(compute)
    else:
        compute()

    @pl.when(kj == n_k - 1)
    def _finalize():
        dq_ref[...] = (dq_scr[...] * sm_scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(qq_ref, kq_ref, v_ref, qs_ref, ks_ref, do_ref, lse_ref,
                    delta_ref, dk_ref, dv_ref, dk_scr, dv_scr, *,
                    sq_real: int, block_q: int, causal: bool,
                    sm_scale: float, n_q: int):
    kj = pl.program_id(1)
    qi = pl.program_id(2)
    hb, bk, _ = kq_ref.shape

    @pl.when(qi == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def compute():
        q_pos = qi * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, bk), 0)
        pos = q_pos < sq_real
        if causal:
            k_pos = kj * bk + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, bk), 1)
            pos = pos & (k_pos <= q_pos)
        for h in range(hb):
            do = do_ref[h]
            s = _bwd_scores(qq_ref[h], kq_ref[h], qs_ref[h, 0, :],
                            ks_ref[h, 0, :], sm_scale, pos)
            p, ds = _ds_tile(s, do, v_ref[h], lse_ref[h, 0, :],
                             delta_ref[h, 0, :])
            # dv's MXU input is a rounded copy; ds keeps the fp32 p
            # (matching the dq kernel) so dk isn't computed from a
            # double-rounded p
            dv_scr[h] += jax.lax.dot_general(
                p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            qd = _dequant_operand(qq_ref[h], qs_ref[h, 0, :], do.dtype)
            dk_scr[h] += jax.lax.dot_general(
                ds.astype(qd.dtype), qd, (((0,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)

    if causal:
        # q blocks whose last row is left of this kv block never land
        pl.when((qi + 1) * block_q - 1 >= kj * bk)(compute)
    else:
        compute()

    @pl.when(qi == n_q - 1)
    def _finalize():
        # ds was accumulated unscaled; the chain-rule sm_scale lands here
        dk_ref[...] = (dk_scr[...] * sm_scale).astype(dk_ref.dtype)
        dv_ref[...] = dv_scr[...].astype(dv_ref.dtype)


def _quantize_heads(x3: jax.Array, seq_p: int,
                    d_p: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-row int8 quantization of a head-flattened (BN, S, D)
    tensor, padded to (BN, seq_p, d_p). Returns the int8 tensor and the
    fp32 scales in the kernel's lse-style (BN, 1, seq_p) layout. Padded
    rows get scale 1.0 (finite dequant; their scores are masked anyway)."""
    xf = x3.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    x_q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    x_q = x_q.astype(jnp.int8)
    bn, seq, d = x3.shape
    x_q = jnp.pad(x_q, ((0, 0), (0, seq_p - seq), (0, d_p - d)))
    scale = jnp.pad(scale, ((0, 0), (0, seq_p - seq)), constant_values=1.0)
    return x_q, scale[:, None, :]


def _resolve_blocks(q, k, v, block_q, block_k):
    """Trace-time block resolution through the tune cache — lookup only.
    Explicit ints win, so the tuner's bench closures cannot recurse."""
    if block_q is not None and block_k is not None:
        return int(block_q), int(block_k)
    from jimm_tpu.tune import best_config
    cfg = best_config("flash_attention_int8",
                      (q.shape, k.shape, v.shape),
                      (q.dtype, k.dtype, v.dtype),
                      default={"block_q": DEFAULT_BLOCK_Q,
                               "block_k": DEFAULT_BLOCK_K})
    return (int(block_q if block_q is not None else cfg["block_q"]),
            int(block_k if block_k is not None else cfg["block_k"]))


def _int8_fwd_impl(q3, k3, v3, causal, sm_scale, block_q, block_k):
    bn, sq, d = q3.shape
    sk = k3.shape[1]
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    d_p = _ceil_to(d, _LANES)
    qq, qs = _quantize_heads(q3, sq_p, d_p)
    kq, ks = _quantize_heads(k3, sk_p, d_p)
    vp = _pad_seq(v3, sk_p)
    n_q, n_k = sq_p // block_q, sk_p // block_k
    hb = _pick_hb(bn, block_q, block_k, d)
    kernel = partial(_fwd_kernel, sk_real=sk, block_k=block_k,
                     causal=causal, sm_scale=sm_scale, n_k=n_k)
    kv_idx = (_causal_kv_index(block_q, block_k, n_k) if causal
              else (lambda h, i, j: (h, j, 0)))
    kv_stat_idx = (
        (lambda h, i, j: (h, 0,
                          _causal_kv_index(block_q, block_k, n_k)(h, i, j)[1]))
        if causal else (lambda h, i, j: (h, 0, j)))
    o, lse = pl.pallas_call(
        kernel,
        grid=(bn // hb, n_q, n_k),
        in_specs=[
            pl.BlockSpec((hb, block_q, d_p), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((hb, block_k, d_p), kv_idx),
            pl.BlockSpec((hb, block_k, d), kv_idx),
            pl.BlockSpec((hb, 1, block_q), lambda h, i, j: (h, 0, i)),
            pl.BlockSpec((hb, 1, block_k), kv_stat_idx),
        ],
        out_specs=[
            pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((hb, 1, block_q), lambda h, i, j: (h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, sq_p, d), q3.dtype),
            jax.ShapeDtypeStruct((bn, 1, sq_p), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, block_q, _LANES), jnp.float32),
            pltpu.VMEM((hb, block_q, _LANES), jnp.float32),
            pltpu.VMEM((hb, block_q, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(qq, kq, vp, qs, ks)
    # same saveable names as the f32 kernel so remat policies that keep
    # flash outputs keep these too (the backward consumes o via delta)
    o = checkpoint_name(o[:, :sq], "flash_o")
    lse = checkpoint_name(lse[:, 0, :sq], "flash_lse")
    # residuals carry the int8 operands the forward actually multiplied —
    # the backward's score recomputation is bit-identical, at 1B/element
    return o, (qq, qs, kq, ks, v3, o, lse)


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_int8(q3, k3, v3, causal, sm_scale, block_q, block_k):
    o, _ = _int8_fwd_impl(q3, k3, v3, causal, sm_scale, block_q, block_k)
    return o


def _int8_fwd(q3, k3, v3, causal, sm_scale, block_q, block_k):
    return _int8_fwd_impl(q3, k3, v3, causal, sm_scale, block_q, block_k)


def _int8_bwd(causal, sm_scale, block_q, block_k, res, do):
    qq, qs, kq, ks, v3, o, lse = res
    bn, sq, d = o.shape
    sk = v3.shape[1]
    sq_p, d_p = qq.shape[1], qq.shape[2]
    sk_p = kq.shape[1]
    n_q, n_k = sq_p // block_q, sk_p // block_k
    vp = _pad_seq(v3, sk_p)
    dop = _pad_seq(do, sq_p)
    # the delta statistic (rowwise sum do*o) is f32 by definition — these
    # are outputs/cotangents, never int8 operand tiles
    do32 = do.astype(jnp.float32)  # jaxlint: disable=JL012 f32 statistic
    o32 = o.astype(jnp.float32)  # jaxlint: disable=JL012 f32 statistic
    delta = jnp.sum(do32 * o32, axis=-1)
    lse_p = jnp.pad(lse, ((0, 0), (0, sq_p - sq)))[:, None]
    delta_p = jnp.pad(delta, ((0, 0), (0, sq_p - sq)))[:, None]
    hb = _pick_hb(bn, block_q, block_k, d, _per_head_bwd_vmem_bytes)

    # ---- dq (grid heads, q, kv) — padded head lanes of the dequantized k
    # are zero, so the extra dq columns are exact zeros, sliced off below
    kv_idx = (_causal_kv_index(block_q, block_k, n_k) if causal
              else (lambda h, i, j: (h, j, 0)))
    kv_stat_idx = (
        (lambda h, i, j: (h, 0,
                          _causal_kv_index(block_q, block_k, n_k)(h, i, j)[1]))
        if causal else (lambda h, i, j: (h, 0, j)))
    q_stat_spec = pl.BlockSpec((hb, 1, block_q), lambda h, i, j: (h, 0, i))
    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, sk_real=sk, block_k=block_k, causal=causal,
                sm_scale=sm_scale, n_k=n_k),
        grid=(bn // hb, n_q, n_k),
        in_specs=[
            pl.BlockSpec((hb, block_q, d_p), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((hb, block_k, d_p), kv_idx),
            pl.BlockSpec((hb, block_k, d), kv_idx),
            q_stat_spec,
            pl.BlockSpec((hb, 1, block_k), kv_stat_idx),
            pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0)),
            q_stat_spec,
            q_stat_spec,
        ],
        out_specs=pl.BlockSpec((hb, block_q, d_p),
                               lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bn, sq_p, d_p), o.dtype),
        scratch_shapes=[pltpu.VMEM((hb, block_q, d_p), jnp.float32)],
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(qq, kq, vp, qs, ks, dop, lse_p, delta_p)[:, :sq, :d]

    # ---- dk / dv (grid heads, kv, q)
    q_idx = (_causal_q_index(block_q, block_k) if causal
             else (lambda h, j, i: (h, i, 0)))
    stat_idx = (_causal_q_index(block_q, block_k, lse_layout=True) if causal
                else (lambda h, j, i: (h, 0, i)))
    stat_spec = pl.BlockSpec((hb, 1, block_q), stat_idx)
    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, sq_real=sq, block_q=block_q, causal=causal,
                sm_scale=sm_scale, n_q=n_q),
        grid=(bn // hb, n_k, n_q),
        in_specs=[
            pl.BlockSpec((hb, block_q, d_p), q_idx),
            pl.BlockSpec((hb, block_k, d_p), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((hb, block_k, d), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((hb, 1, block_q), stat_idx),
            pl.BlockSpec((hb, 1, block_k), lambda h, j, i: (h, 0, j)),
            pl.BlockSpec((hb, block_q, d), q_idx),
            stat_spec,
            stat_spec,
        ],
        out_specs=[
            pl.BlockSpec((hb, block_k, d_p), lambda h, j, i: (h, j, 0)),
            pl.BlockSpec((hb, block_k, d), lambda h, j, i: (h, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bn, sk_p, d_p), o.dtype),
            jax.ShapeDtypeStruct((bn, sk_p, d), v3.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((hb, block_k, d_p), jnp.float32),
            pltpu.VMEM((hb, block_k, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(qq, kq, vp, qs, ks, dop, lse_p, delta_p)
    return dq, dk[:, :sk, :d], dv[:, :sk]


_flash_int8.defvjp(_int8_fwd, _int8_bwd)


def flash_attention_int8(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         is_causal: bool = False,
                         block_q: int | None = None,
                         block_k: int | None = None) -> jax.Array:
    """int8-activation flash attention over ``(B, S, N, D)`` q/k/v.

    Q/K quantize per row to int8, the score matmul runs on the MXU in
    int8, softmax and P@V stay full-precision. Differentiable: a custom
    VJP recomputes score tiles from the saved int8 operands (straight-
    through gradient of the quantized forward), so the ``int8_qk``
    training policy can route attention here. Scale is 1/sqrt(D) like
    `flash_attention`. Runs the Pallas interpreter off-TPU so CPU tests
    and the quant parity harness exercise the same code path.
    """
    b, sq, n, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(q, k, v, block_q, block_k)
    block_q = min(_pick_block(sq, block_q), _ceil_to(sq, _LANES))
    block_k = min(_pick_block(k.shape[1], block_k),
                  _ceil_to(k.shape[1], _LANES))
    q3, k3, v3 = map(_flatten_heads, (q, k, v))
    o = _flash_int8(q3, k3, v3, is_causal, sm_scale, block_q, block_k)
    return _unflatten_heads(o, b, n)
