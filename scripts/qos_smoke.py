"""CI tier-1 smoke for the multi-tenant QoS serving control plane.

End to end on 8 virtual CPU devices, one process, five properties:

1. **Policy load**: a JSON QoS policy (vip=interactive weight 8,
   bulk=batch weight 2) loads through the same :func:`load_policy` path
   ``serve --qos-policy`` uses.
2. **Two lives over one AOT store**: an f32 model sharded over a 2x2
   topology (2 replicas x model-parallel 2) plus an int8 twin on a
   single-device plan, both resident in one :class:`ModelPool`. Life 1
   populates the store through write-through warmup; life 2 (warm
   restart) must report every bucket of every model as ``"aot"``-sourced
   with zero fresh traces.
3. **Weighted-fair shares**: with both class queues saturated, DRR
   dispatch shares converge to the configured weights within 10%.
4. **Interactive isolation**: interactive p99 under full batch
   saturation stays <= 2x the unloaded interactive p99 (the weighted-
   fair queue keeps the latency-sensitive class out of the batch
   backlog).
5. **Zero post-warmup compiles** across both resident models while
   mixed-tenant traffic flows.

Prints one JSON result line; exits non-zero on any failed property.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time
import types

REPLICAS = 2
MODEL_PARALLEL = 2
BATCH_CLIENTS = 16
PROBES = 50          # per latency phase; p99 over 50 samples
PROBE_GAP_S = 0.002
WFQ_DRAWS = 200      # dequeues counted for the share check
MAX_P99_RATIO = 2.0  # loaded interactive p99 vs unloaded

POLICY = {
    "tenants": {
        "vip": {"class": "interactive"},
        "bulk": {"class": "batch"},
    },
}


def fail(msg: str) -> int:
    print(json.dumps({"metric": "qos_smoke", "value": 0.0, "error": msg}),
          flush=True)
    return 1


def p99(samples: list[float]) -> float:
    ranked = sorted(samples)
    return ranked[min(len(ranked) - 1, int(round(0.99 * (len(ranked) - 1))))]


def main() -> int:
    # must land before any jax import anywhere in the process
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    os.environ.setdefault("JAX_PLATFORMS", "cpu")

    import asyncio

    import jax
    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.aot.warmup import AotForward
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.quant import quantize_model
    from jimm_tpu.serve import (AdmissionPolicy, BucketTable, InferenceEngine,
                                RequestError, ServeError,
                                build_replica_forwards, plan_topology)
    from jimm_tpu.serve.qos import (ModelPool, QosScheduler,
                                    WeightedFairQueue, load_policy)

    if jax.device_count() < REPLICAS * MODEL_PARALLEL:
        return fail(f"need {REPLICAS * MODEL_PARALLEL} devices, have "
                    f"{jax.device_count()} — was XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=8 set before "
                    f"another jax import?")

    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    size = cfg.vision.image_size
    plan = plan_topology(REPLICAS, MODEL_PARALLEL)
    # low shed watermark: under batch saturation the coalescing wait is
    # skipped, so the loaded/unloaded comparison isolates queueing, not
    # the (deliberate, policy-owned) 15 ms coalescing window
    policy = AdmissionPolicy(max_queue=64, default_timeout_s=30.0,
                             shed_fraction=0.05)

    with tempfile.TemporaryDirectory(prefix="jimm-qos-smoke-") as root:
        policy_path = os.path.join(root, "qos.json")
        with open(policy_path, "w", encoding="utf-8") as fh:
            json.dump(POLICY, fh)
        registry = load_policy(policy_path)
        if sorted(registry.tenants) != ["bulk", "vip"]:
            return fail(f"policy load: tenants {sorted(registry.tenants)}")
        if registry.class_order[0] != "interactive":
            return fail(f"policy load: class order {registry.class_order}")

        # --- property 3: DRR shares, deterministic, queue-level -----------
        # both classes kept backlogged for the whole 200-draw window, so
        # the measured split is the scheduler's, not the workload's
        wfq = WeightedFairQueue(QosScheduler(registry))
        for _ in range(WFQ_DRAWS + 10):
            wfq.put_nowait(types.SimpleNamespace(klass="interactive"))
            wfq.put_nowait(types.SimpleNamespace(klass="batch"))
        drawn = [wfq.get_nowait().klass for _ in range(WFQ_DRAWS)]
        share = drawn.count("interactive") / WFQ_DRAWS
        w_int = registry.classes["interactive"].weight
        w_bat = registry.classes["batch"].weight
        want = w_int / (w_int + w_bat)
        if abs(share - want) > 0.10 * want:
            return fail(f"WFQ interactive share {share:.3f} not within 10% "
                        f"of weight share {want:.3f}")

        store = ArtifactStore(os.path.join(root, "aot"))

        def make_pool(sched):
            """One f32 sharded engine + one int8 single-device twin,
            shared metrics, shared QoS scheduler — the `serve
            --pool-model` wiring, built directly."""
            model = CLIP(cfg, rngs=nnx.Rngs(0))
            fwd, traces = build_replica_forwards(
                model, plan, method="encode_image",
                item_shape=(size, size, 3), store=store,
                label="qos_smoke:f32")
            eng = InferenceEngine(fwd, item_shape=(size, size, 3),
                                  buckets=BucketTable((1, 2, 4)),
                                  max_delay_ms=15.0, policy=policy,
                                  qos=sched, trace_count=traces)
            qmodel = CLIP(cfg, rngs=nnx.Rngs(0))
            quantize_model(qmodel)
            qfwd = AotForward(qmodel, method="encode_image",
                              item_shape=(size, size, 3), store=store,
                              label="qos_smoke:int8")
            qeng = InferenceEngine(qfwd, item_shape=(size, size, 3),
                                   buckets=BucketTable((1, 2), dtype="int8"),
                                   max_delay_ms=15.0, policy=policy,
                                   metrics=eng.metrics, qos=sched)
            pool = ModelPool({"default": eng, "q8": qeng}, default="default")
            return pool, (lambda: traces() + qfwd.trace_count())

        # --- life 1: populate the store through write-through warmup ------
        pool1, traces1 = make_pool(QosScheduler(registry))
        for eng in pool1.engines():
            eng.warmup_blocking()
        if not traces1():
            return fail("life-1 warmup paid no traces — nothing compiled?")
        if not store.entries():
            return fail("life-1 warmup wrote nothing to the store")

        # --- life 2: warm restart must be fully AOT-sourced ---------------
        sched = QosScheduler(registry)
        pool, traces = make_pool(sched)
        for eng in pool.engines():
            eng.warmup_blocking()
        if traces():
            return fail(f"warm restart paid {traces()} fresh traces; "
                        f"f32/int8 artifacts did not round-trip")
        bad = {}
        for name, row in pool.describe().items():
            report = getattr(pool.get(name), "warmup_report", {})
            for bucket, r in report.items():
                if (r.get("source") != "aot"
                        or any(p.get("source") != "aot"
                               for p in r.get("replicas", []))):
                    bad[f"{name}:{bucket}"] = r.get("source")
        if bad:
            return fail(f"warm restart buckets not AOT-sourced: {bad}")
        compiles_before = traces()

        # --- mixed-tenant traffic on life 2 -------------------------------
        eng = pool.default
        x = np.random.RandomState(0).rand(size, size, 3).astype(np.float32)
        bulk_done = 0
        stop = asyncio.Event()

        async def probe_round():
            lats = []
            for _ in range(PROBES):
                t0 = time.perf_counter()
                await eng.submit(x, tenant="vip")
                lats.append(time.perf_counter() - t0)
                await asyncio.sleep(PROBE_GAP_S)
            return lats

        async def batch_client():
            nonlocal bulk_done
            while not stop.is_set():
                try:
                    await eng.submit(x, tenant="bulk")
                    bulk_done += 1
                except ServeError:
                    await asyncio.sleep(0.001)

        async def drive():
            for e in pool.engines():
                await e.start()
            try:
                unloaded = await probe_round()
                loaders = [asyncio.create_task(batch_client())
                           for _ in range(BATCH_CLIENTS)]
                await asyncio.sleep(0.05)  # let the backlog form
                loaded = await probe_round()
                stop.set()
                await asyncio.gather(*loaders)
                # multi-model residency: routed requests hit the int8 twin
                q8_out = [await pool.get("q8").submit(x, tenant="vip")
                          for _ in range(3)]
                return unloaded, loaded, q8_out
            finally:
                for e in pool.engines():
                    await e.stop()

        unloaded, loaded, q8_out = asyncio.run(drive())
        if not bulk_done:
            return fail("batch tenant fully starved during saturation")
        for out in q8_out:
            if not np.all(np.isfinite(np.asarray(out))):
                return fail("int8 twin returned non-finite output")
        try:
            pool.get("nope")
        except RequestError:
            pass
        else:
            return fail("unknown model name did not raise RequestError")

        compile_delta = traces() - compiles_before
        if compile_delta:
            return fail(f"{compile_delta} fresh compile(s) after warmup")

        p99_unloaded, p99_loaded = p99(unloaded), p99(loaded)
        if p99_loaded > MAX_P99_RATIO * p99_unloaded:
            return fail(f"interactive p99 under batch saturation "
                        f"{p99_loaded * 1e3:.1f} ms > {MAX_P99_RATIO}x "
                        f"unloaded {p99_unloaded * 1e3:.1f} ms")

        snap = sched.snapshot()
        if not snap["classes"]["batch"]["dispatched"]:
            return fail("no batch-class dispatches recorded in snapshot")
        if eng.metrics.count("model_q8_requests_total") < 3:
            return fail("q8 routing not reflected in model counters")

        print(json.dumps({
            "metric": "qos_smoke", "value": 1.0,
            "topology": plan.describe(),
            "models": pool.names(),
            "wfq_interactive_share": round(share, 3),
            "unloaded_p99_ms": round(p99_unloaded * 1e3, 3),
            "loaded_p99_ms": round(p99_loaded * 1e3, 3),
            "batch_served_during_saturation": bulk_done,
            "class_dispatched": {k: row["dispatched"]
                                 for k, row in snap["classes"].items()},
            "compile_count_delta": compile_delta,
            "store_entries": len(store.entries()),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
