"""CI smoke for the low-precision training fast path (``--precision``).

Three gates, end to end through the real ``jimm-tpu train`` CLI on CPU
(interpret-mode Pallas fp8 kernels — the same wrapper/grid code TPU runs):

1. **Same data**: the ``fp8_hybrid`` run and its ``bf16`` control log
   per-step batch fingerprints (``--batch-fingerprint``); they must match
   step for step, so the loss comparison is apples to apples.
2. **Loss parity**: the fp8 run's final-step training loss must match the
   bf16 control within ``LOSS_RTOL`` — delayed scaling plus saturating
   quantization must not bend the tiny-run loss curve.
3. **Zero re-tunes on a warm cache**: the fp8 run executes twice against
   one ``JIMM_TUNE_CACHE`` with ``JIMM_TUNE=1``. Life 1 may measure (the
   cache is cold); life 2 must add ZERO new cache entries — tune keys
   (kernel version + shapes + dtypes) are stable, so a warm cache means
   lookup only, and a re-tune here would mean the fp8 kernels' keys churn
   per process.

``--record`` appends one MEASUREMENTS.jsonl row (``"phase":
"lowp_train_smoke"``) carrying ``precision``, per-variant losses, and the
goodput/MFU readout, so precision sweeps land beside bench rows.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.lowp_train_smoke [--record]
"""

from __future__ import annotations

import argparse
import json
import os
import pathlib
import subprocess
import sys
import tempfile
import time

PRESET = "vit-tiny-patch16-224"
STEPS = 6
BATCH = 4
LOSS_RTOL = 2e-2


def fail(msg: str) -> int:
    print(json.dumps({"metric": "lowp_train_smoke", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def run_train(precision: str, metrics_file: pathlib.Path,
              tune_cache: pathlib.Path | None) -> dict:
    """One tiny CLI train run; returns its parsed goodput report."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if tune_cache is not None:
        env["JIMM_TUNE"] = "1"
        env["JIMM_TUNE_CACHE"] = str(tune_cache)
    cmd = [sys.executable, "-m", "jimm_tpu.cli", "train",
           "--preset", PRESET, "--tiny",
           "--steps", str(STEPS), "--batch-size", str(BATCH),
           "--precision", precision, "--moment-dtype", "bf16",
           "--batch-fingerprint", "--log-every", "1",
           "--metrics-file", str(metrics_file)]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"train --precision {precision} failed: "
                           f"{proc.stderr[-1500:]}")
    for line in proc.stdout.splitlines():
        if line.startswith("goodput: "):
            return json.loads(line[len("goodput: "):])
    raise RuntimeError(f"train --precision {precision} printed no "
                       f"goodput line")


def read_metrics(metrics_file: pathlib.Path) -> list[dict]:
    rows = [json.loads(line) for line in
            metrics_file.read_text().splitlines() if line.strip()]
    return [r for r in rows if "loss" in r]


def imgs_per_sec(rows: list[dict]) -> float | None:
    """Steady-state throughput: first step carries trace+compile, so it is
    excluded; the rest average out interpreter jitter."""
    times = [r["step_time_s"] for r in rows[1:] if r.get("step_time_s")]
    return round(BATCH * len(times) / sum(times), 4) if times else None


def cache_entries(root: pathlib.Path) -> set[str]:
    return {str(p.relative_to(root)) for p in root.rglob("*") if p.is_file()}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    parser.add_argument("--record", action="store_true",
                        help="append the result to MEASUREMENTS.jsonl")
    args = parser.parse_args()

    with tempfile.TemporaryDirectory(prefix="lowp_smoke_") as tmp:
        tmpdir = pathlib.Path(tmp)
        cache = tmpdir / "tune_cache"
        cache.mkdir()

        # --- bf16 control, then fp8 life 1 (cold cache, may tune) --------
        control_goodput = run_train("bf16", tmpdir / "bf16.jsonl", None)
        run_train("fp8_hybrid", tmpdir / "fp8_life1.jsonl", cache)
        warm = cache_entries(cache)

        # --- fp8 life 2: warm cache must stay byte-for-byte the same -----
        fp8_goodput = run_train("fp8_hybrid", tmpdir / "fp8.jsonl", cache)
        if cache_entries(cache) != warm:
            added = sorted(cache_entries(cache) - warm)
            return fail(f"warm tune cache grew on the second fp8 run "
                        f"(re-tuned): {added[:5]}")

        control = read_metrics(tmpdir / "bf16.jsonl")
        lowp = read_metrics(tmpdir / "fp8.jsonl")
        if len(control) != STEPS or len(lowp) != STEPS:
            return fail(f"expected {STEPS} logged steps, got "
                        f"{len(control)} (bf16) / {len(lowp)} (fp8)")

        # --- gate 1: identical data streams ------------------------------
        fp_c = [r.get("batch_fingerprint") for r in control]
        fp_l = [r.get("batch_fingerprint") for r in lowp]
        if None in fp_c or None in fp_l:
            return fail("batch fingerprints missing from metrics rows")
        if fp_c != fp_l:
            return fail(f"batch fingerprints diverge between variants "
                        f"(first mismatch at step "
                        f"{next(i for i, (a, b) in enumerate(zip(fp_c, fp_l)) if a != b)})")

        # --- gate 2: loss parity at the final step ------------------------
        loss_c, loss_l = control[-1]["loss"], lowp[-1]["loss"]
        rel = abs(loss_l - loss_c) / max(abs(loss_c), 1e-9)
        if rel > LOSS_RTOL:
            return fail(f"final loss diverged: bf16 {loss_c:.4f} vs "
                        f"fp8_hybrid {loss_l:.4f} (rel {rel:.3f} > "
                        f"{LOSS_RTOL})")

    result = {
        "metric": "lowp_train_smoke", "value": 1.0,
        "precision": "fp8_hybrid",
        "moment_dtype": fp8_goodput.get("moment_dtype"),
        "steps": STEPS, "batch_size": BATCH,
        "loss_bf16": loss_c, "loss_fp8": loss_l, "loss_rel_diff": rel,
        "mfu_bf16": control_goodput.get("mfu"),
        "mfu_fp8": fp8_goodput.get("mfu"),
        "img_s_bf16": imgs_per_sec(control),
        "img_s_fp8": imgs_per_sec(lowp),
        "tune_entries": len(warm),
    }
    print(json.dumps(result), flush=True)

    if args.record:
        from scripts._measurements import MEASUREMENTS
        ts = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open(MEASUREMENTS, "a") as f:
            f.write(json.dumps({"ts": ts, "phase": "lowp_train_smoke",
                                **{k: v for k, v in result.items()
                                   if k not in ("metric", "value")}})
                    + "\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
