"""Approximate nearest-neighbor retrieval: IVF two-stage search.

- :mod:`~jimm_tpu.retrieval.ann.kmeans` — the coarse quantizer's trainer
  (jit-compiled mini-batch Lloyd's) plus the pure-NumPy assigner and
  codebook framing the jax-free store/CLI paths use.
- :mod:`~jimm_tpu.retrieval.ann.ivf` — the fused two-stage device
  program (coarse centroid scan → runtime-``nprobe`` cluster probe →
  exact rescore of candidate spans) and its AOT-warm searchers.

Like the parent package, importing this never imports jax — the device
program materializes inside function bodies.
"""

from jimm_tpu.retrieval.ann.ivf import (DEFAULT_NPROBE, IvfIndexSearcher,
                                        IvfSearcher, cluster_layout,
                                        make_ivf_fn)
from jimm_tpu.retrieval.ann.kmeans import (CODEBOOK_FORMAT_VERSION,
                                           assign_clusters, clustered_rows,
                                           decode_codebook, encode_codebook,
                                           train_centroids)

__all__ = ["CODEBOOK_FORMAT_VERSION", "DEFAULT_NPROBE", "IvfIndexSearcher",
           "IvfSearcher", "assign_clusters", "cluster_layout",
           "clustered_rows", "decode_codebook", "encode_codebook",
           "make_ivf_fn", "train_centroids"]
