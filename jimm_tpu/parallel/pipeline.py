"""Pipeline parallelism: depth-sharded layer stacks with a microbatched
collective-permute loop.

Absent from the reference (its stack is a python ``nnx.Sequential``,
ref `common/transformer.py:171-188` — SURVEY §2.3 marks PP absent). Here the
encoder's parameters are already *stacked* with a leading ``layers`` axis, so
pipelining is just another sharding of that axis: each device on the
``stage`` mesh axis holds a contiguous block of layers, and microbatches
circulate stage→stage over ICI via ``jax.lax.ppermute`` (the SPMD
"pipelining via collective permute" pattern — no per-stage programs, one
SPMD program).

Schedule: GPipe-style fill-and-drain over ``M`` microbatches and ``S``
stages: ``T = M + S - 1`` ticks; at tick ``t`` a device computes microbatch
``t - stage`` (garbage outside the window — masked out at collection).
Bubble fraction is ``(S-1)/T``; raise M to amortize. Differentiable
end-to-end (`lax.scan` of `ppermute`), composes with remat inside each
stage.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jax import shard_map


def pipeline_forward(stage_apply: Callable, stage_params, x: jax.Array, *,
                     n_microbatches: int, axis_name: str = "stage",
                     mesh: Mesh | None = None,
                     batch_axis: str | None = None) -> jax.Array:
    """Run ``x`` through a depth-stacked stack pipelined over ``axis_name``.

    - ``stage_params``: pytree whose every leaf has a leading global
      ``layers`` dim, sharded over ``axis_name`` (each device gets
      ``layers / n_stages`` consecutive layers).
    - ``stage_apply(local_params, xm)``: applies one device's local layers to
      a microbatch (typically an ``nnx.merge`` + scan over the local stack).
    - ``x``: ``(B, ...)`` activations; ``B`` must divide by
      ``n_microbatches`` (times the ``batch_axis`` size if given).
    - ``batch_axis``: optional mesh axis the batch dim is sharded over
      (pipeline x data parallelism).
    """
    M = n_microbatches
    if M < 1:
        raise ValueError(f"n_microbatches must be >= 1, got {M}")
    x_spec = P(batch_axis) if batch_axis else P()

    def local(params_local, x_local):
        stage = jax.lax.axis_index(axis_name)
        n_stage = jax.lax.axis_size(axis_name)
        b = x_local.shape[0]
        if b % M:
            raise ValueError(f"local batch {b} not divisible by "
                             f"{M} microbatches")
        micro = x_local.reshape(M, b // M, *x_local.shape[1:])

        def step(carry, t):
            # stage 0 feeds fresh microbatches; later stages eat the ring
            inp = jnp.where(stage == 0,
                            micro[jnp.clip(t, 0, M - 1)], carry)
            out = stage_apply(params_local, inp)
            perm = [(i, (i + 1) % n_stage) for i in range(n_stage)]
            return jax.lax.ppermute(out, axis_name, perm), out

        t_total = M + n_stage - 1
        _, outs = jax.lax.scan(step, jnp.zeros_like(micro[0]),
                               jnp.arange(t_total))
        # the last stage emits microbatch m at tick m + n_stage - 1
        window = outs[n_stage - 1:]  # (M, b/M, ...) static slice
        window = jnp.where(stage == n_stage - 1, window,
                           jnp.zeros_like(window))
        result = jax.lax.psum(window, axis_name)
        return result.reshape(b, *x_local.shape[1:])

    kwargs = {} if mesh is None else {"mesh": mesh}
    fn = shard_map(local,
                   in_specs=(P(axis_name), x_spec),
                   out_specs=x_spec,
                   check_vma=False, **kwargs)
    return fn(stage_params, x)
