"""Pure-python CLIP byte-level BPE tokenizer.

The reference delegates all tokenization to `transformers` processors in its
examples (e.g. ref `examples/clip_inference.py`), making torch-free zero-shot
use impossible without the full HF stack. This implements CLIP's tokenizer
(control-char dropping, CJK spacing, NFC normalization, lowercase +
whitespace cleanup — the exact ``transformers.CLIPTokenizer`` no-ftfy
preprocessing — then byte-level BPE with ``</w>`` end-of-word marks,
``<|startoftext|>``/``<|endoftext|>`` specials, endoftext padding)
from the ``vocab.json`` + ``merges.txt`` files that ship inside every CLIP
checkpoint — so ``CLIP.from_pretrained(dir)`` + `CLIPTokenizer.from_dir(dir)`
is a complete offline zero-shot pipeline.

Parity with ``transformers.CLIPTokenizer`` is pinned by
`tests/test_clip_tokenizer.py` (same vocab/merges, identical ids).

SigLIP's tokenizer is SentencePiece (a binary model format) and is NOT
reimplemented — use `--tokenizer` (transformers) or pre-tokenized ids there.
"""

from __future__ import annotations

import functools
import json
import unicodedata
from pathlib import Path

import numpy as np

#: BasicTokenizer's CJK ranges (spaced out before BPE, HF parity)
_CJK = ((0x4E00, 0x9FFF), (0x3400, 0x4DBF), (0x20000, 0x2A6DF),
        (0x2A700, 0x2B73F), (0x2B740, 0x2B81F), (0x2B820, 0x2CEAF),
        (0xF900, 0xFAFF), (0x2F800, 0x2FA1F))


def _basic_clean(text: str) -> str:
    """Mirror ``transformers.CLIPTokenizer``'s no-ftfy preprocessing
    (BasicTokenizer with strip_accents=False, do_split_on_punc=False):
    drop NUL/replacement/control chars, map whitespace to spaces, space out
    CJK chars, NFC-normalize, collapse whitespace, lowercase."""
    out = []
    for ch in text:
        cp = ord(ch)
        if cp in (0, 0xFFFD):
            continue
        cat = unicodedata.category(ch)
        # any C* category (control/format/unassigned/private/surrogate)
        # except the whitespace trio is dropped, like HF's _is_control
        if cat.startswith("C") and ch not in "\t\n\r":
            continue
        if ch in "\t\n\r" or cat == "Zs":
            out.append(" ")
        elif cp >= 0x3400 and any(lo <= cp <= hi for lo, hi in _CJK):
            # guarded: every CJK range starts >= 0x3400, so the common
            # Latin-dominant caption never scans the ranges
            out.append(f" {ch} ")
        else:
            out.append(ch)
    text = unicodedata.normalize("NFC", "".join(out))
    return " ".join(t.lower() for t in text.split())


@functools.lru_cache()
def bytes_to_unicode() -> dict[int, str]:
    """GPT-2's reversible byte <-> printable-unicode table (the byte-level
    BPE alphabet)."""
    bs = (list(range(ord("!"), ord("~") + 1))
          + list(range(ord("\xa1"), ord("\xac") + 1))
          + list(range(ord("\xae"), ord("\xff") + 1)))
    cs = bs[:]
    n = 0
    for b in range(256):
        if b not in bs:
            bs.append(b)
            cs.append(256 + n)
            n += 1
    return dict(zip(bs, map(chr, cs)))


def _get_pairs(word: tuple[str, ...]) -> set[tuple[str, str]]:
    return set(zip(word[:-1], word[1:]))


class CLIPTokenizer:
    """Byte-level BPE with CLIP's text cleanup and special tokens."""

    SOT = "<|startoftext|>"
    EOT = "<|endoftext|>"

    def __init__(self, vocab: dict[str, int],
                 merges: list[tuple[str, str]]):
        self.encoder = dict(vocab)
        self.bpe_ranks = {tuple(m): i for i, m in enumerate(merges)}
        self.byte_encoder = bytes_to_unicode()
        self.sot_id = self.encoder[self.SOT]
        self.eot_id = self.encoder[self.EOT]
        self._cache: dict[str, str] = {}
        import regex  # unicode \p classes (a transformers dependency too)
        self._pat = regex.compile(
            r"""<\|startoftext\|>|<\|endoftext\|>|'s|'t|'re|'ve|'m|'ll|'d"""
            r"""|[\p{L}]+|[\p{N}]|[^\s\p{L}\p{N}]+""",
            regex.IGNORECASE)

    # ------------------------------------------------------------------
    # Loading
    # ------------------------------------------------------------------

    @classmethod
    def from_dir(cls, path: str | Path) -> "CLIPTokenizer":
        """Load ``vocab.json`` + ``merges.txt`` from a checkpoint directory
        (the files every HF CLIP checkpoint ships)."""
        p = Path(path)
        vocab = json.loads((p / "vocab.json").read_text(encoding="utf-8"))
        merges = []
        for line in (p / "merges.txt").read_text(
                encoding="utf-8").splitlines():
            if line.startswith("#version") or not line.strip():
                continue
            a, _, b = line.partition(" ")
            merges.append((a, b))
        return cls(vocab, merges)

    # ------------------------------------------------------------------
    # BPE
    # ------------------------------------------------------------------

    def _bpe(self, token: str) -> str:
        if token in self._cache:
            return self._cache[token]
        word = tuple(token[:-1]) + (token[-1] + "</w>",)
        pairs = _get_pairs(word)
        if not pairs:
            return token + "</w>"
        while True:
            pair = min(pairs,
                       key=lambda p: self.bpe_ranks.get(p, float("inf")))
            if pair not in self.bpe_ranks:
                break
            a, b = pair
            out = []
            i = 0
            while i < len(word):
                try:
                    j = word.index(a, i)
                except ValueError:
                    out.extend(word[i:])
                    break
                out.extend(word[i:j])
                if j < len(word) - 1 and word[j + 1] == b:
                    out.append(a + b)
                    i = j + 2
                else:
                    out.append(word[j])
                    i = j + 1
            word = tuple(out)
            if len(word) == 1:
                break
            pairs = _get_pairs(word)
        result = " ".join(word)
        self._cache[token] = result
        return result

    # ------------------------------------------------------------------
    # Encoding
    # ------------------------------------------------------------------

    def encode(self, text: str) -> list[int]:
        """Text -> token ids, WITH the sot/eot specials (HF parity)."""
        text = _basic_clean(text)
        ids = [self.sot_id]
        for token in self._pat.findall(text):
            if token in (self.SOT, self.EOT):
                # literal specials map to their single id (HF's added-token
                # trie does the same), never through byte-level BPE
                ids.append(self.encoder[token])
                continue
            mapped = "".join(self.byte_encoder[b]
                             for b in token.encode("utf-8"))
            ids.extend(self.encoder[t] for t in self._bpe(mapped).split(" "))
        ids.append(self.eot_id)
        return ids

    def __call__(self, texts: str | list[str], *, context_length: int = 77
                 ) -> np.ndarray:
        """Batch-encode to int32 [B, context_length], truncated (keeping the
        final EOT) and endoftext-padded like HF's ``padding="max_length"``."""
        if isinstance(texts, str):
            texts = [texts]
        out = np.full((len(texts), context_length), self.eot_id, np.int32)
        for i, t in enumerate(texts):
            ids = self.encode(t)
            if len(ids) > context_length:
                ids = ids[: context_length - 1] + [self.eot_id]
            out[i, : len(ids)] = ids
        return out
