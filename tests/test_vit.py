"""ViT parity + loader strictness tests (reference anchor:
`tests/test_vit.py`, atol there 0.05 — we hold ~1e-5)."""

import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import VisionTransformer, ViTConfig, VisionConfig
from jimm_tpu.weights.loader import MappingError

from hf_util import sample_image, save_tiny_vit, torch_image


@pytest.fixture(scope="module")
def vit_ckpt(tmp_path_factory):
    return save_tiny_vit(tmp_path_factory.mktemp("vit"))


def test_parity_vs_hf_torch(vit_ckpt, rng):
    import torch
    from transformers import ViTForImageClassification
    hf = ViTForImageClassification.from_pretrained(vit_ckpt).eval()
    model = VisionTransformer.from_pretrained(vit_ckpt)
    img = sample_image(rng, size=48)
    ours = np.asarray(model(jnp.asarray(img)))
    with torch.no_grad():
        theirs = hf(torch_image(img)).logits.numpy()
    assert ours.shape == theirs.shape == (2, 7)
    np.testing.assert_allclose(ours, theirs, atol=1e-4)


def test_shape_inference_without_config(vit_ckpt, tmp_path, rng):
    """Config-free load must infer width/depth/img size from tensor shapes
    (ref `models/vit.py:144-164`)."""
    import shutil
    d = tmp_path / "noconfig"
    d.mkdir()
    shutil.copy(os.path.join(vit_ckpt, "model.safetensors"), d)
    model = VisionTransformer.from_pretrained(str(d / "model.safetensors"))
    cfg = model.config.vision
    assert (cfg.width, cfg.depth, cfg.mlp_dim, cfg.patch_size,
            cfg.image_size) == (64, 3, 128, 16, 48)
    out = model(jnp.asarray(sample_image(rng, size=48)))
    assert out.shape == (2, 7)


def test_dtype_arg_sets_param_dtype(vit_ckpt):
    """`from_pretrained(dtype=bf16)` loads bf16 params (ref vit.py:181-182)."""
    model = VisionTransformer.from_pretrained(vit_ckpt, dtype=jnp.bfloat16)
    from flax import nnx
    kernel = nnx.state(model)["classifier"]["kernel"].get_value()
    assert kernel.dtype == jnp.bfloat16
    out = model(jnp.ones((1, 48, 48, 3), jnp.bfloat16))
    assert out.dtype == jnp.bfloat16


def test_loader_rejects_corrupt_checkpoint(vit_ckpt, tmp_path):
    """Strict verification: a renamed tensor must fail loudly
    (ref `models/vit.py:259-268`)."""
    from jimm_tpu.weights.safetensors_io import load_file, save_file
    w = load_file(os.path.join(vit_ckpt, "model.safetensors"))
    w = dict(w)
    w["bogus.tensor"] = w.pop("classifier.bias")
    d = tmp_path / "corrupt"
    d.mkdir()
    save_file(w, d / "model.safetensors")
    with open(os.path.join(vit_ckpt, "config.json")) as f:
        (d / "config.json").write_text(f.read())
    with pytest.raises(MappingError):
        VisionTransformer.from_pretrained(str(d))


def test_no_classification_head(rng):
    cfg = ViTConfig(vision=VisionConfig(image_size=32, patch_size=16, width=64,
                                        depth=2, num_heads=2, mlp_dim=128,
                                        ln_eps=1e-12),
                    do_classification=False)
    model = VisionTransformer(cfg)
    out = model(jnp.asarray(sample_image(rng)))
    assert out.shape == (2, 64)


def test_no_torch_in_import_graph():
    """North-star gate: importing jimm_tpu must not pull in torch."""
    import subprocess, sys
    code = ("import sys; import jimm_tpu; "
            "sys.exit(1 if 'torch' in sys.modules else 0)")
    proc = subprocess.run([sys.executable, "-c", code], capture_output=True)
    assert proc.returncode == 0, proc.stderr.decode()


def test_runtime_overrides(vit_ckpt):
    """from_pretrained(runtime=...) flips execution-strategy fields without
    touching architecture; architecture fields are rejected."""
    import pytest

    m = VisionTransformer.from_pretrained(
        str(vit_ckpt), runtime=dict(remat=True, remat_policy="dots",
                                    attn_impl="xla", scan_unroll=3))
    assert m.config.vision.remat and m.config.vision.scan_unroll == 3
    with pytest.raises(ValueError, match="not runtime-overridable"):
        VisionTransformer.from_pretrained(str(vit_ckpt),
                                          runtime=dict(width=128))


def test_with_runtime_per_tower():
    """Flat fields hit both towers; vision=/text= dicts target one; ViT
    rejects text-tower overrides."""
    import pytest

    from jimm_tpu.configs import CLIPConfig, ViTConfig, with_runtime

    cfg = with_runtime(CLIPConfig(), remat=True,
                       vision=dict(pipeline=True, pp_stages=2),
                       text=dict(scan_unroll=4))
    assert cfg.vision.remat and cfg.text.remat
    assert cfg.vision.pipeline and not cfg.text.pipeline
    assert cfg.text.scan_unroll == 4 and cfg.vision.scan_unroll == 1
    with pytest.raises(ValueError, match="no text tower"):
        with_runtime(ViTConfig(), text=dict(remat=True))
