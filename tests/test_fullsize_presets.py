"""Full-size preset load proofs (VERDICT r1 item #3).

Round-1 parity evidence used tiny random-init oracles only; these build the
REAL-dimension checkpoints for each family's largest/oddest preset offline
(random init — no network), then prove the full surface:

    HF torch checkpoint -> from_pretrained -> forward parity (fp32)
      -> save_pretrained -> reload -> identical forward

Covered presets (reference anchor: the ref's tests load real ViT-L/14,
`tests/test_clip.py:10`):
- clip-vit-large-patch14-336 (the ref's tested scale, at 336px)
- siglip-so400m-patch14-384  (non-4x MLP 1152->4304 — unloadable in the ref,
  SURVEY §2.4)
- siglip2-large-patch16-512  (256k-token Gemma vocab, 1024-patch grid)

Marked slow: each builds a multi-GB checkpoint and runs a full-size forward
on CPU. Memory/disk stay bounded by one family at a time (function-scoped
tmp dirs).
"""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import CLIP, SigLIP, preset

from hf_util import torch_image

pytestmark = pytest.mark.slow

ATOL = 2e-3  # fp32 end-to-end at depth 24-27 / seq up to 1025


def _check_roundtrip(model_cls, out_dir, ours, inputs):
    """save_pretrained -> reload -> bitwise-close forward."""
    ours.save_pretrained(out_dir)
    again = model_cls.from_pretrained(str(out_dir), dtype=jnp.float32)
    a = np.asarray(ours(*inputs))
    b = np.asarray(again(*inputs))
    np.testing.assert_allclose(b, a, atol=1e-5)


def test_clip_vit_large_patch14_336(tmp_path, rng):
    import torch
    from transformers import CLIPConfig, CLIPModel

    ref_cfg = preset("clip-vit-large-patch14-336")
    hf = CLIPConfig(
        vision_config=dict(hidden_size=1024, num_hidden_layers=24,
                           num_attention_heads=16, intermediate_size=4096,
                           image_size=336, patch_size=14),
        text_config=dict(hidden_size=768, num_hidden_layers=12,
                         num_attention_heads=12, intermediate_size=3072,
                         vocab_size=49408, max_position_embeddings=77,
                         eos_token_id=2),  # legacy id, like the real ckpt
        projection_dim=768)
    oracle = CLIPModel(hf).eval()
    oracle.save_pretrained(tmp_path / "src", safe_serialization=True)

    model = CLIP.from_pretrained(str(tmp_path / "src"), dtype=jnp.float32)
    # config inference must reproduce the preset's dimensions
    assert model.config.vision == dataclasses.replace(
        ref_cfg.vision, attn_impl=model.config.vision.attn_impl)
    img = rng.randn(1, 336, 336, 3).astype(np.float32)
    txt = rng.randint(1, 49000, size=(1, 77))
    txt[0, 60] = 49407  # EOT = max id (legacy argmax pooling)
    with torch.no_grad():
        ref = oracle(input_ids=torch.tensor(txt),
                     pixel_values=torch_image(img)).logits_per_image.numpy()
    got = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    np.testing.assert_allclose(got, ref, atol=ATOL)
    del oracle
    _check_roundtrip(CLIP, tmp_path / "out", model,
                     (jnp.asarray(img), jnp.asarray(txt)))


def test_siglip_so400m_patch14_384(tmp_path, rng):
    import torch
    from transformers import SiglipConfig, SiglipModel

    ref_cfg = preset("siglip-so400m-patch14-384")
    hf = SiglipConfig(
        vision_config=dict(hidden_size=1152, num_hidden_layers=27,
                           num_attention_heads=16, intermediate_size=4304,
                           image_size=384, patch_size=14),
        text_config=dict(hidden_size=1152, num_hidden_layers=27,
                         num_attention_heads=16, intermediate_size=4304,
                         vocab_size=32000, max_position_embeddings=64))
    oracle = SiglipModel(hf).eval()
    oracle.save_pretrained(tmp_path / "src", safe_serialization=True)

    model = SigLIP.from_pretrained(str(tmp_path / "src"), dtype=jnp.float32)
    assert model.config.vision.mlp_dim == 4304  # the non-4x ratio loads
    assert model.config.vision == dataclasses.replace(
        ref_cfg.vision, attn_impl=model.config.vision.attn_impl)
    img = rng.randn(1, 384, 384, 3).astype(np.float32)
    txt = rng.randint(1, 32000, size=(1, 64))
    with torch.no_grad():
        ref = oracle(input_ids=torch.tensor(txt),
                     pixel_values=torch_image(img)).logits_per_image.numpy()
    got = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    np.testing.assert_allclose(got, ref, atol=ATOL)
    del oracle
    _check_roundtrip(SigLIP, tmp_path / "out", model,
                     (jnp.asarray(img), jnp.asarray(txt)))


def test_siglip2_large_patch16_512(tmp_path, rng):
    import torch
    from transformers import SiglipConfig, SiglipModel

    ref_cfg = preset("siglip2-large-patch16-512")
    hf = SiglipConfig(
        vision_config=dict(hidden_size=1024, num_hidden_layers=24,
                           num_attention_heads=16, intermediate_size=4096,
                           image_size=512, patch_size=16),
        text_config=dict(hidden_size=1024, num_hidden_layers=24,
                         num_attention_heads=16, intermediate_size=4096,
                         vocab_size=256000, max_position_embeddings=64))
    oracle = SiglipModel(hf).eval()
    oracle.save_pretrained(tmp_path / "src", safe_serialization=True)

    model = SigLIP.from_pretrained(str(tmp_path / "src"), dtype=jnp.float32)
    assert model.config.text.vocab_size == 256000
    assert model.config.vision == dataclasses.replace(
        ref_cfg.vision, attn_impl=model.config.vision.attn_impl)
    img = rng.randn(1, 512, 512, 3).astype(np.float32)
    txt = rng.randint(1, 256000, size=(1, 64))
    with torch.no_grad():
        ref = oracle(input_ids=torch.tensor(txt),
                     pixel_values=torch_image(img)).logits_per_image.numpy()
    got = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    np.testing.assert_allclose(got, ref, atol=ATOL)
    del oracle
    _check_roundtrip(SigLIP, tmp_path / "out", model,
                     (jnp.asarray(img), jnp.asarray(txt)))


def test_siglip2_so400m_presets_shapes():
    """SigLIP2 So400m presets: v1 So400m tower dims + Gemma-sized vocab."""
    from jimm_tpu.configs import preset
    for name, patches in (("siglip2-so400m-patch14-384", 729),
                          ("siglip2-so400m-patch16-256", 256)):
        cfg = preset(name)
        assert (cfg.vision.width, cfg.vision.depth,
                cfg.vision.mlp_dim) == (1152, 27, 4304)
        assert cfg.vision.num_patches == patches
        assert cfg.text.vocab_size == 256000
        assert cfg.projection_dim == 1152
