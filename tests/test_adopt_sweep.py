"""scripts/adopt_sweep.py: ranking, fidelity filters, flag spelling —
and the shared soft-alarm guard."""

import json
import pathlib
import time

import scripts.adopt_sweep as adopt


def _write(tmp_path, recs):
    p = tmp_path / "sweep.log"
    p.write_text("\n".join(json.dumps(r) for r in recs) + "\nnot json\n")
    return p


def test_ranking_filters_low_fidelity_records(tmp_path):
    path = _write(tmp_path, [
        {"variant": {"remat": "dots"}, "mfu": 0.45, "device": "TPU v5 lite"},
        # tiny/CPU validation lines must never outrank real measurements
        {"variant": {"remat": "dots"}, "mfu": 0.93, "device": "cpu"},
        {"variant": {"remat": "dots", "ln": "fused"}, "mfu": 0.91,
         "tiny": True, "device": "TPU v5 lite"},
        {"variant": {"remat": "dots", "ln": "fused"}, "mfu": 0.47,
         "device": "TPU v5 lite"},
        {"variant": {"remat": "dots"}, "error": "boom"},
    ])
    recs = adopt.load_records(path, phase_filter=False)
    assert all(isinstance(r["mfu"], float) for r in recs)
    assert sorted(r["mfu"] for r in recs) == [0.45, 0.47]


def test_last_record_per_variant_wins(tmp_path):
    path = _write(tmp_path, [
        {"variant": {"remat": "dots"}, "mfu": 0.40, "device": "TPU"},
        # key order must not split the variant into two entries
        {"variant": {"ln": "fused", "remat": "dots"}, "mfu": 0.30,
         "device": "TPU"},
        {"variant": {"remat": "dots", "ln": "fused"}, "mfu": 0.42,
         "device": "TPU"},
        {"variant": {"remat": "dots"}, "mfu": 0.46, "device": "TPU"},
    ])
    ranked = adopt.rank_records(adopt.load_records(path, phase_filter=False))
    assert [r["mfu"] for r in ranked] == [0.46, 0.42]


def test_flags_for_reproduces_measured_config():
    v = {"remat": "dots+ln", "ln": "fused", "fused_qkv": "1",
         "moment": "bf16", "unroll": "6", "batch": "256", "donate": "0",
         "attn": "saveable"}
    flags = adopt.flags_for(v)
    for expect in ("--remat dots+ln", "--ln fused", "--fused-qkv",
                   "--moment-dtype bf16", "--unroll 6", "--batch-size 256",
                   "--no-donate", "--attn saveable"):
        assert expect in flags, flags


def test_soft_alarm_interrupts_and_restores():
    from jimm_tpu.utils.alarm import soft_alarm
    import signal

    before = signal.getsignal(signal.SIGALRM)
    disarm = soft_alarm(1)
    try:
        time.sleep(5)
        raise AssertionError("alarm did not fire")
    except TimeoutError:
        pass
    finally:
        disarm()
    assert signal.getsignal(signal.SIGALRM) is before

    # disarm before expiry must CANCEL the pending alarm, not just restore
    # the handler — otherwise SIGALRM would land on the restored default
    # handler and kill the process
    fired = []
    old = signal.signal(signal.SIGALRM, lambda s, f: fired.append(s))
    try:
        disarm = soft_alarm(1)
        disarm()
        # disarm restored OUR recording handler; any leaked alarm -> fired
        time.sleep(1.2)
        assert not fired, "disarm() left the alarm pending"
    finally:
        signal.signal(signal.SIGALRM, old)


def test_missing_device_field_is_low_fidelity(tmp_path):
    # pre-r4 sweep logs carry no device tag; they must not outrank (or even
    # enter) the ranking vs provenance-tagged TPU records (ADVICE r4)
    path = _write(tmp_path, [
        {"variant": {"remat": "dots"}, "mfu": 0.45, "device": "TPU v5 lite"},
        {"variant": {"remat": "full"}, "mfu": 0.93},
    ])
    recs = adopt.load_records(path, phase_filter=False)
    assert [r["mfu"] for r in recs] == [0.45]


def test_runtime_for_maps_variant_to_with_runtime_kwargs():
    rt = adopt.runtime_for({"remat": "dots+ln", "attn": "flash",
                            "ln": "fused", "fused_qkv": "1", "unroll": "6",
                            "moment": "bf16", "batch": "256"})
    assert rt == {"remat": True, "remat_policy": "dots+ln",
                  "attn_impl": "flash", "ln_impl": "fused",
                  "fused_qkv": True, "scan_unroll": 6}


def test_apply_adoption_round_trips_through_configs(tmp_path, monkeypatch):
    import jimm_tpu.configs as configs
    monkeypatch.setattr(configs, "ADOPTED_RUNTIME_PATH",
                        tmp_path / "adopted.json")
    best = {"variant": {"remat": "dots+ln", "attn": "flash", "unroll": "12"},
            "mfu": 0.47, "step_time_ms": 240.0, "device": "TPU v5 lite",
            "ts": "2026-07-30T00:00:00Z"}
    path = adopt.apply_adoption(best, "siglip-base-patch16-256")
    data = json.loads(path.read_text())
    entry = data["presets"]["siglip-base-patch16-256"]
    assert entry["provenance"]["mfu"] == 0.47
    assert entry["provenance"]["device"] == "TPU v5 lite"
    assert entry["variant"]["attn"] == "flash"
    # the configs-side loader returns exactly the runtime fields
    assert configs.adopted_runtime("siglip-base-patch16-256") == {
        "remat": True, "remat_policy": "dots+ln", "attn_impl": "flash",
        "scan_unroll": 12}
    # unknown preset -> {}
    assert configs.adopted_runtime("vit-large-patch16-384") == {}
    # a second adoption for another preset preserves the first entry
    adopt.apply_adoption({"variant": {"remat": "dots"}, "mfu": 0.5,
                          "device": "TPU v5 lite"}, "vit-large-patch16-384")
    data = json.loads(path.read_text())
    assert set(data["presets"]) == {"siglip-base-patch16-256",
                                    "vit-large-patch16-384"}


def test_adopted_runtime_rejects_bad_fields_with_warning(tmp_path,
                                                         monkeypatch):
    # a corrupted file must DEGRADE (warning + {}), never crash the CLI or
    # fail minutes into a jit trace with an invalid baked-in value
    import pytest

    import jimm_tpu.configs as configs
    p = tmp_path / "adopted.json"
    monkeypatch.setattr(configs, "ADOPTED_RUNTIME_PATH", p)
    for runtime in ({"width": 4096},              # architecture smuggling
                    {"attn_impl": "flsh"},        # typo'd enum value
                    {"scan_unroll": "12"},        # string where int needed
                    {"remat_policy": "dotz"},     # malformed remat spec
                    ["not", "a", "dict"]):        # wrong container type
        p.write_text(json.dumps({"presets": {"x": {"runtime": runtime}}}))
        with pytest.warns(UserWarning, match="ignoring adopted runtime"):
            assert configs.adopted_runtime("x") == {}
    # valid entries still load
    p.write_text(json.dumps({"presets": {"x": {"runtime": {
        "attn_impl": "flash", "scan_unroll": 12, "remat": True,
        "remat_policy": "dots+ln"}}}}))
    assert configs.adopted_runtime("x")["attn_impl"] == "flash"


def test_bench_resolve_adopted_defaults(tmp_path, monkeypatch):
    import importlib.util
    import pathlib

    import jimm_tpu.configs as configs
    spec = importlib.util.spec_from_file_location(
        "bench_for_adopt_test",
        pathlib.Path(__file__).resolve().parent.parent / "bench.py")
    bench = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bench)
    p = tmp_path / "adopted.json"
    p.write_text(json.dumps({"presets": {"siglip-base-patch16-256": {
        "variant": {"remat": "dots+ln", "attn": "flash", "moment": "bf16",
                    "unroll": "12", "fused_qkv": "1"}}}}))
    monkeypatch.setattr(configs, "ADOPTED_RUNTIME_PATH", p)

    a = bench.parse_args(["--model", "siglip_b16_256"])
    assert bench.resolve_adopted_defaults(a, on_tpu=True)
    assert (a.remat, a.attn, a.moment_dtype, a.unroll, a.fused_qkv) == \
        ("dots+ln", "flash", "bf16", 12, True)

    # explicit flags always beat adopted values
    a = bench.parse_args(["--remat", "dots", "--attn", "xla", "--unroll", "6"])
    bench.resolve_adopted_defaults(a, on_tpu=True)
    assert (a.remat, a.attn, a.unroll) == ("dots", "xla", 6)

    # off-TPU: builtin fallbacks, adopted file untouched
    a = bench.parse_args([])
    assert not bench.resolve_adopted_defaults(a, on_tpu=False)
    assert (a.remat, a.attn, a.ln, a.moment_dtype) == \
        ("dots", "auto", "xla", "f32")

    # no adopted entry for the model's preset -> fallbacks only
    a = bench.parse_args(["--model", "vit_l16_384"])
    assert not bench.resolve_adopted_defaults(a, on_tpu=True)
    assert a.remat == "dots"


def test_sweep_skips_already_measured_tpu_variants(tmp_path, monkeypatch):
    """bench_sweep's retry-resume: only same-model, real-TPU, non-tiny,
    successful records mark a grid variant as already measured."""
    import scripts.bench_sweep as bs
    recs = [
        {"model": "siglip_b16_256", "variant": {"remat": "dots"},
         "mfu": 0.446, "device": "TPU v5 lite"},
        # errored attempt: must be retried
        {"model": "siglip_b16_256", "variant": {"remat": "dots",
                                                "ln": "fused"}, "error": "x"},
        # CPU validation record: never marks a TPU variant done
        {"model": "siglip_b16_256", "variant": {"remat": "dots",
                                                "batch": "192"},
         "mfu": 0.4, "device": "cpu"},
        # other bench model: independent
        {"model": "vit_l16_384", "variant": {"remat": "dots"},
         "mfu": 0.3, "device": "TPU v5 lite"},
        # tiny smoke: low fidelity
        {"model": "siglip_b16_256", "variant": {"remat": "dots+ln"},
         "mfu": 0.4, "device": "TPU v5 lite", "tiny": True},
    ]
    p = _write(tmp_path, recs)
    monkeypatch.setattr(bs, "MEASUREMENTS", p)
    assert bs.measured_variants("siglip_b16_256") == [{"remat": "dots"}]
    assert bs.measured_variants("vit_l16_384") == [{"remat": "dots"}]
    monkeypatch.setattr(bs, "MEASUREMENTS", tmp_path / "absent.jsonl")
    assert bs.measured_variants("siglip_b16_256") == []


def test_hard_watchdog_thread_backstop_fires_without_sigalrm(tmp_path):
    """A PJRT wait parked on a condition variable never lets the SIGALRM
    Python handler run; the daemon-thread backstop must fire anyway."""
    import subprocess
    import sys
    code = """
import signal, sys, time
# neuter SIGALRM delivery so only the thread backstop can fire
real_signal = signal.signal
signal.signal = lambda *a: None
signal.alarm = lambda *a: 0
sys.path.insert(0, %r)
from scripts._watchdog import hard_watchdog
hard_watchdog(1, 7, lambda: print("backstop fired", flush=True))
time.sleep(30)
""" % (str(pathlib.Path(__file__).resolve().parents[1]),)
    t0 = time.time()
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=25)
    assert proc.returncode == 7, (proc.returncode, proc.stderr)
    assert "backstop fired" in proc.stdout
    assert time.time() - t0 < 20  # fired at ~6 s, not the sleep's 30


def test_hard_watchdog_disarm_cancels_backstop():
    import subprocess
    import sys
    code = """
import sys, time
sys.path.insert(0, %r)
from scripts._watchdog import hard_watchdog
disarm = hard_watchdog(1, 7, lambda: print("fired", flush=True))
disarm()
time.sleep(8)
print("survived", flush=True)
""" % (str(pathlib.Path(__file__).resolve().parents[1]),)
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=25)
    assert proc.returncode == 0, (proc.returncode, proc.stderr)
    assert "survived" in proc.stdout


def test_window_report_summarizes_phases(tmp_path, capsys):
    import scripts.window_report as wr
    p = tmp_path / "m.jsonl"
    p.write_text("\n".join([
        json.dumps({"ts": "t1", "phase": "sweep", "attempt": 1, "rc": 124,
                    "variant": {"remat": "dots"}, "mfu": 0.45,
                    "step_time_ms": 251.0}),
        json.dumps({"ts": "t2", "phase": "sweep", "attempt": 1, "rc": 124,
                    "variant": {"ln": "fused"}, "error": "boom"}),
        "not json",
    ]))
    import sys
    old = sys.argv
    try:
        sys.argv = ["window_report", "--file", str(p)]
        wr.main()
    finally:
        sys.argv = old
    out = capsys.readouterr().out
    assert "remat=dots" in out and "mfu=0.45" in out
    assert "ERROR: boom" in out
    assert "sweep=1/2" in out


def test_flashchk_resumes_at_unproven_cases(tmp_path, monkeypatch):
    """A retried compiled-parity phase skips cases already recorded clean
    on a real TPU (value 1.0); failures, CPU records and unseen cases run."""
    import scripts._measurements as m
    import scripts.flash_compiled_check as fc
    p = tmp_path / "m.jsonl"
    p.write_text("\n".join(json.dumps(r) for r in [
        {"metric": "flash_compiled_parity", "case": "seq512_causal0_f32",
         "value": 1.0, "device": "TPU v5 lite"},
        {"metric": "flash_compiled_parity", "case": "seq512_causal1_f32",
         "value": 0.0, "device": "TPU v5 lite"},
        {"metric": "ln_compiled_parity", "case": "r300_f768_f32",
         "value": 1.0, "device": "cpu"},
        {"metric": "ln_compiled_parity", "case": "r2048_f768_bf16",
         "value": 1.0, "device": "TPU v5 lite"},
    ]))
    monkeypatch.setattr(m, "MEASUREMENTS", p)
    assert fc.proven_cases() == {
        ("flash_compiled_parity", "seq512_causal0_f32"),
        ("ln_compiled_parity", "r2048_f768_bf16")}
    monkeypatch.setenv("JIMM_FLASHCHK_NO_SKIP", "1")
    assert fc.proven_cases() == set()


def test_sweep_defers_variants_that_hang_repeatedly(tmp_path, monkeypatch):
    import scripts.bench_sweep as bs

    def hang(attempt):
        return {"model": "siglip_b16_256", "variant": {"remat": "dots+ln"},
                "error": "variant watchdog after 600s (tunnel hang?)",
                "phase": "sweep", "attempt": attempt}

    def ok(attempt):
        # corroboration: the same attempt landed a real measurement, so
        # the tunnel was up when the watchdog fired
        return {"model": "siglip_b16_256", "variant": {"ln": "fused"},
                "mfu": 0.41, "device": "TPU v5 lite",
                "phase": "sweep", "attempt": attempt}

    other_err = {"model": "siglip_b16_256", "variant": {"ln": "fused"},
                 "error": "ValueError('block spec')",
                 "phase": "sweep", "attempt": 1}
    p = _write(tmp_path, [hang(1), ok(1), other_err, hang(2), ok(2)])
    monkeypatch.setattr(bs, "MEASUREMENTS", p)
    # two corroborated hangs -> deferred; non-watchdog error -> retried
    assert bs.hung_variants("siglip_b16_256") == [{"remat": "dots+ln"}]
    assert bs.hung_variants("siglip_b16_256", min_hangs=3) == []
    assert bs.hung_variants("vit_l16_384") == []


def test_sweep_uncorroborated_hangs_do_not_defer(tmp_path, monkeypatch):
    """A dropped tunnel hangs every variant it touches: watchdog records
    from attempts that landed no successful measurement must not count
    toward deferral, or connectivity noise permanently blames variants."""
    import scripts.bench_sweep as bs
    hangs = [{"model": "siglip_b16_256", "variant": {"remat": "dots+ln"},
              "error": "variant watchdog after 600s (tunnel hang?)",
              "phase": "sweep", "attempt": a} for a in (1, 2, 3)]
    # a success in a *different* attempt corroborates nothing above
    ok = {"model": "siglip_b16_256", "variant": {"ln": "fused"},
          "mfu": 0.41, "device": "TPU v5 lite",
          "phase": "sweep", "attempt": 4}
    p = _write(tmp_path, hangs + [ok])
    monkeypatch.setattr(bs, "MEASUREMENTS", p)
    assert bs.hung_variants("siglip_b16_256") == []
