from jimm_tpu.data.pipeline import PrefetchIterator
from jimm_tpu.data.preprocess import (CLIP_MEAN, CLIP_STD, IMAGENET_MEAN,
                                      IMAGENET_STD, SIGLIP_MEAN, SIGLIP_STD,
                                      center_crop, native_available,
                                      preprocess_batch, resize_bilinear,
                                      to_float_normalized)
from jimm_tpu.data.clip_tokenizer import CLIPTokenizer
from jimm_tpu.data.naflex import (image_to_patches, patchify_naflex,
                                  target_size_for_max_patches)
from jimm_tpu.data.grain_pipeline import (TFRecordDataSource,
                                          grain_batches, make_grain_loader)
from jimm_tpu.data.records import (classification_batches, decode_image,
                                   image_text_batches, iter_examples,
                                   naflex_image_text_batches,
                                   pad_tokens, prep_image, resolve_paths,
                                   write_classification_records,
                                   write_image_text_records)
from jimm_tpu.data.synthetic import (blob_classification, contrastive_pairs,
                                     naflex_contrastive_pairs)
from jimm_tpu.data.webdataset import (iter_wds_examples, resolve_tar_paths,
                                      wds_classification_batches,
                                      wds_image_text_batches, write_wds_shard)
from jimm_tpu.data.tfrecord import (TFRecordWriter, crc32c, decode_example,
                                    encode_example, masked_crc32c,
                                    read_tfrecord, write_tfrecord)

__all__ = [
    "PrefetchIterator", "blob_classification", "contrastive_pairs",
    "naflex_contrastive_pairs",
    "patchify_naflex", "image_to_patches", "target_size_for_max_patches",
    "preprocess_batch", "to_float_normalized", "resize_bilinear",
    "center_crop", "native_available", "IMAGENET_MEAN", "IMAGENET_STD",
    "CLIP_MEAN", "CLIP_STD", "SIGLIP_MEAN", "SIGLIP_STD",
    "TFRecordWriter", "write_tfrecord", "read_tfrecord", "crc32c",
    "masked_crc32c", "encode_example", "decode_example",
    "image_text_batches", "naflex_image_text_batches",
    "classification_batches", "iter_examples",
    "decode_image", "resolve_paths", "prep_image", "pad_tokens",
    "write_image_text_records", "write_classification_records",
    "TFRecordDataSource", "make_grain_loader", "grain_batches",
    "CLIPTokenizer",
    "wds_image_text_batches", "wds_classification_batches",
    "iter_wds_examples", "resolve_tar_paths", "write_wds_shard",
]
