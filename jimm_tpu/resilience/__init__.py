"""Preemption-tolerant elastic training.

TPU capacity is revocable: maintenance events and spot reclaims SIGTERM a
worker and give it a short grace window. The reference stack ships no fault
tolerance at all — a preemption loses the run. This package treats failure
as a scheduled event instead:

- :class:`Supervisor` runs training as a restartable attempt — it catches
  worker death and preemption, restarts with bounded jittered backoff
  (:class:`BackoffPolicy`), and resumes through ``train/checkpoint.py``'s
  resharding-on-restore.
- :class:`PreemptionGuard` / :class:`PreemptionHandler` turn the SIGTERM
  grace window into an async orbax save that overlaps the next training
  steps, then exit resumable (:class:`PreemptedError`).
- :class:`FaultPlan` is the seeded fault-injection harness behind
  ``--inject-faults`` (preemption signals, hard crashes, slow-host stalls,
  checkpoint corruption at configured steps) — the drill that
  ``tests/test_resilience.py`` and ``scripts/resilience_smoke.py`` run.
- :mod:`~jimm_tpu.resilience.elastic` closes the goodput loop:
  :func:`plan_data_axis` replans the mesh from surviving devices between
  attempts (restore lands on the new shape via resharding-on-restore) and
  :class:`GoodputAdvisor` adjusts checkpoint cadence / grace steps /
  scan unroll from the per-attempt goodput breakdown — bounded,
  hysteretic, and logged (``supervise --elastic`` / ``--adapt``).

Everything here is host-only (no jax import), so the supervisor can run on
a coordinator box with no accelerator stack. Restarts, lost work, and
grace saves all land in ``jimm_tpu.obs`` (``jimm_train_restarts_total``,
the ``preemption_save`` span, lost-work seconds in the goodput breakdown),
so resilience is measured, not assumed.
"""

from jimm_tpu.resilience.backoff import BackoffPolicy
from jimm_tpu.resilience.elastic import GoodputAdvisor, plan_data_axis
from jimm_tpu.resilience.faults import (Fault, FaultPlan,
                                        corrupt_latest_checkpoint)
from jimm_tpu.resilience.preemption import (PreemptedError, PreemptionGuard,
                                            PreemptionHandler)
from jimm_tpu.resilience.supervisor import (GiveUpError, Supervisor,
                                            note_checkpoint_completed)

__all__ = [
    "BackoffPolicy",
    "Fault",
    "FaultPlan",
    "GiveUpError",
    "GoodputAdvisor",
    "PreemptedError",
    "PreemptionGuard",
    "PreemptionHandler",
    "Supervisor",
    "corrupt_latest_checkpoint",
    "note_checkpoint_completed",
    "plan_data_axis",
]
