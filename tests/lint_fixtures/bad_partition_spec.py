"""JL004 fixture: PartitionSpec axis outside the canonical mesh vocabulary.

``"batch"`` is a *logical* axis name — putting it straight into a
PartitionSpec silently shards nothing on a {data, model, ...} mesh.
"""

from jax.sharding import PartitionSpec as P

SPEC = P("batch", None)  # line 9: JL004
GOOD = P("data", None)
