"""``jimm_tpu.serve.qos`` — multi-tenant QoS serving control plane.

A policy layer above the engine's replica-dispatch data plane: tenant
identity with token-bucket rate limits and quotas (:mod:`.policy`),
per-class weighted-fair (deficit-round-robin) dequeue with class-ordered
shedding (:mod:`.scheduler`), and multi-model residency on one topology
(:mod:`.pool`). Everything here is control plane: the hot compiled path —
buckets, AOT warm starts, replica executors — is untouched, and with no
policy configured the engine runs its original single-FIFO semantics
byte-for-byte. See ``docs/qos.md``.

``policy`` and ``cli`` are stdlib-only (no jax, no numpy) so the
``jimm-tpu qos`` CLI works from any process.
"""

from jimm_tpu.serve.qos.policy import (ClassSpec, QosPolicyError,
                                       TenantRegistry, TenantSpec,
                                       load_policy)
from jimm_tpu.serve.qos.pool import ModelPool
from jimm_tpu.serve.qos.scheduler import (QosScheduler, TokenBucket,
                                          WeightedFairQueue)

__all__ = [
    "ClassSpec", "ModelPool", "QosPolicyError", "QosScheduler",
    "TenantRegistry", "TenantSpec", "TokenBucket", "WeightedFairQueue",
    "load_policy",
]
