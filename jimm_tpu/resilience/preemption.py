"""Preemption-aware checkpointing: signal guard + grace-window async save.

TPU maintenance events deliver SIGTERM and then give the process a short
grace window before the hard kill. The guard turns that signal into a flag
the train loop polls; the handler turns the flag into an *async* orbax save
that overlaps the next ``grace_steps`` training steps (the save's d2h copy
happens up front, the write streams in the background), then flushes the
checkpoint's completion marker and exits resumable via
:class:`PreemptedError`. The supervisor catches that error, backs off, and
restarts with ``--resume``.
"""

from __future__ import annotations

import signal
import threading
import time

from jimm_tpu.obs.journal import get_journal, new_correlation_id

__all__ = ["PreemptedError", "PreemptionGuard", "PreemptionHandler"]


class PreemptedError(RuntimeError):
    """The run was preempted and its state committed at ``step``; a
    ``--resume`` rerun continues at ``step + 1``. ``lost_seconds`` is the
    wall time spent on grace-window steps whose results the restart
    discards (plus the final save flush) — the goodput ``lost_work``
    bucket carries the same number. ``cid`` is the flight-recorder
    correlation id minted at detection; the supervisor threads it through
    the restart so the whole preempt→save→restore→reshard chain shares
    one id in the journal."""

    def __init__(self, step: int, *, grace_steps: int = 0,
                 lost_seconds: float = 0.0, cid: str | None = None):
        super().__init__(f"preempted: state saved at step {step}; "
                         f"resume with --resume")
        self.step = step
        self.grace_steps = grace_steps
        self.lost_seconds = lost_seconds
        self.cid = cid


class PreemptionGuard:
    """Installs handlers for maintenance signals (default SIGTERM) that
    only set a flag — the train loop decides when to act on it, so the
    signal never interrupts a step or an in-flight orbax write mid-way.

    ``install`` snapshots and ``uninstall`` restores the previous handlers.
    Off the main thread (where ``signal.signal`` is unavailable) the guard
    degrades to :meth:`trigger`-only operation."""

    def __init__(self, signals: tuple[int, ...] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._event = threading.Event()
        self._previous: dict[int, object] = {}

    def install(self) -> "PreemptionGuard":
        try:
            for sig in self.signals:
                self._previous[sig] = signal.signal(sig, self._on_signal)
        except ValueError:  # not the main thread: trigger()-only mode
            self._previous.clear()
        return self

    def uninstall(self) -> None:
        for sig, previous in self._previous.items():
            signal.signal(sig, previous)
        self._previous.clear()

    def _on_signal(self, signum, frame) -> None:
        self.trigger()

    def trigger(self) -> None:
        """Mark the process preempted (signal handler / fault drill)."""
        self._event.set()

    @property
    def preempted(self) -> bool:
        return self._event.is_set()


class PreemptionHandler:
    """Drives the grace-window save from the train loop.

    Call :meth:`after_step` once per step, after the normal checkpoint
    block. On the first preempted step it starts a forced async save (or
    adopts the step's normal save when one just ran), keeps the loop
    training for ``grace_steps`` more steps while the write drains, then
    waits the save out, closes the manager (flushing the completion
    marker), and raises :class:`PreemptedError`. While draining,
    :attr:`draining` is True — the loop suppresses its normal per-step
    saves, since nothing after the grace save will be kept.
    """

    def __init__(self, guard: PreemptionGuard, ckpt, *, grace_steps: int = 1,
                 accounter=None, registry=None):
        if ckpt is None:
            raise ValueError("preemption saves need a CheckpointManager")
        self.guard = guard
        self.ckpt = ckpt
        self.grace_steps = max(0, grace_steps)
        self.accounter = accounter
        if registry is None:
            from jimm_tpu.obs import get_registry
            registry = get_registry("jimm_train")
        self.registry = registry
        self.save_step: int | None = None
        self._steps_after = 0
        self._t_detected: float | None = None
        #: incident correlation id, minted at detection (see PreemptedError)
        self.cid: str | None = None

    @property
    def draining(self) -> bool:
        """True once the grace save started — normal saves are pointless."""
        return self.save_step is not None

    def after_step(self, step: int, model, optimizer=None, *,
                   extra: dict | None = None,
                   already_saved: bool = False) -> None:
        """React to a pending preemption at the end of step ``step``.

        ``already_saved``: the loop's normal checkpoint block saved this
        exact step — its async write IS the grace save, skip the forced
        duplicate (orbax rejects a second save of the same step)."""
        if not self.guard.preempted:
            return
        if self.save_step is None:
            self._t_detected = time.monotonic()
            self.save_step = step
            self.cid = new_correlation_id()
            self.registry.counter("preemptions_total").inc()
            get_journal().emit("preempt_detected", cid=self.cid, step=step,
                               grace_steps=self.grace_steps)
            self._timed_save(step, model, optimizer, extra, already_saved)
            if self.grace_steps > 0:
                return  # overlap the async write with the next steps
        else:
            self._steps_after += 1
            if self._steps_after < self.grace_steps:
                return
        self._finish()

    def _timed_save(self, step, model, optimizer, extra,
                    already_saved) -> None:
        from jimm_tpu.obs import span
        t0 = time.perf_counter()
        with span("preemption_save"):
            if not already_saved:
                self.ckpt.save(step, model, optimizer, extra=extra,
                               force=True)
        dt = time.perf_counter() - t0
        if self.accounter is not None:
            self.accounter.add("preemption_save", dt)
        get_journal().emit("grace_save_started", cid=self.cid, step=step,
                           adopted=bool(already_saved), dur_s=round(dt, 6))

    def _finish(self) -> None:
        from jimm_tpu.obs import span
        t0 = time.perf_counter()
        with span("preemption_save"):
            self.ckpt.wait()
        dt = time.perf_counter() - t0
        if self.accounter is not None:
            self.accounter.add("preemption_save", dt)
        self.ckpt.close()  # flushes the completion marker
        lost = time.monotonic() - self._t_detected
        if self.accounter is not None:
            self.accounter.add("lost_work", lost)
        get_journal().emit("grace_save_committed", cid=self.cid,
                           step=self.save_step,
                           grace_steps=self._steps_after,
                           lost_s=round(lost, 4), dur_s=round(dt, 6))
        raise PreemptedError(self.save_step, grace_steps=self._steps_after,
                             lost_seconds=lost, cid=self.cid)
