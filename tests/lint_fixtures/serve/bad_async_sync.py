"""JL006 fixture: blocking host syncs on the serve event loop."""
import asyncio

import numpy as np


async def handle(engine, item):
    arr = np.asarray(item, np.float32)        # JL006: host copy on the loop
    out = await engine.submit(arr)
    out.block_until_ready()                   # JL006: device wait on the loop
    return float(out.item())                  # JL006: host sync on the loop


def pad_blocking(item):
    # ok: sync helper — the sanctioned home for host materialization
    return np.asarray(item, np.float32)


async def ok_path(engine, item):
    loop = asyncio.get_running_loop()
    # ok: the lambda runs on the executor, not the event loop
    return await loop.run_in_executor(None, lambda: np.asarray(item))
