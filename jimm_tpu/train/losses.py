"""Contrastive losses: CLIP softmax and SigLIP sigmoid, plus the ICI ring
implementation of the sigmoid all-pairs loss.

The reference has no training losses for its dual-tower models at all (only
the MNIST example's cross-entropy, ref `examples/vit_training.py:76`). The
north star (`BASELINE.json`) requires the SigLIP sigmoid all-pairs loss as an
ICI ring: text embeddings travel around the data-parallel ring via
``jax.lax.ppermute`` inside ``shard_map`` and each device accumulates its
local-images x traveling-texts chunk — the SigLIP paper's "chunked" algorithm
— so the full B x B logit matrix is never materialized on one chip.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def clip_softmax_loss(img: jax.Array, txt: jax.Array, logit_scale: jax.Array
                      ) -> jax.Array:
    """Symmetric InfoNCE over the global batch (CLIP). Under pjit with batch
    sharded over "data", XLA inserts the all-gathers for the full logits."""
    img = img / jnp.linalg.norm(img, axis=-1, keepdims=True)
    txt = txt / jnp.linalg.norm(txt, axis=-1, keepdims=True)
    logits = jnp.exp(logit_scale) * img @ txt.T
    labels = jnp.arange(logits.shape[0])
    li = optax_softmax_ce(logits, labels)
    lt = optax_softmax_ce(logits.T, labels)
    return (li + lt) / 2


def optax_softmax_ce(logits: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.mean(logp[jnp.arange(logits.shape[0]), labels])


def sigmoid_pairwise_loss(img: jax.Array, txt: jax.Array,
                          logit_scale: jax.Array, logit_bias: jax.Array
                          ) -> jax.Array:
    """Dense SigLIP sigmoid loss over the full batch — the numerical oracle
    for the ring version (and fine on a single chip).

    loss = -mean_i sum_j log sigmoid(z_ij * (scale * <img_i, txt_j> + bias)),
    z_ij = +1 on the diagonal, -1 elsewhere (SigLIP paper eq. 1).
    """
    img = img / jnp.linalg.norm(img, axis=-1, keepdims=True)
    txt = txt / jnp.linalg.norm(txt, axis=-1, keepdims=True)
    logits = jnp.exp(logit_scale) * img @ txt.T + logit_bias
    n = logits.shape[0]
    z = 2 * jnp.eye(n, dtype=logits.dtype) - 1
    return -jnp.sum(jax.nn.log_sigmoid(z * logits)) / n


def _ring_sigmoid_local(img: jax.Array, txt: jax.Array, scale: jax.Array,
                        bias: jax.Array, *, axis_name) -> jax.Array:
    """Per-device body: local images stay put; text chunks ride the ring.
    ``axis_name`` may be a tuple of mesh axes (e.g. ``("replica", "data")``
    on a hybrid DCN x ICI mesh) — the ring then runs over the linearized
    product axis."""
    n_dev = jax.lax.axis_size(axis_name)
    b = img.shape[0]
    img = img / jnp.linalg.norm(img, axis=-1, keepdims=True)
    txt = txt / jnp.linalg.norm(txt, axis=-1, keepdims=True)
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def chunk_loss(txt_chunk: jax.Array, positives: jax.Array) -> jax.Array:
        logits = jnp.exp(scale) * img @ txt_chunk.T + bias
        z = jnp.where(positives, 1.0, -1.0).astype(logits.dtype)
        return -jnp.sum(jax.nn.log_sigmoid(z * logits))

    def step(carry, _):
        txt_chunk, acc = carry
        # traveling chunks are all negatives (positives live in chunk 0,
        # handled outside the scan)
        txt_chunk = jax.lax.ppermute(txt_chunk, axis_name, perm)
        acc = acc + chunk_loss(txt_chunk, jnp.zeros((b, b), bool))
        return (txt_chunk, acc), None

    # own chunk first (diagonal positives), then n_dev-1 permute+accumulate
    # steps — no wasted final ppermute (same shape as ring_attention.py:72-75)
    total0 = chunk_loss(txt, jnp.eye(b, dtype=bool))
    (_, total), _ = jax.lax.scan(step, (txt, total0),
                                 jnp.arange(n_dev - 1))
    # average over the *global* batch like the dense reference
    total = jax.lax.psum(total, axis_name)
    return total / (b * n_dev)


def ring_sigmoid_loss(img: jax.Array, txt: jax.Array, logit_scale: jax.Array,
                      logit_bias: jax.Array, *, mesh: Mesh,
                      axis_name: str | tuple[str, ...] = "data") -> jax.Array:
    """SigLIP sigmoid loss over a batch sharded on ``axis_name``, computed as
    a ``ppermute`` ring so no device ever holds the global text batch or the
    full logit matrix. Differentiable end-to-end (``ppermute``'s transpose is
    the reverse permute, handled by JAX AD)."""
    fn = shard_map(
        partial(_ring_sigmoid_local, axis_name=axis_name),
        mesh=mesh,
        in_specs=(P(axis_name), P(axis_name), P(), P()),
        out_specs=P(),
        check_vma=False)
    return fn(img, txt, logit_scale, logit_bias)
