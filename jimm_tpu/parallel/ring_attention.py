"""Ring attention: exact attention over sequences sharded across devices.

Long-context sequence/context parallelism (absent from the reference — max
sequence there is 577 vision tokens, SURVEY §2.3). The sequence axis is
sharded over a mesh axis; each device keeps its local query block while
key/value blocks travel around the ring via ``jax.lax.ppermute``. Online
(flash-style) softmax accumulation in fp32 makes the result exact — identical
to full attention — while no device ever materializes the full sequence or
the full attention matrix. Differentiable end-to-end through the
``lax.scan``-of-``ppermute`` (JAX AD transposes the permutes).

Complements the Pallas flash kernel (`jimm_tpu/ops/flash_attention.py`):
flash blocks *within* a chip, the ring blocks *across* chips; compose them by
passing ``impl="flash"`` so each local block product uses the kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jimm_tpu.utils.compat import axis_size, shard_map
from jax.sharding import Mesh, PartitionSpec as P

NEG_INF = -1e30


# ---------------------------------------------------------------------------
# Zigzag sequence layout (causal load balancing)
# ---------------------------------------------------------------------------
#
# With contiguous sharding, causal ring attention is imbalanced: the device
# holding the LAST chunk attends every other chunk (works in all rounds)
# while the first-chunk device works only in its own round. The zigzag
# layout splits the sequence into 2*n_dev chunks and gives device i the pair
# (i, 2n-1-i): early-half work and late-half work cancel, so every device
# does ~2 half-chunk products per round — per-rank times balance.

def zigzag_order(seq_len: int, n_dev: int):
    """Permutation taking the natural sequence order to the zigzag layout:
    position block i of the output is chunk i followed by chunk 2n-1-i, so
    plain contiguous sharding over ``n_dev`` devices lands each device its
    zigzag pair. ``seq_len`` must divide into 2*n_dev chunks."""
    import numpy as onp
    if seq_len % (2 * n_dev):
        raise ValueError(f"seq_len {seq_len} not divisible by 2*{n_dev}")
    c = seq_len // (2 * n_dev)
    parts = []
    for i in range(n_dev):
        parts.append(onp.arange(i * c, (i + 1) * c))
        j = 2 * n_dev - 1 - i
        parts.append(onp.arange(j * c, (j + 1) * c))
    return onp.concatenate(parts)


def zigzag_shard(x: jax.Array, n_dev: int, axis: int = 1) -> jax.Array:
    """Reorder ``axis`` from natural to zigzag layout (see `zigzag_order`)."""
    return jnp.take(x, zigzag_order(x.shape[axis], n_dev), axis=axis)


def zigzag_unshard(x: jax.Array, n_dev: int, axis: int = 1) -> jax.Array:
    """Inverse of `zigzag_shard`."""
    import numpy as onp
    order = zigzag_order(x.shape[axis], n_dev)
    inverse = onp.argsort(order)
    return jnp.take(x, inverse, axis=axis)


def _positions(dev, local_len: int, n_dev: int, zigzag: bool) -> jax.Array:
    """Global sequence positions of a device's local chunk. ``dev`` may be a
    traced ``axis_index``."""
    if not zigzag:
        return dev * local_len + jnp.arange(local_len)
    if local_len % 2:
        raise ValueError("zigzag needs an even local sequence length")
    h = local_len // 2
    early = dev * h + jnp.arange(h)
    late = (2 * n_dev - 1 - dev) * h + jnp.arange(h)
    return jnp.concatenate([early, late])


def _block(q, k, v, mask):
    """One (q-block x kv-block) partial attention: returns unnormalized
    accumulator pieces (m, p_sum, pv) in fp32. Shapes (B, Sq, N, D)."""
    d = q.shape[-1]
    qf = q.astype(jnp.float32) / jnp.sqrt(d)
    s = jnp.einsum("bqnd,bknd->bnqk", qf, k.astype(jnp.float32))
    s = jnp.where(mask, s, NEG_INF)
    m = jnp.max(s, axis=-1)  # (B, N, Sq)
    p = jnp.exp(s - m[..., None])
    l = jnp.sum(p, axis=-1)
    pv = jnp.einsum("bnqk,bknd->bqnd", p, v.astype(jnp.float32))
    return m, l, pv


def _ring_local_flash(q, k, v, *, axis_name: str, causal: bool = False,
                      zigzag: bool = False):
    """Ring step where each local (q x kv-chunk) product is the Pallas flash
    kernel (`flash_attention_lse`); chunk results are merged by logsumexp
    reweighting.

    Causal decomposes per chunk pair (block-causal ring attention): the OWN
    chunk is a causal flash call (q/k positions align), chunks from EARLIER
    ring owners attend in full, and later owners' chunks are skipped
    entirely (``lax.cond`` keeps the carry) — no masked flops, and the skip
    halves the average work like the dense causal case.

    ``zigzag`` balances that skip across ranks (`zigzag_order` layout):
    each device holds the (i, 2n-1-i) chunk pair and every round runs
    exactly two half-chunk flash products regardless of rank, so the
    ppermute barrier no longer waits on the last-chunk straggler."""
    from jimm_tpu.ops.flash_attention import flash_attention_lse

    n_dev = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, n, d = q.shape
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    def merge(qh, k_cur, v_cur, lse, acc, *, is_causal=False):
        o_blk, lse_blk = flash_attention_lse(qh, k_cur, v_cur,
                                             is_causal=is_causal)
        lse_new = jnp.logaddexp(lse, lse_blk)
        w_old = jnp.exp(lse - lse_new).transpose(0, 2, 1)[..., None]
        w_blk = jnp.exp(lse_blk - lse_new).transpose(0, 2, 1)[..., None]
        return lse_new, acc * w_old + o_blk.astype(jnp.float32) * w_blk

    if causal and zigzag:
        return _ring_zigzag_causal_flash(q, k, v, merge, idx=idx,
                                         n_dev=n_dev, axis_name=axis_name,
                                         perm=perm)

    combine = partial(merge, q)

    # own chunk first (the only causal-masked pair), then n_dev-1
    # permute+combine steps — no wasted final permute
    lse0 = jnp.full((b, n, sq), NEG_INF, jnp.float32)
    acc0 = jnp.zeros((b, sq, n, d), jnp.float32)
    lse, acc = combine(k, v, lse0, acc0, is_causal=causal)

    def step(carry, j):
        k_cur, v_cur, lse, acc = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        if causal:
            src = (idx - j) % n_dev  # ring owner of this kv chunk
            lse, acc = jax.lax.cond(
                src < idx,  # strictly earlier positions: full attention
                lambda args: combine(k_cur, v_cur, *args),
                lambda args: args,
                (lse, acc))
        else:
            lse, acc = combine(k_cur, v_cur, lse, acc)
        return (k_cur, v_cur, lse, acc), None

    (_, _, _, acc), _ = jax.lax.scan(step, (k, v, lse, acc),
                                     jnp.arange(1, n_dev))
    return acc.astype(q.dtype)


def _ring_zigzag_causal_flash(q, k, v, merge, *, idx, n_dev, axis_name, perm):
    """Causal flash ring in the zigzag layout. Local chunks are the halves
    (early e at global chunk ``idx``, late l at ``2n-1-idx``). Chunk-level
    causality per (q half, kv half) pair:

    - own round: e<-e causal, l<-l causal, l<-e full (e<-l impossible);
    - kv from earlier rank s<i: e<-e full, l<-e full (both kv_l skipped:
      pos 2n-1-s > 2n-1-i = pos(q_l) and > i = pos(q_e));
    - kv from later rank s>i: l<-e full, l<-l full (q_e sees nothing).

    Every branch is two half-products -> balanced per-rank work."""
    b, sq, n, d = q.shape
    if sq % 2:
        raise ValueError("zigzag needs an even local sequence length")
    h = sq // 2

    def halves(x):
        return x[:, :h], x[:, h:]

    q_e, q_l = halves(q)
    lse0 = jnp.full((b, n, h), NEG_INF, jnp.float32)
    acc0 = jnp.zeros((b, h, n, d), jnp.float32)

    k_e, v_e = k[:, :h], v[:, :h]
    k_l, v_l = k[:, h:], v[:, h:]
    lse_e, acc_e = merge(q_e, k_e, v_e, lse0, acc0, is_causal=True)
    lse_l, acc_l = merge(q_l, k_l, v_l, lse0, acc0, is_causal=True)
    lse_l, acc_l = merge(q_l, k_e, v_e, lse_l, acc_l)

    def step(carry, j):
        k_cur, v_cur, lse_e, acc_e, lse_l, acc_l = carry
        k_cur = jax.lax.ppermute(k_cur, axis_name, perm)
        v_cur = jax.lax.ppermute(v_cur, axis_name, perm)
        k_e, v_e = k_cur[:, :h], v_cur[:, :h]
        k_l, v_l = k_cur[:, h:], v_cur[:, h:]
        src = (idx - j) % n_dev

        def from_earlier(args):
            lse_e, acc_e, lse_l, acc_l = args
            lse_e, acc_e = merge(q_e, k_e, v_e, lse_e, acc_e)
            lse_l, acc_l = merge(q_l, k_e, v_e, lse_l, acc_l)
            return lse_e, acc_e, lse_l, acc_l

        def from_later(args):
            lse_e, acc_e, lse_l, acc_l = args
            lse_l, acc_l = merge(q_l, k_e, v_e, lse_l, acc_l)
            lse_l, acc_l = merge(q_l, k_l, v_l, lse_l, acc_l)
            return lse_e, acc_e, lse_l, acc_l

        lse_e, acc_e, lse_l, acc_l = jax.lax.cond(
            src < idx, from_earlier, from_later,
            (lse_e, acc_e, lse_l, acc_l))
        return (k_cur, v_cur, lse_e, acc_e, lse_l, acc_l), None

    (_, _, _, acc_e, _, acc_l), _ = jax.lax.scan(
        step, (k, v, lse_e, acc_e, lse_l, acc_l), jnp.arange(1, n_dev))
    return jnp.concatenate([acc_e, acc_l], axis=1).astype(q.dtype)


def _ring_local(q, k, v, *, axis_name: str, causal: bool,
                zigzag: bool = False):
    n_dev = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, n, d = q.shape
    sk = k.shape[1]

    q_pos = _positions(idx, sq, n_dev, zigzag)

    def combine(j, k_cur, v_cur, m, l, acc):
        src = (idx - j) % n_dev  # ring owner of the current kv chunk
        k_pos = _positions(src, sk, n_dev, zigzag)
        mask = jnp.ones((sq, sk), bool)
        if causal:
            mask = k_pos[None, :] <= q_pos[:, None]
        m_blk, l_blk, pv_blk = _block(q, k_cur, v_cur,
                                      mask[None, None])  # (B,N,Sq[,D])
        m_new = jnp.maximum(m, m_blk)
        c_old = jnp.exp(m - m_new)
        c_blk = jnp.exp(m_blk - m_new)
        l_new = l * c_old + l_blk * c_blk
        acc_new = (acc * c_old.transpose(0, 2, 1)[..., None]
                   + pv_blk * c_blk.transpose(0, 2, 1)[..., None])
        return m_new, l_new, acc_new

    def step(carry, j):
        k_cur, v_cur, m, l, acc = carry
        m, l, acc = combine(j, k_cur, v_cur, m, l, acc)
        perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        k_nxt = jax.lax.ppermute(k_cur, axis_name, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis_name, perm)
        return (k_nxt, v_nxt, m, l, acc), None

    m0 = jnp.full((b, n, sq), NEG_INF, jnp.float32)
    l0 = jnp.zeros((b, n, sq), jnp.float32)
    acc0 = jnp.zeros((b, sq, n, d), jnp.float32)
    # n_dev-1 permuting steps, then the final chunk without the last permute
    (k, v, m, l, acc), _ = jax.lax.scan(step, (k, v, m0, l0, acc0),
                                        jnp.arange(n_dev - 1))
    m, l, acc = combine(n_dev - 1, k, v, m, l, acc)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 2, 1)[..., None]
    return out.astype(q.dtype)


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   mesh: Mesh | None = None, axis_name: str = "seq",
                   is_causal: bool = False, impl: str = "einsum",
                   zigzag: bool = False) -> jax.Array:
    """Exact attention over ``(B, S, N, D)`` q/k/v whose sequence dim is
    sharded over ``axis_name``. Equals full (unsharded) attention to fp32
    accuracy.

    ``mesh=None`` uses the ambient mesh installed by
    ``jimm_tpu.parallel.use_sharding`` / ``jax.set_mesh``.

    ``impl="flash"`` runs each local (q x kv-chunk) product through the
    Pallas flash kernel and merges chunks by logsumexp reweighting — flash
    blocks within the chip, the ring blocks across chips; causal runs
    block-causally (own chunk causal, earlier chunks full, later skipped).
    ``impl="auto"`` picks flash on TPU, einsum otherwise.

    ``zigzag=True`` expects inputs (and produces outputs) in the
    `zigzag_order` sequence layout, which balances the causal skip across
    ranks (the contiguous layout leaves the last rank working every round).
    Use `zigzag_shard` / `zigzag_unshard` at the pipeline boundary — inside
    the model nothing changes because attention is permutation-covariant in
    sequence once positions are accounted for.
    """
    from jimm_tpu.parallel.mesh import resolve_mesh_axis
    # Works both outside and inside jit: the abstract mesh mirrors the
    # ambient concrete mesh installed by use_sharding/jax.set_mesh, and
    # shard_map binds the concrete one itself when no mesh is passed.
    shape = resolve_mesh_axis(mesh, axis_name)
    if impl == "auto":
        # Same shape gate as dot_product_attention's auto path: the Pallas
        # kernel is validated for head_dim 64/128/256 and per-chip chunks
        # worth blocking; everything else takes the einsum path.
        local_seq = q.shape[1] // shape[axis_name]
        flash_ok = (jax.default_backend() == "tpu"
                    and q.shape[-1] in (64, 128, 256) and local_seq >= 128)
        impl = "flash" if flash_ok else "einsum"
    if impl == "flash":
        local = partial(_ring_local_flash, axis_name=axis_name,
                        causal=is_causal, zigzag=zigzag)
    elif impl == "einsum":
        local = partial(_ring_local, axis_name=axis_name, causal=is_causal,
                        zigzag=zigzag)
    else:
        raise ValueError(f"unknown ring attention impl {impl!r}")
    kwargs = {} if mesh is None else {"mesh": mesh}  # None -> ambient mesh
    fn = shard_map(
        local,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name)),
        out_specs=P(None, axis_name),
        check_vma=False, **kwargs)
    return fn(q, k, v)
