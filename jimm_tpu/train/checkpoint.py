"""Orbax-based sharded checkpoint save/restore — the reference is load-only
(SURVEY §5): no save path, no optimizer state, no resume.

Saves the full training state (model params + optimizer state + step) with
async, sharded orbax writes; restores onto the *current* mesh sharding (so a
run can resume on a different topology). HF-interoperable safetensors export
lives in `jimm_tpu/weights/export.py`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import numpy as np
import orbax.checkpoint as ocp
from flax import nnx

import jimm_tpu.utils.compat  # noqa: F401  (nnx backfills: to_flat_state, set_value)


def _split_state(obj) -> Any:
    return nnx.state(obj)


def _storage_layout(model: nnx.Module) -> dict[str, Any] | None:
    """Fingerprint of any baked pipeline placement (`nn/transformer.py`
    pp_stages): layer rows are stored in circular schedule order, so a
    restore into a DIFFERENT placement would permute layers silently —
    shapes all match. Recorded at save, validated at restore."""
    cfg = getattr(model, "config", None)
    if cfg is None:
        return None
    layout: dict[str, Any] = {}
    for tower in ("vision", "text"):
        t = getattr(cfg, tower, None)
        if (t is not None and getattr(t, "pipeline", False)
                and t.pp_virtual > 1 and t.pp_stages):
            layout[tower] = {"pp_stages": t.pp_stages,
                             "pp_virtual": t.pp_virtual, "depth": t.depth}
    return layout or None


def _relayout(state, saved: dict | None, current: dict | None):
    """Re-permute stacked layer rows from a checkpoint's baked pipeline
    placement to the target model's (either may be canonical=None). Applies
    to every leaf under a tower's ``blocks`` whose leading dim is the layer
    count — model params and mirrored optimizer moments alike."""
    from jimm_tpu.parallel.pipeline import circular_layer_order

    perms: dict[str, np.ndarray] = {}
    for tower in ("vision", "text"):
        s = (saved or {}).get(tower)
        c = (current or {}).get(tower)
        if s == c:
            continue
        if s and c and s["depth"] != c["depth"]:
            raise ValueError(f"{tower} depth changed between checkpoint "
                             f"({s['depth']}) and model ({c['depth']})")
        depth = (s or c)["depth"]

        def order(layout):
            if not layout:
                return np.arange(depth)
            return circular_layer_order(depth, layout["pp_stages"],
                                        layout["pp_virtual"])

        o_saved, o_cur = order(s), order(c)
        inv_saved = np.empty(depth, np.int64)
        inv_saved[o_saved] = np.arange(depth)
        perm = inv_saved[o_cur]  # saved-storage -> canonical -> cur-storage
        if not np.array_equal(perm, np.arange(depth)):
            perms[tower] = perm
    if not perms:
        return state

    out = []
    for path, leaf in nnx.to_flat_state(state):
        keys = tuple(str(k) for k in path)
        tower = next((t for t in perms if t in keys), None)
        if tower is not None and "blocks" in keys:
            perm = perms[tower]
            # get_value(): flax 0.12 deprecates .value access on Variables
            val = (leaf.get_value() if hasattr(leaf, "get_value")
                   else leaf)
            if getattr(val, "ndim", 0) >= 1 and val.shape[0] == len(perm):
                new = val[perm]
                if getattr(val, "sharding", None) is not None:
                    # the gather's output sharding is XLA's choice; pin it
                    # back so restore keeps its onto-current-sharding
                    # contract (stage-sharded pipelined params especially)
                    import jax
                    new = jax.device_put(new, val.sharding)
                leaf = leaf.replace(new) if hasattr(leaf, "replace") else new
        out.append((path, leaf))
    return nnx.from_flat_state(out)


class CheckpointManager:
    """Thin nnx-aware wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True))
        #: user-supplied ``extra`` metadata of the last restored step
        #: (e.g. the grain data-iterator state) — populated by `restore`
        self.last_restored_extra: dict[str, Any] = {}

    def save(self, step: int, model: nnx.Module,
             optimizer: nnx.Optimizer | None = None, *,
             extra: dict[str, Any] | None = None, force: bool = False) -> bool:
        """Async-save model (+ optimizer) state at ``step``."""
        from jimm_tpu.obs import get_registry, span
        with span("checkpoint_save"):
            items: dict[str, Any] = {
                "model": ocp.args.StandardSave(nnx.state(model, nnx.Param))}
            if optimizer is not None:
                items["opt"] = ocp.args.StandardSave(
                    nnx.state(optimizer, nnx.optimizer.OptState))
            meta = dict(extra or {})
            layout = _storage_layout(model)
            if layout is not None:
                meta["_storage_layout"] = layout
            if meta:
                items["extra"] = ocp.args.JsonSave(meta)
            saved = self._mgr.save(step, args=ocp.args.Composite(**items),
                                   force=force)
        if saved:
            get_registry("jimm_train").counter("checkpoint_saves_total").inc()
        return saved

    def restore(self, model: nnx.Module,
                optimizer: nnx.Optimizer | None = None,
                *, step: int | None = None) -> int:
        """Restore in place (onto each param's current sharding); returns the
        restored step.

        Baked pipeline placement (`nn/transformer.py` pp_stages) stores
        layer rows in circular schedule order. When the checkpoint's layout
        differs from the model's, the stacked layer arrays are re-permuted
        through canonical order (saved-storage -> canonical -> current-
        storage), so a pipelined run can be evaluated or fine-tuned with any
        other placement — including none."""
        from jimm_tpu.obs import get_registry, span
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        get_registry("jimm_train").counter("checkpoint_restores_total").inc()
        with span("checkpoint_restore"):
            model_state = nnx.state(model, nnx.Param)
            items: dict[str, Any] = {
                "model": ocp.args.StandardRestore(model_state)}
            if optimizer is not None:
                items["opt"] = ocp.args.StandardRestore(
                    nnx.state(optimizer, nnx.optimizer.OptState))
            # probe for the optional extra/ item by its committed directory
            # (the manager uses default step naming) instead of
            # catch-and-retry: a corrupt/unreadable extra must FAIL the
            # restore, not silently skip the placement guard below, and a
            # genuine model-state error must not trigger a pointless second
            # multi-GB restore attempt
            has_extra = (self._mgr.directory / str(step) / "extra").exists()
            if has_extra:
                items["extra"] = ocp.args.JsonRestore()
            restored = self._mgr.restore(step,
                                         args=ocp.args.Composite(**items))
            saved_meta = (restored.get("extra") or {}) if has_extra else {}
            self.last_restored_extra = {k: v for k, v in saved_meta.items()
                                        if k != "_storage_layout"}
            saved = saved_meta.get("_storage_layout")
            current = _storage_layout(model)
            model_state = restored["model"]
            opt_state = restored.get("opt")
            if saved != current:
                model_state = _relayout(model_state, saved, current)
                if opt_state is not None:
                    # optimizer moments live under opt.model mirroring the
                    # param tree; same stacked rows, same re-permutation
                    opt_state = _relayout(opt_state, saved, current)
            nnx.update(model, model_state)
            if optimizer is not None:
                nnx.update(optimizer, opt_state)
        return step

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
