"""CI tier-1 smoke for the int8 low-precision serving fast path.

Two phases, end to end on CPU (interpret-mode Pallas int8 kernels):

1. **Parity**: ``scripts.quant_parity`` on the CPU-tiny CLIP preset must
   hold the acceptance floor — per-image cosine >= 0.999 against the f32
   twin and synthetic zero-shot top-1 agreement >= 0.99.
2. **Serve, two lives**: an int8-quantized model behind the store-backed
   AOT forward. Life 1 starts against an EMPTY tmp store: bucket warmup
   compiles each bucket once (write-through exports them), and a mixed
   stream of request sizes afterwards must add ZERO fresh traces. Life 2
   is a fresh forward + engine (what a process restart gets) against the
   now-warm store: every bucket must source ``"aot"``, the compile gauge
   must stay 0, and one answered request must match the live quantized
   model. The AOT key must also carry the mixed ``float32+int8`` param
   dtype so int8 artifacts can never be adopted by an f32 serve.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.quant_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

COSINE_FLOOR = 0.999
TOP1_FLOOR = 0.99


def fail(msg: str) -> int:
    print(json.dumps({"metric": "quant_smoke", "value": 0.0, "error": msg}),
          flush=True)
    return 1


def run_parity() -> dict:
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable, "-m", "scripts.quant_parity", "--preset", "tiny"],
        env=env, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"quant_parity failed: {proc.stderr[-1500:]}")
    return json.loads(proc.stdout.strip().splitlines()[-1])


def main() -> int:
    # --- phase A: measured parity on the tiny preset ----------------------
    parity = run_parity()
    if parity["cosine_min"] < COSINE_FLOOR:
        return fail(f"cosine_min {parity['cosine_min']} < {COSINE_FLOOR}")
    if parity["top1_agreement"] < TOP1_FLOOR:
        return fail(f"top1_agreement {parity['top1_agreement']} "
                    f"< {TOP1_FLOOR}")

    # --- phase B: int8 serve, two lives over one store --------------------
    import asyncio

    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.aot.warmup import AotForward
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.quant import quantize_model
    from jimm_tpu.serve import BucketTable, InferenceEngine

    buckets = (1, 2)
    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    model = CLIP(cfg, rngs=nnx.Rngs(0))
    quantize_model(model)
    size = cfg.vision.image_size
    rng = np.random.RandomState(0)

    async def drive(engine, items):
        await engine.start()
        try:
            return [np.asarray(r) for r in await asyncio.gather(
                *[engine.submit(x) for x in items])]
        finally:
            await engine.stop()

    with tempfile.TemporaryDirectory(prefix="jimm-quant-smoke-") as root:
        store = ArtifactStore(root)

        # --- life 1: empty store, warmup compiles once, then zero --------
        fwd1 = AotForward(model, method="encode_image",
                          item_shape=(size, size, 3), store=store,
                          label="quant_smoke:int8")
        pd = fwd1.key_for(1).describe()["param_dtype"]
        if "int8" not in pd or "float32" not in pd:
            return fail(f"quantized param_dtype fingerprint is {pd!r}; an "
                        f"f32 serve could adopt int8 artifacts")
        eng1 = InferenceEngine(fwd1, item_shape=(size, size, 3),
                               buckets=BucketTable(buckets, dtype="int8"),
                               max_delay_ms=2.0,
                               trace_count=fwd1.trace_count)
        eng1.warmup_blocking()
        warm_traces = fwd1.trace_count()
        items = [rng.randn(size, size, 3).astype(np.float32)
                 for _ in range(5)]
        asyncio.run(drive(eng1, items))
        post = fwd1.trace_count() - warm_traces
        if post != 0:
            return fail(f"life 1 paid {post} post-warmup recompile(s)")

        # --- life 2: fresh forward/engine, fully store-sourced -----------
        fwd2 = AotForward(model, method="encode_image",
                          item_shape=(size, size, 3), store=store,
                          label="quant_smoke:int8")
        eng2 = InferenceEngine(fwd2, item_shape=(size, size, 3),
                               buckets=BucketTable(buckets, dtype="int8"),
                               max_delay_ms=2.0,
                               trace_count=fwd2.trace_count)
        eng2.warmup_blocking()
        sources = {b: r["source"] for b, r in eng2.warmup_report.items()}
        if sources != {b: "aot" for b in buckets}:
            return fail(f"warm restart not fully AOT-sourced: {sources}")
        if eng2.metrics.snapshot()["compile_count"] != 0:
            return fail(f"warm restart paid "
                        f"{eng2.metrics.snapshot()['compile_count']} "
                        f"fresh compiles")
        got = asyncio.run(drive(eng2, items[:1]))[0]
        want = np.asarray(model.encode_image(items[0][None]))[0]
        if not np.allclose(got, want, rtol=1e-4, atol=1e-4):
            return fail("AOT-loaded int8 forward disagrees with the live "
                        "quantized model")
        if fwd2.trace_count() != 0:
            return fail(f"warm restart traced {fwd2.trace_count()} times")

    print(json.dumps({"metric": "quant_smoke", "value": 1.0,
                      "cosine_min": parity["cosine_min"],
                      "top1_agreement": parity["top1_agreement"],
                      "layers_quantized": parity["layers_quantized"],
                      "param_dtype": pd,
                      "buckets": list(buckets),
                      "life2_sources": sources}), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
