"""`evaluate` CLI: single-pass metrics over tfrecord datasets."""

import json

import numpy as np
import pytest

from jimm_tpu.cli import main
from jimm_tpu.data.records import (write_classification_records,
                                   write_image_text_records)

from hf_util import save_tiny_siglip, save_tiny_vit


def test_evaluate_vit_hf_ckpt(tmp_path, rng, capsys):
    ckpt = save_tiny_vit(tmp_path / "ckpt")  # 7 classes, 48px
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8), i % 7)
             for i in range(8)]
    write_classification_records(tmp_path / "d.tfrecord", pairs,
                                 encoding="raw")
    rc = main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
               "--batch-size", "4", "--ckpt", str(ckpt), "--model", "vit",
               "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 8
    assert 0.0 <= out["top1_accuracy"] <= 1.0


def test_evaluate_siglip_retrieval(tmp_path, rng, capsys):
    ckpt = save_tiny_siglip(tmp_path / "ckpt")
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8),
              [i + 1, i + 2]) for i in range(6)]
    write_image_text_records(tmp_path / "d.tfrecord", pairs, encoding="raw")
    rc = main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
               "--batch-size", "3", "--ckpt", str(ckpt),
               "--model", "siglip", "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 6
    for k in ("retrieval_r1_image_to_text", "retrieval_r1_text_to_image"):
        assert 0.0 <= out[k] <= 1.0


def test_evaluate_trained_orbax_ckpt(tmp_path, rng, capsys):
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8), i % 4)
             for i in range(8)]
    write_classification_records(tmp_path / "d.tfrecord", pairs,
                                 encoding="raw")
    ck = tmp_path / "run"
    assert main(["train", "--preset", "vit-base-patch16-224", "--tiny",
                 "--steps", "2", "--batch-size", "4", "--platform", "cpu",
                 "--data", str(tmp_path / "d.tfrecord"), "--num-classes", "4",
                 "--ckpt-dir", str(ck), "--save-every", "1"]) == 0
    rc = main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
               "--batch-size", "4", "--preset", "vit-base-patch16-224",
               "--tiny", "--ckpt-dir", str(ck), "--num-classes", "4",
               "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 8


def test_evaluate_counts_trailing_remainder(tmp_path, rng, capsys):
    """10 examples at batch 4: the short final batch of 2 must be counted,
    not silently dropped (training pipelines drop it; eval must not)."""
    ckpt = save_tiny_vit(tmp_path / "ckpt")
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8), i % 7)
             for i in range(10)]
    write_classification_records(tmp_path / "d.tfrecord", pairs,
                                 encoding="raw")
    rc = main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
               "--batch-size", "4", "--ckpt", str(ckpt), "--model", "vit",
               "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 10


def test_evaluate_requires_weights_source(tmp_path):
    with pytest.raises(SystemExit, match="ckpt"):
        main(["evaluate", "--data", str(tmp_path), "--platform", "cpu"])


def test_evaluate_zero_shot(tmp_path, rng, capsys):
    """--zero-shot: ensemble weights from a tokens file, accuracy over
    labeled records, class order from the dataset's classes.json."""
    ckpt = save_tiny_siglip(tmp_path / "ckpt")
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8), i % 3)
             for i in range(6)]
    write_classification_records(tmp_path / "d.tfrecord", pairs,
                                 encoding="raw")
    # classes.json defines label-id order; tokens file is deliberately in a
    # DIFFERENT order to prove the dataset order wins
    (tmp_path / "classes.json").write_text(json.dumps(["ant", "bee", "fly"]))
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({
        "fly": [[5, 6], [7, 8]],       # 2-template ensemble
        "ant": [1, 2],                 # single row
        "bee": [[3, 4]],
    }))
    rc = main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
               "--batch-size", "4", "--ckpt", str(ckpt), "--model", "siglip",
               "--zero-shot", str(tokens), "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 6
    assert out["classes"] == 3
    assert out["prompts"] == 4
    assert 0.0 <= out["zero_shot_top1"] <= 1.0


def test_evaluate_zero_shot_rejects_vit(tmp_path, rng):
    ckpt = save_tiny_vit(tmp_path / "ckpt")
    pairs = [(rng.randint(0, 255, size=(16, 16, 3)).astype(np.uint8), 0)]
    write_classification_records(tmp_path / "d.tfrecord", pairs,
                                 encoding="raw")
    tokens = tmp_path / "tokens.json"
    tokens.write_text(json.dumps({"x": [1]}))
    with pytest.raises(SystemExit, match="contrastive"):
        main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
              "--ckpt", str(ckpt), "--model", "vit",
              "--zero-shot", str(tokens), "--platform", "cpu"])


def test_evaluate_naflex_retrieval(tmp_path, rng, capsys):
    """--naflex: retrieval over mixed-size images, aspect preserved."""
    from hf_util import save_tiny_siglip2
    ckpt = save_tiny_siglip2(tmp_path / "ckpt")
    pairs = []
    for i, (h, w) in enumerate([(16, 48), (32, 32), (48, 16), (16, 32)]):
        pairs.append((rng.randint(0, 255, size=(h, w, 3)).astype(np.uint8),
                      [i + 1, i + 2]))
    write_image_text_records(tmp_path / "d.tfrecord", pairs, encoding="raw")
    rc = main(["evaluate", "--data", str(tmp_path / "d.tfrecord"),
               "--batch-size", "2", "--ckpt", str(ckpt), "--model", "siglip",
               "--naflex", "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 4
    for k in ("retrieval_r1_image_to_text", "retrieval_r1_text_to_image"):
        assert 0.0 <= out[k] <= 1.0
