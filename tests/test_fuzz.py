"""Property-based fuzz: the zero-dep TFRecord/Example codec round-trips
arbitrary features, and the built-in CLIP tokenizer matches the transformers
oracle on arbitrary text (not just the hand-picked prompts)."""

import numpy as np
import pytest

pytest.importorskip("hypothesis", reason="property-based tests need the "
                                         "hypothesis package")
from hypothesis import given, settings, strategies as st  # noqa: E402

from jimm_tpu.data.tfrecord import (decode_example, encode_example,
                                    read_tfrecord, write_tfrecord)

# keep runtimes sane on the 1-core CI box
FUZZ = settings(max_examples=50, deadline=None)

feature_values = st.one_of(
    st.binary(min_size=0, max_size=64),
    st.integers(min_value=-(2 ** 62), max_value=2 ** 62),
    st.lists(st.integers(min_value=-(2 ** 30), max_value=2 ** 30),
             min_size=1, max_size=8),
    st.lists(st.floats(width=32, allow_nan=False, allow_infinity=False),
             min_size=1, max_size=8),
)
examples = st.dictionaries(
    st.text(alphabet=st.characters(codec="ascii", min_codepoint=33,
                                   max_codepoint=126), min_size=1,
            max_size=12),
    feature_values, min_size=1, max_size=5)


@FUZZ
@given(examples)
def test_example_roundtrip(features):
    decoded = decode_example(encode_example(features))
    for k, v in features.items():
        got = decoded[k]
        if isinstance(v, bytes):
            assert got == [v]
        elif isinstance(v, int):
            assert got == [v]
        elif v and isinstance(v[0], float):
            np.testing.assert_allclose(got, np.asarray(v, np.float32),
                                       rtol=1e-6)
        else:
            assert got == list(v)


@FUZZ
@given(st.lists(st.binary(min_size=0, max_size=200), min_size=1,
                max_size=10))
def test_tfrecord_framing_roundtrip(payloads):
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = d + "/f.tfrecord"
        write_tfrecord(p, payloads)
        assert list(read_tfrecord(p, verify=True)) == payloads


# ---------------------------------------------------------------------------
# tokenizer parity fuzz (needs the transformers oracle)
# ---------------------------------------------------------------------------

transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def tokenizers(clip_vocab_dir):
    from jimm_tpu.data.clip_tokenizer import CLIPTokenizer
    d = clip_vocab_dir
    ours = CLIPTokenizer.from_dir(d)
    oracle = transformers.CLIPTokenizer(str(d / "vocab.json"),
                                        str(d / "merges.txt"))
    if oracle.fix_text is not None:
        # with ftfy installed the oracle switches to a different
        # preprocessing path (no CJK spacing); parity targets the no-ftfy
        # BasicTokenizer path this environment uses
        pytest.skip("transformers oracle is using ftfy preprocessing")
    return ours, oracle


@FUZZ
@given(st.text(alphabet=st.characters(codec="utf-8"), max_size=40))
def test_tokenizer_matches_oracle_on_arbitrary_text(tokenizers, text):
    # full unicode incl. control chars, combining marks, CJK: the built-in
    # tokenizer mirrors the oracle's no-ftfy preprocessing exactly
    ours, oracle = tokenizers
    assert ours.encode(text) == oracle(text)["input_ids"], repr(text)
