"""Batch shape buckets — the server-side half of the JLT103 discipline.

A jitted forward compiles one executable per input shape. A server that
dispatches whatever batch size the traffic happens to produce compiles an
unbounded family of programs (cache-key churn, multi-second stalls mid-
traffic). The fix is the same one the linter's JLT103 trace check certifies
from the model side: declare a small, fixed set of batch buckets up front,
pad every micro-batch up to the nearest bucket, and warm-compile each bucket
once at startup. After warmup the engine never sees a new shape.

``scripts/inference_bench.py`` reads the same table, so the bench times the
exact compiled programs the server dispatches.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np

#: CPU-smoke bucket set: small enough that warmup is a few tiny compiles.
DEFAULT_BATCH_BUCKETS: tuple[int, ...] = (1, 2, 4, 8)

#: TPU bucket set: powers of two up to the single-chip throughput batch the
#: inference bench tracks (256 is BASELINE's inference batch).
TPU_BATCH_BUCKETS: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)

#: NaFlex token-sequence buckets: variable-resolution batches pad their
#: patch sequences to the nearest bucket, so the NaFlex forward compiles
#: one program per (batch bucket, seq bucket) pair instead of one per
#: traffic-dependent grid. 256 = a 16x16 patch grid, 576 = 24x24 (the
#: SigLIP2 NaFlex training default), 1024 = 32x32. Padding is carried by
#: the key mask, which the attention dispatch runs on the masked flash
#: variant — mask CONTENTS are runtime data, so every real-token count
#: shares the bucket's one executable.
DEFAULT_NAFLEX_SEQ_BUCKETS: tuple[int, ...] = (256, 576, 1024)

#: precisions a serving stack can declare. The dtype names the precision
#: the warm-compiled forwards COMPUTE in — batch assembly stays fp32
#: images; "int8" means quantized weights + dynamic int8 activations
#: inside the Pallas kernels (docs/quantization.md).
SERVE_DTYPES: tuple[str, ...] = ("float32", "bfloat16", "int8")


@dataclasses.dataclass(frozen=True)
class BucketTable:
    """An ascending, de-duplicated set of allowed batch sizes, tagged with
    the serving precision. The dtype rides the table (not the engine)
    because it is part of the same compile-shape contract: one warm
    executable per (bucket, dtype), and MEASUREMENTS rows / ready lines
    report both axes."""

    sizes: tuple[int, ...]
    dtype: str = "float32"

    def __post_init__(self) -> None:
        sizes = tuple(sorted(set(int(s) for s in self.sizes)))
        if not sizes or sizes[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1, got {self.sizes}")
        object.__setattr__(self, "sizes", sizes)
        if self.dtype not in SERVE_DTYPES:
            raise ValueError(f"unknown serve dtype {self.dtype!r}; "
                             f"known: {SERVE_DTYPES}")

    @property
    def max_size(self) -> int:
        return self.sizes[-1]

    def select(self, n: int) -> int | None:
        """Smallest bucket holding ``n`` items (None when ``n`` exceeds the
        largest bucket — the caller splits or rejects)."""
        if n < 1:
            raise ValueError(f"need n >= 1, got {n}")
        for size in self.sizes:
            if size >= n:
                return size
        return None

    def shed(self, n: int) -> int:
        """Largest bucket not exceeding ``n`` — the graceful-degradation
        choice: dispatch a full smaller bucket now instead of waiting to
        fill a bigger one."""
        if n < 1:
            raise ValueError(f"need n >= 1, got {n}")
        best = self.sizes[0]
        for size in self.sizes:
            if size <= n:
                best = size
        return best


def pad_batch(rows: Sequence[np.ndarray], bucket: int) -> np.ndarray:
    """Stack ``rows`` (identical shapes/dtypes) and zero-pad the batch axis
    up to ``bucket``. Rows beyond ``len(rows)`` are padding; the engine
    slices them off the output before completing futures."""
    if not rows:
        raise ValueError("empty batch")
    if len(rows) > bucket:
        raise ValueError(f"{len(rows)} rows do not fit bucket {bucket}")
    stacked = np.stack(rows)
    if len(rows) == bucket:
        return stacked
    pad = np.zeros((bucket - len(rows),) + stacked.shape[1:], stacked.dtype)
    return np.concatenate([stacked, pad])


def default_buckets(platform: str | None = None,
                    dtype: str = "float32") -> BucketTable:
    """The platform's declared bucket table at the given serving precision.
    ``platform`` defaults to the active JAX backend; resolving it lazily
    keeps this module importable without initializing a backend."""
    if platform is None:
        import jax
        platform = jax.default_backend()
    return BucketTable(TPU_BATCH_BUCKETS if platform == "tpu"
                       else DEFAULT_BATCH_BUCKETS, dtype=dtype)
