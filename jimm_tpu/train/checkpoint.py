"""Orbax-based sharded checkpoint save/restore — the reference is load-only
(SURVEY §5): no save path, no optimizer state, no resume.

Saves the full training state (model params + optimizer state + step) with
async, sharded orbax writes; restores onto the *current* mesh sharding (so a
run can resume on a different topology). HF-interoperable safetensors export
lives in `jimm_tpu/weights/export.py`.
"""

from __future__ import annotations

from pathlib import Path
from typing import Any

import jax
import numpy as np
import orbax.checkpoint as ocp
from flax import nnx


def _split_state(obj) -> Any:
    return nnx.state(obj)


class CheckpointManager:
    """Thin nnx-aware wrapper over ``orbax.checkpoint.CheckpointManager``."""

    def __init__(self, directory: str | Path, *, max_to_keep: int = 3,
                 save_interval_steps: int = 1):
        self._mgr = ocp.CheckpointManager(
            Path(directory).absolute(),
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep,
                save_interval_steps=save_interval_steps,
                create=True))

    def save(self, step: int, model: nnx.Module,
             optimizer: nnx.Optimizer | None = None, *,
             extra: dict[str, Any] | None = None, force: bool = False) -> bool:
        """Async-save model (+ optimizer) state at ``step``."""
        items: dict[str, Any] = {
            "model": ocp.args.StandardSave(nnx.state(model, nnx.Param))}
        if optimizer is not None:
            items["opt"] = ocp.args.StandardSave(
                nnx.state(optimizer, nnx.optimizer.OptState))
        if extra:
            items["extra"] = ocp.args.JsonSave(extra)
        return self._mgr.save(step, args=ocp.args.Composite(**items),
                              force=force)

    def restore(self, model: nnx.Module,
                optimizer: nnx.Optimizer | None = None,
                *, step: int | None = None) -> int:
        """Restore in place (onto each param's current sharding); returns the
        restored step."""
        step = step if step is not None else self._mgr.latest_step()
        if step is None:
            raise FileNotFoundError("no checkpoint found")
        model_state = nnx.state(model, nnx.Param)
        items: dict[str, Any] = {
            "model": ocp.args.StandardRestore(model_state)}
        if optimizer is not None:
            items["opt"] = ocp.args.StandardRestore(
                nnx.state(optimizer, nnx.optimizer.OptState))
        restored = self._mgr.restore(step, args=ocp.args.Composite(**items))
        nnx.update(model, restored["model"])
        if optimizer is not None:
            nnx.update(optimizer, restored["opt"])
        return step

    def latest_step(self) -> int | None:
        return self._mgr.latest_step()

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self._mgr.close()
