#!/bin/bash
# Wait for the TPU tunnel to come back, then run the queued measurements
# serially (the single chip must never be shared between processes).
cd /root/repo
for i in $(seq 1 90); do
  if timeout 90 python -c "
import jax
x = (jax.numpy.ones((256,256)) @ jax.numpy.ones((256,256)))
assert float(x[0,0]) == 256.0" 2>/dev/null; then
    echo "TPU alive after $i probes"
    break
  fi
  echo "probe $i: tunnel down, sleeping 120s"
  sleep 120
done

echo "=== 1. attention microbench (head-blocked kernels) ==="
timeout 600 python -m scripts.perf_probe --mode attn 2>&1 | grep -v WARNING | tail -6
echo "=== 2. crossover sweep ==="
timeout 600 python -m scripts.attn_crossover 2>&1 | grep -v WARNING | tail -8
echo "=== 2.5 fused-LN bench ==="
timeout 600 python -m scripts.ln_bench 2>&1 | grep -v WARNING | tail -4
echo "=== 3. train grid (attn x kernels at unroll 12) ==="
timeout 900 python -m scripts.perf_probe --mode train --remat dots --unroll 12 2>&1 | grep -E "train remat" | tail -4
echo "=== 3b. ln fused / qkv fused variants ==="
timeout 900 python -m scripts.perf_probe --mode train --remat dots --unroll 12 --attn auto --ln fused 2>&1 | grep -E "train remat" | tail -2
timeout 900 python -m scripts.perf_probe --mode train --remat dots --unroll 12 --attn auto --fused-qkv 2>&1 | grep -E "train remat" | tail -2
timeout 900 python -m scripts.perf_probe --mode train --remat dots --unroll 12 --attn auto --ln fused --fused-qkv 2>&1 | grep -E "train remat" | tail -2
echo "=== 4. bench.py (benchmark of record) ==="
timeout 1550 python bench.py 2>&1 | tail -2
echo "=== queue done ==="
