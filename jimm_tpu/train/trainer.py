"""Training loop machinery: optimizer factory, jitted step builders for
classification and contrastive training.

The reference ships one MNIST example loop (`examples/vit_training.py`) and
nothing for its dual-tower models. Here training is library code: steps are
built once per (model, loss) pair, jitted with donated state, and work on any
mesh/rules combination (replicated, DP, TP, FSDP, FSDP+TP) because sharding
comes from the logical-rules context — not from the step code.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from functools import partial
from typing import Callable

import jax
import jax.numpy as jnp
import optax
from flax import nnx

from jimm_tpu.train.losses import (clip_softmax_loss, ring_clip_infonce_loss,
                                   ring_sigmoid_loss, sigmoid_pairwise_loss)
from jimm_tpu.utils.compat import optimizer_update


@dataclass(frozen=True)
class OptimizerConfig:
    learning_rate: float = 1e-3
    weight_decay: float = 1e-4
    warmup_steps: int = 0
    total_steps: int | None = None  # cosine decay horizon; None = constant
    b1: float = 0.9
    b2: float = 0.999
    grad_clip_norm: float | None = 1.0
    min_lr_ratio: float = 0.0
    #: dtype for Adam's first moment (optax ``mu_dtype``); "bfloat16" halves
    #: that buffer's HBM footprint and read/write traffic on the (bandwidth-
    #: bound) update. None = accumulate in the param dtype.
    moment_dtype: str | None = None


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    if cfg.total_steps is None:
        if cfg.warmup_steps:
            return optax.linear_schedule(0.0, cfg.learning_rate,
                                         cfg.warmup_steps)
        return optax.constant_schedule(cfg.learning_rate)
    # short runs (smoke tests, debug) can have total_steps <= warmup_steps;
    # optax requires decay_steps > warmup_steps, so clamp the warmup — but
    # loudly, since in a long run this usually means a units typo
    warmup = min(cfg.warmup_steps, max(cfg.total_steps - 1, 0))
    if warmup != cfg.warmup_steps:
        import warnings
        warnings.warn(
            f"warmup_steps={cfg.warmup_steps} >= total_steps="
            f"{cfg.total_steps}; clamping warmup to {warmup}",
            stacklevel=2)
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0, peak_value=cfg.learning_rate,
        warmup_steps=warmup, decay_steps=cfg.total_steps,
        end_value=cfg.learning_rate * cfg.min_lr_ratio)


def make_optimizer(model: nnx.Module, cfg: OptimizerConfig) -> nnx.Optimizer:
    """AdamW with warmup-cosine schedule and global-norm clipping; weight
    decay is masked off 1-D params (LayerNorm/bias) and scalars."""
    schedule = make_schedule(cfg)

    def decay_mask(params):
        return jax.tree.map(lambda p: jnp.ndim(p) > 1, params)

    chain = []
    if cfg.grad_clip_norm:
        chain.append(optax.clip_by_global_norm(cfg.grad_clip_norm))
    chain.append(optax.adamw(schedule, b1=cfg.b1, b2=cfg.b2,
                             weight_decay=cfg.weight_decay, mask=decay_mask,
                             mu_dtype=cfg.moment_dtype))
    return nnx.Optimizer(model, optax.chain(*chain), wrt=nnx.Param)


# ---------------------------------------------------------------------------
# Step builders
# ---------------------------------------------------------------------------

def make_classifier_train_step(*, donate: bool = False) -> Callable:
    """Cross-entropy classification step (ref `examples/vit_training.py:81-102`
    semantics: value_and_grad over model, accuracy metric, optimizer update).
    ``donate=True`` donates model+optimizer buffers so params/m/v update in
    place (same HBM rationale as ``make_contrastive_train_step``)."""

    @partial(nnx.jit, donate_argnums=(0, 1) if donate else ())
    def train_step(model: nnx.Module, optimizer: nnx.Optimizer,
                   images: jax.Array, labels: jax.Array) -> dict[str, jax.Array]:
        def loss_fn(model):
            logits = model(images)
            loss = optax.softmax_cross_entropy_with_integer_labels(
                logits, labels).mean()
            return loss, logits

        # named_scope (not obs.span — this is traced code) tags the emitted
        # ops so profile.op_stats and obs trace lanes share one vocabulary
        with jax.named_scope("fwd_bwd"):
            (loss, logits), grads = nnx.value_and_grad(
                loss_fn, has_aux=True)(model)
        with jax.named_scope("optimizer_update"):
            optimizer_update(optimizer, model, grads)
        accuracy = jnp.mean(jnp.argmax(logits, axis=-1) == labels)
        return {"loss": loss, "accuracy": accuracy}

    return train_step


def make_classifier_eval_step() -> Callable:
    @nnx.jit
    def eval_step(model: nnx.Module, images: jax.Array, labels: jax.Array
                  ) -> dict[str, jax.Array]:
        logits = model(images)
        loss = optax.softmax_cross_entropy_with_integer_labels(
            logits, labels).mean()
        accuracy = jnp.mean(jnp.argmax(logits, axis=-1) == labels)
        return {"loss": loss, "accuracy": accuracy}

    return eval_step


def contrastive_loss_fn(model, images: jax.Array, text: jax.Array, *,
                        kind: str, mesh=None, axis_name: str = "data"
                        ) -> jax.Array:
    """Shared loss dispatch for CLIP/SigLIP models.

    - ``"clip"``: symmetric softmax InfoNCE (needs ``logit_scale``).
    - ``"clip_ring"``: ppermute-ring InfoNCE over ``axis_name`` — streaming
      logsumexp, never materializes the global logit matrix.
    - ``"siglip"``: dense sigmoid all-pairs (oracle / single chip).
    - ``"siglip_ring"``: ppermute-ring sigmoid over ``axis_name`` —
      the north-star loss.

    ``images`` is either a ``(B, H, W, C)`` array or a NaFlex triple
    ``(patches, spatial_shapes, mask)`` (see
    `SigLIP.encode_image_naflex`) — the latter trains SigLIP2 on
    variable-resolution batches, which the reference cannot.
    """
    if isinstance(images, (tuple, list)):
        img = model.encode_image_naflex(*images)
    else:
        img = model.encode_image(images)
    txt = model.encode_text(text)
    scale = model.logit_scale[...]
    if kind == "clip":
        return clip_softmax_loss(img, txt, scale)
    if kind == "clip_ring":
        return ring_clip_infonce_loss(img, txt, scale, mesh=mesh,
                                      axis_name=axis_name)
    bias = model.logit_bias[...]
    if kind == "siglip":
        return sigmoid_pairwise_loss(img, txt, scale, bias)
    if kind == "siglip_ring":
        return ring_sigmoid_loss(img, txt, scale, bias, mesh=mesh,
                                 axis_name=axis_name)
    raise ValueError(f"unknown contrastive loss kind {kind!r}")


def make_contrastive_train_step(kind: str = "siglip_ring", *, mesh=None,
                                axis_name: str = "data",
                                donate: bool = False) -> Callable:
    """``donate=True`` donates the model+optimizer state buffers to XLA so
    params/m/v update in place instead of double-buffering — saves HBM
    capacity and write bandwidth on the hot training path."""
    loss = partial(contrastive_loss_fn, kind=kind, mesh=mesh,
                   axis_name=axis_name)

    @partial(nnx.jit, donate_argnums=(0, 1) if donate else ())
    def train_step(model: nnx.Module, optimizer: nnx.Optimizer,
                   images: jax.Array, text: jax.Array) -> dict[str, jax.Array]:
        def loss_fn(model):
            return loss(model, images, text)

        with jax.named_scope("fwd_bwd"):
            loss_val, grads = nnx.value_and_grad(loss_fn)(model)
        with jax.named_scope("optimizer_update"):
            optimizer_update(optimizer, model, grads)
        return {"loss": loss_val}

    return train_step
