"""Prompt-ensemble zero-shot classification (the CLIP-paper recipe).

The reference's zero-shot flow is one prompt per label
(ref `examples/clip_inference.py`); the standard evaluation recipe instead
averages each class's text embedding over a set of prompt templates —
normalize per prompt, mean over templates, normalize again — which is worth
1-2 points of ImageNet accuracy for CLIP-family models. This module builds
those ensemble classifier weights once, so inference is a single
``(B, D) @ (D, C)`` matmul per batch — MXU-shaped, no text tower in the
inference hot path.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

#: The 7-template ImageNet evaluation subset popularized by the CLIP
#: authors' zero-shot notebook — a strong default when the full 80-template
#: set is overkill.
TEMPLATES: tuple[str, ...] = (
    "itap of a {}.",
    "a bad photo of the {}.",
    "a origami {}.",
    "a photo of the large {}.",
    "a {} in a video game.",
    "art of the {}.",
    "a photo of the small {}.",
)


def expand_templates(labels: Sequence[str],
                     templates: Sequence[str] = TEMPLATES) -> list[str]:
    """All prompts, class-major: ``[t.format(l) for l in labels for t in
    templates]`` — the layout `classifier_weights` expects."""
    return [t.format(label) for label in labels for t in templates]


def classifier_weights(model, text_rows: jax.Array, n_classes: int
                       ) -> jax.Array:
    """Ensemble zero-shot classifier weights from tokenized prompts.

    Args:
        model: CLIP or SigLIP (anything with ``encode_text``).
        text_rows: ``(n_classes * n_templates, L)`` token rows, class-major
            (``expand_templates`` order), each padded/EOT'd the way the
            model's tokenizer requires.
        n_classes: number of classes the rows cover.

    Returns:
        ``(n_classes, D)`` unit-norm class embeddings: per-prompt L2
        normalization, mean over the class's templates, renormalized.
    """
    total = text_rows.shape[0]
    if total % n_classes:
        raise ValueError(f"{total} prompt rows not divisible by "
                         f"{n_classes} classes")
    emb = model.encode_text(text_rows)                       # (C*T, D)
    emb = emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)
    emb = emb.reshape(n_classes, total // n_classes, -1).mean(axis=1)
    return emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)


def zero_shot_logits_from_features(model, img_features: jax.Array,
                                   class_embeds: jax.Array) -> jax.Array:
    """Like `zero_shot_logits` but over precomputed (unnormalized) image
    features — e.g. from `encode_image_naflex`."""
    img = img_features / jnp.linalg.norm(img_features, axis=-1,
                                         keepdims=True)
    logits = jnp.exp(model.logit_scale[...]) * img @ class_embeds.T
    bias = getattr(model, "logit_bias", None)
    if bias is not None:
        logits = logits + bias[...]
    return logits


def zero_shot_logits(model, images: jax.Array,
                     class_embeds: jax.Array) -> jax.Array:
    """``(B, C)`` logits against prebuilt ensemble weights, using the
    model's own calibration: ``exp(logit_scale)`` (CLIP & SigLIP) plus
    ``logit_bias`` when present (SigLIP — feed through a sigmoid for
    per-class probabilities; CLIP logits go through a softmax)."""
    return zero_shot_logits_from_features(model, model.encode_image(images),
                                          class_embeds)
