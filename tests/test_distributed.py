"""Two-process `jax.distributed` smoke (VERDICT r2 weak #7: every
multi-device test ran in one process; `initialize_distributed` was never
exercised even at 2 local processes).

Spawns two real OS processes forming a local CPU cluster: asserts cluster
formation, global mesh construction over non-addressable devices, a
cross-process psum, and a process_allgather — the primitives multi-host
training rests on (SURVEY §2.3 "collective communication backend" row) —
and then a full cross-process TRAIN STEP: FSDP+TP params laid out over
non-addressable devices, the ring sigmoid loss crossing the process
boundary, and per-process data loading reassembled into the global batch
(VERDICT r3 item 4).
"""

import subprocess
import sys

import numpy as np
import pytest

from jimm_tpu.launch import _free_port

WORKER = r"""
import os
import sys
import numpy as np
# override the parent suite's 8-device XLA_FLAGS: each worker owns 2 local
# devices (JAX < 0.5 path; JAX >= 0.5 uses the config key below)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # JAX < 0.5: XLA_FLAGS above covers it

addr, pid = sys.argv[1], int(sys.argv[2])
from jimm_tpu.parallel import initialize_distributed, make_mesh
initialize_distributed(coordinator_address=addr, num_processes=2,
                       process_id=pid)
assert jax.process_count() == 2, jax.process_count()
assert jax.process_index() == pid
assert jax.device_count() == 4, jax.device_count()       # 2 global x 2 local
assert jax.local_device_count() == 2

# double-init must be a no-op (initialize_distributed's contract)
initialize_distributed(coordinator_address=addr, num_processes=2,
                       process_id=pid)

import jax.numpy as jnp
from jimm_tpu.utils.compat import shard_map
from jax.experimental import multihost_utils
from jax.sharding import PartitionSpec as P

# cross-process allgather: one value per process, ordered by process id
got = multihost_utils.process_allgather(jnp.float32(pid + 1))
assert got.tolist() == [1.0, 2.0], got

# global mesh over all 4 devices (2 of them non-addressable here) + psum
mesh = make_mesh({"data": -1})
assert dict(mesh.shape) == {"data": 4}
fn = shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
               in_specs=P(), out_specs=P())
out = jax.jit(fn)(np.float32(1.0))
assert float(out) == 4.0, float(out)
print(f"WORKER_OK {pid}")
"""


def _run_two_workers(script: str, timeout: int = 600):
    addr = f"127.0.0.1:{_free_port()}"
    procs = [subprocess.Popen(
        [sys.executable, "-c", script, addr, str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
        for pid in range(2)]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=timeout)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, (f"worker {pid} rc={rc}\nstdout:{out}\n"
                         f"stderr:{err[-2000:]}")
    return outs


@pytest.mark.slow
def test_two_process_cluster():
    outs = _run_two_workers(WORKER)
    for pid, (rc, out, err) in enumerate(outs):
        assert f"WORKER_OK {pid}" in out


# Tiny SigLIP + 2-step ring-loss training over a global (data=2, model=2)
# mesh. Both the worker pair and the single-process oracle run THIS code —
# only the device/process topology differs, so the printed losses must
# match to float32 tolerance.
TRAIN_BODY = r"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from flax import nnx

from jimm_tpu import SigLIP
from jimm_tpu.configs import SigLIPConfig, TextConfig, VisionConfig
from jimm_tpu.data.synthetic import contrastive_pairs
from jimm_tpu.parallel import FSDP_TP, create_sharded, use_sharding
from jimm_tpu.train import (OptimizerConfig, make_contrastive_train_step,
                            make_optimizer)


def train_losses(devices, shard_index, shard_count):
    mesh = Mesh(np.asarray(devices).reshape(2, 2), ("data", "model"))
    cfg = SigLIPConfig(
        vision=VisionConfig(image_size=16, patch_size=8, width=32, depth=2,
                            num_heads=2, mlp_dim=64, act="gelu_tanh",
                            pooling="map"),
        text=TextConfig(vocab_size=64, context_length=8, width=32, depth=2,
                        num_heads=2, mlp_dim=64, act="gelu_tanh",
                        causal=False, pooling="last", proj_bias=True),
        projection_dim=32)
    model = create_sharded(lambda: SigLIP(cfg, rngs=nnx.Rngs(0)), mesh,
                           FSDP_TP)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-3))
    step = make_contrastive_train_step("siglip_ring", mesh=mesh)
    stream = contrastive_pairs(8, image_size=16, seq_len=8, seed=3,
                               shard_index=shard_index,
                               shard_count=shard_count)
    batch_sharding = NamedSharding(mesh, P("data"))
    losses = []
    with use_sharding(mesh, FSDP_TP):
        for _ in range(2):
            images, text = next(stream)
            gi = jax.make_array_from_process_local_data(batch_sharding,
                                                        images)
            gt = jax.make_array_from_process_local_data(batch_sharding, text)
            losses.append(float(step(model, opt, gi, gt)["loss"]))
    return losses
"""

TRAIN_WORKER = r"""
import os
import sys
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # JAX < 0.5: XLA_FLAGS above covers it

addr, pid = sys.argv[1], int(sys.argv[2])
from jimm_tpu.parallel import initialize_distributed
initialize_distributed(coordinator_address=addr, num_processes=2,
                       process_id=pid)
assert jax.device_count() == 4

""" + TRAIN_BODY + r"""
losses = train_losses(jax.devices(), jax.process_index(),
                      jax.process_count())
print("TRAIN_LOSSES", pid, " ".join(f"{l:.6f}" for l in losses))
"""


@pytest.mark.slow
def test_two_process_train_step_matches_single_process(eight_devices):
    """FSDP+TP ring-loss training, 2 processes x 2 devices: params laid out
    over non-addressable devices, the ring crossing the process boundary
    (data-axis groups are {dev0,dev2}/{dev1,dev3} — one device from each
    process), per-process `contrastive_pairs` shards reassembled with
    `make_array_from_process_local_data`. Loss trajectory must equal the
    single-process 4-device run of the identical code."""
    import jax

    ns = {"__name__": "train_oracle"}
    exec(TRAIN_BODY, ns)  # the oracle runs literally the same code
    expected = ns["train_losses"](jax.devices()[:4], 0, 1)
    assert all(np.isfinite(l) for l in expected), expected

    outs = _run_two_workers(TRAIN_WORKER)
    for pid, (rc, out, err) in enumerate(outs):
        line = [l for l in out.splitlines()
                if l.startswith(f"TRAIN_LOSSES {pid}")]
        assert line, f"worker {pid} printed no losses\nstdout:{out}"
        got = [float(t) for t in line[0].split()[2:]]
        np.testing.assert_allclose(got, expected, atol=1e-5)
