"""scripts/adopt_sweep.py: ranking, fidelity filters, flag spelling —
and the shared soft-alarm guard."""

import json
import time

import scripts.adopt_sweep as adopt


def _write(tmp_path, recs):
    p = tmp_path / "sweep.log"
    p.write_text("\n".join(json.dumps(r) for r in recs) + "\nnot json\n")
    return p


def test_ranking_filters_low_fidelity_records(tmp_path):
    path = _write(tmp_path, [
        {"variant": {"remat": "dots"}, "mfu": 0.45, "device": "TPU v5 lite"},
        # tiny/CPU validation lines must never outrank real measurements
        {"variant": {"remat": "dots"}, "mfu": 0.93, "device": "cpu"},
        {"variant": {"remat": "dots", "ln": "fused"}, "mfu": 0.91,
         "tiny": True, "device": "TPU v5 lite"},
        {"variant": {"remat": "dots", "ln": "fused"}, "mfu": 0.47,
         "device": "TPU v5 lite"},
        {"variant": {"remat": "dots"}, "error": "boom"},
    ])
    recs = adopt.load_records(path, phase_filter=False)
    assert all(isinstance(r["mfu"], float) for r in recs)
    assert sorted(r["mfu"] for r in recs) == [0.45, 0.47]


def test_last_record_per_variant_wins(tmp_path):
    path = _write(tmp_path, [
        {"variant": {"remat": "dots"}, "mfu": 0.40, "device": "TPU"},
        # key order must not split the variant into two entries
        {"variant": {"ln": "fused", "remat": "dots"}, "mfu": 0.30,
         "device": "TPU"},
        {"variant": {"remat": "dots", "ln": "fused"}, "mfu": 0.42,
         "device": "TPU"},
        {"variant": {"remat": "dots"}, "mfu": 0.46, "device": "TPU"},
    ])
    ranked = adopt.rank_records(adopt.load_records(path, phase_filter=False))
    assert [r["mfu"] for r in ranked] == [0.46, 0.42]


def test_flags_for_reproduces_measured_config():
    v = {"remat": "dots+ln", "ln": "fused", "fused_qkv": "1",
         "moment": "bf16", "unroll": "6", "batch": "256", "donate": "0",
         "attn": "saveable"}
    flags = adopt.flags_for(v)
    for expect in ("--remat dots+ln", "--ln fused", "--fused-qkv",
                   "--moment-dtype bf16", "--unroll 6", "--batch-size 256",
                   "--no-donate", "--attn saveable"):
        assert expect in flags, flags


def test_soft_alarm_interrupts_and_restores():
    from jimm_tpu.utils.alarm import soft_alarm
    import signal

    before = signal.getsignal(signal.SIGALRM)
    disarm = soft_alarm(1)
    try:
        time.sleep(5)
        raise AssertionError("alarm did not fire")
    except TimeoutError:
        pass
    finally:
        disarm()
    assert signal.getsignal(signal.SIGALRM) is before

    # disarm before expiry must CANCEL the pending alarm, not just restore
    # the handler — otherwise SIGALRM would land on the restored default
    # handler and kill the process
    fired = []
    old = signal.signal(signal.SIGALRM, lambda s, f: fired.append(s))
    try:
        disarm = soft_alarm(1)
        disarm()
        # disarm restored OUR recording handler; any leaked alarm -> fired
        time.sleep(1.2)
        assert not fired, "disarm() left the alarm pending"
    finally:
        signal.signal(signal.SIGALRM, old)
