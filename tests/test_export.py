"""HF-interoperable export round-trip: our save_pretrained output must load
in `transformers` AND in our own from_pretrained, bit-identically."""

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import CLIP, SigLIP, VisionTransformer

from hf_util import (sample_image, sample_text, save_tiny_clip,
                     save_tiny_siglip, save_tiny_vit, torch_image)


def test_vit_export_roundtrip(tmp_path, rng):
    import torch
    from transformers import ViTForImageClassification
    src = save_tiny_vit(tmp_path / "src")
    model = VisionTransformer.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")

    img = sample_image(rng, size=48)
    ours = np.asarray(model(jnp.asarray(img)))
    # our export loads in torch/transformers
    hf = ViTForImageClassification.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(torch_image(img)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    # and back in our own loader, bit-identical
    again = VisionTransformer.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(ours, np.asarray(again(jnp.asarray(img))))


def test_clip_export_roundtrip(tmp_path, rng):
    import torch
    from transformers import CLIPModel
    src = save_tiny_clip(tmp_path / "src")
    model = CLIP.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    hf = CLIPModel.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(txt),
                    pixel_values=torch_image(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    again = CLIP.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(
        ours, np.asarray(again(jnp.asarray(img), jnp.asarray(txt))))


def test_siglip_export_roundtrip(tmp_path, rng):
    """Round-trip must re-fuse the MAP head's in_proj chunks."""
    import torch
    from transformers import SiglipModel
    src = save_tiny_siglip(tmp_path / "src")
    model = SigLIP.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    hf = SiglipModel.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(txt),
                    pixel_values=torch_image(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    again = SigLIP.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(
        ours, np.asarray(again(jnp.asarray(img), jnp.asarray(txt))))


def test_siglip2_native_export_roundtrip(tmp_path, rng):
    """flavor='siglip2': the export reloads in transformers' Siglip2Model
    (NaFlex Linear patch embed + num_patches table) with feature parity,
    and in our own from_pretrained."""
    import torch
    from transformers import Siglip2Model

    from hf_util import save_tiny_siglip2, siglip2_pixel_inputs
    src = save_tiny_siglip2(tmp_path / "src")
    model = SigLIP.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")  # default flavor: match source
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    hf = Siglip2Model.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(txt),
                    **siglip2_pixel_inputs(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    again = SigLIP.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(
        ours, np.asarray(again(jnp.asarray(img), jnp.asarray(txt))))


def test_siglip2_origin_v1_export_warns_and_loads(tmp_path, rng):
    import pytest as _pytest

    from hf_util import save_tiny_siglip2
    src = save_tiny_siglip2(tmp_path / "src")
    model = SigLIP.from_pretrained(src)
    with _pytest.warns(UserWarning, match="SiglipModel"):
        model.save_pretrained(tmp_path / "v1", flavor="siglip")
    again = SigLIP.from_pretrained(str(tmp_path / "v1"))
    img, txt = sample_image(rng), sample_text(rng)
    np.testing.assert_allclose(
        np.asarray(model(jnp.asarray(img), jnp.asarray(txt))),
        np.asarray(again(jnp.asarray(img), jnp.asarray(txt))), atol=1e-5)


def test_cli_export_flavor_flag(tmp_path):
    """`export --flavor siglip` downgrades a Siglip2-origin checkpoint to
    the v1 layout; `--flavor` on a non-SigLIP model is refused."""
    import warnings

    from hf_util import save_tiny_siglip2, save_tiny_vit
    from jimm_tpu.cli import main
    src = save_tiny_siglip2(tmp_path / "src")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # the intentional v1-downgrade warn
        rc = main(["export", str(src), str(tmp_path / "v1"),
                   "--model", "siglip", "--flavor", "siglip",
                   "--platform", "cpu"])
    assert rc == 0
    assert SigLIP.from_pretrained(
        str(tmp_path / "v1"))._hf_source_flavor == "siglip"
    vit_src = save_tiny_vit(tmp_path / "vsrc")
    with pytest.raises(SystemExit, match="SigLIP"):
        main(["export", str(vit_src), str(tmp_path / "vout"),
              "--model", "vit", "--flavor", "siglip", "--platform", "cpu"])
