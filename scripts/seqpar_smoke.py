"""CI tier-1 smoke for the sequence-parallel mesh axis (docs/performance.md).

Forces 8 virtual CPU devices and proves, end to end, that a sequence too
large for one virtual device's score budget trains AND serves across the
``seq`` ring:

1. **Budget**: the temporal preset's dense per-device ``(S, S)`` score
   buffer exceeds the (emulated) per-virtual-device budget, while the ring's
   per-hop ``(S/p, S/p)`` tile fits — the structural reason the workload
   needs the seq axis at all. At real scale the same inequality is the
   8K-NaFlex / video HBM wall.
2. **Ring engagement**: a masked (NaFlex-style key-padding) forward under
   an ambient ``seq=4`` mesh routes through ``seq_parallel_attention``,
   matches the single-chip oracle, and bumps
   ``jimm_ring_bytes_permuted_total`` — the routing is real, not a silent
   fall-through.
3. **Training parity**: two real ``jimm-tpu train`` runs of the temporal
   preset (10 steps, ``--batch-fingerprint``): ``--mesh data=2,seq=4`` vs
   an unsharded control. Batch fingerprints must be identical step for
   step and per-step losses must agree at rtol 2e-4.
4. **Serving**: a 2-replica x seq=4 topology serves the same temporal
   model over HTTP ``/v1/embed`` (real clips through the real server) with
   ZERO fresh compiles after warmup, and the served output matches the
   unsharded model.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.seqpar_smoke
"""

from __future__ import annotations

import json
import os
import pathlib
import subprocess
import sys
import tempfile

PRESET = "vit-temporal-small-patch16-224-f8"
STEPS = 10
BATCH = 8
SEQ_PARALLEL = 4
REPLICAS = 2
LOSS_RTOL = 2e-4
REQUESTS = 8
# emulated per-virtual-device score-buffer budget: sized so the tiny
# preset's dense (S, S) scores blow it while the ring's per-hop tile fits
# — the same inequality that makes real video/8K-NaFlex sequences
# unservable on one chip
SCORE_BUDGET_BYTES = 16 * 1024


def fail(msg: str) -> int:
    print(json.dumps({"metric": "seqpar_smoke", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def run_train(mesh: str | None, metrics_file: pathlib.Path) -> None:
    """One tiny CLI train run, fingerprinted, metrics to ``metrics_file``."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    cmd = [sys.executable, "-m", "jimm_tpu.cli", "train",
           "--preset", PRESET, "--tiny",
           "--steps", str(STEPS), "--batch-size", str(BATCH),
           "--batch-fingerprint", "--log-every", "1",
           "--metrics-file", str(metrics_file)]
    if mesh:
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
        cmd += ["--mesh", mesh, "--rules", "sp"]
    proc = subprocess.run(cmd, env=env, capture_output=True, text=True,
                          cwd=str(pathlib.Path(__file__).parent.parent))
    if proc.returncode != 0:
        raise RuntimeError(f"train (mesh={mesh}) failed: "
                           f"{proc.stderr[-1500:]}")


def read_steps(metrics_file: pathlib.Path) -> list[dict]:
    rows = [json.loads(line) for line in
            metrics_file.read_text().splitlines() if line.strip()]
    return [r for r in rows if "loss" in r]


def main() -> int:
    # must land before jax initializes its backends
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")

    import numpy as np
    from flax import nnx

    import jax
    from jimm_tpu import preset
    from jimm_tpu.cli import _model_cls, _tiny_override
    from jimm_tpu.obs import get_registry
    from jimm_tpu.parallel.mesh import make_mesh
    from jimm_tpu.parallel.sharding import PRESET_RULES, use_sharding
    from jimm_tpu.serve import (BucketTable, InferenceEngine,
                                build_replica_forwards, plan_topology)
    from jimm_tpu.serve.client import ServeClient
    from jimm_tpu.serve.server import ServingServer

    if jax.device_count() < REPLICAS * SEQ_PARALLEL:
        return fail(f"need {REPLICAS * SEQ_PARALLEL} devices, have "
                    f"{jax.device_count()} — was XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=8 set before "
                    f"another jax import?")

    cfg = _tiny_override(preset(PRESET))
    v = cfg.vision
    seq = v.seq_len
    if seq % SEQ_PARALLEL:
        return fail(f"{PRESET} tiny sequence {seq} not divisible by "
                    f"seq={SEQ_PARALLEL}; the ring cannot engage")

    # --- 1. budget: dense scores cannot fit, the ring tile can ------------
    bucket = 4  # largest serving bucket below
    dense = bucket * v.num_heads * seq * seq * 4
    tile = bucket * v.num_heads * (seq // SEQ_PARALLEL) ** 2 * 4
    if dense <= SCORE_BUDGET_BYTES:
        return fail(f"dense score buffer {dense}B fits the "
                    f"{SCORE_BUDGET_BYTES}B virtual-device budget — the "
                    f"smoke no longer proves the sequence is too large")
    if tile > SCORE_BUDGET_BYTES:
        return fail(f"ring per-hop tile {tile}B exceeds the budget "
                    f"{SCORE_BUDGET_BYTES}B — sharding did not help")

    # --- 2. ring engagement: masked forward under an ambient seq mesh -----
    counter = get_registry("jimm_ring").counter(
        "jimm_ring_bytes_permuted_total")
    before = counter.value
    from jimm_tpu.ops.attention import dot_product_attention
    rng = np.random.RandomState(0)
    b, n, d = 2, v.num_heads, v.width // v.num_heads
    q = rng.randn(b, seq, n, d).astype(np.float32)
    k = rng.randn(b, seq, n, d).astype(np.float32)
    val = rng.randn(b, seq, n, d).astype(np.float32)
    # NaFlex-style key-padding mask with real tokens straddling the last
    # ring shard boundary
    keep = np.ones((b, seq), bool)
    keep[:, -seq // 3:] = False
    mask4 = keep[:, None, None, :]
    mesh = make_mesh({"seq": SEQ_PARALLEL},
                     devices=jax.devices()[:SEQ_PARALLEL])
    with use_sharding(mesh, PRESET_RULES["sp"]):
        got = np.asarray(dot_product_attention(q, k, val, mask=mask4))
    want = np.asarray(dot_product_attention(q, k, val, mask=mask4,
                                            impl="xla"))
    err = float(np.max(np.abs(got - want)))
    if err > 1e-5:
        return fail(f"ring masked forward disagrees with the single-chip "
                    f"oracle: max_err={err:.3e}")
    if counter.value <= before:
        return fail("jimm_ring_bytes_permuted_total did not move — the "
                    "ambient seq mesh fell through to the single-chip path")

    # --- 3. training parity: CLI ring run vs unsharded control ------------
    with tempfile.TemporaryDirectory(prefix="jimm-seqpar-") as root:
        ctl_file = pathlib.Path(root) / "control.jsonl"
        sp_file = pathlib.Path(root) / "seqpar.jsonl"
        run_train(None, ctl_file)
        run_train(f"data={REPLICAS},seq={SEQ_PARALLEL}", sp_file)
        ctl, sp = read_steps(ctl_file), read_steps(sp_file)
        if len(ctl) != STEPS or len(sp) != STEPS:
            return fail(f"expected {STEPS} logged steps, got "
                        f"{len(ctl)} control / {len(sp)} seq-parallel")
        for a, b_ in zip(ctl, sp):
            if a["batch_fingerprint"] != b_["batch_fingerprint"]:
                return fail(f"step {a['step']}: batch fingerprints differ "
                            f"— the runs trained on different data")
            rel = abs(a["loss"] - b_["loss"]) / max(abs(a["loss"]), 1e-9)
            if rel > LOSS_RTOL:
                return fail(f"step {a['step']}: loss {b_['loss']:.6f} "
                            f"(ring) vs {a['loss']:.6f} (control), "
                            f"rel={rel:.2e} > {LOSS_RTOL}")
        final_rel = abs(ctl[-1]["loss"] - sp[-1]["loss"]) \
            / max(abs(ctl[-1]["loss"]), 1e-9)

    # --- 4. serving: /v1/embed across the ring, zero post-warmup compiles -
    model = _model_cls("vit")(cfg, rngs=nnx.Rngs(0))
    plan = plan_topology(REPLICAS, 1, SEQ_PARALLEL)
    item_shape = (v.num_frames, v.image_size, v.image_size, v.channels)
    forwards, traces = build_replica_forwards(
        model, plan, method="__call__", item_shape=item_shape,
        label="seqpar_smoke")
    engine = InferenceEngine(forwards, item_shape=item_shape,
                             buckets=BucketTable((1, bucket)),
                             max_delay_ms=2.0, trace_count=traces)
    server = ServingServer(engine, port=0)
    server.start()
    try:
        compiles_before = traces()
        client = ServeClient(port=server.port, timeout_s=120.0)
        clip = rng.rand(*item_shape).astype(np.float32)
        outs = [np.asarray(client.embed(clip)) for _ in range(REQUESTS)]
        compile_delta = traces() - compiles_before
    finally:
        server.stop()
    if compile_delta:
        return fail(f"{compile_delta} fresh compile(s) after warmup")
    want = np.asarray(model(clip[None]))[0]
    for i, out in enumerate(outs):
        if not np.allclose(out, want, rtol=1e-4, atol=1e-4):
            return fail(f"served output {i} disagrees with the unsharded "
                        f"model")

    print(json.dumps({
        "metric": "seqpar_smoke", "value": 1.0,
        "topology": plan.describe(),
        "seq_len": seq, "seq_parallel": SEQ_PARALLEL,
        "dense_score_bytes": dense, "ring_tile_bytes": tile,
        "score_budget_bytes": SCORE_BUDGET_BYTES,
        "train_steps": STEPS, "final_loss_rel": round(final_rel, 9),
        "requests": REQUESTS, "compile_count_delta": compile_delta,
        "ring_bytes_permuted": int(counter.value),
    }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
