"""Unified metric registry: counters, gauges, histograms, and the process-
wide hub that merges every subsystem's series into one snapshot.

Before this module the repo had three telemetry islands — `train/metrics.py`
(MFU + MetricsLogger), `serve/admission.py` (ServeMetrics + Prometheus), and
`train/profile.py` (trace capture) — that could not be read together. Here
every instrument lives in a :class:`MetricRegistry` under a namespace prefix
(``jimm_train``, ``jimm_serve``, ``jimm_spans``), registries publish
themselves into a process-global hub, and one call renders the union as a
Prometheus text dump / flat snapshot. FlashAttention's IO-accounting lesson
(arXiv:2205.14135) applies at system scale: you cannot attribute time you
never collected in one place.

Thread safety: counters/histograms take a per-registry lock; gauges are
evaluated at snapshot time and a raising gauge is skipped (a bad gauge must
never kill ``/metrics``).
"""

from __future__ import annotations

import os
import threading
import time
from collections import deque
from typing import Callable, Iterable, Mapping

__all__ = [
    "Counter", "DuplicateMetricError", "Gauge", "Histogram", "MetricRegistry",
    "enabled", "get_registry", "percentile", "publish", "registries",
    "render_prometheus", "set_enabled", "snapshot", "unpublish",
]


class DuplicateMetricError(ValueError):
    """Raised when a metric name is re-registered as a different kind (the
    same-kind re-request returns the existing instrument instead)."""


# ---------------------------------------------------------------------------
# enable/disable switch (hot-path instrumentation gates on this)
# ---------------------------------------------------------------------------

_enabled = os.environ.get("JIMM_OBS", "1").lower() not in ("0", "false", "off")


def enabled() -> bool:
    """True unless observability is switched off (``JIMM_OBS=0``). Span and
    goodput instrumentation become no-ops when disabled; registries keep
    working (serving counters are product behavior, not telemetry)."""
    return _enabled


def set_enabled(value: bool) -> None:
    global _enabled
    _enabled = bool(value)


# ---------------------------------------------------------------------------
# shared percentile math
# ---------------------------------------------------------------------------

def percentile(values: Iterable[float], pct: float) -> float:
    """Nearest-rank percentile over ``values`` (0 on empty input).

    This is THE percentile implementation: ServeMetrics' latency reservoir,
    the obs histograms, and the bench scripts all call it, so a reported
    bench p99 and the runtime p99 can never drift apart on index math.
    """
    data = sorted(values)
    if not data:
        return 0.0
    idx = min(len(data) - 1, int(round(pct / 100.0 * (len(data) - 1))))
    return data[idx]


# ---------------------------------------------------------------------------
# instruments
# ---------------------------------------------------------------------------

class Counter:
    """Monotonic counter. Prometheus convention: name it ``*_total``."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, by: int | float = 1) -> None:
        with self._lock:
            self._value += by

    @property
    def value(self) -> int | float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0


class Gauge:
    """Point-in-time value: either set explicitly (``set``) or bound to a
    callable evaluated at snapshot time (cache hit rate, queue depth)."""

    __slots__ = ("name", "_value", "fn")

    def __init__(self, name: str, fn: Callable[[], float] | None = None):
        self.name = name
        self._value = 0.0
        self.fn = fn

    def set(self, value: float) -> None:
        self._value = value

    def read(self) -> float:
        """Current value; raises whatever a bound callable raises (the
        registry snapshot catches it)."""
        if self.fn is not None:
            return float(self.fn())
        return self._value


class Histogram:
    """Bounded-reservoir histogram with nearest-rank percentiles.

    Keeps the last ``window`` observations (same sliding-window semantics
    ServeMetrics' latency deque always had) plus an unbounded count/sum, so
    rates survive the window rolling over.
    """

    __slots__ = ("name", "_window", "_count", "_sum", "_lock", "unit")

    def __init__(self, name: str, window: int = 4096, unit: str = "s"):
        self.name = name
        self.unit = unit
        self._window: deque[float] = deque(maxlen=window)
        self._count = 0
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self._window.append(value)
            self._count += 1
            self._sum += value

    def percentile(self, pct: float) -> float:
        with self._lock:
            data = list(self._window)
        return percentile(data, pct)

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def snapshot(self) -> dict[str, float]:
        """Flat series: ``{name}_p50``/``_p99`` (window), ``{name}_count``
        and ``{name}_sum`` (lifetime)."""
        with self._lock:
            data = list(self._window)
            count, total = self._count, self._sum
        return {
            f"{self.name}_p50": percentile(data, 50),
            f"{self.name}_p99": percentile(data, 99),
            f"{self.name}_count": count,
            f"{self.name}_sum": round(total, 6),
        }


# ---------------------------------------------------------------------------
# registry
# ---------------------------------------------------------------------------

class MetricRegistry:
    """One namespace of instruments; series render as ``{prefix}_{name}``.

    ``counter``/``histogram`` are get-or-create: asking twice for the same
    name returns the same instrument, asking for an existing name as a
    different kind raises :class:`DuplicateMetricError` — the "no duplicate
    registrations" discipline the CI smoke asserts on the merged dump.
    ``gauge`` with a callable re-binds (latest wins), matching the old
    ``ServeMetrics.bind_gauge`` dict-assignment semantics.
    """

    def __init__(self, prefix: str = "jimm"):
        self.prefix = prefix
        self._lock = threading.Lock()
        self._counters: dict[str, Counter] = {}
        self._gauges: dict[str, Gauge] = {}
        self._histograms: dict[str, Histogram] = {}
        self._t_start = time.monotonic()

    # -- registration -----------------------------------------------------

    def _check_free(self, name: str, kind: dict) -> None:
        for other in (self._counters, self._gauges, self._histograms):
            if other is not kind and name in other:
                raise DuplicateMetricError(
                    f"metric {name!r} already registered in "
                    f"{self.prefix!r} as a different kind")

    def counter(self, name: str) -> Counter:
        with self._lock:
            self._check_free(name, self._counters)
            if name not in self._counters:
                self._counters[name] = Counter(name)
            return self._counters[name]

    def gauge(self, name: str,
              fn: Callable[[], float] | None = None) -> Gauge:
        with self._lock:
            self._check_free(name, self._gauges)
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name, fn)
            elif fn is not None:
                g.fn = fn  # re-bind: latest callable wins
            return g

    def histogram(self, name: str, window: int = 4096,
                  unit: str = "s") -> Histogram:
        with self._lock:
            self._check_free(name, self._histograms)
            if name not in self._histograms:
                self._histograms[name] = Histogram(name, window, unit)
            return self._histograms[name]

    # -- read -------------------------------------------------------------

    @property
    def uptime_s(self) -> float:
        return time.monotonic() - self._t_start

    def snapshot(self) -> dict[str, float]:
        """Flat ``{name: value}`` dict (no prefix). Counters keep int-ness;
        gauges evaluate now (a raising gauge is skipped); histograms expand
        to their ``_p50/_p99/_count/_sum`` series."""
        with self._lock:
            counters = list(self._counters.values())
            gauges = list(self._gauges.values())
            hists = list(self._histograms.values())
        out: dict[str, float] = {}
        for c in counters:
            out[c.name] = c.value
        for h in hists:
            out.update(h.snapshot())
        for g in gauges:
            try:
                out[g.name] = g.read()
            except Exception:  # noqa: BLE001 — a gauge must not kill /metrics
                pass
        return out

    def reset(self) -> None:
        """Drop every instrument (test isolation)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._t_start = time.monotonic()


# ---------------------------------------------------------------------------
# process-global hub
# ---------------------------------------------------------------------------

_hub_lock = threading.Lock()
_hub: dict[str, MetricRegistry] = {}


def publish(registry: MetricRegistry) -> MetricRegistry:
    """Attach a registry to the hub under its prefix. Re-publishing a prefix
    replaces the previous registry (latest wins): e.g. each ServeMetrics
    publishes its private registry, and the newest server owns the
    ``jimm_serve`` series in the unified dump."""
    with _hub_lock:
        _hub[registry.prefix] = registry
    return registry


def unpublish(prefix: str) -> None:
    with _hub_lock:
        _hub.pop(prefix, None)


def get_registry(prefix: str) -> MetricRegistry:
    """The hub's shared registry for ``prefix``, created (and published) on
    first use — the way train-side code gets ``jimm_train``."""
    with _hub_lock:
        reg = _hub.get(prefix)
        if reg is None:
            reg = _hub[prefix] = MetricRegistry(prefix)
        return reg


def registries() -> dict[str, MetricRegistry]:
    with _hub_lock:
        return dict(_hub)


def snapshot() -> dict[str, float]:
    """The unified snapshot: every published registry's series under its
    full ``{prefix}_{name}`` name. Prefixes are distinct by construction
    (hub keys) and names are unique per registry (dict keys), so the merged
    dump can never hold a duplicate series."""
    out: dict[str, float] = {}
    for prefix, reg in sorted(registries().items()):
        for name, value in reg.snapshot().items():
            out[f"{prefix}_{name}"] = value
    return out


def render_prometheus() -> str:
    """Prometheus text exposition of the unified snapshot. Counters keep
    their ``*_total`` names; everything else renders as a gauge — the same
    convention ServeMetrics always used, now for every namespace."""
    from jimm_tpu.obs.exporters import render_prometheus_text
    return render_prometheus_text(snapshot())
