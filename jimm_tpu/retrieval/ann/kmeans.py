"""Mini-batch Lloyd's k-means: the IVF coarse quantizer's trainer.

The codebook is a row-normalized ``(C, D)`` float32 matrix of unit
centroids — cosine assignment is then a plain argmax over one small
``(batch, C)`` matmul, the same dot-product contract ``retrieval/topk.py``
scores on device. Training is deterministic given ``seed``: centroid init
draws distinct corpus rows from a seeded generator, every mini-batch is
drawn from the same stream, and the jit-compiled step (assign + per-center
sums) has no data-dependent shapes, so two trainings of the same corpus
produce bit-identical codebooks. Empty clusters never survive: a centroid
that captures nothing in a batch is re-seeded onto a (seeded-random) member
of that batch's largest cluster, and a final full-corpus pass re-splits any
centroid that is still globally empty.

Persistence reuses the segment framing idiom (header JSON line + raw row
bytes) so a codebook is one content-addressed artifact in the same
:class:`~jimm_tpu.aot.store.ArtifactStore` that holds segments — atomic
writes, integrity on read, quarantine-never-delete.

``assign_clusters`` is pure NumPy (chunked argmax, never a sort) so the
store's write path and the jax-free ``jimm-tpu index`` CLI can assign rows
without an accelerator stack; jax only materializes inside
:func:`train_centroids`.
"""

from __future__ import annotations

import json
from typing import Sequence

import numpy as np

from jimm_tpu.retrieval.store import RetrievalStoreError, normalize_rows

__all__ = ["CODEBOOK_FORMAT_VERSION", "assign_clusters", "clustered_rows",
           "decode_codebook", "encode_codebook", "train_centroids"]

#: bump when the codebook payload framing changes — old artifacts then
#: fail loudly instead of decoding garbage
CODEBOOK_FORMAT_VERSION = 1

#: host-side assignment tile: bounds the (rows, C) score working set
_ASSIGN_CHUNK = 8192


def assign_clusters(vectors: np.ndarray,
                    centroids: np.ndarray) -> np.ndarray:
    """Nearest-centroid id per row (cosine == dot over unit rows), pure
    NumPy and chunked so the host working set stays ``(chunk, C)`` — an
    argmax (selection), never a sort. ``np.argmax`` ties resolve to the
    lowest centroid index, deterministically."""
    vecs = np.asarray(vectors, np.float32)
    cents = np.asarray(centroids, np.float32)
    if vecs.ndim != 2 or cents.ndim != 2 or vecs.shape[1] != cents.shape[1]:
        raise ValueError(
            f"vectors {vecs.shape} and centroids {cents.shape} must be "
            f"(N, D) / (C, D) with one D")
    out = np.empty(vecs.shape[0], np.int32)
    for i in range(0, vecs.shape[0], _ASSIGN_CHUNK):
        tile = vecs[i:i + _ASSIGN_CHUNK]
        out[i:i + _ASSIGN_CHUNK] = np.argmax(tile @ cents.T, axis=1)
    return out


def train_centroids(vectors: np.ndarray, n_clusters: int, *,
                    iters: int = 25, batch_rows: int = 4096,
                    seed: int = 0) -> np.ndarray:
    """Train a row-normalized ``(n_clusters, D)`` codebook with
    jit-compiled mini-batch Lloyd's. Deterministic per ``seed``; empty
    clusters re-split onto members of the batch's largest cluster (and a
    final full pass guarantees no globally-empty centroid survives)."""
    import jax
    import jax.numpy as jnp

    vecs = normalize_rows(np.asarray(vectors, np.float32))
    n, _dim = vecs.shape
    c = int(n_clusters)
    if c < 1:
        raise ValueError(f"n_clusters must be >= 1; got {c}")
    if n < c:
        raise ValueError(f"need at least n_clusters={c} rows; got {n}")
    rng = np.random.default_rng(seed)
    centroids = vecs[rng.choice(n, size=c, replace=False)].copy()
    batch_rows = min(max(int(batch_rows), c), n)

    @jax.jit
    def step(cents, batch):
        # the whole inner loop is one program: (b, C) assign scores,
        # one-hot scatter into per-center sums/counts — no host sync
        scores = batch @ cents.T
        assign = jnp.argmax(scores, axis=1)
        one_hot = jax.nn.one_hot(assign, c, dtype=jnp.float32)
        return one_hot.T @ batch, one_hot.sum(axis=0), assign

    for _ in range(max(1, int(iters))):
        take = rng.choice(n, size=batch_rows, replace=False)
        sums, counts, assign = (np.asarray(x)
                                for x in step(centroids, vecs[take]))
        moved = sums / np.maximum(counts[:, None], 1.0)
        centroids = np.where(counts[:, None] > 0, moved, centroids)
        empty = np.flatnonzero(counts == 0)
        if empty.size:
            donors = take[assign == int(np.argmax(counts))]
            centroids[empty] = vecs[rng.choice(donors, size=empty.size)]
        centroids = normalize_rows(centroids)

    # a centroid can still be globally empty (its batch wins were stolen by
    # later updates); one full assignment pass re-splits those too
    full = assign_clusters(vecs, centroids)
    sizes = np.bincount(full, minlength=c)
    empty = np.flatnonzero(sizes == 0)
    if empty.size:
        donors = np.flatnonzero(full == int(np.argmax(sizes)))
        centroids[empty] = vecs[rng.choice(donors, size=empty.size)]
        centroids = normalize_rows(centroids)
    return np.ascontiguousarray(centroids, dtype=np.float32)


# ---------------------------------------------------------------------------
# codebook persistence (one content-addressed artifact)
# ---------------------------------------------------------------------------

def encode_codebook(centroids: np.ndarray, *, trained_rows: int = 0,
                    seed: int = 0) -> bytes:
    """Frame a codebook payload: header JSON line + raw f32 row bytes."""
    mat = np.ascontiguousarray(normalize_rows(centroids), np.float32)
    header = {"codebook_format": CODEBOOK_FORMAT_VERSION,
              "clusters": int(mat.shape[0]), "dim": int(mat.shape[1]),
              "dtype": "float32", "trained_rows": int(trained_rows),
              "seed": int(seed)}
    return json.dumps(header, sort_keys=True,
                      separators=(",", ":")).encode() + b"\n" + mat.tobytes()


def decode_codebook(payload: bytes) -> tuple[np.ndarray, dict]:
    """Inverse of :func:`encode_codebook`; raises
    :class:`RetrievalStoreError` on framing/shape inconsistency (the
    caller quarantines)."""
    head, sep, body = payload.partition(b"\n")
    if not sep:
        raise RetrievalStoreError("codebook payload has no header line")
    try:
        header = json.loads(head)
    except ValueError as e:
        raise RetrievalStoreError(f"bad codebook header: {e}") from None
    if header.get("codebook_format") != CODEBOOK_FORMAT_VERSION:
        raise RetrievalStoreError(
            f"codebook format {header.get('codebook_format')!r} != "
            f"{CODEBOOK_FORMAT_VERSION}")
    clusters, dim = int(header["clusters"]), int(header["dim"])
    expected = clusters * dim * 4
    if len(body) != expected:
        raise RetrievalStoreError(
            f"codebook body is {len(body)} bytes, header promises "
            f"{expected}")
    mat = np.frombuffer(body, np.float32).reshape(clusters, dim)
    return mat, header


# ---------------------------------------------------------------------------
# synthetic clustered corpora (tests / smokes / frontier)
# ---------------------------------------------------------------------------

def clustered_rows(n: int, dim: int, centers: int, *, noise: float = 0.15,
                   seed: int = 0,
                   center_mat: np.ndarray | None = None
                   ) -> tuple[np.ndarray, np.ndarray]:
    """Seeded mixture-of-Gaussians unit rows — the workload IVF exists
    for (real embedding corpora cluster; i.i.d. Gaussian rows do not).
    Returns ``(rows (n, dim) f32 unit, center_mat (centers, dim))``; pass
    ``center_mat`` back (with a different seed) to draw queries from the
    same mixture."""
    rng = np.random.default_rng(seed)
    if center_mat is None:
        center_mat = normalize_rows(
            rng.standard_normal((int(centers), int(dim)),
                                dtype=np.float32))
    which = rng.integers(0, center_mat.shape[0], size=int(n))
    rows = center_mat[which] + noise * rng.standard_normal(
        (int(n), int(dim)), dtype=np.float32)
    return normalize_rows(rows), center_mat


def cluster_runs(assign_sorted: Sequence[int]) -> list[list[int]]:
    """Run-length encode an already cluster-major assignment vector into
    the manifest's ``[[cluster_id, count], ...]`` form."""
    runs: list[list[int]] = []
    for cid in assign_sorted:
        cid = int(cid)
        if runs and runs[-1][0] == cid:
            runs[-1][1] += 1
        else:
            runs.append([cid, 1])
    return runs
