"""HF-interoperable export round-trip: our save_pretrained output must load
in `transformers` AND in our own from_pretrained, bit-identically."""

import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu import CLIP, SigLIP, VisionTransformer

from hf_util import (sample_image, sample_text, save_tiny_clip,
                     save_tiny_siglip, save_tiny_vit, torch_image)


def test_vit_export_roundtrip(tmp_path, rng):
    import torch
    from transformers import ViTForImageClassification
    src = save_tiny_vit(tmp_path / "src")
    model = VisionTransformer.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")

    img = sample_image(rng, size=48)
    ours = np.asarray(model(jnp.asarray(img)))
    # our export loads in torch/transformers
    hf = ViTForImageClassification.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(torch_image(img)).logits.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    # and back in our own loader, bit-identical
    again = VisionTransformer.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(ours, np.asarray(again(jnp.asarray(img))))


def test_clip_export_roundtrip(tmp_path, rng):
    import torch
    from transformers import CLIPModel
    src = save_tiny_clip(tmp_path / "src")
    model = CLIP.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    hf = CLIPModel.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(txt),
                    pixel_values=torch_image(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    again = CLIP.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(
        ours, np.asarray(again(jnp.asarray(img), jnp.asarray(txt))))


def test_siglip_export_roundtrip(tmp_path, rng):
    """Round-trip must re-fuse the MAP head's in_proj chunks."""
    import torch
    from transformers import SiglipModel
    src = save_tiny_siglip(tmp_path / "src")
    model = SigLIP.from_pretrained(src)
    model.save_pretrained(tmp_path / "out")
    img, txt = sample_image(rng), sample_text(rng)
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    hf = SiglipModel.from_pretrained(tmp_path / "out").eval()
    with torch.no_grad():
        theirs = hf(input_ids=torch.tensor(txt),
                    pixel_values=torch_image(img)).logits_per_image.numpy()
    np.testing.assert_allclose(ours, theirs, atol=1e-4)
    again = SigLIP.from_pretrained(str(tmp_path / "out"))
    np.testing.assert_array_equal(
        ours, np.asarray(again(jnp.asarray(img), jnp.asarray(txt))))
