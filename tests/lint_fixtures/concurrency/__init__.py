"""Living fixtures for the whole-program concurrency rules (JL017–JL019).

Each module seeds one bug family the graph-based detector must keep
catching — plus a clean counterpart shaped the same way, so the guard-set
and root inference are pinned from both directions. ``tests/
test_lint_graph.py`` asserts exact findings per file; the directory is
excluded from directory walks like the rest of ``lint_fixtures``.
"""
