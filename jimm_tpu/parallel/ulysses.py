"""All-to-all (Ulysses-style) sequence parallelism: the second SP scheme.

Complements `ring_attention` (absent from the reference, whose max sequence
is 577 vision tokens — SURVEY §2.3). Where the ring keeps queries local and
rotates key/value chunks via ``ppermute`` (P2P bandwidth, O(p) steps), the
all-to-all scheme redistributes ONCE per attention call: an
``all_to_all`` swaps the sharded axis from sequence to heads, every device
runs ordinary full-sequence attention over its head subset — causal masking
is exact with zero extra machinery, and the single-chip Pallas flash kernel
applies unchanged — then a second ``all_to_all`` swaps back. Four
all-to-alls total (q, k, v in; o out) instead of a p-step scan; the trade
is head-count divisibility (``num_heads % axis_size == 0``) and all-to-all bandwidth,
which rides the TPU ICI fabric well.

Same call contract as `ring_attention`: full ``(B, S, N, D)`` arrays whose
sequence dim is sharded over ``axis_name``; exact (fp32-softmax) equality
with unsharded attention is tested in `tests/test_ulysses.py`.
"""

from __future__ import annotations

from functools import partial

import jax
from jimm_tpu.utils.compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P


def _seq_to_heads(x: jax.Array, axis_name: str) -> jax.Array:
    """(B, S/p, N, D) per device -> (B, S, N/p, D): shard heads, gather
    sequence. One tiled all-to-all over the SP axis."""
    return jax.lax.all_to_all(x, axis_name, split_axis=2, concat_axis=1,
                              tiled=True)


def _heads_to_seq(x: jax.Array, axis_name: str) -> jax.Array:
    """Inverse of `_seq_to_heads`."""
    return jax.lax.all_to_all(x, axis_name, split_axis=1, concat_axis=2,
                              tiled=True)


def _ulysses_local(q, k, v, mask, *, axis_name: str, kind: str, causal: bool,
                   impl: str, logit_bias):
    # head divisibility was validated by ulysses_attention before shard_map.
    # The key-padding mask enters REPLICATED (every device holds the full
    # (B, S) rows — bytes are trivial next to KV) so the local full-sequence
    # kernel applies it directly: no gather, nothing rides the exchange.
    qg = _seq_to_heads(q, axis_name)
    kg = _seq_to_heads(k, axis_name)
    vg = _seq_to_heads(v, axis_name)
    if kind == "sigmoid":
        if impl == "flash":
            from jimm_tpu.ops.flash_attention import sigmoid_attention
            o = sigmoid_attention(qg, kg, vg, is_causal=causal, mask=mask,
                                  logit_bias=logit_bias)
        else:
            from jimm_tpu.ops.attention import reference_sigmoid_attention
            o = reference_sigmoid_attention(qg, kg, vg, is_causal=causal,
                                            mask=mask, logit_bias=logit_bias)
    elif impl == "flash":
        if mask is not None:
            from jimm_tpu.ops.flash_attention import flash_attention_masked
            o = flash_attention_masked(qg, kg, vg, mask, is_causal=causal)
        else:
            from jimm_tpu.ops.flash_attention import flash_attention
            o = flash_attention(qg, kg, vg, is_causal=causal)
    else:
        from jimm_tpu.ops.attention import reference_attention
        mask4 = mask if mask is None else (mask != 0)[:, None, None, :]
        o = reference_attention(qg, kg, vg, is_causal=causal, mask=mask4)
    return _heads_to_seq(o, axis_name)


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mask: jax.Array | None = None, kind: str = "softmax",
                      mesh: Mesh | None = None, axis_name: str = "seq",
                      is_causal: bool = False, impl: str = "auto",
                      logit_bias: float | None = None) -> jax.Array:
    """Exact attention over ``(B, S, N, D)`` q/k/v whose sequence dim is
    sharded over ``axis_name``, via head redistribution (see module
    docstring). ``impl="flash"`` runs each device's full-sequence head
    subset through the Pallas kernel (``"auto"``: flash on TPU when shapes
    qualify, einsum otherwise).

    ``mask`` is a per-sample key-padding mask (bool ``(B, S)`` or
    ``(B, 1, 1, S)``), passed replicated to the local kernels.
    ``kind="sigmoid"`` runs SigLIP-style sigmoid attention (``logit_bias``
    defaults to ``-log(S_global)`` inside the op — after redistribution the
    local kernel sees the full sequence, so the single-chip default is
    already the global one)."""
    from jimm_tpu.parallel.mesh import resolve_mesh_axis
    shape = resolve_mesh_axis(mesh, axis_name)
    if q.shape[2] % shape[axis_name]:
        raise ValueError(f"ulysses attention needs num_heads {q.shape[2]} "
                         f"divisible by the {axis_name!r} axis size "
                         f"{shape[axis_name]} (use attn_impl='ring' "
                         "otherwise)")
    if kind not in ("softmax", "sigmoid"):
        raise ValueError(f"unknown ulysses variant kind {kind!r}")
    if mask is not None and mask.ndim == 4:
        if mask.shape[1] != 1 or mask.shape[2] != 1:
            raise ValueError(
                "ulysses attention supports KEY-PADDING masks only "
                f"((B, Sk) or (B, 1, 1, Sk)); got {tuple(mask.shape)}")
        mask = mask[:, 0, 0, :]
    if impl == "auto":
        # after redistribution each device sees the FULL sequence, so the
        # measured single-chip crossover gate applies to the global length
        from jimm_tpu.ops.attention import _flash_eligible
        flash_ok = (jax.default_backend() == "tpu" and _flash_eligible(q, k))
        impl = "flash" if flash_ok else "einsum"
    if impl not in ("flash", "einsum"):
        raise ValueError(f"unknown ulysses attention impl {impl!r}")
    local = partial(_ulysses_local, axis_name=axis_name, kind=kind,
                    causal=is_causal, impl=impl, logit_bias=logit_bias)
    kwargs = {} if mesh is None else {"mesh": mesh}  # None -> ambient mesh
    fn = shard_map(
        local,
        in_specs=(P(None, axis_name), P(None, axis_name), P(None, axis_name),
                  P()),  # mask replicated — see _ulysses_local
        out_specs=P(None, axis_name),
        check_vma=False, **kwargs)
    return fn(q, k, v, mask)
