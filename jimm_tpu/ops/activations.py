"""Activation functions.

The reference maps HF ``hidden_act`` loosely — any non-quick_gelu act falls
back to flax's default (tanh-approximate) GELU (ref `models/vit.py:139-142`,
`common/transformer.py:90`). We keep exact semantics per HF name instead:
``gelu`` is the erf GELU, ``gelu_tanh``/``gelu_pytorch_tanh`` the tanh
approximation, ``quick_gelu`` the sigmoid approximation
(ref `common/transformer.py:12-19`).
"""

from __future__ import annotations

import warnings
from typing import Callable

import jax



def quick_gelu(x: jax.Array) -> jax.Array:
    """OpenAI CLIP's GELU approximation: ``x * sigmoid(1.702 * x)``."""
    return x * jax.nn.sigmoid(1.702 * x)


def gelu_exact(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=False)


def gelu_tanh(x: jax.Array) -> jax.Array:
    return jax.nn.gelu(x, approximate=True)


_ACTS: dict[str, Callable[[jax.Array], jax.Array]] = {
    "gelu": gelu_exact,
    "gelu_tanh": gelu_tanh,
    "gelu_pytorch_tanh": gelu_tanh,
    "gelu_new": gelu_tanh,
    "quick_gelu": quick_gelu,
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
}


def get_activation(name: str) -> Callable[[jax.Array], jax.Array]:
    """Resolve an activation by (HF) name; warn + GELU fallback like the
    reference (`models/vit.py:139-142`) for unknown names."""
    if name not in _ACTS:
        warnings.warn(f"unknown activation {name!r}; falling back to gelu_tanh")
        return gelu_tanh
    return _ACTS[name]
