"""jimm_tpu.obs: registry, spans, goodput, exporters, and the train+serve
unified-dump integration the CI smoke step re-asserts end to end."""

import json
import math
import time

import numpy as np
import pytest

from jimm_tpu import obs
from jimm_tpu.obs.registry import _hub


@pytest.fixture(autouse=True)
def _obs_enabled():
    """Every test runs with obs on (the env default), restored afterwards."""
    prev = obs.enabled()
    obs.set_enabled(True)
    yield
    obs.set_enabled(prev)


class TestRegistry:
    def test_counter_gauge_histogram(self):
        reg = obs.MetricRegistry("t_basic")
        c = reg.counter("requests_total")
        c.inc()
        c.inc(4)
        assert c.value == 5
        g = reg.gauge("depth")
        g.set(3.5)
        assert g.read() == 3.5
        h = reg.histogram("lat_seconds")
        for v in (1.0, 2.0, 3.0, 4.0):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(10.0)
        snap = reg.snapshot()
        assert snap["requests_total"] == 5
        assert snap["depth"] == 3.5
        assert snap["lat_seconds_count"] == 4
        assert snap["lat_seconds_p99"] == 4.0

    def test_get_or_create_returns_same_instrument(self):
        reg = obs.MetricRegistry("t_same")
        assert reg.counter("a_total") is reg.counter("a_total")
        assert reg.histogram("h") is reg.histogram("h")

    def test_kind_conflict_raises(self):
        reg = obs.MetricRegistry("t_conflict")
        reg.counter("x_total")
        with pytest.raises(obs.DuplicateMetricError):
            reg.gauge("x_total")
        with pytest.raises(obs.DuplicateMetricError):
            reg.histogram("x_total")

    def test_gauge_rebind_latest_wins(self):
        reg = obs.MetricRegistry("t_rebind")
        reg.gauge("v", lambda: 1.0)
        reg.gauge("v", lambda: 2.0)
        assert reg.snapshot()["v"] == 2.0

    def test_raising_gauge_skipped(self):
        reg = obs.MetricRegistry("t_raise")
        reg.gauge("broken", lambda: 1 / 0)
        reg.counter("fine_total").inc()
        snap = reg.snapshot()
        assert "broken" not in snap and snap["fine_total"] == 1

    def test_percentile_matches_serve_metrics_index_math(self):
        # the shared helper must agree with ServeMetrics' historical
        # nearest-rank formula on the exact reservoir it used
        data = [float(i) for i in range(1, 101)]
        idx50 = min(len(data) - 1, int(round(50 / 100.0 * (len(data) - 1))))
        idx99 = min(len(data) - 1, int(round(99 / 100.0 * (len(data) - 1))))
        assert obs.percentile(data, 50) == sorted(data)[idx50]
        assert obs.percentile(data, 99) == sorted(data)[idx99]
        assert obs.percentile([], 50) == 0.0

    def test_hub_publish_latest_wins_and_unified_prefixing(self):
        a = obs.MetricRegistry("t_hub")
        a.counter("n_total").inc()
        obs.publish(a)
        b = obs.MetricRegistry("t_hub")
        b.counter("n_total").inc(7)
        obs.publish(b)
        try:
            snap = obs.snapshot()
            assert snap["t_hub_n_total"] == 7  # latest registry owns prefix
        finally:
            obs.unpublish("t_hub")

    def test_unified_snapshot_has_no_duplicate_series(self):
        # dict construction cannot hold dupes; assert the render agrees
        text = obs.render_prometheus()
        names = [line.split(" ")[0] for line in text.splitlines()
                 if line and not line.startswith("#")]
        assert len(names) == len(set(names))


class TestSpans:
    def test_span_records_into_spans_registry(self):
        with obs.span("unit_test_region"):
            time.sleep(0.002)
        reg = obs.get_registry("jimm_spans")
        snap = reg.snapshot()
        assert snap["unit_test_region_seconds_count"] >= 1
        assert snap["unit_test_region_seconds_p50"] >= 0.002

    def test_disabled_span_is_noop_singleton(self):
        obs.set_enabled(False)
        s1, s2 = obs.span("a"), obs.span("b")
        assert s1 is s2  # shared no-op object: no allocation when off

    def test_trace_ids_unique(self):
        ids = {obs.new_trace_id() for _ in range(100)}
        assert len(ids) == 100

    def test_disabled_overhead_under_one_percent_of_a_1ms_step(self):
        # acceptance: with obs disabled, instrumentation costs < 1% of a
        # step. Budget against a (pessimistically fast) 1 ms step: the
        # disabled span must cost < 10 us per call; measure the mean over
        # enough calls to drown out timer noise.
        obs.set_enabled(False)
        n = 50_000
        t0 = time.perf_counter()
        for _ in range(n):
            with obs.span("hot_loop"):
                pass
        per_call = (time.perf_counter() - t0) / n
        assert per_call < 10e-6, f"disabled span costs {per_call * 1e6:.2f}us"


class TestGoodput:
    def test_buckets_sum_to_wall_within_2_percent(self):
        acct = obs.GoodputAccounter(obs.MetricRegistry("t_goodput"))
        with acct.measure("compile"):
            time.sleep(0.03)
        for _ in range(3):
            with acct.measure("data_wait"):
                time.sleep(0.005)
            with acct.measure("step"):
                time.sleep(0.02)
            with acct.measure("host_sync"):
                time.sleep(0.002)
        with acct.measure("checkpoint"):
            time.sleep(0.01)
        report = acct.report()
        fracs = [report[f"{b}_frac"] for b in
                 ("compile", "data_wait", "step", "checkpoint",
                  "host_sync", "other")]
        assert sum(fracs) == pytest.approx(1.0, abs=0.02)
        assert report["goodput"] == pytest.approx(
            report["step_s"] / report["wall_s"], abs=0.01)

    def test_unknown_bucket_rejected(self):
        acct = obs.GoodputAccounter(obs.MetricRegistry("t_goodput2"))
        with pytest.raises(KeyError):
            with acct.measure("coffee"):
                pass

    def test_mfu_adjusted_goodput(self):
        acct = obs.GoodputAccounter(obs.MetricRegistry("t_goodput3"))
        with acct.measure("step"):
            time.sleep(0.01)
        report = acct.report(mfu=0.5)
        assert report["mfu"] == 0.5
        assert report["mfu_adjusted_goodput"] == pytest.approx(
            report["goodput"] * 0.5, abs=1e-3)  # report() rounds its fields

    def test_registry_mirroring(self):
        reg = obs.MetricRegistry("t_goodput4")
        acct = obs.GoodputAccounter(reg)
        with acct.measure("step"):
            time.sleep(0.005)
        snap = reg.snapshot()
        assert snap["goodput_step_seconds_total"] >= 0.005
        assert 0.0 <= snap["goodput_ratio"] <= 1.0


class TestExporters:
    def test_prometheus_roundtrip(self):
        series = {"x_total": 3, "y": 1.5, "h_count": 7}
        text = obs.render_prometheus_text(series)
        assert "# TYPE x_total counter" in text
        assert "# TYPE y gauge" in text
        assert "# TYPE h_count counter" in text
        assert obs.parse_prometheus_text(text) == {
            "x_total": 3.0, "y": 1.5, "h_count": 7.0}

    def test_jsonl_exporter_measurements_format(self, tmp_path):
        path = tmp_path / "m.jsonl"
        rec = obs.JsonlExporter(str(path), phase="unit").export({"a": 1})
        line = json.loads(path.read_text().strip())
        assert line == rec
        assert line["phase"] == "unit" and "ts" in line and line["a"] == 1

    def test_console_table_and_diff(self):
        table = obs.console_table({"loss": 0.5, "steps_total": 10})
        assert "loss" in table and "steps_total" in table
        d = obs.diff_snapshots({"a": 1, "b": 2, "gone": 0},
                               {"a": 1, "b": 5, "new": 9})
        assert d["added"] == {"new": 9}
        assert d["removed"] == {"gone": 0}
        assert d["changed"]["b"]["delta"] == 3


class TestMfuDegenerate:
    def test_degenerate_inputs_return_zero_and_count(self):
        from jimm_tpu.train.metrics import mfu
        counter = obs.get_registry("jimm_train").counter(
            "mfu_degenerate_total")
        before = counter.value
        assert mfu(None, 1.0, n_devices=1) == 0.0          # cost analysis
        assert mfu(1e12, 0.0, n_devices=1) == 0.0          # zero step time
        assert mfu(1e12, -1.0, n_devices=1) == 0.0         # negative
        assert mfu(1e12, float("nan"), n_devices=1) == 0.0  # NaN time
        assert mfu(float("nan"), 1.0, n_devices=1) == 0.0  # NaN flops
        assert counter.value == before + 5

    def test_healthy_path_unchanged(self):
        import jax

        from jimm_tpu.train.metrics import device_peak_tflops, mfu
        peak = device_peak_tflops(jax.devices()[0]) * 1e12
        got = mfu(peak * 0.4, 1.0, n_devices=1)
        assert got == pytest.approx(0.4)
        assert math.isfinite(got)


class TestMetricsLoggerRegistry:
    def test_scalars_mirrored(self, tmp_path):
        from jimm_tpu.train.metrics import MetricsLogger
        reg = obs.MetricRegistry("t_logger")
        logger = MetricsLogger(print_every=0, registry=reg)
        logger.log(0, step_time_s=0.5, loss=2.0, note="non-numeric")
        logger.log(1, step_time_s=0.3, loss=1.0)
        logger.close()
        snap = reg.snapshot()
        assert snap["steps_logged_total"] == 2
        assert snap["step_time_seconds_count"] == 2
        assert snap["loss"] == 1.0  # last-value gauge
        assert "note" not in snap

    def test_no_registry_no_mirroring(self):
        from jimm_tpu.train.metrics import MetricsLogger
        logger = MetricsLogger(print_every=0)
        # sentinel name: other tests legitimately mirror common fields
        # (loss etc.) into the global jimm_train registry
        logger.log(0, zz_sentinel_unmirrored=1.0)
        logger.close()
        assert ("zz_sentinel_unmirrored"
                not in obs.get_registry("jimm_train").snapshot())


class TestServeIntegration:
    def _engine(self, **kw):
        from jimm_tpu.serve import BucketTable, InferenceEngine

        def forward(batch):
            return batch.reshape(batch.shape[0], -1)[:, :4]

        return InferenceEngine(forward, item_shape=(4, 4, 3),
                               buckets=BucketTable((1, 2, 4)),
                               max_delay_ms=2.0, **kw)

    def test_serve_metrics_publish_and_phase_decomposition(self):
        import asyncio

        engine = self._engine()
        item = np.zeros((4, 4, 3), np.float32)

        async def go():
            await engine.start()
            try:
                await asyncio.gather(*[engine.submit(item)
                                       for _ in range(8)])
            finally:
                await engine.stop()

        asyncio.run(go())
        m = engine.metrics
        snap = m.snapshot()
        # back-compat names intact
        assert snap["responses_total"] == 8
        # per-request decomposition: every phase observed per batch
        for phase in ("queue", "pad", "device", "readback"):
            assert snap[f"span_{phase}_p50_ms"] >= 0.0
            assert m.phase_percentile(phase, 50) >= 0.0
        # trace records decompose each request
        assert engine.recent_traces
        tr = engine.recent_traces[-1]
        assert set(tr) >= {"trace_id", "queue_s", "pad_s", "device_s",
                           "readback_s", "total_s"}
        assert tr["total_s"] >= tr["device_s"]
        # the unified dump carries the serve series under its prefix
        uni = obs.snapshot()
        assert uni["jimm_serve_responses_total"] == 8
        assert "jimm_serve_span_device_seconds_p50" in uni

    def test_trace_id_propagates_to_dispatch(self):
        import asyncio

        engine = self._engine()
        item = np.zeros((4, 4, 3), np.float32)

        async def go():
            await engine.start()
            try:
                await engine.submit(item, trace_id="t-fixed-id")
            finally:
                await engine.stop()

        asyncio.run(go())
        assert any(t["trace_id"] == "t-fixed-id"
                   for t in engine.recent_traces)

    def test_combined_train_and_serve_unified_dump(self):
        """The acceptance smoke in miniature: train-side goodput + serve
        engine in one process -> one snapshot with both namespaces, buckets
        summing to 100% +- 2%."""
        import asyncio

        acct = obs.GoodputAccounter()  # jimm_train registry
        with acct.measure("compile"):
            time.sleep(0.01)
        with acct.measure("step"):
            time.sleep(0.01)

        engine = self._engine()
        item = np.zeros((4, 4, 3), np.float32)

        async def go():
            await engine.start()
            try:
                await engine.submit(item)
            finally:
                await engine.stop()

        asyncio.run(go())

        uni = obs.snapshot()
        assert any(k.startswith("jimm_train_") for k in uni)
        assert any(k.startswith("jimm_serve_") for k in uni)
        report = acct.report()
        total = sum(report[f"{b}_frac"] for b in
                    ("compile", "data_wait", "step", "checkpoint",
                     "host_sync", "other"))
        assert total == pytest.approx(1.0, abs=0.02)


class TestObsCli:
    def test_snapshot_and_diff(self, tmp_path, capsys):
        from jimm_tpu.obs.cli import main
        before = tmp_path / "before.json"
        after_txt = tmp_path / "after.prom"
        before.write_text(json.dumps({"a_total": 1, "b": 2}))
        after_txt.write_text(obs.render_prometheus_text(
            {"a_total": 3, "c": 1}))

        assert main(["obs", "snapshot", str(before), "--json"]) == 0
        out = capsys.readouterr().out
        assert json.loads(out) == {"a_total": 1, "b": 2}

        # diff exits 1 when there are differences, prints the delta
        assert main(["obs", "diff", str(before), str(after_txt)]) == 1
        out = capsys.readouterr().out
        assert "a_total" in out and "+2" in out
        assert main(["obs", "diff", str(before), str(before)]) == 0

    def test_snapshot_save_for_later_diff(self, tmp_path, capsys):
        from jimm_tpu.obs.cli import main
        src = tmp_path / "metrics.prom"
        src.write_text(obs.render_prometheus_text({"x_total": 5}))
        out_json = tmp_path / "snap.json"
        assert main(["obs", "snapshot", str(src),
                     "-o", str(out_json)]) == 0
        capsys.readouterr()
        assert json.loads(out_json.read_text()) == {"x_total": 5.0}

    def test_wired_into_main_cli(self):
        from jimm_tpu.cli import build_parser
        args = build_parser().parse_args(["obs", "snapshot", "x.json"])
        assert args.obs_cmd == "snapshot"
        assert callable(args.fn)
