"""Offline analysis of committed profiler captures — **no jax import**.

A committed capture (see :mod:`jimm_tpu.obs.prof.capture`) contains the
``*.trace.json.gz`` Chrome-trace file the jax profiler writes. This module
turns those into:

- a top-k per-op table (``op_table`` / ``top_ops``): self-time, occurrence
  count, bytes accessed, achieved HBM bandwidth — FlashAttention's
  IO-accounting argument turned into a runtime artifact;
- a **direction-aware diff** between two captures (``diff_ops``): op time
  is lower-better, so a positive delta is a regression and a negative one
  an improvement, feeding the same verdict vocabulary as ``obs regress``.

Everything here is stdlib-only so ``jimm-tpu obs prof ls/show/diff`` stays
usable on a machine (or in a CI lane) with no accelerator stack installed.
The parsing core is shared with :func:`jimm_tpu.train.profile.op_stats`,
which wraps these rows in its ``OpStat`` dataclass.
"""

from __future__ import annotations

import glob
import gzip
import json
import re
from pathlib import Path

__all__ = [
    "aggregate_ops", "diff_ops", "find_trace_file", "load_trace_events",
    "op_table", "render_diff", "render_table", "top_ops",
]

#: container/framework events that would double-count their children
_NON_OP = re.compile(r"^(while\.|jit_|\d+$|SyncOnDone|.*Module)")


def find_trace_file(source: str | Path) -> Path:
    """Newest ``*.trace.json.gz`` under ``source`` (a capture dir, a raw
    ``--profile-dir``, or the file itself)."""
    source = Path(source)
    if source.is_file():
        return source
    paths = sorted(glob.glob(str(source / "**" / "*.trace.json.gz"),
                             recursive=True))
    if not paths:
        raise FileNotFoundError(f"no *.trace.json.gz under {source}")
    return Path(paths[-1])


def load_trace_events(source: str | Path) -> list[dict]:
    """The ``traceEvents`` list from the newest trace file under
    ``source`` (gzip or plain JSON)."""
    path = find_trace_file(source)
    opener = gzip.open if path.name.endswith(".gz") else open
    with opener(path, "rt") as f:
        return json.load(f)["traceEvents"]


def aggregate_ops(events: list[dict], *,
                  device: int | None = 0) -> list[dict]:
    """Aggregate device-op self times from raw trace events into rows
    ``{name, category, total_us, count, bytes_accessed, long_name}``,
    sorted by descending total time.

    ``device`` picks ONE device pid (default: the first) — under SPMD every
    core runs the same program, and summing across cores would report
    n_devices times the per-step time. ``None`` aggregates all devices."""
    pids = {e["pid"]: e["args"].get("name", "")
            for e in events if e.get("ph") == "M"
            and e.get("name") == "process_name"}
    tnames = {(e["pid"], e["tid"]): e["args"].get("name", "")
              for e in events if e.get("ph") == "M"
              and e.get("name") == "thread_name"}
    device_pids = {p for p, n in pids.items() if n.startswith("/device:")}
    if device_pids and device is not None:
        device_pids = {sorted(device_pids)[device]}
    if not device_pids:  # CPU-only capture: ops run inside the host process
        device_pids = set(pids)

    def is_op_lane(lane: str) -> bool:
        # TPU: per-core "XLA Ops" lanes; CPU: tf_XLAEigen/... executor
        # threads. Everything else (python host frames, "Steps", module
        # lanes) would double-count or pollute the aggregation.
        return "XLA Ops" in lane or lane.startswith("tf_XLA")

    have_op_lanes = any(is_op_lane(n) for n in tnames.values())

    agg: dict[str, list] = {}
    for e in events:
        if e.get("ph") != "X" or e.get("pid") not in device_pids:
            continue
        lane = tnames.get((e["pid"], e["tid"]), "")
        if have_op_lanes:
            if not is_op_lane(lane):
                continue
        elif lane == "python":
            continue
        if _NON_OP.match(e["name"]):
            continue
        a = e.get("args", {})
        r = agg.setdefault(e["name"], [0.0, 0, 0, "",
                                       a.get("hlo_category", "?")])
        r[0] += e.get("dur", 0)
        r[1] += 1
        r[2] += int(a.get("bytes_accessed", 0) or 0)
        r[3] = r[3] or a.get("long_name", "")

    rows = [{"name": k, "category": v[4], "total_us": v[0], "count": v[1],
             "bytes_accessed": v[2], "long_name": v[3]}
            for k, v in agg.items()]
    rows.sort(key=lambda r: -r["total_us"])
    return rows


def op_table(source: str | Path, *, device: int | None = 0) -> list[dict]:
    """``aggregate_ops`` over the newest trace file under ``source``."""
    return aggregate_ops(load_trace_events(source), device=device)


def top_ops(rows: list[dict], k: int = 20,
            by: str = "total_us") -> list[dict]:
    return sorted(rows, key=lambda r: -r.get(by, 0))[:k]


def _gbps(row: dict) -> float:
    if not row["total_us"]:
        return 0.0
    return row["bytes_accessed"] / (row["total_us"] * 1e-6) / 1e9


def render_table(rows: list[dict], *, top: int = 20) -> str:
    """Human-readable top-k table (us, n, MB total, GB/s)."""
    total = sum(r["total_us"] for r in rows)
    lines = [f"device op time: {total / 1e3:.2f} ms over {len(rows)} ops",
             f"{'us':>10} {'n':>5} {'MB':>9} {'GB/s':>7}  name"]
    for r in rows[:top]:
        lines.append(f"{r['total_us']:10.1f} {r['count']:5d} "
                     f"{r['bytes_accessed'] / 1e6:9.2f} {_gbps(r):7.1f}  "
                     f"{r['name'][:60]}")
    return "\n".join(lines)


def diff_ops(before: list[dict], after: list[dict], *,
             threshold: float = 0.10, top: int = 20,
             min_us: float = 1.0) -> dict:
    """Direction-aware per-op diff between two op tables.

    Op time is lower-better: an op whose ``total_us`` grew by more than
    ``threshold`` (fractionally) is a *regression*, one that shrank is an
    *improvement* — the same vocabulary ``obs regress`` gates on. Ops
    below ``min_us`` in both tables are noise and skipped. The overall
    ``verdict`` is ``"regression"`` when total device-op time grew past
    the threshold, else ``"ok"``."""
    b = {r["name"]: r for r in before}
    a = {r["name"]: r for r in after}
    regressions, improvements, added, removed = [], [], [], []
    for name in sorted(set(b) | set(a)):
        bu = b.get(name, {}).get("total_us", 0.0)
        au = a.get(name, {}).get("total_us", 0.0)
        if bu < min_us and au < min_us:
            continue
        if name not in b:
            added.append({"name": name, "after_us": au})
            continue
        if name not in a:
            removed.append({"name": name, "before_us": bu})
            continue
        delta = au - bu
        frac = delta / bu if bu else 0.0
        entry = {"name": name, "before_us": round(bu, 1),
                 "after_us": round(au, 1), "delta_us": round(delta, 1),
                 "delta_frac": round(frac, 4)}
        if frac > threshold:
            regressions.append(entry)
        elif frac < -threshold:
            improvements.append(entry)
    regressions.sort(key=lambda e: -e["delta_us"])
    improvements.sort(key=lambda e: e["delta_us"])
    total_b = sum(r["total_us"] for r in before)
    total_a = sum(r["total_us"] for r in after)
    total_frac = (total_a - total_b) / total_b if total_b else 0.0
    return {
        "total_before_us": round(total_b, 1),
        "total_after_us": round(total_a, 1),
        "total_delta_frac": round(total_frac, 4),
        "threshold": threshold,
        "regressions": regressions[:top],
        "improvements": improvements[:top],
        "added": added[:top],
        "removed": removed[:top],
        "verdict": "regression" if total_frac > threshold else "ok",
    }


def render_diff(d: dict) -> str:
    lines = [f"total device-op time: {d['total_before_us'] / 1e3:.2f} ms -> "
             f"{d['total_after_us'] / 1e3:.2f} ms "
             f"({d['total_delta_frac']:+.1%}) [{d['verdict']}]"]
    for label, mark in (("regressions", "REGRESSION"),
                        ("improvements", "+"),):
        for e in d[label]:
            lines.append(f"{mark} {e['name'][:56]}: {e['before_us']}us -> "
                         f"{e['after_us']}us ({e['delta_frac']:+.1%})")
    for e in d["added"]:
        lines.append(f"? new op {e['name'][:56]} ({e['after_us']}us)")
    for e in d["removed"]:
        lines.append(f"? gone op {e['name'][:56]} ({e['before_us']}us)")
    return "\n".join(lines)
