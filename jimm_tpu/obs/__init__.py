"""jimm_tpu.obs — unified observability: one registry, spans, goodput.

Public surface::

    from jimm_tpu import obs

    reg = obs.get_registry("jimm_train")        # namespaced registry
    reg.counter("steps_total").inc()
    with obs.span("checkpoint_save"): ...        # host timing + TraceAnnotation
    acct = obs.GoodputAccounter()
    with acct.measure("data_wait"): batch = next(it)
    obs.snapshot()                               # unified {prefix_name: value}
    obs.render_prometheus()                      # one text dump, all namespaces

Disable all optional instrumentation with ``JIMM_OBS=0`` (or
``obs.set_enabled(False)``): spans and goodput measures become no-ops;
registries keep counting (serve counters are product behavior).
"""

from jimm_tpu.obs.exporters import (JsonlExporter, console_table,
                                    diff_snapshots, parse_prometheus_text,
                                    render_prometheus_text)
from jimm_tpu.obs.goodput import BUCKETS, GoodputAccounter
from jimm_tpu.obs.registry import (Counter, DuplicateMetricError, Gauge,
                                   Histogram, MetricRegistry, enabled,
                                   get_registry, percentile, publish,
                                   registries, render_prometheus,
                                   set_enabled, snapshot, unpublish)
from jimm_tpu.obs.spans import new_trace_id, span

__all__ = [
    "BUCKETS", "Counter", "DuplicateMetricError", "Gauge", "GoodputAccounter",
    "Histogram", "JsonlExporter", "MetricRegistry", "console_table",
    "diff_snapshots", "enabled", "get_registry", "new_trace_id",
    "parse_prometheus_text", "percentile", "publish", "registries",
    "render_prometheus", "render_prometheus_text", "set_enabled", "snapshot",
    "span", "unpublish",
]
