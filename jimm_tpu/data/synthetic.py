"""Synthetic datasets for offline training demos/benchmarks.

The reference's training example depends on a tfds MNIST download
(ref `examples/vit_training.py:205-212`), which needs network. These
generators are procedural (learnable but offline) and shape-compatible with
the real pipelines.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np


def blob_classification(batch_size: int, *, image_size: int = 28,
                        num_classes: int = 4, channels: int = 3,
                        seed: int = 0, num_frames: int = 1
                        ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Classify which quadrant contains a bright Gaussian blob — a learnable
    stand-in for MNIST in the from-scratch training demo.

    ``num_frames > 1`` yields ``(B, T, H, W, C)`` clips for temporal
    towers: the blob drifts a little per frame (same label), so the
    temporal preset has motion to attend over. ``num_frames=1`` keeps the
    legacy ``(B, H, W, C)`` stream byte for byte (same RandomState draw
    order), so existing fingerprint-based smokes stay stable."""
    rng = np.random.RandomState(seed)
    grid = np.stack(np.meshgrid(np.arange(image_size), np.arange(image_size),
                                indexing="ij"), -1).astype(np.float32)
    half = image_size / 2
    centers = np.asarray([(0.25, 0.25), (0.25, 0.75), (0.75, 0.25),
                          (0.75, 0.75)], np.float32) * image_size
    while True:
        labels = rng.randint(0, num_classes, size=batch_size)
        jitter = rng.randn(batch_size, 2).astype(np.float32) * half * 0.15
        mu = centers[labels % 4] + jitter
        if num_frames > 1:
            drift = rng.randn(batch_size, 2).astype(np.float32) * half * 0.05
            t = np.arange(num_frames, dtype=np.float32)[None, :, None]
            mu_t = mu[:, None] + drift[:, None] * t      # (B, T, 2)
            d2 = np.sum((grid[None, None] - mu_t[:, :, None, None]) ** 2, -1)
        else:
            d2 = np.sum((grid[None] - mu[:, None, None]) ** 2, -1)
        images = np.exp(-d2 / (2 * (image_size * 0.08) ** 2))
        images = images[..., None].repeat(channels, -1)
        images += rng.randn(*images.shape).astype(np.float32) * 0.05
        yield images.astype(np.float32), labels.astype(np.int32)


def contrastive_pairs(batch_size: int, *, image_size: int = 32,
                      vocab_size: int = 64, seq_len: int = 8,
                      channels: int = 3, seed: int = 0,
                      shard_index: int = 0, shard_count: int = 1
                      ) -> Iterator[tuple[np.ndarray, np.ndarray]]:
    """Image/text pairs with shared latent structure: the text tokens encode
    the blob quadrant, so contrastive training has signal to align on.

    ``shard_index/shard_count`` (pass ``jax.process_index()/count()``) give
    multi-host data loading: ``batch_size`` stays the GLOBAL batch; every
    process draws the identical global stream (same seed) and yields only
    its contiguous row block, so the shards reassemble — e.g. via
    ``jax.make_array_from_process_local_data`` — into exactly the batch a
    single-process run would see."""
    if batch_size % shard_count:
        raise ValueError(f"batch_size={batch_size} not divisible by "
                         f"shard_count={shard_count}")
    if not 0 <= shard_index < shard_count:
        raise ValueError(f"shard_index={shard_index} outside "
                         f"[0, {shard_count})")
    rng = np.random.RandomState(seed)
    img_gen = blob_classification(batch_size, image_size=image_size,
                                  num_classes=4, channels=channels, seed=seed)
    lo = shard_index * (batch_size // shard_count)
    hi = lo + batch_size // shard_count
    while True:
        images, labels = next(img_gen)
        text = rng.randint(4, vocab_size, size=(batch_size, seq_len))
        text[:, 0] = labels  # class token leads the caption
        yield images[lo:hi], text[lo:hi].astype(np.int32)


def naflex_contrastive_pairs(batch_size: int, *, patch_size: int = 16,
                             max_num_patches: int = 4, vocab_size: int = 64,
                             seq_len: int = 8, seed: int = 0,
                             shard_index: int = 0, shard_count: int = 1):
    """`contrastive_pairs` in NaFlex form: the square blob images are
    resized to a cycling set of aspect ratios (wide / square / tall) before
    patchification, so every batch exercises variable grids, per-sample
    position resampling, and the padding mask. Yields
    ``((patches, spatial_shapes, mask), tokens)``."""
    from jimm_tpu.data.naflex import patchify_naflex
    from jimm_tpu.data.preprocess import resize_bilinear

    base = patch_size * 2  # native square size before aspect warping
    aspects = [(1.0, 3.0), (1.0, 1.0), (3.0, 1.0), (1.0, 2.0)]
    pairs = contrastive_pairs(batch_size, image_size=base,
                              vocab_size=vocab_size, seq_len=seq_len,
                              seed=seed, shard_index=shard_index,
                              shard_count=shard_count)
    lo = shard_index * (batch_size // shard_count)
    step = 0
    while True:
        images, tokens = next(pairs)
        warped = []
        for j, img in enumerate(images):
            # aspect keyed by GLOBAL row, preserving contrastive_pairs'
            # invariant: per-process shards reassemble into exactly the
            # single-process stream (shapes included)
            gidx = step * batch_size + lo + j
            ah, aw = aspects[gidx % len(aspects)]
            h = max(patch_size, int(base * ah))
            w = max(patch_size, int(base * aw))
            warped.append(resize_bilinear(img[None], (h, w))[0])
        step += 1
        yield (patchify_naflex(warped, patch_size=patch_size,
                               max_num_patches=max_num_patches), tokens)
