"""Sequence-parallel attention plans: ring KV rotation and Ulysses head
scatter, behind one planner.

`ring_attention.py` proved the mechanism for plain softmax: shard the
sequence over a ``"seq"`` mesh axis, rotate KV chunks with
``jax.lax.ppermute``, and fold each hop into the online-normalizer carry
(the flash carry is associative, so the result is exact). This module
generalizes it into the *production* sequence-parallel path:

- **Variants share the carry.** Softmax, key-padding-masked softmax, and
  sigmoid attention all run through one hop loop. The mask chunk travels
  WITH its KV chunk around the ring (a ``(B, Sk/p)`` additive row vector per
  device), so NaFlex batches shard their padding too. Sigmoid has no row
  normalizer — its hops are plainly additive and reuse the same loop with a
  trivial carry.

- **Custom VJP re-rotates for dK/dV.** JAX AD through a scan-of-ppermute
  would save every hop's KV chunk — O(p) copies of the full KV, exactly the
  memory the ring exists to avoid. The hand-written backward recomputes each
  hop's probabilities from the saved GLOBAL ``(o, lse)`` (one chunk each),
  rotating ``(k, v, mask, dk_acc, dv_acc)`` together so gradient
  accumulators ride the same ring; after the last hop one final ppermute
  homes dk/dv to their owner devices. Per-hop grads against global
  statistics are exact: ``p_ij = exp(s_ij - lse_i)`` and
  ``delta_i = sum_j do_ij * o_ij`` already include every other chunk's
  contribution.

- **Per-hop flash on TPU.** With ``impl="flash"`` each hop's local product
  is the PR 9 Pallas core — `ring_hop_fwd`/`ring_hop_bwd` expose the shared
  kernel with external residuals, so the ring backward drives the SAME
  ``ds = p * (dp - delta)`` kernels as the single-chip path. ``impl="auto"``
  picks flash on TPU for supported head dims, einsum elsewhere (CPU tests
  run the einsum hops).

- **Ulysses is the alternate plan, not a fork.** When ``heads % p == 0``
  an all-to-all trades seq sharding for head sharding around the UNMODIFIED
  local kernel (`parallel/ulysses.py`), moving ~``4/p`` of the activation
  bytes per device versus ring's ``2·(p-1)/p`` — cheaper for ``p > 2``.
  `plan_seq_parallel` encodes that rule; `seq_parallel_attention` applies
  it (FastUSP: ring and head-scatter are alternate plans chosen by
  topology, PAPERS.md).

Observability: every hop runs under a ``ring_hop`` span +
``jax.named_scope`` (host span measures trace-time and annotates the
profiler timeline; the named scope labels the device timeline), and the
``jimm_ring_bytes_permuted_total`` counter accounts the plan's per-step
ppermute volume (incremented per wrapper call — once per trace under jit,
i.e. the counter tracks *planned* bytes/step, correlate with step counts
for rates).
"""

from __future__ import annotations

import math
from functools import partial

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from jimm_tpu.utils.compat import axis_size, shard_map

NEG_INF = -1e30

__all__ = ["seq_parallel_attention", "ring_attention_sp", "plan_seq_parallel",
           "seqpar_comm_bytes"]


# ---------------------------------------------------------------------------
# Planner
# ---------------------------------------------------------------------------

def seqpar_comm_bytes(b: int, s: int, n: int, d: int, p: int, *,
                      itemsize: int = 2, plan: str = "ring",
                      masked: bool = False) -> int:
    """Per-device bytes moved by one FORWARD step of a sequence-parallel
    plan over a ``p``-way axis (the number `jimm_ring_bytes_permuted_total`
    accounts, and the docs/performance.md table's formula).

    ring: ``(p-1)`` hops each rotating the local K and V chunks (plus the
    f32 mask rows when masked); ulysses: tiled all_to_all of q/k/v in and o
    out, each moving ``(p-1)/p`` of the local tensor.
    """
    local = (s // p) * n * d * itemsize * b
    if plan == "ring":
        bytes_ = 2 * (p - 1) * local
        if masked:
            bytes_ += (p - 1) * b * (s // p) * 4  # f32 additive mask rows
        return bytes_
    if plan == "ulysses":
        return 4 * local * (p - 1) // p
    raise ValueError(f"unknown seq-parallel plan {plan!r}")


def plan_seq_parallel(num_heads: int, axis_n: int, *,
                      plan: str = "auto") -> str:
    """Choose ring vs Ulysses for a ``p``-way seq axis.

    Ulysses needs ``heads % p == 0`` (the all_to_all splits the head axis).
    When it qualifies, its per-device comm volume is ``4·(p-1)/p²`` of the
    sequence activations versus ring's ``2·(p-1)/p`` — strictly cheaper for
    ``p > 2`` and a tie at ``p == 2``, where ring wins by overlapping each
    hop's compute with the next ppermute. Hence: ulysses iff divisible and
    ``p > 2``."""
    if plan != "auto":
        if plan not in ("ring", "ulysses"):
            raise ValueError(f"unknown seq-parallel plan {plan!r}")
        if plan == "ulysses" and num_heads % axis_n:
            raise ValueError(
                f"ulysses needs num_heads ({num_heads}) divisible by the "
                f"seq axis ({axis_n}); use plan='ring'")
        return plan
    if num_heads % axis_n == 0 and axis_n > 2:
        return "ulysses"
    return "ring"


# ---------------------------------------------------------------------------
# Ring core: one hop loop, three variants, custom VJP
# ---------------------------------------------------------------------------

def _rotate(axis_name, perm, *xs):
    """ppermute every non-None operand one step around the ring."""
    return tuple(None if x is None else jax.lax.ppermute(x, axis_name, perm)
                 for x in xs)


def _hop_scores(q, k_cur, mask_cur, sm_scale, causal, q_pos, k_pos):
    """f32 scores for one (local q × visiting kv chunk) product:
    ``(B, N, Sq, Sk)`` with the traveling additive mask rows and (when
    causal) the global-position causal term folded in."""
    s = jnp.einsum("bqnd,bknd->bnqk", q.astype(jnp.float32) * sm_scale,
                   k_cur.astype(jnp.float32))
    if mask_cur is not None:
        s = s + mask_cur[:, None, None, :]
    if causal:
        s = s + jnp.where(k_pos[None, :] <= q_pos[:, None], 0.0,
                          NEG_INF)[None, None]
    return s


def _hop_span(j: int):
    """Host span + device named_scope for ring hop ``j`` (see module doc)."""
    from contextlib import ExitStack

    from jimm_tpu.obs.spans import span
    stack = ExitStack()
    stack.enter_context(span("ring_hop"))
    stack.enter_context(jax.named_scope(f"ring_hop{j}"))
    return stack


def _ring_fwd_local(q, k, v, maskrows, axis_name, kind, causal, sm_scale,
                    logit_bias, impl, blocks):
    """Per-device forward: returns ``(o, lse)`` (lse None for sigmoid).
    ``maskrows`` is the local additive f32 ``(B, Sk/p)`` chunk or None."""
    n_dev = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, n, d = q.shape
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
    q_pos = idx * sq + jnp.arange(sq) if causal else None

    if impl == "flash":
        return _ring_fwd_local_flash(q, k, v, maskrows, axis_name=axis_name,
                                     kind=kind, sm_scale=sm_scale,
                                     logit_bias=logit_bias, blocks=blocks,
                                     perm=perm, n_dev=n_dev)

    k_cur, v_cur, mask_cur = k, v, maskrows
    if kind == "sigmoid":
        acc = jnp.zeros((b, sq, n, d), jnp.float32)
        for j in range(n_dev):
            with _hop_span(j):
                src = (idx - j) % n_dev
                k_pos = src * sq + jnp.arange(sq) if causal else None
                s = _hop_scores(q, k_cur, mask_cur, sm_scale, causal,
                                q_pos, k_pos)
                p = jax.nn.sigmoid(s + logit_bias)
                acc = acc + jnp.einsum("bnqk,bknd->bqnd", p,
                                       v_cur.astype(jnp.float32))
                if j != n_dev - 1:
                    k_cur, v_cur, mask_cur = _rotate(
                        axis_name, perm, k_cur, v_cur, mask_cur)
        return acc.astype(q.dtype), None

    m = jnp.full((b, n, sq), NEG_INF, jnp.float32)
    l = jnp.zeros((b, n, sq), jnp.float32)
    acc = jnp.zeros((b, sq, n, d), jnp.float32)
    for j in range(n_dev):
        with _hop_span(j):
            src = (idx - j) % n_dev
            k_pos = src * sq + jnp.arange(sq) if causal else None
            s = _hop_scores(q, k_cur, mask_cur, sm_scale, causal,
                            q_pos, k_pos)
            m_new = jnp.maximum(m, jnp.max(s, axis=-1))
            p = jnp.exp(s - m_new[..., None])
            scale = jnp.exp(m - m_new)
            l = l * scale + jnp.sum(p, axis=-1)
            acc = (acc * scale.transpose(0, 2, 1)[..., None]
                   + jnp.einsum("bnqk,bknd->bqnd", p,
                                v_cur.astype(jnp.float32)))
            m = m_new
            if j != n_dev - 1:
                k_cur, v_cur, mask_cur = _rotate(
                    axis_name, perm, k_cur, v_cur, mask_cur)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    o = (acc / l_safe.transpose(0, 2, 1)[..., None]).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return o, lse


def _ring_fwd_local_flash(q, k, v, maskrows, *, axis_name, kind, sm_scale,
                          logit_bias, blocks, perm, n_dev):
    """Per-hop Pallas flash hops, merged by logsumexp reweighting (softmax)
    or plain summation (sigmoid). Runs in the flattened-heads ``(B*N, S, D)``
    space of the kernel family."""
    from jimm_tpu.ops.flash_attention import (VariantSpec, _expand_mask,
                                              _flatten_heads, ring_hop_fwd)
    b, sq, n, d = q.shape
    block_q, block_k = blocks
    spec = VariantSpec(kind="softmax" if kind == "softmax" else kind,
                       has_mask=maskrows is not None)
    q3, k3, v3 = map(_flatten_heads, (q, k, v))
    mask3 = (_expand_mask(maskrows > NEG_INF / 2, n)
             if maskrows is not None else None)

    if kind == "sigmoid":
        acc = jnp.zeros_like(q3, dtype=jnp.float32)
        for j in range(n_dev):
            with _hop_span(j):
                o_blk, _ = ring_hop_fwd(q3, k3, v3, mask3, spec, sm_scale,
                                        logit_bias, block_q, block_k)
                acc = acc + o_blk.astype(jnp.float32)
                if j != n_dev - 1:
                    k3, v3, mask3 = _rotate(axis_name, perm, k3, v3, mask3)
        return acc.astype(q.dtype).reshape(b, n, sq, d).transpose(
            0, 2, 1, 3), None

    lse = jnp.full((b * n, sq), NEG_INF, jnp.float32)
    acc = jnp.zeros_like(q3, dtype=jnp.float32)
    for j in range(n_dev):
        with _hop_span(j):
            o_blk, lse_blk = ring_hop_fwd(q3, k3, v3, mask3, spec, sm_scale,
                                          0.0, block_q, block_k)
            lse_new = jnp.logaddexp(lse, lse_blk)
            acc = (acc * jnp.exp(lse - lse_new)[..., None]
                   + o_blk.astype(jnp.float32)
                   * jnp.exp(lse_blk - lse_new)[..., None])
            lse = lse_new
            if j != n_dev - 1:
                k3, v3, mask3 = _rotate(axis_name, perm, k3, v3, mask3)
    o = acc.astype(q.dtype).reshape(b, n, sq, d).transpose(0, 2, 1, 3)
    return o, lse.reshape(b, n, sq)


def _hop_bwd_tile(q, k_cur, v_cur, mask_cur, do32, lse, delta, kind,
                  sm_scale, logit_bias, causal, q_pos, k_pos):
    """One (local q × visiting kv chunk) backward tile: recompute this
    hop's probabilities against the GLOBAL ``lse`` and return the
    ``(dq, dk, dv)`` increments. ``delta`` is None for sigmoid."""
    s = _hop_scores(q, k_cur, mask_cur, sm_scale, causal, q_pos, k_pos)
    dp = jnp.einsum("bqnd,bknd->bnqk", do32, v_cur.astype(jnp.float32))
    if kind == "sigmoid":
        p = jax.nn.sigmoid(s + logit_bias)
        ds = p * (1.0 - p) * dp
    else:
        p = jnp.exp(s - lse[..., None])
        ds = p * (dp - delta[..., None])
    dq_inc = sm_scale * jnp.einsum("bnqk,bknd->bqnd", ds,
                                   k_cur.astype(jnp.float32))
    dk_inc = sm_scale * jnp.einsum("bnqk,bqnd->bknd", ds,
                                   q.astype(jnp.float32))
    dv_inc = jnp.einsum("bnqk,bqnd->bknd", p, do32)
    return dq_inc, dk_inc, dv_inc


def _ring_bwd_local(q, k, v, maskrows, o, lse, do, axis_name, kind, causal,
                    sm_scale, logit_bias, impl, blocks):
    """Per-device backward. Recomputes each hop's probabilities against the
    GLOBAL (o, lse); (k, v, mask, dk_acc, dv_acc) rotate together and a
    final ppermute returns the accumulators to their owners."""
    n_dev = axis_size(axis_name)
    idx = jax.lax.axis_index(axis_name)
    b, sq, n, d = q.shape
    perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

    if impl == "flash":
        return _ring_bwd_local_flash(q, k, v, maskrows, o, lse, do,
                                     axis_name=axis_name, kind=kind,
                                     sm_scale=sm_scale, logit_bias=logit_bias,
                                     blocks=blocks, perm=perm, n_dev=n_dev)

    q_pos = idx * sq + jnp.arange(sq) if causal else None
    do32 = do.astype(jnp.float32)
    delta = None
    if kind == "softmax":
        # delta already includes every chunk's contribution (o is global)
        delta = jnp.sum(do32 * o.astype(jnp.float32),
                        axis=-1).transpose(0, 2, 1)  # (B, N, Sq)

    k_cur, v_cur, mask_cur = k, v, maskrows
    dq = jnp.zeros((b, sq, n, d), jnp.float32)
    dk_acc = jnp.zeros((b, sq, n, d), jnp.float32)
    dv_acc = jnp.zeros((b, sq, n, d), jnp.float32)
    for j in range(n_dev):
        with _hop_span(j):
            src = (idx - j) % n_dev
            k_pos = src * sq + jnp.arange(sq) if causal else None
            dq_inc, dk_inc, dv_inc = _hop_bwd_tile(
                q, k_cur, v_cur, mask_cur, do32, lse, delta, kind,
                sm_scale, logit_bias, causal, q_pos, k_pos)
            dq = dq + dq_inc
            dk_acc = dk_acc + dk_inc
            dv_acc = dv_acc + dv_inc
            if j != n_dev - 1:
                k_cur, v_cur, mask_cur, dk_acc, dv_acc = _rotate(
                    axis_name, perm, k_cur, v_cur, mask_cur, dk_acc, dv_acc)
    # accumulators now hold grads for chunk (idx+1) % n_dev; one more hop
    # homes them (full circle)
    dk_acc, dv_acc = _rotate(axis_name, perm, dk_acc, dv_acc)
    return (dq.astype(q.dtype), dk_acc.astype(k.dtype),
            dv_acc.astype(v.dtype),
            None if maskrows is None else jnp.zeros_like(maskrows))


def _ring_bwd_local_flash(q, k, v, maskrows, o, lse, do, *, axis_name, kind,
                          sm_scale, logit_bias, blocks, perm, n_dev):
    """Flash-kernel hops for the backward: the shared `_flash_bwd` kernels
    run per hop with external GLOBAL (o, lse) residuals — the same
    ``ds = p * (dp - delta)`` tiles as the single-chip path."""
    from jimm_tpu.ops.flash_attention import (VariantSpec, _expand_mask,
                                              _flatten_heads, ring_hop_bwd)
    b, sq, n, d = q.shape
    block_q, block_k = blocks
    spec = VariantSpec(kind="softmax" if kind == "softmax" else kind,
                       has_mask=maskrows is not None)
    q3, k3, v3, do3 = map(_flatten_heads, (q, k, v, do))
    o3 = _flatten_heads(o)
    lse3 = lse.reshape(b * n, sq) if lse is not None else None
    mask3 = (_expand_mask(maskrows > NEG_INF / 2, n)
             if maskrows is not None else None)

    dq3 = jnp.zeros_like(q3, dtype=jnp.float32)
    dk3 = jnp.zeros_like(k3, dtype=jnp.float32)
    dv3 = jnp.zeros_like(v3, dtype=jnp.float32)
    for j in range(n_dev):
        with _hop_span(j):
            dq_h, dk_h, dv_h = ring_hop_bwd(q3, k3, v3, mask3, o3, lse3, do3,
                                            spec, sm_scale, logit_bias,
                                            block_q, block_k)
            dq3 = dq3 + dq_h.astype(jnp.float32)
            dk3 = dk3 + dk_h.astype(jnp.float32)
            dv3 = dv3 + dv_h.astype(jnp.float32)
            if j != n_dev - 1:
                k3, v3, mask3, dk3, dv3 = _rotate(axis_name, perm, k3, v3,
                                                  mask3, dk3, dv3)
    dk3, dv3 = _rotate(axis_name, perm, dk3, dv3)

    def un3(x, like):
        return x.astype(like.dtype).reshape(b, n, sq, d).transpose(0, 2, 1, 3)

    return (un3(dq3, q), un3(dk3, k), un3(dv3, v),
            None if maskrows is None else jnp.zeros_like(maskrows))


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10))
def _ring_core(q, k, v, maskrows, axis_name, kind, causal, sm_scale,
               logit_bias, impl, blocks):
    o, _ = _ring_fwd_local(q, k, v, maskrows, axis_name, kind, causal,
                           sm_scale, logit_bias, impl, blocks)
    return o


def _ring_core_fwd(q, k, v, maskrows, axis_name, kind, causal, sm_scale,
                   logit_bias, impl, blocks):
    o, lse = _ring_fwd_local(q, k, v, maskrows, axis_name, kind, causal,
                             sm_scale, logit_bias, impl, blocks)
    # residuals: ONE local chunk each — no per-hop KV copies (the whole
    # point of writing this VJP by hand)
    return o, (q, k, v, maskrows, o, lse)


def _ring_core_bwd(axis_name, kind, causal, sm_scale, logit_bias, impl,
                   blocks, res, do):
    q, k, v, maskrows, o, lse = res
    dq, dk, dv, dmask = _ring_bwd_local(q, k, v, maskrows, o, lse, do,
                                        axis_name, kind, causal, sm_scale,
                                        logit_bias, impl, blocks)
    return dq, dk, dv, dmask


_ring_core.defvjp(_ring_core_fwd, _ring_core_bwd)


# ---------------------------------------------------------------------------
# Host-side wrappers
# ---------------------------------------------------------------------------

def _canon_mask_rows(mask, b: int, sk: int):
    """Bool key-padding mask ((B, Sk) or (B, 1, 1, Sk)) -> additive f32
    ``(B, Sk)`` rows (0 keep / NEG_INF drop) — the form that rotates."""
    if mask.ndim == 4:
        if mask.shape[1] != 1 or mask.shape[2] != 1:
            raise ValueError(
                "sequence-parallel attention supports KEY-PADDING masks "
                f"only ((B, Sk) or (B, 1, 1, Sk)); got {tuple(mask.shape)}")
        mask = mask[:, 0, 0, :]
    if mask.shape != (b, sk):
        raise ValueError(f"key-padding mask shape {tuple(mask.shape)} does "
                         f"not match (B, Sk)=({b}, {sk})")
    return jnp.where(mask != 0, 0.0, NEG_INF).astype(jnp.float32)


def _resolve_ring_blocks(q, k, v, n_dev: int):
    """Per-hop flash block sizes through the tune cache: keyed on the LOCAL
    chunk shapes (what each hop's kernel actually sees), kernel name
    ``"ring_attention"``. Lookup only — never a measurement."""
    from jimm_tpu.ops.flash_attention import (DEFAULT_BLOCK_K,
                                              DEFAULT_BLOCK_Q, _ceil_to,
                                              _pick_block)
    from jimm_tpu.tune import best_config
    local = lambda x: (x.shape[0], x.shape[1] // n_dev) + x.shape[2:]  # noqa: E731
    cfg = best_config("ring_attention", (local(q), local(k), local(v)),
                      (q.dtype, k.dtype, v.dtype),
                      default={"block_q": DEFAULT_BLOCK_Q,
                               "block_k": DEFAULT_BLOCK_K})
    sq = q.shape[1] // n_dev
    sk = k.shape[1] // n_dev
    block_q = min(_pick_block(sq, int(cfg["block_q"])), _ceil_to(sq, 128))
    block_k = min(_pick_block(sk, int(cfg["block_k"])), _ceil_to(sk, 128))
    return block_q, block_k


def _count_permuted_bytes(q, n_dev: int, *, plan: str, masked: bool) -> None:
    from jimm_tpu.obs.registry import get_registry
    b, s, n, d = q.shape
    by = seqpar_comm_bytes(b, s, n, d, n_dev, itemsize=q.dtype.itemsize,
                          plan=plan, masked=masked)
    get_registry("jimm_ring").counter(
        "jimm_ring_bytes_permuted_total").inc(by * n_dev)


def ring_attention_sp(q: jax.Array, k: jax.Array, v: jax.Array, *,
                      mask: jax.Array | None = None, kind: str = "softmax",
                      is_causal: bool = False, mesh: Mesh | None = None,
                      axis_name: str = "seq", impl: str = "auto",
                      logit_bias: float | None = None) -> jax.Array:
    """Exact sequence-parallel attention over ``(B, S, N, D)`` q/k/v whose
    sequence dim is sharded over ``axis_name``; the key-padding ``mask``
    (bool ``(B, S)`` or ``(B, 1, 1, S)``) shards and rotates with KV.

    ``kind``: ``"softmax"`` (optionally masked/causal) or ``"sigmoid"``
    (SigLIP pairing; ``logit_bias`` defaults to ``-log(S_global)`` exactly
    like the single-chip op). ``impl``: ``"einsum"``, ``"flash"`` (per-hop
    Pallas core; non-causal only), or ``"auto"``.
    """
    from jimm_tpu.parallel.mesh import resolve_mesh_axis
    if kind not in ("softmax", "sigmoid"):
        raise ValueError(f"unknown ring variant kind {kind!r}")
    shape = resolve_mesh_axis(mesh, axis_name)
    n_dev = shape[axis_name]
    b, s, n, d = q.shape
    if s % n_dev or k.shape[1] % n_dev:
        raise ValueError(
            f"sequence length {s} (q) / {k.shape[1]} (k) not divisible by "
            f"seq axis {axis_name}={n_dev}")
    if q.shape[1] != k.shape[1]:
        raise ValueError("ring attention shards one sequence axis; "
                         f"Sq={q.shape[1]} != Sk={k.shape[1]}")
    sm_scale = 1.0 / math.sqrt(d)
    if kind == "sigmoid" and logit_bias is None:
        logit_bias = -math.log(max(k.shape[1], 1))
    maskrows = None if mask is None else _canon_mask_rows(mask, b, k.shape[1])

    if impl == "auto":
        flash_ok = (jax.default_backend() == "tpu" and d in (64, 128, 256)
                    and s // n_dev >= 128 and not is_causal)
        impl = "flash" if flash_ok else "einsum"
    if impl not in ("einsum", "flash"):
        raise ValueError(f"unknown ring attention impl {impl!r}")
    if impl == "flash" and is_causal:
        raise ValueError("the per-hop flash ring is non-causal (the hop "
                         "mask is key-padding rows); causal softmax rings "
                         "go through parallel/ring_attention.py")
    blocks = (_resolve_ring_blocks(q, k, v, n_dev) if impl == "flash"
              else (0, 0))

    _count_permuted_bytes(q, n_dev, plan="ring", masked=mask is not None)
    lb = 0.0 if logit_bias is None else float(logit_bias)

    def local(q, k, v, mr):
        # custom_vjp nondiff args are positional by contract
        return _ring_core(q, k, v, mr, axis_name, kind, is_causal, sm_scale,
                          lb, impl, blocks)

    kwargs = {} if mesh is None else {"mesh": mesh}
    fn = shard_map(local,
                   in_specs=(P(None, axis_name),) * 4,
                   out_specs=P(None, axis_name),
                   check_vma=False, **kwargs)
    return fn(q, k, v, maskrows)


def seq_parallel_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                           mask: jax.Array | None = None,
                           kind: str = "softmax", is_causal: bool = False,
                           mesh: Mesh | None = None, axis_name: str = "seq",
                           plan: str = "auto", impl: str = "auto",
                           logit_bias: float | None = None) -> jax.Array:
    """One entry for both sequence-parallel plans: picks ring vs Ulysses via
    `plan_seq_parallel` (heads divisibility + comm cost), then dispatches.
    Exact in both cases."""
    from jimm_tpu.parallel.mesh import resolve_mesh_axis
    shape = resolve_mesh_axis(mesh, axis_name)
    n_dev = shape[axis_name]
    plan = plan_seq_parallel(q.shape[2], n_dev, plan=plan)
    if plan == "ulysses":
        from jimm_tpu.parallel.ulysses import ulysses_attention
        _count_permuted_bytes(q, n_dev, plan="ulysses",
                              masked=mask is not None)
        return ulysses_attention(q, k, v, mask=mask, kind=kind,
                                 is_causal=is_causal, mesh=mesh,
                                 axis_name=axis_name, impl=impl,
                                 logit_bias=logit_bias)
    return ring_attention_sp(q, k, v, mask=mask, kind=kind,
                             is_causal=is_causal, mesh=mesh,
                             axis_name=axis_name, impl=impl,
                             logit_bias=logit_bias)
