"""Pick the measured-best sweep variant and adopt it as the framework's
default execution config (VERDICT r3 item 2 / r4 item 7).

Reads sweep records from MEASUREMENTS.jsonl (phase "sweep", as persisted
by scripts/tpu_measure_r5.sh) or from a bench_sweep output file passed
with --from. Only records with a real mfu field count; error records,
CPU runs, --tiny validation runs, and records with no device provenance
are ignored. Prints the winner, the full ranking, and the exact flag
spelling for bench.py / docs.

With ``--apply``, writes the winner into ``jimm_tpu/adopted_runtime.json``
(with full provenance: mfu, step time, device, source commit, timestamp).
That file is consumed by ``jimm_tpu.configs.adopted_runtime`` so
``jimm train --preset <name>`` and ``bench.py`` run the measured-best
execution config by default; explicit flags still win.

    python -m scripts.adopt_sweep              # rank only
    python -m scripts.adopt_sweep --apply      # rank + write adopted file
    python -m scripts.adopt_sweep --from /tmp/sweep.log
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

REPO = pathlib.Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:  # jimm_tpu.configs import, any invocation style
    sys.path.insert(0, str(REPO))


def load_records(path: pathlib.Path, phase_filter: bool,
                 phase: str = "sweep") -> list[dict]:
    from scripts._measurements import read_records
    recs = []
    for rec in read_records(path):
        if phase_filter and rec.get("phase") != phase:
            continue
        if "variant" not in rec or not isinstance(rec.get("mfu"), float):
            continue
        # fidelity: a --tiny validation or CPU run must never supersede a
        # real TPU measurement of the same variant in the ranking; a record
        # with NO device provenance (pre-r4 sweep logs) is treated as
        # low-fidelity too (ADVICE r4) — re-measure rather than trust it
        device = str(rec.get("device", "")).lower()
        if rec.get("tiny") or "cpu" in device or not device:
            continue
        recs.append(rec)
    return recs


def rank_records(recs: list[dict]) -> list[dict]:
    """Best-first ranking with last-record-per-variant-wins (later attempts
    supersede partial earlier ones)."""
    by_variant: dict[str, dict] = {}
    for rec in recs:
        by_variant[json.dumps(rec["variant"], sort_keys=True)] = rec
    return sorted(by_variant.values(), key=lambda r: -r["mfu"])


def flags_for(variant: dict) -> str:
    """bench.py flag spelling for a sweep variant dict."""
    parts = []
    if "remat" in variant:
        parts.append(f"--remat {variant['remat']}")
    if "attn" in variant:
        parts.append(f"--attn {variant['attn']}")
    if variant.get("ln") == "fused":
        parts.append("--ln fused")
    if variant.get("fused_qkv") in ("1", "true"):
        parts.append("--fused-qkv")
    if variant.get("moment") == "bf16":
        parts.append("--moment-dtype bf16")
    if "unroll" in variant:
        parts.append(f"--unroll {variant['unroll']}")
    if "batch" in variant:
        parts.append(f"--batch-size {variant['batch']}")
    if variant.get("donate") in ("0", "false"):
        parts.append("--no-donate")
    return " ".join(parts)


def runtime_for(variant: dict) -> dict:
    """Sweep variant -> `with_runtime` kwargs (execution-strategy fields
    only; batch/moment/donate are bench-level knobs, kept in bench_flags)."""
    from jimm_tpu.configs import parse_remat
    rt: dict = {}
    if "remat" in variant:
        rt.update(parse_remat(variant["remat"]))
    if "attn" in variant:
        rt["attn_impl"] = variant["attn"]
    if "ln" in variant:
        rt["ln_impl"] = variant["ln"]
    if "fused_qkv" in variant:
        rt["fused_qkv"] = str(variant["fused_qkv"]).lower() in ("1", "true")
    if "unroll" in variant:
        rt["scan_unroll"] = int(variant["unroll"])
    return rt


def apply_adoption(best: dict, preset_name: str) -> pathlib.Path:
    """Write the winner into jimm_tpu/adopted_runtime.json (merge-preserving
    other presets' entries), with full measurement provenance."""
    import subprocess
    import time
    from jimm_tpu.configs import ADOPTED_RUNTIME_PATH
    try:
        commit = subprocess.run(["git", "-C", str(REPO), "rev-parse",
                                 "--short", "HEAD"], capture_output=True,
                                text=True, timeout=10
                                ).stdout.strip() or "unknown"
    except Exception:  # noqa: BLE001 — provenance only, never fatal
        commit = "unknown"
    data: dict = {}
    if ADOPTED_RUNTIME_PATH.exists():
        try:
            data = json.loads(ADOPTED_RUNTIME_PATH.read_text())
        except json.JSONDecodeError:
            data = {}
    variant = best["variant"]
    data.setdefault("presets", {})[preset_name] = {
        "runtime": runtime_for(variant),
        "variant": variant,
        "bench_flags": flags_for(variant),
        "provenance": {
            "mfu": best.get("mfu"),
            "step_time_ms": best.get("step_time_ms"),
            "images_per_sec": best.get("images_per_sec"),
            "device": best.get("device"),
            "measured_at": best.get("ts"),
            "adopted_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "adopted_from_commit": commit,
            "source": "scripts/adopt_sweep.py --apply",
        },
    }
    ADOPTED_RUNTIME_PATH.write_text(json.dumps(data, indent=2,
                                               sort_keys=True) + "\n")
    return ADOPTED_RUNTIME_PATH


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--from", dest="src", default=None,
                   help="bench_sweep output file (default: repo "
                        "MEASUREMENTS.jsonl, sweep phase)")
    p.add_argument("--top", type=int, default=5)
    p.add_argument("--apply", action="store_true",
                   help="write the winner to jimm_tpu/adopted_runtime.json "
                        "so CLI presets and bench.py default to it")
    p.add_argument("--preset", default="siglip-base-patch16-256",
                   help="preset the sweep measured (adoption key)")
    p.add_argument("--phase", default="sweep",
                   help="MEASUREMENTS.jsonl phase tag to rank (the watcher "
                        "persists the ViT sweep as 'vit_sweep')")
    args = p.parse_args()

    path = pathlib.Path(args.src) if args.src else REPO / "MEASUREMENTS.jsonl"
    if not path.exists():
        print(f"no records: {path} does not exist", file=sys.stderr)
        return 1
    recs = load_records(path, phase_filter=args.src is None,
                        phase=args.phase)
    # records tag the bench model they measured; a ViT sweep log must never
    # adopt under the SigLIP preset key (or vice versa). Pre-r5 records
    # without the tag pass through.
    expected_model = {"siglip-base-patch16-256": "siglip_b16_256",
                      "vit-large-patch16-384": "vit_l16_384"}.get(args.preset)
    def _model_mismatch(r):
        return (expected_model and r.get("model")
                and r["model"] != expected_model)

    dropped = [r for r in recs if _model_mismatch(r)]
    if dropped:
        print(f"ignoring {len(dropped)} records measured on "
              f"{dropped[0]['model']!r} (adopting for {args.preset!r})",
              file=sys.stderr)
        recs = [r for r in recs if not _model_mismatch(r)]
    if not recs:
        print(f"no usable sweep records (variant + float mfu) in {path}",
              file=sys.stderr)
        return 1
    ranked = rank_records(recs)

    print(f"{len(ranked)} variants measured; top {args.top}:")
    for rec in ranked[:args.top]:
        print(f"  mfu={rec['mfu']:.4f}  "
              f"step={rec.get('step_time_ms', '?')}ms  "
              f"img/s={rec.get('images_per_sec', '?')}  "
              f"{json.dumps(rec['variant'])}")
    best = ranked[0]
    print("\nadopt as bench.py defaults / run as:")
    print(f"  python bench.py {flags_for(best['variant'])}")
    if args.apply:
        path = apply_adoption(best, args.preset)
        print(f"adopted -> {path} (preset {args.preset}, "
              f"mfu={best.get('mfu')})")
    if isinstance(best.get("mfu"), float) and best["mfu"] >= 0.50:
        print(f"\nNORTH STAR MET: mfu={best['mfu']:.4f} >= 0.50")
    return 0


if __name__ == "__main__":
    sys.exit(main())
