from jimm_tpu.data.pipeline import PrefetchIterator
from jimm_tpu.data.preprocess import (CLIP_MEAN, CLIP_STD, IMAGENET_MEAN,
                                      IMAGENET_STD, SIGLIP_MEAN, SIGLIP_STD,
                                      center_crop, native_available,
                                      preprocess_batch, resize_bilinear,
                                      to_float_normalized)
from jimm_tpu.data.synthetic import blob_classification, contrastive_pairs

__all__ = [
    "PrefetchIterator", "blob_classification", "contrastive_pairs",
    "preprocess_batch", "to_float_normalized", "resize_bilinear",
    "center_crop", "native_available", "IMAGENET_MEAN", "IMAGENET_STD",
    "CLIP_MEAN", "CLIP_STD", "SIGLIP_MEAN", "SIGLIP_STD",
]
