from jimm_tpu.utils.env import configure_platform
from jimm_tpu.utils.jit import jit_forward

__all__ = ["configure_platform", "jit_forward"]
