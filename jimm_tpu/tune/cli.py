"""``jimm-tpu tune`` — sweep kernel block configs offline, inspect results.

Two verbs:

- ``run`` — measure every feasible candidate for a kernel at given shapes
  (explicit ``--kernel``/``--shapes``, or derived from a ``--preset`` +
  ``--batch-size``) and persist the winners; the next train/serve/bench
  process gets pure cache hits.
- ``ls``  — list tuned entries (kernel, shapes, config, timing) without
  importing jax (pure host tool, same rule as ``jimm-tpu aot ls``).

Wired as a subparser under the main ``jimm-tpu`` CLI (see jimm_tpu/cli.py).
"""

from __future__ import annotations

import argparse
import json

from jimm_tpu.tune.cache import TuneCache, default_root

__all__ = ["add_tune_parser", "cmd_tune"]


def _parse_shapes(text: str) -> list[tuple[int, ...]]:
    """``"8x256x12x64,8x256x12x64"`` -> [(8, 256, 12, 64), (8, 256, 12, 64)]."""
    shapes = []
    for part in text.split(","):
        dims = tuple(int(d) for d in part.strip().split("x"))
        if not dims:
            raise ValueError(f"empty shape in {text!r}")
        shapes.append(dims)
    return shapes


def _preset_points(preset_name: str, batch_size: int,
                   dtype: str) -> list[dict]:
    """The (kernel, shapes, dtypes) tuning points one preset's vision tower
    exercises: flash attention at (B, S, N, D) and LN at (B*S, width)."""
    from jimm_tpu import preset
    cfg = preset(preset_name)
    v = cfg.vision
    s, n, w = v.seq_len, v.num_heads, v.width
    d = w // n
    qkv = (batch_size, s, n, d)
    # one dtype PER OPERAND — the ops hot path keys on
    # (q.dtype, k.dtype, v.dtype), so a single-entry list would fingerprint
    # to a key best_config never looks up
    return [
        {"kernel": "flash_attention", "shapes": [qkv, qkv, qkv],
         "dtypes": [dtype] * 3},
        {"kernel": "layer_norm", "shapes": [(batch_size * s, w)],
         "dtypes": [dtype]},
    ]


def _cmd_run(args) -> int:
    from jimm_tpu.tune.api import tune_kernel
    if args.preset:
        points = _preset_points(args.preset, args.batch_size, args.dtype)
        if args.kernel:
            points = [p for p in points if p["kernel"] == args.kernel]
    else:
        if not (args.kernel and args.shapes):
            raise SystemExit("tune run needs --preset or "
                             "--kernel + --shapes")
        shapes = _parse_shapes(args.shapes)
        points = [{"kernel": args.kernel, "shapes": shapes,
                   "dtypes": [args.dtype] * len(shapes)}]
    cache = TuneCache(args.store)
    report = []
    for point in points:
        result = tune_kernel(point["kernel"], point["shapes"],
                             point["dtypes"], cache=cache, reps=args.reps)
        report.append({"kernel": point["kernel"],
                       "shapes": point["shapes"],
                       "dtypes": point["dtypes"],
                       "config": result["config"],
                       "time_s": result["time_s"],
                       "candidates": result["candidates"],
                       "fingerprint": result["fingerprint"][:16]})
    print(json.dumps({"store": str(cache.root), "tuned": report}, indent=2))
    return 0


def _cmd_ls(args) -> int:
    cache = TuneCache(args.store)
    rows = []
    for e in cache.entries():
        rows.append({"fingerprint": e.fingerprint,
                     "kernel": e.meta.get("kernel"),
                     "shapes": e.meta.get("shapes"),
                     "dtypes": e.meta.get("dtypes"),
                     "backend": e.meta.get("backend"),
                     "jax": e.meta.get("jax"),
                     "last_used": e.last_used})
    if args.json:
        print(json.dumps(rows, indent=2, sort_keys=True))
        return 0
    if not rows:
        print(f"(empty tune cache: {cache.root})")
        return 0
    for r in sorted(rows, key=lambda r: r["last_used"], reverse=True):
        shapes = ",".join("x".join(str(d) for d in s)
                          for s in (r["shapes"] or []))
        print(f"{r['fingerprint'][:16]}  {r['kernel'] or '?':<16}  "
              f"{shapes:<28}  {','.join(r['dtypes'] or [])}  "
              f"backend={r['backend'] or '?'}")
    print(f"total: {len(rows)} entries")
    return 0


def add_tune_parser(subparsers) -> None:
    """Attach the ``tune`` subcommand tree to the main CLI's subparsers."""
    p = subparsers.add_parser(
        "tune", help="autotune Pallas kernel block sizes into a "
                     "persistent cache")
    p.set_defaults(fn=cmd_tune)
    sub = p.add_subparsers(dest="tune_cmd", required=True)

    pr = sub.add_parser("run", help="sweep candidates and persist winners")
    pr.add_argument("--store", default=default_root(),
                    help="tune cache root (default: JIMM_TUNE_CACHE or "
                         "~/.cache/jimm_tpu/tune)")
    pr.add_argument("--preset", default=None,
                    help="derive tuning points from a preset's vision tower")
    pr.add_argument("--batch-size", type=int, default=8)
    pr.add_argument("--kernel", default=None,
                    choices=["flash_attention", "layer_norm"],
                    help="restrict to one kernel (with --preset) or name "
                         "the kernel for explicit --shapes")
    pr.add_argument("--shapes", default=None,
                    help="comma-separated operand shapes, dims joined with "
                         "'x', e.g. 8x256x12x64,8x256x12x64,8x256x12x64")
    pr.add_argument("--dtype", default="float32",
                    help="operand dtype (default float32)")
    pr.add_argument("--reps", type=int, default=None,
                    help="timed reps per candidate (default: 7 on TPU, "
                         "1 off-TPU)")
    pr.set_defaults(tune_func=_cmd_run)

    pl = sub.add_parser("ls", help="list tuned entries (no jax import)")
    pl.add_argument("--store", default=default_root())
    pl.add_argument("--json", action="store_true")
    pl.set_defaults(tune_func=_cmd_ls)


def cmd_tune(args) -> int:
    return args.tune_func(args)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="jimm-tpu-tune")
    sub = parser.add_subparsers(dest="command", required=True)
    add_tune_parser(sub)
    args = parser.parse_args(argv)
    return cmd_tune(args)


if __name__ == "__main__":
    raise SystemExit(main())
