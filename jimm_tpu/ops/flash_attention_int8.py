"""Pallas TPU flash attention with int8-quantized Q/K — the serving variant.

A registered low-precision variant of ``ops/flash_attention.py`` (the
Flashlight template discipline: same grid layout, same online-softmax
recurrence, same DMA-eliding causal index maps — only the score matmul
changes). Q and K are quantized symmetrically per row at trace time
(:func:`_quantize_heads`, scale = max|row|/127) so the (S, S) score matmul
runs int8 x int8 -> int32 on the MXU at twice the bf16 rate; the int32
scores dequantize through the per-row scale outer product inside
:func:`_dequant_scores` (the one sanctioned f32 upcast — JL012), and the
softmax + P@V accumulation stay in f32/storage dtype exactly as in the f32
kernel. V is NOT quantized: the probability-weighted value sum is where
per-row quantization error would compound, and keeping it full-precision is
what holds end-to-end cosine above the 0.999 parity bound the smoke
enforces.

Head dim pads to 128 lanes for the int8 operands (int8 Mosaic tiles are
(32, 128); d=64 towers would otherwise sit below the minimum lane tile).
Zero padding quantizes to zero and contributes nothing to the dot.

Forward-only by design: this is the serving fast path — training runs the
differentiable f32/bf16 kernel. Block sizes resolve through
``tune.best_config("flash_attention_int8", ...)``; VMEM per grid cell is
modeled by :func:`_per_head_vmem_bytes` (mirrored jax-free in
``tune.space.int8_flash_vmem_bytes``, sync-tested).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from jimm_tpu.ops.flash_attention import (NEG_INF, _LANES, _SEMANTICS,
                                          _bcast_lanes, _causal_kv_index,
                                          _ceil_to, _flatten_heads,
                                          _from_lanes, _interpret, _pad_seq,
                                          _pick_block, _unflatten_heads)

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512

#: same per-cell budget as the f32 kernel (of ~16MB/core VMEM)
_VMEM_BUDGET = 8 * 1024 * 1024


def _per_head_vmem_bytes(block_q: int, block_k: int, d: int) -> int:
    """Resident VMEM per head in one grid cell. int8 q/k tiles carry the
    128-padded head dim; v and the out tile keep the storage dtype (bf16
    bound); scales ride in the lse-style (hb, 1, block) layout. Mirrored
    jax-free in ``tune.space.int8_flash_vmem_bytes`` (sync-tested)."""
    dp = _ceil_to(d, _LANES)
    return (block_q * dp + block_k * dp   # int8 q/k tiles
            + 2 * block_k * d * 2         # v in + double-buffer
            + block_q * d * 2             # out tile
            + 2 * block_q * _LANES * 4    # m/l stats scratch
            + block_q * d * 4             # fp32 accumulator
            + (block_q + block_k) * 4     # per-row q/k scale tiles
            + block_q * block_k * 6)      # s fp32 + p bf16 intermediate


def _pick_hb(bn: int, block_q: int, block_k: int, d: int) -> int:
    per_head = _per_head_vmem_bytes(block_q, block_k, d)
    for hb in (8, 4, 2):
        if bn % hb == 0 and hb * per_head <= _VMEM_BUDGET:
            return hb
    return 1


def _dequant_scores(s: jax.Array, q_scale: jax.Array,
                    k_scale: jax.Array) -> jax.Array:
    """int32 score block -> f32 via the per-row quantization scales' outer
    product. The ONE sanctioned f32 upcast in this kernel (JL012)."""
    return s.astype(jnp.float32) * q_scale[:, None] * k_scale[None, :]


def _fwd_kernel(qq_ref, kq_ref, v_ref, qs_ref, ks_ref, o_ref,
                m_scr, l_scr, acc_scr, *, sk_real: int, block_k: int,
                causal: bool, sm_scale: float, n_k: int):
    qi = pl.program_id(1)
    kj = pl.program_id(2)
    hb, bq, _ = qq_ref.shape

    @pl.when(kj == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def compute():
        k_pos = kj * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (bq, block_k), 1)
        mask = k_pos < sk_real
        if causal:
            q_pos = qi * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, block_k), 0)
            mask = mask & (k_pos <= q_pos)
        for h in range(hb):
            qq = qq_ref[h]                               # (bq, dp) int8
            kq = kq_ref[h]                               # (bk, dp) int8
            v = v_ref[h]                                 # (bk, d)
            s_i32 = jax.lax.dot_general(
                qq, kq, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            s = _dequant_scores(s_i32, qs_ref[h, 0, :],
                                ks_ref[h, 0, :]) * sm_scale
            s = jnp.where(mask, s, NEG_INF)
            m_prev = _from_lanes(m_scr[h])
            l_prev = _from_lanes(l_scr[h])
            m_new = jnp.maximum(m_prev, jnp.max(s, axis=1))
            p = jnp.exp(s - m_new[:, None])
            corr = jnp.exp(m_prev - m_new)
            l_new = l_prev * corr + jnp.sum(p, axis=1)
            acc_scr[h] = acc_scr[h] * corr[:, None] + jax.lax.dot_general(
                p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            m_scr[h] = _bcast_lanes(m_new)
            l_scr[h] = _bcast_lanes(l_new)

    if causal:
        pl.when(kj * block_k <= (qi + 1) * bq - 1)(compute)
        last_j = jnp.minimum(n_k - 1, ((qi + 1) * bq - 1) // block_k)
    else:
        compute()
        last_j = n_k - 1

    @pl.when(kj == last_j)
    def _finalize():
        for h in range(hb):
            l = _from_lanes(l_scr[h])
            l_safe = jnp.where(l == 0.0, 1.0, l)
            o_ref[h] = (acc_scr[h] / l_safe[:, None]).astype(o_ref.dtype)


def _quantize_heads(x3: jax.Array, seq_p: int,
                    d_p: int) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-row int8 quantization of a head-flattened (BN, S, D)
    tensor, padded to (BN, seq_p, d_p). Returns the int8 tensor and the
    fp32 scales in the kernel's lse-style (BN, 1, seq_p) layout. Padded
    rows get scale 1.0 (finite dequant; their scores are masked anyway)."""
    xf = x3.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.where(amax > 0, amax / 127.0, 1.0)
    x_q = jnp.clip(jnp.round(xf / scale[..., None]), -127, 127)
    x_q = x_q.astype(jnp.int8)
    bn, seq, d = x3.shape
    x_q = jnp.pad(x_q, ((0, 0), (0, seq_p - seq), (0, d_p - d)))
    scale = jnp.pad(scale, ((0, 0), (0, seq_p - seq)), constant_values=1.0)
    return x_q, scale[:, None, :]


def _resolve_blocks(q, k, v, block_q, block_k):
    """Trace-time block resolution through the tune cache — lookup only.
    Explicit ints win, so the tuner's bench closures cannot recurse."""
    if block_q is not None and block_k is not None:
        return int(block_q), int(block_k)
    from jimm_tpu.tune import best_config
    cfg = best_config("flash_attention_int8",
                      (q.shape, k.shape, v.shape),
                      (q.dtype, k.dtype, v.dtype),
                      default={"block_q": DEFAULT_BLOCK_Q,
                               "block_k": DEFAULT_BLOCK_K})
    return (int(block_q if block_q is not None else cfg["block_q"]),
            int(block_k if block_k is not None else cfg["block_k"]))


def flash_attention_int8(q: jax.Array, k: jax.Array, v: jax.Array, *,
                         is_causal: bool = False,
                         block_q: int | None = None,
                         block_k: int | None = None) -> jax.Array:
    """int8-activation flash attention over ``(B, S, N, D)`` q/k/v.

    Forward-only serving variant: Q/K quantize per row to int8, the score
    matmul runs on the MXU in int8, softmax and P@V stay full-precision.
    Scale is 1/sqrt(D) like `flash_attention`. Runs the Pallas interpreter
    off-TPU so CPU tests and the quant parity harness exercise the same
    code path.
    """
    b, sq, n, d = q.shape
    sm_scale = 1.0 / (d ** 0.5)
    block_q, block_k = _resolve_blocks(q, k, v, block_q, block_k)
    block_q = min(_pick_block(sq, block_q), _ceil_to(sq, _LANES))
    block_k = min(_pick_block(k.shape[1], block_k),
                  _ceil_to(k.shape[1], _LANES))
    q3, k3, v3 = map(_flatten_heads, (q, k, v))
    bn = q3.shape[0]
    sk = k3.shape[1]
    sq_p, sk_p = _ceil_to(sq, block_q), _ceil_to(sk, block_k)
    d_p = _ceil_to(d, _LANES)
    qq, qs = _quantize_heads(q3, sq_p, d_p)
    kq, ks = _quantize_heads(k3, sk_p, d_p)
    vp = _pad_seq(v3, sk_p)
    n_q, n_k = sq_p // block_q, sk_p // block_k
    hb = _pick_hb(bn, block_q, block_k, d)
    kernel = partial(_fwd_kernel, sk_real=sk, block_k=block_k,
                     causal=is_causal, sm_scale=sm_scale, n_k=n_k)
    kv_idx = (_causal_kv_index(block_q, block_k, n_k) if is_causal
              else (lambda h, i, j: (h, j, 0)))
    kv_stat_idx = (
        (lambda h, i, j: (h, 0,
                          _causal_kv_index(block_q, block_k, n_k)(h, i, j)[1]))
        if is_causal else (lambda h, i, j: (h, 0, j)))
    o = pl.pallas_call(
        kernel,
        grid=(bn // hb, n_q, n_k),
        in_specs=[
            pl.BlockSpec((hb, block_q, d_p), lambda h, i, j: (h, i, 0)),
            pl.BlockSpec((hb, block_k, d_p), kv_idx),
            pl.BlockSpec((hb, block_k, d), kv_idx),
            pl.BlockSpec((hb, 1, block_q), lambda h, i, j: (h, 0, i)),
            pl.BlockSpec((hb, 1, block_k), kv_stat_idx),
        ],
        out_specs=pl.BlockSpec((hb, block_q, d), lambda h, i, j: (h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bn, sq_p, d), q3.dtype),
        scratch_shapes=[
            pltpu.VMEM((hb, block_q, _LANES), jnp.float32),
            pltpu.VMEM((hb, block_q, _LANES), jnp.float32),
            pltpu.VMEM((hb, block_q, d), jnp.float32),
        ],
        compiler_params=_SEMANTICS,
        interpret=_interpret(),
    )(qq, kq, vp, qs, ks)
    return _unflatten_heads(o[:, :sq], b, n)
