"""Shared spec for the recorded-golden parity suite (VERDICT r3 item 4).

The reference's tests hit real published checkpoints over the network at
test time (ref `tests/test_clip.py:10`, `tests/test_siglip.py:9`,
`tests/test_vit.py:17-52`). Here the torch oracle runs ONCE, with network,
via `scripts/dump_goldens.py`, recording logits + tower embeddings for
deterministic inputs into small `.npz` files under `tests/goldens/`;
`tests/test_goldens.py` then asserts parity offline, with neither torch nor
network in the loop. Both sides import THIS module so inputs can never
drift apart.
"""

from __future__ import annotations

import numpy as np

#: BASELINE.json tracked configs; atols are the reference's own bars
#: (ref `tests/test_vit.py:52`, `test_clip.py:48`, `test_siglip.py:69`).
#: The five reference-anchored checkpoints: every repo the reference's own
#: parity tests load (ref `tests/test_vit.py:20-22,49-52` both ViT sizes,
#: `tests/test_clip.py:10` CLIP-L/14, `tests/test_siglip.py:9` SigLIP-B/16)
#: plus CLIP-B/32, BASELINE.md tracked config #2.
GOLDEN_SPECS: dict[str, dict] = {
    "vit-base-patch16-224": {
        "repo": "google/vit-base-patch16-224", "family": "vit",
        "image_size": 224, "atol": 0.05},
    "vit-base-patch32-384": {
        "repo": "google/vit-base-patch32-384", "family": "vit",
        "image_size": 384, "atol": 0.05},
    "clip-vit-base-patch32": {
        "repo": "openai/clip-vit-base-patch32", "family": "clip",
        "image_size": 224, "ctx": 77, "atol": 1e-1},
    "clip-vit-large-patch14": {
        "repo": "openai/clip-vit-large-patch14", "family": "clip",
        "image_size": 224, "ctx": 77, "atol": 1e-1},
    "siglip-base-patch16-256": {
        "repo": "google/siglip-base-patch16-256", "family": "siglip",
        "image_size": 256, "ctx": 64, "atol": 1e-2},
}


def golden_image(size: int, n: int = 2) -> np.ndarray:
    """Deterministic NHWC 'preprocessed pixel' batch, within the value range
    mean/std-normalized images occupy. Fed identically to both models
    (HF gets the NCHW transpose), so processor differences cannot leak in."""
    rng = np.random.RandomState(1234)
    return (rng.rand(n, size, size, 3).astype(np.float32) * 2.0) - 1.0


def golden_text(family: str, ctx: int, n: int = 2) -> np.ndarray:
    """Deterministic token batch per family.

    CLIP: <start>=49406 first, EOT=49407 at a distinct position per row
    (argmax pooling — EOT is the max vocab id), low filler ids elsewhere.
    SigLIP: full random rows in-vocab (last-token pooling, no padding
    semantics to honor)."""
    rng = np.random.RandomState(4321)
    if family == "clip":
        txt = rng.randint(1000, 20000, size=(n, ctx)).astype(np.int64)
        txt[:, 0] = 49406
        for row in range(n):
            txt[row, 5 + 3 * row] = 49407
            txt[row, 5 + 3 * row + 1:] = 0
        return txt
    return rng.randint(2, 30000, size=(n, ctx)).astype(np.int64)
