"""JL014 fixture: request-keyed serving tables with no eviction."""

from collections import OrderedDict


class TenantTracker:
    def __init__(self):
        self.per_tenant = {}
        self.latencies = {}

    def on_request(self, tenant_id):
        self.per_tenant[tenant_id] = (                # JL014: grows per name
            self.per_tenant.get(tenant_id, 0) + 1)

    def on_latency(self, tenant_id, seconds):
        bucket = self.latencies.setdefault(tenant_id, [])  # JL014: same hole
        bucket.append(seconds)


class ModelRouter:
    # ok: writes are param-keyed but remove() is the eviction path, so the
    # operator (not traffic) bounds the table
    def __init__(self):
        self.engines = {}

    def add(self, name, engine):
        self.engines[name] = engine

    def remove(self, name):
        return self.engines.pop(name)


class WarmupLedger:
    # ok: keyed by the engine's own bucket sizes (a loop over config), not
    # by anything a caller passed in
    def __init__(self, sizes):
        self.report = {}
        for size in sizes:
            self.report[size] = "pending"


class ResponseCache:
    # ok: bounded LRU — the popitem eviction keeps every insert legal
    def __init__(self, capacity=128):
        self.capacity = capacity
        self.entries = OrderedDict()

    def put(self, key, value):
        self.entries[key] = value
        while len(self.entries) > self.capacity:
            self.entries.popitem(last=False)


class StatsSink:
    # ok: broad but justified — series names are code-defined constants
    def __init__(self):
        self.series = {}

    def record(self, name, value):
        self.series[name] = value  # jaxlint: disable=JL014 — code-defined metric names
