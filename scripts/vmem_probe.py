"""Validate `_pick_hb`'s VMEM model against compiled reality (VERDICT r3
weak #5: the 8 MB budget and per-head byte estimate were never checked on
TPU — an overestimate silently halves head batching, an underestimate would
OOM at exotic shapes).

Method: for each shipped (bn, seq, d) combination, force the heads-per-cell
value and ask Mosaic to COMPILE the forward and backward flash kernels.
Mosaic statically rejects kernels whose resident tiles exceed VMEM, so
"largest hb that compiles" is the hardware truth. We probe `_pick_hb`'s
choice (must compile), then one step larger (if that also compiles, the
model is conservative there). Prints one JSON line per probe:

    {"metric": "vmem_probe", "bn":..., "seq":..., "d":..., "hb":...,
     "which": "fwd"|"bwd", "chosen": bool, "ok": bool, "est_bytes": ...,
     "err": "..."}

Run on TPU (the watcher's vmem phase); off-TPU it exits 0 with a note —
interpret mode has no VMEM to validate.
"""

from __future__ import annotations

import json
import os
import sys
import time


def probe(bn: int, seq: int, d: int, budget_deadline: float) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from jimm_tpu.ops import flash_attention as fa

    rng = np.random.RandomState(0)
    # the public API takes (B, S, N, D); use N=bn heads with B=1 so the
    # flattened head-batch dim equals bn exactly
    q = jnp.asarray(rng.randn(1, seq, bn, d), jnp.bfloat16)
    k = jnp.asarray(rng.randn(1, seq, bn, d), jnp.bfloat16)
    v = jnp.asarray(rng.randn(1, seq, bn, d), jnp.bfloat16)

    # the REAL call path's block selection (incl. the ceil-to-128 cap) and
    # the REAL per-head formula — the probe must validate what ships
    _, _, _, _, block_q, block_k = fa._prologue(q, k, v, fa.DEFAULT_BLOCK_Q,
                                                fa.DEFAULT_BLOCK_K)
    chosen = fa._pick_hb(bn, block_q, block_k, d)
    est = fa._per_head_vmem_bytes(block_q, block_k, d)

    def compiles(which: str) -> tuple[bool, str]:
        try:
            if which == "fwd":
                fn = jax.jit(lambda a, b, c: fa.flash_attention(a, b, c))
            else:
                fn = jax.jit(jax.grad(
                    lambda a, b, c: fa.flash_attention(a, b, c)
                    .astype(jnp.float32).sum(), argnums=(0, 1, 2)))
            fn.lower(q, k, v).compile()
            return True, ""
        except Exception as e:  # noqa: BLE001 — Mosaic VMEM reject lands here
            return False, repr(e)[-400:]

    # probe the chosen hb and, if divisibility allows, one step larger
    candidates = [chosen]
    if bn % (chosen * 2) == 0:
        candidates.append(chosen * 2)
    orig = fa._pick_hb
    try:
        for hb in candidates:
            for which in ("fwd", "bwd"):
                if time.monotonic() > budget_deadline:
                    print(json.dumps({"metric": "vmem_probe",
                                      "note": "budget exhausted"}),
                          flush=True)
                    return
                fa._pick_hb = lambda *a, _hb=hb: _hb
                ok, err = compiles(which)
                print(json.dumps({
                    "metric": "vmem_probe", "bn": bn, "seq": seq, "d": d,
                    "block_q": block_q, "block_k": block_k, "hb": hb,
                    "which": which, "chosen": hb == chosen, "ok": ok,
                    "est_bytes_per_head": est,
                    "est_cell_bytes": est * hb, "err": err,
                }), flush=True)
    finally:
        fa._pick_hb = orig


def main() -> int:
    import jimm_tpu.utils.env
    jimm_tpu.utils.env.configure_platform()
    import jax
    if jax.default_backend() != "tpu":
        print(json.dumps({"metric": "vmem_probe",
                          "note": "not on TPU; interpret mode has no VMEM "
                                  "to validate"}), flush=True)
        return 0
    budget = float(os.environ.get("VMEM_PROBE_BUDGET_S", "540"))
    deadline = time.monotonic() + budget
    # shipped shapes: ViT-B/16-256 towers (batch 128 x 12 heads, S=256 and
    # S=64 text), long-context ring chunks, and a d=128 exotic
    for bn, seq, d in [(1536, 256, 64), (1536, 64, 64),
                       (8, 8192, 64), (16, 2048, 64), (8, 2048, 128)]:
        probe(bn, seq, d, deadline)
    return 0


if __name__ == "__main__":
    sys.exit(main())
