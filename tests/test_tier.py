"""jimm_tpu.retrieval.tier: PQ residual codec, tier planner, cold IO
engine, the budgeted TieredSearcher, and the IndexDaemon.

The searcher tests pin the tiered path to the same stable NumPy argsort
oracle the exact kernel answers to — but compare on *id strings*, because
``build_ivf`` rewrites segments cluster-major and row positions move.
The residency tests assert the two load-bearing invariants: device-
resident bytes stay flat across growth/re-tiering (fixed arena), and the
runtime ``nprobe``/growth/re-tier path never retraces. The store-
interleaving tests pin that tombstoned rows never surface through any
tier once a refresh lands.
"""

import numpy as np
import pytest

from jimm_tpu.aot.store import ArtifactStore
from jimm_tpu.obs import get_journal, get_registry, reset_journal
from jimm_tpu.retrieval import VectorStore
from jimm_tpu.retrieval.ann import (assign_clusters, clustered_rows,
                                    train_centroids)
from jimm_tpu.retrieval.tier import (AccessStats, IndexDaemon,
                                     PQ_FORMAT_VERSION, PqCodec,
                                     TierIoEngine, TieredSearcher,
                                     adc_scores, decode_cluster, decode_pq,
                                     encode_cluster, encode_pq,
                                     encode_rows, plan_tiers, query_luts,
                                     train_pq)

DIM = 32
N_CLUSTERS = 12


def seeded_store(root, n=1200, seed=3):
    rows, centers = clustered_rows(n, DIM, N_CLUSTERS, seed=seed)
    store = VectorStore(str(root))
    store.create("idx", DIM)
    store.add("idx", [f"r{i}" for i in range(n)], rows)
    cents = train_centroids(rows, N_CLUSTERS, iters=5, seed=0)
    store.set_codebook("idx", cents, seed=0)
    store.build_ivf("idx")
    return store, rows, centers, cents


def oracle_ids(queries, loaded, k=10):
    """Stable argsort oracle over the *loaded* snapshot, answered in id
    strings (positions are layout-dependent after build_ivf)."""
    scores = np.asarray(queries, np.float32) @ loaded.matrix_f32().T
    order = np.argsort(-scores, axis=1, kind="stable")[:, :k]
    return [[loaded.ids[j] for j in row] for row in order]


def recall_at(got_ids, want_ids, k=10):
    return float(np.mean([
        len(set(g[:k]) & set(w[:k])) / k
        for g, w in zip(got_ids, want_ids)]))


# ---------------------------------------------------------------------------
# PQ residual codec
# ---------------------------------------------------------------------------

class TestPqCodec:
    def _residuals(self, n=800, seed=1):
        rows, _ = clustered_rows(n, DIM, N_CLUSTERS, seed=seed)
        cents = train_centroids(rows, N_CLUSTERS, iters=4, seed=0)
        return rows - cents[assign_clusters(rows, cents)], rows

    def test_train_is_seeded_and_8x(self):
        residuals, _ = self._residuals()
        a = train_pq(residuals, seed=0)
        b = train_pq(residuals, seed=0)
        assert a == b  # same seed, bit-identical codebooks
        assert a != train_pq(residuals, seed=1)
        assert a.codebooks.shape == (DIM // 2, 256, 2)
        # 8x: D/2 uint8 codes vs 4*D float32 bytes
        assert a.code_bytes_per_row() * 8 == DIM * 4

    def test_adc_approximates_residual_dots(self):
        residuals, rows = self._residuals()
        codec = train_pq(residuals, seed=0)
        codes = encode_rows(codec, residuals)
        assert codes.shape == (len(residuals), codec.n_sub)
        assert codes.dtype == np.uint8
        q = rows[:4].astype(np.float32)
        luts = query_luts(codec, q)
        est = np.stack([adc_scores(codec, luts[b], codes)
                        for b in range(4)])
        true = q @ residuals.T
        # quantization noise must be small against the residual energy:
        # ADC only ranks within clusters; exact rescore fixes the rest
        assert np.abs(est - true).mean() < 0.25 * np.abs(true).mean()

    def test_artifact_round_trip_and_framing_errors(self):
        residuals, _ = self._residuals(n=300)
        codec = train_pq(residuals, dsub=4, ksub=64, seed=2)
        payload = encode_pq(codec)
        back = decode_pq(payload)
        assert back == codec
        assert back.meta["seed"] == 2
        with pytest.raises(ValueError, match="header"):
            decode_pq(b"garbage-without-newline")
        with pytest.raises(ValueError, match="pq_format"):
            decode_pq(b'{"pq_format":99}\n')
        with pytest.raises(ValueError, match="bytes"):
            decode_pq(payload[:-8])

    def test_validation(self):
        residuals, _ = self._residuals(n=100)
        with pytest.raises(ValueError, match="dsub"):
            train_pq(residuals, dsub=5)
        with pytest.raises(ValueError, match="ksub"):
            train_pq(residuals, ksub=512)
        codec = train_pq(residuals, seed=0)
        with pytest.raises(ValueError, match="residuals"):
            encode_rows(codec, residuals[:, : DIM // 2])


# ---------------------------------------------------------------------------
# tier planner
# ---------------------------------------------------------------------------

class TestPlanner:
    def test_greedy_by_ema_deterministic_and_budgeted(self):
        counts = np.array([100, 100, 100, 100, 0])
        ema = np.array([1.0, 3.0, 2.0, 0.5, 0.0])
        kw = dict(arena_blocks=2, block_n=128, row_bytes=DIM * 4,
                  max_bpc=4, host_budget_bytes=100 * DIM * 4)
        plan = plan_tiers(counts, ema, **kw)
        assert plan == plan_tiers(counts, ema, **kw)  # deterministic
        # hottest two fill the 2-block arena; next by EMA takes the host
        # budget; the rest is cold; the empty cluster is nominally hot
        assert plan.hot == (1, 2, 4)
        assert plan.warm == (0,)
        assert plan.cold == (3,)
        assert plan.hot_blocks <= 2
        assert plan.warm_bytes <= 100 * DIM * 4
        assert plan.tier_of(3) == "cold" and plan.tier_of(4) == "hot"

    def test_oversize_cluster_never_hot(self):
        counts = np.array([1000, 10])
        ema = np.array([9.0, 1.0])  # hottest, but 8 blocks > max_bpc
        plan = plan_tiers(counts, ema, arena_blocks=16, block_n=128,
                          row_bytes=DIM * 4, max_bpc=2)
        assert 0 in plan.warm and 1 in plan.hot

    def test_cold_disabled_spills_nothing(self):
        counts = np.array([500, 500, 500])
        plan = plan_tiers(counts, np.zeros(3), arena_blocks=1,
                          block_n=128, row_bytes=DIM * 4, max_bpc=1,
                          host_budget_bytes=0, cold_enabled=False)
        assert plan.cold == ()

    def test_access_stats_decay_and_rank(self):
        stats = AccessStats(4)
        for _ in range(5):
            stats.record(np.array([2, 2, 3]))  # dedup within a batch
        stats.record(np.array([1]))
        snap = stats.snapshot()
        assert snap[2] > snap[1] > snap[0] == 0.0
        assert stats.batches == 6
        # out-of-range ids are ignored, not crashed on
        stats.record(np.array([-1, 99]))


# ---------------------------------------------------------------------------
# cold IO engine
# ---------------------------------------------------------------------------

class TestIoEngine:
    def test_segment_round_trip_and_framing_errors(self):
        ids = np.arange(10, dtype=np.int64)
        rows = np.random.default_rng(0).standard_normal(
            (10, DIM)).astype(np.float32)
        c, got_ids, got_rows = decode_cluster(encode_cluster(7, ids, rows))
        assert c == 7
        assert np.array_equal(got_ids, ids)
        assert np.array_equal(got_rows, rows)
        with pytest.raises(ValueError, match="header"):
            decode_cluster(b"no-newline-here")
        with pytest.raises(ValueError, match="tier_format"):
            decode_cluster(b'{"tier_format":0}\n')
        with pytest.raises(ValueError, match="bytes"):
            decode_cluster(encode_cluster(7, ids, rows)[:-4])

    def test_spill_prefetch_collect(self, tmp_path):
        engine = TierIoEngine(ArtifactStore(str(tmp_path)), label="t")
        ids = np.arange(6, dtype=np.int64)
        rows = np.ones((6, DIM), np.float32)
        fp = engine.spill(3, ids, rows)
        assert engine.spill(3, ids, rows) == fp  # content-addressed
        engine.prefetch(3, fp)
        engine.prefetch(3, fp)  # dedups the read, registers a waiter
        got_ids, got_rows = engine.collect(3)
        assert np.array_equal(got_ids, ids)
        assert np.array_equal(got_rows, rows)
        got_ids2, _ = engine.collect(3)  # second waiter still served
        assert np.array_equal(got_ids2, ids)
        assert engine.pending() == 0  # last waiter consumed the entry
        with pytest.raises(KeyError):
            engine.collect(3)
        engine.close()

    def test_concurrent_searches_share_one_fetch(self, tmp_path):
        """Two request threads racing prefetch+collect on the same
        cluster must both get rows — the losing thread must never see
        the winner consume the staging entry out from under it."""
        from concurrent.futures import ThreadPoolExecutor
        engine = TierIoEngine(ArtifactStore(str(tmp_path)), label="t")
        ids = np.arange(8, dtype=np.int64)
        rows = np.full((8, DIM), 2.0, np.float32)
        fp = engine.spill(9, ids, rows)

        def one(_):
            engine.prefetch(9, fp)
            got_ids, _rows = engine.collect(9, timeout_s=10.0)
            return np.array_equal(got_ids, ids)

        try:
            for _ in range(20):
                with ThreadPoolExecutor(max_workers=8) as pool:
                    assert all(pool.map(one, range(8)))
                assert engine.pending() == 0
        finally:
            engine.close()

    def test_corrupt_segment_fails_loudly_and_quarantines(self, tmp_path):
        artifacts = ArtifactStore(str(tmp_path))
        engine = TierIoEngine(artifacts, label="t")
        artifacts.put("bad-fp", b"not a segment", {"kind": "tier_cluster"})
        reset_journal()
        try:
            engine.prefetch(5, "bad-fp")
            with pytest.raises(RuntimeError, match="cluster 5"):
                engine.collect(5)
            events = [e["event"] for e in get_journal().events()]
            assert "tier_fetch_failed" in events
            assert artifacts.get("bad-fp") is None  # quarantined
        finally:
            engine.close()
            reset_journal()

    def test_missing_artifact_fails(self, tmp_path):
        engine = TierIoEngine(ArtifactStore(str(tmp_path)), label="t")
        try:
            engine.prefetch(1, "never-spilled")
            with pytest.raises(RuntimeError, match="missing"):
                engine.collect(1)
        finally:
            engine.close()


# ---------------------------------------------------------------------------
# TieredSearcher: recall, residency, zero-recompile
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def tiered_env(tmp_path_factory):
    """One warm+cold searcher over a seeded store: 6-block arena,
    host budget small enough to force cold clusters."""
    root = tmp_path_factory.mktemp("tier")
    store, rows, centers, cents = seeded_store(root / "vs")
    searcher = TieredSearcher(
        store.load("idx"), cents, store.load_assignments("idx"), k=10,
        nprobe_max=N_CLUSTERS, device_budget_bytes=6 * 128 * DIM * 4,
        block_n=128, buckets=(8,), max_bpc=4,
        host_budget_bytes=120 * DIM * 4,
        artifacts=ArtifactStore(str(root / "art")))
    yield store, searcher, centers
    searcher.close()


class TestTieredSearcher:
    def test_plan_spans_all_three_tiers(self, tiered_env):
        _store, searcher, _ = tiered_env
        d = searcher.tier_plan().describe()
        assert d["hot_clusters"] and d["warm_clusters"] \
            and d["cold_clusters"]

    def test_full_probe_matches_oracle(self, tiered_env):
        store, searcher, centers = tiered_env
        queries, _ = clustered_rows(8, DIM, N_CLUSTERS, seed=9,
                                    center_mat=centers)
        _vals, _idx, ids = searcher.search(queries, nprobe=N_CLUSTERS)
        want = oracle_ids(queries, store.load("idx"))
        assert recall_at(ids, want) == 1.0
        stats = searcher.last_stats
        assert stats["nprobe"] == N_CLUSTERS
        assert stats["degraded_clusters"] == 0

    def test_partial_probe_recall_floor_no_retrace(self, tiered_env):
        store, searcher, centers = tiered_env
        queries, _ = clustered_rows(8, DIM, N_CLUSTERS, seed=11,
                                    center_mat=centers)
        searcher.search(queries, nprobe=2)  # warm both programs
        tc = searcher.trace_count()
        want = oracle_ids(queries, store.load("idx"))
        for nprobe in (2, 4, 8, N_CLUSTERS):
            _v, _i, ids = searcher.search(queries, nprobe=nprobe)
        assert recall_at(ids, want) >= 0.95
        assert searcher.trace_count() == tc  # runtime scalar, no retrace

    def test_cold_path_journaled_and_counted(self, tiered_env):
        store, searcher, centers = tiered_env
        queries, _ = clustered_rows(4, DIM, N_CLUSTERS, seed=13,
                                    center_mat=centers)
        reset_journal()
        try:
            searcher.search(queries, nprobe=N_CLUSTERS)  # probes all
            events = [e["event"] for e in get_journal().events()]
            assert "tier_fetch" in events
        finally:
            reset_journal()
        stats = searcher.tier_stats()
        assert stats["io_pending"] == 0
        snap = get_registry("jimm_tier").snapshot()
        assert snap["jimm_tier_cold_fetches_total"] > 0
        assert snap["jimm_tier_device_resident_bytes"] \
            == searcher.resident_bytes()

    def test_gauges_follow_latest_searcher(self, tiered_env):
        _store, searcher, _ = tiered_env
        snap = get_registry("jimm_tier").snapshot()
        assert snap["jimm_tier_hot_clusters"] \
            == len(searcher.tier_plan().hot)
        assert snap["jimm_tier_host_resident_bytes"] > 0

    def test_validation(self, tiered_env):
        _store, searcher, _ = tiered_env
        with pytest.raises(ValueError, match="nprobe"):
            searcher.search(np.zeros((1, DIM), np.float32),
                            nprobe=N_CLUSTERS + 1)
        with pytest.raises(ValueError, match="queries must be"):
            searcher.search(np.zeros((1, DIM + 1), np.float32))


class TestResidencyAcrossGrowth:
    def test_growth_retier_flat_bytes_zero_retrace(self, tmp_path):
        store, rows, centers, cents = seeded_store(tmp_path / "vs", n=900)
        searcher = TieredSearcher(
            store.load("idx"), cents, store.load_assignments("idx"),
            k=10, nprobe_max=N_CLUSTERS,
            device_budget_bytes=5 * 128 * DIM * 4, block_n=128,
            buckets=(8,), max_bpc=4,
            artifacts=ArtifactStore(str(tmp_path / "art")))
        try:
            queries, _ = clustered_rows(8, DIM, N_CLUSTERS, seed=21,
                                        center_mat=centers)
            searcher.search(queries, nprobe=4)
            tc = searcher.trace_count()
            rb = searcher.resident_bytes()
            # 3 growth rounds: add -> reload -> refresh -> search
            for round_i in range(3):
                more, _ = clustered_rows(300, DIM, N_CLUSTERS,
                                         seed=30 + round_i,
                                         center_mat=centers)
                store.add("idx", [f"g{round_i}_{j}" for j in range(300)],
                          more)
                searcher.refresh(store.load("idx"),
                                 assign=store.load_assignments("idx"))
                _v, _i, ids = searcher.search(queries, nprobe=N_CLUSTERS)
                want = oracle_ids(queries, store.load("idx"))
                assert recall_at(ids, want) >= 0.95
                assert searcher.resident_bytes() == rb  # arena is fixed
            assert searcher.trace_count() == tc  # repack, not retrace
            assert len(searcher.index) == 900 + 3 * 300
        finally:
            searcher.close()

    def test_refresh_rejects_shape_changes(self, tmp_path):
        store, rows, _centers, cents = seeded_store(tmp_path / "vs",
                                                    n=600)
        searcher = TieredSearcher(store.load("idx"), cents, k=10,
                                  nprobe_max=4, block_n=128, buckets=(1,))
        try:
            with pytest.raises(ValueError, match="centroid"):
                searcher.refresh(centroids=cents[: N_CLUSTERS - 2])
        finally:
            searcher.close()


class TestStoreInterleaving:
    """The satellite invariant: interleaved add/delete/compact under a
    live tier map never resurrects a tombstoned row through any tier."""

    def test_tombstoned_rows_never_fetched_back(self, tmp_path):
        store, rows, centers, cents = seeded_store(tmp_path / "vs",
                                                   n=1000)
        searcher = TieredSearcher(
            store.load("idx"), cents, store.load_assignments("idx"),
            k=10, nprobe_max=N_CLUSTERS,
            device_budget_bytes=4 * 128 * DIM * 4, block_n=128,
            buckets=(8,), max_bpc=4, host_budget_bytes=100 * DIM * 4,
            artifacts=ArtifactStore(str(tmp_path / "art")))
        try:
            queries, _ = clustered_rows(8, DIM, N_CLUSTERS, seed=17,
                                        center_mat=centers)
            _v, _i, before = searcher.search(queries, nprobe=N_CLUSTERS)
            # tombstone exactly the rows the searcher currently returns
            # (they live in hot, warm AND cold clusters), plus interleave
            # an add so segment layout churns
            doomed = sorted({rid for row in before for rid in row})
            assert doomed
            store.delete("idx", doomed)
            more, _ = clustered_rows(200, DIM, N_CLUSTERS, seed=23,
                                     center_mat=centers)
            store.add("idx", [f"n{j}" for j in range(200)], more)
            store.compact("idx")
            store.build_ivf("idx")
            searcher.refresh(store.load("idx"),
                             assign=store.load_assignments("idx"))
            _v, _i, after = searcher.search(queries, nprobe=N_CLUSTERS)
            got = {rid for row in after for rid in row}
            assert not got & set(doomed), \
                "tombstoned rows surfaced through a tier"
            # and the post-delete oracle still agrees
            want = oracle_ids(queries, store.load("idx"))
            assert recall_at(after, want) >= 0.95
            assert any(rid.startswith("n") for rid in got)
        finally:
            searcher.close()


# ---------------------------------------------------------------------------
# IndexDaemon
# ---------------------------------------------------------------------------

class TestIndexDaemon:
    def test_quiet_store_no_decision(self, tmp_path):
        store, *_ = seeded_store(tmp_path / "vs", n=600)
        d = IndexDaemon(store, "idx", window=1, cooldown=0)
        assert d.step() is None
        assert d.describe()["decisions"] == 0

    def test_staleness_trips_retrain_and_one_cid_chain(self, tmp_path):
        store, rows, centers, _ = seeded_store(tmp_path / "vs", n=600)
        # grow past the staleness threshold with run-less segments
        more, _ = clustered_rows(400, DIM, N_CLUSTERS, seed=5,
                                 center_mat=centers)
        store.add("idx", [f"s{j}" for j in range(400)], more)
        assert store.ann_status("idx")["staleness"] >= 0.25
        d = IndexDaemon(store, "idx", window=1, cooldown=0, seed=0)
        reset_journal()
        try:
            decision = d.step()
            assert decision["action"] == "retrain"
            assert store.ann_status("idx")["staleness"] == 0.0
            chain = [e["event"] for e in get_journal().chain(d.cid)]
            assert "tier_daemon_decision" in chain
            assert "tier_daemon_applied" in chain
        finally:
            reset_journal()
        # hysteresis: the signal is gone, the next tick stays quiet
        assert d.step() is None

    def test_tombstones_trip_compact(self, tmp_path):
        store, *_ = seeded_store(tmp_path / "vs", n=600)
        store.delete("idx", [f"r{i}" for i in range(250)])
        d = IndexDaemon(store, "idx", window=1, cooldown=0)
        decision = d.step()
        assert decision["action"] == "compact"
        assert len(store.manifest("idx").get("tombstones", [])) == 0

    def test_window_and_cooldown_bound_decisions(self, tmp_path):
        store, *_ = seeded_store(tmp_path / "vs", n=600)
        store.delete("idx", [f"r{i}" for i in range(250)])
        d = IndexDaemon(store, "idx", window=3, cooldown=2)
        # ticks 1-2: window not full yet
        assert d.tick() is None and d.tick() is None
        decision = d.tick()
        assert decision is not None  # exactly one decision fires
        # cooldown: even with the signal still tripped, the next 2 ticks
        # stay quiet
        assert d.tick() is None and d.tick() is None

    def test_drift_trips_retier_with_live_searcher(self, tmp_path):
        store, rows, centers, cents = seeded_store(tmp_path / "vs",
                                                   n=900)
        searcher = TieredSearcher(
            store.load("idx"), cents, store.load_assignments("idx"),
            k=10, nprobe_max=2, device_budget_bytes=3 * 128 * DIM * 4,
            block_n=128, buckets=(4,), max_bpc=2)
        try:
            # hammer two specific clusters so the access EMA disagrees
            # with the install-time (uniform) ranking
            probe_q = np.repeat(cents[N_CLUSTERS - 2:][:, :], 2, axis=0)
            for _ in range(12):
                searcher.search(probe_q.astype(np.float32), nprobe=2)
            d = IndexDaemon(store, "idx", searcher, window=1, cooldown=0)
            sample = d.sample()
            if sample["hot_drift"] >= d.retier_high:
                decision = d.step()
                assert decision["action"] == "retier"
                hot_now = set(searcher.tier_plan().hot)
                proposed = set(searcher.propose_plan().hot)
                assert hot_now == proposed  # re-tier converged
        finally:
            searcher.close()

    def test_start_stop_thread(self, tmp_path):
        store, *_ = seeded_store(tmp_path / "vs", n=600)
        d = IndexDaemon(store, "idx", window=1, cooldown=0)
        d.start(interval_s=0.05)
        assert d.describe()["running"]
        d.stop()
        assert not d.describe()["running"]

    def test_validation(self, tmp_path):
        store, *_ = seeded_store(tmp_path / "vs", n=600)
        with pytest.raises(ValueError, match="window"):
            IndexDaemon(store, "idx", window=0)
        with pytest.raises(ValueError, match="trip"):
            IndexDaemon(store, "idx", compact_high=0.0)
