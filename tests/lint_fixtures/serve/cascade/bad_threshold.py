"""Fixture: hardcoded confidence thresholds in cascade routing code (JL021)."""


def route(scores, escalation_threshold=0.95):          # JL021 line 4: default
    confidence = scores.max()
    if confidence >= 0.92:                             # JL021 line 6: comparison
        return "accept"
    return "escalate"


class BadRouter:
    def __init__(self, stages):
        self.stages = stages
        self.confidence_floor = 0.9                    # JL021 line 14: assignment
        self.margin_threshold: float = -0.05           # JL021 line 15: assignment

    def build(self):
        return make_router(self.stages, threshold=0.88)  # JL021 line 18: keyword


def make_router(stages, **kw):
    return kw


def fine(calibration, confidence):
    # Loading from a fitted artifact and formatting are fine: no literal
    # ever binds to or gates on a threshold-named value here.
    threshold = calibration.threshold
    shown = round(confidence, 6)
    if confidence >= threshold:
        return shown
    return None
