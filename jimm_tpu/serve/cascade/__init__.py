"""Confidence-cascade serving: route cheap, escalate on doubt.

Three pieces over the existing serving substrate:

- :mod:`~jimm_tpu.serve.cascade.calibrate` — fit the confidence threshold
  on a holdout set for a target top-1 disagreement rate and persist the
  result as a content-addressed artifact on the AOT store (routers never
  ship hardcoded thresholds; lint rule JL021 enforces it).
- :mod:`~jimm_tpu.serve.cascade.router` — requests hit the cheapest
  resident pool model first and escalate to wider dtypes when the
  calibrated confidence signal (temperature-scaled logit margin,
  optionally cross-checked by embedding-neighbor agreement) says the
  cheap answer is not trustworthy.
- :mod:`~jimm_tpu.serve.cascade.autoscale` — a bounded, hysteretic
  control loop converting SLO burn rates and per-class queue depth into
  residency actions: shift replicas between pool models via
  ``engine.replan``, hot-swap dtypes via ``ModelPool.swap``.

See docs/cascade.md for the calibration workflow and the measured
disagreement/cost table.
"""

from jimm_tpu.serve.cascade.autoscale import CascadeAutoscaler, ScaleTarget
from jimm_tpu.serve.cascade.calibrate import (CascadeCalibration,
                                              fit_calibration,
                                              fit_from_logits,
                                              list_calibrations,
                                              load_calibration,
                                              save_calibration)
from jimm_tpu.serve.cascade.router import (CascadeResult, CascadeRouter,
                                           CascadeStage)

__all__ = [
    "CascadeAutoscaler",
    "CascadeCalibration",
    "CascadeResult",
    "CascadeRouter",
    "CascadeStage",
    "ScaleTarget",
    "fit_calibration",
    "fit_from_logits",
    "list_calibrations",
    "load_calibration",
    "save_calibration",
]
