"""jimm_tpu — a TPU-native image-model framework (ViT / CLIP / SigLIP).

TPU-first rebuild of the capabilities of `pythoncrazy/jimm`: flax-NNX models
with scanned layer stacks, logical-axis sharding policies over `jax.sharding`
meshes, pure-safetensors HuggingFace checkpoint loading (zero torch), Pallas
flash attention, and distributed contrastive training with a ring sigmoid
loss.
"""

def _check_versions() -> None:
    """Fail fast with a clear message on JAX/flax older than the tested
    floor (pyproject.toml mirrors these; pip cannot enforce them for
    source checkouts or pre-installed environments)."""
    import jax
    from flax import __version__ as flax_version

    def parse(v: str) -> tuple[int, ...]:
        parts = []
        for p in v.split(".")[:3]:
            digits = "".join(ch for ch in p if ch.isdigit())
            if not digits:
                break
            parts.append(int(digits))
        return tuple(parts)

    floors = (("jax", jax.__version__, (0, 4, 35)),
              ("flax", flax_version, (0, 10)))
    for name, have, floor in floors:
        if parse(have) and parse(have) < floor:
            raise ImportError(
                f"jimm_tpu requires {name} >= {'.'.join(map(str, floor))}, "
                f"found {have}. Upgrade with `pip install -U {name}` "
                f"(TPU: `pip install -U 'jax[tpu]'`).")


_check_versions()

# imported for its side effects too: backfills nnx module/class attributes
# (to_flat_state, Variable.set_value, ...) that flax 0.10 lacks, before any
# model/weights code touches them
import jimm_tpu.utils.compat  # noqa: E402,F401  isort: skip

from jimm_tpu.configs import (CLIPConfig, SigLIPConfig, TextConfig,
                              TransformerConfig, ViTConfig, VisionConfig,
                              PRESETS, RUNTIME_FIELDS, preset, with_runtime)
from jimm_tpu.models import CLIP, SigLIP, VisionTransformer

__version__ = "0.1.0"

__all__ = [
    "CLIP", "SigLIP", "VisionTransformer",
    "CLIPConfig", "SigLIPConfig", "ViTConfig", "VisionConfig", "TextConfig",
    "TransformerConfig", "PRESETS", "preset",
    "RUNTIME_FIELDS", "with_runtime",
]
