"""Ring attention (sequence parallelism) vs full-attention oracle."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu.ops.attention import reference_attention
from jimm_tpu.parallel import make_mesh
from jimm_tpu.parallel.ring_attention import ring_attention


@pytest.fixture(scope="module")
def mesh(eight_devices):
    return make_mesh({"seq": 8})


@pytest.mark.parametrize("causal", [False, True])
def test_matches_full_attention(rng, mesh, causal):
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.5)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh=mesh, is_causal=causal)
    ref = reference_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5)


def test_sharded_inputs_under_jit(rng, mesh):
    from jax.sharding import NamedSharding, PartitionSpec as P
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32))
               for _ in range(3))
    sharding = NamedSharding(mesh, P(None, "seq"))
    qs, ks, vs = (jax.device_put(x, sharding) for x in (q, k, v))
    out = jax.jit(lambda a, b, c: ring_attention(a, b, c, mesh=mesh))(qs, ks, vs)
    ref = reference_attention(q, k, v)
    np.testing.assert_allclose(np.asarray(out), ref, atol=2e-5)
    # output stays sequence-sharded — no gather materializes the full seq
    assert out.sharding.spec == P(None, "seq")


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_gradients_match_full_attention(rng, mesh, causal):
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32) * 0.5)
               for _ in range(3))

    def loss_ring(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh,
                                      is_causal=causal) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(reference_attention(q, k, v, is_causal=causal) ** 2)

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gf = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for name, a, b in zip("qkv", gr, gf):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=f"d{name}")


@pytest.mark.parametrize("causal", [False, True])
def test_flash_ring_matches_full_attention(rng, mesh, causal):
    """Flash-within-chip x ring-across-chips composition; causal runs
    block-causally (own chunk causal, earlier full, later skipped)."""
    q, k, v = (jnp.asarray(rng.randn(2, 64, 2, 16).astype(np.float32) * 0.5)
               for _ in range(3))
    out = ring_attention(q, k, v, mesh=mesh, impl="flash", is_causal=causal)
    ref = reference_attention(q, k, v, is_causal=causal)
    np.testing.assert_allclose(out, ref, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.slow
def test_flash_ring_gradients_match(rng, mesh, causal):
    q, k, v = (jnp.asarray(rng.randn(1, 32, 2, 8).astype(np.float32) * 0.5)
               for _ in range(3))

    def loss(fn):
        return jax.grad(
            lambda q, k, v: jnp.sum(fn(q, k, v) ** 2), argnums=(0, 1, 2)
        )(q, k, v)

    gr = loss(lambda q, k, v: ring_attention(q, k, v, mesh=mesh,
                                             impl="flash", is_causal=causal))
    gf = loss(lambda q, k, v: reference_attention(q, k, v,
                                                  is_causal=causal))
    for name, a, b in zip("qkv", gr, gf):
        np.testing.assert_allclose(a, b, atol=1e-4, err_msg=f"d{name}")


def test_transformer_ring_impl_matches_xla(rng, mesh):
    """attn_impl='ring' inside a full encoder stack under a seq-sharded mesh
    equals the single-device xla path."""
    import jax.numpy as jnp
    from flax import nnx
    from jimm_tpu.configs import TransformerConfig
    from jimm_tpu.nn.transformer import Transformer
    from jimm_tpu.parallel import (SEQUENCE_PARALLEL, make_mesh, shard_batch,
                                   use_sharding)

    sp_mesh = make_mesh({"data": 1, "seq": 8})
    x = rng.randn(2, 64, 32).astype(np.float32)

    base = dict(width=32, depth=2, num_heads=2, mlp_dim=64)
    plain = Transformer(TransformerConfig(**base, attn_impl="xla"),
                        nnx.Rngs(0))
    ref = np.asarray(plain(jnp.asarray(x)))

    ringed = Transformer(TransformerConfig(**base, attn_impl="ring"),
                         nnx.Rngs(0))
    with use_sharding(sp_mesh, SEQUENCE_PARALLEL):
        xs = shard_batch(x, sp_mesh, SEQUENCE_PARALLEL)
        out = np.asarray(ringed(xs))
    np.testing.assert_allclose(out, ref, atol=2e-5)
