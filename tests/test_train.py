"""Training-loop tests: loss correctness (ring vs dense), step mechanics,
FSDP training on a virtual mesh, checkpoint save/restore/resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from jimm_tpu import (SigLIP, SigLIPConfig, TextConfig, VisionTransformer,
                      ViTConfig, VisionConfig)
from jimm_tpu.parallel import (DATA_PARALLEL, FSDP, make_mesh, shard_batch,
                               use_sharding)
from jimm_tpu.train import (CheckpointManager, OptimizerConfig,
                            clip_softmax_loss, make_classifier_train_step,
                            make_contrastive_train_step, make_optimizer,
                            ring_clip_infonce_loss, ring_sigmoid_loss,
                            sigmoid_pairwise_loss)


def tiny_vit(seed=0):
    cfg = ViTConfig(vision=VisionConfig(image_size=16, patch_size=8, width=32,
                                        depth=2, num_heads=2, mlp_dim=64,
                                        ln_eps=1e-12),
                    num_classes=4)
    return VisionTransformer(cfg, rngs=nnx.Rngs(seed))


def tiny_siglip(seed=0):
    cfg = SigLIPConfig(
        vision=VisionConfig(image_size=16, patch_size=8, width=32, depth=2,
                            num_heads=2, mlp_dim=64, act="gelu_tanh",
                            pooling="map"),
        text=TextConfig(vocab_size=64, context_length=8, width=32, depth=2,
                        num_heads=2, mlp_dim=64, act="gelu_tanh", causal=False,
                        pooling="last", proj_bias=True),
        projection_dim=32)
    return SigLIP(cfg, rngs=nnx.Rngs(seed))


# ---------------------------------------------------------------------------
# Losses
# ---------------------------------------------------------------------------

def test_ring_sigmoid_matches_dense(rng, eight_devices):
    img = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    txt = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    scale, bias = jnp.asarray(1.0), jnp.asarray(-2.0)
    mesh = make_mesh({"data": 8})
    dense = sigmoid_pairwise_loss(img, txt, scale, bias)
    ring = ring_sigmoid_loss(img, txt, scale, bias, mesh=mesh)
    np.testing.assert_allclose(ring, dense, rtol=1e-5)


def test_ring_sigmoid_gradients_match_dense(rng, eight_devices):
    """Gradient must flow through the traveling ppermute chunks."""
    img = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    txt = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    scale, bias = jnp.asarray(1.0), jnp.asarray(-2.0)
    mesh = make_mesh({"data": 8})
    gd = jax.grad(lambda a, b, s, z: sigmoid_pairwise_loss(a, b, s, z),
                  argnums=(0, 1, 2, 3))(img, txt, scale, bias)
    gr = jax.grad(lambda a, b, s, z: ring_sigmoid_loss(a, b, s, z, mesh=mesh),
                  argnums=(0, 1, 2, 3))(img, txt, scale, bias)
    for d, r in zip(gd, gr):
        np.testing.assert_allclose(r, d, atol=1e-6)


def test_ring_infonce_matches_dense(rng, eight_devices):
    img = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    txt = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    scale = jnp.asarray(1.5)
    mesh = make_mesh({"data": 8})
    dense = clip_softmax_loss(img, txt, scale)
    ring = ring_clip_infonce_loss(img, txt, scale, mesh=mesh)
    np.testing.assert_allclose(ring, dense, rtol=1e-5)


def test_ring_infonce_gradients_match_dense(rng, eight_devices):
    """Gradient must flow through both the traveling text chunks AND the
    traveling streaming-logsumexp stats (the carried max-correction)."""
    img = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    txt = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    scale = jnp.asarray(1.5)
    mesh = make_mesh({"data": 8})
    gd = jax.grad(lambda a, b, s: clip_softmax_loss(a, b, s),
                  argnums=(0, 1, 2))(img, txt, scale)
    gr = jax.grad(
        lambda a, b, s: ring_clip_infonce_loss(a, b, s, mesh=mesh),
        argnums=(0, 1, 2))(img, txt, scale)
    for d, r in zip(gd, gr):
        np.testing.assert_allclose(r, d, atol=1e-6)


def test_ring_infonce_hybrid_tuple_axis(rng, eight_devices):
    """The ring must linearize over a (DCN, ICI) product axis like the
    sigmoid ring does — batch sharded over replica x data."""
    img = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    txt = jnp.asarray(rng.randn(16, 8).astype(np.float32))
    scale = jnp.asarray(1.5)
    mesh = make_mesh({"replica": 2, "data": 4})
    dense = clip_softmax_loss(img, txt, scale)
    ring = ring_clip_infonce_loss(img, txt, scale, mesh=mesh,
                                  axis_name=("replica", "data"))
    np.testing.assert_allclose(ring, dense, rtol=1e-5)


def test_clip_softmax_loss_sanity(rng):
    """Perfectly aligned embeddings with a big scale -> near-zero loss."""
    emb = jnp.asarray(np.eye(8, 16, dtype=np.float32))
    loss_aligned = clip_softmax_loss(emb, emb, jnp.asarray(4.0))
    loss_random = clip_softmax_loss(
        emb, jnp.asarray(rng.randn(8, 16).astype(np.float32)),
        jnp.asarray(4.0))
    assert float(loss_aligned) < 0.05 < float(loss_random)


# ---------------------------------------------------------------------------
# Train steps
# ---------------------------------------------------------------------------

def test_classifier_train_step_decreases_loss(rng):
    model = tiny_vit()
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-2,
                                                warmup_steps=0))
    step = make_classifier_train_step()
    images = jnp.asarray(rng.randn(16, 16, 16, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 4, size=(16,)))
    first = None
    for _ in range(20):
        metrics = step(model, opt, images, labels)
        first = first if first is not None else float(metrics["loss"])
    assert float(metrics["loss"]) < first * 0.5
    assert float(metrics["accuracy"]) >= 0.5


@pytest.mark.slow
def test_contrastive_ring_train_step(rng, eight_devices):
    """SigLIP ring-loss training on a DP mesh must run and reduce loss."""
    mesh = make_mesh({"data": 8})
    model = tiny_siglip()
    opt = make_optimizer(model, OptimizerConfig(learning_rate=3e-3))
    step = make_contrastive_train_step("siglip_ring", mesh=mesh)
    images = rng.randn(16, 16, 16, 3).astype(np.float32)
    text = rng.randint(1, 64, size=(16, 8))
    with use_sharding(mesh, DATA_PARALLEL):
        img_b = shard_batch(images, mesh, DATA_PARALLEL)
        txt_b = shard_batch(text, mesh, DATA_PARALLEL)
        losses = [float(step(model, opt, img_b, txt_b)["loss"])
                  for _ in range(10)]
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_ring_equals_dense_train_step(eight_devices):
    """Ring-loss model gradients == dense-loss model gradients (same init,
    same batch). Gradient equality implies identical optimizer steps, so the
    full dense-vs-ring train-step pair isn't traced separately (it cost 2
    more 8-device compiles for no extra coverage; post-Adam params can also
    drift — the normalized update amplifies fp32 reduction-order noise).

    Owns its rng (NOT the session fixture): the comparison sits near fp32
    reduction-order noise (measured up to ~1.4e-5 abs on O(10) gradients
    across seeds), so the data must not shift with suite composition."""
    rng = np.random.RandomState(0)
    mesh = make_mesh({"data": 8})
    images = rng.randn(8, 16, 16, 3).astype(np.float32)
    text = rng.randint(1, 64, size=(8, 8))

    from jimm_tpu.train import contrastive_loss_fn
    m = tiny_siglip()
    gd = nnx.grad(lambda mm: contrastive_loss_fn(
        mm, jnp.asarray(images), jnp.asarray(text), kind="siglip"))(m)
    with use_sharding(mesh, DATA_PARALLEL):
        gr = nnx.grad(lambda mm: contrastive_loss_fn(
            mm, shard_batch(images, mesh, DATA_PARALLEL),
            shard_batch(text, mesh, DATA_PARALLEL),
            kind="siglip_ring", mesh=mesh))(m)
    for (kd, vd), (kr, vr) in zip(nnx.to_flat_state(gd),
                                  nnx.to_flat_state(gr)):
        np.testing.assert_allclose(np.asarray(vr.get_value()),
                                   np.asarray(vd.get_value()), atol=5e-5,
                                   err_msg=str(kd))


@pytest.mark.slow
def test_fsdp_training_runs(rng, eight_devices):
    mesh = make_mesh({"data": 8})
    model = VisionTransformer(
        ViTConfig(vision=VisionConfig(image_size=16, patch_size=8, width=32,
                                      depth=2, num_heads=2, mlp_dim=64,
                                      ln_eps=1e-12), num_classes=4),
        rngs=nnx.Rngs(0), mesh=mesh, rules=FSDP)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-2))
    step = make_classifier_train_step()
    with use_sharding(mesh, FSDP):
        images = shard_batch(rng.randn(16, 16, 16, 3).astype(np.float32),
                             mesh, FSDP)
        labels = shard_batch(rng.randint(0, 4, size=(16,)), mesh, FSDP)
        l0 = float(step(model, opt, images, labels)["loss"])
        for _ in range(5):
            metrics = step(model, opt, images, labels)
    assert float(metrics["loss"]) < l0


# ---------------------------------------------------------------------------
# Checkpointing
# ---------------------------------------------------------------------------

def test_checkpoint_save_restore_resume(tmp_path, rng):
    model = tiny_vit()
    opt = make_optimizer(model, OptimizerConfig(learning_rate=1e-2))
    step = make_classifier_train_step()
    images = jnp.asarray(rng.randn(8, 16, 16, 3).astype(np.float32))
    labels = jnp.asarray(rng.randint(0, 4, size=(8,)))
    for _ in range(3):
        step(model, opt, images, labels)

    mgr = CheckpointManager(tmp_path / "ckpt")
    assert mgr.save(3, model, opt, force=True)
    mgr.wait()

    # continue training the original for 2 more steps
    for _ in range(2):
        expected = step(model, opt, images, labels)

    # restore into a freshly-initialized model+opt and replay the same 2 steps
    model2 = tiny_vit(seed=123)
    opt2 = make_optimizer(model2, OptimizerConfig(learning_rate=1e-2))
    mgr2 = CheckpointManager(tmp_path / "ckpt")
    assert mgr2.restore(model2, opt2) == 3
    for _ in range(2):
        resumed = step(model2, opt2, images, labels)
    np.testing.assert_allclose(float(resumed["loss"]),
                               float(expected["loss"]), rtol=1e-6)
    mgr.close()
    mgr2.close()


def test_checkpoint_restores_across_topologies(tmp_path, rng, eight_devices):
    """Elastic-recovery story (SURVEY §5 failure row): a checkpoint written
    under one mesh/sharding restores onto a different topology — each param
    lands in the new model's current sharding."""
    from jimm_tpu.parallel import TENSOR_PARALLEL

    def build(mesh, rules, seed=0):
        cfg = ViTConfig(vision=VisionConfig(image_size=16, patch_size=8,
                                            width=32, depth=2, num_heads=2,
                                            mlp_dim=64, ln_eps=1e-12),
                        num_classes=4)
        return VisionTransformer(cfg, rngs=nnx.Rngs(seed), mesh=mesh,
                                 rules=rules)

    fsdp_mesh = make_mesh({"data": 8})
    model = build(fsdp_mesh, FSDP)
    images = jnp.asarray(rng.randn(4, 16, 16, 3).astype(np.float32))
    ref = np.asarray(model(images))

    mgr = CheckpointManager(tmp_path / "x")
    assert mgr.save(0, model, force=True)
    mgr.wait()
    mgr.close()

    # restore onto a (data=4, model=2) TP mesh
    tp_mesh = make_mesh({"data": 4, "model": 2})
    model2 = build(tp_mesh, TENSOR_PARALLEL, seed=99)
    mgr2 = CheckpointManager(tmp_path / "x")
    assert mgr2.restore(model2) == 0
    mgr2.close()
    np.testing.assert_allclose(np.asarray(model2(images)), ref, atol=1e-5)
    # params really live on the TP mesh sharding
    kernel = model2.vision.encoder.blocks.mlp.fc1.kernel
    assert kernel.get_value().sharding.mesh.shape == dict(tp_mesh.shape)


def test_checkpoint_relayouts_baked_placement(tmp_path, rng, eight_devices):
    """A checkpoint saved with pp_stages-baked (schedule-ordered) storage
    restores into ANY other placement — different stage count, or canonical
    (no pipeline) — by re-permuting layer rows through canonical order.
    Every shape matches, so without the relayout rows would silently land
    permuted."""
    import numpy as _np

    from jimm_tpu import SigLIP
    from jimm_tpu.configs import SigLIPConfig, TextConfig, VisionConfig
    from jimm_tpu.parallel import PIPELINE
    from jimm_tpu.parallel.pipeline import circular_layer_order

    def build(pp_stages):
        pp = (dict(pipeline=True, pp_microbatches=4, pp_virtual=2,
                   pp_stages=pp_stages) if pp_stages else {})
        cfg = SigLIPConfig(
            vision=VisionConfig(image_size=32, patch_size=16, width=32,
                                depth=8, num_heads=2, mlp_dim=64,
                                act="gelu_tanh", pooling="map", **pp),
            text=TextConfig(vocab_size=64, context_length=8, width=32,
                            depth=8, num_heads=2, mlp_dim=64, act="gelu_tanh",
                            causal=False, pooling="last", proj_bias=True,
                            **pp),
            projection_dim=32)
        if not pp_stages:
            return SigLIP(cfg, rngs=nnx.Rngs(0))
        mesh = make_mesh({"data": 8 // pp_stages, "stage": pp_stages})
        return SigLIP(cfg, rngs=nnx.Rngs(0), mesh=mesh, rules=PIPELINE)

    def canonical_fc1(model, pp_stages):
        stored = np.asarray(
            model.vision.encoder.blocks.mlp.fc1.kernel.get_value())
        if not pp_stages:
            return stored
        order = circular_layer_order(8, pp_stages, 2)
        inv = _np.empty(8, _np.int64)
        inv[order] = _np.arange(8)
        return stored[inv]

    model = build(pp_stages=4)
    want = canonical_fc1(model, 4)
    mgr = CheckpointManager(tmp_path / "pp")
    assert mgr.save(0, model, force=True)
    mgr.wait()
    mgr.close()

    mgr2 = CheckpointManager(tmp_path / "pp")
    # different schedule order: rows re-permuted 4-stage -> 2-stage
    other = build(pp_stages=2)
    assert mgr2.restore(other) == 0
    np.testing.assert_array_equal(canonical_fc1(other, 2), want)
    # canonical (unpipelined) model: rows land in layer order
    plain = build(pp_stages=0)
    assert mgr2.restore(plain) == 0
    np.testing.assert_array_equal(canonical_fc1(plain, 0), want)
    # identical placement: untouched fast path
    same = build(pp_stages=4)
    assert mgr2.restore(same) == 0
    np.testing.assert_array_equal(canonical_fc1(same, 4), want)
    mgr2.close()
