"""Pure-numpy safetensors reader/writer — no torch, no Rust wheel needed.

The format (https://github.com/huggingface/safetensors) is: 8-byte LE uint64
header length, a JSON header mapping tensor name -> {dtype, shape,
data_offsets}, then raw little-endian tensor bytes. The reference depends on
the `safetensors` wheel (ref `src/jimm/common/utils.py:11,102`); this
implementation removes the dependency (SURVEY §2.2) and adds bf16 support via
`ml_dtypes` (already a jax dependency).
"""

from __future__ import annotations

import json
import mmap
import os
from typing import Any, Mapping

import ml_dtypes
import numpy as np

_DTYPES: dict[str, np.dtype] = {
    "F64": np.dtype(np.float64),
    "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16),
    "BF16": np.dtype(ml_dtypes.bfloat16),
    "F8_E4M3": np.dtype(ml_dtypes.float8_e4m3fn),
    "F8_E5M2": np.dtype(ml_dtypes.float8_e5m2),
    "I64": np.dtype(np.int64),
    "I32": np.dtype(np.int32),
    "I16": np.dtype(np.int16),
    "I8": np.dtype(np.int8),
    "U8": np.dtype(np.uint8),
    "U16": np.dtype(np.uint16),
    "U32": np.dtype(np.uint32),
    "U64": np.dtype(np.uint64),
    "BOOL": np.dtype(np.bool_),
}
_DTYPE_NAMES = {v: k for k, v in _DTYPES.items()}


def read_header(path: str | os.PathLike) -> tuple[dict[str, Any], int]:
    """Parse just the JSON header: ``(header, data_start_offset)``.

    ``header`` maps tensor name -> {dtype, shape, data_offsets} (plus the
    optional ``__metadata__`` entry) without touching the tensor bytes."""
    with open(path, "rb") as f:
        header_len = int.from_bytes(f.read(8), "little")
        header: dict[str, Any] = json.loads(f.read(header_len))
    return header, 8 + header_len


def load_file(path: str | os.PathLike) -> dict[str, np.ndarray]:
    """Read every tensor from a .safetensors file (zero-copy mmap views)."""
    header, data_start = read_header(path)
    with open(path, "rb") as f:
        mm = mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ)
    out: dict[str, np.ndarray] = {}
    for name, info in header.items():
        if name == "__metadata__":
            continue
        dtype = _DTYPES[info["dtype"]]
        start, end = info["data_offsets"]
        count = (end - start) // dtype.itemsize
        # np.frombuffer over the mmap is a true zero-copy view; slicing the
        # mmap object would copy the bytes
        arr = np.frombuffer(mm, dtype=dtype, count=count,
                            offset=data_start + start).reshape(info["shape"])
        out[name] = arr
    return out


def save_file(tensors: Mapping[str, np.ndarray], path: str | os.PathLike,
              metadata: Mapping[str, str] | None = None) -> None:
    """Write tensors to a .safetensors file (HF-interoperable export)."""
    header: dict[str, Any] = {}
    if metadata:
        header["__metadata__"] = dict(metadata)
    offset = 0
    blobs: list[bytes] = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dt = np.dtype(arr.dtype)
        if dt not in _DTYPE_NAMES:
            raise ValueError(f"unsupported dtype {dt} for tensor {name!r}")
        blob = arr.tobytes()
        header[name] = {"dtype": _DTYPE_NAMES[dt], "shape": list(arr.shape),
                        "data_offsets": [offset, offset + len(blob)]}
        blobs.append(blob)
        offset += len(blob)
    hjson = json.dumps(header, separators=(",", ":")).encode()
    # pad header to 8-byte alignment like the upstream implementation
    pad = (8 - len(hjson) % 8) % 8
    hjson += b" " * pad
    with open(path, "wb") as f:
        f.write(len(hjson).to_bytes(8, "little"))
        f.write(hjson)
        for blob in blobs:
            f.write(blob)
