"""Preemption-tolerant training: supervisor, grace-window saves, fault
drills, checkpoint quarantine, and the serve-side retry/watchdog paths.

The CLI drills here are the in-process versions of what
``scripts/resilience_smoke.py`` runs end-to-end in CI: deterministic fault
plans against tiny models, with an uninterrupted control run as the oracle
for step and loss continuity.
"""

import asyncio
import json
import signal

import numpy as np
import pytest

from jimm_tpu.cli import main
from jimm_tpu.resilience import (BackoffPolicy, FaultPlan, GiveUpError,
                                 PreemptedError, PreemptionGuard, Supervisor)

COMMON = ["train", "--preset", "vit-base-patch16-224", "--tiny",
          "--batch-size", "4", "--steps", "6", "--save-every", "1",
          "--log-every", "0", "--seed", "7"]


def read_metrics(path):
    with open(path) as f:
        return [rec for rec in map(json.loads, f)]


def by_step(records):
    return {rec["step"]: rec for rec in records}


# ---------------------------------------------------------------------------
# units: backoff, fault plan, guard, supervisor
# ---------------------------------------------------------------------------

class TestBackoffPolicy:
    def test_exact_exponential_without_jitter(self):
        p = BackoffPolicy(base_s=0.5)
        assert [p.delay(i) for i in range(4)] == [0.5, 1.0, 2.0, 4.0]

    def test_cap_and_jitter_bounds(self):
        p = BackoffPolicy(base_s=1.0, max_s=4.0, jitter=0.5, seed=0)
        for i in range(20):
            d = p.delay(i)
            assert 0.0 <= d <= 4.0 * 1.5

    def test_seeded_jitter_replays(self):
        a = [BackoffPolicy(jitter=0.5, seed=3).delay(i) for i in range(5)]
        b = [BackoffPolicy(jitter=0.5, seed=3).delay(i) for i in range(5)]
        assert a == b

    def test_validation(self):
        with pytest.raises(ValueError):
            BackoffPolicy(retries=-1)
        with pytest.raises(ValueError):
            BackoffPolicy(jitter=1.5)


class TestFaultPlan:
    def test_parse_and_order(self):
        plan = FaultPlan.parse("crash@5,preempt@2,stall@5:0.25,corrupt@5")
        assert [str(f) for f in plan.faults] == [
            "preempt@2", "stall@5:0.25", "corrupt@5", "crash@5"]
        assert plan.needs("corrupt") and not plan.needs("nope")
        assert [f.kind for f in plan.events_at(5)] == ["stall", "corrupt",
                                                       "crash"]

    @pytest.mark.parametrize("spec", ["boom@2", "preempt@-1", "stall@3",
                                      "crash@2:5", "preempt@x"])
    def test_parse_rejects_bad_specs(self, spec):
        with pytest.raises(ValueError, match="bad fault spec entry"):
            FaultPlan.parse(spec)

    def test_stall_sleeps_and_crash_raises(self):
        slept = []
        plan = FaultPlan.parse("stall@1:0.5,crash@2", sleep=slept.append)
        plan.fire(0)
        assert slept == [] and plan.fired == []
        plan.fire(1)
        assert slept == [0.5]
        with pytest.raises(RuntimeError, match="injected failure at step 2"):
            plan.fire(2)
        assert [str(f) for f in plan.fired] == ["stall@1:0.5", "crash@2"]

    def test_corrupt_requires_checkpoint(self):
        with pytest.raises(ValueError, match="checkpoint"):
            FaultPlan.parse("corrupt@0").fire(0, ckpt=None)


class TestPreemptionGuard:
    def test_sigterm_sets_flag_and_uninstall_restores(self):
        previous = signal.getsignal(signal.SIGTERM)
        guard = PreemptionGuard().install()
        try:
            assert not guard.preempted
            signal.raise_signal(signal.SIGTERM)
            assert guard.preempted
        finally:
            guard.uninstall()
        assert signal.getsignal(signal.SIGTERM) is previous

    def test_trigger_without_install(self):
        guard = PreemptionGuard()
        guard.trigger()
        assert guard.preempted


class TestSupervisor:
    def _sup(self, max_restarts=3):
        slept = []
        sup = Supervisor(max_restarts=max_restarts,
                         backoff=BackoffPolicy(base_s=0.5),
                         sleep=slept.append)
        return sup, slept

    def test_success_first_try(self):
        sup, slept = self._sup()
        assert sup.run(lambda i, resume: 0) == 0
        assert sup.restarts == 0 and slept == []

    def test_restarts_then_succeeds_with_resume_flag(self):
        sup, slept = self._sup()
        calls = []

        def attempt(i, resume):
            calls.append((i, resume))
            if i < 2:
                raise RuntimeError("worker died")
            return 0

        assert sup.run(attempt) == 0
        assert calls == [(0, False), (1, True), (2, True)]
        assert sup.restarts == 2 and slept == [0.5, 1.0]

    def test_preemption_counts_as_restartable(self):
        sup, _ = self._sup()
        seen = []

        def attempt(i, resume):
            seen.append(resume)
            if i == 0:
                raise PreemptedError(4, lost_seconds=1.5)
            return 0

        assert sup.run(attempt) == 0
        assert seen == [False, True]
        assert "preempted" in sup.history[0]

    def test_gives_up_after_max_restarts(self):
        sup, slept = self._sup(max_restarts=2)

        def attempt(i, resume):
            raise RuntimeError(f"death #{i}")

        with pytest.raises(GiveUpError, match="giving up after 2 restarts"):
            sup.run(attempt)
        assert sup.restarts == 2 and len(slept) == 2
        assert len(sup.history) == 3  # every attempt recorded

    def test_nonzero_exit_code_is_a_failure(self):
        sup, _ = self._sup(max_restarts=1)
        rcs = iter([3, 0])
        assert sup.run(lambda i, resume: next(rcs)) == 0
        assert sup.history == ["exit code 3"]

    def test_counters_land_in_registry(self):
        import time as _time

        from jimm_tpu.obs.registry import MetricRegistry
        reg = MetricRegistry("t")
        sup = Supervisor(max_restarts=1, backoff=BackoffPolicy(base_s=0.0),
                         sleep=lambda s: None, registry=reg)
        flag = []

        def attempt(i, resume):
            if not flag:
                flag.append(1)
                _time.sleep(0.002)  # make the lost-work window measurable
                raise RuntimeError("boom")
            return 0

        assert sup.run(attempt) == 0
        snap = reg.snapshot()
        assert snap["restarts_total"] == 1
        assert snap["goodput_lost_work_seconds_total"] > 0


# ---------------------------------------------------------------------------
# checkpoint robustness: partial dirs, corruption, quarantine
#
# These CLI drills each run full tiny training jobs (~40s total), so they
# carry the slow mark and run in CI's non-blocking slow job; the blocking
# job covers the same acceptance path via scripts/resilience_smoke.py.
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestCheckpointRobustness:
    def test_partial_step_dir_is_skipped_and_quarantined(self, tmp_path):
        """A partially-written (unmarked) newest step dir — what a mid-save
        kill leaves — must not win latest-step: resume restores the last
        COMPLETED step and sweeps the torso into quarantine."""
        ckpt = tmp_path / "ckpt"
        first = tmp_path / "first.jsonl"
        short = list(COMMON)
        short[short.index("--steps") + 1] = "3"
        assert main(short + ["--ckpt-dir", str(ckpt),
                             "--metrics-file", str(first)]) == 0
        # fake the torso: a step dir newer than anything marked complete
        partial = ckpt / "7" / "model"
        partial.mkdir(parents=True)
        resumed = tmp_path / "resumed.jsonl"
        assert main(COMMON + ["--ckpt-dir", str(ckpt), "--resume",
                              "--metrics-file", str(resumed)]) == 0
        steps = {r["step"] for r in read_metrics(resumed)}
        assert steps == {3, 4, 5}, "resume must continue after step 2"
        assert not (ckpt / "7").exists()
        assert (ckpt / ".quarantine" / "7").is_dir()
        reason = (ckpt / ".quarantine" / "7"
                  / ".jimm_quarantine_reason.txt").read_text()
        assert "partial" in reason

    def test_corrupt_checkpoint_quarantined_and_resume_falls_back(
            self, tmp_path):
        """The corrupt@STEP drill: the newest checkpoint's metadata is
        garbage; resume must quarantine it (never delete) and continue
        from the previous good step, matching the control run."""
        control = tmp_path / "control.jsonl"
        assert main(COMMON + ["--metrics-file", str(control)]) == 0

        ckpt = tmp_path / "ckpt"
        crashed = tmp_path / "crashed.jsonl"
        with pytest.raises(RuntimeError, match="injected failure at step 2"):
            main(COMMON + ["--ckpt-dir", str(ckpt),
                           "--metrics-file", str(crashed),
                           "--inject-faults", "corrupt@2,crash@2"])

        resumed = tmp_path / "resumed.jsonl"
        with pytest.warns(RuntimeWarning, match="quarantined"):
            assert main(COMMON + ["--ckpt-dir", str(ckpt), "--resume",
                                  "--metrics-file", str(resumed)]) == 0
        res = by_step(read_metrics(resumed))
        # step 2's checkpoint was corrupted -> fall back to step 1, so the
        # resumed run re-trains steps 2..5
        assert set(res) == {2, 3, 4, 5}
        qdir = ckpt / ".quarantine" / "2"
        assert qdir.is_dir(), "corrupt step must be quarantined, not deleted"
        assert "restore failed" in (
            qdir / ".jimm_quarantine_reason.txt").read_text()
        ctl = by_step(read_metrics(control))
        for step in (2, 3, 4, 5):
            np.testing.assert_allclose(
                res[step]["loss"], ctl[step]["loss"], rtol=2e-4,
                err_msg=f"loss diverged from control at step {step}")


# ---------------------------------------------------------------------------
# supervised end-to-end: preemption drill with data-resume proof
# (slow for the same reason as TestCheckpointRobustness above)
# ---------------------------------------------------------------------------

@pytest.mark.slow
class TestSupervisedPreemption:
    def test_preempt_grace_save_restart_and_zero_replay(self, tmp_path,
                                                        capsys):
        """The CI fault drill, in-process: SIGTERM at step 2 -> grace-window
        save -> supervisor restarts with --resume -> losses match the
        control step-for-step and batch fingerprints prove the data
        pipeline replayed and skipped nothing."""
        control = tmp_path / "control.jsonl"
        assert main(COMMON + ["--metrics-file", str(control),
                              "--batch-fingerprint"]) == 0

        ckpt = tmp_path / "ckpt"
        drilled = tmp_path / "drilled.jsonl"
        rc = main(["supervise", "--max-restarts", "2",
                   "--backoff-base-s", "0.01", "--seed", "0", "--"]
                  + COMMON + ["--ckpt-dir", str(ckpt),
                              "--metrics-file", str(drilled),
                              "--batch-fingerprint",
                              "--inject-faults", "preempt@2"])
        assert rc == 0

        records = read_metrics(drilled)
        steps = [r["step"] for r in records]
        # attempt 1 trains 0..3 (step 3 is the grace-window step whose
        # result is discarded); attempt 2 resumes at 3 and finishes
        assert steps == [0, 1, 2, 3, 3, 4, 5]
        ctl = by_step(read_metrics(control))
        final = by_step(records)  # later (resumed) rows win duplicate steps
        for step in range(6):
            np.testing.assert_allclose(
                final[step]["loss"], ctl[step]["loss"], rtol=2e-4,
                err_msg=f"loss diverged from control at step {step}")
            assert final[step]["batch_fingerprint"] == \
                ctl[step]["batch_fingerprint"], \
                f"data pipeline replayed/skipped batches at step {step}"

        out = capsys.readouterr().out
        resilience = json.loads(
            [ln for ln in out.splitlines()
             if ln.startswith("resilience: ")][-1].split("resilience: ")[1])
        assert resilience["jimm_train_restarts_total"] >= 1
        assert resilience["jimm_train_preemptions_total"] >= 1
        assert resilience["jimm_train_goodput_lost_work_seconds_total"] > 0

    def test_supervise_gives_up_and_reports(self, tmp_path, capsys):
        """A fault plan that crashes every attempt exhausts the restart
        budget: supervise returns nonzero with a clear give-up message."""
        ckpt = tmp_path / "ckpt"
        short = list(COMMON)
        short[short.index("--steps") + 1] = "3"
        rc = main(["supervise", "--max-restarts", "1",
                   "--backoff-base-s", "0.01", "--seed", "0", "--"]
                  + short + ["--ckpt-dir", str(ckpt),
                             "--inject-faults", "crash@0,crash@1"])
        assert rc == 1
        err = capsys.readouterr().err
        assert "giving up after 1 restarts" in err

class TestSuperviseCli:
    def test_supervise_rejects_non_train_commands(self):
        with pytest.raises(SystemExit, match="train"):
            main(["supervise", "--", "evaluate", "--data", "x"])
        with pytest.raises(SystemExit, match="ckpt-dir"):
            main(["supervise", "--", "train", "--preset", "x"])


# ---------------------------------------------------------------------------
# serve side: client backoff retry + replica watchdog
# ---------------------------------------------------------------------------

class TestClientRetry:
    def _client(self, **kw):
        from jimm_tpu.serve.client import ServeClient
        client = ServeClient(port=1, backoff_seed=0, **kw)
        slept = []
        client._sleep = slept.append
        return client, slept

    def test_fresh_connection_failures_backoff_then_raise(self):
        client, slept = self._client(retries=2, backoff_base_s=0.05)
        with pytest.raises(OSError):
            client.healthz()  # nothing listens on port 1
        assert len(slept) == 2, "bounded retries with a sleep between each"
        assert all(0.0 <= s <= 0.05 * 2 * 1.5 for s in slept)

    def test_zero_retries_raises_immediately(self):
        client, slept = self._client(retries=0)
        with pytest.raises(OSError):
            client.healthz()
        assert slept == []

    def test_deadline_bounds_the_retry_budget(self):
        client, slept = self._client(retries=5, backoff_base_s=10.0)
        with pytest.raises(OSError):
            client._request("GET", "/healthz", deadline_s=0.5)
        assert slept == [], "sleeping 10s past a 0.5s deadline is refused"


class TestReplicaWatchdog:
    def _engine(self, forwards):
        from jimm_tpu.serve import BucketTable, InferenceEngine
        return InferenceEngine(forwards, item_shape=(3,),
                               buckets=BucketTable((1, 2)),
                               max_delay_ms=1.0)

    def test_failing_replica_restarts_once_then_fenced(self):
        ok = lambda x: x * 2  # noqa: E731
        def bad(x):
            raise RuntimeError("device lost")

        engine = self._engine([ok, bad])

        async def go():
            await engine.start()
            try:
                # drive requests until replica 1 has failed twice (one
                # failure -> executor restart, second -> fenced off)
                for _ in range(16):
                    try:
                        await engine.submit(np.ones(3, np.float32))
                    except RuntimeError:
                        pass
                    if engine.dead_replicas():
                        break
                assert engine.dead_replicas() == [1]
                stats = {s["replica"]: s for s in engine.replica_stats()}
                assert stats[1]["restarts"] == 1 and stats[1]["dead"]
                assert not stats[0]["dead"]
                # a fenced replica never gets picked again
                out = await engine.submit(np.ones(3, np.float32))
                np.testing.assert_allclose(np.asarray(out), 2.0)
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_last_live_replica_is_never_fenced(self):
        def bad(x):
            raise RuntimeError("device lost")

        engine = self._engine([bad])

        async def go():
            await engine.start()
            try:
                for _ in range(4):
                    with pytest.raises(RuntimeError, match="device lost"):
                        await engine.submit(np.ones(3, np.float32))
                assert engine.dead_replicas() == []
                stats = engine.replica_stats()[0]
                assert stats["restarts"] == 1 and not stats["dead"]
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_healthz_reports_degraded_with_dead_replica(self):
        from jimm_tpu.serve import ServingServer
        engine = self._engine([lambda x: x, lambda x: x])
        engine._replicas[1].dead = True
        server = ServingServer(engine, warmup=False)  # never started: the
        # probe payload is computable without binding a port
        out = server.healthz()
        assert out["status"] == "degraded"
        assert out["dead_replicas"] == [1]
        assert out["replicas"][1]["dead"] is True
