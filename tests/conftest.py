"""Test harness: force an 8-device virtual CPU platform so sharding,
FSDP/TP, ring-loss, and distributed tests run without a TPU pod
(SURVEY §4 "Implication for the build").

Must run before jax initializes a backend — pytest imports conftest first.
"""

import os

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def rng() -> np.random.RandomState:
    return np.random.RandomState(0)


@pytest.fixture(scope="session")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices")
    return devs
