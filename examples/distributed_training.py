"""Multi-process (multi-host-shaped) contrastive training.

One process per host, launched with `python -m jimm_tpu.launch` (or by the
Cloud TPU pod runtime, which starts the processes for you — then
`initialize_distributed()` auto-detects and the rest is identical):

  python -m jimm_tpu.launch --nproc 2 --platform cpu --host-devices 2 -- \
      python examples/distributed_training.py --steps 5 --batch-size 8

What the reference cannot do at all (single-process GSPMD only,
ref `examples/vit_training.py`), demonstrated end to end:
  - `initialize_distributed()` joins the launcher's process group;
  - one global FSDP mesh spans every process's devices;
  - each process loads only ITS shard of the global batch
    (`contrastive_pairs(shard_index=...)`) and the shards are assembled
    into one global array with `jax.make_array_from_process_local_data`;
  - the ring sigmoid loss ppermutes text chunks across the process
    boundary; gradients/optimizer state update under FSDP layouts that
    include non-addressable devices.
"""

from __future__ import annotations

from jimm_tpu.parallel import initialize_distributed

initialize_distributed()  # env (launcher) or TPU-pod auto-detect

import argparse  # noqa: E402

import jax  # noqa: E402
import numpy as np  # noqa: E402
from flax import nnx  # noqa: E402
from jax.sharding import NamedSharding, PartitionSpec as P  # noqa: E402

from jimm_tpu import SigLIP  # noqa: E402
from jimm_tpu.configs import (SigLIPConfig, TextConfig,  # noqa: E402
                              VisionConfig)
from jimm_tpu.data import contrastive_pairs  # noqa: E402
from jimm_tpu.parallel import (FSDP, create_sharded, make_mesh,  # noqa: E402
                               use_sharding)
from jimm_tpu.train import (OptimizerConfig,  # noqa: E402
                            make_contrastive_train_step, make_optimizer)


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=16,
                   help="GLOBAL batch (split across processes)")
    args = p.parse_args()

    rank, world = jax.process_index(), jax.process_count()
    mesh = make_mesh({"data": -1})  # every device in the cluster
    if rank == 0:
        print(f"cluster: {world} processes, {jax.device_count()} devices, "
              f"mesh {dict(mesh.shape)}")

    cfg = SigLIPConfig(
        vision=VisionConfig(image_size=16, patch_size=8, width=64, depth=2,
                            num_heads=2, mlp_dim=128, act="gelu_tanh",
                            pooling="map"),
        text=TextConfig(vocab_size=64, context_length=8, width=64, depth=2,
                        num_heads=2, mlp_dim=128, act="gelu_tanh",
                        causal=False, pooling="last", proj_bias=True),
        projection_dim=64)
    # init under jit with sharding constraints: parameters are born on the
    # global mesh, never materialized on one host
    model = create_sharded(lambda: SigLIP(cfg, rngs=nnx.Rngs(0)), mesh, FSDP)
    opt = make_optimizer(model, OptimizerConfig(learning_rate=3e-3))
    step = make_contrastive_train_step("siglip_ring", mesh=mesh,
                                       donate=True)

    stream = contrastive_pairs(args.batch_size, image_size=16, seq_len=8,
                               shard_index=rank, shard_count=world)
    batch_sharding = NamedSharding(mesh, P("data"))
    with use_sharding(mesh, FSDP):
        for i in range(args.steps):
            images, text = next(stream)
            gi = jax.make_array_from_process_local_data(batch_sharding,
                                                        images)
            gt = jax.make_array_from_process_local_data(batch_sharding, text)
            loss = float(step(model, opt, gi, gt)["loss"])
            if rank == 0:
                print(f"step {i}: loss={loss:.4f}")
    assert np.isfinite(loss)
    print(f"rank {rank} done, final loss {loss:.4f}")


if __name__ == "__main__":
    main()
