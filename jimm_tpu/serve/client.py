"""Pure-Python client for the jimm-tpu serving endpoint.

Stdlib only (``http.client`` + ``json`` + ``base64``): usable from any
process without installing jimm_tpu's accelerator stack. Arrays go over the
wire as base64 raw float32 when the input quacks like a numpy array
(``astype``/``tobytes``), else as nested JSON lists — matching what
``serve.server`` accepts.
"""

from __future__ import annotations

import base64
import dataclasses
import http.client
import itertools
import json
import os
import threading
import time

from jimm_tpu.resilience.backoff import BackoffPolicy  # stdlib-only module

_trace_counter = itertools.count(1)
_trace_lock = threading.Lock()


def client_trace_id() -> str:
    """Client-minted end-to-end trace id, sent as ``X-Jimm-Trace-Id``. The
    server inherits it into its journal records and trace ring, so one id
    threads client retry → admission → replica dispatch → capture. Prefixed
    with the client pid so ids from a client herd never collide."""
    with _trace_lock:
        n = next(_trace_counter)
    return f"tc{os.getpid():x}-{n:06x}"

#: cascade response headers (mirrors serve.cascade.router — spelled out
#: here because this module must stay stdlib-only importable)
CASCADE_HEADER_MODELS = "X-Jimm-Cascade-Models"
CASCADE_HEADER_MODEL = "X-Jimm-Cascade-Model"
CASCADE_HEADER_CONFIDENCE = "X-Jimm-Cascade-Confidence"


@dataclasses.dataclass(frozen=True)
class CascadeInfo:
    """Escalation metadata a cascade-routed response carried: which models
    the request tried (cheapest first), which one answered, and the
    calibrated confidence the final decision rode on (None when the
    terminal stage accepted by fiat). This is what serve_bench bills
    cost/request from — no server log scraping."""

    models_tried: tuple[str, ...]
    model: str
    confidence: float | None

    @property
    def escalations(self) -> int:
        return len(self.models_tried) - 1


def parse_cascade_headers(headers) -> CascadeInfo | None:
    """Parse the ``X-Jimm-Cascade-*`` response headers (a mapping or a
    ``(name, value)`` iterable, matched case-insensitively) into a
    :class:`CascadeInfo`; None when the response was not cascade-routed."""
    items = headers.items() if hasattr(headers, "items") else headers
    lower = {str(k).lower(): v for k, v in items}
    model = lower.get(CASCADE_HEADER_MODEL.lower())
    if model is None:
        return None
    raw = lower.get(CASCADE_HEADER_MODELS.lower()) or ""
    models = tuple(m for m in raw.split(",") if m) or (model,)
    confidence = None
    conf_raw = lower.get(CASCADE_HEADER_CONFIDENCE.lower())
    if conf_raw is not None:
        try:
            confidence = float(conf_raw)
        except ValueError:
            confidence = None
    return CascadeInfo(models_tried=models, model=str(model),
                       confidence=confidence)


class EmbedResult(list):
    """``embed()``'s return value: still the plain features list every
    existing caller indexes into, plus the response's routing metadata
    (:attr:`cascade` is None on non-cascade servers) and trace id."""

    def __init__(self, features, *, cascade: CascadeInfo | None = None,
                 trace_id: str | None = None):
        super().__init__(features)
        self.cascade = cascade
        self.trace_id = trace_id


class ServeClientError(Exception):
    """Server-reported error: carries the HTTP status and the typed code
    (``queue_full``, ``deadline_exceeded``, ``bad_request``, ...), plus
    the server's ``Retry-After`` hint (seconds) when it sent one."""

    def __init__(self, status: int, code: str, message: str,
                 retry_after_s: float | None = None):
        super().__init__(f"{code} (HTTP {status}): {message}")
        self.status = status
        self.code = code
        self.retry_after_s = retry_after_s


class ThrottledClientError(ServeClientError):
    """429: the QoS policy rate-limited this tenant — the request was
    never admitted. Waiting ``retry_after_s`` (the token bucket's refill
    time) before retrying is sufficient, not just polite."""


class ShedClientError(ServeClientError):
    """503 with code ``shed``: the request WAS queued but got evicted
    under overload in favor of a higher-priority class. The server is
    saturated; back off harder than for a throttle."""


def _typed_error(status: int, code: str, message: str,
                 retry_after_s: float | None) -> ServeClientError:
    if status == 429:
        return ThrottledClientError(status, code, message, retry_after_s)
    if status == 503 and code == "shed":
        return ShedClientError(status, code, message, retry_after_s)
    return ServeClientError(status, code, message, retry_after_s)


def encode_image_payload(image) -> dict:
    """The wire form of one image: b64 float32 for array-likes, nested
    lists otherwise."""
    if hasattr(image, "astype") and hasattr(image, "tobytes"):
        arr = image.astype("float32")
        return {"image_b64": base64.b64encode(arr.tobytes()).decode("ascii"),
                "shape": list(arr.shape), "dtype": "float32"}
    return {"image": image}


class ServeClient:
    """One server endpoint with keep-alive transport.

    Each thread reuses one persistent HTTP/1.1 connection across calls
    (``serve.server`` always answers with ``Content-Length``, so the socket
    stays open) instead of paying TCP setup + slow-start per request — the
    dominant client-side cost at micro-batch latencies. Connections live in
    thread-local storage, so a client instance is still safe to share
    across threads: a 64-thread load generator holds 64 sockets, same as
    64 clients, but makes thousands of requests on them. A dead or stale
    socket (server restart, idle timeout) is dropped and the request
    retried immediately on a fresh connection; a fresh connection failing
    (server restarting, briefly unreachable) is retried up to ``retries``
    times with bounded jittered backoff — the same
    :class:`~jimm_tpu.resilience.backoff.BackoffPolicy` the hub-download
    and training-supervisor retry loops use. A request deadline
    (``timeout_s=`` on the call) bounds the whole retry budget: the client
    never sleeps past it.
    """

    def __init__(self, host: str = "127.0.0.1", port: int = 8000,
                 timeout_s: float = 30.0, retries: int = 2,
                 backoff_base_s: float = 0.05,
                 backoff_seed: int | None = None,
                 tenant: str | None = None, model: str | None = None,
                 retry_throttled: int = 0):
        self.host = host
        self.port = port
        self.timeout_s = timeout_s
        #: tenant id / model name sent as X-Jimm-Tenant / X-Jimm-Model on
        #: every request (None sends nothing — the anonymous default path)
        self.tenant = tenant
        self.model = model
        #: how many 429-throttled / 503-shed responses to retry before
        #: surfacing the typed error. 0 (default) never retries: batch
        #: drivers opt in, latency-sensitive callers see the error at once.
        self.retry_throttled = retry_throttled
        self._backoff = BackoffPolicy(retries=retries, base_s=backoff_base_s,
                                      max_s=2.0, jitter=0.5,
                                      seed=backoff_seed)
        self._sleep = time.sleep  # injectable for tests
        self._local = threading.local()

    # -- transport --------------------------------------------------------

    def _connection(self) -> http.client.HTTPConnection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = http.client.HTTPConnection(self.host, self.port,
                                              timeout=self.timeout_s)
            self._local.conn = conn
        return conn

    def _drop_connection(self) -> None:
        conn = getattr(self._local, "conn", None)
        if conn is not None:
            try:
                conn.close()
            except OSError:
                pass
            self._local.conn = None

    def close(self) -> None:
        """Close this thread's persistent connection (other threads'
        sockets close when their threads exit or on their own next error).
        """
        self._drop_connection()

    def _request(self, method: str, path: str, payload: dict | None = None,
                 *, deadline_s: float | None = None,
                 with_headers: bool = False):
        body = None if payload is None else json.dumps(payload).encode()
        headers = {"Content-Type": "application/json"} if body else {}
        if self.tenant is not None:
            headers["X-Jimm-Tenant"] = self.tenant
        if self.model is not None:
            headers["X-Jimm-Model"] = self.model
        if body:
            # one id for the whole logical request, retries included — the
            # server inherits it (see server.request_trace_id) so every
            # attempt journals under the same identity
            headers["X-Jimm-Trace-Id"] = client_trace_id()
        deadline = (time.monotonic() + deadline_s
                    if deadline_s is not None else None)
        fresh_failures = 0
        throttle_retries = 0
        while True:
            reused = getattr(self._local, "conn", None) is not None
            conn = self._connection()
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                raw = resp.read()
            except TimeoutError:
                # a slow server is not a stale socket — surface it
                self._drop_connection()
                raise
            except (http.client.HTTPException, OSError):
                self._drop_connection()
                if reused:
                    # reused socket went stale (server restart, idle close)
                    # before the response started: retry at once, fresh —
                    # this costs nothing and is almost always the fix
                    continue
                # a FRESH connection failing means the server is down or
                # restarting: back off (jittered, so a client herd doesn't
                # reconnect in lockstep), bounded by retries and by the
                # request's own deadline
                if fresh_failures >= self._backoff.retries:
                    raise
                delay = self._backoff.delay(fresh_failures)
                fresh_failures += 1
                if (deadline is not None
                        and time.monotonic() + delay >= deadline):
                    raise  # honoring the deadline beats one more attempt
                self._sleep(delay)
                continue
            if resp.getheader("Connection", "").lower() == "close":
                self._drop_connection()
            content_type = resp.getheader("Content-Type") or ""
            if not content_type.startswith("application/json"):
                if resp.status >= 400:
                    raise ServeClientError(resp.status, "http_error",
                                           raw.decode(errors="replace")[:200])
                return raw.decode(errors="replace")
            obj = json.loads(raw)
            if resp.status < 400:
                if with_headers:
                    return obj, dict(resp.getheaders())
                return obj
            try:
                retry_after = float(resp.getheader("Retry-After"))
            except (TypeError, ValueError):
                retry_after = None
            err = _typed_error(resp.status, obj.get("error", "http_error"),
                               obj.get("message", ""), retry_after)
            if (isinstance(err, (ThrottledClientError, ShedClientError))
                    and throttle_retries < self.retry_throttled):
                # honor Retry-After: sleep at least the server's hint,
                # escalated by the shared jittered BackoffPolicy so a
                # throttled herd doesn't return in lockstep — still
                # bounded by the request deadline
                delay = max(self._backoff.delay(throttle_retries),
                            retry_after or 0.0)
                throttle_retries += 1
                if (deadline is None
                        or time.monotonic() + delay < deadline):
                    self._sleep(delay)
                    continue
            raise err

    # -- API --------------------------------------------------------------

    def healthz(self) -> dict:
        return self._request("GET", "/healthz")

    def metrics_text(self) -> str:
        return self._request("GET", "/metrics")

    def embed(self, image, timeout_s: float | None = None) -> EmbedResult:
        """One image in, its features out — as an :class:`EmbedResult`
        (a plain list, plus ``.cascade`` escalation metadata when the
        server routed through a confidence cascade)."""
        payload = encode_image_payload(image)
        if timeout_s is not None:
            payload["timeout_s"] = timeout_s
        obj, headers = self._request("POST", "/v1/embed", payload,
                                     deadline_s=timeout_s,
                                     with_headers=True)
        return EmbedResult(obj["features"],
                           cascade=parse_cascade_headers(headers),
                           trace_id=obj.get("trace_id"))

    def embed_many(self, images, timeout_s: float | None = None) -> list:
        """Bulk embed: one request, one ``features`` row per image. The
        server submits each image individually so the engine coalesces the
        burst into its warm buckets."""
        payload = {"images": [encode_image_payload(img) for img in images]}
        if timeout_s is not None:
            payload["timeout_s"] = timeout_s
        return self._request("POST", "/v1/embed", payload,
                             deadline_s=timeout_s)["features"]

    def classify(self, image, tokens: dict,
                 timeout_s: float | None = None) -> dict:
        """``tokens``: ``{label: [ids]}`` (or ``{label: [[ids], ...]}`` for
        prompt ensembles). Returns ``{"scores": {label: p}, "cached": b}``.
        """
        payload = encode_image_payload(image)
        payload["tokens"] = tokens
        if timeout_s is not None:
            payload["timeout_s"] = timeout_s
        return self._request("POST", "/v1/classify", payload,
                             deadline_s=timeout_s)

    def search(self, *, vector=None, image=None, k: int | None = None,
               nprobe: int | None = None,
               timeout_s: float | None = None) -> dict:
        """Top-k over the server's retrieval index. Pass a raw ``vector``
        (searched directly) or an ``image`` (embedded through the engine
        first). ``nprobe`` widens/narrows the probe per request when the
        server runs ``--index-mode ivf`` (rejected in exact mode).
        Returns ``{"ids", "scores", "index", "k", "trace_id"}``."""
        if (vector is None) == (image is None):
            raise ValueError("search needs exactly one of vector= or "
                             "image=")
        if vector is not None:
            payload: dict = {"vector": (vector.astype("float32").tolist()
                                        if hasattr(vector, "astype")
                                        else list(vector))}
        else:
            payload = encode_image_payload(image)
        if k is not None:
            payload["k"] = int(k)
        if nprobe is not None:
            payload["nprobe"] = int(nprobe)
        if timeout_s is not None:
            payload["timeout_s"] = timeout_s
        return self._request("POST", "/v1/search", payload,
                             deadline_s=timeout_s)
