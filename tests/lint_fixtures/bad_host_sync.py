"""JL002 fixtures: host-device syncs inside a jitted function."""

import jax
import numpy as np


@jax.jit
def leaky_step(x, threshold):
    lr = float(threshold)          # line 9: JL002 float() on traced value
    host = np.asarray(x)           # line 10: JL002 device->host copy
    if x > 0:                      # line 11: JL002 branch on traced value
        return x * lr + host.sum()
    return x.sum().item()          # line 13: JL002 .item() sync
