"""WebDataset tar shards: grouping, loader parity with records, CLI routing."""

import numpy as np
import pytest

from jimm_tpu.data.webdataset import (iter_wds_examples, resolve_tar_paths,
                                      wds_classification_batches,
                                      wds_image_text_batches, write_wds_shard)


@pytest.fixture()
def cls_shards(tmp_path, rng):
    paths = []
    for s in range(2):
        exs = [{"image": rng.randint(0, 255, size=(8, 8, 3)).astype(np.uint8),
                "label": (s * 5 + i) % 3} for i in range(5)]
        p = tmp_path / f"shard-{s}.tar"
        write_wds_shard(p, exs)
        paths.append(str(p))
    return paths


def test_iter_groups_members(cls_shards):
    exs = list(iter_wds_examples(cls_shards, repeat=False))
    assert len(exs) == 10
    assert all("image" in e and "label" in e for e in exs)


def test_classification_batches(cls_shards):
    batches = list(wds_classification_batches(
        cls_shards, 4, image_size=8, repeat=False))
    assert len(batches) == 2  # 10 examples, remainder dropped
    images, labels = batches[0]
    assert images.shape == (4, 8, 8, 3) and images.dtype == np.float32
    assert labels.dtype == np.int32
    # remainder kept when asked
    batches = list(wds_classification_batches(
        cls_shards, 4, image_size=8, repeat=False, drop_remainder=False))
    assert sum(len(b[1]) for b in batches) == 10


def test_image_text_batches(tmp_path, rng):
    exs = [{"image": rng.randint(0, 255, size=(8, 8, 3)).astype(np.uint8),
            "tokens": [i + 1, i + 2]} for i in range(6)]
    p = tmp_path / "pairs.tar"
    write_wds_shard(p, exs)
    images, tokens = next(wds_image_text_batches(
        str(p), 6, image_size=16, seq_len=4, repeat=False))
    assert images.shape == (6, 16, 16, 3)  # resized from 8
    np.testing.assert_array_equal(tokens[0], [1, 2, 0, 0])


def test_sharding_partitions(cls_shards):
    a = [e["label"][0] for e in iter_wds_examples(
        cls_shards, repeat=False, shard_index=0, shard_count=2)]
    b = [e["label"][0] for e in iter_wds_examples(
        cls_shards, repeat=False, shard_index=1, shard_count=2)]
    assert len(a) == len(b) == 5


def test_cli_train_and_evaluate_from_tar(tmp_path, rng, capsys):
    import json

    from jimm_tpu.cli import main
    exs = [{"image": rng.randint(0, 255, size=(8, 8, 3)).astype(np.uint8),
            "label": i % 3} for i in range(12)]
    write_wds_shard(tmp_path / "train.tar", exs)
    ck = tmp_path / "run"
    assert main(["train", "--preset", "vit-base-patch16-224", "--tiny",
                 "--steps", "2", "--batch-size", "6", "--platform", "cpu",
                 "--data", str(tmp_path), "--num-classes", "3",
                 "--ckpt-dir", str(ck), "--save-every", "1"]) == 0
    assert main(["evaluate", "--data", str(tmp_path), "--batch-size", "6",
                 "--preset", "vit-base-patch16-224", "--tiny",
                 "--num-classes", "3", "--ckpt-dir", str(ck),
                 "--platform", "cpu"]) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["examples"] == 12


def test_train_from_tar_reads_classes_json(tmp_path, rng, capsys):
    """num_classes auto-detection must work for tar data too (it used to
    crash in the tfrecord path resolver)."""
    import json

    from jimm_tpu.cli import main
    exs = [{"image": rng.randint(0, 255, size=(8, 8, 3)).astype(np.uint8),
            "label": i % 5} for i in range(8)]
    write_wds_shard(tmp_path / "t.tar", exs)
    (tmp_path / "classes.json").write_text(json.dumps(
        {f"c{i}": i for i in range(5)}))
    assert main(["train", "--preset", "vit-base-patch16-224", "--tiny",
                 "--steps", "1", "--batch-size", "4", "--platform", "cpu",
                 "--data", str(tmp_path), "--log-every", "1"]) == 0
    assert "num_classes=5" in capsys.readouterr().out


def test_resolve_rejects_empty(tmp_path):
    with pytest.raises(FileNotFoundError):
        resolve_tar_paths(str(tmp_path / "nope-*.tar"))
