from jimm_tpu.parallel.mesh import (TOPOLOGIES, initialize_distributed,
                                    make_hybrid_mesh, make_mesh,
                                    make_topology)
from jimm_tpu.parallel.pipeline import pipeline_forward
from jimm_tpu.parallel.ulysses import ulysses_attention
from jimm_tpu.parallel.ring_attention import (ring_attention, zigzag_order,
                                              zigzag_shard, zigzag_unshard)
from jimm_tpu.parallel.sharding import (DATA_PARALLEL, FSDP, FSDP_SP,
                                        FSDP_TP,
                                        HYBRID_FSDP_TP, PIPELINE,
                                        PRESET_RULES, REPLICATED,
                                        SEQUENCE_PARALLEL, TENSOR_PARALLEL,
                                        ShardingRules, create_sharded,
                                        logical, logical_constraint,
                                        shard_batch, shard_model,
                                        sharded_copy, use_sharding)

__all__ = [
    "make_mesh", "make_hybrid_mesh", "make_topology", "TOPOLOGIES",
    "initialize_distributed", "ShardingRules", "use_sharding",
    "create_sharded", "shard_model", "shard_batch", "sharded_copy", "logical",
    "logical_constraint", "pipeline_forward", "ring_attention", "ulysses_attention",
    "zigzag_order", "zigzag_shard", "zigzag_unshard",
    "REPLICATED", "DATA_PARALLEL", "TENSOR_PARALLEL",
    "FSDP", "FSDP_SP", "FSDP_TP", "HYBRID_FSDP_TP", "SEQUENCE_PARALLEL", "PIPELINE",
    "PRESET_RULES",
]
