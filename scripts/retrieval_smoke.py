"""CI tier-1 smoke for the on-TPU retrieval platform (docs/retrieval.md).

Forces 8 virtual CPU devices, builds a 10k-vector index, and proves the
whole retrieval path end to end in one process:

1. **Store + plan**: a tmp :class:`VectorStore` gets 10,000 unit rows;
   ``plan_topology(2, 2)`` splits the corpus across 2 replicas (each a
   2-device model-parallel submesh). ``block_n=128`` is pinned so the
   corpus is >= 64x the block size — the streaming scan is exercised for
   real, never a one-block degenerate.
2. **Life 1**: a :class:`RetrievalService` against a tmp AOT store warms
   every (replica, bucket); write-through populates the store.
3. **Warm restart**: a second service over the same stores reaches
   readiness with ZERO fresh traces and every bucket sourced ``"aot"`` —
   sharded top-k executables round-trip across process lives.
4. **Recall**: the warm service's top-10 against a NumPy oracle on 128
   queries — recall@10 must be exactly 1.0 (score ties tolerated).
5. **Load**: 64 concurrent clients in a closed loop against a live
   ``/v1/search`` endpoint — every request answered, zero post-warmup
   recompiles on either the searcher or the serving engine.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.retrieval_smoke
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
from concurrent.futures import ThreadPoolExecutor

ROWS = 10_000
DIM = 64
K = 10
BLOCK_N = 128          # 10_000 >= 64 * 128: the scan streams ~79 blocks
REPLICAS = 2
MODEL_PARALLEL = 2
RECALL_QUERIES = 128
CLIENTS = 64
PER_CLIENT = 2
TIE_EPS = 1e-5


def fail(msg: str) -> int:
    print(json.dumps({"metric": "retrieval_smoke", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def main() -> int:
    # must land before jax initializes its backends
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")

    import jax
    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.retrieval import RetrievalService, VectorStore
    from jimm_tpu.retrieval.store import normalize_rows
    from jimm_tpu.serve import (BucketTable, InferenceEngine, ServeClient,
                                ServingServer, counting_forward,
                                plan_topology)

    if jax.device_count() < REPLICAS * MODEL_PARALLEL:
        return fail(f"need {REPLICAS * MODEL_PARALLEL} devices, have "
                    f"{jax.device_count()} — was XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=8 set before "
                    f"another jax import?")

    rng = np.random.RandomState(7)
    corpus = normalize_rows(rng.standard_normal((ROWS, DIM)).astype(
        np.float32))
    ids = [f"doc{i:05d}" for i in range(ROWS)]
    queries = normalize_rows(rng.standard_normal(
        (RECALL_QUERIES, DIM)).astype(np.float32))
    plan = plan_topology(REPLICAS, MODEL_PARALLEL)
    buckets = (1, 8)

    with tempfile.TemporaryDirectory(prefix="jimm-retrieval-smoke-") as root:
        vstore = VectorStore(os.path.join(root, "index"))
        vstore.create("corpus", DIM)
        vstore.add("corpus", ids, corpus)
        store = ArtifactStore(os.path.join(root, "aot"))

        # --- life 1: populate the AOT store through warmup ---------------
        svc1 = RetrievalService.from_store(
            vstore, "corpus", k=K, buckets=buckets, block_n=BLOCK_N,
            plan=plan, aot_store=store)
        svc1.warmup()
        if not store.entries():
            return fail("life-1 warmup wrote nothing to the AOT store")

        # --- warm restart: sharded top-k AOT round-trip -------------------
        service = RetrievalService.from_store(
            vstore, "corpus", k=K, buckets=buckets, block_n=BLOCK_N,
            plan=plan, aot_store=store)
        report = service.warmup()
        if service.trace_count():
            return fail(f"warm restart paid {service.trace_count()} fresh "
                        f"traces; top-k artifacts did not round-trip")
        bad = {b: s for b, s in report.items() if s != "aot"}
        if bad:
            return fail(f"warm restart buckets not fully AOT-sourced: {bad}")

        # --- recall@10 against the NumPy oracle ---------------------------
        # (host argsort is the *oracle*, not the serving path — the served
        # path is the device scan + bounded lexsort merge under test)
        oracle_scores = queries @ corpus.T
        kth = np.sort(oracle_scores, axis=1)[:, -K]
        hits = 0
        for start in range(0, RECALL_QUERIES, buckets[-1]):
            batch = queries[start:start + buckets[-1]]
            values, id_rows = service.search_blocking(batch)
            for qi, row in enumerate(id_rows):
                q = start + qi
                for rank, rid in enumerate(row):
                    got = float(values[qi, rank])
                    if got >= kth[q] - TIE_EPS and abs(
                            got - oracle_scores[q, int(rid[3:])]) < 1e-4:
                        hits += 1
        recall = hits / (RECALL_QUERIES * K)
        if recall != 1.0:
            return fail(f"recall@{K} = {recall:.4f} != 1.0 over "
                        f"{RECALL_QUERIES} queries")

        # --- 64-client closed loop through a live /v1/search --------------
        cfg = _tiny_override(preset("clip-vit-base-patch16"))
        model = CLIP(cfg, rngs=nnx.Rngs(0))
        size = cfg.vision.image_size
        forward, traces = counting_forward(model, "encode_image")
        engine = InferenceEngine(forward, item_shape=(size, size, 3),
                                 buckets=BucketTable((1,)),
                                 max_delay_ms=2.0, trace_count=traces)
        server = ServingServer(engine, retrieval=service, port=0)
        server.start()
        try:
            engine_traces = traces()
            topk_traces = service.trace_count()

            def one_client(seed: int) -> int:
                client = ServeClient(port=server.port, timeout_s=60.0)
                try:
                    done = 0
                    for j in range(PER_CLIENT):
                        q = queries[(seed * PER_CLIENT + j)
                                    % RECALL_QUERIES]
                        out = client.search(vector=q, k=K)
                        if len(out["ids"]) == K:
                            done += 1
                    return done
                finally:
                    client.close()

            with ThreadPoolExecutor(max_workers=CLIENTS) as pool:
                answered = sum(pool.map(one_client, range(CLIENTS)))
            if answered != CLIENTS * PER_CLIENT:
                return fail(f"only {answered}/{CLIENTS * PER_CLIENT} "
                            f"searches answered")
            topk_delta = service.trace_count() - topk_traces
            engine_delta = traces() - engine_traces
            if topk_delta or engine_delta:
                return fail(f"post-warmup recompiles: searcher={topk_delta} "
                            f"engine={engine_delta}")
        finally:
            server.stop()

        print(json.dumps({
            "metric": "retrieval_smoke", "value": 1.0,
            "rows": ROWS, "dim": DIM, "k": K, "block_n": BLOCK_N,
            "topology": plan.describe(),
            "recall_at_10": recall,
            "searches": answered,
            "warm_restart": {str(b): s for b, s in sorted(report.items())},
            "store_entries": len(store.entries()),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
