"""jimm_tpu.serve.qos: policy, scheduler, WFQ, pool, and the tenant wire.

Property-style coverage of the three QoS guarantees:

- **weighted fairness**: under saturation the deficit-round-robin dequeue
  shares converge to the configured class weights;
- **class-ordered shedding**: a queued request is only ever evicted in
  favor of a strictly higher class, and only while every class below the
  victim's is empty;
- **byte-compatibility**: with no policy configured the engine uses a
  plain ``asyncio.Queue``, healthz carries no ``qos``/``models`` blocks,
  and the submit path is the pre-QoS one.
"""

import asyncio
import json
import subprocess
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from jimm_tpu.serve import (AdmissionPolicy, BucketTable, InferenceEngine,
                            ModelPool, QosPolicyError, QosScheduler,
                            QueueFullError, RequestError, ServeClient,
                            ServeMetrics, ServingServer, ShedClientError,
                            ShedError, ThrottledClientError, ThrottledError,
                            WeightedFairQueue)
from jimm_tpu.serve.qos.policy import (DEFAULT_CLASSES, TenantRegistry,
                                       load_policy)
from jimm_tpu.serve.qos.scheduler import TokenBucket

POLICY = {
    "classes": {"interactive": {"weight": 8}, "batch": {"weight": 2},
                "background": {"weight": 1}},
    "tenants": {
        "vip": {"class": "interactive", "rate": 100, "burst": 200},
        "bulk": {"class": "batch"},
        "crawler": {"class": "background", "max_queued": 2},
    },
    "default": {"class": "batch"},
}


def _registry(data=None):
    return TenantRegistry.from_dict(data if data is not None else POLICY)


class _Item:
    """Queue stub carrying the two attributes the WFQ reads."""

    def __init__(self, klass, tag=0):
        self.klass = klass
        self.tag = tag
        self.tenant = None


# ---------------------------------------------------------------------------
# policy
# ---------------------------------------------------------------------------

class TestPolicy:
    def test_parse_and_priority_order(self):
        reg = _registry()
        assert reg.class_order == ("interactive", "batch", "background")
        assert reg.classes["interactive"].weight == 8.0
        assert reg.rank_of("interactive") == 0
        assert reg.rank_of("background") == 2
        assert reg.tenants["vip"].rate == 100.0
        assert reg.tenants["crawler"].max_queued == 2
        assert reg.default.klass == "batch"

    def test_missing_sections_get_defaults(self):
        reg = _registry({})
        assert reg.class_order == tuple(n for n, _ in DEFAULT_CLASSES)
        assert reg.tenants == {}
        # the built-in default tenant rides the highest class, unlimited
        assert reg.default.klass == "interactive"
        assert reg.default.rate is None

    def test_unknown_and_anonymous_resolve_to_default(self):
        reg = _registry()
        assert reg.resolve_spec(None) is reg.default
        assert reg.resolve_spec("never-heard-of-you") is reg.default
        assert reg.resolve_spec("vip").klass == "interactive"

    def test_all_problems_reported_at_once(self):
        bad = {"classes": {"a": {"weight": -1}},
               "tenants": {"t1": {"class": "nope", "rate": 0},
                           "t2": {"burst": 0.5, "frobnicate": 1}},
               "surprise": {}}
        with pytest.raises(QosPolicyError) as err:
            _registry(bad)
        problems = str(err.value).split("; ")
        assert len(problems) >= 5
        assert any("weight" in p for p in problems)
        assert any("unknown class" in p for p in problems)
        assert any("rate" in p for p in problems)
        assert any("burst" in p for p in problems)
        assert any("frobnicate" in str(p) for p in problems)

    def test_load_json_file(self, tmp_path):
        path = tmp_path / "policy.json"
        path.write_text(json.dumps(POLICY))
        reg = load_policy(str(path))
        assert sorted(reg.tenants) == ["bulk", "crawler", "vip"]

    def test_load_errors_are_typed(self, tmp_path):
        with pytest.raises(QosPolicyError):
            load_policy(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text("{nope")
        with pytest.raises(QosPolicyError):
            load_policy(str(bad))

    def test_describe_is_json_shaped(self):
        desc = _registry().describe()
        assert [c["name"] for c in desc["classes"]] == [
            "interactive", "batch", "background"]
        assert json.loads(json.dumps(desc)) == desc


# ---------------------------------------------------------------------------
# token bucket + scheduler admission
# ---------------------------------------------------------------------------

class TestTokenBucket:
    def test_burst_then_refill(self):
        bucket = TokenBucket(rate=10.0, burst=2.0, now=0.0)
        assert bucket.try_take(0.0) == 0.0
        assert bucket.try_take(0.0) == 0.0
        wait = bucket.try_take(0.0)
        assert wait == pytest.approx(0.1)
        # after the hinted wait a token exists again
        assert bucket.try_take(wait) == 0.0

    def test_refill_caps_at_burst(self):
        bucket = TokenBucket(rate=100.0, burst=3.0, now=0.0)
        bucket.try_take(1000.0)
        assert bucket.tokens == pytest.approx(2.0)

    def test_peek_reports_without_mutating(self):
        # regression (JL017): metrics-scrape readers used to call _refill,
        # racing the admission path's read-modify-write of `tokens`
        bucket = TokenBucket(rate=10.0, burst=5.0, now=0.0)
        bucket.try_take(0.0)
        before = (bucket.tokens, bucket.t_last)
        assert bucket.peek(0.5) == pytest.approx(
            min(5.0, before[0] + 0.5 * 10.0))
        assert (bucket.tokens, bucket.t_last) == before
        # a stale clock reading never rolls the bucket backwards either
        assert bucket.peek(-1.0) == pytest.approx(before[0])
        assert (bucket.tokens, bucket.t_last) == before


class TestScheduler:
    def _scheduler(self, t0=0.0):
        clock = {"now": t0}
        sched = QosScheduler(_registry(), clock=lambda: clock["now"])
        return sched, clock

    def test_rate_limit_throttles_with_hint(self):
        sched, clock = self._scheduler()
        reg = _registry({"tenants": {"slow": {"rate": 2, "burst": 1}}})
        sched = QosScheduler(reg, clock=lambda: clock["now"])
        state = sched.resolve("slow")
        sched.admit(state)
        with pytest.raises(ThrottledError) as err:
            sched.admit(state)
        assert err.value.http_status == 429
        assert err.value.retry_after_s == pytest.approx(0.5)
        clock["now"] += 0.5
        sched.admit(state)  # the hint was sufficient, not just polite

    def test_max_queued_quota(self):
        sched, _ = self._scheduler()
        state = sched.resolve("crawler")
        sched.admit(state)
        sched.on_enqueue(state)
        sched.admit(state)
        sched.on_enqueue(state)
        with pytest.raises(ThrottledError):
            sched.admit(state)

    def test_timeout_inheritance(self):
        reg = _registry({"tenants": {"t": {"timeout_s": 0.25}}})
        sched = QosScheduler(reg)
        state = sched.resolve("t")
        assert sched.timeout_for(state, None) == 0.25
        assert sched.timeout_for(state, 1.5) == 1.5  # explicit wins
        assert sched.timeout_for(sched.resolve(None), None) is None

    def test_tenant_cardinality_is_bounded_by_policy(self):
        # the JL014 discipline at runtime: traffic cannot grow the table
        sched, _ = self._scheduler()
        before = len(sched._states)
        default = sched.resolve(None)
        for i in range(100):
            assert sched.resolve(f"invented-{i}") is default
        assert len(sched._states) == before

    def test_snapshot_and_gauges_leave_buckets_untouched(self):
        # regression (JL017): snapshot/scrape are observers; only admit()
        # may advance a bucket's (tokens, t_last) state
        sched, clock = self._scheduler()
        reg = _registry({"tenants": {"slow": {"rate": 2, "burst": 1}}})
        sched = QosScheduler(reg, clock=lambda: clock["now"])
        state = sched.resolve("slow")
        sched.admit(state)
        frozen = (state.bucket.tokens, state.bucket.t_last)
        clock["now"] += 0.25
        snap = sched.snapshot()
        assert (state.bucket.tokens, state.bucket.t_last) == frozen
        assert snap["tenants"]["slow"]["tokens"] == pytest.approx(0.5)

    def test_metrics_precreated_and_snapshot_shape(self):
        sched, _ = self._scheduler()
        metrics = ServeMetrics()
        sched.bind_metrics(metrics)
        snap = metrics.snapshot()
        assert snap["tenant_vip_requests_total"] == 0
        assert snap["class_background_shed_total"] == 0
        qos = sched.snapshot()
        assert sorted(qos["tenants"]) == ["bulk", "crawler", "default",
                                          "vip"]
        assert qos["classes"]["interactive"]["weight"] == 8.0
        assert json.loads(json.dumps(qos)) == qos


# ---------------------------------------------------------------------------
# weighted-fair queue
# ---------------------------------------------------------------------------

class TestWeightedFairQueue:
    def _wfq(self):
        return WeightedFairQueue(QosScheduler(_registry()))

    def test_saturated_shares_converge_to_weights(self):
        q = self._wfq()
        for i in range(400):
            for klass in ("background", "batch", "interactive"):
                q.put_nowait(_Item(klass, i))
        served = {"interactive": 0, "batch": 0, "background": 0}
        for _ in range(440):  # every class stays saturated throughout
            served[q.get_nowait().klass] += 1
        total = sum(served.values())
        for klass, weight in (("interactive", 8), ("batch", 2),
                              ("background", 1)):
            share = served[klass] / total
            assert share == pytest.approx(weight / 11, rel=0.10), served

    def test_fifo_within_class_and_idle_classes_cost_nothing(self):
        q = self._wfq()
        for i in range(5):
            q.put_nowait(_Item("batch", i))
        # no interactive/background traffic: batch drains back-to-back
        assert [q.get_nowait().tag for t in range(5)] == [0, 1, 2, 3, 4]
        with pytest.raises(asyncio.QueueEmpty):
            q.get_nowait()

    def test_control_lane_served_after_work_drains(self):
        q = self._wfq()
        stop = object()  # the engine's _STOP sentinel has no klass attr
        q.put_nowait(_Item("batch", 1))
        q.put_nowait(stop)
        q.put_nowait(_Item("interactive", 2))
        assert q.qsize() == 2  # control items are not queued work
        # both queued requests drain BEFORE the sentinel (stop-then-drain
        # would drop in-flight work on shutdown)
        assert {q.get_nowait().tag, q.get_nowait().tag} == {1, 2}
        assert q.get_nowait() is stop

    def test_async_get_wakes_on_put(self):
        async def go():
            q = self._wfq()
            getter = asyncio.create_task(q.get())
            await asyncio.sleep(0.01)
            assert not getter.done()
            q.put_nowait(_Item("interactive", 7))
            return (await getter).tag

        assert asyncio.run(go()) == 7

    def test_shed_only_strictly_lower_class(self):
        q = self._wfq()
        q.put_nowait(_Item("interactive", 0))
        q.put_nowait(_Item("batch", 1))
        q.put_nowait(_Item("batch", 2))
        q.put_nowait(_Item("background", 3))
        # interactive arrival: background is the lowest non-empty victim
        victim = q.shed_lower(0)
        assert victim.klass == "background"
        # background now empty -> batch gives back its NEWEST
        victim = q.shed_lower(0)
        assert (victim.klass, victim.tag) == ("batch", 2)
        # batch arrival cannot touch batch or interactive
        assert q.shed_lower(1) is None
        # background arrival (lowest class) can never shed anyone
        assert q.shed_lower(2) is None
        q.get_nowait()
        q.get_nowait()
        # queue holds nothing below interactive -> its arrivals get None
        assert q.shed_lower(0) is None

    def test_shed_never_violates_priority_under_churn(self):
        q = self._wfq()
        rank = {"interactive": 0, "batch": 1, "background": 2}
        pattern = ["batch", "background", "interactive", "batch",
                   "background", "batch", "interactive", "background"]
        for i, klass in enumerate(pattern * 5):
            q.put_nowait(_Item(klass, i))
        queued = {k: sum(1 for n in pattern * 5 if n == k) for k in rank}
        while True:
            victim = q.shed_lower(0)
            if victim is None:
                break
            # the victim is the lowest non-empty class below interactive
            assert rank[victim.klass] > 0
            lower = [k for k in rank if rank[k] > rank[victim.klass]]
            assert all(queued[k] == 0 for k in lower), victim.klass
            queued[victim.klass] -= 1
        assert queued["batch"] == queued["background"] == 0
        assert queued["interactive"] == 10  # never touched


# ---------------------------------------------------------------------------
# engine integration
# ---------------------------------------------------------------------------

def _qos_engine(fwd=None, *, max_queue=256, registry=None, **kw):
    sched = QosScheduler(registry or _registry())
    kw.setdefault("buckets", BucketTable((1, 2, 4)))
    kw.setdefault("max_delay_ms", 1.0)
    engine = InferenceEngine(
        fwd or (lambda batch: batch * 2.0), item_shape=(3,),
        policy=AdmissionPolicy(max_queue=max_queue, default_timeout_s=5.0),
        qos=sched, **kw)
    return engine, sched


class TestEngineQos:
    def test_tenant_requests_roundtrip_and_count(self):
        async def go():
            engine, sched = _qos_engine()
            await engine.start()
            out = await engine.submit(np.full(3, 2.0, np.float32),
                                      tenant="vip")
            await engine.stop()
            return out, sched

        out, sched = asyncio.run(go())
        assert np.allclose(out, 4.0)
        snap = sched.snapshot()
        assert snap["tenants"]["vip"]["requests"] == 1
        assert snap["classes"]["interactive"]["dispatched"] == 1

    def test_rate_limited_tenant_throttled(self):
        async def go():
            reg = _registry({"tenants": {"slow": {"rate": 0.1, "burst": 1}}})
            engine, _ = _qos_engine(registry=reg)
            await engine.start()
            item = np.zeros(3, np.float32)
            await engine.submit(item, tenant="slow")
            try:
                with pytest.raises(ThrottledError) as err:
                    await engine.submit(item, tenant="slow")
                return err.value
            finally:
                await engine.stop()

        err = asyncio.run(go())
        assert err.retry_after_s and err.retry_after_s > 1.0

    def test_tenant_deadline_inherited(self):
        def slow(batch):
            time.sleep(0.3)
            return batch

        async def go():
            from jimm_tpu.serve import DeadlineExceededError
            reg = _registry({"tenants": {"t": {"timeout_s": 0.05}}})
            engine, _ = _qos_engine(slow, registry=reg)
            await engine.start()
            try:
                with pytest.raises(DeadlineExceededError):
                    await engine.submit(np.zeros(3, np.float32), tenant="t")
            finally:
                await engine.stop()

        asyncio.run(go())

    def test_overload_sheds_lower_class_for_higher(self):
        def slow(batch):
            time.sleep(0.25)
            return batch * 2.0

        async def go():
            engine, sched = _qos_engine(slow, max_queue=3,
                                        buckets=BucketTable((1,)))
            await engine.start()
            item = np.zeros(3, np.float32)
            filler = asyncio.create_task(
                engine.submit(item, tenant="bulk"))
            await asyncio.sleep(0.1)  # batcher takes it into the slow lane
            bulk = [asyncio.create_task(engine.submit(item, tenant="bulk"))
                    for _ in range(3)]
            await asyncio.sleep(0)  # run each submit's sync admission part
            # queue is at max_queue: a BATCH arrival has no lower class to
            # shed, so it takes the plain queue-full rejection
            with pytest.raises(QueueFullError):
                await engine.submit(item, tenant="bulk")
            # an INTERACTIVE arrival evicts the newest bulk request instead
            vip = await engine.submit(item, tenant="vip")
            results = await asyncio.gather(filler, *bulk,
                                           return_exceptions=True)
            await engine.stop()
            return vip, results, sched

        vip, results, sched = asyncio.run(go())
        assert np.allclose(vip, 0.0)
        shed = [r for r in results if isinstance(r, ShedError)]
        served = [r for r in results if not isinstance(r, Exception)]
        assert len(shed) == 1
        assert shed[0].retry_after_s is not None
        assert len(served) == 3
        snap = sched.snapshot()
        assert snap["tenants"]["bulk"]["shed"] == 1
        assert snap["classes"]["batch"]["shed"] == 1

    def test_no_policy_path_is_plain_queue(self):
        async def go():
            engine = InferenceEngine(lambda b: b, item_shape=(3,),
                                     buckets=BucketTable((1, 2)))
            await engine.start()
            kind = type(engine._queue)
            # tenant= is accepted and ignored without a scheduler
            out = await engine.submit(np.zeros(3, np.float32),
                                      tenant="whoever")
            await engine.stop()
            return kind, out, engine

        kind, out, engine = asyncio.run(go())
        assert kind is asyncio.Queue
        assert engine.qos is None
        snap = engine.metrics.snapshot()
        assert not any(k.startswith(("tenant_", "class_")) for k in snap)


# ---------------------------------------------------------------------------
# model pool
# ---------------------------------------------------------------------------

def _pool_engine(scale, metrics, qos=None):
    return InferenceEngine(lambda b, s=scale: b * s, item_shape=(3,),
                           buckets=BucketTable((1, 2, 4)), max_delay_ms=1.0,
                           metrics=metrics, qos=qos)


class TestModelPool:
    def test_routing_and_unknown_model(self):
        metrics = ServeMetrics()
        a, b = _pool_engine(2.0, metrics), _pool_engine(3.0, metrics)
        pool = ModelPool({"default": a, "beta": b}, default="default")
        assert pool.get(None) is a
        assert pool.get("beta") is b
        with pytest.raises(RequestError):
            pool.get("gamma")
        assert metrics.count("model_beta_requests_total") == 1

    def test_add_swap_remove(self):
        metrics = ServeMetrics()
        a, b, c = (_pool_engine(s, metrics) for s in (1.0, 2.0, 3.0))
        pool = ModelPool({"default": a}, default="default")
        pool.add("canary", b)
        with pytest.raises(ValueError):
            pool.add("canary", c)  # already resident: swap, don't add
        old = pool.swap("canary", c)
        assert old is b
        assert pool.get("canary") is c
        assert pool.remove("canary") is c
        with pytest.raises(ValueError):
            pool.remove("default")  # the default model is not evictable
        assert pool.names() == ["default"]

    def test_describe_shape(self):
        metrics = ServeMetrics()
        pool = ModelPool({"default": _pool_engine(1.0, metrics)},
                         default="default")
        desc = pool.describe()
        assert desc["default"]["default"] is True
        assert desc["default"]["buckets"] == [1, 2, 4]


# ---------------------------------------------------------------------------
# HTTP end to end: tenant headers, model routing, typed errors, healthz
# ---------------------------------------------------------------------------

@pytest.fixture()
def qos_server():
    registry = _registry({
        "classes": POLICY["classes"],
        "tenants": dict(POLICY["tenants"],
                        slow={"class": "batch", "rate": 0.1, "burst": 1}),
        "default": {"class": "batch"},
    })
    sched = QosScheduler(registry)
    metrics = ServeMetrics()
    default = _pool_engine(2.0, metrics, qos=sched)
    beta = _pool_engine(3.0, metrics, qos=sched)
    pool = ModelPool({"default": default, "beta": beta}, default="default")
    server = ServingServer(default, pool=pool, port=0)
    server.start()
    try:
        yield server
    finally:
        server.stop()


class TestHttpQos:
    def _item(self):
        return np.full(3, 1.0, np.float32)

    def test_model_routing_via_header(self, qos_server):
        base = ServeClient(port=qos_server.port, tenant="vip")
        beta = ServeClient(port=qos_server.port, tenant="vip", model="beta")
        assert np.allclose(base.embed(self._item(), timeout_s=5), 2.0)
        assert np.allclose(beta.embed(self._item(), timeout_s=5), 3.0)
        from jimm_tpu.serve import ServeClientError
        bad = ServeClient(port=qos_server.port, model="gamma")
        with pytest.raises(ServeClientError) as err:
            bad.embed(self._item(), timeout_s=5)
        assert err.value.status == 400
        assert "gamma" in str(err.value)

    def test_throttled_is_typed_with_retry_after(self, qos_server):
        client = ServeClient(port=qos_server.port, tenant="slow")
        client.embed(self._item(), timeout_s=5)
        with pytest.raises(ThrottledClientError) as err:
            client.embed(self._item(), timeout_s=5)
        assert err.value.status == 429
        assert err.value.code == "throttled"
        assert err.value.retry_after_s and err.value.retry_after_s > 1.0

    def test_healthz_has_qos_and_models_blocks(self, qos_server):
        health = ServeClient(port=qos_server.port).healthz()
        assert "vip" in health["qos"]["tenants"]
        assert health["qos"]["classes"]["interactive"]["weight"] == 8.0
        assert sorted(health["models"]) == ["beta", "default"]
        assert health["models"]["default"]["default"] is True

    def test_metrics_expose_tenant_and_class_series(self, qos_server):
        client = ServeClient(port=qos_server.port, tenant="vip")
        client.embed(self._item(), timeout_s=5)
        text = client.metrics_text()
        assert "jimm_serve_tenant_vip_requests_total" in text
        assert "jimm_serve_class_interactive_requests_total" in text
        assert "jimm_serve_model_beta_requests_total" in text

    def test_policy_free_server_healthz_unchanged(self):
        engine = _pool_engine(2.0, ServeMetrics())
        server = ServingServer(engine, port=0)
        server.start()
        try:
            health = ServeClient(port=server.port).healthz()
        finally:
            server.stop()
        assert "qos" not in health
        assert "models" not in health


# ---------------------------------------------------------------------------
# client retry behavior against a stub server
# ---------------------------------------------------------------------------

class _StubHandler(BaseHTTPRequestHandler):
    script: list = []  # [(status, body_dict, retry_after or None), ...]
    seen: list = []

    def log_message(self, fmt, *args):  # noqa: A003 — quiet test output
        pass

    def do_POST(self):  # noqa: N802 — BaseHTTPRequestHandler API
        self.rfile.read(int(self.headers.get("Content-Length", 0)))
        type(self).seen.append(dict(self.headers))
        status, obj, retry_after = (self.script.pop(0) if self.script
                                    else (200, {"features": [[1.0]]}, None))
        body = json.dumps(obj).encode()
        self.send_response(status)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        if retry_after is not None:
            self.send_header("Retry-After", f"{retry_after:.3f}")
        self.end_headers()
        self.wfile.write(body)


@pytest.fixture()
def stub_server():
    _StubHandler.script = []
    _StubHandler.seen = []
    httpd = ThreadingHTTPServer(("127.0.0.1", 0), _StubHandler)
    thread = threading.Thread(target=httpd.serve_forever, daemon=True)
    thread.start()
    try:
        yield httpd
    finally:
        httpd.shutdown()
        httpd.server_close()


class TestClientRetry:
    def test_tenant_and_model_headers_sent(self, stub_server):
        client = ServeClient(port=stub_server.server_port, tenant="alice",
                             model="beta")
        client.embed(np.zeros(3, np.float32))
        headers = _StubHandler.seen[0]
        assert headers["X-Jimm-Tenant"] == "alice"
        assert headers["X-Jimm-Model"] == "beta"

    def test_throttled_not_retried_by_default(self, stub_server):
        _StubHandler.script = [
            (429, {"error": "throttled", "message": "slow down"}, 0.123)]
        client = ServeClient(port=stub_server.server_port)
        with pytest.raises(ThrottledClientError) as err:
            client.embed(np.zeros(3, np.float32))
        assert err.value.retry_after_s == pytest.approx(0.123)

    def test_retry_throttled_honors_retry_after(self, stub_server):
        _StubHandler.script = [
            (429, {"error": "throttled", "message": "slow down"}, 0.123)]
        client = ServeClient(port=stub_server.server_port, retry_throttled=2,
                             backoff_base_s=0.001, backoff_seed=7)
        slept = []
        client._sleep = slept.append
        out = client.embed(np.zeros(3, np.float32))
        assert out == [[1.0]]
        assert len(_StubHandler.seen) == 2
        assert slept and slept[0] >= 0.123  # at least the server's hint

    def test_shed_is_typed_and_retryable(self, stub_server):
        _StubHandler.script = [
            (503, {"error": "shed", "message": "sacrificed"}, 0.05),
            (503, {"error": "shed", "message": "sacrificed"}, 0.05)]
        client = ServeClient(port=stub_server.server_port, retry_throttled=1,
                             backoff_base_s=0.001, backoff_seed=7)
        client._sleep = lambda s: None
        with pytest.raises(ShedClientError) as err:
            client.embed(np.zeros(3, np.float32))
        assert err.value.status == 503
        assert err.value.code == "shed"
        assert len(_StubHandler.seen) == 2  # one retry, then surfaced

    def test_retry_budget_bounded_by_deadline(self, stub_server):
        _StubHandler.script = [
            (429, {"error": "throttled", "message": "later"}, 30.0)]
        client = ServeClient(port=stub_server.server_port, retry_throttled=5)
        client._sleep = lambda s: pytest.fail("slept past the deadline")
        with pytest.raises(ThrottledClientError):
            client.embed(np.zeros(3, np.float32), timeout_s=0.2)

    def test_queue_full_stays_untyped_503(self, stub_server):
        from jimm_tpu.serve import ServeClientError
        _StubHandler.script = [
            (503, {"error": "queue_full", "message": "full"}, None)]
        client = ServeClient(port=stub_server.server_port)
        with pytest.raises(ServeClientError) as err:
            client.embed(np.zeros(3, np.float32))
        assert not isinstance(err.value, (ThrottledClientError,
                                          ShedClientError))
        assert err.value.code == "queue_full"


# ---------------------------------------------------------------------------
# CLI + import hygiene
# ---------------------------------------------------------------------------

class TestQosCli:
    def test_validate_ok(self, tmp_path, capsys):
        from jimm_tpu.serve.qos.cli import main
        path = tmp_path / "policy.json"
        path.write_text(json.dumps(POLICY))
        assert main(["qos", "validate", str(path)]) == 0
        assert "OK" in capsys.readouterr().out

    def test_validate_lists_every_problem(self, tmp_path, capsys):
        from jimm_tpu.serve.qos.cli import main
        path = tmp_path / "bad.json"
        path.write_text(json.dumps(
            {"tenants": {"t": {"class": "nope", "rate": -1}}}))
        assert main(["qos", "validate", str(path)]) == 1
        out = capsys.readouterr().out
        assert "INVALID" in out
        assert "unknown class" in out
        assert "rate" in out

    def test_ls_json(self, tmp_path, capsys):
        from jimm_tpu.serve.qos.cli import main
        path = tmp_path / "policy.json"
        path.write_text(json.dumps(POLICY))
        assert main(["qos", "ls", str(path), "--json"]) == 0
        desc = json.loads(capsys.readouterr().out)
        assert [t["name"] for t in desc["tenants"]] == ["bulk", "crawler",
                                                        "vip"]

    def test_qos_package_imports_without_jax(self):
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys\n"
             "import jimm_tpu.serve.qos.cli\n"
             "import jimm_tpu.serve.qos.policy\n"
             "assert 'jax' not in sys.modules, 'qos CLI dragged in jax'"],
            capture_output=True, text=True)
        assert proc.returncode == 0, proc.stderr
