"""TFRecord codec + file-based input pipeline tests.

Format compatibility is pinned against real tensorflow (installed in the dev
image, never imported by library code): records we write must parse with
``tf.data`` / ``tf.train.Example``, and vice versa. The end-to-end test
trains the CLI from tfrecord files on disk (VERDICT r1 item #5)."""

import json

import numpy as np
import pytest

from jimm_tpu.data.tfrecord import (TFRecordWriter, _crc32c_py, crc32c,
                                    decode_example, encode_example,
                                    masked_crc32c, read_tfrecord,
                                    write_tfrecord)


def test_crc32c_known_vectors():
    # RFC 3720 / iSCSI test vectors
    assert crc32c(b"123456789") == 0xE3069283
    assert crc32c(b"") == 0
    assert crc32c(b"\x00" * 32) == 0x8A9136AA
    assert _crc32c_py(b"123456789") == 0xE3069283
    assert _crc32c_py(bytes(range(32))) == crc32c(bytes(range(32)))


def test_tfrecord_roundtrip(tmp_path):
    path = tmp_path / "x.tfrecord"
    records = [b"one", b"", b"three" * 1000]
    assert write_tfrecord(path, records) == 3
    assert list(read_tfrecord(path)) == records


def test_tfrecord_detects_corruption(tmp_path):
    path = tmp_path / "x.tfrecord"
    write_tfrecord(path, [b"payload-bytes"])
    raw = bytearray(path.read_bytes())
    raw[14] ^= 0xFF  # flip a payload byte
    path.write_bytes(bytes(raw))
    with pytest.raises(ValueError, match="corrupt record crc"):
        list(read_tfrecord(path))
    assert list(read_tfrecord(path, verify=False))  # opt-out still reads


def test_example_roundtrip():
    feats = {"image": b"\x89PNGxxxx", "tokens": [3, 1, 4, -1, 5],
             "score": [0.5, 2.25], "name": "caption"}
    dec = decode_example(encode_example(feats))
    assert dec["image"] == [b"\x89PNGxxxx"]
    assert dec["tokens"] == [3, 1, 4, -1, 5]
    assert dec["score"] == [0.5, 2.25]
    assert dec["name"] == [b"caption"]


tf = pytest.importorskip("tensorflow")


def test_example_parses_with_tensorflow():
    buf = encode_example({"tokens": [7, -9, 1 << 40], "img": b"ab",
                          "w": [1.5]})
    ex = tf.train.Example.FromString(buf)
    f = ex.features.feature
    assert list(f["tokens"].int64_list.value) == [7, -9, 1 << 40]
    assert f["img"].bytes_list.value[0] == b"ab"
    assert abs(f["w"].float_list.value[0] - 1.5) < 1e-6


def test_decode_tensorflow_serialized_example():
    ex = tf.train.Example(features=tf.train.Features(feature={
        "a": tf.train.Feature(int64_list=tf.train.Int64List(value=[7, -9])),
        "b": tf.train.Feature(bytes_list=tf.train.BytesList(value=[b"xy"])),
        "c": tf.train.Feature(float_list=tf.train.FloatList(value=[2.5])),
    }))
    dec = decode_example(ex.SerializeToString())
    assert dec["a"] == [7, -9]
    assert dec["b"] == [b"xy"]
    assert dec["c"] == [2.5]


def test_tensorflow_reads_our_tfrecord(tmp_path):
    path = str(tmp_path / "ours.tfrecord")
    records = [b"alpha", encode_example({"x": [1]}), b"z" * 999]
    write_tfrecord(path, records)
    got = [r.numpy() for r in tf.data.TFRecordDataset(path)]
    assert got == records


def test_we_read_tensorflow_tfrecord_with_crc(tmp_path):
    path = str(tmp_path / "tfs.tfrecord")
    with tf.io.TFRecordWriter(path) as w:
        for r in [b"alpha", b"beta" * 77]:
            w.write(r)
    assert list(read_tfrecord(path, verify=True)) == [b"alpha", b"beta" * 77]


# ---------------------------------------------------------------------------
# File-based batch pipeline
# ---------------------------------------------------------------------------

def _write_pairs(path, n, image_size=20, seq_len=6, seed=0):
    from jimm_tpu.data.records import write_image_text_records
    rng = np.random.RandomState(seed)
    pairs = [(rng.randint(0, 255, size=(image_size, image_size, 3),
                          dtype=np.uint8).astype(np.uint8),
              rng.randint(1, 60, size=rng.randint(2, seq_len + 3)))
             for _ in range(n)]
    write_image_text_records(path, pairs, encoding="png")
    return pairs


def test_image_text_batches_from_png_records(tmp_path):
    from jimm_tpu.data.records import image_text_batches
    pairs = _write_pairs(tmp_path / "a.tfrecord", 10)
    it = image_text_batches(str(tmp_path / "a.tfrecord"), 4, image_size=16,
                            seq_len=8, repeat=False)
    batches = list(it)
    assert len(batches) == 2  # 10 examples -> two full batches of 4
    images, tokens = batches[0]
    assert images.shape == (4, 16, 16, 3) and images.dtype == np.float32
    assert tokens.shape == (4, 8) and tokens.dtype == np.int32
    # first example's tokens survive the pad/truncate round trip
    t0 = np.asarray(pairs[0][1][:8])
    assert (tokens[0, :len(t0)] == t0).all()


def test_classification_batches_sharded(tmp_path):
    from jimm_tpu.data.records import (classification_batches,
                                       write_classification_records)
    rng = np.random.RandomState(1)
    pairs = [(rng.randint(0, 255, size=(12, 12, 3), dtype=np.uint8), i % 4)
             for i in range(12)]
    write_classification_records(tmp_path / "c.tfrecord", pairs,
                                 encoding="raw")
    # two shards must partition the label stream disjointly
    seen = []
    for shard in (0, 1):
        for _, labels in classification_batches(
                str(tmp_path / "c.tfrecord"), 2, image_size=12, repeat=False,
                shard_index=shard, shard_count=2):
            seen.extend(labels.tolist())
    assert sorted(seen) == sorted(p[1] for p in pairs)


def test_cli_train_from_tfrecord(tmp_path):
    """End-to-end: training runs from files on disk through the CLI."""
    from jimm_tpu.cli import main
    _write_pairs(tmp_path / "train.tfrecord", 24, image_size=32, seq_len=8,
                 seed=3)
    metrics = tmp_path / "m.jsonl"
    rc = main(["train", "--preset", "siglip-base-patch16-256", "--tiny",
               "--data", str(tmp_path / "train.tfrecord"),
               "--batch-size", "4", "--steps", "3", "--log-every", "0",
               "--shuffle-buffer", "8", "--metrics-file", str(metrics)])
    assert rc == 0
    with open(metrics) as f:
        recs = [json.loads(line) for line in f]
    assert [r["step"] for r in recs] == [0, 1, 2]
    assert all(np.isfinite(r["loss"]) for r in recs)
