"""Confidence calibration for the cascade router (jax-free).

The router's escalation signal is a **temperature-scaled logit margin**:
softmax the cheap model's score row at temperature ``T`` and take
``p_top1 - p_top2``. Raw margins are uncalibrated — an int8 twin can be
confidently wrong — so the threshold the router compares against is *fit
on a holdout set* for a target top-1 disagreement rate and persisted as a
content-addressed artifact on the AOT store. Routers load calibrations;
they never ship hardcoded thresholds (lint rule JL021 bans numeric
threshold literals everywhere in ``serve/cascade/`` except this module).

Fitting is two stages over ``(cheap_logits, agree)`` pairs, where
``agree[i]`` says whether the cheap model's top-1 matched the reference
(wide-dtype) model's on holdout item ``i``:

1. **Temperature**: grid-search ``T`` minimizing the binary cross-entropy
   between the margin and the agreement labels — the margin becomes an
   honest probability-like predictor of "the expensive model would say
   the same thing".
2. **Threshold**: rank the holdout by calibrated margin and pick the
   *lowest* threshold whose accepted prefix keeps top-1 disagreement at
   or under the target (default 1%). Lowest = maximal acceptance =
   maximal cost saving at the contracted quality.

The artifact's fingerprint is the SHA-256 of its canonical JSON payload,
so identical calibrations land on identical store entries and a router
can pin a calibration by content, not by path.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json

import numpy as np

__all__ = ["CALIBRATION_KIND", "CALIBRATION_VERSION", "CascadeCalibration",
           "fit_calibration", "fit_from_logits", "list_calibrations",
           "load_calibration", "save_calibration"]

#: meta.json ``kind`` tag that marks a store entry as a cascade calibration
CALIBRATION_KIND = "cascade_calibration"
CALIBRATION_VERSION = 1

#: temperature grid (log-spaced): wide enough to cover peaked int8 logits
#: and nearly-flat random-init embeddings
_TEMPERATURES = np.logspace(-1.5, 1.5, 61)


@dataclasses.dataclass(frozen=True)
class CascadeCalibration:
    """One fitted (cheap model, reference model) escalation policy."""

    cheap_model: str
    reference_model: str
    temperature: float
    threshold: float
    target_disagreement: float
    measured_disagreement: float
    escalation_fraction: float
    holdout: int
    version: int = CALIBRATION_VERSION

    def confidence(self, scores) -> float:
        """Temperature-scaled top-1/top-2 softmax margin of one score row,
        in [0, 1]. This is THE confidence signal the router thresholds."""
        z = np.asarray(scores, np.float64).reshape(-1) / self.temperature
        z -= z.max()
        p = np.exp(z)
        p /= p.sum()
        if p.size < 2:
            return 1.0
        top1, top2 = _top2(p)
        return float(top1 - top2)

    def accepts(self, scores) -> tuple[bool, float]:
        """(accept, confidence) for one cheap-model score row."""
        conf = self.confidence(scores)
        return conf >= self.threshold, conf

    # -- wire format -------------------------------------------------------

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, data: dict) -> "CascadeCalibration":
        version = data.get("version")
        if version != CALIBRATION_VERSION:
            raise ValueError(f"calibration version {version!r} != "
                             f"{CALIBRATION_VERSION}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(data) - known
        if unknown:
            raise ValueError(f"unknown calibration keys {sorted(unknown)}")
        missing = known - set(data)
        if missing:
            raise ValueError(f"missing calibration keys {sorted(missing)}")
        return cls(**{k: (int(v) if k in ("holdout", "version")
                          else str(v) if k.endswith("_model") else float(v))
                      for k, v in data.items()})

    def payload(self) -> bytes:
        """Canonical JSON bytes — the content the fingerprint addresses."""
        return json.dumps(self.to_dict(), sort_keys=True,
                          separators=(",", ":")).encode()

    @property
    def fingerprint(self) -> str:
        return hashlib.sha256(self.payload()).hexdigest()


def _top2(p: np.ndarray) -> tuple[float, float]:
    """Largest two entries without a full sort (O(n) partition)."""
    idx = int(np.argmax(p))
    top1 = float(p[idx])
    rest = np.delete(p, idx)
    return top1, float(rest.max()) if rest.size else 0.0


def _margins(logits: np.ndarray, temperature: float) -> np.ndarray:
    """Per-row temperature-scaled softmax margin, vectorized for the fit."""
    z = logits / temperature
    z = z - z.max(axis=1, keepdims=True)
    p = np.exp(z)
    p /= p.sum(axis=1, keepdims=True)
    part = -np.partition(-p, 1, axis=1)
    return part[:, 0] - part[:, 1]


def fit_calibration(cheap_logits, agree, *, cheap_model: str,
                    reference_model: str,
                    target_disagreement: float = 0.01
                    ) -> CascadeCalibration:
    """Fit temperature + threshold from holdout score rows and per-row
    top-1 agreement labels (True = cheap and reference models agreed)."""
    logits = np.asarray(cheap_logits, np.float64)
    agree = np.asarray(agree, bool).reshape(-1)
    if logits.ndim != 2 or logits.shape[1] < 2:
        raise ValueError(f"cheap_logits must be (N, C>=2), "
                         f"got shape {logits.shape}")
    if logits.shape[0] != agree.shape[0]:
        raise ValueError(f"{logits.shape[0]} logit rows vs "
                         f"{agree.shape[0]} agreement labels")
    if not 0.0 < target_disagreement < 1.0:
        raise ValueError(f"target_disagreement must be in (0, 1), "
                         f"got {target_disagreement}")
    n = logits.shape[0]

    # stage 1: temperature by BCE between margin and agreement
    y = agree.astype(np.float64)
    best_t, best_loss = 1.0, np.inf
    for t in _TEMPERATURES:
        m = np.clip(_margins(logits, float(t)), 1e-9, 1.0 - 1e-9)
        loss = float(-(y * np.log(m) + (1.0 - y) * np.log1p(-m)).mean())
        if loss < best_loss:
            best_t, best_loss = float(t), loss

    # stage 2: lowest threshold whose accepted prefix meets the target.
    # np.lexsort is the sanctioned host-side ranking (JL011): the holdout
    # is a bounded operator-supplied set, not serving traffic.
    conf = _margins(logits, best_t)
    order = np.lexsort((conf,))[::-1]  # descending confidence
    disagree = (~agree[order]).cumsum()
    accepted = np.arange(1, n + 1)
    feasible = np.nonzero(disagree <= target_disagreement * accepted)[0]
    if feasible.size:
        k = int(feasible.max())
        threshold = float(conf[order[k]])
    else:
        # no prefix is clean enough: escalate everything
        threshold = float(np.nextafter(conf.max(), np.inf))
    keep = conf >= threshold
    kept = int(keep.sum())
    measured = float((~agree[keep]).sum() / n)
    # temperature/threshold ship at full float precision: the boundary
    # row's accept/escalate decision must reproduce bit-exactly from the
    # stored artifact (rounding here once moved `measured` by one row)
    return CascadeCalibration(
        cheap_model=cheap_model, reference_model=reference_model,
        temperature=float(best_t), threshold=float(threshold),
        target_disagreement=float(target_disagreement),
        measured_disagreement=round(measured, 6),
        escalation_fraction=round(1.0 - kept / n, 6), holdout=n)


def fit_from_logits(cheap_logits, reference_logits, **kwargs
                    ) -> CascadeCalibration:
    """Fit from both models' holdout score rows: the agreement label is
    per-row top-1 equality. See :func:`fit_calibration` for kwargs."""
    cheap = np.asarray(cheap_logits, np.float64)
    ref = np.asarray(reference_logits, np.float64)
    if cheap.shape != ref.shape:
        raise ValueError(f"logit shapes differ: cheap {cheap.shape} vs "
                         f"reference {ref.shape}")
    agree = cheap.argmax(axis=1) == ref.argmax(axis=1)
    return fit_calibration(cheap, agree, **kwargs)


# -- store persistence (content-addressed, AOT ArtifactStore) --------------

def save_calibration(store, calib: CascadeCalibration) -> str:
    """Persist on the AOT artifact store; returns the content fingerprint.
    Identical calibrations re-land on the same entry (same bytes, same
    hash), so saves are idempotent."""
    payload = calib.payload()
    fp = calib.fingerprint
    store.put(fp, payload, meta={
        "kind": CALIBRATION_KIND,
        "label": f"cascade:{calib.cheap_model}->{calib.reference_model}",
        "threshold": calib.threshold,
        "temperature": calib.temperature,
        "measured_disagreement": calib.measured_disagreement,
        "escalation_fraction": calib.escalation_fraction,
    })
    return fp


def load_calibration(store, fingerprint: str) -> CascadeCalibration:
    """Load + verify a calibration by content fingerprint. Raises
    ``ValueError`` on a missing, corrupt, or mis-addressed entry — a
    router must fail loudly rather than serve an uncalibrated cascade."""
    payload = store.get(fingerprint)
    if payload is None:
        raise ValueError(f"no calibration {fingerprint!r} in store "
                         f"{store.root}")
    if hashlib.sha256(payload).hexdigest() != fingerprint:
        raise ValueError(f"calibration {fingerprint!r} is not content-"
                         "addressed by its payload hash")
    try:
        data = json.loads(payload)
    except ValueError as e:
        raise ValueError(f"calibration {fingerprint!r}: bad JSON payload: "
                         f"{e}") from None
    return CascadeCalibration.from_dict(data)


def list_calibrations(store) -> list[dict]:
    """Calibration entries on a store (the ``jimm-tpu cascade ls`` rows),
    newest first."""
    rows = []
    for entry in store.entries():
        if entry.meta.get("kind") != CALIBRATION_KIND:
            continue
        rows.append({
            "fingerprint": entry.fingerprint,
            "label": entry.meta.get("label"),
            "threshold": entry.meta.get("threshold"),
            "temperature": entry.meta.get("temperature"),
            "measured_disagreement": entry.meta.get("measured_disagreement"),
            "escalation_fraction": entry.meta.get("escalation_fraction"),
            "created": entry.created,
        })
    rows.sort(key=lambda r: r["created"], reverse=True)
    return rows
