"""Two-stage IVF approximate top-k: coarse centroid scan + exact rescore.

Stage 1 is one small ``(B, C)`` matmul against the codebook followed by
``lax.top_k`` over ``nprobe_max`` clusters. Stage 2 exact-rescores *only*
the candidate cluster spans with the same streaming-scan + running
``lax.top_k`` idiom ``topk.py`` proves out: the corpus lives on device
cluster-major as ``(nblocks, block_n, D)`` blocks (no block spans two
clusters), and a ``lax.scan`` of ``nprobe_max * max_blocks_per_cluster``
steps gathers each query's candidate blocks by *runtime* block index —
derived on device from the resident per-cluster (start, count) span table
— and folds per-block ``top_k`` into a ``(B, k)`` carry. Rows carry their
global index in a resident ``(nblocks, block_n)`` id map (``-1`` padding
masks to ``-inf``), so results are exact over the probed subset.

Both stages are one fused program. Everything that varies at request time
— the probe width ``nprobe``, the live-centroid count — is a *runtime
scalar*, so every request shape compiles once: equally-padded replica
partitions share one program and one AOT fingerprint
(``method="retrieval_ivf"`` in the same artifact store as the serve
buckets), and sweeping ``nprobe`` on a warm server is zero recompiles by
construction. The corpus stays replica-sharded over the PR 6 submeshes —
clusters partition contiguously across replicas, each replica scores its
owned spans (unowned clusters have empty span tables), and the final merge
is the bounded host-side ``merge_partials`` lexsort over ``R * k``
candidates. Block sizes resolve through
``tune.best_config("retrieval_ivf", ...)``; an explicit ``block_n`` wins.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Callable, Sequence

import numpy as np

from jimm_tpu.retrieval.store import LoadedIndex, normalize_rows
from jimm_tpu.retrieval.topk import merge_partials

__all__ = ["DEFAULT_NPROBE", "IvfIndexSearcher", "IvfSearcher",
           "cluster_layout", "make_ivf_fn"]

#: serve-time default probe width; ``--nprobe`` / per-request ``nprobe``
#: override it up to the searcher's compiled ``nprobe_max``
DEFAULT_NPROBE = 8

_LANES = 128


def _ceil_to(x: int, m: int) -> int:
    return (x + m - 1) // m * m


# ---------------------------------------------------------------------------
# device program
# ---------------------------------------------------------------------------

def make_ivf_fn(k: int, nprobe_max: int, max_bpc: int) -> Callable:
    """The traceable two-stage program for one ``(k, nprobe_max,
    max_bpc)``.

    Signature: ``fn(blocks (nb, bn, D), row_ids (nb, bn) i32,
    centroids (Cp, D) f32, cl_start (Cp,) i32, cl_count (Cp,) i32,
    live_c () i32, nprobe () i32, queries (B, D) f32) -> (values (B, k)
    f32, indices (B, k) i32, cand_rows (B,) i32)`` where ``indices`` are
    global corpus rows (from the resident id map, ``-1`` past the probed
    set) and ``cand_rows`` counts the live rows each query rescored —
    the candidate_frac observability series divides it by corpus size.
    """
    import jax
    import jax.numpy as jnp

    k, nprobe_max, max_bpc = int(k), int(nprobe_max), int(max_bpc)

    def fn(blocks, row_ids, centroids, cl_start, cl_count, live_c,
           nprobe, queries):
        qf = queries.astype(jnp.float32)
        batch = qf.shape[0]
        block_n = blocks.shape[1]
        kk = min(k, block_n)

        # stage 1: (B, Cp) coarse scores -> top nprobe_max clusters;
        # padded centroid rows mask to -inf so they sort last, and the
        # runtime nprobe mask trims the probe list without a retrace
        cscores = qf @ centroids.astype(jnp.float32).T
        c_iota = jax.lax.iota(jnp.int32, centroids.shape[0])
        cscores = jnp.where(c_iota[None, :] < live_c, cscores, -jnp.inf)
        _, sel = jax.lax.top_k(cscores, nprobe_max)  # (B, P) cluster ids
        probe_live = jax.lax.iota(jnp.int32, nprobe_max) < nprobe

        # candidate block list per query: each selected cluster expands to
        # its span of (at most max_bpc) blocks via the resident runtime
        # offsets/live-counts; -1 marks padding (unowned or past-count)
        starts = cl_start[sel]                       # (B, P)
        counts = cl_count[sel]                       # (B, P)
        j = jax.lax.iota(jnp.int32, max_bpc)
        cand = starts[..., None] + j[None, None, :]  # (B, P, M)
        live_cand = (j[None, None, :] < counts[..., None]) \
            & probe_live[None, :, None]
        cand = jnp.where(live_cand, cand, -1)
        cand = cand.reshape(batch, nprobe_max * max_bpc)

        def body(carry, bidx):
            carry_vals, carry_idx, carry_rows = carry
            safe = jnp.maximum(bidx, 0)
            blk = blocks[safe]                       # (B, bn, D) gather
            rid = row_ids[safe]                      # (B, bn)
            # the MXU step, batched per query's own block
            scores = jnp.einsum("bd,bnd->bn", qf,
                                blk.astype(jnp.float32))
            live = (rid >= 0) & (bidx >= 0)[:, None]
            scores = jnp.where(live, scores, -jnp.inf)
            block_vals, block_pos = jax.lax.top_k(scores, kk)
            block_idx = jnp.take_along_axis(
                jnp.where(live, rid, -1), block_pos, axis=1)
            # carry first: same stable earlier-candidate tie order as the
            # exact kernel, within the probe traversal
            merged_vals, merged_pos = jax.lax.top_k(
                jnp.concatenate([carry_vals, block_vals], axis=1), k)
            merged_idx = jnp.take_along_axis(
                jnp.concatenate([carry_idx, block_idx], axis=1),
                merged_pos, axis=1)
            carry_rows = carry_rows + jnp.sum(live, axis=1,
                                              dtype=jnp.int32)
            return (merged_vals, merged_idx, carry_rows), None

        init = (jnp.full((batch, k), -jnp.inf, jnp.float32),
                jnp.full((batch, k), -1, jnp.int32),
                jnp.zeros((batch,), jnp.int32))
        (vals, idx, rows), _ = jax.lax.scan(body, init, cand.T)
        return vals, idx, rows

    return fn


# ---------------------------------------------------------------------------
# host-side layout
# ---------------------------------------------------------------------------

def cluster_layout(vectors: np.ndarray, assign: np.ndarray,
                   n_clusters: int, *, block_n: int,
                   row_ids: np.ndarray | None = None,
                   pad_blocks: int | None = None
                   ) -> tuple[np.ndarray, np.ndarray, np.ndarray,
                              np.ndarray]:
    """Pack rows cluster-major into the device layout: ``(blocks (nb, bn,
    D), row_ids (nb, bn) i32, cl_start (C,) i32, cl_count (C,) i32)``.
    No block spans two clusters (each cluster pads its last block), so a
    cluster's span is exactly ``cl_start[c] : cl_start[c] + cl_count[c]``
    blocks. ``row_ids`` carries each packed row's global corpus index
    (``-1`` padding); ``pad_blocks`` pads ``nb`` so every replica
    partition of one index shares shapes — and one AOT fingerprint."""
    vectors = np.ascontiguousarray(np.asarray(vectors))
    if vectors.ndim != 2:
        raise ValueError(f"vectors must be (N, D); got {vectors.shape}")
    n, dim = vectors.shape
    block_n = max(1, int(block_n))
    n_clusters = int(n_clusters)
    assign = np.asarray(assign, np.int64)
    if assign.shape != (n,):
        raise ValueError(f"assign must be ({n},); got {assign.shape}")
    if n and (assign.min() < 0 or assign.max() >= n_clusters):
        raise ValueError("assign has cluster ids outside "
                         f"[0, {n_clusters})")
    if row_ids is None:
        row_ids = np.arange(n, dtype=np.int64)
    row_ids = np.asarray(row_ids, np.int64)
    # stable cluster-major order (ties by global row id) via the
    # sanctioned lexsort — primary key last
    order = np.lexsort((row_ids, assign))
    counts = np.bincount(assign, minlength=n_clusters) if n else \
        np.zeros(n_clusters, np.int64)
    blocks_per = (counts + block_n - 1) // block_n
    nb = max(int(blocks_per.sum()), 1)
    if pad_blocks is not None:
        if int(pad_blocks) < nb:
            raise ValueError(f"pad_blocks={pad_blocks} < {nb} blocks")
        nb = int(pad_blocks)
    blocks = np.zeros((nb, block_n, dim), vectors.dtype)
    rids = np.full((nb, block_n), -1, np.int32)
    cl_start = np.zeros(n_clusters, np.int32)
    cl_count = np.asarray(blocks_per, np.int32)
    b = pos = 0
    for c in range(n_clusters):
        cnt = int(counts[c])
        cl_start[c] = b
        if not cnt:
            continue
        rows = order[pos:pos + cnt]
        pos += cnt
        for off in range(0, cnt, block_n):
            chunk = rows[off:off + block_n]
            blocks[b, :len(chunk)] = vectors[chunk]
            rids[b, :len(chunk)] = row_ids[chunk]
            b += 1
    return blocks, rids, cl_start, cl_count


def _resolve_block_n(n: int, dim: int, dtype, batch: int,
                     block_n: int | None) -> int:
    """Explicit block wins (tuner bench closures must not recurse);
    otherwise consult the persistent tune cache — same contract as
    ``retrieval_topk``, separate kernel key (the IVF scan gathers one
    block *per query* per step, so its VMEM model scales with batch)."""
    if block_n is not None:
        return int(block_n)
    from jimm_tpu import tune
    config = tune.best_config(
        "retrieval_ivf",
        shapes=[(int(batch), int(dim)), (int(n), int(dim))],
        dtypes=[np.dtype(dtype)])
    return int(config["block_n"])


# ---------------------------------------------------------------------------
# warm searchers (AOT + tune integration)
# ---------------------------------------------------------------------------

class IvfSearcher:
    """One cluster partition's warm IVF forward: device-resident
    cluster-major blocks + span tables + codebook, and a store-first
    compiled program per query bucket.

    Same dispatch contract as :class:`~jimm_tpu.retrieval.topk.Searcher`:
    ``prepare(bucket)`` consults the artifact store under an ``aot_load``
    span (hit/miss/fallback counted in ``jimm_aot``), the fresh path is a
    counting jit, and a loaded executable that raises at call time
    quarantines itself and degrades to fresh.
    """

    def __init__(self, vectors: np.ndarray, assign: np.ndarray,
                 centroids: np.ndarray, *, k: int, nprobe_max: int,
                 buckets: Sequence[int] = (1,), block_n: int | None = None,
                 mesh: Any = None, row_ids: np.ndarray | None = None,
                 pad_blocks: int | None = None, max_bpc: int | None = None,
                 aot_store: Any = None, label: str = "retrieval_ivf",
                 write_through: bool = True):
        import jax

        vectors = np.ascontiguousarray(np.asarray(vectors))
        centroids = np.asarray(centroids, np.float32)
        self.k = int(k)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        self.dim = int(centroids.shape[1])
        self.n_rows = int(vectors.shape[0])
        self.n_clusters = int(centroids.shape[0])
        self.nprobe_max = max(1, min(int(nprobe_max), self.n_clusters))
        self.mesh = mesh
        self.store = aot_store
        self.label = label
        self.write_through = write_through
        self.block_n = _resolve_block_n(self.n_rows, self.dim,
                                        vectors.dtype, self.buckets[-1],
                                        block_n)
        # pad the codebook (and its span tables) to the lane boundary so
        # the coarse matmul is lane-aligned; padded rows are zero vectors
        # masked by the runtime live-centroid count
        cp = _ceil_to(self.n_clusters, _LANES)
        cents = np.zeros((cp, self.dim), np.float32)
        cents[:self.n_clusters] = centroids
        blocks, rids, cl_start, cl_count = cluster_layout(
            vectors, assign, self.n_clusters, block_n=self.block_n,
            row_ids=row_ids, pad_blocks=pad_blocks)
        self.nblocks = int(blocks.shape[0])
        self.max_bpc = max(1, int(max_bpc if max_bpc is not None
                                  else cl_count.max(initial=0)))
        if int(cl_count.max(initial=0)) > self.max_bpc:
            raise ValueError(f"max_bpc={self.max_bpc} < largest cluster "
                             f"span {int(cl_count.max())}")
        start_p = np.zeros(cp, np.int32)
        count_p = np.zeros(cp, np.int32)
        start_p[:self.n_clusters] = cl_start
        count_p[:self.n_clusters] = cl_count
        self._corpus_dtype = str(blocks.dtype)
        if mesh is not None:
            # the program has no collectives; replicate the partition over
            # its submesh so every device answers (the replica axis is the
            # sharding — clusters split across replicas, not within)
            from jax.sharding import NamedSharding, PartitionSpec
            self._sharding = NamedSharding(mesh, PartitionSpec())
            put = lambda x: jax.device_put(x, self._sharding)  # noqa: E731
        else:
            self._sharding = None
            put = jax.device_put
        self._blocks = put(blocks)
        self._row_ids = put(rids)
        self._centroids = put(cents)
        self._cl_start = put(start_p)
        self._cl_count = put(count_p)
        self._live_c = np.int32(self.n_clusters)
        self._traces = {"count": 0}
        fn = make_ivf_fn(self.k, self.nprobe_max, self.max_bpc)

        def counting(*args):
            self._traces["count"] += 1
            return fn(*args)

        self._fn = fn
        self._fresh = jax.jit(counting)
        self._loaded: dict[int, Callable] = {}
        #: bucket -> "aot" | "miss" | "fallback" | "compile"
        self.sources: dict[int, str] = {}

    def trace_count(self) -> int:
        return self._traces["count"]

    def resident_bytes(self) -> int:
        """Device-resident bytes: cluster-major blocks, row ids, padded
        codebook, and span tables — comparable with the exact scan's and
        the tiered searcher's accounting."""
        return sum(int(a.nbytes) for a in
                   (self._blocks, self._row_ids, self._centroids,
                    self._cl_start, self._cl_count))

    # -- AOT keys ---------------------------------------------------------

    def key_for(self, bucket: int):
        from jimm_tpu.aot.keys import serve_forward_key
        return serve_forward_key(
            {"kind": "retrieval_ivf", "nblocks": self.nblocks,
             "block_n": self.block_n, "dim": self.dim, "k": self.k,
             "clusters_padded": int(self._centroids.shape[0]),
             "nprobe_max": self.nprobe_max, "max_bpc": self.max_bpc,
             "corpus_dtype": self._corpus_dtype},
            method="retrieval_ivf", bucket=int(bucket),
            item_shape=(self.dim,), in_dtype=np.float32,
            param_dtype=self._corpus_dtype, mesh=self.mesh)

    def _arg_specs(self, bucket: int):
        import jax
        cp = int(self._centroids.shape[0])
        s = self._sharding
        return (
            jax.ShapeDtypeStruct(
                (self.nblocks, self.block_n, self.dim),
                self._blocks.dtype, sharding=s),
            jax.ShapeDtypeStruct((self.nblocks, self.block_n), np.int32,
                                 sharding=s),
            jax.ShapeDtypeStruct((cp, self.dim), np.float32, sharding=s),
            jax.ShapeDtypeStruct((cp,), np.int32, sharding=s),
            jax.ShapeDtypeStruct((cp,), np.int32, sharding=s),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((), np.int32),
            jax.ShapeDtypeStruct((int(bucket), self.dim), np.float32),
        )

    # -- warm-start -------------------------------------------------------

    def prepare(self, bucket: int) -> str:
        """Store-first warm-start for one query bucket; never raises."""
        bucket = int(bucket)
        if bucket in self.sources:
            return self.sources[bucket]
        if self.store is None:
            self.sources[bucket] = "compile"
            return "compile"
        from jimm_tpu import obs
        from jimm_tpu.aot.warmup import _runtime_versions, aot_metrics
        hit, miss, fallback = aot_metrics()
        key = self.key_for(bucket)
        fp = key.fingerprint()
        existed = self.store.contains(fp)
        source = "miss"
        with obs.span("aot_load"):
            payload = self.store.get(fp,
                                     expect_versions=_runtime_versions())
            if payload is not None:
                try:
                    self._loaded[bucket] = self._bind(payload)
                    source = "aot"
                except Exception as e:  # noqa: BLE001 — degrade, never die
                    self.store.quarantine(fp,
                                          f"deserialize/bind failed: {e}")
                    source = "fallback"
            elif existed:
                source = "fallback"  # store.get already quarantined it
        if source == "aot":
            hit.inc()
        elif source == "fallback":
            fallback.inc()
        else:
            miss.inc()
            if self.write_through:
                self._export_and_put(bucket, key, fp)
        self.sources[bucket] = source
        return source

    def _bind(self, payload: bytes) -> Callable:
        import jax
        from jax import export as jax_export
        exported = jax_export.deserialize(bytearray(payload))
        flat_avals = jax.tree.flatten(exported.in_avals)[0] \
            if hasattr(exported, "in_avals") else []
        if flat_avals and len(flat_avals) != 8:
            raise ValueError(f"artifact expects {len(flat_avals)} input "
                             f"leaves, retrieval_ivf provides 8")
        return jax.jit(exported.call)

    def _export_and_put(self, bucket: int, key, fp: str) -> None:
        """Write-through on a miss so the next process (and every sibling
        replica — same padded shapes, same fingerprint) starts warm.
        Failure to serialize must not break search."""
        try:
            import jax
            from jax import export as jax_export

            from jimm_tpu.aot.keys import AOT_FORMAT_VERSION
            exported = jax_export.export(jax.jit(self._fn))(
                *self._arg_specs(bucket))
            self.store.put(fp, exported.serialize(),
                           meta={"label": self.label, **key.describe(),
                                 "format_version": AOT_FORMAT_VERSION})
        except Exception:  # noqa: BLE001
            pass

    def warmup(self) -> dict[int, str]:
        """Prepare + prime every bucket; returns {bucket: source}."""
        for bucket in self.buckets:
            self.prepare(bucket)
            zeros = np.zeros((bucket, self.dim), np.float32)
            self.search_partial(zeros, self.nprobe_max)
        return dict(self.sources)

    # -- dispatch ---------------------------------------------------------

    def _bucket_for(self, batch: int) -> int:
        for bucket in self.buckets:
            if batch <= bucket:
                return bucket
        raise ValueError(f"query batch {batch} exceeds largest retrieval "
                         f"bucket {self.buckets[-1]}")

    def search_partial(self, queries: np.ndarray, nprobe: int
                       ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Score a ``(B, D)`` f32 query batch against this partition's
        clusters; returns host partials ``(values (B, k), indices (B, k)
        global, cand_rows (B,))``. ``nprobe`` is a runtime scalar — any
        value in ``[1, nprobe_max]`` reuses the same compiled program.
        Batches past the largest bucket run as chunks of it."""
        queries = np.asarray(queries, np.float32)
        nprobe = np.int32(max(1, min(int(nprobe), self.nprobe_max)))
        batch = queries.shape[0]
        top = self.buckets[-1]
        if batch > top:
            outs = [self.search_partial(queries[i:i + top], int(nprobe))
                    for i in range(0, batch, top)]
            return (np.concatenate([o[0] for o in outs], axis=0),
                    np.concatenate([o[1] for o in outs], axis=0),
                    np.concatenate([o[2] for o in outs], axis=0))
        bucket = self._bucket_for(batch)
        if batch < bucket:
            padded = np.zeros((bucket, self.dim), np.float32)
            padded[:batch] = queries
            queries = padded
        args = (self._blocks, self._row_ids, self._centroids,
                self._cl_start, self._cl_count, self._live_c, nprobe,
                queries)
        fn = self._loaded.get(bucket)
        if fn is not None:
            try:
                vals, idx, rows = fn(*args)
            except Exception:  # noqa: BLE001 — a bad artifact must not
                # fail the query: quarantine, recompile fresh
                from jimm_tpu.aot.warmup import aot_metrics
                aot_metrics()[2].inc()
                del self._loaded[bucket]
                self.sources[bucket] = "fallback"
                if self.store is not None:
                    self.store.quarantine(
                        self.key_for(bucket).fingerprint(),
                        "loaded executable raised at call time")
                vals, idx, rows = self._fresh(*args)
        else:
            vals, idx, rows = self._fresh(*args)
        return (np.asarray(vals)[:batch],
                np.asarray(idx, np.int64)[:batch],
                np.asarray(rows, np.int64)[:batch])


class IvfIndexSearcher:
    """IVF-search one :class:`LoadedIndex` across the serving topology.

    Clusters partition contiguously across the plan's replicas (a cluster
    lives wholly in one partition, so probing is local); every replica
    holds the full codebook, computes the identical coarse top-``nprobe``,
    rescoring only the spans it owns, and the ``R * k`` partials fold
    through the bounded host-side :func:`merge_partials`. All partitions
    pad to common block counts, so they share one compiled program and one
    AOT fingerprint. ``search`` accepts a per-call ``nprobe`` (a runtime
    scalar — never a recompile) up to the compiled ``nprobe_max``.
    """

    def __init__(self, index: LoadedIndex, centroids: np.ndarray,
                 assign: np.ndarray | None = None, *, k: int = 10,
                 nprobe_max: int = 32, buckets: Sequence[int] = (1,),
                 block_n: int | None = None, plan: Any = None,
                 aot_store: Any = None, label: str | None = None):
        from jimm_tpu.retrieval.ann.kmeans import assign_clusters
        if len(index) == 0:
            raise ValueError(f"index {index.name!r} is empty")
        self.index = index
        self.k = int(k)
        self.buckets = tuple(sorted(int(b) for b in buckets))
        centroids = np.asarray(centroids, np.float32)
        n_clusters = int(centroids.shape[0])
        self.n_clusters = n_clusters
        self.nprobe_max = max(1, min(int(nprobe_max), n_clusters))
        label = label or f"retrieval_ivf:{index.name}"
        if assign is None:
            assign = assign_clusters(index.matrix_f32(), centroids)
        else:
            assign = np.asarray(assign, np.int64).copy()
            stale = np.flatnonzero(assign < 0)
            if stale.size:
                # rows from segments written before the codebook (or never
                # re-clustered): assign them here so search stays exact
                # over the probed set; `index stats` still advises a
                # build-ivf to persist the assignment
                assign[stale] = assign_clusters(
                    index.matrix_f32()[stale], centroids)
        assign = np.asarray(assign, np.int64)
        corpus = index.vectors
        resolved_bn = _resolve_block_n(
            len(index), index.dim, corpus.dtype, self.buckets[-1], block_n)
        counts = np.bincount(assign, minlength=n_clusters)
        bpc = int(((counts + resolved_bn - 1) // resolved_bn)
                  .max(initial=0)) or 1
        if plan is not None and not plan.is_trivial:
            replicas = plan.replicas
            meshes = plan.meshes()
            cc = math.ceil(n_clusters / replicas)
            parts = [np.flatnonzero((assign >= r * cc)
                                    & (assign < (r + 1) * cc))
                     for r in range(replicas)]
            part_blocks = []
            for rows in parts:
                pc = np.bincount(assign[rows], minlength=n_clusters)
                part_blocks.append(
                    int(((pc + resolved_bn - 1) // resolved_bn).sum()))
            pad_blocks = max(max(part_blocks), 1)
            self.searchers = [
                IvfSearcher(corpus[rows], assign[rows], centroids,
                            k=self.k, nprobe_max=self.nprobe_max,
                            buckets=self.buckets, block_n=resolved_bn,
                            mesh=meshes[r], row_ids=rows,
                            pad_blocks=pad_blocks, max_bpc=bpc,
                            aot_store=aot_store, label=label)
                for r, rows in enumerate(parts)]
        else:
            self.searchers = [
                IvfSearcher(corpus, assign, centroids, k=self.k,
                            nprobe_max=self.nprobe_max,
                            buckets=self.buckets, block_n=resolved_bn,
                            max_bpc=bpc, aot_store=aot_store, label=label)]
        #: {bucket: "aot"|"miss"|"compile"|"fallback"|"mixed"} after warmup
        self.warmup_report: dict[int, str] = {}
        #: stats of the most recent search (the ivf obs gauges read these)
        self.last_stats: dict[str, float] = {}
        self._dispatch_lock = threading.Lock()

    @property
    def block_n(self) -> int:
        return self.searchers[0].block_n

    def trace_count(self) -> int:
        return sum(s.trace_count() for s in self.searchers)

    def resident_bytes(self) -> int:
        return sum(s.resident_bytes() for s in self.searchers)

    def prepare(self, bucket: int) -> str:
        sources = {s.prepare(bucket) for s in self.searchers}
        return sources.pop() if len(sources) == 1 else "mixed"

    def warmup(self) -> dict[int, str]:
        """Warm every (replica, bucket); returns the aggregated
        {bucket: source} map the serve ready line reports."""
        for searcher in self.searchers:
            searcher.warmup()
        report: dict[int, str] = {}
        for bucket in self.buckets:
            sources = {s.sources.get(bucket) for s in self.searchers}
            report[bucket] = (sources.pop() if len(sources) == 1
                              else "mixed")
        self.warmup_report = report
        return report

    def search(self, queries: np.ndarray, nprobe: int | None = None
               ) -> tuple[np.ndarray, np.ndarray, list[list[str]]]:
        """Approximate top-k for a ``(B, D)`` (or ``(D,)``) query batch at
        the given probe width (default: the compiled ``nprobe_max``).
        Returns ``(values (B, k'), indices (B, k'), ids)`` with ``k' =
        min(k, N)``; when the probed clusters hold fewer than ``k'`` rows
        a row's id list is shorter (indices carry ``-1`` tails)."""
        nprobe = self.nprobe_max if nprobe is None else int(nprobe)
        if not 1 <= nprobe <= self.nprobe_max:
            raise ValueError(f"nprobe must be in [1, {self.nprobe_max}] "
                             f"(the compiled probe width); got {nprobe}")
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.ndim != 2 or queries.shape[1] != self.index.dim:
            raise ValueError(
                f"queries must be (B, {self.index.dim}); got "
                f"{queries.shape}")
        queries = normalize_rows(queries)
        # one search on the device at a time — same rationale as the exact
        # IndexSearcher: concurrent launches on shared submeshes interleave
        with self._dispatch_lock:
            partials = [s.search_partial(queries, nprobe)
                        for s in self.searchers]
        values = np.stack([p[0] for p in partials], axis=0)
        indices = np.stack([p[1] for p in partials], axis=0)
        cand_rows = np.sum([p[2] for p in partials], axis=0)
        k_eff = min(self.k, len(self.index))
        vals, idx = merge_partials(values, indices, k_eff)
        ids = [[self.index.ids[j] for j in row if j >= 0] for row in idx]
        found = float(np.mean([len(row) for row in ids])) if len(ids) \
            else 0.0
        self.last_stats = {
            "nprobe": float(nprobe),
            "candidate_frac": round(
                float(np.mean(cand_rows)) / max(len(self.index), 1), 6),
            "fill_ratio": round(found / max(k_eff, 1), 6),
        }
        return vals, idx, ids
