"""JL001 fixture: version-gated config key with no guard (line 6)."""

import jax


jax.config.update("jax_num_cpu_devices", 8)  # line 6: JL001
