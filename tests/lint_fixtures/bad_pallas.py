"""JL005 fixtures: Pallas block shapes off the (8, 128) TPU tile and a VMEM
scratch allocation over the budget."""

from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
import jax.numpy as jnp

BLOCK_Q = 100
ROWS = 12

BAD_LANES = pl.BlockSpec((16, BLOCK_Q), lambda i: (i, 0))  # line 11: JL005
BAD_SUBLANES = pl.BlockSpec((ROWS, 256), lambda i: (i, 0))  # line 12: JL005
HUGE_SCRATCH = pltpu.VMEM((4096, 4096), jnp.float32)  # line 13: JL005 budget
GOOD = pl.BlockSpec((8, 128), lambda i: (i, 0))
GOOD_SCRATCH = pltpu.VMEM((8, 128), jnp.float32)
