"""Async micro-batching inference engine.

Single requests arrive on an asyncio loop; a batcher task coalesces them
under a max-latency/max-batch policy, pads each micro-batch up to one of the
pre-declared :mod:`~jimm_tpu.serve.buckets`, and dispatches through a warm
pre-compiled jitted forward. The coalescing policy:

1. take the first queued request, open a ``max_delay_ms`` window;
2. drain whatever else is already queued (no await, no added latency);
3. wait out the remainder of the window for stragglers — unless the queue
   depth is past the admission policy's shed watermark, in which case
   dispatch immediately at the largest already-full bucket (graceful
   degradation: shed latency, not requests);
4. stop early the moment the largest bucket fills.

Device compute runs on per-replica single-thread executors so the event loop
keeps accepting and coalescing while batches are in flight (continuous
batching: batch N+1 forms while batch N computes). With one replica that is
exactly the classic single-device engine; with several (``forward`` given as
a list, normally built by :func:`~jimm_tpu.serve.topology
.build_replica_forwards`) a capacity semaphore lets up to one batch per
replica run concurrently and each coalesced micro-batch is dispatched to the
least-loaded replica (queue-depth balancing, round-robin on ties). Host
syncs (``np.asarray`` on the result) happen only inside those executors —
the ``*_blocking`` functions — never on the loop; the JL006 lint rule
enforces exactly this split for every ``async def`` in this package.
"""

from __future__ import annotations

import asyncio
import contextlib
import functools
import json
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable

import numpy as np

from jimm_tpu.obs.journal import get_journal, new_correlation_id
from jimm_tpu.obs.spans import new_trace_id, span
from jimm_tpu.serve.admission import (AdmissionController, AdmissionPolicy,
                                      DeadlineExceededError, EngineClosedError,
                                      RequestError, ServeMetrics, ShedError)
from jimm_tpu.serve.buckets import BucketTable, default_buckets, pad_batch

_STOP = object()


def _prof_trigger(cid: str | None, reason: str) -> None:
    """Deep profiler capture on an incident cid — a no-op unless a global
    capture manager is configured (``--prof-dir`` / ``JIMM_PROF_DIR``),
    and deduped per cid inside the manager so heal + replan + SLO burn on
    one incident yield one capture."""
    from jimm_tpu.obs.prof.capture import maybe_trigger
    maybe_trigger(cid, reason)


def counting_forward(model, method: str = "encode_image"
                     ) -> tuple[Callable, Callable[[], int]]:
    """A jitted ``model.<method>`` plus a trace-count getter.

    Same explicit-module-argument spelling as ``utils/jit.py``'s
    ``jit_forward``; the counter increments inside the traced Python body,
    which runs once per compilation — so the getter IS the compile count the
    zero-recompiles-after-warmup acceptance check reads.
    """
    from flax import nnx

    state = {"traces": 0}

    @nnx.jit
    def _fwd(m, x):
        state["traces"] += 1
        return getattr(m, method)(x)

    return functools.partial(_fwd, model), lambda: state["traces"]


class _Request:
    # tenant/klass are QoS annotations (the scheduler's tenant state and
    # priority-class name); both stay None on the policy-free path
    __slots__ = ("item", "future", "deadline", "t0", "rid", "tenant",
                 "klass")

    def __init__(self, item: np.ndarray, future: asyncio.Future,
                 deadline: float, t0: float, rid: str,
                 tenant=None, klass: str | None = None):
        self.item = item
        self.future = future
        self.deadline = deadline
        self.t0 = t0
        self.rid = rid
        self.tenant = tenant
        self.klass = klass


class _Replica:
    """One compute lane: a forward, its single-thread executor, and its
    load counters. ``inflight`` is the replica's queue depth (batches
    assigned but not finished) — the quantity dispatch balances on.
    ``restarts``/``dead`` belong to the engine's watchdog: a failing lane
    gets one fresh executor, then is fenced off. ``revived`` counts
    operator/self-heal un-fencings (each one re-arms the free restart)."""

    __slots__ = ("index", "forward", "name", "pool", "inflight",
                 "dispatched", "device_s", "restarts", "dead", "revived",
                 "incident_cid")

    def __init__(self, index: int, forward: Callable, name: str):
        self.index = index
        self.forward = forward
        self.name = name
        self.pool = ThreadPoolExecutor(max_workers=1,
                                       thread_name_prefix=name)
        self.inflight = 0
        self.dispatched = 0
        self.device_s = 0.0
        self.restarts = 0
        self.dead = False
        self.revived = 0
        # flight-recorder correlation id of the incident this replica is
        # currently the subject of (minted at the first fault, cleared on
        # revive) — every fence/probe/heal/replan event inherits it
        self.incident_cid: str | None = None


class InferenceEngine:
    """Coalesces single-item requests into bucketed micro-batches.

    Args:
        forward: callable over a ``(B, *item_shape)`` array returning an
            array-like whose row ``i`` answers input row ``i`` (e.g. the
            pair from :func:`counting_forward`) — or a *list* of such
            callables, one per serving replica (see
            :func:`~jimm_tpu.serve.topology.build_replica_forwards`).
            Replicas compute concurrently on their own executor threads;
            every coalesced micro-batch goes to the least-loaded one. A
            bare callable is exactly the single-replica engine.
        item_shape: per-request input shape (no batch axis); submissions
            with any other shape are rejected with a typed
            :class:`~jimm_tpu.serve.admission.RequestError`.
        dtype: dtype batches are assembled in (requests are cast).
        buckets: allowed batch sizes (default: the platform table).
        max_delay_ms: coalescing window — the latency each request may
            spend waiting for batch-mates.
        policy: admission policy (queue bound, default deadline, shed
            watermark).
        metrics: shared :class:`ServeMetrics` (one per server).
        trace_count: optional compile-count getter, exported as the
            ``compile_count`` gauge.
        qos: optional :class:`~jimm_tpu.serve.qos.QosScheduler`. When
            given, submissions carry tenant identity through token-bucket
            admission, the FIFO queue becomes the per-class weighted-fair
            queue, and overload sheds class-ordered. When None (the
            default) every path below is byte-identical to the policy-free
            engine.
    """

    def __init__(self, forward, *, item_shape: tuple[int, ...],
                 dtype=np.float32, buckets: BucketTable | None = None,
                 max_delay_ms: float = 5.0,
                 policy: AdmissionPolicy | None = None,
                 metrics: ServeMetrics | None = None,
                 trace_count: Callable[[], int] | None = None,
                 qos=None, recent_traces_entries: int = 64,
                 recent_traces_max_bytes: int = 64 << 10):
        # A list of forwards means explicit replicas (topology-planned
        # serving); a bare callable is the classic single-replica engine.
        # The per-replica jimm_serve_replica_* series exist only in the
        # explicit case so single-device metric output stays unchanged.
        self._multi = isinstance(forward, (list, tuple))
        forwards = list(forward) if self._multi else [forward]
        if not forwards:
            raise ValueError("forward list must name at least one replica")
        self._replicas = [
            _Replica(i, f, name=(f"jimm-serve-fwd-r{i}" if self._multi
                                 else "jimm-serve-fwd"))
            for i, f in enumerate(forwards)]
        self.forward = forwards[0]
        self.item_shape = tuple(item_shape)
        self.dtype = np.dtype(dtype)
        self.buckets = buckets if buckets is not None else default_buckets()
        self.max_delay_s = max_delay_ms / 1e3
        self.metrics = metrics or ServeMetrics()
        self.admission = AdmissionController(policy, self.metrics)
        self.qos = qos
        if qos is not None:
            qos.bind_metrics(self.metrics)
        self.trace_count = trace_count
        if trace_count is not None:
            self.metrics.bind_gauge("compile_count", trace_count)
        self.metrics.bind_gauge("queue_depth_now",
                                lambda: float(self._queue.qsize())
                                if self._queue is not None else 0.0)
        if self._multi:
            # "n_replicas", not "replica_count": the obs exporter renders
            # *_count names as histogram counters
            self.metrics.bind_gauge("n_replicas",
                                    lambda: float(len(self._replicas)))
            self.metrics.bind_gauge(
                "replicas_alive",
                lambda: float(sum(1 for r in self._replicas if not r.dead)))
            # pre-created at zero so "never replanned" is visible in scrapes
            self.metrics.inc("replans_total", 0)
            for replica in self._replicas:
                self._bind_replica_metrics(replica)
        # asyncio.Queue, or a qos.WeightedFairQueue (same surface) when a
        # policy is configured
        self._queue = None
        self._task: asyncio.Task | None = None
        self._capacity: asyncio.Semaphore | None = None
        self._dispatch_tasks: set[asyncio.Task] = set()
        self._rr = 0
        self._running = False
        # submission gate, separate from _running: a replan pauses the
        # batcher (so _task/_capacity can be swapped safely) while submit()
        # keeps enqueueing — queued requests ride through the swap
        self._accepting = False
        # self-heal hook (set_heal): a blocking factory rebuilding the full
        # replica set from the AOT store, invoked by the watchdog when a
        # fence would otherwise be permanent
        self._heal: Callable | None = None
        self._heal_task: asyncio.Task | None = None
        self._replan_lock = asyncio.Lock()
        #: repr of the last failed self-heal attempt (healthz debugging)
        self.last_heal_error: str | None = None
        # SLO burn-rate engine (attach_slo): fed one observation per
        # finished request; fast-burn escalates into the self-heal path
        self.slo = None
        self._slo_burning: set = set()
        # Per-request phase decomposition (trace id -> phase seconds),
        # newest last; read by /healthz debugging and tests. Bounded by
        # entries AND bytes: a long incident producing fat rows (big
        # tenant ids, cascade metadata) must not grow host memory — the
        # byte cap evicts oldest and counts each drop.
        self.recent_traces: deque[dict] = deque()
        self._trace_sizes: deque[int] = deque()
        self._traces_bytes = 0
        self.recent_traces_entries = int(recent_traces_entries)
        self.recent_traces_max_bytes = int(recent_traces_max_bytes)
        # pre-created at zero so "never dropped" is visible in scrapes
        self.metrics.inc("traces_dropped_total", 0)
        self.metrics.bind_gauge("recent_traces_bytes",
                                lambda: float(self._traces_bytes))
        # bucket -> {"seconds", "source"} filled by warmup_blocking;
        # source is "compile" (plain forward) or the AOT outcome
        # ("aot"/"miss"/"fallback") when the forward is store-backed.
        # Multi-replica engines add a per-replica breakdown under
        # "replicas" and report "mixed" when the sources disagree.
        self.warmup_report: dict = {}

    # -- replicas ---------------------------------------------------------

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def _bind_replica_metrics(self, replica: _Replica) -> None:
        """Register this replica's jimm_serve_replica_* series: queue depth
        (inflight batches), dispatch count, and accumulated device seconds.
        The counter is pre-created at zero so a replica that never wins a
        dispatch still shows up in scrapes."""
        i = replica.index
        self.metrics.inc(f"replica_{i}_dispatched_total", 0)
        self.metrics.bind_gauge(f"replica_{i}_inflight",
                                lambda r=replica: float(r.inflight))
        self.metrics.bind_gauge(f"replica_{i}_device_seconds",
                                lambda r=replica: round(r.device_s, 6))

    def _record_trace(self, row: dict) -> None:
        """Append to the debug trace ring under both bounds (entry count
        and serialized bytes), counting evictions in
        ``jimm_serve_traces_dropped_total``. Loop-confined (called from
        dispatch coroutines only), so the bookkeeping needs no lock."""
        try:
            size = len(json.dumps(row, default=str))
        except (TypeError, ValueError):
            size = 256  # unserializable row: charge a nominal size
        self.recent_traces.append(row)
        self._trace_sizes.append(size)
        self._traces_bytes += size
        while len(self.recent_traces) > 1 and (
                len(self.recent_traces) > self.recent_traces_entries
                or self._traces_bytes > self.recent_traces_max_bytes):
            self.recent_traces.popleft()
            self._traces_bytes -= self._trace_sizes.popleft()
            self.metrics.inc("traces_dropped_total")

    def replica_stats(self) -> list[dict]:
        """Per-replica load snapshot (healthz payload and the sharded serve
        smoke's balance check)."""
        return [{"replica": r.index, "dispatched": r.dispatched,
                 "inflight": r.inflight,
                 "device_seconds": round(r.device_s, 6),
                 "restarts": r.restarts, "dead": r.dead,
                 "revived": r.revived}
                for r in self._replicas]

    def dead_replicas(self) -> list[int]:
        """Indices of replicas the watchdog fenced off (healthz surfaces
        these as a ``degraded`` status)."""
        return [r.index for r in self._replicas if r.dead]

    def _note_replica_failure(self, replica: _Replica) -> None:
        """Watchdog: a replica whose forward raised gets ONE fresh executor
        (its worker thread may be wedged on a dead device handle); a
        replica that fails again after its restart is fenced off — unless
        it is the last live lane, which keeps serving (and erroring
        loudly) rather than leaving the engine with nothing to pick."""
        if replica.incident_cid is None:
            replica.incident_cid = new_correlation_id()
        if replica.restarts == 0:
            replica.pool.shutdown(wait=False)
            replica.pool = ThreadPoolExecutor(max_workers=1,
                                              thread_name_prefix=replica.name)
            replica.restarts += 1
            if self._multi:
                self.metrics.inc(f"replica_{replica.index}_restarts_total")
            get_journal().emit("replica_fault", cid=replica.incident_cid,
                               replica=replica.index, action="restart")
            return
        live = [r for r in self._replicas if not r.dead]
        if len(live) > 1:
            replica.dead = True
            if self._multi:
                self.metrics.inc(f"replica_{replica.index}_dead_total")
            get_journal().emit("replica_fenced", cid=replica.incident_cid,
                               replica=replica.index,
                               live=len(live) - 1)
            # fence -> attempt-revive -> replan-around: with a heal hook
            # installed the fence is an escalation step, not a terminus
            self._maybe_heal(replica)
        else:
            get_journal().emit("replica_fault", cid=replica.incident_cid,
                               replica=replica.index, action="last_lane",
                               live=len(live))

    def revive(self, index: int) -> dict:
        """Operator hook: un-fence a watchdog-dead replica — fresh executor,
        restart budget re-armed — without touching its siblings. Raises
        ValueError for an unknown index or a replica that is not fenced
        (the server maps that to a 400, so a typo'd revive is loud).
        Returns the replica's new stats row."""
        if not isinstance(index, int) or not 0 <= index < len(self._replicas):
            raise ValueError(f"no replica {index!r} "
                             f"(engine has {len(self._replicas)})")
        replica = self._replicas[index]
        if not replica.dead:
            raise ValueError(f"replica {index} is not fenced; "
                             "nothing to revive")
        replica.pool.shutdown(wait=False)
        replica.pool = ThreadPoolExecutor(max_workers=1,
                                          thread_name_prefix=replica.name)
        replica.restarts = 0
        replica.dead = False
        replica.revived += 1
        if self._multi:
            self.metrics.inc(f"replica_{index}_revived_total")
            self.metrics.inc("revives_total")
        get_journal().emit("replica_revived", cid=replica.incident_cid,
                           replica=index, revived=replica.revived)
        replica.incident_cid = None  # incident closed
        return self.replica_stats()[index]

    # -- self-heal / live replan ------------------------------------------

    def attach_slo(self, slo) -> None:
        """Install an :class:`~jimm_tpu.obs.slo.SloEngine`: every finished
        request (success, forward error, deadline timeout) becomes one
        per-tenant availability/latency observation, and a tenant entering
        fast burn escalates into the self-heal path (see
        :meth:`_slo_check_escalate`) and triggers a deep profiler capture
        on the incident's correlation id (via the burn-transition listener
        hook) — the capture of *why the burn happened* starts while the
        anomaly is still live, not after a human reads the page."""
        self.slo = slo
        slo.add_listener(self._on_burn_transition_capture)

    def _on_burn_transition_capture(self, tenant, entered: bool,
                                    fast: float, slow: float) -> None:
        if not entered:
            return
        dead = [r for r in self._replicas if r.dead]
        _prof_trigger(dead[0].incident_cid if dead else None,
                      "slo_fast_burn")

    def _observe_slo(self, req, ok: bool, latency_s: float | None) -> None:
        if self.slo is None:
            return
        tenant = req.tenant.spec.name if req.tenant is not None else None
        self.slo.observe(tenant, ok, latency_s)

    def _slo_check_escalate(self) -> None:
        """Called after bad observations: when a tenant *enters* fast burn
        (multi-window guard inside the SLO engine), journal the escalation
        and kick the self-heal watchdog at the first fenced replica — the
        burn is the symptom, a dead lane is the usual cause."""
        if self.slo is None:
            return
        burning = set(self.slo.fast_burning())
        newly = burning - self._slo_burning
        self._slo_burning = burning
        if not newly:
            return
        dead = [r for r in self._replicas if r.dead]
        cid = dead[0].incident_cid if dead else None
        get_journal().emit("slo_fast_burn", cid=cid,
                           tenants=sorted(newly),
                           dead_replicas=[r.index for r in dead])
        self.metrics.inc("slo_fast_burn_total")
        if dead:
            self._maybe_heal(dead[0])

    def set_heal(self, factory: Callable) -> None:
        """Install the self-heal hook: a *blocking* zero-arg factory that
        rebuilds the full replica forward set (normally a closure over
        :func:`~jimm_tpu.serve.topology.build_replica_forwards` and the AOT
        store, so the rebuild deserializes executables instead of
        re-tracing). Invoked off-loop by the watchdog after a fence: probe
        the fenced lane first (transient fault -> revive in place), else
        rebuild and :meth:`replan` around it."""
        self._heal = factory
        self.metrics.inc("heal_failures_total", 0)

    def _maybe_heal(self, replica: _Replica) -> None:
        if self._heal is None:
            return
        if self._heal_task is not None and not self._heal_task.done():
            return
        try:
            loop = asyncio.get_running_loop()
        except RuntimeError:
            return  # fenced outside a loop (sync tests): nothing to schedule
        self._heal_task = loop.create_task(self._heal_around(replica),
                                           name="jimm-serve-heal")

    async def _heal_around(self, replica: _Replica) -> None:
        loop = asyncio.get_running_loop()
        cid = replica.incident_cid
        t_heal = time.perf_counter()
        ok = await loop.run_in_executor(None, self._probe_blocking, replica)
        get_journal().emit("heal_probe", cid=cid, replica=replica.index,
                           ok=ok)
        # deep profiler capture on the incident cid: the heal window is
        # exactly when the degraded topology's behavior is capturable
        _prof_trigger(cid, "heal")
        if ok:
            # the fault was transient (wedged thread, recovered device):
            # the lane still computes, so un-fence it in place
            self.revive(replica.index)
            self.metrics.inc("goodput_heal_seconds_total",
                             time.perf_counter() - t_heal)
            return
        try:
            built = await loop.run_in_executor(None, self._heal)
        except Exception as e:  # noqa: BLE001 — a failed heal must never kill the loop; it is counted and surfaced, and the engine keeps serving degraded
            self.metrics.inc("heal_failures_total")
            self.last_heal_error = f"{type(e).__name__}: {e}"
            self.metrics.inc("goodput_heal_seconds_total",
                             time.perf_counter() - t_heal)
            get_journal().emit("heal_failed", cid=cid,
                               replica=replica.index,
                               error=self.last_heal_error)
            return
        forwards, trace_count = self._normalize_built(built)
        heal_s = time.perf_counter() - t_heal
        # heal bucket = probe + rebuild; the replan books its own bucket
        self.metrics.inc("goodput_heal_seconds_total", heal_s)
        get_journal().emit("heal_rebuilt", cid=cid, replica=replica.index,
                           replicas=len(forwards), dur_s=round(heal_s, 6))
        await self.replan(forwards, trace_count=trace_count, cid=cid)

    @staticmethod
    def _normalize_built(built):
        """Accept either ``(forwards, trace_count)`` — the
        build_replica_forwards return shape — or a bare forward list."""
        if (isinstance(built, tuple) and len(built) == 2
                and isinstance(built[0], (list, tuple))
                and (built[1] is None or callable(built[1]))):
            return list(built[0]), built[1]
        return built, None

    def _probe_blocking(self, replica: _Replica) -> bool:
        """One min-bucket forward on a fenced replica, off its (possibly
        wedged) executor. True means the lane still computes."""
        size = min(self.buckets.sizes)
        zeros = np.zeros((size,) + self.item_shape, self.dtype)
        try:
            self._forward_blocking(zeros, replica)
        except Exception:  # noqa: BLE001 — any failure IS the probe's answer; the caller escalates to a full rebuild
            return False
        return True

    async def replan(self, forward, *, trace_count: Callable[[], int]
                     | None = None, warm: bool = True,
                     cid: str | None = None) -> dict:
        """Swap the live replica set for a new one — grow, shrink, or heal —
        without dropping queued work.

        Sequence: (1) warm every bucket of every new forward *off-loop*
        while the old replicas keep serving (store-backed forwards go
        through ``prepare_bucket`` first, so a warm AOT store means zero
        fresh traces here); (2) pause the batcher via the ``_STOP``
        sentinel and drain in-flight dispatches (their futures resolve
        normally); (3) swap replicas/semaphore/gauges; (4) restart the
        batcher. ``submit()`` keeps accepting throughout — queued requests
        ride through the swap and dispatch onto the new topology.

        ``cid`` threads the triggering incident's flight-recorder
        correlation id (the self-heal path passes the fenced replica's);
        operator-initiated replans journal under a fresh id."""
        new_multi = isinstance(forward, (list, tuple))
        forwards = list(forward) if new_multi else [forward]
        if not forwards:
            raise ValueError("replan needs at least one replica forward")
        cid = cid or new_correlation_id()
        t_replan = time.perf_counter()
        get_journal().emit("replan_started", cid=cid,
                           replicas_to=len(forwards),
                           replicas_from=len(self._replicas))
        _prof_trigger(cid, "replan")
        async with self._replan_lock:
            loop = asyncio.get_running_loop()
            if warm:
                await loop.run_in_executor(
                    None, self._warm_forwards_blocking, forwards)
            was_running = self._running and self._task is not None
            if was_running:
                assert self._queue is not None
                self._queue.put_nowait(_STOP)
                await self._task
                self._task = None
                if self._dispatch_tasks:
                    await asyncio.gather(*tuple(self._dispatch_tasks),
                                         return_exceptions=True)
            old = self._replicas
            for replica in old:
                replica.pool.shutdown(wait=True)
            self._multi = new_multi
            self._replicas = [
                _Replica(i, f, name=(f"jimm-serve-fwd-r{i}" if new_multi
                                     else "jimm-serve-fwd"))
                for i, f in enumerate(forwards)]
            self.forward = forwards[0]
            self._rr = 0
            if trace_count is not None:
                self.trace_count = trace_count
                self.metrics.bind_gauge("compile_count", trace_count)
            if new_multi:
                self.metrics.bind_gauge(
                    "n_replicas", lambda: float(len(self._replicas)))
                self.metrics.bind_gauge(
                    "replicas_alive",
                    lambda: float(sum(1 for r in self._replicas
                                      if not r.dead)))
                for replica in self._replicas:
                    self._bind_replica_metrics(replica)
            # a shrink leaves higher-index gauges bound to dead objects:
            # freeze them at zero so scrapes don't report ghost load
            for i in range(len(forwards), len(old)):
                self.metrics.bind_gauge(f"replica_{i}_inflight", lambda: 0.0)
                self.metrics.bind_gauge(f"replica_{i}_device_seconds",
                                        lambda: 0.0)
            if was_running:
                self._capacity = asyncio.Semaphore(len(self._replicas))
                self._dispatch_tasks = set()
                self._task = loop.create_task(self._batcher(),
                                              name="jimm-serve-batcher")
            self.metrics.inc("replans_total")
            replan_s = time.perf_counter() - t_replan
            self.metrics.inc("goodput_replan_seconds_total", replan_s)
            get_journal().emit("replan_done", cid=cid,
                               replicas=len(self._replicas),
                               was_running=was_running,
                               dur_s=round(replan_s, 6))
            return {"replicas": len(self._replicas),
                    "was_running": was_running,
                    "replans": self.metrics.count("replans_total")}

    def _warm_forwards_blocking(self, forwards) -> None:
        """Every bucket of every new forward prepared and primed (blocking;
        run off-loop). The priming call matters: an AotForward falls back
        to a fresh trace for any bucket it was never primed on, which
        would break replan's zero-fresh-traces contract."""
        for size in self.buckets.sizes:
            zeros = np.zeros((size,) + self.item_shape, self.dtype)
            for fwd in forwards:
                prepare = getattr(fwd, "prepare_bucket", None)
                if prepare is not None:
                    prepare(size)
                out = fwd(zeros)
                if hasattr(out, "block_until_ready"):
                    out.block_until_ready()

    def _pick_replica(self) -> _Replica:
        """Least-loaded live replica by inflight batch count; ties break
        round-robin from the cursor so equal-depth replicas alternate.
        Dead (watchdog-fenced) replicas are skipped; at least one replica
        is always live by construction (see _note_replica_failure)."""
        n = len(self._replicas)
        best = None
        for off in range(n):
            r = self._replicas[(self._rr + off) % n]
            if r.dead:
                continue
            if best is None or r.inflight < best.inflight:
                best = r
        self._rr = (best.index + 1) % n
        return best

    # -- lifecycle --------------------------------------------------------

    def warmup_blocking(self) -> dict:
        """Compile every bucket before traffic (call off the event loop).
        Returns {bucket: seconds}; after this, steady-state traffic hits
        only warm executables.

        Store-first forwards (jimm_tpu.aot.AotForward) are consulted via
        their ``prepare_bucket(size)`` hook before the priming call: on an
        AOT hit the forward installs a deserialized executable, so the
        priming run below is a device warm-up, not a fresh trace+compile.
        The per-bucket outcome lands in ``self.warmup_report``."""
        times = {}
        self.warmup_report = {}
        for size in self.buckets.sizes:
            zeros = np.zeros((size,) + self.item_shape, self.dtype)
            per_replica = []
            for replica in self._replicas:
                prepare = getattr(replica.forward, "prepare_bucket", None)
                source = prepare(size) if prepare is not None else "compile"
                t0 = time.monotonic()
                with span("serve_warmup_aot" if source == "aot"
                          else "serve_warmup_compile"):
                    self._forward_blocking(zeros, replica)
                per_replica.append(
                    {"seconds": round(time.monotonic() - t0, 4),
                     "source": source})
            times[size] = round(sum(e["seconds"] for e in per_replica), 4)
            sources = {e["source"] for e in per_replica}
            report = {"seconds": times[size],
                      "source": (per_replica[0]["source"]
                                 if len(sources) == 1 else "mixed")}
            if self._multi:
                report["replicas"] = per_replica
            self.warmup_report[size] = report
        return times

    async def start(self) -> None:
        if self._running:
            return
        if self.qos is not None:
            # per-class deques + deficit-round-robin drain; same
            # put/get/qsize surface, so the batcher below is untouched
            from jimm_tpu.serve.qos.scheduler import WeightedFairQueue
            self._queue = WeightedFairQueue(self.qos)
        else:
            self._queue = asyncio.Queue()
        # one permit per replica: the batcher only forms the next batch
        # when some replica can take it, so admission backpressure still
        # sees every queued request (nothing hides in formed-but-unrunnable
        # batches) and a single-replica engine serializes exactly as before
        self._capacity = asyncio.Semaphore(len(self._replicas))
        self._dispatch_tasks = set()
        self._running = True
        self._accepting = True
        self._task = asyncio.get_running_loop().create_task(
            self._batcher(), name="jimm-serve-batcher")

    async def stop(self) -> None:
        if not self._running:
            return
        self._running = False
        self._accepting = False
        if self._heal_task is not None:
            self._heal_task.cancel()
            with contextlib.suppress(asyncio.CancelledError):
                await self._heal_task
            self._heal_task = None
        assert self._queue is not None
        self._queue.put_nowait(_STOP)
        if self._task is not None:
            await self._task
            self._task = None
        if self._dispatch_tasks:
            await asyncio.gather(*tuple(self._dispatch_tasks),
                                 return_exceptions=True)
        for replica in self._replicas:
            replica.pool.shutdown(wait=True)

    # -- submission -------------------------------------------------------

    async def submit(self, item: np.ndarray,
                     timeout_s: float | None = None,
                     trace_id: str | None = None,
                     tenant: str | None = None,
                     escalated: bool = False) -> np.ndarray:
        """One request in, one output row out. Raises
        :class:`QueueFullError` (backpressure), :class:`RequestError`
        (shape mismatch), or :class:`DeadlineExceededError` (deadline hit
        while queued or in flight). ``trace_id`` (admission-assigned, or
        generated here) follows the request into bucket dispatch and keys
        its phase decomposition in ``recent_traces``.

        With a QoS scheduler configured, ``tenant`` selects the policy
        applied: token-bucket/quota admission may raise
        :class:`~jimm_tpu.serve.admission.ThrottledError` (429), the
        tenant's deadline is inherited when ``timeout_s`` is None, and
        under overload a lower-class queued request is shed
        (:class:`~jimm_tpu.serve.admission.ShedError`, 503) to admit a
        higher-class arrival. Without a scheduler ``tenant`` is ignored
        and this path is byte-identical to the original engine.

        ``escalated=True`` marks a cascade re-submit: the client already
        paid the request counter and the tenant's token bucket at the
        cheap stage, so the escalation must not double-bill either — it
        still honors the queue bound (capacity is physical) but skips the
        rate-limit charge and counts under ``escalated_submits_total``.
        """
        if not self._accepting or self._queue is None:
            raise EngineClosedError("engine is not running; call start()")
        item = self._coerce(item)
        self.metrics.inc("escalated_submits_total" if escalated
                         else "requests_total")
        tenant_state = klass = None
        if self.qos is not None:
            tenant_state = self.qos.resolve(tenant)
            klass = tenant_state.spec.klass
            if not escalated:
                self.qos.admit(tenant_state)
            timeout_s = self.qos.timeout_for(tenant_state, timeout_s)
            if self._queue.qsize() >= self.admission.policy.max_queue:
                self._shed_for(klass)
        self.admission.admit(self._queue.qsize())
        now = time.monotonic()
        deadline = self.admission.deadline_for(timeout_s, now)
        future = asyncio.get_running_loop().create_future()
        self._queue.put_nowait(_Request(item, future, deadline, now,
                                        trace_id or new_trace_id(),
                                        tenant_state, klass))
        if tenant_state is not None:
            self.qos.on_enqueue(tenant_state)
        self.metrics.set_queue_depth(self._queue.qsize())
        try:
            return await asyncio.wait_for(future, timeout=deadline - now)
        except asyncio.TimeoutError:
            self.metrics.inc("timeouts_total")
            if self.slo is not None:
                tname = tenant_state.spec.name \
                    if tenant_state is not None else None
                self.slo.observe(tname, False, deadline - now)
                self._slo_check_escalate()
            raise DeadlineExceededError(
                f"request deadline ({deadline - now:.3f}s) exceeded") \
                from None

    def _shed_for(self, klass: str) -> None:
        """Class-ordered overload shedding: evict the newest queued
        request of the lowest class strictly below ``klass`` so the
        arriving higher-class request can be admitted. When every lower
        class is empty nothing is evicted — the arrival then takes the
        normal queue-full rejection, so a class never preempts its peers
        or its betters."""
        victim = self._queue.shed_lower(self.qos.rank_of(klass))
        if victim is not None and not victim.future.done():
            victim.future.set_exception(ShedError(
                f"shed under overload to admit class {klass!r} traffic; "
                "retry with backoff",
                retry_after_s=round(self.max_delay_s * 4, 4)))

    def _coerce(self, item) -> np.ndarray:
        """Validate and cast one request payload (host-side, cheap)."""
        arr = np.asarray(item, self.dtype)
        if arr.shape != self.item_shape:
            self.metrics.inc("errors_total")
            raise RequestError(f"item shape {arr.shape} != engine shape "
                               f"{self.item_shape}")
        return arr

    # -- batching loop ----------------------------------------------------

    async def _batcher(self) -> None:
        assert self._queue is not None and self._capacity is not None
        queue = self._queue
        loop = asyncio.get_running_loop()
        while True:
            # wait for compute capacity BEFORE taking work: requests keep
            # accumulating in the bounded admission queue while every
            # replica is busy, so queue-full rejection fires at the same
            # depth it did in the single-executor engine
            await self._capacity.acquire()
            first = await queue.get()
            if first is _STOP:
                self._capacity.release()
                break
            batch = [first]
            window_end = time.monotonic() + self.max_delay_s
            max_size = self.buckets.max_size
            stop = False
            shed = False
            while len(batch) < max_size:
                # drain what is already here — free batch-mates
                try:
                    nxt = queue.get_nowait()
                except asyncio.QueueEmpty:
                    nxt = None
                if nxt is _STOP:
                    stop = True
                    break
                if nxt is not None:
                    batch.append(nxt)
                    continue
                if self.admission.under_pressure(len(batch) + queue.qsize()):
                    # graceful degradation: dispatch the largest already-
                    # full smaller bucket instead of waiting out the window
                    shed = True
                    break
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    nxt = await asyncio.wait_for(queue.get(),
                                                 timeout=remaining)
                except asyncio.TimeoutError:
                    break
                if nxt is _STOP:
                    stop = True
                    break
                batch.append(nxt)
            self.metrics.set_queue_depth(queue.qsize())
            replica = self._pick_replica()
            replica.inflight += 1
            task = loop.create_task(
                self._dispatch_tracked(replica, batch, shed),
                name=f"jimm-serve-dispatch-r{replica.index}")
            self._dispatch_tasks.add(task)
            task.add_done_callback(self._dispatch_tasks.discard)
            if stop:
                break

    async def _dispatch_tracked(self, replica: _Replica,
                                batch: list[_Request], shed: bool) -> None:
        """Run one batch on one replica, then return its capacity permit.
        Runs as a task so replicas compute concurrently while the batcher
        keeps coalescing."""
        try:
            await self._dispatch(batch, replica=replica, shed=shed)
        finally:
            replica.inflight -= 1
            if self._capacity is not None:
                self._capacity.release()

    async def _dispatch(self, batch: list[_Request], *,
                        replica: _Replica | None = None,
                        shed: bool = False) -> None:
        now = time.monotonic()
        live = []
        for req in batch:
            if req.future.cancelled():
                # submit()'s wait_for already gave the client its timeout
                self.metrics.inc("cancelled_total")
            elif req.deadline <= now:
                self.metrics.inc("cancelled_total")
                if not req.future.done():
                    req.future.set_exception(DeadlineExceededError(
                        "deadline expired before dispatch"))
            else:
                live.append(req)
        if not live:
            return
        n = len(live)
        # queue phase ends here: time from submit to the start of dispatch
        for req in live:
            self.metrics.observe_phase("queue", now - req.t0)
        bucket = self.buckets.select(n) or self.buckets.max_size
        t_pad = time.perf_counter()
        with span("serve_pad"):
            padded = pad_batch([req.item for req in live], bucket)
        pad_s = time.perf_counter() - t_pad
        self.metrics.observe_phase("pad", pad_s)
        replica = replica if replica is not None else self._replicas[0]
        loop = asyncio.get_running_loop()
        try:
            out, device_s, readback_s = await loop.run_in_executor(
                replica.pool, self._forward_blocking_timed, padded, replica)
        except Exception as e:  # noqa: BLE001 — surface to every waiter
            self.metrics.inc("errors_total")
            self._note_replica_failure(replica)
            t_err = time.monotonic()
            for req in live:
                if not req.future.done():
                    req.future.set_exception(e)
                self._observe_slo(req, False, t_err - req.t0)
            self._slo_check_escalate()
            return
        replica.dispatched += 1
        replica.device_s += device_s
        if self._multi:
            self.metrics.inc(f"replica_{replica.index}_dispatched_total")
        self.metrics.observe_phase("device", device_s)
        self.metrics.observe_phase("readback", readback_s)
        self.metrics.observe_batch(n, bucket, shed=shed)
        done = time.monotonic()
        for i, req in enumerate(live):
            if not req.future.done():
                req.future.set_result(out[i])
                self.metrics.inc("responses_total")
                self.metrics.observe_latency(done - req.t0)
                self._observe_slo(req, True, done - req.t0)
                self._record_trace({
                    "trace_id": req.rid,
                    "replica": replica.index,
                    "bucket": bucket,
                    "queue_s": round(now - req.t0, 6),
                    "pad_s": round(pad_s, 6),
                    "device_s": round(device_s, 6),
                    "readback_s": round(readback_s, 6),
                    "total_s": round(done - req.t0, 6),
                    # same clock as journal "mono": lets the timeline
                    # exporter place this request among incident events
                    "done_mono": round(done, 6),
                })

    # -- device side (executor thread, never the event loop) --------------

    def _forward_blocking(self, padded: np.ndarray,
                          replica: _Replica | None = None) -> np.ndarray:
        """Runs the warm forward and materializes the result on host. The
        only place in the engine that blocks on the device."""
        return self._forward_blocking_timed(padded, replica)[0]

    def _forward_blocking_timed(
            self, padded: np.ndarray, replica: _Replica | None = None
    ) -> tuple[np.ndarray, float, float]:
        """`_forward_blocking` plus the device/readback split: seconds the
        device spent computing (dispatch + ``block_until_ready``) vs.
        copying the result back to host memory (``np.asarray``). Multi-
        replica engines nest a replica-tagged span inside the aggregate
        ``serve_device`` one so per-replica device time shows up as its own
        lane in the span dump and any profiler capture."""
        replica = replica if replica is not None else self._replicas[0]
        tagged = (span(f"serve_device_r{replica.index}") if self._multi
                  else contextlib.nullcontext())
        t0 = time.perf_counter()
        with span("serve_device"), tagged:
            out = replica.forward(padded)
            if hasattr(out, "block_until_ready"):
                out.block_until_ready()
        t1 = time.perf_counter()
        with span("serve_readback"):
            host = np.asarray(out)
        return host, t1 - t0, time.perf_counter() - t1
