"""The restartable-attempt supervisor.

Runs training as a sequence of attempts: a preemption
(:class:`~jimm_tpu.resilience.preemption.PreemptedError`), a crash, or a
nonzero exit restarts the attempt with ``--resume`` after a bounded
jittered backoff, up to ``max_restarts`` times; then it gives up with a
clear :class:`GiveUpError`. ``launch.py`` applies the same policy at
process-group granularity; ``jimm-tpu supervise`` applies this one
in-process around ``cmd_train``.

Every restart increments ``jimm_train_restarts_total`` and adds the lost
wall time (work since the last committed checkpoint, or the grace-window
loss a :class:`PreemptedError` reports) to the goodput ``lost_work``
bucket — resilience shows up in the same breakdown as compile and
data-wait time.
"""

from __future__ import annotations

import time
from typing import Callable

from jimm_tpu.obs.journal import correlate, get_journal, new_correlation_id
from jimm_tpu.resilience.backoff import BackoffPolicy
from jimm_tpu.resilience.preemption import PreemptedError

__all__ = ["GiveUpError", "Supervisor", "note_checkpoint_completed"]

#: monotonic time of the last committed checkpoint in this process —
#: train/checkpoint.py calls note_checkpoint_completed() when a step's
#: completion marker lands, so the supervisor can bound how much work a
#: crash actually lost.
_last_checkpoint_t: float | None = None


def note_checkpoint_completed() -> None:
    global _last_checkpoint_t
    _last_checkpoint_t = time.monotonic()


class GiveUpError(RuntimeError):
    """The supervisor exhausted its restart budget."""


class Supervisor:
    """Run ``attempt_fn(attempt, resume)`` until it returns 0 or the
    restart budget runs out.

    ``attempt_fn`` is called with the 0-based attempt index and a resume
    flag (False on the first attempt, True on every restart) and returns a
    process-style exit code; raising is treated like a crash. ``sleep`` is
    injectable so tests and drills replay instantly.
    """

    def __init__(self, *, max_restarts: int = 3,
                 backoff: BackoffPolicy | None = None,
                 sleep: Callable[[float], None] = time.sleep,
                 registry=None):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        self.max_restarts = max_restarts
        self.backoff = backoff if backoff is not None \
            else BackoffPolicy(base_s=1.0, max_s=30.0, jitter=0.5)
        self._sleep = sleep
        if registry is None:
            from jimm_tpu.obs import get_registry
            registry = get_registry("jimm_train")
        self.registry = registry
        self.restarts = 0
        #: one entry per failed attempt, oldest first
        self.history: list[str] = []

    def run(self, attempt_fn: Callable[[int, bool], int]) -> int:
        journal = get_journal()
        # correlation id of the incident currently being recovered from:
        # minted when an attempt fails, inherited by everything the
        # restarted attempt does (restore, reshard, advisor decisions)
        # via the ambient correlate() context.
        incident: str | None = None
        for attempt in range(self.max_restarts + 1):
            t0 = time.monotonic()
            lost: float | None = None
            cid: str | None = None
            try:
                with correlate(incident):
                    rc = attempt_fn(attempt, attempt > 0)
            except PreemptedError as e:
                failure = str(e)
                lost = 0.0  # the grace window already booked its lost work
                cid = getattr(e, "cid", None)
            except KeyboardInterrupt:
                raise  # operator stop is not a failure to retry
            except Exception as e:  # worker death: restartable by design
                failure = f"{type(e).__name__}: {e}"
            else:
                if rc == 0:
                    if incident is not None:
                        journal.emit("supervise_recovered", cid=incident,
                                     attempt=attempt)
                    return 0
                failure = f"exit code {rc}"
            if lost is None:
                # crash path: everything since the last committed
                # checkpoint (or the attempt start) is gone
                since = _last_checkpoint_t
                base = since if since is not None and since >= t0 else t0
                lost = time.monotonic() - base
            self.history.append(failure)
            incident = cid or incident or new_correlation_id()
            journal.emit("attempt_failed", cid=incident, attempt=attempt,
                         failure=failure, lost_s=round(lost, 4))
            if attempt >= self.max_restarts:
                journal.emit("supervise_gave_up", cid=incident,
                             attempts=attempt + 1, failure=failure)
                raise GiveUpError(
                    f"giving up after {self.max_restarts} restarts "
                    f"({attempt + 1} attempts); last failure: {failure}")
            self.restarts += 1
            self.registry.counter("restarts_total").inc()
            if lost > 0:
                self.registry.counter(
                    "goodput_lost_work_seconds_total").inc(lost)
            delay = self.backoff.delay(attempt)
            journal.emit("restart", cid=incident, attempt=attempt + 1,
                         backoff_s=round(delay, 4), failure=failure)
            print(  # jaxlint: disable=JL007 — operator-facing restart narration
                f"[supervise] attempt {attempt + 1} failed ({failure}); "
                f"restarting in {delay:.2f}s")
            self._sleep(delay)
        raise AssertionError("unreachable")
