"""Candidate timing that measures the kernel, not the compiler or the
dispatch queue.

Three classic autotuning mistakes are designed out:

- **Compile time in the sample**: the first (warmup) call traces, lowers,
  and compiles; it is waited on and discarded.
- **Async dispatch**: jax returns before the device finishes, so every
  timed rep wraps the call in ``jax.block_until_ready``.
- **Scheduling noise**: the reported figure is the trimmed median of k
  reps (min/max dropped once there are enough samples), not a single
  best-of run.

Off-TPU the kernels run in the Pallas interpreter, where timings are
meaningless but the *path* is identical — so reps short-circuit to 1 and
tier-1 CPU tests (and `scripts/tune_smoke.py`) exercise the full
measure → persist → lookup cycle.

Every measurement increments ``jimm_tune_measure_total`` and runs under a
``tune_measure`` span (plus a per-kernel ``tune_measure_{kernel}`` span
when the caller names the kernel — one row per attention-family variant):
the CI smoke asserts a warm cache re-run keeps the counter at zero.
"""

from __future__ import annotations

import time
from typing import Callable, Sequence

from jimm_tpu import obs

__all__ = ["measure", "trimmed_median"]


def trimmed_median(samples: Sequence[float]) -> float:
    """Median after dropping the min and max (when >= 5 samples)."""
    xs = sorted(samples)
    if not xs:
        raise ValueError("no samples")
    if len(xs) >= 5:
        xs = xs[1:-1]
    mid = len(xs) // 2
    if len(xs) % 2:
        return xs[mid]
    return 0.5 * (xs[mid - 1] + xs[mid])


def measure(fn: Callable[[], object], *, reps: int | None = None,
            warmup: int = 1, kernel: str | None = None) -> float:
    """Trimmed-median wall-clock seconds of ``fn()`` (see module docstring).

    ``fn`` should return the computation's output (a jax array or pytree)
    so ``block_until_ready`` has something to wait on. ``kernel`` adds a
    per-kernel ``tune_measure_{kernel}`` span alongside the aggregate, so
    a dump attributes sweep time to the kernel family member that spent it.
    """
    import jax
    from contextlib import nullcontext

    if reps is None:
        # interpret-mode short-circuit: off-TPU the number is not a kernel
        # timing, one rep keeps the full path testable without the cost
        reps = 7 if jax.default_backend() == "tpu" else 1
    registry = obs.get_registry("jimm_tune")
    per_kernel = obs.span(f"tune_measure_{kernel}") if kernel else nullcontext()
    with obs.span("tune_measure"), per_kernel:
        for _ in range(max(1, warmup)):
            jax.block_until_ready(fn())
        samples = []
        for _ in range(max(1, reps)):
            t0 = time.perf_counter()
            jax.block_until_ready(fn())
            samples.append(time.perf_counter() - t0)
    registry.counter("measure_total").inc()
    return trimmed_median(samples)
