"""Per-layer unit tests the reference lacks (SURVEY §4 implication (b))."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from flax import nnx

from jimm_tpu.configs import TransformerConfig, VisionConfig
from jimm_tpu.nn.transformer import Attention, Block, Transformer
from jimm_tpu.nn.vision import MAPHead, PatchEmbed, VisionTower
from jimm_tpu.ops.activations import get_activation, quick_gelu
from jimm_tpu.ops.attention import dot_product_attention, reference_attention


def test_quick_gelu_formula():
    x = jnp.linspace(-3, 3, 13)
    np.testing.assert_allclose(quick_gelu(x), x * jax.nn.sigmoid(1.702 * x),
                               rtol=1e-6)


def test_activation_registry_warns_and_falls_back():
    import warnings
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        fn = get_activation("totally_unknown")
        assert len(w) == 1
    x = jnp.ones((3,))
    np.testing.assert_allclose(fn(x), jax.nn.gelu(x, approximate=True))


def test_patch_embed_shapes():
    cfg = VisionConfig(image_size=32, patch_size=8, width=16, depth=1,
                       num_heads=2, mlp_dim=32)
    pe = PatchEmbed(cfg, nnx.Rngs(0))
    out = pe(jnp.ones((2, 32, 32, 3)))
    assert out.shape == (2, 16, 16)  # 4x4 grid of patches


def test_xla_attention_matches_reference():
    rng = np.random.RandomState(0)
    q, k, v = (jnp.asarray(rng.randn(2, 10, 4, 8).astype(np.float32))
               for _ in range(3))
    out_xla = dot_product_attention(q, k, v, impl="xla")
    out_ref = reference_attention(q, k, v)
    np.testing.assert_allclose(out_xla, out_ref, atol=1e-5)


def test_causal_attention_blocks_future():
    """Changing a future token must not affect earlier outputs."""
    rng = np.random.RandomState(0)
    attn = Attention(16, 2, nnx.Rngs(0), is_causal=True, impl="xla")
    x = jnp.asarray(rng.randn(1, 8, 16).astype(np.float32))
    y1 = attn(x)
    x2 = x.at[0, -1].set(123.0)
    y2 = attn(x2)
    np.testing.assert_allclose(y1[0, :-1], y2[0, :-1], atol=1e-5)
    assert np.abs(np.asarray(y1[0, -1] - y2[0, -1])).max() > 1e-3


def test_block_residual_order():
    """Pre-LN order: out = x + attn(ln1 x) + mlp(ln2(x + attn(ln1 x)))."""
    cfg = TransformerConfig(width=16, depth=1, num_heads=2, mlp_dim=32)
    blk = Block(cfg, nnx.Rngs(0))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(1, 5, 16).astype(np.float32))
    h = x + blk.attn(blk.ln1(x))
    expected = h + blk.mlp(blk.ln2(h))
    np.testing.assert_allclose(blk(x), expected, atol=1e-6)


def test_transformer_scan_matches_python_loop():
    cfg = TransformerConfig(width=16, depth=4, num_heads=2, mlp_dim=32)
    tr = Transformer(cfg, nnx.Rngs(0))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 5, 16).astype(np.float32))
    out_scan = tr(x)

    # manually unroll: slice layer i's params out of the stacked blocks
    graphdef, state = nnx.split(tr.blocks)
    y = x
    for i in range(cfg.depth):
        layer_state = jax.tree.map(lambda a: a[i], state)
        block = nnx.merge(graphdef, layer_state)
        y = block(y)
    np.testing.assert_allclose(out_scan, y, atol=1e-5)


def test_transformer_remat_same_output():
    cfg = TransformerConfig(width=16, depth=3, num_heads=2, mlp_dim=32)
    cfg_r = TransformerConfig(width=16, depth=3, num_heads=2, mlp_dim=32,
                              remat=True)
    tr = Transformer(cfg, nnx.Rngs(0))
    tr_r = Transformer(cfg_r, nnx.Rngs(0))
    x = jnp.ones((1, 5, 16))
    np.testing.assert_allclose(tr(x), tr_r(x), atol=1e-6)


def test_transformer_remat_policies_same_gradients():
    """Full remat ("none") and dots-saveable remat must both match the
    un-rematerialized gradient — they change memory/FLOPs, not math."""
    base = dict(width=16, depth=3, num_heads=2, mlp_dim=32)
    x = jnp.asarray(np.random.RandomState(0).randn(2, 5, 16), jnp.float32)

    def grad_sum(cfg):
        m = Transformer(cfg, nnx.Rngs(0))
        g = nnx.grad(lambda m: (m(x) ** 2).sum())(m)
        return jax.tree.reduce(lambda a, b: a + float(jnp.abs(b).sum()),
                               nnx.state(g, nnx.Param), 0.0)

    plain = grad_sum(TransformerConfig(**base))
    full = grad_sum(TransformerConfig(**base, remat=True))
    dots = grad_sum(TransformerConfig(**base, remat=True,
                                      remat_policy="dots"))
    np.testing.assert_allclose(full, plain, rtol=1e-5)
    np.testing.assert_allclose(dots, plain, rtol=1e-5)


def test_map_head_residual_is_pre_layernorm():
    """MAP residual order quirk (ref `common/vit.py:96-101`)."""
    cfg = VisionConfig(image_size=32, patch_size=16, width=16, depth=1,
                       num_heads=2, mlp_dim=32, pooling="map")
    head = MAPHead(cfg, nnx.Rngs(0))
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 4, 16).astype(np.float32))
    probe = jnp.broadcast_to(head.probe[...], (2, 1, 16))
    attn_out = head.attn(probe, kv=x)
    expected = (attn_out + head.mlp(head.ln(attn_out)))[:, 0]
    np.testing.assert_allclose(head(x), expected, atol=1e-6)


@pytest.mark.parametrize("pre_norm", [False, True])
def test_vision_tower_pre_norm_toggle(pre_norm):
    cfg = VisionConfig(image_size=32, patch_size=16, width=16, depth=1,
                       num_heads=2, mlp_dim=32, pre_norm=pre_norm,
                       patch_bias=not pre_norm)
    tower = VisionTower(cfg, nnx.Rngs(0))
    assert hasattr(tower, "ln_pre") == pre_norm
    out = tower(jnp.ones((1, 32, 32, 3)))
    assert out.shape == (1, 16)


def test_text_pos_embed_sliced_to_seq_len():
    """Shorter sequences must use a prefix of the positional table
    (ref `models/clip.py:160`)."""
    from jimm_tpu.configs import TextConfig
    from jimm_tpu.nn.text import TextTower
    cfg = TextConfig(vocab_size=50, context_length=16, width=16, depth=1,
                     num_heads=2, mlp_dim=32, causal=True)
    tower = TextTower(cfg, nnx.Rngs(0))
    short = tower(jnp.ones((1, 8), jnp.int32))
    assert short.shape == (1, 8, 16)
    full = tower(jnp.ones((1, 16), jnp.int32))
    # causal: prefix positions see identical context -> identical activations
    np.testing.assert_allclose(short[0], full[0, :8], atol=1e-5)


def test_default_backend_not_cached(monkeypatch):
    """VERDICT r2 weak #5: `_default_backend` was functools.cached, so a
    script that dispatched attention once before configuring the platform
    got permanently wrong `auto` routing. It must track the live backend."""
    from jimm_tpu.ops import attention
    answers = iter(["tpu", "cpu"])
    monkeypatch.setattr(attention.jax, "default_backend",
                        lambda: next(answers))
    assert attention._default_backend() == "tpu"
    # a cached implementation would return the stale "tpu" here
    assert attention._default_backend() == "cpu"
