"""weights.resolve hub robustness: bounded retry, backoff, cache fallback.

A transient network error during an ``aot warmup`` or a train start must
not kill the run: transient failures retry with exponential backoff, the
hub's not-found family (sharded-vs-single control flow) never retries,
and when the network stays down a locally-cached copy is served.
"""

import pytest

from jimm_tpu.weights.resolve import _hub_download_with_retry, _retryable


class EntryNotFoundError(Exception):
    """Name-matched stand-in for huggingface_hub's (same-name) class."""


class FlakyHub:
    """hf_hub_download double: raises ``fail_times`` transient errors
    (or a scripted exception) before succeeding; records every call."""

    def __init__(self, fail_times=0, exc=None):
        self.fail_times = fail_times
        self.exc = exc
        self.calls = []

    def __call__(self, repo_id, filename, local_files_only=False):
        self.calls.append({"filename": filename,
                           "local_files_only": local_files_only})
        if local_files_only:
            raise FileNotFoundError("nothing cached")
        if self.exc is not None:
            raise self.exc
        if len([c for c in self.calls if not c["local_files_only"]]) \
                <= self.fail_times:
            raise ConnectionError("reset by peer")
        return f"/cache/{filename}"


class TestHubRetry:
    def test_transient_error_retries_with_backoff(self):
        hub = FlakyHub(fail_times=2)
        slept = []
        out = _hub_download_with_retry(hub, "org/repo", "model.safetensors",
                                       retries=3, backoff_s=0.5,
                                       sleep=slept.append)
        assert out == "/cache/model.safetensors"
        assert len(hub.calls) == 3
        assert slept == [0.5, 1.0]  # exponential: backoff * 2**attempt

    def test_not_found_family_never_retries(self):
        # EntryNotFoundError is sharded-vs-single control flow — retrying
        # it would turn every single-file repo probe into dead waiting
        hub = FlakyHub(exc=EntryNotFoundError("no such file"))
        slept = []
        with pytest.raises(EntryNotFoundError):
            _hub_download_with_retry(hub, "org/repo",
                                     "model.safetensors.index.json",
                                     retries=5, backoff_s=1.0,
                                     sleep=slept.append)
        assert len(hub.calls) == 1
        assert slept == []
        assert not _retryable(EntryNotFoundError("x"))
        assert _retryable(ConnectionError("x"))
        assert _retryable(TimeoutError("x"))

    def test_offline_falls_back_to_local_cache(self):
        class CachedHub(FlakyHub):
            def __call__(self, repo_id, filename, local_files_only=False):
                self.calls.append({"local_files_only": local_files_only})
                if local_files_only:
                    return f"/cache/{filename}"  # previously downloaded
                raise ConnectionError("network down")

        hub = CachedHub()
        out = _hub_download_with_retry(hub, "org/repo", "model.safetensors",
                                       retries=2, backoff_s=0.0,
                                       sleep=lambda s: None)
        assert out == "/cache/model.safetensors"
        assert [c["local_files_only"] for c in hub.calls] \
            == [False, False, True]

    def test_offline_and_uncached_raises_the_transient_error(self):
        hub = FlakyHub(exc=ConnectionError("network down"))
        with pytest.raises(ConnectionError):  # not the cache-miss error
            _hub_download_with_retry(hub, "org/repo", "f.bin",
                                     retries=2, backoff_s=0.0,
                                     sleep=lambda s: None)
        assert hub.calls[-1]["local_files_only"] is True

    def test_env_defaults(self, monkeypatch):
        monkeypatch.setenv("JIMM_HUB_RETRIES", "1")
        hub = FlakyHub(fail_times=1)
        with pytest.raises(ConnectionError):
            _hub_download_with_retry(hub, "org/repo", "f.bin",
                                     backoff_s=0.0, sleep=lambda s: None)
        # one attempt (env) + the local-cache last resort
        assert len(hub.calls) == 2
