"""Tier planning: which clusters live where, under explicit byte budgets.

A :class:`TierPlan` assigns every cluster to exactly one residency tier:

- **hot** — full-precision rows packed into the fixed-capacity device
  arena (``device_budget_bytes`` worth of ``block_n``-row blocks). The
  arena's *shape* never changes — growth repacks its contents, so the
  compiled rescore program and the ``jimm_tier_device_resident_bytes``
  gauge both stay flat by construction.
- **warm** — full-precision rows pinned in host RAM, streamed onto
  device per probe.
- **cold** — full-precision rows spilled to disk segments on the
  artifact store, fetched by the IO engine when probed.

Placement is greedy by access frequency: clusters sort on their decayed
access EMA (ties broken by cluster id, so planning is deterministic) and
fill hot until the arena is full, then warm until the host budget runs
out, and the remainder goes cold. A cluster wider than ``max_bpc``
blocks is never hot — the compiled scan's per-cluster span is a
build-time constant, so an oversize cluster would force a retrace.

PQ codes for every non-hot cluster always stay host-resident (they are
the 8× compressed form — the whole point is that *they* fit when the
full-precision rows do not), so the planner only budgets full-precision
bytes.
"""

from __future__ import annotations

import dataclasses

import numpy as np

__all__ = ["AccessStats", "TierPlan", "plan_tiers"]

#: decay applied to every cluster's access EMA per recorded batch — high
#: enough that a burst promotes quickly, low enough that one quiet period
#: does not evict the working set
EMA_DECAY = 0.9


class AccessStats:
    """Per-cluster probe-frequency EMA the planner ranks on.

    ``record`` is called with the probed cluster ids of one search batch;
    all counters decay together so the ranking is a frequency, not a
    lifetime total. Snapshotting is cheap (one array copy) — the daemon
    reads it from its own thread.
    """

    def __init__(self, n_clusters: int):
        self.ema = np.zeros(int(n_clusters), np.float64)
        self.batches = 0

    def record(self, probed: np.ndarray) -> None:
        self.ema *= EMA_DECAY
        hit = np.unique(np.asarray(probed, np.int64))
        hit = hit[(hit >= 0) & (hit < len(self.ema))]
        self.ema[hit] += 1.0
        self.batches += 1

    def snapshot(self) -> np.ndarray:
        return self.ema.copy()


@dataclasses.dataclass(frozen=True)
class TierPlan:
    """One residency assignment. ``hot``/``warm``/``cold`` are sorted
    cluster-id tuples; byte totals are full-precision row bytes per tier
    (the arena's *allocated* bytes are fixed elsewhere — ``hot_bytes``
    here is the used portion)."""

    hot: tuple[int, ...]
    warm: tuple[int, ...]
    cold: tuple[int, ...]
    hot_blocks: int
    hot_bytes: int
    warm_bytes: int
    cold_bytes: int

    def tier_of(self, cluster: int) -> str:
        if cluster in self._hot_set:
            return "hot"
        if cluster in self._warm_set:
            return "warm"
        return "cold"

    @property
    def _hot_set(self) -> frozenset:
        return frozenset(self.hot)

    @property
    def _warm_set(self) -> frozenset:
        return frozenset(self.warm)

    def describe(self) -> dict:
        return {"hot_clusters": len(self.hot),
                "warm_clusters": len(self.warm),
                "cold_clusters": len(self.cold),
                "hot_blocks": self.hot_blocks,
                "hot_bytes": self.hot_bytes,
                "warm_bytes": self.warm_bytes,
                "cold_bytes": self.cold_bytes}


def plan_tiers(counts: np.ndarray, ema: np.ndarray, *,
               arena_blocks: int, block_n: int, row_bytes: int,
               max_bpc: int,
               host_budget_bytes: int | None = None,
               cold_enabled: bool = True) -> TierPlan:
    """Greedy residency assignment for ``counts[c]`` rows per cluster.

    ``arena_blocks`` is the device arena capacity in blocks;
    ``row_bytes`` is one full-precision row (``dim * itemsize``). With
    ``cold_enabled=False`` (no artifact store to spill to) everything
    that misses the arena is warm regardless of the host budget.
    """
    counts = np.asarray(counts, np.int64)
    ema = np.asarray(ema, np.float64)
    n_clusters = len(counts)
    if ema.shape != (n_clusters,):
        raise ValueError(f"ema must be ({n_clusters},); got {ema.shape}")
    blocks_per = (counts + block_n - 1) // block_n
    # rank: hottest first, deterministic tie order by cluster id
    order = np.lexsort((np.arange(n_clusters), -ema))
    hot: list[int] = []
    warm: list[int] = []
    cold: list[int] = []
    free = int(arena_blocks)
    host_free = (float("inf") if host_budget_bytes is None
                 else int(host_budget_bytes))
    hot_bytes = warm_bytes = cold_bytes = 0
    for c in (int(i) for i in order):
        if not counts[c]:
            # empty clusters are nominally hot: probing one costs nothing
            hot.append(c)
            continue
        nbytes = int(counts[c]) * row_bytes
        nblocks = int(blocks_per[c])
        if nblocks <= free and nblocks <= max_bpc:
            hot.append(c)
            free -= nblocks
            hot_bytes += nbytes
        elif not cold_enabled or nbytes <= host_free:
            warm.append(c)
            host_free -= nbytes
            warm_bytes += nbytes
        else:
            cold.append(c)
            cold_bytes += nbytes
    return TierPlan(hot=tuple(sorted(hot)), warm=tuple(sorted(warm)),
                    cold=tuple(sorted(cold)),
                    hot_blocks=int(arena_blocks) - free,
                    hot_bytes=hot_bytes, warm_bytes=warm_bytes,
                    cold_bytes=cold_bytes)
