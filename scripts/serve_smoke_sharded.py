"""CI tier-1 smoke for sharded multi-chip serving (docs/serving.md).

Forces 8 virtual CPU devices, plans a 2-replica x 2-model-parallel
topology, and proves the whole multi-replica path end to end in one
process:

1. **Plan + shard**: ``plan_topology(2, 2)`` over the 8 devices;
   ``build_replica_forwards`` gives each replica its own submesh-sharded
   model copy backed by a tmp AOT store (write-through on). Life 1's
   warmup populates the store (replica 0 compiles + writes through,
   replica 1 already loads replica 0's artifact — same fingerprint).
2. **Warm restart**: a second engine against the populated store reaches
   readiness with ZERO fresh traces — every bucket of every replica
   sourced ``"aot"`` — proving sharded artifacts round-trip across
   replica device sets and process lives.
3. **Load**: a 64-client closed loop through the warm engine — zero fresh
   compiles after warmup, every request answered, and each replica's
   ``jimm_serve_replica_{i}_dispatched_total`` counter (parsed from the
   rendered Prometheus text, the same bytes ``/metrics`` serves) holding
   at least 30% of the dispatches, so the load balancer provably spreads.
   (The load runs on the *warm* engine deliberately: its replicas are
   symmetric — both AOT-loaded — so the >=30% check tests the balancer,
   not the fresh-jit vs. AOT call-overhead gap of a half-warm life.)
4. **Numerics**: one served embedding matches the unsharded model.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.serve_smoke_sharded
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile

CLIENTS = 64
PER_CLIENT = 4
REPLICAS = 2
MODEL_PARALLEL = 2
MIN_SHARE = 0.30


def fail(msg: str) -> int:
    print(json.dumps({"metric": "serve_smoke_sharded", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def main() -> int:
    # must land before jax initializes its backends
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")

    import asyncio

    import jax
    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.serve import (BucketTable, InferenceEngine,
                                build_replica_forwards, plan_topology)

    if jax.device_count() < REPLICAS * MODEL_PARALLEL:
        return fail(f"need {REPLICAS * MODEL_PARALLEL} devices, have "
                    f"{jax.device_count()} — was XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=8 set before "
                    f"another jax import?")

    # small buckets on purpose: 64 clients x 4 requests coalesce into ~64
    # batches, enough dispatches for the >=30% per-replica share check to be
    # a property of the balancer rather than of scheduler noise
    buckets = (1, 4)
    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    model = CLIP(cfg, rngs=nnx.Rngs(0))
    size = cfg.vision.image_size
    plan = plan_topology(REPLICAS, MODEL_PARALLEL)

    def make_engine(store):
        forwards, traces = build_replica_forwards(
            model, plan, method="encode_image", item_shape=(size, size, 3),
            store=store, label="serve_smoke_sharded")
        return InferenceEngine(forwards, item_shape=(size, size, 3),
                               buckets=BucketTable(buckets),
                               max_delay_ms=2.0, trace_count=traces), traces

    with tempfile.TemporaryDirectory(prefix="jimm-serve-sharded-") as root:
        store = ArtifactStore(root)
        # life 1: populate the store through write-through warmup
        engine1, traces1 = make_engine(store)
        engine1.warmup_blocking()
        if not store.entries():
            return fail("life-1 warmup wrote nothing to the store")

        # --- warm restart: sharded AOT round-trip -------------------------
        engine, traces = make_engine(store)
        engine.warmup_blocking()
        if traces():
            return fail(f"warm restart paid {traces()} fresh traces; "
                        f"sharded artifacts did not round-trip")
        bad = {b: r for b, r in engine.warmup_report.items()
               if r.get("source") != "aot"
               or any(p.get("source") != "aot"
                      for p in r.get("replicas", []))}
        if bad:
            return fail(f"warm restart buckets not fully AOT-sourced: {bad}")
        compiles_before = traces()

        # --- 64-client closed loop ----------------------------------------
        x = np.random.RandomState(0).rand(size, size, 3).astype(np.float32)

        async def one_client():
            outs = []
            for _ in range(PER_CLIENT):
                outs.append(await engine.submit(x))
            return outs

        async def drive():
            await engine.start()
            try:
                return await asyncio.gather(
                    *[one_client() for _ in range(CLIENTS)])
            finally:
                await engine.stop()

        results = asyncio.run(drive())
        answered = sum(len(r) for r in results)
        if answered != CLIENTS * PER_CLIENT:
            return fail(f"only {answered}/{CLIENTS * PER_CLIENT} requests "
                        f"answered")
        compile_delta = traces() - compiles_before
        if compile_delta:
            return fail(f"{compile_delta} fresh compile(s) after warmup")

        # --- balance, read off the rendered Prometheus text ---------------
        text = engine.metrics.render_prometheus()
        counts = {int(i): float(v) for i, v in re.findall(
            r"^jimm_serve_replica_(\d+)_dispatched_total (\S+)$",
            text, re.MULTILINE)}
        if sorted(counts) != list(range(REPLICAS)):
            return fail(f"expected jimm_serve_replica_*_dispatched_total "
                        f"for replicas 0..{REPLICAS - 1}, got {counts}")
        total = sum(counts.values())
        if not total:
            return fail("no dispatches counted")
        shares = {i: v / total for i, v in counts.items()}
        if any(s < MIN_SHARE for s in shares.values()):
            return fail(f"replica dispatch share below {MIN_SHARE:.0%}: "
                        f"{ {i: round(s, 3) for i, s in shares.items()} }")

        # --- numerics vs the unsharded model ------------------------------
        got = np.asarray(results[0][0])
        want = np.asarray(model.encode_image(x[None]))[0]
        if not np.allclose(got, want, rtol=1e-4, atol=1e-4):
            return fail("sharded serving output disagrees with the "
                        "unsharded model")

        print(json.dumps({
            "metric": "serve_smoke_sharded", "value": 1.0,
            "topology": plan.describe(),
            "requests": answered,
            "compile_count_delta": compile_delta,
            "replica_dispatch": {i: int(v) for i, v in counts.items()},
            "store_entries": len(store.entries()),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
