"""Profiling & observability: MFU, throughput, structured metric logging.

The reference's only observability is ``print`` per step
(ref `examples/vit_training.py:226`). The north star requires MFU as the
metric of record (`BASELINE.json`), so we compute achieved FLOP/s from XLA's
own cost analysis of the compiled step and divide by the chip's peak.
"""

from __future__ import annotations

import json
import math
import time
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, IO

import jax

from jimm_tpu.obs.registry import MetricRegistry, get_registry

#: Peak dense (bf16) TFLOP/s per chip. Sources: public TPU/GPU spec sheets.
PEAK_TFLOPS: dict[str, float] = {
    "tpu v2": 22.5, "tpu v3": 61.0, "tpu v4": 137.5, "tpu v5 lite": 196.6,
    "tpu v5e": 196.6, "tpu v5p": 459.0, "tpu v6e": 918.0, "tpu v6 lite": 918.0,
    "cpu": 0.1,
}


def device_peak_tflops(device: jax.Device | None = None) -> float:
    device = device or jax.devices()[0]
    kind = device.device_kind.lower()
    for name, peak in PEAK_TFLOPS.items():
        if kind.startswith(name):
            return peak
    return PEAK_TFLOPS.get(device.platform, 1.0)


def compiled_flops(compiled) -> float | None:
    """Total FLOPs of one execution from XLA cost analysis (per-process)."""
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, list):
            cost = cost[0]
        return float(cost.get("flops", 0.0)) or None
    except Exception:
        return None


def mfu(flops_per_step: float | None, step_time_s: float,
        n_devices: int | None = None,
        device: jax.Device | None = None) -> float:
    """Model FLOPs utilization in [0, 1]. ``flops_per_step`` is the global
    FLOP count of one step; peak scales with device count.

    Degenerate inputs — ``flops_per_step`` of ``None`` (the
    :func:`compiled_flops` cost-analysis-failed path), a zero/negative/NaN
    step time, or a NaN FLOP count — return 0.0 instead of raising, and
    bump the ``jimm_train`` registry's ``mfu_degenerate_total`` counter so
    a bench that silently reports 0 MFU is still diagnosable.
    """
    if (flops_per_step is None or step_time_s is None
            or not math.isfinite(step_time_s) or step_time_s <= 0.0
            or not math.isfinite(flops_per_step) or flops_per_step < 0.0):
        get_registry("jimm_train").counter("mfu_degenerate_total").inc()
        return 0.0
    n = n_devices if n_devices is not None else jax.device_count()
    peak = device_peak_tflops(device) * 1e12 * n
    if peak <= 0.0:
        get_registry("jimm_train").counter("mfu_degenerate_total").inc()
        return 0.0
    return flops_per_step / (step_time_s * peak)


@dataclass
class StepTimer:
    """Wall-clock step timing with device sync on the boundaries.

    Sync is by host materialization (``jax.device_get``), not
    ``block_until_ready``: on remote-tunnel TPU platforms the latter can
    return before the dispatch chain executes.
    """

    t0: float = 0.0

    def start(self, *sync: jax.Array) -> None:
        for a in sync:
            jax.device_get(a)
        self.t0 = time.perf_counter()

    def stop(self, *sync: jax.Array) -> float:
        for a in sync:
            jax.device_get(a)
        return time.perf_counter() - self.t0


@dataclass
class MetricsLogger:
    """Structured metrics: console + JSONL file (one object per step) +
    optional TensorBoard scalars (``tensorboard_dir``; writes event files
    through the ``tensorboard`` package directly — no tensorflow).

    When ``registry`` is set (cmd_train passes the shared ``jimm_train``
    registry), every logged scalar is mirrored into it: ``step`` as the
    ``steps_logged_total`` counter, ``step_time_s`` into the
    ``step_time_seconds`` histogram, and every other numeric value as a
    last-value gauge — so the unified ``obs.snapshot()`` carries the same
    series the JSONL does.
    """

    path: str | Path | None = None
    print_every: int = 1
    tensorboard_dir: str | Path | None = None
    registry: MetricRegistry | None = None
    _file: IO | None = field(default=None, repr=False)
    _tb: Any = field(default=None, repr=False)
    _step: int = 0

    def log(self, step: int, **metrics: Any) -> None:
        record = {"step": step, "time": time.time(), **metrics}
        if self.path is not None:
            if self._file is None:
                Path(self.path).parent.mkdir(parents=True, exist_ok=True)
                self._file = open(self.path, "a")
            self._file.write(json.dumps(record, default=float) + "\n")
            self._file.flush()
        if self.registry is not None:
            self._registry_log(metrics)
        if self.tensorboard_dir is not None:
            self._tb_log(step, metrics)
        if self.print_every and step % self.print_every == 0:
            parts = " ".join(f"{k}={float(v):.4g}" if isinstance(v, (int, float))
                             else f"{k}={v}" for k, v in metrics.items())
            print(f"step {step}: {parts}")  # jaxlint: disable=JL007 — the console sink IS the logger

    def _registry_log(self, metrics: dict[str, Any]) -> None:
        reg = self.registry
        reg.counter("steps_logged_total").inc()
        for k, v in metrics.items():
            try:
                value = float(v)
            except (TypeError, ValueError):
                continue  # non-numeric: JSONL-only, same as TensorBoard
            if k == "step_time_s":
                reg.histogram("step_time_seconds").observe(value)
            else:
                try:
                    reg.gauge(k).set(value)
                except Exception:  # jaxlint: disable=JL013 — best-effort mirror; a name clash with a counter must not fail the log call  # noqa: BLE001
                    pass

    def _tb_log(self, step: int, metrics: dict[str, Any]) -> None:
        if self._tb is None:
            try:
                from tensorboard.summary.writer.event_file_writer import (
                    EventFileWriter)
            except ImportError:
                self.tensorboard_dir = None  # optional dep absent: degrade
                import warnings
                warnings.warn("tensorboard not installed; scalar event "
                              "logging disabled", stacklevel=3)
                return
            Path(self.tensorboard_dir).mkdir(parents=True, exist_ok=True)
            self._tb = EventFileWriter(str(self.tensorboard_dir))
        from tensorboard.compat.proto.event_pb2 import Event
        from tensorboard.compat.proto.summary_pb2 import Summary
        values = []
        for k, v in metrics.items():
            try:
                # match the JSONL path's default=float coercion: np/jax
                # scalars must land in TensorBoard too, not just floats
                values.append(Summary.Value(tag=k, simple_value=float(v)))
            except (TypeError, ValueError):
                pass  # non-numeric (strings etc.) — JSONL-only
        if values:
            self._tb.add_event(Event(step=step, wall_time=time.time(),
                                     summary=Summary(value=values)))

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None
        if self._tb is not None:
            self._tb.close()
            self._tb = None


# ---------------------------------------------------------------------------
# Analytic model FLOPs (XLA cost analysis counts a scanned layer body once,
# so compiled_flops undercounts depth-L towers by ~L; MFU uses these instead)
# ---------------------------------------------------------------------------

def _tower_fwd_flops(width: int, depth: int, mlp_dim: int, seq: int) -> float:
    matmul_params = depth * (4 * width * width + 2 * width * mlp_dim)
    attn = depth * 4 * seq * seq * width  # qk^T and pv
    return 2 * matmul_params * seq + attn


def vision_fwd_flops(v) -> float:
    """Per-image forward FLOPs of a VisionConfig tower (+ patch conv, MAP)."""
    seq = v.seq_len
    total = _tower_fwd_flops(v.width, v.depth, v.mlp_dim, seq)
    total += 2 * (v.patch_size ** 2 * v.channels * v.width) * v.num_patches
    if v.pooling == "map":
        # probe cross-attention: k/v projections over seq + mlp on 1 token
        total += 2 * (2 * v.width ** 2) * seq + 2 * (2 * v.width * v.mlp_dim)
    return total


def text_fwd_flops(t) -> float:
    return _tower_fwd_flops(t.width, t.depth, t.mlp_dim, t.context_length)


def model_fwd_flops(cfg) -> float:
    """Per-sample forward FLOPs for a ViT/CLIP/SigLIP config."""
    total = vision_fwd_flops(cfg.vision)
    if hasattr(cfg, "text"):
        total += text_fwd_flops(cfg.text)
        proj = getattr(cfg, "projection_dim", cfg.text.width)
        total += 2 * cfg.text.width * proj
        if hasattr(cfg.vision, "width") and cfg.vision.pooling == "cls":
            total += 2 * cfg.vision.width * proj  # CLIP visual projection
    return total


def train_step_flops(cfg, batch_size: int) -> float:
    """Model FLOPs (no remat recompute) of one training step: fwd + 2x bwd."""
    return 3.0 * model_fwd_flops(cfg) * batch_size
