"""Trace capture + offline per-op analysis (no TensorBoard)."""

import jax
import jax.numpy as jnp
import numpy as np

from jimm_tpu.train.profile import op_stats, summarize, trace


def test_trace_capture_and_analysis(tmp_path):
    x = jnp.asarray(np.random.RandomState(0).randn(256, 256), jnp.float32)

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x) @ x

    f(x).block_until_ready()
    with trace(tmp_path):
        for _ in range(3):
            out = f(x)
        out.block_until_ready()

    stats = op_stats(tmp_path)
    assert stats, "no ops aggregated from the capture"
    assert sum(s.total_us for s in stats) > 0
    text = summarize(stats, top=5, steps=3)
    assert "device op time" in text and "by category" in text
