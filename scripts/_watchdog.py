"""Hard SIGALRM watchdog shared by the TPU measurement entry points.

Deliberately imports NOTHING beyond the stdlib: every caller arms the
watchdog BEFORE the first jax/jimm import, because backend plugin discovery
can touch the axon tunnel whose failure mode is an indefinite hang that only
a signal interrupts. (bench.py, scripts/flash_compiled_check.py, and
scripts/profile_step.py all key their retry logic on the exit codes armed
here — keep the semantics in this one place.)
"""

from __future__ import annotations

import os
import signal
from typing import Callable


def hard_watchdog(seconds: int, exit_code: int,
                  emit: Callable[[], None]) -> Callable[[], None]:
    """Arm SIGALRM: after ``seconds`` with no disarm, call ``emit()`` (print
    the failure evidence — it must not raise) and ``os._exit(exit_code)``.
    Returns a ``disarm()`` that cancels the alarm."""
    def on_alarm(signum, frame):
        try:
            emit()
        finally:
            os._exit(exit_code)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    return lambda: signal.alarm(0)
