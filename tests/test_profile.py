"""Trace capture + offline per-op analysis (no TensorBoard)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from jimm_tpu.train.profile import op_stats, summarize, trace


def test_trace_capture_and_analysis(tmp_path):
    x = jnp.asarray(np.random.RandomState(0).randn(256, 256), jnp.float32)

    @jax.jit
    def f(x):
        return jnp.tanh(x @ x) @ x

    f(x).block_until_ready()
    with trace(tmp_path):
        for _ in range(3):
            out = f(x)
        out.block_until_ready()

    stats = op_stats(tmp_path)
    assert stats, "no ops aggregated from the capture"
    assert sum(s.total_us for s in stats) > 0
    text = summarize(stats, top=5, steps=3)
    assert "device op time" in text and "by category" in text


def test_metrics_logger_tensorboard(tmp_path):
    """Scalar events written through the tensorboard package (no TF) read
    back with the right tags and values."""
    pytest.importorskip("tensorboard")
    from jimm_tpu.train.metrics import MetricsLogger

    logger = MetricsLogger(tensorboard_dir=tmp_path, print_every=0)
    logger.log(0, loss=2.5, note="skipped-non-numeric")
    logger.log(1, loss=1.25)
    logger.close()

    from tensorboard.backend.event_processing.event_file_loader import (
        EventFileLoader)
    from tensorboard.util.tensor_util import make_ndarray
    files = list(tmp_path.glob("events.out.tfevents.*"))
    assert len(files) == 1
    got = {}
    for ev in EventFileLoader(str(files[0])).Load():
        for v in getattr(ev.summary, "value", []):
            # the event-processing layer migrates simple_value -> tensor
            val = (float(make_ndarray(v.tensor))
                   if v.WhichOneof("value") == "tensor" else v.simple_value)
            got[(ev.step, v.tag)] = val
    assert got == {(0, "loss"): 2.5, (1, "loss"): 1.25}
