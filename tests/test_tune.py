"""jimm_tpu.tune: key stability, cache hit/miss/fallback, space pruning,
measurement discipline, and the ops integration (block sizes resolved from
the persistent cache at trace time)."""

import json
import subprocess
import sys

import numpy as np
import pytest

from jimm_tpu import obs
from jimm_tpu.tune import (KERNELS, TuneCache, best_config, kernel_space,
                           trimmed_median, tune_kernel, tune_key)

FLASH_SHAPES = ((2, 128, 4, 64), (2, 128, 4, 64), (2, 128, 4, 64))
LN_SHAPES = ((64, 256),)


def flash_key(**over):
    kw = dict(kernel="flash_attention", shapes=FLASH_SHAPES,
              dtypes=("float32",) * 3,
              kernel_version=KERNELS["flash_attention"].version,
              backend="cpu", jax_version="0.4.37")
    kw.update(over)
    kernel = kw.pop("kernel")
    return tune_key(kernel, **kw)


def counters():
    return obs.get_registry("jimm_tune").snapshot()


def delta(before, after, name):
    return after.get(name, 0) - before.get(name, 0)


class TestKeys:
    def test_fingerprint_deterministic(self):
        assert flash_key().fingerprint() == flash_key().fingerprint()

    def test_fingerprint_sensitivity(self):
        base = flash_key().fingerprint()
        assert flash_key(shapes=((2, 256, 4, 64),) * 3).fingerprint() != base
        assert flash_key(dtypes=("bfloat16",) * 3).fingerprint() != base
        assert flash_key(kernel_version=99).fingerprint() != base
        assert flash_key(backend="tpu").fingerprint() != base
        assert flash_key(jax_version="0.5.0").fingerprint() != base

    def test_dtype_spellings_canonicalize(self):
        # np dtype objects, type objects, and names all mean the same key
        a = flash_key(dtypes=(np.float32, np.dtype("float32"), "float32"))
        assert a.fingerprint() == flash_key().fingerprint()

    def test_fingerprint_stable_across_processes(self):
        # the persistence contract: a fresh interpreter maps the same
        # logical key to the same fingerprint (no per-process hash seeds,
        # dict ordering, or repr details leak in)
        code = (
            "from jimm_tpu.tune import tune_key\n"
            "k = tune_key('flash_attention',"
            " shapes=((2, 128, 4, 64),) * 3, dtypes=('float32',) * 3,"
            " kernel_version=%d, backend='cpu', jax_version='0.4.37')\n"
            "print(k.fingerprint())\n" % KERNELS["flash_attention"].version)
        out = subprocess.run([sys.executable, "-c", code],
                             capture_output=True, text=True, check=True)
        assert out.stdout.strip() == flash_key().fingerprint()

    def test_cli_preset_points_key_like_the_ops_hot_path(self):
        # the CLI writes one dtype PER OPERAND because ops key on
        # (q.dtype, k.dtype, v.dtype); a drift here makes offline tuning
        # silently useless (configs that best_config never finds)
        from jimm_tpu.tune.cli import _preset_points
        pts = {p["kernel"]: p for p in
               _preset_points("clip-vit-base-patch16", 2, "float32")}
        flash = pts["flash_attention"]
        assert len(flash["dtypes"]) == len(flash["shapes"]) == 3
        cli_key = tune_key("flash_attention", shapes=flash["shapes"],
                           dtypes=flash["dtypes"], kernel_version=1,
                           backend="cpu", jax_version="x")
        ops_key = tune_key(
            "flash_attention",
            shapes=tuple(tuple(s) for s in flash["shapes"]),
            dtypes=tuple(np.dtype("float32") for _ in range(3)),
            kernel_version=1, backend="cpu", jax_version="x")
        assert cli_key.fingerprint() == ops_key.fingerprint()
        assert len(pts["layer_norm"]["dtypes"]) == 1

    def test_describe_is_json_round_trippable(self):
        d = flash_key().describe()
        assert json.loads(json.dumps(d)) == d
        assert d["kernel"] == "flash_attention"


class TestJaxFreeImport:
    @pytest.mark.parametrize("module", [
        "jimm_tpu.tune", "jimm_tpu.tune.cache", "jimm_tpu.tune.space",
        "jimm_tpu.tune.cli"])
    def test_import_does_not_pull_jax(self, module):
        code = (f"import {module}, sys; "
                f"assert 'jax' not in sys.modules, 'jax leaked'")
        subprocess.run([sys.executable, "-c", code], check=True,
                       capture_output=True)


class TestCache:
    def test_put_get_round_trip(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        key = flash_key()
        fp = cache.put(key, {"block_q": 128, "block_k": 256},
                       metrics={"time_s": 0.5})
        assert fp == key.fingerprint()
        rec = cache.get(key)
        assert rec["config"] == {"block_q": 128, "block_k": 256}
        assert rec["metrics"]["time_s"] == 0.5

    def test_second_instance_sees_persisted_config(self, tmp_path):
        TuneCache(tmp_path / "c").put(flash_key(), {"block_q": 512,
                                                    "block_k": 128})
        rec = TuneCache(tmp_path / "c").get(flash_key())
        assert rec["config"]["block_q"] == 512

    def test_miss_returns_none_and_is_not_memoized(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        assert cache.get(flash_key()) is None
        # an offline tune between lookups must become visible
        cache.put(flash_key(), {"block_q": 256, "block_k": 256})
        assert cache.get(flash_key())["config"]["block_q"] == 256

    def test_corrupt_record_quarantined_as_miss(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        key = flash_key()
        cache.put(key, {"block_q": 128, "block_k": 128})
        (cache.entries()[0].path / "artifact.bin").write_bytes(b"not json")
        fresh = TuneCache(tmp_path / "c")  # bypass the in-process memo
        assert fresh.get(key) is None

    def test_entries_meta_labels(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        cache.put(flash_key(), {"block_q": 128, "block_k": 128})
        (entry,) = cache.entries()
        assert entry.meta["label"] == "tune:flash_attention"
        assert entry.meta["kernel"] == "flash_attention"


class TestBestConfig:
    def test_hit_path(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        cache.put(tune_key("layer_norm", shapes=LN_SHAPES,
                           dtypes=("float32",),
                           kernel_version=KERNELS["layer_norm"].version),
                  {"block_rows": 32})
        before = counters()
        cfg = best_config("layer_norm", LN_SHAPES, ("float32",), cache=cache)
        after = counters()
        assert cfg == {"block_rows": 32}
        assert delta(before, after, "hit_total") == 1
        assert delta(before, after, "measure_total") == 0

    def test_fallback_path_uses_default_and_never_measures(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        before = counters()
        cfg = best_config("layer_norm", ((999, 333),), ("float32",),
                          default={"block_rows": 64}, cache=cache)
        after = counters()
        assert cfg == {"block_rows": 64}
        assert delta(before, after, "miss_total") == 1
        assert delta(before, after, "fallback_total") == 1
        assert delta(before, after, "measure_total") == 0

    def test_fallback_without_explicit_default_uses_kernel_default(
            self, tmp_path):
        from jimm_tpu.ops.layer_norm import DEFAULT_BLOCK_ROWS
        cfg = best_config("layer_norm", ((7, 48),), ("float32",),
                          cache=TuneCache(tmp_path / "c"))
        assert cfg == {"block_rows": DEFAULT_BLOCK_ROWS}

    def test_jimm_tune_env_tunes_on_miss(self, tmp_path, monkeypatch):
        monkeypatch.setenv("JIMM_TUNE", "1")
        cache = TuneCache(tmp_path / "c")
        before = counters()
        cfg = best_config("layer_norm", ((16, 128),), ("float32",),
                          cache=cache)
        after = counters()
        assert "block_rows" in cfg
        assert delta(before, after, "measure_total") >= 1
        # and the result persisted: the next lookup is a pure hit
        assert cache.get(tune_key(
            "layer_norm", shapes=((16, 128),), dtypes=("float32",),
            kernel_version=KERNELS["layer_norm"].version)) is not None


class TestTuneKernel:
    def test_persists_winner_and_second_lookup_is_pure_hit(self, tmp_path):
        cache = TuneCache(tmp_path / "c")
        report = tune_kernel("layer_norm", ((32, 128),), ("float32",),
                             cache=cache)
        assert report["candidates"] == len(report["trials"]) >= 1
        assert report["config"] in [t["config"] for t in report["trials"]]
        before = counters()
        cfg = best_config("layer_norm", ((32, 128),), ("float32",),
                          cache=TuneCache(tmp_path / "c"))
        after = counters()
        assert cfg == report["config"]
        assert delta(before, after, "hit_total") == 1
        assert delta(before, after, "measure_total") == 0

    def test_explicit_candidates_override_space(self, tmp_path):
        report = tune_kernel("layer_norm", ((16, 128),), ("float32",),
                             cache=TuneCache(tmp_path / "c"),
                             candidates=[{"block_rows": 8}])
        assert report["config"] == {"block_rows": 8}
        assert report["candidates"] == 1


class TestSpace:
    def test_flash_space_prunes_oversized_blocks(self):
        cands = kernel_space("flash_attention", FLASH_SHAPES,
                             ("float32",) * 3)
        assert cands
        for c in cands:
            # seq len 128 -> no point in blocks beyond its 128-multiple
            assert c["block_q"] <= 128 and c["block_k"] <= 128

    def test_flash_space_vmem_formula_matches_ops(self):
        # the pruner's VMEM model must BE the ops guard's model — if the
        # kernel's working-set formula changes, this fails and space.py
        # follows
        from jimm_tpu.ops import flash_attention as fa
        from jimm_tpu.tune.space import VMEM_BUDGET, flash_vmem_bytes
        assert VMEM_BUDGET == fa._VMEM_BUDGET
        for bq in (128, 256, 512):
            for bk in (128, 256, 512):
                for d in (64, 128):
                    assert flash_vmem_bytes(bq, bk, d) == \
                        fa._per_head_vmem_bytes(bq, bk, d)

    def test_ln_space_clamps_to_row_count(self):
        cands = kernel_space("layer_norm", ((16, 128),), ("float32",))
        assert cands
        assert all(c["block_rows"] <= 16 for c in cands)

    def test_spaces_never_empty(self):
        # even absurd shapes yield the safe-default singleton
        assert kernel_space("layer_norm", ((1, 100000),), ("float32",))
        assert kernel_space("flash_attention",
                            ((1, 8, 1, 4096),) * 3, ("float32",) * 3)
        assert kernel_space("int8_matmul", ((1, 100000), (100000, 1)),
                            ("int8", "int8"))
        assert kernel_space("flash_attention_int8",
                            ((1, 16384, 1, 128),) * 3, ("float32",) * 3)

    def test_int8_matmul_space_prunes_to_shape(self):
        cands = kernel_space("int8_matmul", ((40, 64), (64, 40)),
                             ("int8", "int8"))
        assert cands
        for c in cands:
            # m=40 -> 64-row ceiling; n=40 -> one 128-lane tile
            assert c["block_m"] <= 64 and c["block_n"] <= 128

    def test_int8_matmul_vmem_formula_matches_ops(self):
        from jimm_tpu.ops import int8_matmul as im
        from jimm_tpu.tune.space import VMEM_BUDGET, int8_matmul_vmem_bytes
        assert VMEM_BUDGET == im._VMEM_BUDGET
        for bm in (32, 64, 256):
            for bn in (128, 512):
                for k in (64, 768):
                    assert int8_matmul_vmem_bytes(bm, bn, k) == \
                        im._per_cell_vmem_bytes(bm, bn, k)

    def test_int8_flash_vmem_formula_matches_ops(self):
        from jimm_tpu.ops import flash_attention_int8 as fi
        from jimm_tpu.tune.space import int8_flash_vmem_bytes
        for bq in (128, 512):
            for bk in (128, 512):
                for d in (64, 128):
                    assert int8_flash_vmem_bytes(bq, bk, d) == \
                        fi._per_head_vmem_bytes(bq, bk, d)

    def test_int8_flash_bwd_vmem_formula_matches_ops(self):
        # blocks are shared between the fwd and bwd kernels, so the pruner
        # must model BOTH working sets — this pins the bwd one
        from jimm_tpu.ops import flash_attention_int8 as fi
        from jimm_tpu.tune.space import int8_flash_bwd_vmem_bytes
        for bq in (128, 512):
            for bk in (128, 512):
                for d in (64, 128):
                    assert int8_flash_bwd_vmem_bytes(bq, bk, d) == \
                        fi._per_head_bwd_vmem_bytes(bq, bk, d)

    def test_fp8_matmul_vmem_formula_matches_ops(self):
        from jimm_tpu.ops import fp8_matmul as fm
        from jimm_tpu.tune.space import VMEM_BUDGET, fp8_matmul_vmem_bytes
        assert VMEM_BUDGET == fm._VMEM_BUDGET
        for bm in (32, 64, 256):
            for bn in (128, 512):
                for k in (64, 768):
                    assert fp8_matmul_vmem_bytes(bm, bn, k) == \
                        fm._per_cell_vmem_bytes(bm, bn, k)

    def test_fp8_matmul_space_prunes_to_shape(self):
        cands = kernel_space("fp8_matmul", ((40, 64), (64, 40)),
                             ("float8_e4m3fn", "float8_e4m3fn"))
        assert cands
        for c in cands:
            # m=40 -> 64-row ceiling; n=40 -> one 128-lane tile
            assert c["block_m"] <= 64 and c["block_n"] <= 128

    def test_int8_kernels_registered(self):
        for name in ("int8_matmul", "flash_attention_int8", "fp8_matmul"):
            assert name in KERNELS
            assert KERNELS[name].version >= 1
            assert callable(KERNELS[name].bench)

    def test_int8_flash_version_bumped_for_backward(self):
        # the lse output changed the fwd working set and the bwd added new
        # feasibility constraints — configs tuned for version 1 must miss
        assert KERNELS["flash_attention_int8"].version >= 2

    def test_attention_variant_vmem_formulas_match_ops(self):
        # one formula per family member: the pruner's model must BE the
        # kernel guard's model with that variant's spec flags
        from jimm_tpu.ops import flash_attention as fa
        from jimm_tpu.tune.space import (bias_flash_vmem_bytes,
                                         masked_flash_vmem_bytes,
                                         sigmoid_vmem_bytes)
        for bq in (128, 256, 512):
            for bk in (128, 256, 512):
                for d in (64, 128):
                    assert masked_flash_vmem_bytes(bq, bk, d) == \
                        fa._per_head_vmem_bytes(bq, bk, d, has_mask=True)
                    assert bias_flash_vmem_bytes(bq, bk, d) == \
                        fa._per_head_vmem_bytes(bq, bk, d, has_bias=True)
                    assert sigmoid_vmem_bytes(bq, bk, d) == \
                        fa._per_head_vmem_bytes(bq, bk, d, kind="sigmoid",
                                                has_mask=True)

    def test_attention_variant_spaces_and_kernels_registered(self):
        for name in ("flash_attention_masked", "flash_attention_bias",
                     "sigmoid_attention"):
            assert name in KERNELS
            assert KERNELS[name].version >= 1
            assert callable(KERNELS[name].bench)
            cands = kernel_space(name, FLASH_SHAPES, ("float32",) * 3)
            assert cands
            # seq len 128 -> no point in blocks beyond its 128-multiple
            assert all(c["block_q"] <= 128 and c["block_k"] <= 128
                       for c in cands)

    def test_bias_space_is_subset_of_flash_space(self):
        # the bias variant's extra (bq, bk) f32 tiles can only shrink the
        # feasible set, never grow it
        shapes = ((2, 1024, 4, 128),) * 3
        flash = {tuple(sorted(c.items()))
                 for c in kernel_space("flash_attention", shapes)}
        bias = {tuple(sorted(c.items()))
                for c in kernel_space("flash_attention_bias", shapes)}
        assert bias <= flash


class TestMeasure:
    def test_trimmed_median_drops_extremes(self):
        assert trimmed_median([100.0, 1.0, 2.0, 3.0, 0.1]) == 2.0

    def test_trimmed_median_small_samples(self):
        assert trimmed_median([3.0]) == 3.0
        assert trimmed_median([1.0, 3.0]) == 2.0

    def test_measure_counts_and_returns_positive(self):
        from jimm_tpu.tune.measure import measure
        before = counters()
        t = measure(lambda: sum(range(100)), reps=3, warmup=1)
        after = counters()
        assert t > 0
        assert delta(before, after, "measure_total") == 1


class TestOpsIntegration:
    def test_layer_norm_resolves_tuned_block(self, tmp_path):
        import jax.numpy as jnp

        from jimm_tpu.ops.layer_norm import layer_norm
        from jimm_tpu.tune import api as tune_api
        cache = tune_api.configure(tmp_path / "c")
        cache.put(tune_key("layer_norm", shapes=((24, 128),),
                           dtypes=("float32",),
                           kernel_version=KERNELS["layer_norm"].version),
                  {"block_rows": 8})
        x = jnp.arange(24 * 128, dtype=jnp.float32).reshape(24, 128) / 100
        before = counters()
        out = layer_norm(x, jnp.ones((128,)), jnp.zeros((128,)))
        after = counters()
        assert delta(before, after, "hit_total") >= 1
        assert delta(before, after, "measure_total") == 0
        ref = (x - x.mean(-1, keepdims=True)) / np.sqrt(
            x.var(-1, keepdims=True) + 1e-6)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=2e-5)

    def test_masked_flash_resolves_tuned_block(self, tmp_path):
        """The variant looks up under its OWN kernel name — a tuned masked
        config must be honored by flash_attention_masked (and produce the
        oracle's numbers at the tuned blocks)."""
        import jax.numpy as jnp

        from jimm_tpu.ops.attention import reference_attention
        from jimm_tpu.ops.flash_attention import flash_attention_masked
        from jimm_tpu.tune import api as tune_api
        shapes = ((1, 128, 2, 64),) * 3
        cache = tune_api.configure(tmp_path / "c")
        cache.put(tune_key("flash_attention_masked", shapes=shapes,
                           dtypes=("float32",) * 3,
                           kernel_version=KERNELS[
                               "flash_attention_masked"].version),
                  {"block_q": 128, "block_k": 128})
        rng = np.random.RandomState(0)
        q, k, v = (jnp.asarray(rng.randn(1, 128, 2, 64).astype(np.float32))
                   for _ in range(3))
        mask = jnp.asarray(rng.rand(1, 128) > 0.3).at[:, 0].set(True)
        before = counters()
        out = flash_attention_masked(q, k, v, mask)
        after = counters()
        assert delta(before, after, "hit_total") >= 1
        assert delta(before, after, "measure_total") == 0
        ref = reference_attention(q, k, v, mask=mask[:, None, None, :])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   atol=3e-5)

    def test_flash_explicit_blocks_skip_cache(self):
        import jax
        import jax.numpy as jnp

        from jimm_tpu.ops.flash_attention import flash_attention
        k = jax.random.split(jax.random.PRNGKey(0), 3)
        q, kk, v = (jax.random.normal(ki, (1, 128, 2, 64)) for ki in k)
        before = counters()
        flash_attention(q, kk, v, block_q=128, block_k=128)
        after = counters()
        for name in ("hit_total", "miss_total", "fallback_total"):
            assert delta(before, after, name) == 0
