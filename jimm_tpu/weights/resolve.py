"""Checkpoint resolution: local safetensors/pytorch file/dir or HF hub repo id.

Preserves the reference's full user-visible loading contract
(SURVEY §2.4 "both formats"): local `.safetensors` or `pytorch_model.bin`
file with sibling/parent `config.json` discovery (ref `common/utils.py:77-86`),
local directory, or HF hub repo-id (ref `common/utils.py:55-99`) — but with
zero torch in the import graph: `.bin` files are read by the stdlib-only
unpickler in :mod:`jimm_tpu.weights.torch_pickle`. Adds sharded-checkpoint
support (`*.index.json`), which the reference lacks.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Any

import numpy as np

from jimm_tpu.weights import torch_pickle
from jimm_tpu.weights.safetensors_io import load_file

_TORCH_SUFFIXES = (".bin", ".pt", ".pth")


def _load_config(path: Path) -> dict[str, Any] | None:
    if path.is_file():
        with open(path) as f:
            return json.load(f)
    return None


def _sharded(d: Path, index: Path, loader) -> dict[str, np.ndarray]:
    with open(index) as f:
        weight_map: dict[str, str] = json.load(f)["weight_map"]
    weights: dict[str, np.ndarray] = {}
    for shard in sorted(set(weight_map.values())):
        weights.update(loader(d / shard))
    return weights


def _from_dir(d: Path, use_pytorch: bool = False
              ) -> tuple[dict[str, np.ndarray], dict | None]:
    config = _load_config(d / "config.json")
    if use_pytorch:
        index = d / "pytorch_model.bin.index.json"
        if index.is_file():
            return _sharded(d, index, torch_pickle.load_file), config
        single = d / "pytorch_model.bin"
        if single.is_file():
            return torch_pickle.load_file(single), config
        raise FileNotFoundError(f"no pytorch_model.bin under {d}")
    index = d / "model.safetensors.index.json"
    if index.is_file():
        return _sharded(d, index, load_file), config
    single = d / "model.safetensors"
    if single.is_file():
        return load_file(single), config
    candidates = sorted(d.glob("*.safetensors"))
    if candidates:
        weights: dict[str, np.ndarray] = {}
        for c in candidates:
            weights.update(load_file(c))
        return weights, config
    # fall back to the torch format when no safetensors exist at all
    bin_index = d / "pytorch_model.bin.index.json"
    if bin_index.is_file():
        return _sharded(d, bin_index, torch_pickle.load_file), config
    if (d / "pytorch_model.bin").is_file():
        return torch_pickle.load_file(d / "pytorch_model.bin"), config
    raise FileNotFoundError(f"no .safetensors or pytorch_model.bin "
                            f"weights under {d}")


def _from_file(p: Path) -> tuple[dict[str, np.ndarray], dict | None]:
    if p.suffix in _TORCH_SUFFIXES:
        weights = torch_pickle.load_file(p)
    else:
        weights = load_file(p)
    # config discovery: sibling config.json, else parent of a `model/` dir
    # (ref common/utils.py:77-86)
    config = _load_config(p.parent / "config.json")
    if config is None and p.parent.name == "model":
        config = _load_config(p.parent.parent / "config.json")
    return weights, config


def _from_hub(repo_id: str, use_pytorch: bool = False
              ) -> tuple[dict[str, np.ndarray], dict | None]:
    try:
        from huggingface_hub import hf_hub_download
    except ImportError as e:  # pragma: no cover
        raise FileNotFoundError(
            f"{repo_id!r} is not a local path and huggingface_hub is "
            "unavailable") from e
    def fetch(single: str, loader) -> dict[str, np.ndarray]:
        # sharded checkpoints first (large models), then the single file
        try:
            index_path = hf_hub_download(repo_id, single + ".index.json")
            with open(index_path) as f:
                weight_map: dict[str, str] = json.load(f)["weight_map"]
            out: dict[str, np.ndarray] = {}
            for shard in sorted(set(weight_map.values())):
                out.update(loader(hf_hub_download(repo_id, shard)))
            return out
        except Exception:
            return loader(hf_hub_download(repo_id, single))

    formats = [("model.safetensors", load_file),
               ("pytorch_model.bin", torch_pickle.load_file)]
    if use_pytorch:
        formats.reverse()
    try:
        try:
            weights = fetch(*formats[0])
        except Exception:
            weights = fetch(*formats[1])  # repo hosts only the other format
    except Exception as e:
        raise FileNotFoundError(
            f"could not fetch {repo_id!r} from the HF hub "
            f"(offline, or repo has neither format?): {e}") from e
    try:
        config_path = hf_hub_download(repo_id, "config.json")
        config = _load_config(Path(config_path))
    except Exception:
        config = None
    return weights, config


def resolve_checkpoint(name_or_path: str | os.PathLike, *,
                       use_pytorch: bool = False
                       ) -> tuple[dict[str, np.ndarray], dict | None]:
    """Return ``(flat hf tensor dict, hf config dict | None)``.

    ``use_pytorch=True`` prefers the ``pytorch_model.bin`` format (ref
    `common/utils.py:55-71`) — read torch-free by
    :mod:`~jimm_tpu.weights.torch_pickle`.
    """
    p = Path(name_or_path).expanduser()
    if p.is_dir():
        return _from_dir(p, use_pytorch)
    if p.is_file():
        return _from_file(p)
    name = str(name_or_path)
    if name.startswith((".", "/", "~")) or name.count("/") != 1:
        # filesystem-looking, but nothing there — don't confuse with a repo id
        raise FileNotFoundError(f"no checkpoint file or directory at {name!r}")
    return _from_hub(name, use_pytorch)
