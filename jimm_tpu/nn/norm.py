"""LayerNorm module with a swappable kernel.

Drop-in for ``nnx.LayerNorm`` (same ``scale``/``bias`` param names, so
checkpoint mappings are unchanged) that can route through the fused Pallas
kernel (`jimm_tpu/ops/layer_norm.py`) — one pass over HBM for the backward
instead of XLA's multi-fusion LN bwd (profiled at ~340 GB/s,
docs/performance.md)."""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import nnx

from jimm_tpu.ops.layer_norm import layer_norm
from jimm_tpu.parallel.sharding import logical


class FusedLayerNorm(nnx.Module):
    def __init__(self, dim: int, *, epsilon: float, rngs: nnx.Rngs,
                 dtype=None, param_dtype=jnp.float32):
        self.epsilon = epsilon
        self.dtype = dtype
        self.scale = nnx.Param(
            logical(nnx.initializers.ones_init(), "embed")(
                rngs.params(), (dim,), param_dtype))
        self.bias = nnx.Param(
            logical(nnx.initializers.zeros_init(), "embed")(
                rngs.params(), (dim,), param_dtype))

    def __call__(self, x: jax.Array) -> jax.Array:
        shape = x.shape
        dtype = self.dtype or x.dtype
        x2 = x.reshape(-1, shape[-1]).astype(dtype)
        out = layer_norm(x2, self.scale[...].astype(dtype),
                         self.bias[...].astype(dtype), self.epsilon)
        return out.reshape(shape)
