"""grain-backed loader: random-access tfrecord source, batch parity with the
records pipeline, sharding, determinism, and checkpointable resume."""

import numpy as np
import pytest

pg = pytest.importorskip("grain.python")

from jimm_tpu.data.grain_pipeline import (TFRecordDataSource, grain_batches,
                                          make_grain_loader)
from jimm_tpu.data.records import (write_classification_records,
                                   write_image_text_records)
from jimm_tpu.data.tfrecord import decode_example


@pytest.fixture(scope="module")
def shards(tmp_path_factory, rng):
    d = tmp_path_factory.mktemp("grain_data")
    paths = []
    k = 0
    for s in range(2):
        pairs = []
        for _ in range(6):
            img = rng.randint(0, 255, size=(8, 8, 3)).astype(np.uint8)
            pairs.append((img, [k + 1, k + 2, k + 3]))
            k += 1
        p = d / f"part-{s}.tfrecord"
        write_image_text_records(p, pairs, encoding="raw")
        paths.append(str(p))
    return paths


def test_random_access_source(shards):
    src = TFRecordDataSource(shards)
    assert len(src) == 12
    ex = decode_example(src[0])
    assert set(ex) >= {"image", "tokens", "shape"}
    # random access: last record readable without touching the others
    assert decode_example(src[11])["tokens"]


def test_contrastive_batches(shards):
    loader = make_grain_loader(shards, 4, task="contrastive", image_size=16,
                               seq_len=5, shuffle=False, num_epochs=1)
    batches = list(grain_batches(loader))
    assert len(batches) == 3  # 12 examples / 4
    images, tokens = batches[0]
    assert images.shape == (4, 16, 16, 3) and images.dtype == np.float32
    assert tokens.shape == (4, 5) and tokens.dtype == np.int32
    assert np.all(tokens[:, 3:] == 0)  # padded to seq_len


def test_classification_batches(tmp_path, rng):
    pairs = [(rng.randint(0, 255, size=(8, 8, 3)).astype(np.uint8), i % 3)
             for i in range(8)]
    p = tmp_path / "cls.tfrecord"
    write_classification_records(p, pairs, encoding="raw")
    loader = make_grain_loader(str(p), 4, task="classification",
                               image_size=8, shuffle=False, num_epochs=1)
    images, labels = next(grain_batches(loader))
    assert images.shape == (4, 8, 8, 3)
    np.testing.assert_array_equal(labels, [0, 1, 2, 0])


def test_sharding_partitions(shards):
    def tokens_of(shard_index):
        loader = make_grain_loader(shards, 2, task="contrastive",
                                   image_size=8, seq_len=3, shuffle=False,
                                   num_epochs=1, shard_index=shard_index,
                                   shard_count=2)
        return {int(t[0]) for _, toks in grain_batches(loader) for t in toks}

    a, b = tokens_of(0), tokens_of(1)
    assert a and b and not (a & b)  # disjoint, non-empty halves


def test_shuffle_deterministic(shards):
    def order(seed):
        loader = make_grain_loader(shards, 3, task="contrastive",
                                   image_size=8, seq_len=3, seed=seed,
                                   num_epochs=1)
        return [int(t[0]) for _, toks in grain_batches(loader) for t in toks]

    assert order(7) == order(7)
    assert order(7) != order(8)


def test_subprocess_workers_match_inprocess(shards):
    """worker_count=1 spawns a real subprocess: exercises source pickling
    (__getstate__ drops fds) and produces identical batches."""
    def run(wc):
        loader = make_grain_loader(shards, 3, task="contrastive",
                                   image_size=8, seq_len=3, shuffle=False,
                                   num_epochs=1, worker_count=wc)
        return [t.tolist() for _, t in grain_batches(loader)]

    assert run(1) == run(0)


def test_cross_instance_resume(shards):
    """State saved from one loader restores into a FRESH loader (new source
    object, as after a process restart) — requires the stable __repr__
    grain uses to validate the data source."""
    mk = lambda: make_grain_loader(shards, 2, task="contrastive",
                                   image_size=8, seq_len=3, seed=3,
                                   num_epochs=1)
    it = iter(mk())
    next(it)
    state = it.get_state()
    rest = [t.tolist() for _, t in it]
    it2 = iter(mk())
    it2.set_state(state)
    assert [t.tolist() for _, t in it2] == rest


def test_checkpointable_resume(shards):
    loader = make_grain_loader(shards, 2, task="contrastive", image_size=8,
                               seq_len=3, seed=1, num_epochs=1)
    it = iter(loader)
    next(it)
    state = it.get_state()
    rest = [t.tolist() for _, t in it]
    it2 = iter(loader)
    it2.set_state(state)
    resumed = [t.tolist() for _, t in it2]
    assert resumed == rest


def test_consumed_state_survives_prefetch_readahead(shards):
    """ADVICE r2 #4: checkpointing the raw iterator's state after a
    PrefetchIterator had read ahead silently skipped up to `prefetch`
    batches on resume. CheckpointableGrainStream pairs states with batches
    and exposes the state of the last CONSUMED one."""
    from jimm_tpu.data.grain_pipeline import CheckpointableGrainStream
    loader = make_grain_loader(shards, 2, task="contrastive", image_size=8,
                               seq_len=3, seed=1, num_epochs=1)
    stream = CheckpointableGrainStream(iter(loader))
    producer = stream.batches()
    # simulate a prefetcher that pulled 3 batches ahead of the trainer
    buffered = [next(producer) for _ in range(3)]
    consumer = stream.track(iter(buffered))
    next(consumer)  # the trainer consumed exactly ONE batch
    state = stream.consumed_state

    it_truth = iter(loader)
    next(it_truth)  # ground truth: everything after batch 0
    want = [t.tolist() for _, t in it_truth]
    it_resumed = iter(loader)
    it_resumed.set_state(state)
    assert [t.tolist() for _, t in it_resumed] == want


def test_track_rejects_foreign_batch(shards):
    """ADVICE r3 #3: a batch the stream never produced must fail loudly,
    not popleft an empty deque / mispair states with batches."""
    from jimm_tpu.data.grain_pipeline import CheckpointableGrainStream
    loader = make_grain_loader(shards, 2, task="contrastive", image_size=8,
                               seq_len=3, seed=1, num_epochs=1)
    stream = CheckpointableGrainStream(iter(loader))
    foreign = [("not", "ours")]
    with pytest.raises(RuntimeError, match="not produced by batches"):
        next(stream.track(iter(foreign)))
