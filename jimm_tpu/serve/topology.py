"""Multi-chip serving topology: replica groups of (data=1, model=k) submeshes.

One host holds N visible devices; the serving engine wants R independent
*replicas* (inter-request parallelism — each replica computes a whole
micro-batch) that are each k-way *model-parallel* (intra-request parallelism
— one forward's matmuls sharded Megatron-style over k chips). The planner
here partitions the device list into R contiguous groups of k and builds one
``Mesh`` with axes ``("data", "model")`` = ``(1, k)`` per group; the forwards
built from the plan carry ``NamedSharding`` annotations from
:mod:`jimm_tpu.parallel.sharding` on both parameters (``sharded_copy`` with
the ``tp`` rules) and batches (a single sharded ``device_put`` per
micro-batch — never per-leaf transfers).

The degenerate ``replicas=1, model_parallel=1`` plan is *trivial*: callers
must take today's single-device path (plain jitted forward, no mesh, no
device_put) so single-chip serving stays byte-identical. ``plan_topology``
rejects infeasible splits (``R * k > n_devices``) with an error that names
the fix.

Plans are **revisable at runtime**: :meth:`TopologyPlan.revise` derives a
new plan (grow, shrink, or re-partition around a lost group) and
``build_replica_forwards`` over it produces the forward list that
``InferenceEngine.replan`` swaps in live — queued requests ride through,
and a warm AOT store makes the rebuild trace-free. The boot-time plan is
just the first revision.

FastUSP (PAPERS.md) motivates exactly this two-level split — replication for
throughput, tensor parallelism for per-request latency on towers too big for
one chip.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Sequence

import numpy as np

__all__ = ["ReplicaForward", "TopologyPlan", "build_replica_forwards",
           "plan_topology"]


@dataclasses.dataclass(frozen=True)
class TopologyPlan:
    """The outcome of partitioning ``n_devices`` into replica groups.

    ``device_groups`` holds the concrete device objects, one tuple of
    ``model_parallel`` devices per replica, in ``jax.devices()`` order
    (contiguous groups — on TPU, neighbouring devices share ICI links, so
    the model-axis collectives stay on-slice). Devices beyond
    ``replicas * model_parallel`` are left unused (reported, not silently
    dropped).
    """

    replicas: int
    model_parallel: int
    n_devices: int
    device_groups: tuple[tuple, ...]

    @property
    def is_trivial(self) -> bool:
        """True for the 1x1 plan: callers must use the single-device serve
        path (no mesh, no sharded transfers) — byte-compatible with a serve
        stack that never imported this module."""
        return self.replicas == 1 and self.model_parallel == 1

    @property
    def devices_used(self) -> int:
        return self.replicas * self.model_parallel

    def meshes(self) -> list:
        """One ``(data=1, model=k)`` mesh per replica group."""
        from jimm_tpu.parallel.mesh import make_mesh
        return [make_mesh({"data": 1, "model": self.model_parallel},
                          devices=list(group))
                for group in self.device_groups]

    def describe(self) -> dict:
        """Flat JSON-able summary for ready lines, healthz, and the
        MEASUREMENTS.jsonl topology fields."""
        return {"n_devices": self.n_devices, "replicas": self.replicas,
                "model_parallel": self.model_parallel,
                "devices_used": self.devices_used,
                "devices_unused": self.n_devices - self.devices_used}

    def revise(self, *, replicas: int | None = None,
               model_parallel: int | None = None,
               devices: Sequence | None = None) -> "TopologyPlan":
        """Derive a runtime revision of this plan: same partitioning rules,
        new shape and/or device set. Unspecified dimensions keep their
        current values; ``devices=None`` re-plans over this plan's own
        device list (flattened groups plus any unused tail is NOT
        recoverable here — pass the surviving ``jax.devices()`` subset
        explicitly when healing around lost hardware). Feed the result to
        :func:`build_replica_forwards` and then
        ``InferenceEngine.replan`` to apply it live."""
        if devices is None:
            devices = [d for group in self.device_groups for d in group]
        return plan_topology(
            self.replicas if replicas is None else replicas,
            self.model_parallel if model_parallel is None else model_parallel,
            devices=devices)


def plan_topology(replicas: int | None = None,
                  model_parallel: int | None = None,
                  devices: Sequence | None = None) -> TopologyPlan:
    """Partition the visible devices into ``replicas`` groups of
    ``model_parallel``.

    Defaults are conservative: ``replicas=1, model_parallel=1`` (the trivial
    single-device plan) — scaling out is an explicit operator choice via
    ``--replicas``/``--model-parallel``. Raises ``ValueError`` when the
    split does not fit the device count, naming both sides of the
    inequality so the error is actionable from a launch log.
    """
    if devices is None:
        import jax
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)
    replicas = 1 if replicas is None else int(replicas)
    model_parallel = 1 if model_parallel is None else int(model_parallel)
    if replicas < 1 or model_parallel < 1:
        raise ValueError(
            f"replicas ({replicas}) and model_parallel ({model_parallel}) "
            f"must both be >= 1")
    need = replicas * model_parallel
    if need > n:
        raise ValueError(
            f"topology needs replicas * model_parallel = {replicas} * "
            f"{model_parallel} = {need} devices but only {n} are visible; "
            f"lower --replicas/--model-parallel or raise the device count "
            f"(e.g. XLA_FLAGS=--xla_force_host_platform_device_count="
            f"{need} on CPU)")
    groups = tuple(tuple(devices[i * model_parallel:(i + 1) * model_parallel])
                   for i in range(replicas))
    return TopologyPlan(replicas=replicas, model_parallel=model_parallel,
                        n_devices=n, device_groups=groups)


class ReplicaForward:
    """One replica's warm forward: a single sharded ``device_put`` of the
    padded batch onto the replica's mesh, then the replica-local compiled
    forward (plain counting jit or a store-backed
    :class:`~jimm_tpu.aot.warmup.AotForward`).

    The batch transfer is ONE ``jax.device_put`` of the whole padded array
    with a ``NamedSharding`` — the input lands committed to the replica's
    devices, so the compiled program never sees a host fallback transfer
    and never migrates buffers between replicas.
    """

    def __init__(self, inner: Callable, mesh, batch_sharding):
        self._inner = inner
        self.mesh = mesh
        self.batch_sharding = batch_sharding

    def prepare_bucket(self, bucket: int) -> str:
        """Delegate AOT warm-start to the wrapped forward (engine warmup
        calls this per bucket); plain jitted inners report "compile"."""
        prepare = getattr(self._inner, "prepare_bucket", None)
        return prepare(bucket) if prepare is not None else "compile"

    @property
    def trace_count(self) -> Callable[[], int] | None:
        return getattr(self._inner, "trace_count", None)

    def __call__(self, padded):
        import jax
        x = jax.device_put(np.asarray(padded), self.batch_sharding)
        return self._inner(x)


def build_replica_forwards(model, plan: TopologyPlan, *, method: str,
                           item_shape: tuple[int, ...],
                           in_dtype: Any = np.float32, store=None,
                           label: str = ""
                           ) -> tuple[list[ReplicaForward],
                                      Callable[[], int]]:
    """Materialize the plan: one sharded model copy + warm forward per
    replica group.

    Each replica gets an independent parameter copy placed on its submesh
    via :func:`~jimm_tpu.parallel.sharding.sharded_copy` with the ``tp``
    (Megatron tensor-parallel) rules — on a ``model=1`` submesh that
    degenerates to whole-params-on-one-chip, which is exactly replicated
    serving. With ``store`` set, every replica forward is an
    :class:`~jimm_tpu.aot.warmup.AotForward` keyed on the replica mesh (all
    replicas share one fingerprint — same shapes, same mesh shape — so one
    write-through warms every replica and the next restart).

    Returns ``(forwards, trace_count)`` where ``trace_count`` sums fresh
    traces across replicas: the number the engine exports as
    ``compile_count`` and the zero-recompiles-after-warmup checks read.
    """
    from jax.sharding import NamedSharding

    from jimm_tpu.parallel.sharding import TENSOR_PARALLEL, sharded_copy

    batch_spec = TENSOR_PARALLEL.spec(
        "batch", *([None] * len(tuple(item_shape))))
    forwards: list[ReplicaForward] = []
    counters: list[Callable[[], int]] = []
    for mesh in plan.meshes():
        replica_model = sharded_copy(model, mesh, TENSOR_PARALLEL)
        batch_sharding = NamedSharding(mesh, batch_spec)
        if store is not None:
            from jimm_tpu.aot.warmup import AotForward
            inner = AotForward(replica_model, method=method,
                               item_shape=item_shape, in_dtype=in_dtype,
                               store=store, label=label, mesh=mesh,
                               in_sharding=batch_sharding)
            counters.append(inner.trace_count)
        else:
            from jimm_tpu.serve.engine import counting_forward
            inner, traces = counting_forward(replica_model, method)
            counters.append(traces)
        forwards.append(ReplicaForward(inner, mesh, batch_sharding))
    return forwards, lambda: sum(c() for c in counters)
