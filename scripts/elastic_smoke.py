"""CI drill for goodput-driven elastic adaptation (ISSUE 12).

Two legs in one process, both through shipped code paths:

**Train leg — shrink between attempts.** A control run on a ``data=8`` mesh
is the oracle; then ``supervise --elastic --shrink-plan 8,4 --adapt`` runs
the same job with ``preempt@2`` injected. Attempt 1 plans ``data=8``, the
preemption's grace-window save commits, and attempt 2 — seeing only 4
surviving devices — replans ``data=4`` and restores the 8-device checkpoint
onto the smaller mesh (resharding-on-restore). The finished run must match
the control step-for-step: same losses (rtol 2e-4) and same batch content
hashes, with ``restarts_total``, ``topology_changes_total``,
``checkpoint_topology_changes_total`` all >= 1 and the GoodputAdvisor's
decision counter present (auditable, possibly zero decisions).

**Serve leg — kill one replica of a 2x2 topology.** A 2-replica x
2-model-parallel engine over a warm AOT store (populated by a first life,
so the serving life starts with zero fresh traces) gets a self-heal
factory, serves traffic, then has one replica's forward replaced with a
raiser. The watchdog restarts it, fences it, probes it, rebuilds from the
store, and replans around it — the engine must keep answering throughout,
finish with full capacity (``replicas_alive == 2`` in the rendered
Prometheus text, ``replans_total >= 1``, no dead replicas) and pay ZERO
fresh compiles for the heal (the rebuild deserializes store artifacts).

Exits nonzero with a JSON error line on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.elastic_smoke
"""

from __future__ import annotations

import json
import os
import re
import sys
import tempfile
from pathlib import Path

RTOL = 2e-4
STEPS = 6
REPLICAS = 2
MODEL_PARALLEL = 2


def fail(msg: str) -> int:
    print(json.dumps({"metric": "elastic_smoke", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def read_metrics(path: Path) -> dict[int, dict]:
    with open(path) as f:
        records = [json.loads(line) for line in f]
    # later rows win duplicate steps: a grace-window step's row is
    # superseded by its resumed re-run
    return {rec["step"]: rec for rec in records}


def check_against_control(ctl: dict[int, dict], got: dict[int, dict],
                          steps, what: str) -> str | None:
    for step in steps:
        if step not in got:
            return f"{what}: step {step} missing from resumed metrics"
        if abs(got[step]["loss"] - ctl[step]["loss"]) > \
                RTOL * abs(ctl[step]["loss"]):
            return (f"{what}: loss diverged at step {step}: "
                    f"{got[step]['loss']} vs control {ctl[step]['loss']}")
        if got[step].get("batch_fingerprint") != \
                ctl[step].get("batch_fingerprint"):
            return (f"{what}: batch fingerprint mismatch at step {step} — "
                    f"the shrunk run replayed or skipped batches")
    return None


def train_leg(tmp: Path) -> tuple[str | None, dict]:
    from jimm_tpu import cli, obs

    common = ["train", "--preset", "vit-tiny-patch16-224", "--tiny",
              "--batch-size", "8", "--steps", str(STEPS),
              "--save-every", "1", "--log-every", "0", "--seed", "7",
              "--batch-fingerprint"]

    control_file = tmp / "control.jsonl"
    rc = cli.main(common + ["--mesh", "data=8", "--rules", "dp",
                            "--metrics-file", str(control_file)])
    if rc:
        return f"control train exited {rc}", {}
    ctl = read_metrics(control_file)
    if set(ctl) != set(range(STEPS)):
        return f"control logged steps {sorted(ctl)}, expected 0..{STEPS - 1}", {}

    drill_file = tmp / "elastic.jsonl"
    rc = cli.main(["supervise", "--max-restarts", "2",
                   "--backoff-base-s", "0.01", "--seed", "0",
                   "--elastic", "--shrink-plan", "8,4", "--adapt", "--"]
                  + common + ["--ckpt-dir", str(tmp / "ckpt"),
                              "--metrics-file", str(drill_file),
                              "--inject-faults", "preempt@2"])
    if rc:
        return f"supervised elastic drill exited {rc}", {}
    err = check_against_control(ctl, read_metrics(drill_file),
                                range(STEPS), "elastic drill")
    if err:
        return err, {}

    snap = obs.snapshot()
    if snap.get("jimm_train_restarts_total", 0) < 1:
        return "restarts_total is 0 after a preemption", {}
    if snap.get("jimm_train_topology_changes_total", 0) < 1:
        return ("topology_changes_total is 0 — the supervisor never "
                "replanned the mesh"), {}
    if snap.get("jimm_train_checkpoint_topology_changes_total", 0) < 1:
        return ("checkpoint_topology_changes_total is 0 — the restore "
                "never crossed mesh shapes"), {}
    if "jimm_train_goodput_advisor_decisions_total" not in snap:
        return ("advisor decision counter missing from the snapshot — "
                "--adapt never instantiated the GoodputAdvisor"), {}
    return None, {
        "restarts_total": snap.get("jimm_train_restarts_total"),
        "topology_changes_total": snap.get(
            "jimm_train_topology_changes_total"),
        "checkpoint_topology_changes_total": snap.get(
            "jimm_train_checkpoint_topology_changes_total"),
        "advisor_decisions_total": snap.get(
            "jimm_train_goodput_advisor_decisions_total"),
    }


def serve_leg() -> tuple[str | None, dict]:
    import asyncio

    import numpy as np
    from flax import nnx

    from jimm_tpu import CLIP, preset
    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.cli import _tiny_override
    from jimm_tpu.serve import (BucketTable, InferenceEngine,
                                build_replica_forwards, plan_topology)

    cfg = _tiny_override(preset("clip-vit-base-patch16"))
    model = CLIP(cfg, rngs=nnx.Rngs(0))
    size = cfg.vision.image_size
    plan = plan_topology(REPLICAS, MODEL_PARALLEL)

    with tempfile.TemporaryDirectory(prefix="jimm-elastic-serve-") as root:
        store = ArtifactStore(root)

        def build():
            return build_replica_forwards(
                model, plan, method="encode_image",
                item_shape=(size, size, 3), store=store,
                label="elastic_smoke")

        # life 1: populate the store through write-through warmup
        forwards1, traces1 = build()
        warm1 = InferenceEngine(forwards1, item_shape=(size, size, 3),
                                buckets=BucketTable((1, 4)),
                                max_delay_ms=2.0, trace_count=traces1)
        warm1.warmup_blocking()
        if not store.entries():
            return "life-1 warmup wrote nothing to the store", {}

        # serving life: warm start, then self-heal from the same store
        forwards, traces = build()
        engine = InferenceEngine(forwards, item_shape=(size, size, 3),
                                 buckets=BucketTable((1, 4)),
                                 max_delay_ms=2.0, trace_count=traces)
        engine.warmup_blocking()
        if traces():
            return (f"warm start paid {traces()} fresh traces; the store "
                    f"did not round-trip"), {}
        engine.set_heal(build)

        x = np.random.RandomState(0).rand(size, size, 3).astype(np.float32)

        class Raiser:
            def __call__(self, _):
                raise RuntimeError("injected: replica device lost")

        async def drive():
            await engine.start()
            answered = errors = 0
            try:
                for _ in range(8):
                    await engine.submit(x)
                    answered += 1
                # kill replica 1 and keep driving until the watchdog
                # fences it and the self-heal replans around it
                engine._replicas[1].forward = Raiser()
                for _ in range(400):
                    try:
                        await engine.submit(x)
                        answered += 1
                    except RuntimeError:
                        errors += 1
                    if engine.metrics.count("replans_total") >= 1:
                        break
                    await asyncio.sleep(0.01)
                else:
                    return None, answered, errors, "no replan happened"
                # healed: full capacity, every request answered
                post = []
                for _ in range(16):
                    post.append(np.asarray(await engine.submit(x)))
                    answered += 1
                return post, answered, errors, None
            finally:
                await engine.stop()

        post, answered, errors, err = asyncio.run(drive())
        if err:
            return f"serve leg: {err} (answered={answered}, " \
                   f"errors={errors})", {}
        if engine.dead_replicas():
            return (f"dead replicas after heal: "
                    f"{engine.dead_replicas()}"), {}
        if engine.n_replicas != REPLICAS:
            return (f"replan restored {engine.n_replicas} replicas, "
                    f"wanted {REPLICAS}"), {}
        # zero fresh compiles for the heal: replan rebinds compile_count to
        # the rebuilt forwards' counter, which must still read 0 (every
        # bucket of every replica deserialized from the store)
        if engine.trace_count():
            return (f"heal paid {engine.trace_count()} fresh compile(s); "
                    f"the rebuild did not come from the store"), {}
        want = np.asarray(model.encode_image(x[None]))[0]
        for out in post:
            if not np.allclose(out, want, rtol=1e-4, atol=1e-4):
                return "post-heal output disagrees with the model", {}
        text = engine.metrics.render_prometheus()
        alive = re.search(r"^jimm_serve_replicas_alive (\S+)$", text,
                          re.MULTILINE)
        if alive is None or float(alive.group(1)) != REPLICAS:
            return (f"jimm_serve_replicas_alive != {REPLICAS} in the "
                    f"Prometheus text (got "
                    f"{alive.group(1) if alive else 'missing'})"), {}
        replans = re.search(r"^jimm_serve_replans_total (\S+)$", text,
                            re.MULTILINE)
        if replans is None or float(replans.group(1)) < 1:
            return "jimm_serve_replans_total < 1 in the Prometheus text", {}
        return None, {
            "requests_answered": answered,
            "errors_during_fence": errors,
            "replans_total": int(float(replans.group(1))),
            "replicas_alive": int(float(alive.group(1))),
            "heal_compiles": engine.trace_count(),
        }


def main() -> int:
    # must land before jax initializes its backends
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")
    import jax
    if jax.device_count() < 8:
        return fail(f"need 8 virtual devices, have {jax.device_count()} — "
                    f"was XLA_FLAGS set before another jax import?")

    tmp = Path(tempfile.mkdtemp(prefix="elastic_smoke_"))
    err, train_summary = train_leg(tmp)
    if err:
        return fail(f"train leg: {err}")
    err, serve_summary = serve_leg()
    if err:
        return fail(f"serve leg: {err}")
    print(json.dumps({"metric": "elastic_smoke", "value": 1.0,
                      "train": train_summary, "serve": serve_summary}),
          flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
