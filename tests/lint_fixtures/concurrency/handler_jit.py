"""Interprocedural JL008 seed: the jit construction hides in a helper two
hops from the do_GET handler — per-file JL008 can't see the handler, the
call graph can. The module-scope jit is the clean shape."""

import jax

_FORWARD = jax.jit(lambda x: x * 2)  # built once at import: clean


class FixtureHandler:
    def do_GET(self):
        return self._respond()

    def _respond(self):
        return self._make_fn()

    def _make_fn(self):
        return jax.jit(lambda x: x + 1)  # JL008: fresh wrapper per request

    def fast_path(self, x):
        return _FORWARD(x)
