"""Service facade gluing the vector store and the top-k searcher into the
serving stack, plus the ``jimm_retrieval`` observability namespace.

:class:`RetrievalService` is what ``serve --index`` constructs and
:class:`~jimm_tpu.serve.server.ServingServer` consults for ``/v1/search``:
it owns the loaded index, the warm searcher — exact
:class:`~jimm_tpu.retrieval.topk.IndexSearcher`, approximate
:class:`~jimm_tpu.retrieval.ann.ivf.IvfIndexSearcher`, or budgeted
:class:`~jimm_tpu.retrieval.tier.TieredSearcher` (which adds the
``jimm_tier_*`` residency gauges), per ``serve --index-mode`` — and the
metric series the obs docs list:

- ``jimm_retrieval_search_total`` / ``jimm_retrieval_embed_total``
  counters (embed counts rows, not requests: a bulk ``/v1/embed`` of 16
  images is 16),
- ``jimm_retrieval_index_size`` / ``jimm_retrieval_index_segments`` /
  ``jimm_retrieval_index_staleness_seconds`` gauges (staleness = seconds
  since the manifest last changed; a serving process holds the index
  snapshot it loaded, so a growing staleness under active writers says
  "restart or reload me"),
- in ivf mode, ``jimm_retrieval_ivf_nprobe`` /
  ``jimm_retrieval_ivf_candidate_frac`` /
  ``jimm_retrieval_ivf_recall_proxy`` gauges tracking the most recent
  search: probe width, fraction of the corpus rescored, and the fill
  ratio (results found / k — a cheap online recall proxy; the measured
  recall@10 lives in MEASUREMENTS.jsonl via ``scripts/ann_frontier.py``),
- the ``retrieval_topk`` / ``retrieval_ivf`` span around every scoring
  call (device scan + host merge), in ``jimm_spans_*`` like every span.

Everything here is callable from HTTP handler threads (blocking is fine;
the engine's event loop is never entered) and from the CLI.
"""

from __future__ import annotations

import time
from typing import Any

import numpy as np

from jimm_tpu.retrieval.store import (LoadedIndex, RetrievalStoreError,
                                      VectorStore)
from jimm_tpu.retrieval.topk import IndexSearcher

__all__ = ["RetrievalService", "retrieval_metrics"]


def retrieval_metrics():
    """The ``jimm_retrieval`` registry's (search_total, embed_total)
    counters — shared by the service and the bulk-embed endpoint."""
    from jimm_tpu import obs
    reg = obs.get_registry("jimm_retrieval")
    return reg.counter("search_total"), reg.counter("embed_total")


class RetrievalService:
    """One named index, searchable: loaded snapshot + warm searcher +
    metrics. Built once at serve startup (``from_store``) or directly in
    tests/benches with a pre-built searcher. ``mode`` is ``"exact"``
    (streaming full-scan top-k) or ``"ivf"`` (two-stage approximate; the
    searcher must then be an ``IvfIndexSearcher`` and requests may carry
    a per-call ``nprobe``)."""

    def __init__(self, index: LoadedIndex, searcher: Any, *,
                 store: VectorStore | None = None, mode: str = "exact",
                 nprobe: int | None = None):
        from jimm_tpu import obs
        if mode not in ("exact", "ivf", "tiered"):
            raise ValueError(f"mode must be 'exact', 'ivf', or 'tiered'; "
                             f"got {mode!r}")
        self.index = index
        self.searcher = searcher
        self.store = store
        self.mode = mode
        self.search_counter, self.embed_counter = retrieval_metrics()
        reg = obs.get_registry("jimm_retrieval")
        reg.gauge("index_size", lambda: float(len(self.index)))
        reg.gauge("index_segments", fn=self._segments_now)
        reg.gauge("index_staleness_seconds", fn=self._staleness_now)
        if mode in ("ivf", "tiered"):
            from jimm_tpu.retrieval.ann.ivf import DEFAULT_NPROBE
            cap = searcher.nprobe_max
            self.default_nprobe = min(
                int(nprobe) if nprobe is not None else DEFAULT_NPROBE, cap)
            if self.default_nprobe < 1:
                raise ValueError(f"nprobe must be >= 1; got {nprobe}")
            stat = lambda key: lambda: float(  # noqa: E731
                self.searcher.last_stats.get(key, 0.0))
            reg.gauge("ivf_nprobe", fn=stat("nprobe"))
            reg.gauge("ivf_candidate_frac", fn=stat("candidate_frac"))
            # fill ratio (found / k) — online recall proxy: probing too
            # few clusters surfaces as under-filled result rows long
            # before an offline frontier run quantifies the recall loss
            reg.gauge("ivf_recall_proxy", fn=stat("fill_ratio"))
        else:
            self.default_nprobe = None

    @classmethod
    def from_store(cls, store: VectorStore, name: str, *, k: int = 10,
                   buckets=(1,), block_n: int | None = None,
                   plan: Any = None, aot_store: Any = None,
                   mode: str = "exact", nprobe: int | None = None,
                   nprobe_max: int = 32,
                   device_budget_bytes: int | None = None,
                   host_budget_bytes: int | None = None
                   ) -> "RetrievalService":
        index = store.load(name)
        if mode in ("ivf", "tiered"):
            loaded = store.codebook(name)
            if loaded is None:
                raise RetrievalStoreError(
                    f"index {name!r} has no trained codebook — run "
                    f"`jimm-tpu index train-centroids` (and `build-ivf`) "
                    f"before serving with --index-mode {mode}")
            centroids, _meta = loaded
            assign = store.load_assignments(name)
            if mode == "tiered":
                from jimm_tpu.retrieval.tier import TieredSearcher
                searcher: Any = TieredSearcher(
                    index, centroids, assign, k=k, nprobe_max=nprobe_max,
                    buckets=buckets, block_n=block_n,
                    device_budget_bytes=device_budget_bytes,
                    host_budget_bytes=host_budget_bytes,
                    aot_store=aot_store, artifacts=store.artifacts)
            else:
                from jimm_tpu.retrieval.ann.ivf import IvfIndexSearcher
                searcher = IvfIndexSearcher(
                    index, centroids, assign, k=k, nprobe_max=nprobe_max,
                    buckets=buckets, block_n=block_n, plan=plan,
                    aot_store=aot_store)
        else:
            searcher = IndexSearcher(index, k=k, buckets=buckets,
                                     block_n=block_n, plan=plan,
                                     aot_store=aot_store)
        return cls(index, searcher, store=store, mode=mode, nprobe=nprobe)

    # -- gauges -----------------------------------------------------------

    def _segments_now(self) -> float:
        if self.store is None:
            return 1.0
        try:
            return float(self.store.stats(self.index.name)["segments"])
        except Exception:  # noqa: BLE001 — a gauge must never raise
            return 0.0

    def _staleness_now(self) -> float:
        """Seconds since the *on-disk* manifest last changed — reads
        through to the store so concurrent writers move this gauge even
        though the serving snapshot is pinned."""
        updated = self.index.updated
        if self.store is not None:
            try:
                updated = float(
                    self.store.manifest(self.index.name)["updated"])
            except Exception:  # noqa: BLE001
                pass
        return max(0.0, round(time.time() - updated, 3))

    # -- lifecycle --------------------------------------------------------

    def warmup(self) -> dict[int, str]:
        """Warm every (replica, bucket); the serve ready line and healthz
        report the per-bucket sources."""
        return self.searcher.warmup()

    def trace_count(self) -> int:
        return self.searcher.trace_count()

    def describe(self) -> dict:
        out = {"index": self.index.name, "rows": len(self.index),
               "dim": self.index.dim, "dtype": self.index.dtype,
               "metric": self.index.metric, "k": self.searcher.k,
               "block_n": self.searcher.block_n,
               "buckets": list(self.searcher.buckets),
               "partitions": len(getattr(self.searcher, "searchers", [0])),
               "mode": self.mode,
               "staleness_s": self._staleness_now()}
        if self.mode in ("ivf", "tiered"):
            out["nprobe"] = self.default_nprobe
            out["nprobe_max"] = self.searcher.nprobe_max
            out["clusters"] = self.searcher.n_clusters
        if self.mode == "tiered":
            out["resident_bytes"] = self.searcher.resident_bytes()
            out["tiers"] = self.searcher.tier_plan().describe()
        return out

    # -- queries ----------------------------------------------------------

    def search_blocking(self, queries: np.ndarray, k: int | None = None,
                        nprobe: int | None = None
                        ) -> tuple[np.ndarray, list[list[str]]]:
        """Top-k ids + scores for a ``(D,)`` or ``(B, D)`` query batch.
        ``k`` may trim below the searcher's compiled k but never exceed it
        (the device program's carry width is fixed at build time). In ivf
        mode ``nprobe`` widens/narrows the probe per call — a runtime
        scalar up to the compiled ``nprobe_max``, never a recompile. Call
        from a handler thread or the CLI — this blocks on the device."""
        from jimm_tpu import obs
        from jimm_tpu.serve.admission import RequestError
        queries = np.asarray(queries, np.float32)
        if queries.ndim == 1:
            queries = queries[None, :]
        if queries.ndim != 2 or queries.shape[1] != self.index.dim:
            raise RequestError(
                f"query must have dim {self.index.dim} (index "
                f"{self.index.name!r}); got shape {tuple(queries.shape)}")
        if not np.all(np.isfinite(queries)):
            raise RequestError("query contains non-finite values")
        k_eff = self.searcher.k if k is None else int(k)
        if k_eff < 1 or k_eff > self.searcher.k:
            raise RequestError(
                f"k must be in [1, {self.searcher.k}] (the searcher's "
                f"compiled carry width); got {k_eff}")
        if self.mode in ("ivf", "tiered"):
            np_eff = self.default_nprobe if nprobe is None else int(nprobe)
            if np_eff < 1 or np_eff > self.searcher.nprobe_max:
                raise RequestError(
                    f"nprobe must be in [1, {self.searcher.nprobe_max}] "
                    f"(the searcher's compiled probe width); got {np_eff}")
            span_name = ("retrieval_tier" if self.mode == "tiered"
                         else "retrieval_ivf")
            with obs.span(span_name):
                values, _indices, ids = self.searcher.search(
                    queries, nprobe=np_eff)
        else:
            if nprobe is not None:
                raise RequestError(
                    "nprobe is only valid in ivf index mode (this server "
                    "runs --index-mode exact)")
            with obs.span("retrieval_topk"):
                values, _indices, ids = self.searcher.search(queries)
        self.search_counter.inc(queries.shape[0])
        return values[:, :k_eff], [row[:k_eff] for row in ids]
