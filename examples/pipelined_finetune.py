"""Pipelined fine-tuning of a pretrained checkpoint.

Loads an HF SigLIP checkpoint with RUNTIME overrides (execution strategy,
not architecture — `configs.RUNTIME_FIELDS`): interleaved pipeline
parallelism with the circular placement baked into parameter storage at
load, remat, and dropout for fine-tuning. The reference can only load a
checkpoint into the exact execution mode it was authored for (none — it has
no pipeline/remat machinery at all, SURVEY §2.3).

Offline demo: builds a tiny random-init HF checkpoint first so no network
is needed; swap `make_demo_checkpoint()` for a real repo id in practice.

Run (single host / CPU mesh):
  XLA_FLAGS=--xla_force_host_platform_device_count=8 \
  python examples/pipelined_finetune.py --steps 10
"""

from __future__ import annotations

import jimm_tpu.utils.env

jimm_tpu.utils.env.configure_platform()

import argparse
import tempfile

import numpy as np

from jimm_tpu import SigLIP
from jimm_tpu.parallel import PIPELINE, make_mesh, shard_batch, use_sharding
from jimm_tpu.train import (MetricsLogger, OptimizerConfig,
                            make_contrastive_train_step, make_optimizer)


def make_demo_checkpoint(tmpdir: str) -> str:
    """Random-init 8-layer SigLIP saved in HF format (offline stand-in for
    e.g. 'google/siglip-base-patch16-256')."""
    from transformers import SiglipConfig, SiglipModel

    cfg = SiglipConfig(
        vision_config=dict(hidden_size=64, intermediate_size=128,
                           num_hidden_layers=8, num_attention_heads=2,
                           image_size=32, patch_size=16),
        text_config=dict(hidden_size=64, intermediate_size=128,
                         num_hidden_layers=8, num_attention_heads=2))
    SiglipModel(cfg).eval().save_pretrained(tmpdir, safe_serialization=True)
    return tmpdir


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--checkpoint", default=None,
                   help="HF repo id or local dir (default: tiny offline demo)")
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--stages", type=int, default=4)
    p.add_argument("--virtual", type=int, default=2)
    p.add_argument("--microbatches", type=int, default=4)
    args = p.parse_args()

    src = args.checkpoint or make_demo_checkpoint(tempfile.mkdtemp())

    mesh = make_mesh({"data": -1, "stage": args.stages})

    # runtime= changes HOW the checkpoint executes, never its architecture;
    # pp_stages bakes the interleaved placement into storage at load
    model = SigLIP.from_pretrained(
        src, mesh=mesh, rules=PIPELINE,
        runtime=dict(remat=True, remat_policy="dots", dropout=0.1,
                     pipeline=True, pp_microbatches=args.microbatches,
                     pp_virtual=args.virtual, pp_stages=args.stages))
    model.set_attributes(deterministic=False)  # fine-tuning: dropout active

    optimizer = make_optimizer(model, OptimizerConfig(
        learning_rate=1e-4, warmup_steps=2, total_steps=args.steps))
    step = make_contrastive_train_step("siglip", donate=True)
    log = MetricsLogger()

    rng = np.random.RandomState(0)
    v = model.config.vision
    with use_sharding(mesh, PIPELINE):
        for i in range(args.steps):
            # hand shard_batch HOST arrays: a jnp input would round-trip
            # device -> host -> sharded placement every step
            images = shard_batch(
                rng.randn(args.batch_size, v.image_size, v.image_size, 3)
                .astype(np.float32), mesh)
            text = shard_batch(
                rng.randint(1, model.config.text.vocab_size,
                            size=(args.batch_size,
                                  model.config.text.context_length))
                .astype(np.int32), mesh)
            metrics = step(model, optimizer, images, text)
            log.log(i, loss=float(metrics["loss"]))


if __name__ == "__main__":
    main()
