"""CI tier-1 smoke for IVF approximate retrieval (docs/retrieval.md).

Forces 8 virtual CPU devices, builds a 50k-vector clustered index, and
proves the ANN subsystem end to end in one process:

1. **Store + codebook**: a tmp :class:`VectorStore` gets 40k clustered
   unit rows, trains a 128-centroid codebook (seeded, deterministic),
   cluster-orders the existing segment with ``build_ivf``, then appends
   10k more rows through the cluster-aware write path (runs recorded at
   add time — staleness stays 0).
2. **Life 1**: an ivf-mode :class:`RetrievalService` over
   ``plan_topology(2, 2)`` warms every (replica, bucket) against a tmp
   AOT store; write-through populates it (one fingerprint per bucket —
   equally-padded cluster partitions share programs).
3. **Warm restart**: a second service reaches readiness with ZERO fresh
   traces and every bucket sourced ``"aot"``.
4. **Recall**: warm-service top-10 at the smoke ``nprobe`` vs the exact
   NumPy oracle over 128 mixture queries — recall@10 must be ≥ 0.95.
5. **Runtime nprobe**: sweeping nprobe across the compiled probe ceiling
   on the warm service must add ZERO traces (nprobe is a runtime scalar;
   every value shares the padded layout's one program).
6. **jax-free stats**: ``jimm-tpu index stats`` in a subprocess must
   report the ann block (clusters, staleness, advice) without importing
   jax.

Exits nonzero (with a JSON error line) on any violation.

Usage:
    JAX_PLATFORMS=cpu python -m scripts.ann_smoke
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

ROWS_BASE = 40_000
ROWS_ADD = 10_000
DIM = 64
CENTERS = 128          # mixture components in the synthetic corpus
CLUSTERS = 128         # trained codebook size (~sqrt(50k) rounded up)
K = 10
BLOCK_N = 128
NPROBE_SMOKE = 8
NPROBE_MAX = 16
REPLICAS = 2
MODEL_PARALLEL = 2
RECALL_QUERIES = 128
RECALL_FLOOR = 0.95


def fail(msg: str) -> int:
    print(json.dumps({"metric": "ann_smoke", "value": 0.0,
                      "error": msg}), flush=True)
    return 1


def main() -> int:
    # must land before jax initializes its backends
    os.environ.setdefault("XLA_FLAGS",
                          "--xla_force_host_platform_device_count=8")

    import jax
    import numpy as np

    from jimm_tpu.aot import ArtifactStore
    from jimm_tpu.retrieval import RetrievalService, VectorStore
    from jimm_tpu.retrieval.ann import clustered_rows, train_centroids
    from jimm_tpu.serve import plan_topology

    if jax.device_count() < REPLICAS * MODEL_PARALLEL:
        return fail(f"need {REPLICAS * MODEL_PARALLEL} devices, have "
                    f"{jax.device_count()} — was XLA_FLAGS="
                    f"--xla_force_host_platform_device_count=8 set before "
                    f"another jax import?")

    total = ROWS_BASE + ROWS_ADD
    corpus, centers = clustered_rows(total, DIM, CENTERS, seed=3)
    queries, _ = clustered_rows(RECALL_QUERIES, DIM, CENTERS, seed=11,
                                center_mat=centers)
    ids = [f"doc{i:05d}" for i in range(total)]
    plan = plan_topology(REPLICAS, MODEL_PARALLEL)
    buckets = (1, 8)

    with tempfile.TemporaryDirectory(prefix="jimm-ann-smoke-") as root:
        idx_root = os.path.join(root, "index")
        vstore = VectorStore(idx_root)
        vstore.create("corpus", DIM)
        # segment 1 predates the codebook: build_ivf must retrofit it
        vstore.add("corpus", ids[:ROWS_BASE], corpus[:ROWS_BASE])
        codebook = train_centroids(corpus[:ROWS_BASE], CLUSTERS, seed=0)
        vstore.set_codebook("corpus", codebook, trained_rows=ROWS_BASE)
        report = vstore.build_ivf("corpus")
        if report["rewritten"] != 1:
            return fail(f"build_ivf should rewrite the pre-codebook "
                        f"segment; report={report}")
        # segment 2 rides the cluster-aware write path (runs at add time)
        vstore.add("corpus", ids[ROWS_BASE:], corpus[ROWS_BASE:])
        status = vstore.ann_status("corpus")
        if status["unassigned_rows"]:
            return fail(f"cluster-aware add left unassigned rows: "
                        f"{status}")
        store = ArtifactStore(os.path.join(root, "aot"))

        # --- life 1: populate the AOT store through warmup ---------------
        svc1 = RetrievalService.from_store(
            vstore, "corpus", k=K, buckets=buckets, block_n=BLOCK_N,
            plan=plan, aot_store=store, mode="ivf", nprobe=NPROBE_SMOKE,
            nprobe_max=NPROBE_MAX)
        svc1.warmup()
        if not store.entries():
            return fail("life-1 warmup wrote nothing to the AOT store")
        fps = {s.key_for(b).fingerprint()
               for s in svc1.searcher.searchers for b in buckets}
        if len(fps) != len(buckets):
            return fail(f"replica partitions must share one fingerprint "
                        f"per bucket; got {len(fps)} for {len(buckets)} "
                        f"buckets")

        # --- warm restart: ivf executables round-trip ---------------------
        service = RetrievalService.from_store(
            vstore, "corpus", k=K, buckets=buckets, block_n=BLOCK_N,
            plan=plan, aot_store=store, mode="ivf", nprobe=NPROBE_SMOKE,
            nprobe_max=NPROBE_MAX)
        warm = service.warmup()
        if service.trace_count():
            return fail(f"warm restart paid {service.trace_count()} fresh "
                        f"traces; ivf artifacts did not round-trip")
        bad = {b: s for b, s in warm.items() if s != "aot"}
        if bad:
            return fail(f"warm restart buckets not fully AOT-sourced: "
                        f"{bad}")

        # --- recall@10 vs the exact oracle --------------------------------
        # (host argsort is the *oracle*, not the serving path)
        oracle = np.argsort(-(queries @ corpus.T), axis=1,
                            kind="stable")[:, :K]
        oracle_ids = [{ids[j] for j in row} for row in oracle]
        hits = 0
        for start in range(0, RECALL_QUERIES, buckets[-1]):
            batch = queries[start:start + buckets[-1]]
            _vals, id_rows = service.search_blocking(batch)
            for qi, row in enumerate(id_rows):
                hits += len(set(row) & oracle_ids[start + qi])
        recall = hits / (RECALL_QUERIES * K)
        if recall < RECALL_FLOOR:
            return fail(f"recall@{K} = {recall:.4f} < {RECALL_FLOOR} at "
                        f"nprobe={NPROBE_SMOKE}")

        # --- runtime nprobe: one padded layout, zero recompiles -----------
        traces_before = service.trace_count()
        for nprobe in (1, 2, NPROBE_SMOKE, NPROBE_MAX):
            service.search_blocking(queries[:buckets[-1]], nprobe=nprobe)
        nprobe_delta = service.trace_count() - traces_before
        if nprobe_delta:
            return fail(f"nprobe sweep retraced {nprobe_delta}x — nprobe "
                        f"must be a runtime scalar on one program")

        # --- `jimm-tpu index stats` stays jax-free ------------------------
        code = (
            "import json, sys\n"
            "from jimm_tpu.retrieval.cli import main\n"
            "rc = main(['stats', '--store', sys.argv[1], 'corpus'])\n"
            "assert 'jax' not in sys.modules, 'index stats dragged in jax'\n"
            "sys.exit(rc)\n")
        proc = subprocess.run(
            [sys.executable, "-c", code, idx_root],
            capture_output=True, text=True,
            env={**os.environ, "JAX_PLATFORMS": ""})
        if proc.returncode != 0:
            return fail(f"jax-free `index stats` failed: "
                        f"{proc.stderr.strip()[-300:]}")
        stats = json.loads(proc.stdout)
        if stats.get("ann", {}).get("clusters") != CLUSTERS:
            return fail(f"index stats ann block wrong: {stats.get('ann')}")

        print(json.dumps({
            "metric": "ann_smoke", "value": 1.0,
            "rows": total, "dim": DIM, "clusters": CLUSTERS, "k": K,
            "block_n": BLOCK_N, "nprobe": NPROBE_SMOKE,
            "nprobe_max": NPROBE_MAX,
            "topology": plan.describe(),
            "recall_at_10": round(recall, 4),
            "candidate_frac": service.searcher.last_stats.get(
                "candidate_frac"),
            "staleness": status["staleness"],
            "warm_restart": {str(b): s for b, s in sorted(warm.items())},
            "store_entries": len(store.entries()),
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
