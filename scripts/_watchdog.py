"""Hard watchdog shared by the TPU measurement entry points.

Deliberately imports NOTHING beyond the stdlib: every caller arms the
watchdog BEFORE the first jax/jimm import, because backend plugin discovery
can touch the axon tunnel whose failure mode is an indefinite hang that only
an external nudge interrupts. (bench.py, scripts/flash_compiled_check.py,
and scripts/profile_step.py all key their retry logic on the exit codes
armed here — keep the semantics in this one place.)

Two mechanisms, belt and braces:

- SIGALRM: fires in the main thread's eval loop. Sufficient when the hang
  is at a point that returns to the interpreter (or an EINTR-able syscall).
- A daemon thread: Python signal handlers only run when the MAIN thread
  re-enters the bytecode loop; a PJRT wait parked on a condition variable
  is signal-restarted and never returns, so SIGALRM alone can sit armed
  forever while the tunnel is down. The thread needs only the GIL (which a
  blocked-but-released C call isn't holding) to emit and _exit.
"""

from __future__ import annotations

import os
import signal
import threading
from typing import Callable


def hard_watchdog(seconds: int, exit_code: int,
                  emit: Callable[[], None]) -> Callable[[], None]:
    """After ``seconds`` with no disarm, call ``emit()`` (print the failure
    evidence — it must not raise) and ``os._exit(exit_code)``. Returns a
    ``disarm()`` that cancels both mechanisms."""
    fired = threading.Lock()  # emit exactly once even if both fire

    def die():
        if not fired.acquire(blocking=False):
            return
        try:
            emit()
        finally:
            os._exit(exit_code)

    def on_alarm(signum, frame):
        die()

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)
    cancel = threading.Event()
    # +5 s grace so SIGALRM (whose emit runs on the main thread, with
    # context) wins when the interpreter is actually responsive
    t = threading.Timer(seconds + 5, lambda: cancel.is_set() or die())
    t.daemon = True
    t.start()

    def disarm():
        signal.alarm(0)
        cancel.set()
        t.cancel()

    return disarm
