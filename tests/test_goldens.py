"""Offline parity vs REAL published checkpoints via recorded goldens
(VERDICT r3 item 4; reference anchors `tests/test_vit.py:17-52`,
`test_clip.py:10`, `test_siglip.py:9` — which needed torch + network at
test time; here neither is).

Two artifacts gate each case, both produced outside this zero-egress build
environment and skipped cleanly when absent:

- ``tests/goldens/<name>.npz`` — HF oracle outputs recorded once by
  `scripts/dump_goldens.py` (needs network + torch),
- the real checkpoint weights — found in the HF hub cache
  (``local_files_only``) or under ``$JIMM_GOLDEN_CKPTS/<repo-basename>``.
"""

import os
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from golden_util import GOLDEN_SPECS, golden_image, golden_text

GOLDEN_DIR = Path(__file__).parent / "goldens"


def _find_checkpoint(repo: str) -> str | None:
    env_dir = os.environ.get("JIMM_GOLDEN_CKPTS")
    if env_dir:
        cand = Path(env_dir) / repo.split("/")[-1]
        if cand.exists():
            return str(cand)
    try:
        from huggingface_hub import snapshot_download
        return snapshot_download(repo, local_files_only=True)
    except Exception:
        return None


def _model_cls(family: str):
    import jimm_tpu
    return {"vit": jimm_tpu.VisionTransformer, "clip": jimm_tpu.CLIP,
            "siglip": jimm_tpu.SigLIP}[family]


@pytest.mark.parametrize("name", sorted(GOLDEN_SPECS))
def test_real_checkpoint_parity(name):
    spec = GOLDEN_SPECS[name]
    npz_path = GOLDEN_DIR / f"{name}.npz"
    if not npz_path.exists():
        pytest.skip("golden not recorded — run scripts/dump_goldens.py once "
                    "with network access")
    ckpt = _find_checkpoint(spec["repo"])
    if ckpt is None:
        pytest.skip(f"checkpoint {spec['repo']} not cached locally")
    golden = np.load(npz_path)
    # the recorded inputs are authoritative; regenerate and cross-check so
    # a drifted golden_util can never silently compare different inputs
    img = golden["image"]
    np.testing.assert_array_equal(img, golden_image(spec["image_size"]))

    model = _model_cls(spec["family"]).from_pretrained(ckpt)
    if spec["family"] == "vit":
        ours = np.asarray(model(jnp.asarray(img)))
        np.testing.assert_allclose(ours, golden["logits"],
                                   atol=spec["atol"])
        return
    txt = golden["text"]
    np.testing.assert_array_equal(txt, golden_text(spec["family"],
                                                   spec["ctx"]))
    np.testing.assert_allclose(
        np.asarray(model.encode_image(jnp.asarray(img))),
        golden["image_embeds"], atol=spec["atol"])
    np.testing.assert_allclose(
        np.asarray(model.encode_text(jnp.asarray(txt))),
        golden["text_embeds"], atol=spec["atol"])
    ours = np.asarray(model(jnp.asarray(img), jnp.asarray(txt)))
    np.testing.assert_allclose(ours, golden["logits"], atol=spec["atol"])
