"""Profiling hooks (SURVEY §5 tracing row): `jax.profiler` trace capture
around training steps, viewable in TensorBoard / Perfetto — plus an
offline per-op analyzer so a capture can be read without TensorBoard (the
workflow behind docs/performance.md; `python -m jimm_tpu profile-analyze`).

Since the continuous profiler landed, :func:`trace` delegates to
:func:`jimm_tpu.obs.prof.capture.profiler_session` — the process-wide
sanctioned ``start_trace``/``stop_trace`` home (lint JL022) — so a
one-shot ``--profile-dir`` capture and the ``--prof-ring`` continuous ring
can never double-start the profiler. The parsing core lives jax-free in
:mod:`jimm_tpu.obs.prof.opstats`; this module keeps the :class:`OpStat`
shape the CLI and tests consume."""

from __future__ import annotations

import collections
from contextlib import contextmanager
from dataclasses import dataclass
from pathlib import Path

from jimm_tpu.obs.prof.opstats import op_table


@contextmanager
def trace(log_dir: str | Path, *, host_tracer_level: int = 2):
    """Capture a device+host trace for the enclosed steps::

        with trace("/tmp/profile"):
            for _ in range(5):
                train_step(...)
    """
    from jimm_tpu.obs.prof.capture import profiler_session
    with profiler_session(log_dir):
        yield


def annotate(name: str):
    """Named region that shows up in the trace timeline."""
    import jax
    return jax.profiler.TraceAnnotation(name)


# ---------------------------------------------------------------------------
# Offline trace analysis
# ---------------------------------------------------------------------------

@dataclass
class OpStat:
    """One XLA op aggregated across its occurrences in a trace.
    ``bytes_accessed`` is the TOTAL over all occurrences."""

    name: str
    category: str
    total_us: float
    count: int
    bytes_accessed: int
    long_name: str

    @property
    def gbps(self) -> float:
        """Achieved HBM bandwidth (GB/s) — the number that shows whether a
        fusion is bandwidth-bound or stalling."""
        if not self.total_us:
            return 0.0
        return self.bytes_accessed / (self.total_us * 1e-6) / 1e9


def op_stats(log_dir: str | Path, *, device: int | None = 0) -> list[OpStat]:
    """Aggregate device-op self times from the newest ``*.trace.json.gz``
    under ``log_dir`` (written by :func:`trace`). Pure stdlib — no
    TensorBoard required.

    ``device`` picks ONE device pid (default: the first) — under SPMD every
    core runs the same program, and summing across cores would report
    n_devices times the per-step time. ``None`` aggregates all devices."""
    return [OpStat(**row) for row in op_table(log_dir, device=device)]


def summarize(stats: list[OpStat], top: int = 25, steps: int = 1) -> str:
    """Human-readable per-op and per-category summary. ``steps`` divides the
    totals so numbers read as per-training-step."""
    total = sum(s.total_us for s in stats)
    by_cat = collections.Counter()
    for s in stats:
        by_cat[s.category] += s.total_us
    lines = [f"device op time: {total / steps / 1e3:.2f} ms/step",
             "by category (ms/step):"]
    for cat, us in by_cat.most_common():
        lines.append(f"  {us / steps / 1e3:9.2f}  {cat}")
    lines.append(f"top {top} ops (ms/step, n/step, MB/occurrence, GB/s):")
    for s in stats[:top]:
        per_occ = s.bytes_accessed / max(s.count, 1)
        lines.append(
            f"  {s.total_us / steps / 1e3:8.2f} n={s.count // steps:4d} "
            f"{per_occ / 1e6:8.1f}MB {s.gbps:6.0f}GB/s  "
            f"{s.name[:44]:44s} {s.long_name[:60]}")
    return "\n".join(lines)
