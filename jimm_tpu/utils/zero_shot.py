"""Prompt-ensemble zero-shot classification (the CLIP-paper recipe).

The reference's zero-shot flow is one prompt per label
(ref `examples/clip_inference.py`); the standard evaluation recipe instead
averages each class's text embedding over a set of prompt templates —
normalize per prompt, mean over templates, normalize again — which is worth
1-2 points of ImageNet accuracy for CLIP-family models. This module builds
those ensemble classifier weights once, so inference is a single
``(B, D) @ (D, C)`` matmul per batch — MXU-shaped, no text tower in the
inference hot path.
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp

#: The 7-template ImageNet evaluation subset popularized by the CLIP
#: authors' zero-shot notebook — a strong default when the full 80-template
#: set is overkill.
TEMPLATES: tuple[str, ...] = (
    "itap of a {}.",
    "a bad photo of the {}.",
    "a origami {}.",
    "a photo of the large {}.",
    "a {} in a video game.",
    "art of the {}.",
    "a photo of the small {}.",
)


def expand_templates(labels: Sequence[str],
                     templates: Sequence[str] = TEMPLATES) -> list[str]:
    """All prompts, class-major: ``[t.format(l) for l in labels for t in
    templates]`` — the layout `classifier_weights` expects."""
    return [t.format(label) for label in labels for t in templates]


def classifier_weights(model, text_rows: jax.Array, n_classes: int
                       ) -> jax.Array:
    """Ensemble zero-shot classifier weights from tokenized prompts.

    Args:
        model: CLIP or SigLIP (anything with ``encode_text``).
        text_rows: ``(n_classes * n_templates, L)`` token rows, class-major
            (``expand_templates`` order), each padded/EOT'd the way the
            model's tokenizer requires.
        n_classes: number of classes the rows cover.

    Returns:
        ``(n_classes, D)`` unit-norm class embeddings: per-prompt L2
        normalization, mean over the class's templates, renormalized.
    """
    total = text_rows.shape[0]
    if total % n_classes:
        raise ValueError(f"{total} prompt rows not divisible by "
                         f"{n_classes} classes")
    emb = model.encode_text(text_rows)                       # (C*T, D)
    emb = emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)
    emb = emb.reshape(n_classes, total // n_classes, -1).mean(axis=1)
    return emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)


def token_table_rows(table: dict, context_length: int,
                     labels: Sequence[str] | None = None
                     ) -> tuple[list[str], "jnp.ndarray", list[int]]:
    """Flatten a ``{label: [ids]}`` / ``{label: [[ids], ...]}`` token table
    into padded class-major rows.

    Returns ``(labels, (N, L) token rows, owner)`` where ``owner[i]`` is the
    class index row ``i`` belongs to (classes may carry different template
    counts). Raises ``ValueError`` for rows longer than ``context_length``
    (silent truncation would drop CLIP's EOT pooling token).
    """
    from jimm_tpu.data.records import pad_tokens
    import numpy as np

    labels = list(table) if labels is None else list(labels)
    missing = [label for label in labels if label not in table]
    if missing:
        raise ValueError(f"token table lacks entries for {missing[:5]}")
    rows, owner = [], []
    for ci, label in enumerate(labels):
        entry = table[label]
        per_class = entry if entry and isinstance(entry[0], list) else [entry]
        for r in per_class:
            if len(r) > context_length:
                raise ValueError(
                    f"tokens for {label!r} are {len(r)} ids but "
                    f"context_length is {context_length}; re-tokenize to fit")
            rows.append(pad_tokens(r, context_length))
            owner.append(ci)
    return labels, jnp.asarray(np.stack(rows)), owner


def weights_from_rows(model, rows: jax.Array, owner: Sequence[int],
                      n_classes: int) -> jax.Array:
    """Ensemble class weights from flat prompt rows with per-row class
    ownership (the ragged-template generalization of `classifier_weights`):
    per-prompt L2 normalization, mean over each class's rows, renormalized.
    """
    import numpy as np

    emb = np.array(model.encode_text(rows), np.float32)  # copy: writable
    emb /= np.linalg.norm(emb, axis=-1, keepdims=True)
    owner_arr = np.asarray(owner)
    weights = np.stack([emb[owner_arr == ci].mean(axis=0)
                        for ci in range(n_classes)])
    weights /= np.linalg.norm(weights, axis=-1, keepdims=True)
    return jnp.asarray(weights)


def zero_shot_logits_from_features(model, img_features: jax.Array,
                                   class_embeds: jax.Array) -> jax.Array:
    """Like `zero_shot_logits` but over precomputed (unnormalized) image
    features — e.g. from `encode_image_naflex`."""
    img = img_features / jnp.linalg.norm(img_features, axis=-1,
                                         keepdims=True)
    logits = jnp.exp(model.logit_scale[...]) * img @ class_embeds.T
    bias = getattr(model, "logit_bias", None)
    if bias is not None:
        logits = logits + bias[...]
    return logits


def zero_shot_logits(model, images: jax.Array,
                     class_embeds: jax.Array) -> jax.Array:
    """``(B, C)`` logits against prebuilt ensemble weights, using the
    model's own calibration: ``exp(logit_scale)`` (CLIP & SigLIP) plus
    ``logit_bias`` when present (SigLIP — feed through a sigmoid for
    per-class probabilities; CLIP logits go through a softmax)."""
    return zero_shot_logits_from_features(model, model.encode_image(images),
                                          class_embeds)
