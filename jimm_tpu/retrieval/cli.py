"""``jimm-tpu index`` — manage retrieval vector stores from the shell.

Pure-host tooling in the aot/tune/obs CLI mold: no jax import anywhere on
these paths, so ``index build|add|ls|verify`` run on any machine that can
see the store directory (an ops box, a CI runner) without an accelerator
stack. Vectors come in as ``.npy`` matrices with ids from a text/JSON
sidecar, or as seeded synthetic data (``--random``) for smoke tests and
benches.

    jimm-tpu index build  --store ./idx corpus --dim 512 --random 10000
    jimm-tpu index add    --store ./idx corpus --from-npy embs.npy --ids ids.txt
    jimm-tpu index ls     --store ./idx
    jimm-tpu index verify --store ./idx
    jimm-tpu index compact --store ./idx corpus
    jimm-tpu index train-centroids --store ./idx corpus --clusters 256
    jimm-tpu index build-ivf --store ./idx corpus
    jimm-tpu index stats  --store ./idx corpus

The one exception to "no jax" is ``train-centroids`` — the mini-batch
Lloyd's step is a jit-compiled program by design. Everything else,
including ``build-ivf`` (pure-NumPy assignment against the persisted
codebook) and ``stats`` (manifest-only staleness/advice), stays jax-free.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

import numpy as np

from jimm_tpu.retrieval.store import (RetrievalStoreError, VectorStore,
                                      normalize_rows)

__all__ = ["add_index_parser", "main"]


def _load_ids(path: str, n: int) -> list[str]:
    """Ids sidecar: a JSON list, or one id per text line."""
    text = Path(path).read_text()
    try:
        ids = json.loads(text)
        if not isinstance(ids, list):
            raise ValueError("ids JSON must be a list")
    except ValueError:
        ids = [line.strip() for line in text.splitlines() if line.strip()]
    ids = [str(i) for i in ids]
    if len(ids) != n:
        raise SystemExit(f"{path} has {len(ids)} ids for {n} vectors")
    return ids


def _rows_from_args(args: argparse.Namespace, dim: int | None
                    ) -> tuple[list[str], np.ndarray]:
    if args.from_npy:
        mat = np.load(args.from_npy)
        if mat.ndim != 2:
            raise SystemExit(f"{args.from_npy} must hold an (N, D) matrix; "
                             f"got shape {mat.shape}")
        ids = (_load_ids(args.ids, mat.shape[0]) if args.ids
               else [f"{Path(args.from_npy).stem}:{i}"
                     for i in range(mat.shape[0])])
        return ids, mat
    if args.random:
        if dim is None:
            raise SystemExit("--random needs --dim (or an existing index)")
        rng = np.random.default_rng(args.seed)
        mat = normalize_rows(rng.standard_normal((args.random, dim),
                                                 dtype=np.float32))
        return [f"rand:{args.seed}:{i}" for i in range(args.random)], mat
    raise SystemExit("need --from-npy FILE (with optional --ids) or "
                     "--random N")


def _cmd_build(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    dim = args.dim
    if dim is None and args.from_npy:
        dim = int(np.load(args.from_npy).shape[1])
    if dim is None:
        raise SystemExit("need --dim (or --from-npy to infer it)")
    store.create(args.name, dim, dtype=args.dtype,
                 exist_ok=args.exist_ok)
    out = {"index": args.name, "dim": int(dim), "dtype": args.dtype}
    if args.from_npy or args.random:
        ids, mat = _rows_from_args(args, dim)
        out["segment"] = store.add(args.name, ids, mat)[:12]
        out["rows"] = len(ids)
    print(json.dumps(out))
    return 0


def _cmd_add(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    man = store.manifest(args.name)
    ids, mat = _rows_from_args(args, int(man["dim"]))
    fp = store.add(args.name, ids, mat)
    print(json.dumps({"index": args.name, "segment": fp[:12],
                      "rows": len(ids),
                      "total_rows": store.stats(args.name)["rows"]}))
    return 0


def _cmd_ls(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    rows = store.ls()
    if args.json:
        print(json.dumps(rows, indent=1))
        return 0
    if not rows:
        print(f"no indexes under {args.store}")
        return 0
    print(f"{'name':24s} {'rows':>8s} {'dim':>6s} {'dtype':10s} "
          f"{'segs':>5s} {'dead':>6s} {'bytes':>12s}")
    for r in rows:
        print(f"{r['name']:24s} {r['rows']:8d} {r['dim']:6d} "
              f"{r['dtype']:10s} {r['segments']:5d} {r['dead_rows']:6d} "
              f"{r['bytes']:12d}")
    return 0


def _cmd_verify(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    problems = store.verify(args.name)
    for p in problems:
        print(json.dumps(p))
    summary = {"indexes": len([args.name] if args.name else store.names()),
               "problems": len(problems)}
    print(json.dumps(summary))
    return 1 if problems else 0


def _cmd_compact(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    report = store.compact(args.name)
    print(json.dumps({"index": args.name, **report}))
    return 0


def _cmd_train_centroids(args: argparse.Namespace) -> int:
    # the one jax-using index command: the Lloyd's step is a jit program
    from jimm_tpu.retrieval.ann.kmeans import train_centroids
    store = VectorStore(args.store)
    index = store.load(args.name)
    if len(index) < args.clusters:
        raise SystemExit(f"index {args.name!r} has {len(index)} live rows "
                         f"< --clusters {args.clusters}")
    centroids = train_centroids(index.matrix_f32(), args.clusters,
                                iters=args.iters,
                                batch_rows=args.batch_rows,
                                seed=args.seed)
    fp = store.set_codebook(args.name, centroids,
                            trained_rows=len(index), seed=args.seed)
    print(json.dumps({"index": args.name, "codebook": fp[:12],
                      "clusters": int(args.clusters),
                      "trained_rows": len(index),
                      "hint": "run `index build-ivf` to cluster existing "
                              "segments"}))
    return 0


def _cmd_build_ivf(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    report = store.build_ivf(args.name)
    print(json.dumps({"index": args.name, **report}))
    return 0


def _cmd_stats(args: argparse.Namespace) -> int:
    store = VectorStore(args.store)
    names = [args.name] if args.name else store.names()
    out = [store.stats(n) for n in names]
    doc = out if args.name is None else out[0]
    if args.json:
        # machine-readable contract: one compact line, stable under
        # pretty-print drift — what tier_smoke and the IndexDaemon's
        # operators parse
        print(json.dumps(doc, separators=(",", ":"), sort_keys=True))
    else:
        print(json.dumps(doc, indent=1))
    return 0


def cmd_index(args: argparse.Namespace) -> int:
    try:
        return args.index_func(args)
    except RetrievalStoreError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1


def add_index_parser(subparsers) -> None:
    """Register ``jimm-tpu index ...`` on the main CLI."""
    p = subparsers.add_parser(
        "index", help="manage retrieval vector indexes (no jax needed)")
    p.set_defaults(fn=cmd_index)
    sub = p.add_subparsers(dest="index_cmd", required=True)

    def _store_flag(sp):
        sp.add_argument("--store", required=True,
                        help="vector store root directory")

    sp = sub.add_parser("build", help="create an index (optionally "
                                      "seeding rows)")
    _store_flag(sp)
    sp.add_argument("name", help="index name")
    sp.add_argument("--dim", type=int, default=None,
                    help="embedding dimension (inferred from --from-npy)")
    sp.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16"])
    sp.add_argument("--from-npy", default=None,
                    help="seed rows from an (N, D) .npy matrix")
    sp.add_argument("--ids", default=None,
                    help="ids sidecar for --from-npy (JSON list or one id "
                         "per line; default: derived from the file name)")
    sp.add_argument("--random", type=int, default=None, metavar="N",
                    help="seed N synthetic unit vectors (smoke/bench)")
    sp.add_argument("--seed", type=int, default=0)
    sp.add_argument("--exist-ok", action="store_true",
                    help="reuse an existing index instead of failing")
    sp.set_defaults(index_func=_cmd_build)

    sp = sub.add_parser("add", help="append rows to an index")
    _store_flag(sp)
    sp.add_argument("name")
    sp.add_argument("--from-npy", default=None)
    sp.add_argument("--ids", default=None)
    sp.add_argument("--random", type=int, default=None, metavar="N")
    sp.add_argument("--seed", type=int, default=1)
    sp.set_defaults(index_func=_cmd_add)

    sp = sub.add_parser("ls", help="list indexes with row/segment stats")
    _store_flag(sp)
    sp.add_argument("--json", action="store_true",
                    help="machine-readable output")
    sp.set_defaults(index_func=_cmd_ls)

    sp = sub.add_parser("verify",
                        help="re-validate manifests + segment payloads "
                             "(bad segments quarantine; exit 1 on problems)")
    _store_flag(sp)
    sp.add_argument("name", nargs="?", default=None,
                    help="one index (default: all)")
    sp.set_defaults(index_func=_cmd_verify)

    sp = sub.add_parser("compact",
                        help="fold live rows into one segment and drop "
                             "tombstoned bytes")
    _store_flag(sp)
    sp.add_argument("name")
    sp.set_defaults(index_func=_cmd_compact)

    sp = sub.add_parser("train-centroids",
                        help="train the IVF coarse codebook over the live "
                             "rows (jit-compiled k-means; needs jax)")
    _store_flag(sp)
    sp.add_argument("name")
    sp.add_argument("--clusters", type=int, required=True,
                    help="codebook size C (rule of thumb: ~sqrt(N))")
    sp.add_argument("--iters", type=int, default=25)
    sp.add_argument("--batch-rows", type=int, default=4096)
    sp.add_argument("--seed", type=int, default=0)
    sp.set_defaults(index_func=_cmd_train_centroids)

    sp = sub.add_parser("build-ivf",
                        help="cluster-order existing segments against the "
                             "trained codebook (pure NumPy, no jax)")
    _store_flag(sp)
    sp.add_argument("name")
    sp.set_defaults(index_func=_cmd_build_ivf)

    sp = sub.add_parser("stats",
                        help="row/segment/ann stats incl. IVF staleness "
                             "and re-train advice (manifest-only, no jax)")
    _store_flag(sp)
    sp.add_argument("name", nargs="?", default=None,
                    help="one index (default: all)")
    sp.add_argument("--json", action="store_true",
                    help="one compact sorted-key JSON line (machine-"
                         "readable; default output is pretty-printed)")
    sp.set_defaults(index_func=_cmd_stats)


def main(argv: list[str] | None = None) -> int:
    """Standalone entry point (``python -m jimm_tpu.retrieval.cli``)."""
    parser = argparse.ArgumentParser(prog="jimm-tpu-index")
    sub = parser.add_subparsers(dest="cmd", required=True)
    add_index_parser(sub)
    args = parser.parse_args(["index", *(argv if argv is not None
                                         else sys.argv[1:])])
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
