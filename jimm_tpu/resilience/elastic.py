"""Goodput-driven elastic adaptation: mesh replanning + a bounded advisor.

Two host-only pieces (no jax import, same contract as the rest of this
package) that turn the static supervise loop into an adaptive one:

- :func:`plan_data_axis` picks the data-parallel mesh width for an attempt
  from whatever devices survived — ``cmd_supervise --elastic`` calls it
  between attempts and rewrites the train command's ``--mesh``/
  ``--max-devices``, so a restart after losing hosts restores the
  checkpoint onto a *smaller* mesh (resharding-on-restore in
  ``train/checkpoint.py``) instead of dying on the old shape.
- :class:`GoodputAdvisor` watches the per-attempt goodput breakdown
  (``obs.goodput`` bucket deltas, including ``preemption_save`` and
  ``lost_work``) over a sliding window and adjusts the runtime knobs the
  next attempt launches with — checkpoint cadence, preemption grace steps,
  layer-scan unroll. This is the "adopted-plus-adapted" runtime: the
  measured ``adopted_runtime.json`` pick seeds the knobs, live goodput
  revises them.

Every advisor decision is **bounded** (hard per-knob clamps), **hysteretic**
(windowed means with a cooldown between decisions and a dead band between
the opposing checkpoint-cadence rules, so it cannot oscillate), and
**audited** — each one is emitted as a parseable
``goodput_advisor_decision: {...}`` JSON line and counted in
``jimm_train_goodput_advisor_decisions_total``. With no faults and healthy
goodput the advisor makes no decisions, and nothing here runs at all unless
``supervise --adapt``/``--elastic`` is passed.
"""

from __future__ import annotations

import json
from collections import deque
from typing import Callable

__all__ = ["GoodputAdvisor", "plan_data_axis"]

#: per-knob hard clamps — a runaway rule can never push a knob outside these
KNOB_BOUNDS = {
    "save_every": (1, 512),
    "grace_steps": (0, 8),
    "scan_unroll": (1, 64),
}

#: knob name -> the train-command flag supervise rewrites between attempts
KNOB_FLAGS = {
    "save_every": "--save-every",
    "grace_steps": "--grace-steps",
    "scan_unroll": "--scan-unroll",
}


def plan_data_axis(n_devices: int, batch_size: int) -> int:
    """Widest data-parallel mesh axis that fits ``n_devices`` and divides
    ``batch_size`` evenly (``shard_batch`` and the pipeline validators both
    require divisibility). Always >= 1, so a single surviving device still
    yields a runnable (degenerate) plan."""
    if n_devices < 1:
        raise ValueError(f"n_devices must be >= 1, got {n_devices}")
    if batch_size < 1:
        raise ValueError(f"batch_size must be >= 1, got {batch_size}")
    k = min(n_devices, batch_size)
    while k > 1 and batch_size % k:
        k -= 1
    return k


class GoodputAdvisor:
    """Sliding-window goodput feedback over restart attempts.

    Feed :meth:`observe` one goodput breakdown per finished attempt (the
    per-attempt *delta* of the ``goodput_{bucket}_seconds_total`` counters,
    plus that attempt's wall seconds). When a fraction stays bad across the
    window, the advisor moves exactly ONE knob by one bounded notch:

    - ``lost_work`` high -> checkpoint more often (halve ``save_every``,
      floor 1); once already at every step, widen the preemption grace
      window instead (``grace_steps`` + 1, cap 8) so the SIGTERM save
      overlaps more surviving steps.
    - ``checkpoint`` overhead high *and* lost work comfortably low (a dead
      band below the lost-work threshold, so this rule and the one above
      can never ping-pong) -> checkpoint less often (double ``save_every``,
      cap 512).
    - ``compile`` dominating across >= 2 attempts (every restart repays the
      trace) -> ``scan_unroll`` 1, the cheapest-retrace layer scan.

    A decision starts a ``cooldown`` (observations, not seconds) during
    which the advisor only watches — the next attempt must actually run
    with the new knob before its effect is judged.
    """

    def __init__(self, *, window: int = 3, cooldown: int = 1,
                 lost_work_high: float = 0.08,
                 checkpoint_high: float = 0.25,
                 compile_high: float = 0.35,
                 knobs: dict[str, int] | None = None,
                 registry=None,
                 emit: Callable[[str], None] | None = None):
        if window < 1:
            raise ValueError(f"window must be >= 1, got {window}")
        self.window = window
        self.cooldown = max(0, cooldown)
        self.lost_work_high = lost_work_high
        self.checkpoint_high = checkpoint_high
        self.compile_high = compile_high
        #: current knob values the next attempt should launch with; seeded
        #: by the caller from the train command's flags (adopted runtime
        #: included), revised in place by decisions
        self.knobs: dict[str, int] = dict(knobs or {})
        #: every decision made, oldest first (the JSONL audit trail mirrors
        #: this list line for line)
        self.decisions: list[dict] = []
        self._fracs: deque[dict[str, float]] = deque(maxlen=window)
        self._since_decision = self.cooldown  # first window may decide
        if registry is None:
            from jimm_tpu.obs import get_registry
            registry = get_registry("jimm_train")
        self.registry = registry
        # pre-created at 0 so "the advisor ran and did nothing" is visible
        # in every snapshot, distinct from "the advisor never ran"
        self._counter = registry.counter("goodput_advisor_decisions_total")
        self._emit = emit

    # -- feedback ---------------------------------------------------------

    def observe(self, attempt: int, wall_s: float,
                buckets: dict[str, float]) -> dict | None:
        """Record one attempt's goodput breakdown; returns the decision it
        triggered (already applied to :attr:`knobs`, logged, and counted)
        or None."""
        wall = max(float(wall_s), 1e-9)
        self._fracs.append({
            name: max(0.0, float(buckets.get(name, 0.0))) / wall
            for name in ("lost_work", "checkpoint", "preemption_save",
                         "compile", "step")})
        if self._since_decision < self.cooldown:
            self._since_decision += 1
            return None
        decision = self._decide(attempt)
        if decision is None:
            self._since_decision += 1
            return None
        self._apply(decision)
        return decision

    def _mean(self, name: str) -> float:
        return sum(f[name] for f in self._fracs) / len(self._fracs)

    def _decide(self, attempt: int) -> dict | None:
        lost = self._mean("lost_work")
        ckpt = self._mean("checkpoint")
        comp = self._mean("compile")
        fracs = {"lost_work": round(lost, 4), "checkpoint": round(ckpt, 4),
                 "compile": round(comp, 4),
                 "preemption_save": round(self._mean("preemption_save"), 4)}

        def notch(knob: str, value: int, reason: str) -> dict | None:
            lo, hi = KNOB_BOUNDS[knob]
            value = max(lo, min(hi, int(value)))
            if value == self.knobs.get(knob):
                return None
            return {"attempt": attempt, "knob": knob,
                    "from": self.knobs.get(knob), "to": value,
                    "reason": reason, "window_fracs": fracs,
                    "window": len(self._fracs)}

        if lost > self.lost_work_high:
            save_every = self.knobs.get("save_every")
            if save_every is not None and save_every > 1:
                return notch("save_every", save_every // 2,
                             "lost_work fraction high: checkpoint more "
                             "often so restarts replay less")
            grace = self.knobs.get("grace_steps")
            if grace is not None:
                return notch("grace_steps", grace + 1,
                             "lost_work fraction high at save_every=1: "
                             "overlap more steps with the grace-window "
                             "save")
        # dead band: only relax the cadence when lost work sits well below
        # the tightening threshold, so the two rules cannot alternate
        elif (ckpt > self.checkpoint_high
              and lost < self.lost_work_high / 2
              and self.knobs.get("save_every") is not None):
            return notch("save_every", self.knobs["save_every"] * 2,
                         "checkpoint overhead high with lost_work low: "
                         "checkpoint less often")
        if (comp > self.compile_high and len(self._fracs) >= 2
                and self.knobs.get("scan_unroll") != 1):
            return notch("scan_unroll", 1,
                         "compile dominating across restarts: cheapest-"
                         "retrace layer scan")
        return None

    def _apply(self, decision: dict) -> None:
        from jimm_tpu.obs.journal import get_journal
        self.knobs[decision["knob"]] = decision["to"]
        self.decisions.append(decision)
        self._counter.inc()
        self._since_decision = 0
        # the audit trail: journaled (joining the active incident's chain
        # when one is ambient), echoed as the legacy parseable line only
        # for injected sinks (tests, supervise transcripts)
        rec = get_journal().emit("advisor_decision", **decision)
        # an advisor notch means goodput is measurably degrading — worth a
        # deep profiler capture on the same incident chain (no-op unless a
        # capture ring is configured)
        from jimm_tpu.obs.prof.capture import maybe_trigger
        maybe_trigger(rec.get("cid"), "advisor_" + str(decision["knob"]))
        if self._emit is not None:
            self._emit("goodput_advisor_decision: " + json.dumps(decision))

    # -- handoff ----------------------------------------------------------

    def argv_overrides(self) -> list[str]:
        """The knob state as train-command flags, appended after the user's
        own argv so argparse's last-wins makes them effective."""
        out: list[str] = []
        for knob, value in self.knobs.items():
            flag = KNOB_FLAGS.get(knob)
            if flag is not None and value is not None:
                out += [flag, str(value)]
        return out
