"""Tests for jimm_tpu.obs.prof — the continuous-profiling capture ring,
the HBM watchdog, the jax-free op-stats diff — plus the satellite pieces
that ride on them: the byte-bounded serve trace ring, rotation-surviving
``obs tail --follow``, and the prof lane on the incident timeline.

Every test injects a fake profiler/sampler, so nothing here starts a real
``jax.profiler`` session or needs a device.
"""

import io
import json
import os
import threading
import time

import pytest

from jimm_tpu.obs.journal import EventJournal
from jimm_tpu.obs.prof.capture import (CaptureManager, configure_capture,
                                       list_captures, maybe_trigger,
                                       reset_capture)
from jimm_tpu.obs.prof.memory import MemoryMonitor
from jimm_tpu.obs.prof.opstats import diff_ops, top_ops


class FakeProfiler:
    """Writes a deterministic payload instead of a real xplane capture."""

    def __init__(self, payload_bytes: int = 512):
        self.payload_bytes = payload_bytes
        self.active_dir = None
        self.sessions = 0

    def start(self, log_dir: str) -> None:
        assert self.active_dir is None, "double start"
        self.active_dir = log_dir
        self.sessions += 1

    def stop(self) -> None:
        assert self.active_dir is not None, "stop without start"
        os.makedirs(self.active_dir, exist_ok=True)
        with open(os.path.join(self.active_dir, "fake.xplane.pb"),
                  "wb") as f:
            f.write(b"x" * self.payload_bytes)
        self.active_dir = None


def make_manager(tmp_path, **kw):
    journal = EventJournal()  # memory-only ring
    kw.setdefault("profiler", FakeProfiler())
    kw.setdefault("min_trigger_interval_s", 0.0)
    mgr = CaptureManager(tmp_path / "ring", journal=journal, **kw)
    return mgr, journal


def journal_events(journal, name=None):
    recs = list(journal._ring)
    return [r for r in recs if name is None or r["event"] == name]


class TestCaptureManager:
    def test_ring_windows_commit_on_schedule(self, tmp_path):
        mgr, journal = make_manager(tmp_path, every_steps=10, window_steps=2)
        for step in range(35):
            mgr.on_step(step)
        metas = mgr.ls()
        # windows open at steps 2/12/22/32 (offset 2: past compile) and
        # commit two steps later
        assert [m["kind"] for m in metas] == ["window"] * 4
        assert all(m["name"].startswith("cap-") for m in metas)
        # every committed capture journaled a started/committed pair on
        # one cid, with a dur_s the timeline can render as a span
        started = journal_events(journal, "prof_capture_started")
        committed = journal_events(journal, "prof_capture_committed")
        assert len(started) == len(committed) == 4
        for s, c in zip(started, committed):
            assert s["cid"] == c["cid"]
            assert c["dur_s"] >= 0
            assert c["bytes"] > 0

    def test_trigger_deep_capture_tags_cid_and_dedupes(self, tmp_path):
        mgr, journal = make_manager(tmp_path, every_steps=0,
                                    deep_window_s=0.02)
        meta = mgr.trigger("c-incident", "heal")
        assert meta is not None and meta["kind"] == "deep"
        assert meta["cid"] == "c-incident"
        # second trigger on the same incident is suppressed: one deep
        # capture per incident is the useful artifact
        assert mgr.trigger("c-incident", "replan") is None
        deadline = time.monotonic() + 2.0
        while not mgr.ls() and time.monotonic() < deadline:
            time.sleep(0.005)
        metas = mgr.ls()
        assert len(metas) == 1 and metas[0]["cid"] == "c-incident"
        committed = journal_events(journal, "prof_capture_committed")
        assert len(committed) == 1 and committed[0]["cid"] == "c-incident"

    def test_byte_budget_evicts_oldest(self, tmp_path):
        mgr, _ = make_manager(tmp_path, every_steps=0,
                              profiler=FakeProfiler(payload_bytes=1000),
                              max_ring_bytes=2500)
        for i in range(4):
            assert mgr.start("window", step=i) is not None
            mgr.commit()
        metas = mgr.ls()
        # 4 x ~1000B captures under a 2500B budget: oldest evicted first,
        # the newest always survives
        assert 1 <= len(metas) < 4
        seqs = [m["seq"] for m in metas]
        assert seqs == sorted(seqs) and seqs[-1] == 4
        assert 1 not in seqs
        assert mgr.ring_bytes() <= 2500

    def test_leftover_tmp_quarantined_not_deleted(self, tmp_path):
        root = tmp_path / "ring"
        stale = root / "cap-000007-window.tmp"
        stale.mkdir(parents=True)
        (stale / "partial.pb").write_bytes(b"wreck")
        mgr, _ = make_manager(tmp_path)
        assert mgr.ls() == []
        qdir = root / "quarantine"
        moved = list(qdir.glob("*/partial.pb"))
        assert len(moved) == 1 and moved[0].read_bytes() == b"wreck"

    def test_global_maybe_trigger_is_noop_unconfigured(self, tmp_path):
        reset_capture()
        try:
            os.environ.pop("JIMM_PROF_DIR", None)
            assert maybe_trigger("c-x", "heal") is None
            configure_capture(tmp_path / "g", profiler=FakeProfiler(),
                              min_trigger_interval_s=0.0, deep_window_s=0.01)
            meta = maybe_trigger("c-x", "heal")
            assert meta is not None and meta["cid"] == "c-x"
        finally:
            reset_capture()


class TestMemoryMonitor:
    def test_leak_watchdog_one_record_per_episode(self, tmp_path):
        journal = EventJournal()
        rows = {"bytes": 0.0}

        def sampler():
            return [{"device": 0, "source": "fake",
                     "bytes_in_use": rows["bytes"],
                     "peak_bytes_in_use": rows["bytes"],
                     "bytes_limit": 1 << 30, "fragmentation": 0.0}]

        mon = MemoryMonitor(leak_window=3, leak_min_growth_frac=0.01,
                            leak_min_growth_bytes=1000, journal=journal,
                            sampler=sampler)
        mon.register_subsystem("serve_buffers", lambda: 42.0)
        # monotonic growth across the window -> exactly one record,
        # a dip closes the episode, renewed growth opens a second
        for b in (1000, 2000, 3000, 4000, 5000, 1000, 2000, 3000, 4000,
                  5000):
            rows["bytes"] = float(b)
            mon.sample()
        leaks = journal_events(journal, "hbm_leak_suspected")
        assert len(leaks) == 2
        assert all(r["cid"] for r in leaks)
        assert leaks[0]["cid"] != leaks[1]["cid"]
        assert leaks[0]["growth_bytes"] > 0
        from jimm_tpu.obs import get_registry
        snap = get_registry("jimm_hbm").snapshot()
        assert snap["device0_bytes_in_use"] == 5000.0
        assert snap["subsystem_serve_buffers_bytes"] == 42.0

    def test_raising_subsystem_reports_zero(self):
        mon = MemoryMonitor(sampler=lambda: [], journal=EventJournal())

        def boom():
            raise RuntimeError("index offline")

        mon.register_subsystem("retrieval_index", boom)
        report = mon.sample()
        assert report["subsystems"]["retrieval_index"] == 0.0


class TestOpStatsDiff:
    ROWS_BEFORE = [
        {"name": "fusion.1", "category": "fusion", "total_us": 100.0,
         "count": 10, "bytes_accessed": 1000, "long_name": "f1"},
        {"name": "copy.2", "category": "copy", "total_us": 50.0,
         "count": 5, "bytes_accessed": 500, "long_name": "c2"},
        {"name": "gone.3", "category": "fusion", "total_us": 20.0,
         "count": 2, "bytes_accessed": 0, "long_name": "g3"},
    ]

    def test_direction_aware_verdict(self):
        after = [
            dict(self.ROWS_BEFORE[0], total_us=300.0),   # 3x slower
            dict(self.ROWS_BEFORE[1], total_us=30.0),    # 40% faster
            {"name": "new.4", "category": "fusion", "total_us": 5.0,
             "count": 1, "bytes_accessed": 0, "long_name": "n4"},
        ]
        d = diff_ops(self.ROWS_BEFORE, after, threshold=0.10)
        # verdict keys on TOTAL device-op time (the step-time proxy)
        assert d["verdict"] == "regression"
        assert d["total_delta_frac"] > 0.10
        assert [r["name"] for r in d["regressions"]] == ["fusion.1"]
        assert [r["name"] for r in d["improvements"]] == ["copy.2"]
        assert [r["name"] for r in d["added"]] == ["new.4"]
        assert [r["name"] for r in d["removed"]] == ["gone.3"]
        # time is lower-better: total going DOWN must not be a regression
        d2 = diff_ops(self.ROWS_BEFORE, self.ROWS_BEFORE, threshold=0.10)
        assert d2["verdict"] == "ok" and not d2["regressions"]

    def test_top_ops_by_bytes(self):
        rows = top_ops(self.ROWS_BEFORE, k=2, by="bytes_accessed")
        assert [r["name"] for r in rows] == ["fusion.1", "copy.2"]


class TestServeTraceRingBudget:
    """Satellite: recent_traces is byte-bounded, not just entry-bounded."""

    def _engine(self, **kw):
        from jimm_tpu.serve import BucketTable, InferenceEngine
        return InferenceEngine(lambda b: b, item_shape=(3,),
                               buckets=BucketTable((1, 2)), **kw)

    def test_byte_budget_drops_oldest_and_counts(self):
        engine = self._engine(recent_traces_entries=1000,
                              recent_traces_max_bytes=2048)
        row = {"trace_id": "t", "replica": 0, "bucket": 1,
               "queue_s": 0.001, "pad_s": 0.0, "device_s": 0.002,
               "readback_s": 0.0, "total_s": 0.003, "done_mono": 1.0,
               "note": "x" * 100}
        for i in range(100):
            engine._record_trace(dict(row, trace_id=f"t{i:03d}"))
        assert engine._traces_bytes <= 2048
        assert len(engine.recent_traces) < 100
        # newest survive, oldest dropped, and the drop is observable
        assert engine.recent_traces[-1]["trace_id"] == "t099"
        snap = engine.metrics.snapshot()
        dropped = snap["traces_dropped_total"]
        assert dropped == 100 - len(engine.recent_traces)
        assert snap["recent_traces_bytes"] == float(engine._traces_bytes)

    def test_single_oversized_row_is_kept(self):
        # the ring never evicts down to empty: the newest row always
        # survives even when it alone exceeds the budget
        engine = self._engine(recent_traces_max_bytes=64)
        engine._record_trace({"trace_id": "big", "note": "x" * 500})
        assert len(engine.recent_traces) == 1


class TestTailRotation:
    """Satellite: ``obs tail --follow`` survives journal rotation."""

    def test_follow_survives_rotation(self, tmp_path):
        from jimm_tpu.obs.cli import _tail_jsonl
        path = tmp_path / "journal.jsonl"
        journal = EventJournal(path, max_bytes=300, max_segments=3)
        journal.emit("before_rotation", phase="a")
        out = io.StringIO()
        state = {"polls": 0}

        def fake_sleep(_):
            state["polls"] += 1
            if state["polls"] == 1:
                # force rotation: pad past max_bytes so the live file is
                # renamed aside and recreated under the follower
                for i in range(8):
                    journal.emit("filler", i=i, pad="x" * 64)
                journal.emit("after_rotation", phase="b")

        rc = _tail_jsonl(str(path), follow=True, sleep=fake_sleep,
                         should_stop=lambda: state["polls"] >= 5, out=out)
        assert rc == 0
        text = out.getvalue()
        assert "before_rotation" in text
        # the follower reopened the recreated file and saw post-rotation
        # records — the old behavior read EOF on the renamed segment
        # forever
        assert "after_rotation" in text
        assert (tmp_path / "journal.1.jsonl").exists()

    def test_no_follow_reads_once_and_exits(self, tmp_path):
        from jimm_tpu.obs.cli import _tail_jsonl
        path = tmp_path / "m.jsonl"
        path.write_text(json.dumps({"ts": "t", "phase": "p", "v": 1}) + "\n")
        out = io.StringIO()
        assert _tail_jsonl(str(path), follow=False, out=out) == 0
        assert "[p] v=1" in out.getvalue()


class TestTimelineProfLane:
    """Satellite: a deep capture overlapping a replan renders on a shared
    clock — prof, serve, and goodput lanes in one trace, the capture span
    carrying the incident cid."""

    def test_deep_capture_overlaps_replan_on_shared_clock(self, tmp_path):
        from jimm_tpu.obs.timeline import (export_timeline,
                                           validate_chrome_trace)
        cid = "c-incident-7"
        # replan spans mono 10.0..10.4 (journal); the deep capture the
        # replan triggered spans 10.1..10.35 (capture meta); both stamped
        # from the same time.monotonic() clock
        events = [
            {"seq": 0, "ts": "t", "mono": 10.0, "event": "replan_started",
             "cid": cid},
            {"seq": 1, "ts": "t", "mono": 10.1, "event":
             "prof_capture_started", "cid": cid, "kind": "deep"},
            {"seq": 2, "ts": "t", "mono": 10.35, "event":
             "prof_capture_committed", "cid": cid, "kind": "deep",
             "dur_s": 0.25, "bytes": 4096},
            {"seq": 3, "ts": "t", "mono": 10.4, "event": "replan_done",
             "cid": cid, "dur_s": 0.4},
        ]
        captures = [{"seq": 1, "name": "cap-000001-deep", "kind": "deep",
                     "cid": cid, "reason": "replan", "step": None,
                     "ts": "t", "start_mono": 10.1, "end_mono": 10.35,
                     "dur_s": 0.25, "bytes": 4096}]
        goodput = {"step": 0.3, "replan": 0.1}
        trace = export_timeline(events, captures=captures, goodput=goodput)
        assert validate_chrome_trace(trace) == []
        by_lane = {}
        for ev in trace["traceEvents"]:
            if ev.get("ph") != "M":
                by_lane.setdefault(ev["tid"], []).append(ev)
        assert {"serve", "prof", "goodput"} <= set(by_lane)
        # the capture meta's span on the prof lane carries the incident
        # cid and sits inside the replan window on the shared clock
        cap = [e for e in by_lane["prof"] if e["ph"] == "X"
               and e["name"] == "capture:deep"]
        assert len(cap) == 1
        assert cap[0]["args"]["cid"] == cid
        replan = [e for e in by_lane["serve"]
                  if e["name"] == "replan_done"][0]
        assert replan["ts"] <= cap[0]["ts"]
        assert cap[0]["ts"] + cap[0]["dur"] \
            <= replan["ts"] + replan["dur"] + 1e-6
        # journal prof_* events land on the prof lane too
        assert any(e["name"] == "prof_capture_committed"
                   for e in by_lane["prof"])


class TestEngineTriggerWiring:
    """Incident paths call maybe_trigger with their cid (no-op here until a
    manager is configured; then a deep capture appears on that cid)."""

    def test_heal_and_replan_reasons_reach_manager(self, tmp_path):
        from jimm_tpu.serve.engine import _prof_trigger
        reset_capture()
        try:
            mgr = configure_capture(tmp_path / "ring",
                                    profiler=FakeProfiler(),
                                    min_trigger_interval_s=0.0,
                                    deep_window_s=0.01)
            _prof_trigger("c-heal-1", "heal")
            deadline = time.monotonic() + 2.0
            while not mgr.ls() and time.monotonic() < deadline:
                time.sleep(0.005)
            metas = mgr.ls()
            assert [m["cid"] for m in metas] == ["c-heal-1"]
            assert metas[0]["reason"] == "heal"
        finally:
            reset_capture()

    def test_trigger_never_raises_without_manager(self):
        from jimm_tpu.serve.engine import _prof_trigger
        reset_capture()
        try:
            os.environ.pop("JIMM_PROF_DIR", None)
            _prof_trigger("c-x", "slo_fast_burn")  # must be a silent no-op
        finally:
            reset_capture()
